"""Roofline profiler, resource ledger, digest table, flame export (PR 5).

Covers the acceptance properties:

- one driver query against a remote-store-backed server yields a single
  trace whose ledger totals (cells read, bytes moved) equal the sum of
  the ``ledger.*`` span annotations over that trace, and the flame
  export of the same trace emits valid collapsed-stack lines;
- ledger propagation negotiates its feature bit in BOTH directions (new
  client <-> old server, old client <-> new server) over the remote
  store AND index protocols, mirroring the PR 4 trace-header tests;
- TPU/CPU pagerank run records report flops, bytes, operational
  intensity, and roofline utilization for every superstep — via XLA
  cost_analysis AND via the host estimator fallback;
- ``.profile()`` returns a ``resources`` block in the ledger vocabulary;
- slow-op and flight ``slow_span`` events carry the query digest.
"""

import json
import re
import time
import urllib.request

import pytest

from janusgraph_tpu.core.graph import open_graph
from janusgraph_tpu.driver import JanusGraphClient
from janusgraph_tpu.observability import tracer
from janusgraph_tpu.observability.profiler import (
    ResourceLedger,
    accrue,
    current_ledger,
    digest_table,
    encode_ledger_block,
    flame_lines,
    ledger_scope,
    shape_digest,
    split_ledger_block,
    traversal_shape,
)
from janusgraph_tpu.server import JanusGraphManager, JanusGraphServer
from janusgraph_tpu.storage.inmemory import InMemoryStoreManager
from janusgraph_tpu.storage.kcvs import KeySliceQuery, SliceQuery
from janusgraph_tpu.storage.remote import (
    RemoteStoreManager,
    RemoteStoreServer,
)

_SLICE = SliceQuery(b"", b"\xff")


def _span_ledger_sum(trace, field):
    """Sum of one ledger.* annotation over every span of a trace."""
    total = 0

    def walk(span):
        nonlocal total
        total += int(span.attrs.get(f"ledger.{field}", 0))
        for c in span.children:
            walk(c)

    for root in trace:
        walk(root)
    return total


def _wait_trace(trace_id, pred, timeout_s=2.0):
    """Remote handlers finish their spans just after replying — poll the
    stitched trace until `pred` holds (or time out and return anyway)."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        trace = tracer.find_trace(trace_id)
        if pred(trace):
            return trace
        time.sleep(0.01)
    return tracer.find_trace(trace_id)


# ------------------------------------------------------------------ ledger
def test_ledger_scope_nesting_merges_to_parent():
    with ledger_scope() as outer:
        accrue(cells_read=1)
        with ledger_scope() as inner:
            accrue(cells_read=2, index_hits=3)
        assert inner.get("cells_read") == 2
    assert outer.get("cells_read") == 3
    assert outer.get("index_hits") == 3
    assert current_ledger() is None


def test_accrue_annotates_current_span_aggregating():
    with ledger_scope() as led:
        with tracer.span("work") as sp:
            accrue(cells_read=2)
            accrue(cells_read=3, bytes_read=10)
    assert sp.attrs["ledger.cells_read"] == 5
    assert sp.attrs["ledger.bytes_read"] == 10
    assert led.get("cells_read") == 5


def test_accrue_is_noop_outside_scope():
    with tracer.span("unprofiled") as sp:
        accrue(cells_read=99)
    assert "ledger.cells_read" not in sp.attrs


def test_ledger_wall_by_layer_and_to_dict():
    led = ResourceLedger()
    led.add(cells_read=4)
    led.add_wall("storage", 1.5)
    led.add_wall("storage", 0.5)
    d = led.to_dict()
    assert d["cells_read"] == 4
    assert d["wall_ms_by_layer"]["storage"] == 2.0


def test_ledger_block_codec_roundtrip_and_degradation():
    fields = {"cells_read": 7, "bytes_written": 1 << 40, "wall_ns": 123}
    blob = encode_ledger_block(fields) + b"PAYLOAD"
    decoded, rest = split_ledger_block(blob)
    assert decoded == fields
    assert rest == b"PAYLOAD"
    # malformed blocks degrade to None without consuming the body
    assert split_ledger_block(b"") == (None, b"")
    garbage = bytes([200]) + b"\x01"
    assert split_ledger_block(garbage) == (None, garbage)


# ------------------------------------------------- remote store wire compat
@pytest.fixture
def served():
    server = RemoteStoreServer(InMemoryStoreManager()).start()
    host, port = server.address
    mgr = RemoteStoreManager(host, port)
    yield server, mgr
    mgr.close()
    server.stop()


def test_ledger_echo_over_remote_store(served):
    """new client <-> new server: flagged ops come back with an echoed
    ledger block; the storage node's span carries the same fields."""
    _server, mgr = served
    store = mgr.open_database("edgestore")
    tx = mgr.begin_transaction()
    store.mutate(b"k", [(b"c1", b"v1"), (b"c2", b"v2")], [], tx)
    with ledger_scope() as led:
        with tracer.span("client.root") as root:
            entries = store.get_slice(KeySliceQuery(b"k", _SLICE), tx)
    assert len(entries) == 2
    assert mgr._remote_ledger is True
    assert led.get("cells_read") == 2
    assert led.get("bytes_read") == sum(
        len(c) + len(v) for c, v in entries
    )
    assert "store.remote" in led.to_dict().get("wall_ms_by_layer", {})
    trace = _wait_trace(
        root.trace_id,
        lambda t: any(s.name == "store.remote.getSlice" for s in t),
    )
    assert led.get("cells_read") == _span_ledger_sum(trace, "cells_read")


def test_new_client_against_old_server_falls_back_to_local_counting():
    server = RemoteStoreServer(
        InMemoryStoreManager(), ledger_echo=False
    ).start()
    host, port = server.address
    mgr = RemoteStoreManager(host, port)
    try:
        store = mgr.open_database("edgestore")
        tx = mgr.begin_transaction()
        store.mutate(b"k", [(b"c", b"vv")], [], tx)
        with ledger_scope() as led:
            with tracer.span("client.oldsrv") as root:
                store.get_slice(KeySliceQuery(b"k", _SLICE), tx)
        assert mgr._remote_ledger is False  # negotiated OFF
        # the client counted decoded entries locally, annotating ITS span
        assert led.get("cells_read") == 1
        assert root.attrs.get("ledger.cells_read") == 1
    finally:
        mgr.close()
        server.stop()


def test_old_client_against_new_server_stays_byte_compatible(served):
    """resource_ledger=False = a pre-ledger client: frames never carry the
    flag, the server replies with plain payloads."""
    _server, _ = served
    host, port = _server.address
    old = RemoteStoreManager(host, port, resource_ledger=False)
    try:
        store = old.open_database("edgestore")
        tx = old.begin_transaction()
        store.mutate(b"k", [(b"c", b"v")], [], tx)
        with ledger_scope() as led:
            entries = store.get_slice(KeySliceQuery(b"k", _SLICE), tx)
        assert entries == [(b"c", b"v")]
        # no echo, no local counting: the client is ledger-oblivious
        assert led.get("cells_read") == 0
    finally:
        old.close()


def test_scan_counts_rows_client_side(served):
    _server, mgr = served
    store = mgr.open_database("edgestore")
    tx = mgr.begin_transaction()
    for i in range(5):
        store.mutate(b"row%d" % i, [(b"c", b"v%d" % i)], [], tx)
    with ledger_scope() as led:
        rows = list(store.get_keys(_SLICE, tx))
    assert len(rows) == 5
    assert led.get("cells_read") == 5
    assert led.get("bytes_read") > 0


# ------------------------------------------------- remote index wire compat
def _index_fixture(ledger_echo=True):
    from janusgraph_tpu.indexing.memindex import InMemoryIndexProvider
    from janusgraph_tpu.indexing.provider import (
        IndexQuery,
        KeyInformation,
        Mapping,
        PredicateCondition,
    )
    from janusgraph_tpu.indexing.remote import (
        RemoteIndexProvider,
        RemoteIndexServer,
    )
    from janusgraph_tpu.core.predicates import Cmp

    backing = InMemoryIndexProvider()
    server = RemoteIndexServer(backing, ledger_echo=ledger_echo).start()
    host, port = server.address
    client = RemoteIndexProvider(hostname=host, port=port)
    info = KeyInformation(str, Mapping.STRING, "SINGLE")
    client.register("store", "name", info)
    client.mutate(
        {"store": {"d1": _mut([("name", "zeus")]),
                   "d2": _mut([("name", "zeus")])}},
        {"store": {"name": info}},
    )
    q = IndexQuery(PredicateCondition("name", Cmp.EQUAL, "zeus"))
    return server, client, q


def _mut(adds):
    from janusgraph_tpu.indexing.provider import IndexEntry, IndexMutation

    m = IndexMutation(is_new=True)
    for f, v in adds:
        m.additions.append(IndexEntry(f, v))
    return m


def test_index_ledger_echo_both_directions():
    # new <-> new: hits measured at the index node, echoed + merged
    server, client, q = _index_fixture()
    try:
        with ledger_scope() as led:
            with tracer.span("idx.client") as root:
                hits = client.query("store", q)
        assert sorted(hits) == ["d1", "d2"]
        assert client._remote_ledger is True
        assert led.get("index_hits") == 2
        trace = _wait_trace(
            root.trace_id,
            lambda t: any(s.name == "index.remote.query" for s in t),
        )
        assert _span_ledger_sum(trace, "index_hits") == 2
    finally:
        client.close()
        server.stop()

    # new client <-> old server: negotiated OFF, local fallback counts
    server, client, q = _index_fixture(ledger_echo=False)
    try:
        with ledger_scope() as led:
            hits = client.query("store", q)
        assert sorted(hits) == ["d1", "d2"]
        assert client._remote_ledger is False
        assert led.get("index_hits") == 2
    finally:
        client.close()
        server.stop()

    # old client <-> new server: byte-compatible, ledger-oblivious
    server, client, q = _index_fixture()
    try:
        from janusgraph_tpu.indexing.remote import RemoteIndexProvider

        old = RemoteIndexProvider(
            hostname=server.address[0], port=server.address[1],
            resource_ledger=False,
        )
        with ledger_scope() as led:
            hits = old.query("store", q)
        assert sorted(hits) == ["d1", "d2"]
        assert led.get("index_hits") == 0
        old.close()
    finally:
        client.close()
        server.stop()


# ----------------------------------------------------------- acceptance
def test_driver_query_ledger_totals_match_span_sums_and_flame():
    """THE acceptance property: one driver query against a
    remote-store-backed server yields a single trace whose ledger totals
    (cells read, bytes moved) equal the sum over its spans' ledger.*
    annotations; the same trace renders to valid collapsed-stack lines
    via `janusgraph_tpu flame <id>`."""
    store_server = RemoteStoreServer(InMemoryStoreManager()).start()
    host, port = store_server.address
    g = open_graph({
        "storage.backend": "remote",
        "storage.hostname": host,
        "storage.port": port,
        "ids.authority-wait-ms": 0.0,
    })
    m = JanusGraphManager()
    m.put_graph("graph", g)
    server = JanusGraphServer(manager=m).start()
    client = JanusGraphClient(port=server.port)
    try:
        tx = g.new_transaction()
        tx.add_vertex(name="costly")
        tx.commit()
        with ledger_scope() as led:
            assert client.submit(
                "g.V().has('name','costly').count()"
            ) == 1
        assert led.get("cells_read") > 0, led.to_dict()
        root = [
            r for r in tracer.recent() if r.name == "driver.submit"
        ][-1]
        trace = _wait_trace(
            root.trace_id,
            lambda t: (
                any(s.name == "server.request" for s in t)
                and _span_ledger_sum(t, "cells_read")
                >= led.get("cells_read")
            ),
        )
        # totals == span sums, for cells and for bytes moved
        for field in ("cells_read", "bytes_read", "cells_written",
                      "bytes_written"):
            assert led.get(field) == _span_ledger_sum(trace, field), field

        # flame export of the same trace: valid collapsed-stack lines
        from janusgraph_tpu.cli import main as cli_main
        import io
        import contextlib

        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = cli_main(["flame", f"{root.trace_id:016x}"])
        assert rc == 0
        lines = [ln for ln in buf.getvalue().splitlines() if ln]
        assert lines
        frame_line = re.compile(r"^[^;\s]+(;[^;\s]+)* \d+$")
        for ln in lines:
            assert frame_line.match(ln), ln
        joined = "\n".join(lines)
        assert "driver.submit" in joined
        assert "server.request" in joined
        # server-side spans fold UNDER the driver root (stitched graft)
        assert any(
            ln.startswith("driver.submit;") and "server.request" in ln
            for ln in lines
        ), lines
        # ledger annotations fold into frame names
        assert "cells_read:" in joined
    finally:
        server.stop()
        g.close()
        store_server.stop()


def test_server_echoes_status_ledger_and_profile_endpoint():
    g = open_graph({"ids.authority-wait-ms": 0.0})
    m = JanusGraphManager()
    m.put_graph("graph", g)
    server = JanusGraphServer(manager=m).start()
    try:
        tx = g.new_transaction()
        tx.add_vertex(name="hera")
        tx.commit()
        body = json.dumps({"gremlin": "g.V().count()"}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/gremlin", data=body,
            method="POST",
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req) as resp:
            payload = json.loads(resp.read())
        ledger = payload["status"].get("ledger")
        assert ledger and ledger.get("cells_read", 0) > 0
        # GET /profile serves the digest table, the just-run shape ranked
        with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/profile"
        ) as resp:
            prof = json.loads(resp.read())
        assert any(
            "full-scan" in d["shape"] for d in prof["digests"]
        ), prof
        # GET /profile/flame of the request's trace -> text lines
        trace_id = payload["status"]["trace"]
        with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/profile/flame?trace={trace_id}"
        ) as resp:
            text = resp.read().decode()
        assert "server.request" in text
    finally:
        server.stop()
        g.close()


# -------------------------------------------------------------- digests
def test_digest_ignores_literals_and_separates_shapes():
    g = open_graph({"ids.authority-wait-ms": 0.0})
    try:
        mgmt = g.management()
        mgmt.make_property_key("uid", int)
        mgmt.build_composite_index("byUid", ["uid"])
        tx = g.new_transaction()
        for i in range(4):
            tx.add_vertex(uid=i)
        tx.commit()
        digest_table.reset()
        src = g.traversal()
        src.V().has("uid", 1).to_list()
        src.V().has("uid", 2).to_list()  # same shape, different literal
        src.V().has("uid", 3).values("uid").to_list()  # extra step
        src.tx.rollback()
        top = digest_table.top(10)
        by_shape = {d["shape"]: d for d in top}
        indexed = [d for d in top if "byUid" in d["shape"]]
        assert indexed, top  # index choice is part of the shape
        same = [d for d in indexed if d["count"] == 2]
        assert same, top  # the two literal-variants share one digest
        assert len(indexed) == 2, top  # count() split into its own shape
        assert all(
            d["digest"] == shape_digest(d["shape"]) for d in top
        )
        assert by_shape  # sanity: table rendered
    finally:
        g.close()


def test_digest_table_bounded_eviction_keeps_heavy_hitters():
    t = __import__(
        "janusgraph_tpu.observability.profiler", fromlist=["DigestTable"]
    ).DigestTable(capacity=3)
    t.observe("aa", "heavy", 100.0)
    for i in range(5):
        t.observe(f"l{i}", f"light{i}", 0.5)
    assert len(t) <= 3
    assert any(d["digest"] == "aa" for d in t.top(10))


def test_profile_returns_resources_block():
    g = open_graph({"ids.authority-wait-ms": 0.0})
    try:
        tx = g.new_transaction()
        tx.add_vertex(name="ares")
        tx.commit()
        metrics = g.traversal().V().has("name", "ares").profile()
        assert metrics.resources.get("cells_read", 0) > 0
        assert metrics.as_dict()["annotations"]["resources"] == (
            metrics.resources
        )
    finally:
        g.close()


def test_slow_span_events_carry_digest():
    from janusgraph_tpu.observability import flight_recorder

    g = open_graph({
        "ids.authority-wait-ms": 0.0,
        "metrics.slow-op-threshold-ms": 0.0001,
    })
    try:
        tx = g.new_transaction()
        tx.add_vertex(name="slowpoke")
        tx.commit()
        g.traversal().V().profile()  # runs under the oltp.traversal span
        slow = [
            e for e in tracer.slow_ops()
            if e["attrs"].get("digest")
        ]
        assert slow, tracer.slow_ops()
        digest = slow[-1]["attrs"]["digest"]
        flights = [
            e for e in flight_recorder.events("slow_span")
            if e.get("digest") == digest
        ]
        assert flights
    finally:
        tracer.configure(slow_threshold_ms=100.0)
        g.close()


def test_traversal_shape_normalization():
    shape = traversal_shape(
        ["adjacentVertexHasId(1, 7)", "has", "out", "count"],
        {"access": "composite-index", "index": "byUid"},
    )
    assert shape == "composite-index[byUid]>adjacentVertexHasId>has>out>count"
    # digits and quoted literals are stripped
    assert traversal_shape(["limit5"], {}) == "traversal>limit"


# -------------------------------------------------------------- roofline
def test_tpu_run_records_report_roofline_via_cost_analysis():
    from janusgraph_tpu.olap.generators import rmat_csr
    from janusgraph_tpu.olap.programs import PageRankProgram
    from janusgraph_tpu.olap.tpu_executor import TPUExecutor

    csr = rmat_csr(7, 4)
    ex = TPUExecutor(csr)
    with ledger_scope() as led:
        ex.run(PageRankProgram(max_iterations=4, tol=0.0))
    info = ex.last_run_info
    records = info["superstep_records"]
    assert records
    for r in records:
        assert r["flops"] > 0
        assert r["bytes_accessed"] > 0
        assert r["operational_intensity"] > 0
        assert r["roofline_utilization"] is None or (
            r["roofline_utilization"] >= 0
        )
        assert r["cost_source"] == "xla"  # CPU XLA exposes cost_analysis
    assert info["roofline"]["peak_flops"] > 0
    assert "dense" in info["roofline_by_tier"]
    assert info["resources"]["h2d_bytes"] == info["h2d_arg_bytes"]
    # the run billed its transfer bytes to the ambient ledger
    assert led.get("h2d_bytes") == info["h2d_arg_bytes"]
    assert led.get("d2h_bytes") == info["d2h_bytes"]


def test_tpu_roofline_estimator_fallback(monkeypatch):
    from janusgraph_tpu.observability import profiler
    from janusgraph_tpu.olap.generators import rmat_csr
    from janusgraph_tpu.olap.programs import PageRankProgram
    from janusgraph_tpu.olap.tpu_executor import TPUExecutor

    monkeypatch.setattr(profiler, "harvest_cost", lambda lowered: None)
    csr = rmat_csr(7, 4)
    ex = TPUExecutor(csr)
    ex.run(PageRankProgram(max_iterations=3, tol=0.0))
    records = ex.last_run_info["superstep_records"]
    assert records
    for r in records:
        assert r["cost_source"] == "estimate"
        assert r["flops"] > 0
        assert r["operational_intensity"] > 0


def test_cpu_run_records_report_roofline():
    from janusgraph_tpu.olap.cpu_executor import CPUExecutor
    from janusgraph_tpu.olap.generators import rmat_csr
    from janusgraph_tpu.olap.programs import PageRankProgram

    csr = rmat_csr(6, 4)
    ex = CPUExecutor(csr)
    ex.run(PageRankProgram(max_iterations=3, tol=0.0))
    info = ex.last_run_info
    assert info["path"] == "cpu"
    assert len(info["superstep_records"]) == 3
    for r in info["superstep_records"]:
        assert r["flops"] > 0
        assert r["bytes_accessed"] > 0
        assert r["operational_intensity"] > 0
        assert r["cost_source"] == "estimate"
    assert info["resources"]["flops"] > 0


def test_roofline_peak_config_override():
    from janusgraph_tpu.observability import profiler

    try:
        profiler.configure_roofline(
            peak_flops=1e12, peak_bytes_per_s=1e11
        )
        peaks = profiler.device_peaks("anything")
        assert peaks["peak_flops"] == 1e12
        assert peaks["peak_bytes_per_s"] == 1e11
        assert peaks["source"] == "config"
        point = profiler.roofline_point(1e9, 1e8, 10.0, peaks)
        # oi = 10 flops/byte -> roof = min(1e12, 10 * 1e11) = 1e12;
        # achieved = 1e9 / 0.01s = 1e11 -> utilization 0.1
        assert point["operational_intensity"] == 10.0
        assert abs(point["roofline_utilization"] - 0.1) < 1e-9
    finally:
        profiler.configure_roofline(peak_flops=0.0, peak_bytes_per_s=0.0)


# ------------------------------------------------------------------- CLI
def test_cli_top_command(capsys):
    from janusgraph_tpu.cli import main as cli_main

    digest_table.reset()
    digest_table.observe("abcd1234", "full-scan>count", 5.0, cells=7)
    assert cli_main(["top", "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["digests"][0]["digest"] == "abcd1234"
    assert cli_main(["top"]) == 0
    assert "full-scan>count" in capsys.readouterr().out


def test_cli_flame_unknown_trace_fails():
    from janusgraph_tpu.cli import main as cli_main

    assert cli_main(["flame", "00000000deadbeef"]) == 1


def test_flame_lines_self_time_and_graft():
    from janusgraph_tpu.observability.spans import Span, Tracer

    t = Tracer()
    with t.span("root") as root:
        with t.span("child"):
            time.sleep(0.002)
    # a remote-parented local root grafts under the retained parent
    with t.child_span(root.context(), "remote.op"):
        pass
    lines = flame_lines(t.find_trace(root.trace_id))
    stacks = {ln.rsplit(" ", 1)[0] for ln in lines}
    assert "root" in stacks
    assert "root;child" in stacks
    assert "root;remote.op" in stacks
    for ln in lines:
        assert int(ln.rsplit(" ", 1)[1]) >= 0
