"""Mixed-index subsystem tests: provider SPI contract + graph integration
(reference test model: IndexProviderTest.java:1290 SPI contract,
JanusGraphIndexTest.java mixed-index graph behavior)."""

import pytest

from janusgraph_tpu.core.graph import open_graph
from janusgraph_tpu.core.predicates import Cmp, Geo, Geoshape, Text
from janusgraph_tpu.core.traversal import P
from janusgraph_tpu.exceptions import SchemaViolationError
from janusgraph_tpu.indexing import (
    And,
    IndexMutation,
    IndexQuery,
    InMemoryIndexProvider,
    KeyInformation,
    Mapping,
    Not,
    Or,
    Order,
    PredicateCondition,
    RawQuery,
)
from janusgraph_tpu.storage.inmemory import InMemoryStoreManager


# ---------------------------------------------------------------- SPI contract
# Abstract-suite pattern (reference: IndexProviderTest.java parameterized per
# backend): every SPI-contract test below runs against BOTH the in-memory
# provider and the persistent localindex provider.
@pytest.fixture(params=["memindex", "localindex", "remote"])
def provider(request, tmp_path):
    server = None
    if request.param == "memindex":
        p = InMemoryIndexProvider()
    elif request.param == "remote":
        # the networked tier: a localindex served over TCP, queried through
        # the wire client (reference: janusgraph-es RestElasticSearchClient)
        from janusgraph_tpu.indexing import (
            LocalIndexProvider,
            RemoteIndexProvider,
            RemoteIndexServer,
        )

        backend = LocalIndexProvider(directory=str(tmp_path / "idx"))
        server = RemoteIndexServer(backend).start()
        host, port = server.address
        p = RemoteIndexProvider(hostname=host, port=port)
    else:
        from janusgraph_tpu.indexing import LocalIndexProvider

        p = LocalIndexProvider(directory=str(tmp_path / "idx"))
    p.register("store", "name", KeyInformation(str, Mapping.TEXT))
    p.register("store", "title", KeyInformation(str, Mapping.STRING))
    p.register("store", "weight", KeyInformation(float))
    p.register("store", "loc", KeyInformation(Geoshape))
    docs = {
        "d1": [("name", "Hercules son of Zeus"), ("weight", 0.5),
               ("title", "hero"), ("loc", Geoshape.point(37.97, 23.72))],
        "d2": [("name", "Zeus god of thunder"), ("weight", 2.5),
               ("title", "god"), ("loc", Geoshape.point(52.5, 13.4))],
        "d3": [("name", "Cerberus hound"), ("weight", 1.0),
               ("title", "monster"), ("loc", Geoshape.point(38.0, 23.7))],
    }
    muts = {"store": {}}
    for docid, fields in docs.items():
        m = IndexMutation(is_new=True)
        for f, v in fields:
            m.add(f, v)
        muts["store"][docid] = m
    p.mutate(muts, {})
    yield p
    p.close()
    if server is not None:
        server.stop()


def q(cond, **kw):
    return IndexQuery(cond, **kw)


def test_text_contains_query(provider):
    hits = provider.query("store", q(PredicateCondition("name", Text.CONTAINS, "zeus")))
    assert set(hits) == {"d1", "d2"}


def test_string_exact_query(provider):
    hits = provider.query("store", q(PredicateCondition("title", Cmp.EQUAL, "god")))
    assert hits == ["d2"]


def test_numeric_range_query(provider):
    hits = provider.query(
        "store", q(PredicateCondition("weight", Cmp.GREATER_THAN, 0.6))
    )
    assert set(hits) == {"d2", "d3"}
    hits = provider.query(
        "store", q(PredicateCondition("weight", Cmp.LESS_THAN_EQUAL, 1.0))
    )
    assert set(hits) == {"d1", "d3"}


def test_geo_query(provider):
    athens_area = Geoshape.circle(38.0, 23.7, 100)
    hits = provider.query(
        "store", q(PredicateCondition("loc", Geo.WITHIN, athens_area))
    )
    assert set(hits) == {"d1", "d3"}


def test_boolean_conditions(provider):
    cond = And(
        (
            PredicateCondition("name", Text.CONTAINS, "zeus"),
            PredicateCondition("weight", Cmp.GREATER_THAN, 1.0),
        )
    )
    assert provider.query("store", q(cond)) == ["d2"]
    cond = Or(
        (
            PredicateCondition("title", Cmp.EQUAL, "god"),
            PredicateCondition("title", Cmp.EQUAL, "hero"),
        )
    )
    assert set(provider.query("store", q(cond))) == {"d1", "d2"}
    cond = Not(PredicateCondition("name", Text.CONTAINS, "zeus"))
    assert provider.query("store", q(cond)) == ["d3"]


def test_order_limit_offset(provider):
    cond = PredicateCondition("weight", Cmp.GREATER_THAN, 0.0)
    ordered = provider.query(
        "store", q(cond, orders=(Order("weight"),))
    )
    assert ordered == ["d1", "d3", "d2"]
    desc = provider.query("store", q(cond, orders=(Order("weight", desc=True),)))
    assert desc == ["d2", "d3", "d1"]
    assert provider.query("store", q(cond, limit=1, offset=1)) == ["d2"]


def test_mutation_update_delete(provider):
    m = IndexMutation()
    m.delete("title", "hero")
    m.add("title", "demigod")
    provider.mutate({"store": {"d1": m}}, {})
    assert provider.query(
        "store", q(PredicateCondition("title", Cmp.EQUAL, "demigod"))
    ) == ["d1"]
    m = IndexMutation(is_deleted=True)
    provider.mutate({"store": {"d1": m}}, {})
    assert provider.query(
        "store", q(PredicateCondition("name", Text.CONTAINS, "hercules"))
    ) == []


def test_restore_overwrites(provider):
    from janusgraph_tpu.indexing import IndexEntry

    provider.restore(
        {"store": {"d2": [IndexEntry("title", "skyfather")]}}, {}
    )
    assert provider.query(
        "store", q(PredicateCondition("title", Cmp.EQUAL, "skyfather"))
    ) == ["d2"]
    # old fields gone
    assert provider.query(
        "store", q(PredicateCondition("name", Text.CONTAINS, "zeus"))
    ) == ["d1"]


def test_raw_query_and_totals(provider):
    hits = provider.raw_query("store", RawQuery("v.name:zeus"))
    assert {d for d, _ in hits} == {"d1", "d2"}
    assert provider.totals("store", RawQuery("name:zeus")) == 2


def test_supports(provider):
    text_info = KeyInformation(str, Mapping.TEXT)
    string_info = KeyInformation(str, Mapping.STRING)
    both_info = KeyInformation(str, Mapping.TEXTSTRING)
    assert provider.supports(text_info, Text.CONTAINS)
    assert not provider.supports(text_info, Text.PREFIX)
    assert provider.supports(string_info, Text.PREFIX)
    assert not provider.supports(string_info, Text.CONTAINS)
    assert provider.supports(both_info, Text.CONTAINS)
    assert provider.supports(both_info, Text.PREFIX)
    assert provider.supports(KeyInformation(float), Cmp.LESS_THAN)
    assert provider.supports(KeyInformation(Geoshape), Geo.INTERSECT)


# ------------------------------------------------------------ graph integration
@pytest.fixture
def graph():
    g = open_graph({"schema.default": "auto"})
    yield g
    g.close()


def _load_people(g):
    mgmt = g.management()
    mgmt.make_property_key("bio", str)
    mgmt.make_property_key("age", int)
    mgmt.build_mixed_index("people", ["bio", "age"], backing="search")
    tx = g.new_transaction()
    a = tx.add_vertex(bio="fought the nemean lion", age=30)
    b = tx.add_vertex(bio="god of thunder and sky", age=5000)
    c = tx.add_vertex(bio="three headed hound", age=100)
    tx.commit()
    return a.id, b.id, c.id


def test_mixed_index_traversal_query(graph):
    a, b, c = _load_people(graph)
    g = graph.traversal()
    hits = g.V().has("bio", P.text_contains("thunder")).to_list()
    assert [v.id for v in hits] == [b]
    hits = g.V().has("age", P.lt(500)).to_list()
    assert {v.id for v in hits} == {a, c}
    # combined: both conditions pushed to the same index
    hits = g.V().has("bio", P.text_contains("hound")).has("age", P.gt(50)).to_list()
    assert [v.id for v in hits] == [c]


def test_mixed_index_sees_updates_and_removals(graph):
    a, b, c = _load_people(graph)
    tx = graph.new_transaction()
    v = tx.get_vertex(a)
    tx.add_property(v, "bio", "slew the hydra")
    tx.commit()
    g = graph.traversal()
    assert [v.id for v in g.V().has("bio", P.text_contains("hydra")).to_list()] == [a]
    assert g.V().has("bio", P.text_contains("nemean")).to_list() == []
    tx = graph.new_transaction()
    tx.remove_vertex(tx.get_vertex(c))
    tx.commit()
    g = graph.traversal()
    assert g.V().has("bio", P.text_contains("hound")).to_list() == []


def test_mixed_index_tx_visibility(graph):
    """Uncommitted writes are visible to the writing tx via overlay."""
    _load_people(graph)
    g = graph.traversal()
    g.add_v(bio="swift messenger of the gods", age=900)
    hits = g.V().has("bio", P.text_contains("messenger")).to_list()
    assert len(hits) == 1


def test_raw_index_query_on_graph(graph):
    a, b, c = _load_people(graph)
    hits = graph.index_query("people", "v.bio:hound")
    assert [vid for vid, _ in hits] == [c]
    assert graph.index_totals("people", "bio:god") == 1


def test_mixed_index_label_constraint(graph):
    mgmt = graph.management()
    mgmt.make_property_key("motto", str)
    mgmt.make_vertex_label("clan")
    mgmt.build_mixed_index("clans", ["motto"], backing="search", label="clan")
    tx = graph.new_transaction()
    tx.add_vertex("clan", motto="strength and honor")
    tx.add_vertex(motto="strength in numbers")  # not a clan
    tx.commit()
    g = graph.traversal()
    hits = g.V().has_label("clan").has("motto", P.text_contains("strength")).to_list()
    assert len(hits) == 1


def test_string_mapping(graph):
    mgmt = graph.management()
    mgmt.make_property_key("code", str)
    mgmt.build_mixed_index(
        "codes", ["code"], backing="search", mappings={"code": "STRING"}
    )
    tx = graph.new_transaction()
    v = tx.add_vertex(code="ABC-123")
    tx.commit()
    g = graph.traversal()
    assert len(g.V().has("code", P.text_prefix("ABC")).to_list()) == 1
    assert len(g.V().has("code", P.eq("ABC-123")).to_list()) == 1


def test_geo_mixed_index(graph):
    mgmt = graph.management()
    mgmt.make_property_key("spot", Geoshape)
    mgmt.build_mixed_index("places", ["spot"], backing="search")
    tx = graph.new_transaction()
    athens = tx.add_vertex(spot=Geoshape.point(37.97, 23.72))
    berlin = tx.add_vertex(spot=Geoshape.point(52.5, 13.4))
    tx.commit()
    g = graph.traversal()
    hits = g.V().has(
        "spot", P.geo_within(Geoshape.circle(38.0, 23.7, 100))
    ).to_list()
    assert [v.id for v in hits] == [athens.id]


def test_add_index_key(graph):
    mgmt = graph.management()
    mgmt.make_property_key("alpha", str)
    mgmt.make_property_key("beta", str)
    mgmt.build_mixed_index("ab", ["alpha"], backing="search")
    mgmt.add_index_key("ab", "beta", mapping="TEXT")
    tx = graph.new_transaction()
    v = tx.add_vertex(alpha="one", beta="two three")
    tx.commit()
    g = graph.traversal()
    assert len(g.V().has("beta", P.text_contains("three")).to_list()) == 1
    idx = graph.indexes["ab"]
    assert len(idx.key_ids) == 2


def test_mixed_index_survives_reopen():
    sm = InMemoryStoreManager()
    g = open_graph({"schema.default": "auto"}, store_manager=sm)
    mgmt = g.management()
    mgmt.make_property_key("t", str)
    mgmt.build_mixed_index("ti", ["t"], backing="search")
    tx = g.new_transaction()
    tx.add_vertex(t="persistent words")
    tx.commit()
    g.close()
    g2 = open_graph({"schema.default": "auto"}, store_manager=sm)
    tr = g2.traversal()
    assert len(tr.V().has("t", P.text_contains("persistent")).to_list()) == 1
    g2.close()


def test_mixed_failure_heals_via_recovery():
    """Injected mixed-index failure -> WAL secondary-failure -> recovery
    restores the documents from primary storage (reference:
    StandardTransactionLogProcessor.fixSecondaryFailure)."""
    sm = InMemoryStoreManager()
    g = open_graph(
        {"schema.default": "auto", "tx.log-tx": True}, store_manager=sm
    )
    mgmt = g.management()
    mgmt.make_property_key("note", str)
    mgmt.build_mixed_index("notes", ["note"], backing="search")
    tx = g.new_transaction()
    tx._fail_mixed_for_test = True
    tx.add_vertex(note="lost then found")
    tx.commit()
    tr = g.traversal()
    assert tr.V().has("note", P.text_contains("lost")).to_list() == []
    healed = g.start_transaction_recovery().run(max_commit_time_ms=0.0)
    assert len(healed) >= 1
    tr = g.traversal()
    assert len(tr.V().has("note", P.text_contains("lost")).to_list()) == 1
    g.close()


def test_build_mixed_index_validation(graph):
    mgmt = graph.management()
    mgmt.make_property_key("x", str)
    with pytest.raises(SchemaViolationError):
        mgmt.build_mixed_index("bad", ["x"], backing="nope")
    with pytest.raises(SchemaViolationError):
        mgmt.build_mixed_index("bad2", [], backing="search")
    mgmt.build_mixed_index("ok", ["x"], backing="search")
    with pytest.raises(SchemaViolationError):
        mgmt.build_mixed_index("ok", ["x"], backing="search")


# ---------------------------------------------------- localindex persistence
def _mk_local(tmp_path, name="idx"):
    from janusgraph_tpu.indexing import LocalIndexProvider

    return LocalIndexProvider(directory=str(tmp_path / name))


def test_localindex_survives_reopen(tmp_path):
    p = _mk_local(tmp_path)
    p.register("s", "name", KeyInformation(str, Mapping.TEXT))
    p.register("s", "score", KeyInformation(float))
    m = IndexMutation(is_new=True)
    m.add("name", "cerberus the hound")
    m.add("score", 4.5)
    p.mutate({"s": {"doc9": m}}, {})
    p.close()

    p2 = _mk_local(tmp_path)
    assert p2.query(
        "s", IndexQuery(PredicateCondition("name", Text.CONTAINS, "hound"))
    ) == ["doc9"]
    assert p2.query(
        "s", IndexQuery(PredicateCondition("score", Cmp.GREATER_THAN, 4.0))
    ) == ["doc9"]
    # field metadata (mapping) also persisted
    assert p2.supports(
        KeyInformation(str, Mapping.TEXT), Text.CONTAINS
    )
    p2.close()


def test_localindex_survives_compaction(tmp_path):
    p = _mk_local(tmp_path)
    p.register("s", "w", KeyInformation(float))
    for i in range(20):
        m = IndexMutation(is_new=True)
        m.add("w", float(i))
        p.mutate({"s": {f"d{i}": m}}, {})
    p.compact()
    p.close()
    p2 = _mk_local(tmp_path)
    hits = p2.query(
        "s",
        IndexQuery(PredicateCondition("w", Cmp.GREATER_THAN_EQUAL, 17.0)),
    )
    assert sorted(hits) == ["d17", "d18", "d19"]
    p2.close()


def test_localindex_range_is_contiguous_scan(tmp_path):
    """Numeric ranges resolve via ONE ordered-KV range scan, not a doc scan."""
    p = _mk_local(tmp_path)
    p.register("s", "w", KeyInformation(float))
    for i in range(50):
        m = IndexMutation(is_new=True)
        m.add("w", float(i))
        p.mutate({"s": {f"d{i:02d}": m}}, {})
    calls = []
    orig = p._kv.scan

    def spy(start, end, txh):
        calls.append((start, end))
        return orig(start, end, txh)

    p._kv.scan = spy
    hits = p.query(
        "s", IndexQuery(PredicateCondition("w", Cmp.LESS_THAN, 3.0))
    )
    assert sorted(hits) == ["d00", "d01", "d02"]
    assert len(calls) == 1  # one contiguous posting-range scan
    p.close()


def test_graph_with_localindex_backing(tmp_path):
    g = open_graph({
        "schema.default": "auto",
        "index.search.backend": "localindex",
        "index.search.directory": str(tmp_path / "gidx"),
    })
    mgmt = g.management()
    mgmt.make_property_key("bio", str)
    mgmt.make_property_key("age", int)
    mgmt.build_mixed_index("people", ["bio", "age"], backing="search")
    tx = g.new_transaction()
    a = tx.add_vertex(bio="fought the nemean lion", age=30)
    b = tx.add_vertex(bio="god of thunder and sky", age=5000)
    tx.commit()
    t = g.traversal()
    hits = t.V().has("bio", P.text_contains("thunder")).to_list()
    assert [v.id for v in hits] == [b.id]
    hits = t.V().has("age", P.lt(500)).to_list()
    assert [v.id for v in hits] == [a.id]
    g.close()


def test_localindex_reindex_existing_data(tmp_path):
    """REINDEX repopulates the persistent provider from primary storage
    (restore path) for data written before the index existed."""
    from janusgraph_tpu.core.management import SchemaAction

    g = open_graph({
        "schema.default": "auto",
        "index.search.backend": "localindex",
        "index.search.directory": str(tmp_path / "ridx"),
    })
    tx = g.new_transaction()
    a = tx.add_vertex(story="the hydra grew two heads")
    tx.commit()
    mgmt = g.management()
    idx = mgmt.build_mixed_index("stories", ["story"], backing="search")
    mgmt.update_index("stories", SchemaAction.REINDEX)
    hits = g.traversal().V().has("story", P.text_contains("hydra")).to_list()
    assert [v.id for v in hits] == [a.id]
    g.close()


def test_localindex_cross_type_numeric_conditions(tmp_path):
    """Int conditions on float fields (and vice versa) must behave like the
    in-memory provider: conditions encode in the FIELD's value space."""
    p = _mk_local(tmp_path)
    p.register("s", "w", KeyInformation(float))
    p.register("s", "n", KeyInformation(int))
    m = IndexMutation(is_new=True)
    m.add("w", 0.5)
    m.add("n", 2)
    p.mutate({"s": {"d1": m}}, {})
    # int condition on float field
    assert p.query("s", IndexQuery(PredicateCondition("w", Cmp.LESS_THAN, 3))) == ["d1"]
    assert p.query("s", IndexQuery(PredicateCondition("w", Cmp.GREATER_THAN, 3))) == []
    # non-integral float condition on int field: exact range rewrite
    assert p.query("s", IndexQuery(PredicateCondition("n", Cmp.GREATER_THAN, 1.5))) == ["d1"]
    assert p.query("s", IndexQuery(PredicateCondition("n", Cmp.LESS_THAN, 1.5))) == []
    assert p.query("s", IndexQuery(PredicateCondition("n", Cmp.EQUAL, 1.5))) == []
    assert p.query("s", IndexQuery(PredicateCondition("n", Cmp.EQUAL, 2.0))) == ["d1"]
    p.close()


def test_localindex_write_side_value_coercion(tmp_path):
    """Values stored with a looser Python type than the field's registered
    type must still be reachable by typed conditions (parity with the
    in-memory provider's behavior)."""
    p = _mk_local(tmp_path)
    p.register("s", "w", KeyInformation(float))
    m = IndexMutation(is_new=True)
    m.add("w", 2)  # int value on a float field
    p.mutate({"s": {"d1": m}}, {})
    assert p.query("s", IndexQuery(PredicateCondition("w", Cmp.EQUAL, 2.0))) == ["d1"]
    assert p.query("s", IndexQuery(PredicateCondition("w", Cmp.EQUAL, 2))) == ["d1"]
    p.close()


def test_localindex_bulk_list_values(tmp_path):
    """A large LIST-cardinality mutation completes quickly (batched doc
    encoding) and survives the u32 value count."""
    import time as _time

    p = _mk_local(tmp_path)
    p.register("s", "tags", KeyInformation(float, cardinality="LIST"))
    m = IndexMutation(is_new=True)
    for i in range(70_000):
        m.add("tags", float(i))
    t0 = _time.perf_counter()
    p.mutate({"s": {"d1": m}}, {})
    assert _time.perf_counter() - t0 < 20.0
    hits = p.query(
        "s", IndexQuery(PredicateCondition("tags", Cmp.GREATER_THAN, 69_998.0))
    )
    assert hits == ["d1"]
    p.close()


def test_localindex_bulk_list_deletion_fast(tmp_path):
    """Batched deletions mirror batched adds (no O(n^2) re-encoding)."""
    import time as _time

    p = _mk_local(tmp_path)
    p.register("s", "tags", KeyInformation(float, cardinality="LIST"))
    m = IndexMutation(is_new=True)
    for i in range(40_000):
        m.add("tags", float(i))
    p.mutate({"s": {"d1": m}}, {})
    d = IndexMutation()
    for i in range(40_000):
        d.delete("tags", float(i))
    t0 = _time.perf_counter()
    p.mutate({"s": {"d1": d}}, {})
    assert _time.perf_counter() - t0 < 15.0
    assert p.query(
        "s", IndexQuery(PredicateCondition("tags", Cmp.GREATER_THAN_EQUAL, 0.0))
    ) == []
    p.close()


def test_localindex_rejects_foreign_format(tmp_path):
    from janusgraph_tpu.exceptions import BackendError
    import struct as _struct

    p = _mk_local(tmp_path)
    p.register("s", "w", KeyInformation(float))
    m = IndexMutation(is_new=True)
    m.add("w", 1.0)
    p.mutate({"s": {"d1": m}}, {})
    # simulate a directory written by a different format version
    p._kv.insert(p._VKEY, _struct.pack(">I", 1), p._tx)
    p._tx.commit()
    p.close()
    with pytest.raises(BackendError, match="format"):
        _mk_local(tmp_path)


# -------------------------------------------------------------- remote tier
def test_remote_index_restore_and_features(tmp_path):
    """restore() and features() cross the wire intact (reference:
    IndexProvider.restore used by recovery/reindex; ES features flags)."""
    from janusgraph_tpu.indexing import (
        IndexEntry,
        LocalIndexProvider,
        RemoteIndexProvider,
        RemoteIndexServer,
    )

    backend = LocalIndexProvider(directory=str(tmp_path / "idx"))
    server = RemoteIndexServer(backend).start()
    host, port = server.address
    p = RemoteIndexProvider(hostname=host, port=port)
    try:
        assert p.features().supports_geo == backend.features().supports_geo
        p.register("s", "name", KeyInformation(str, Mapping.TEXT))
        p.restore(
            {"s": {"d9": [IndexEntry("name", "restored hydra document")]}},
            {"s": {"name": KeyInformation(str, Mapping.TEXT)}},
        )
        from janusgraph_tpu.core.predicates import Text

        assert p.query(
            "s",
            IndexQuery(PredicateCondition("name", Text.CONTAINS, "hydra")),
        ) == ["d9"]
        assert p.exists()
        # supports() memoizes: second identical ask answers without a call
        info = KeyInformation(str, Mapping.TEXT)
        assert p.supports(info, Text.CONTAINS)
        n_before = p._pool_idx
        assert p.supports(info, Text.CONTAINS)
        assert p._pool_idx == n_before
    finally:
        p.close()
        server.stop()


def test_remote_index_error_mapping(tmp_path):
    """Server-side failures surface as PermanentBackendError with the
    original type name, not broken sockets."""
    from janusgraph_tpu.exceptions import PermanentBackendError
    from janusgraph_tpu.indexing import (
        InMemoryIndexProvider,
        RemoteIndexProvider,
        RemoteIndexServer,
    )

    server = RemoteIndexServer(InMemoryIndexProvider()).start()
    host, port = server.address
    p = RemoteIndexProvider(hostname=host, port=port, retry_time_s=0.5)
    try:
        with pytest.raises(PermanentBackendError):
            p._call(99, b"")  # unknown op: server maps to PERM status
        with pytest.raises(PermanentBackendError):
            # malformed body: server-side decode failure crosses back as a
            # permanent error, and the connection stays usable after it
            p._call(4, b"\xff\xff")
        # connection still serves real requests after both failures
        p.register("s", "w", KeyInformation(float))
        m = IndexMutation(is_new=True)
        m.add("w", 1.5)
        p.mutate({"s": {"d1": m}}, {"s": {"w": KeyInformation(float)}})
        assert p.query(
            "s", IndexQuery(PredicateCondition("w", Cmp.GREATER_THAN, 1.0))
        ) == ["d1"]
    finally:
        p.close()
        server.stop()


def test_graph_with_remote_storage_and_remote_index(tmp_path):
    """The full networked deployment shape: graph -> TCP storage backend +
    TCP index provider (reference: cql + es deployment,
    janusgraph-dist config recipes)."""
    from janusgraph_tpu.indexing import (
        LocalIndexProvider,
        RemoteIndexServer,
    )
    from janusgraph_tpu.storage.inmemory import InMemoryStoreManager
    from janusgraph_tpu.storage.remote import (
        RemoteStoreManager,
        RemoteStoreServer,
    )

    store_srv = RemoteStoreServer(InMemoryStoreManager()).start()
    idx_srv = RemoteIndexServer(
        LocalIndexProvider(directory=str(tmp_path / "ridx"))
    ).start()
    sm = RemoteStoreManager(*store_srv.address)
    g = open_graph(
        {
            "schema.default": "auto",
            "index.search.backend": "remote",
            "index.search.hostname": idx_srv.address[0],
            "index.search.port": idx_srv.address[1],
        },
        store_manager=sm,
    )
    try:
        mgmt = g.management()
        mgmt.make_property_key("bio", str)
        mgmt.make_property_key("age", int)
        mgmt.build_mixed_index("people", ["bio", "age"], backing="search")
        tx = g.new_transaction()
        a = tx.add_vertex(bio="fought the nemean lion", age=30)
        b = tx.add_vertex(bio="god of thunder and sky", age=5000)
        tx.commit()
        t = g.traversal()
        hits = t.V().has("bio", P.text_contains("thunder")).to_list()
        assert [v.id for v in hits] == [b.id]
        hits = t.V().has("age", P.lt(500)).to_list()
        assert [v.id for v in hits] == [a.id]
        # removal propagates over the wire
        tx = g.new_transaction()
        tx.get_vertex(b.id).remove()
        tx.commit()
        assert g.traversal().V().has(
            "bio", P.text_contains("thunder")
        ).to_list() == []
    finally:
        g.close()
        store_srv.stop()
        idx_srv.stop()


def test_query_stream_pages_through_results(tmp_path):
    """Scroll-API analogue: query_stream pages through a large result set
    and matches the one-shot query (reference: ElasticSearchScroll.java:80)."""
    from janusgraph_tpu.indexing import (
        InMemoryIndexProvider,
        LocalIndexProvider,
        RemoteIndexProvider,
        RemoteIndexServer,
    )

    backend = LocalIndexProvider(directory=str(tmp_path / "sidx"))
    server = RemoteIndexServer(backend).start()
    remote = RemoteIndexProvider(
        hostname=server.address[0], port=server.address[1]
    )
    mem = InMemoryIndexProvider()
    try:
        for p in (backend, mem):
            p.register("s", "w", KeyInformation(float))
        m = {"s": {}}
        for i in range(57):
            mu = IndexMutation(is_new=True)
            mu.add("w", float(i))
            m["s"][f"d{i:03}"] = mu
        backend.mutate(m, {})
        # rebuild equivalent mutations for the independent mem provider
        m2 = {"s": {}}
        for i in range(57):
            mu = IndexMutation(is_new=True)
            mu.add("w", float(i))
            m2["s"][f"d{i:03}"] = mu
        mem.mutate(m2, {})
        q = IndexQuery(
            PredicateCondition("w", Cmp.GREATER_THAN_EQUAL, 0.0),
            orders=(Order("w"),),
        )
        expect = backend.query("s", q)
        assert len(expect) == 57
        for p in (backend, remote, mem):
            got = list(p.query_stream("s", q, page_size=10))
            assert got == expect, type(p).__name__
        # limit + offset respected across pages
        q2 = IndexQuery(q.condition, q.orders, limit=25, offset=5)
        assert list(remote.query_stream("s", q2, page_size=10)) == expect[5:30]
    finally:
        remote.close()
        server.stop()
        backend.close()


def test_remote_index_retry_on_transient_failure(tmp_path):
    """The retry guard replays idempotent index reads through transient
    backend failures (reference: RestElasticSearchClient retry handling).
    A provider that fails the first N calls with TemporaryBackendError is
    served transparently; mutate (non-idempotent) is NOT replayed."""
    from janusgraph_tpu.exceptions import TemporaryBackendError
    from janusgraph_tpu.indexing import (
        InMemoryIndexProvider,
        RemoteIndexProvider,
        RemoteIndexServer,
    )

    class Flaky(InMemoryIndexProvider):
        def __init__(self):
            super().__init__()
            self.fail_next = 0
            self.query_calls = 0
            self.fail_mutate_next = 0
            self.mutate_calls = 0

        def query(self, store, q):
            self.query_calls += 1
            if self.fail_next > 0:
                self.fail_next -= 1
                raise TemporaryBackendError("injected index flake")
            return super().query(store, q)

        def mutate(self, mutations, key_infos):
            self.mutate_calls += 1
            if self.fail_mutate_next > 0:
                self.fail_mutate_next -= 1
                raise TemporaryBackendError("injected mutate flake")
            return super().mutate(mutations, key_infos)

    backend = Flaky()
    server = RemoteIndexServer(backend).start()
    p = RemoteIndexProvider(
        hostname=server.address[0], port=server.address[1],
        retry_time_s=5.0,
    )
    try:
        p.register("s", "w", KeyInformation(float))
        m = IndexMutation(is_new=True)
        m.add("w", 2.0)
        p.mutate({"s": {"d1": m}}, {})
        backend.fail_next = 2
        hits = p.query(
            "s", IndexQuery(PredicateCondition("w", Cmp.GREATER_THAN, 1.0))
        )
        assert hits == ["d1"]
        assert backend.query_calls >= 3  # 2 injected failures + success
        # non-idempotent mutate: a server-side temporary failure surfaces
        # as outcome-unknown WITHOUT replay (exactly one backend attempt)
        from janusgraph_tpu.exceptions import PermanentBackendError

        backend.fail_mutate_next = 1
        before = backend.mutate_calls
        m2 = IndexMutation(is_new=True)
        m2.add("w", 9.0)
        with pytest.raises(PermanentBackendError, match="not replayed"):
            p.mutate({"s": {"d2": m2}}, {})
        assert backend.mutate_calls == before + 1
    finally:
        p.close()
        server.stop()


def test_within_pushdown(provider):
    """Contain.IN pushes down to every provider as a union of equality
    lookups; NOT_IN is NOT pushable (matches docs lacking the field,
    same rationale as NOT_EQUAL)."""
    from janusgraph_tpu.core.predicates import Contain

    store = "wd"
    infos = {"city": KeyInformation(str, Mapping.STRING),
             "n": KeyInformation(int)}
    for k, i in infos.items():
        provider.register(store, k, i)
    muts = {store: {}}
    for d, (city, n) in {
        "d1": ("sf", 1), "d2": ("nyc", 2), "d3": ("ber", 3),
    }.items():
        m = IndexMutation(is_new=True)
        m.add("city", city)
        m.add("n", n)
        muts[store][d] = m
    provider.mutate(muts, {})

    assert provider.supports(infos["city"], Contain.IN)
    assert provider.supports(infos["n"], Contain.IN)
    assert not provider.supports(infos["city"], Contain.NOT_IN)
    hits = provider.query(store, IndexQuery(
        PredicateCondition("city", Contain.IN, ("sf", "ber", "nope"))
    ))
    assert sorted(hits) == ["d1", "d3"]
    hits = provider.query(store, IndexQuery(
        PredicateCondition("n", Contain.IN, (2, 3))
    ))
    assert sorted(hits) == ["d2", "d3"]


def test_within_pushdown_traversal(graph):
    """g.V().has(key, P.within(...)) over a MIXED-indexed key pushes to
    the provider instead of scanning."""
    a, b, c = _load_people(graph)
    g = graph.traversal()
    hits = g.V().has("age", P.within(30, 100)).to_list()
    assert {v.id for v in hits} == {a, c}
    prof = graph.traversal().V().has("age", P.within(30, 100)).profile()
    assert "mixed-index" in str(prof)
    # without() stays host-evaluated (correct, just not pushed)
    hits2 = g.V().has("age", P.without(30, 100)).to_list()
    assert {v.id for v in hits2} == {b}
