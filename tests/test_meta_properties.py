"""META-properties: properties on vertex properties (reference:
JanusGraphVertexProperty extends Relation; TinkerPop
v.property(key, value, metaK, metaV) — JanusGraph's signature multi/meta
property model). Encoded as the same inline-props block edge cells use,
appended to the property cell."""

import pytest

from janusgraph_tpu.core.codecs import Cardinality
from janusgraph_tpu.core.graph import open_graph


@pytest.fixture()
def g():
    graph = open_graph({"ids.authority-wait-ms": 0.0})
    yield graph
    graph.close()


def test_meta_properties_roundtrip_all_cardinalities(g):
    mgmt = g.management()
    mgmt.make_property_key("single", str, Cardinality.SINGLE)
    mgmt.make_property_key("lst", str, Cardinality.LIST)
    mgmt.make_property_key("st", str, Cardinality.SET)
    tx = g.new_transaction()
    v = tx.add_vertex()
    tx.add_property(v, "single", "a", since=2020, by="me")
    tx.add_property(v, "lst", "x", since=2021)
    tx.add_property(v, "lst", "y")  # no metas
    tx.add_property(v, "st", "s1", since=2022)
    tx.commit()

    tx = g.new_transaction()
    v = tx.get_vertex(v.id)
    (sp,) = tx.get_properties(v, "single")
    assert sp.value_of("since") == 2020 and sp.value_of("by") == "me"
    assert sp.property_values() == {"since": 2020, "by": "me"}
    lst = {p.value: p.property_values() for p in tx.get_properties(v, "lst")}
    assert lst == {"x": {"since": 2021}, "y": {}}
    (stp,) = tx.get_properties(v, "st")
    assert stp.value_of("since") == 2022
    tx.rollback()


def test_meta_property_set_on_new_and_loaded(g):
    tx = g.new_transaction()
    v = tx.add_vertex()
    p = tx.add_property(v, "name", "ada")
    p.set_property("since", 1840)  # NEW: mutates in place
    tx.commit()

    tx = g.new_transaction()
    v = tx.get_vertex(v.id)
    (p,) = tx.get_properties(v, "name")
    assert p.value_of("since") == 1840
    # LOADED: rewrite preserves value + other metas, updates the target
    live = p.set_property("by", "babbage")
    live2 = p.set_property("since", 1841)  # forwards through replacement
    tx.commit()

    tx = g.new_transaction()
    (p,) = tx.get_properties(tx.get_vertex(v.id), "name")
    assert p.value == "ada"
    assert p.property_values() == {"since": 1841, "by": "babbage"}
    tx.rollback()


def test_meta_properties_typed_and_list_siblings_untouched(g):
    from janusgraph_tpu.exceptions import SchemaViolationError

    mgmt = g.management()
    mgmt.make_property_key("nick", str, Cardinality.LIST)
    mgmt.make_property_key("since", int)
    tx = g.new_transaction()
    v = tx.add_vertex()
    a = tx.add_property(v, "nick", "ace", since=1)
    tx.add_property(v, "nick", "alpha", since=2)
    tx.commit()

    tx = g.new_transaction()
    v = tx.get_vertex(v.id)
    target = next(
        p for p in tx.get_properties(v, "nick") if p.value == "ace"
    )
    target.set_property("since", 99)
    tx.commit()
    tx = g.new_transaction()
    vals = {
        p.value: p.value_of("since")
        for p in tx.get_properties(tx.get_vertex(v.id), "nick")
    }
    assert vals == {"ace": 99, "alpha": 2}
    # meta values respect the meta key's declared type
    tx2 = g.new_transaction()
    with pytest.raises(SchemaViolationError):
        tx2.add_property(tx2.get_vertex(v.id), "name", "x", since="not-int")
    tx2.rollback()
    tx.rollback()


def test_meta_free_cells_unchanged_and_graphson_unaffected(g):
    """Meta-free property cells stay byte-identical to the old layout
    (trailing-bytes extension), and GraphSON export still works."""
    import io

    from janusgraph_tpu.core.io import export_graphson

    tx = g.new_transaction()
    tx.add_vertex(name="plain", n=3)
    tx.commit()
    buf = io.StringIO()
    assert export_graphson(g, buf)["vertices"] == 1


def test_meta_review_regressions(g):
    """Rejected meta writes leave NO mutations (SINGLE survives); removed
    properties refuse meta sets; SET dedup keeps metas; reserved
    serializer id refused; v.property(...) forwards metas."""
    from janusgraph_tpu.core.attributes import Serializer, SerializerError
    from janusgraph_tpu.exceptions import (
        InvalidElementError,
        SchemaViolationError,
    )

    g.management().make_property_key("since", int)
    tx = g.new_transaction()
    v = tx.add_vertex()
    v.property("name", "ada", since=1840)  # element-level meta forwarding
    tx.commit()

    # rejected meta write must NOT remove the committed SINGLE value
    tx = g.new_transaction()
    v = tx.get_vertex(v.id)
    with pytest.raises(SchemaViolationError):
        tx.add_property(v, "name", "x", since="not-an-int")
    tx.commit()
    tx = g.new_transaction()
    v = tx.get_vertex(v.id)
    assert v.value("name") == "ada"  # survived the rejected write

    # removed property refuses meta sets
    (p,) = tx.get_properties(v, "name")
    tx.remove_property(p)
    with pytest.raises(InvalidElementError, match="removed"):
        p.set_property("since", 1)
    tx.rollback()

    # SET dedup keeps the caller's metas
    from janusgraph_tpu.core.codecs import Cardinality

    g.management().make_property_key("tag", str, Cardinality.SET)
    tx = g.new_transaction()
    v = tx.get_vertex(v.id)
    tx.add_property(v, "tag", "t1")
    tx.commit()
    tx = g.new_transaction()
    v = tx.get_vertex(v.id)
    live = tx.add_property(v, "tag", "t1", since=7)  # dedup + meta update
    tx.commit()
    tx = g.new_transaction()
    (tp,) = tx.get_properties(tx.get_vertex(v.id), "tag")
    assert tp.value == "t1" and tp.value_of("since") == 7
    tx.rollback()

    # the 0xFFFF meta marker can never collide with a registered id
    s = Serializer()
    with pytest.raises(SerializerError, match="reserved"):
        class _Weird:
            type_id = 0xFFFF
            py_type = bytes
        s.register(_Weird())


def test_meta_properties_survive_graphson_roundtrip(g, tmp_path):
    import io

    from janusgraph_tpu.core.graph import open_graph
    from janusgraph_tpu.core.io import export_graphson, import_graphson

    tx = g.new_transaction()
    v = tx.add_vertex()
    v.property("name", "ada", since=1840, by="x")
    tx.commit()
    buf = io.StringIO()
    export_graphson(g, buf)
    dst = open_graph()
    import_graphson(dst, io.StringIO(buf.getvalue()))
    (p,) = dst.traversal().V().next().properties("name")
    assert p.property_values() == {"since": 1840, "by": "x"}
    dst.close()
