"""LDBC-SNB-shaped proxy generator (BASELINE configs #2/#5 dataset shape):
deterministic heavy-tail degrees + community locality, exercised by the
ConnectedComponents and filtered-3hop workloads (VERDICT r3 #10)."""

import numpy as np

from janusgraph_tpu.core.predicates import Cmp
from janusgraph_tpu.olap.cpu_executor import CPUExecutor
from janusgraph_tpu.olap.generators import ldbc_snb_csr, ldbc_snb_edges
from janusgraph_tpu.olap.programs import ConnectedComponentsProgram
from janusgraph_tpu.olap.programs.olap_traversal import (
    OLAPTraversalProgram,
    PropertyFilter,
    TraversalStep,
    evaluate_filter_mask,
)
from janusgraph_tpu.olap.tpu_executor import TPUExecutor


def test_shape_properties():
    n, src, dst, props = ldbc_snb_edges(12)
    assert n == 4096 and len(src) == len(dst)
    deg = np.bincount(src, minlength=n)
    # heavy tail: hub degree far above the mean (SNB person-knows shape)
    assert deg.max() > 8 * deg.mean()
    # community locality ~ the configured fraction
    comm = props["community"]
    intra = (comm[src] == comm[dst]).mean()
    assert 0.7 < intra < 0.9
    # attributes aligned + bounded
    assert props["country"].max() < 60
    assert np.array_equal(props["country"], comm % 60)
    assert (src != dst).all()  # no self loops


def test_deterministic():
    a = ldbc_snb_edges(11, seed=3)
    b = ldbc_snb_edges(11, seed=3)
    c = ldbc_snb_edges(11, seed=4)
    assert np.array_equal(a[1], b[1]) and np.array_equal(a[2], b[2])
    assert not np.array_equal(a[2], c[2])


def test_cc_and_filtered_3hop_run_on_proxy():
    csr = ldbc_snb_csr(11)
    cc_prog = lambda: ConnectedComponentsProgram(max_iterations=64)  # noqa: E731
    cpu = CPUExecutor(csr).run(cc_prog())
    tpu = TPUExecutor(csr).run(cc_prog())
    np.testing.assert_array_equal(
        np.asarray(cpu["component"]), np.asarray(tpu["component"])
    )
    # dense community graph: far fewer components than vertices
    assert len(np.unique(np.asarray(tpu["component"]))) < csr.num_vertices / 10

    flt = (PropertyFilter("creation_day", Cmp.GREATER_THAN, 1825),)
    mask = evaluate_filter_mask(csr, flt)
    assert 0.3 < mask.mean() < 0.7  # ~half the days pass
    steps = (
        TraversalStep("out"),
        TraversalStep("out", None, flt),
        TraversalStep("out"),
    )
    masks = np.stack(
        [np.ones(csr.num_vertices, np.float32), mask,
         np.ones(csr.num_vertices, np.float32)], axis=1,
    )
    prog = lambda: OLAPTraversalProgram(steps, step_masks=masks)  # noqa: E731
    r_cpu = CPUExecutor(csr).run(prog())
    r_tpu = TPUExecutor(csr).run(prog())
    np.testing.assert_allclose(
        np.asarray(r_tpu["count"], np.float64),
        np.asarray(r_cpu["count"], np.float64), rtol=1e-5,
    )
    assert float(np.asarray(r_tpu["count"]).sum()) > 0


def test_ldbc_sf_sized_proxy():
    """ldbc_sf_csr hits the documented SF1 dimensions (scaled) and keeps
    the SNB shape: community structure + heavy-tailed degrees."""
    import numpy as np

    from janusgraph_tpu.olap.generators import LDBC_SF_SIZES, ldbc_sf_csr

    csr = ldbc_sf_csr(1, scale_down=32)  # 100k / 540k — CI-sized
    nv, ne = LDBC_SF_SIZES[1]
    assert csr.num_vertices == nv // 32
    assert csr.num_edges == ne // 32  # lands EXACTLY (_land_edge_count)
    assert "community" in csr.properties
    deg = np.diff(csr.out_indptr)
    # heavy tail: p99 well above the mean
    assert np.percentile(deg, 99) > 4 * deg.mean()


def test_twitter_shaped_proxy_power_law():
    import numpy as np

    from janusgraph_tpu.olap.generators import twitter_csr

    csr = twitter_csr(1 << 15, 30)
    assert csr.num_edges == (1 << 15) * 30  # exact (_land_edge_count)
    ind = np.diff(csr.in_indptr)
    # celebrity hubs: the top account is followed by >1% of all users
    assert ind.max() > csr.num_vertices * 0.01
    # power-law tail: CCDF log-log slope ~ -(2.3 - 1)
    x = ind[ind >= 10].astype(float)
    uniq = np.unique(x)
    ccdf = np.array([(x >= v).mean() for v in uniq])
    slope = np.polyfit(np.log(uniq), np.log(ccdf), 1)[0]
    assert -1.8 < slope < -0.9, slope
    # determinism
    a = twitter_csr(1 << 12, 20, seed=3)
    b = twitter_csr(1 << 12, 20, seed=3)
    assert np.array_equal(a.in_src, b.in_src)
