"""ShortestPath path tracking (VERDICT r2 #9): predecessor-array state on
device + host chain reconstruction, parity vs networkx on random graphs,
across CPU oracle / TPU executor / 8-device mesh.
"""

import numpy as np
import pytest

from janusgraph_tpu.olap import csr_from_edges
from janusgraph_tpu.olap.cpu_executor import CPUExecutor
from janusgraph_tpu.olap.programs import ShortestPathProgram
from janusgraph_tpu.olap.programs.shortest_path import reconstruct_path
from janusgraph_tpu.olap.tpu_executor import TPUExecutor
from janusgraph_tpu.parallel import ShardedExecutor


def random_graph(n=150, m=600, seed=5):
    rng = np.random.default_rng(seed)
    src = rng.integers(0, n, m).astype(np.int32)
    dst = rng.integers(0, n, m).astype(np.int32)
    return csr_from_edges(n, src, dst), src, dst


@pytest.fixture(scope="module")
def mesh8():
    import jax
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()[:8]), ("p",))


def nx_graph(n, src, dst):
    import networkx as nx

    g = nx.DiGraph()
    g.add_nodes_from(range(n))
    g.add_edges_from(zip(src.tolist(), dst.tolist()))
    return g


@pytest.mark.parametrize("runner", ["cpu", "tpu", "mesh"])
def test_paths_match_networkx(runner, mesh8):
    import networkx as nx

    g, src, dst = random_graph()
    prog = ShortestPathProgram(seed_index=0, track_paths=True)
    if runner == "cpu":
        res = CPUExecutor(g).run(prog)
    elif runner == "tpu":
        res = TPUExecutor(g).run(prog)
    else:
        res = ShardedExecutor(g, mesh=mesh8).run(prog)

    G = nx_graph(g.num_vertices, src, dst)
    nx_dist = nx.single_source_shortest_path_length(G, 0)
    nx_paths = nx.single_source_shortest_path(G, 0)

    dist = np.asarray(res["distance"])
    for v in range(g.num_vertices):
        if v in nx_dist:
            assert dist[v] == nx_dist[v], f"distance mismatch at {v}"
            path = reconstruct_path(res, v)
            assert path is not None
            # same length as an optimal path, valid edges, right endpoints
            assert len(path) == len(nx_paths[v])
            assert path[0] == 0 and path[-1] == v
            edges = set(zip(src.tolist(), dst.tolist()))
            for a, b in zip(path, path[1:]):
                assert (a, b) in edges, f"path uses nonexistent edge {a}->{b}"
        else:
            assert dist[v] >= 1e18
            assert reconstruct_path(res, v) is None


def test_undirected_paths(mesh8):
    g, src, dst = random_graph(n=60, m=150, seed=9)
    prog = ShortestPathProgram(seed_index=3, track_paths=True, undirected=True)
    res = CPUExecutor(g).run(prog)

    import networkx as nx

    G = nx.Graph()
    G.add_nodes_from(range(g.num_vertices))
    G.add_edges_from(zip(src.tolist(), dst.tolist()))
    nx_dist = nx.single_source_shortest_path_length(G, 3)
    dist = np.asarray(res["distance"])
    edges = set(zip(src.tolist(), dst.tolist())) | set(
        zip(dst.tolist(), src.tolist())
    )
    for v, d in nx_dist.items():
        assert dist[v] == d
        path = reconstruct_path(res, v)
        assert len(path) == d + 1
        for a, b in zip(path, path[1:]):
            assert (a, b) in edges


def test_track_paths_rejects_weighted():
    with pytest.raises(ValueError, match="unweighted"):
        ShortestPathProgram(seed_index=0, weighted=True, track_paths=True)


def test_plain_distance_mode_unchanged(mesh8):
    g, _, _ = random_graph(n=80, m=300, seed=2)
    plain = CPUExecutor(g).run(ShortestPathProgram(seed_index=0))
    tracked = CPUExecutor(g).run(
        ShortestPathProgram(seed_index=0, track_paths=True)
    )
    np.testing.assert_allclose(plain["distance"], tracked["distance"])
