"""ShortestPath path tracking (VERDICT r2 #9): predecessor-array state on
device + host chain reconstruction, parity vs networkx on random graphs,
across CPU oracle / TPU executor / 8-device mesh.
"""

import numpy as np
import pytest

from janusgraph_tpu.olap import csr_from_edges
from janusgraph_tpu.olap.cpu_executor import CPUExecutor
from janusgraph_tpu.olap.programs import ShortestPathProgram
from janusgraph_tpu.olap.programs.shortest_path import reconstruct_path
from janusgraph_tpu.olap.tpu_executor import TPUExecutor
from janusgraph_tpu.parallel import ShardedExecutor


def random_graph(n=150, m=600, seed=5):
    rng = np.random.default_rng(seed)
    src = rng.integers(0, n, m).astype(np.int32)
    dst = rng.integers(0, n, m).astype(np.int32)
    return csr_from_edges(n, src, dst), src, dst


@pytest.fixture(scope="module")
def mesh8():
    import jax
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()[:8]), ("p",))


def nx_graph(n, src, dst):
    import networkx as nx

    g = nx.DiGraph()
    g.add_nodes_from(range(n))
    g.add_edges_from(zip(src.tolist(), dst.tolist()))
    return g


@pytest.mark.parametrize("runner", ["cpu", "tpu", "mesh"])
def test_paths_match_networkx(runner, mesh8):
    import networkx as nx

    g, src, dst = random_graph()
    prog = ShortestPathProgram(seed_index=0, track_paths=True)
    if runner == "cpu":
        res = CPUExecutor(g).run(prog)
    elif runner == "tpu":
        res = TPUExecutor(g).run(prog)
    else:
        res = ShardedExecutor(g, mesh=mesh8).run(prog)

    G = nx_graph(g.num_vertices, src, dst)
    nx_dist = nx.single_source_shortest_path_length(G, 0)
    nx_paths = nx.single_source_shortest_path(G, 0)

    dist = np.asarray(res["distance"])
    for v in range(g.num_vertices):
        if v in nx_dist:
            assert dist[v] == nx_dist[v], f"distance mismatch at {v}"
            path = reconstruct_path(res, v)
            assert path is not None
            # same length as an optimal path, valid edges, right endpoints
            assert len(path) == len(nx_paths[v])
            assert path[0] == 0 and path[-1] == v
            edges = set(zip(src.tolist(), dst.tolist()))
            for a, b in zip(path, path[1:]):
                assert (a, b) in edges, f"path uses nonexistent edge {a}->{b}"
        else:
            assert dist[v] >= 1e18
            assert reconstruct_path(res, v) is None


def test_undirected_paths(mesh8):
    g, src, dst = random_graph(n=60, m=150, seed=9)
    prog = ShortestPathProgram(seed_index=3, track_paths=True, undirected=True)
    res = CPUExecutor(g).run(prog)

    import networkx as nx

    G = nx.Graph()
    G.add_nodes_from(range(g.num_vertices))
    G.add_edges_from(zip(src.tolist(), dst.tolist()))
    nx_dist = nx.single_source_shortest_path_length(G, 3)
    dist = np.asarray(res["distance"])
    edges = set(zip(src.tolist(), dst.tolist())) | set(
        zip(dst.tolist(), src.tolist())
    )
    for v, d in nx_dist.items():
        assert dist[v] == d
        path = reconstruct_path(res, v)
        assert len(path) == d + 1
        for a, b in zip(path, path[1:]):
            assert (a, b) in edges


def test_track_paths_rejects_weighted():
    with pytest.raises(ValueError, match="unweighted"):
        ShortestPathProgram(seed_index=0, weighted=True, track_paths=True)


def test_plain_distance_mode_unchanged(mesh8):
    g, _, _ = random_graph(n=80, m=300, seed=2)
    plain = CPUExecutor(g).run(ShortestPathProgram(seed_index=0))
    tracked = CPUExecutor(g).run(
        ShortestPathProgram(seed_index=0, track_paths=True)
    )
    np.testing.assert_allclose(plain["distance"], tracked["distance"])


# --------------------------------------------- weighted paths (round 5)
def test_weighted_paths_parity_networkx():
    """Weighted SSSP paths: the device program carries only distances;
    weighted_predecessors derives the predecessor array host-side from
    the fixpoint relaxation equation. Distance-parity vs networkx
    dijkstra, and every reconstructed path's weight sum equals the
    reported distance."""
    import networkx as nx

    from janusgraph_tpu.olap.programs.shortest_path import (
        INF,
        reconstruct_path,
        weighted_predecessors,
    )

    rng = np.random.default_rng(11)
    n, m = 120, 500
    src = rng.integers(0, n, m).astype(np.int32)
    dst = rng.integers(0, n, m).astype(np.int32)
    wts = rng.uniform(0.5, 3.0, m).astype(np.float32)
    csr = csr_from_edges(n, src, dst, weights=wts)
    seed = int(src[0])
    prog = ShortestPathProgram(
        seed_index=seed, weighted=True, max_iterations=200
    )
    res = TPUExecutor(csr).run(prog)
    dist = np.asarray(res["distance"])

    G = nx.DiGraph()
    G.add_nodes_from(range(n))
    for s, d, w in zip(src, dst, wts):
        # parallel edges: networkx DiGraph keeps ONE — keep the minimum
        if G.has_edge(int(s), int(d)):
            G[int(s)][int(d)]["weight"] = min(
                G[int(s)][int(d)]["weight"], float(w)
            )
        else:
            G.add_edge(int(s), int(d), weight=float(w))
    nx_dist = nx.single_source_dijkstra_path_length(G, seed)
    for v in range(n):
        if v in nx_dist:
            assert abs(dist[v] - nx_dist[v]) < 1e-3, (v, dist[v], nx_dist[v])
        else:
            assert dist[v] >= INF

    pred = weighted_predecessors(csr, res, seed)
    res2 = {"distance": dist, "predecessor": pred}
    # weight lookup for path verification
    wmap = {}
    for s, d, w in zip(src, dst, wts):
        key = (int(s), int(d))
        wmap[key] = min(wmap.get(key, float("inf")), float(w))
    checked = 0
    for v in range(n):
        if v == seed or dist[v] >= INF:
            continue
        path = reconstruct_path(res2, v)
        assert path is not None and path[0] == seed and path[-1] == v
        total = sum(wmap[(a, b)] for a, b in zip(path, path[1:]))
        assert abs(total - dist[v]) < 1e-3, (v, total, dist[v])
        checked += 1
    assert checked > 50  # the graph is well connected from the seed


def test_weighted_paths_adversarial_cases():
    """Review repros: zero-weight self-loops, zero-weight cycles among
    equal-distance vertices, and long cheap chains vs short expensive
    edges must all yield correct paths."""
    from janusgraph_tpu.olap.programs.shortest_path import (
        reconstruct_path,
        weighted_predecessors,
    )

    # zero-weight self-loop must not become its own predecessor
    csr = csr_from_edges(
        2,
        np.array([1, 0], dtype=np.int32),
        np.array([1, 1], dtype=np.int32),
        weights=np.array([0.0, 1.0], dtype=np.float32),
    )
    prog = ShortestPathProgram(seed_index=0, weighted=True,
                               max_iterations=10)
    res = dict(TPUExecutor(csr).run(prog))
    res["predecessor"] = weighted_predecessors(csr, res, 0)
    assert reconstruct_path(res, 1) == [0, 1]

    # zero-weight cycle between equal-distance vertices
    csr = csr_from_edges(
        3,
        np.array([0, 0, 1, 2], dtype=np.int32),
        np.array([1, 2, 2, 1], dtype=np.int32),
        weights=np.array([1.0, 1.0, 0.0, 0.0], dtype=np.float32),
    )
    prog = ShortestPathProgram(seed_index=0, weighted=True,
                               max_iterations=10)
    res = dict(TPUExecutor(csr).run(prog))
    res["predecessor"] = weighted_predecessors(csr, res, 0)
    assert reconstruct_path(res, 1) == [0, 1]
    assert reconstruct_path(res, 2) == [0, 2]


def test_weighted_shortest_path_step_reaches_fixpoint():
    """The traversal step must converge weighted relaxation past the
    unweighted max_hops default: a 12-edge cheap chain beats a direct
    expensive edge."""
    from janusgraph_tpu.core.graph import open_graph

    g = open_graph({"ids.authority-wait-ms": 0.0})
    mgmt = g.management()
    mgmt.make_property_key("w", float)
    mgmt.make_edge_label("road")
    t = g.traversal()
    tx = t.tx
    vs = [tx.add_vertex("place") for _ in range(13)]
    for a, b in zip(vs, vs[1:]):
        tx.add_edge(a, "road", b, w=0.1)
    tx.add_edge(vs[0], "road", vs[12], w=100.0)
    t.commit()
    try:
        paths = g.traversal().V(vs[0].id).shortest_path(
            weight_key="w"
        ).to_list()
        dest = {p[-1].id: p for p in paths}
        assert len(dest[vs[12].id]) == 13  # the cheap chain, not the hop
    finally:
        g.close()
