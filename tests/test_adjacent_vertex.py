"""AdjacentVertex optimizer rewrites (reference: graphdb/tinkerpop/optimize/
strategy/AdjacentVertex{HasId,Is}OptimizerStrategy): `.out(lbl).has_id(v)`
collapses into per-traverser adjacency POINT LOOKUPS (one bounded column
slice per (label, target)) instead of materializing the whole neighborhood.
"""

import pytest

from janusgraph_tpu.core import gods
from janusgraph_tpu.core.codecs import Direction
from janusgraph_tpu.core.graph import open_graph


@pytest.fixture()
def g():
    graph = open_graph()
    gods.load(graph)
    yield graph
    graph.close()


def _vid(g, name):
    return g.traversal().V().has("name", name).next().id


def test_has_id_after_out_rewrites_and_matches(g):
    jupiter = _vid(g, "jupiter")
    t = g.traversal()
    trav = t.V().has("name", "hercules").out("father").has_id(jupiter)
    # the peephole replaced expansion+filter with ONE adjacency step
    assert len(trav._steps) == 1
    assert "adjacentVertex" in trav._steps[0]._label
    out = trav.values("name").to_list()
    assert out == ["jupiter"]


def test_has_id_no_match(g):
    pluto = _vid(g, "pluto")
    out = (
        g.traversal().V().has("name", "hercules")
        .out("father").has_id(pluto).to_list()
    )
    assert out == []


def test_is_vertex_rewrites(g):
    tx = g.new_transaction()
    jupiter_v = tx.get_vertex(_vid(g, "jupiter"))
    t = g.traversal()
    trav = t.V().has("name", "hercules").out("father").is_(jupiter_v)
    assert "adjacentVertex" in trav._steps[0]._label
    assert [v.value("name") for v in trav.to_list()] == ["jupiter"]


def test_adjacency_point_lookup_slice_is_bounded(g):
    """The rewrite must issue a NARROW slice (per target), not the label's
    whole neighborhood range."""
    jupiter = _vid(g, "jupiter")
    t = g.traversal()
    tx = t.tx
    seen = []
    orig = tx.backend_tx.edge_store_query

    def spy(q):
        seen.append(q)
        return orig(q)

    tx.backend_tx.edge_store_query = spy
    t.V().has("name", "hercules").out("father").has_id(jupiter).to_list()
    # the LAST query is the adjacency lookup: [start, increment(start)) with
    # the 8-byte target vid embedded after the (cat,type,dir,sklen) head
    q = seen[-1].slice
    # head = [cat:1][type:8][dir:1][sklen:1] = 11 bytes, then other_vid:8
    assert q.start[11:19] == jupiter.to_bytes(8, "big")


def test_rewrite_skipped_for_sorted_labels_and_edges(g):
    cerberus = _vid(g, "cerberus")
    t = g.traversal()
    # battled has a sort key -> other_vid is not at a fixed offset; the
    # rewrite still answers correctly via the fallback path
    out = (
        t.V().has("name", "hercules").out("battled").has_id(cerberus)
        .values("name").to_list()
    )
    assert out == ["cerberus"]
    # edge expansion (out_e) is not rewritten
    trav = t.V().out_e("father").has_id(999)
    assert "adjacentVertex" not in getattr(trav._steps[0], "_label", "")


def test_tx_added_edges_visible_to_adjacency(g):
    tx = g.new_transaction()
    h = tx.get_vertex(_vid(g, "hercules"))
    sphinx = tx.add_vertex("monster", name="sphinx")
    tx.add_edge(h, "pet", sphinx)
    edges = tx.adjacency_edges(h, Direction.OUT, ("pet",), {sphinx.id})
    assert len(edges) == 1 and edges[0].other(h).id == sphinx.id


def test_both_direction_adjacency(g):
    tx = g.new_transaction()
    jupiter = tx.get_vertex(_vid(g, "jupiter"))
    neptune_id = _vid(g, "neptune")
    edges = tx.adjacency_edges(
        jupiter, Direction.BOTH, ("brother",), {neptune_id}
    )
    # jupiter-brother-neptune exists in both orientations
    assert len(edges) == 2


# ----------------------------------------------- within() index-union fold
def test_within_folds_to_index_union():
    """P.within on composite-index keys folds to a UNION of point lookups
    (the reference's Contain.IN handling) instead of a full scan —
    including multi-key cartesians, tx-overlay visibility, the combo cap
    degrading to a scan, and query.force-index acceptance."""
    from janusgraph_tpu.core.graph import open_graph
    from janusgraph_tpu.core.traversal import P

    g = open_graph({"ids.authority-wait-ms": 0.0})
    mgmt = g.management()
    mgmt.make_property_key("city", str)
    mgmt.make_property_key("tier", int)
    mgmt.build_composite_index("byCityTier", ["city", "tier"])
    t = g.traversal()
    for city in ("sf", "nyc", "ber"):
        for tier in (1, 2):
            t.tx.add_vertex(city=city, tier=tier)
    t.commit()

    q = g.traversal().V().has("city", P.within("sf", "ber")).has("tier", 1)
    got = {(v.value("city"), v.value("tier")) for v in q.to_list()}
    assert got == {("sf", 1), ("ber", 1)}
    prof = (
        g.traversal().V()
        .has("city", P.within("sf", "ber")).has("tier", 1).profile()
    )
    assert "composite-index-union" in str(prof)
    assert "point_lookups=2" in str(prof)

    # cartesian across two within conditions
    q2 = (
        g.traversal().V()
        .has("city", P.within("sf", "nyc")).has("tier", P.within(1, 2))
    )
    assert len(q2.to_list()) == 4

    # tx overlay: an uncommitted matching vertex appears in union results
    t2 = g.traversal()
    t2.tx.add_vertex(city="sf", tier=1)
    assert len(
        t2.V().has("city", P.within("sf")).has("tier", 1).to_list()
    ) == 2

    # a huge IN-list degrades to the scan path (combo cap), still correct
    many = [f"c{i}" for i in range(100)] + ["sf"]
    prof3 = g.traversal().V().has(
        "city", P.within(*many)
    ).has("tier", 1).profile()
    assert "full-scan" in str(prof3)
    assert len(
        g.traversal().V().has("city", P.within(*many)).has("tier", 1)
        .to_list()
    ) == 1
    g.close()

    # review regressions: eq narrows a same-key within back to a single
    # point lookup even past the combo cap
    g3 = open_graph({"ids.authority-wait-ms": 0.0})
    m3 = g3.management()
    m3.make_property_key("city", str)
    m3.build_composite_index("byCity", ["city"])
    t3 = g3.traversal()
    t3.tx.add_vertex(city="sf")
    t3.commit()
    many_c = [f"z{i}" for i in range(80)] + ["sf"]
    prof_eq = g3.traversal().V().has(
        "city", P.within(*many_c)
    ).has("city", "sf").profile()
    assert "access=composite-index," in str(prof_eq).replace("  ", " ")
    # duplicates in within() dedup before planning
    prof_dup = g3.traversal().V().has(
        "city", P.within(*(["sf", "oak"] * 40))
    ).profile()
    assert "point_lookups=2" in str(prof_dup)
    g3.close()

    # over-cap on a WIDE index falls back to a narrower covered index
    g4 = open_graph({"ids.authority-wait-ms": 0.0})
    m4 = g4.management()
    m4.make_property_key("a", str)
    m4.make_property_key("b", int)
    m4.build_composite_index("byAB", ["a", "b"])
    m4.build_composite_index("byA", ["a"])
    t4 = g4.traversal()
    t4.tx.add_vertex(a="x", b=1)
    t4.commit()
    prof_n = g4.traversal().V().has("a", P.within("x", "y")).has(
        "b", P.within(*range(60))
    ).profile()
    assert "index=byA" in str(prof_n)  # byAB would be 120 combos
    g4.close()

    # query.force-index accepts within-covered starts
    g2 = open_graph({
        "ids.authority-wait-ms": 0.0, "query.force-index": True,
    })
    m2 = g2.management()
    m2.make_property_key("name", str)
    m2.build_composite_index("byName", ["name"])
    tt = g2.traversal()
    tt.tx.add_vertex(name="x")
    tt.commit()
    assert len(
        g2.traversal().V().has("name", P.within("x", "y")).to_list()
    ) == 1
    # force-index + over-cap IN-list: the covered index runs UNCAPPED
    # (an index the user has must not produce 'no index' errors)
    many2 = [f"q{i}" for i in range(80)] + ["x"]
    prof_fi = g2.traversal().V().has("name", P.within(*many2)).profile()
    assert "point_lookups=81" in str(prof_fi)
    assert len(
        g2.traversal().V().has("name", P.within(*many2)).to_list()
    ) == 1
    g2.close()


def test_has_id_start_fold():
    """V().has_id(ids) folds into the point-lookup start (JanusGraphStep
    hasId folding) — no full scan; composes with has() either side; the
    empty and rid-carrying forms keep filter semantics."""
    from janusgraph_tpu.core import gods
    from janusgraph_tpu.core.graph import open_graph

    g = open_graph({"ids.authority-wait-ms": 0.0})
    gods.load(g)
    t = g.traversal()
    jid = t.V().has("name", "jupiter").next().id
    prof = g.traversal().V().has_id(jid).profile()
    assert "access=ids" in str(prof)
    assert g.traversal().V().has_id(jid).has(
        "name", "jupiter"
    ).count() == 1
    assert g.traversal().V().has("name", "jupiter").has_id(
        jid
    ).count() == 1
    # empty has_id drops everything (must NOT fold into a full scan)
    assert g.traversal().V().has_id().count() == 0
    # a relation id can never match a vertex
    e = t.V().has("name", "jupiter").out_e("brother").next()
    assert g.traversal().V().has_id(e.identifier).count() == 0
    # symmetric edge fold: E().has_id(rid) point-looks (no scan)
    eh = g.traversal().E().has_id(e.identifier).next()
    assert eh.id == e.id
    # mixed rid+int sets keep filter semantics (no fold fires); -1 can
    # never be a relation id, so exactly the rid matches
    assert g.traversal().E().has_id(e.identifier, -1).count() == 1
    g.close()
