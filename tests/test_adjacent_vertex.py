"""AdjacentVertex optimizer rewrites (reference: graphdb/tinkerpop/optimize/
strategy/AdjacentVertex{HasId,Is}OptimizerStrategy): `.out(lbl).has_id(v)`
collapses into per-traverser adjacency POINT LOOKUPS (one bounded column
slice per (label, target)) instead of materializing the whole neighborhood.
"""

import pytest

from janusgraph_tpu.core import gods
from janusgraph_tpu.core.codecs import Direction
from janusgraph_tpu.core.graph import open_graph


@pytest.fixture()
def g():
    graph = open_graph()
    gods.load(graph)
    yield graph
    graph.close()


def _vid(g, name):
    return g.traversal().V().has("name", name).next().id


def test_has_id_after_out_rewrites_and_matches(g):
    jupiter = _vid(g, "jupiter")
    t = g.traversal()
    trav = t.V().has("name", "hercules").out("father").has_id(jupiter)
    # the peephole replaced expansion+filter with ONE adjacency step
    assert len(trav._steps) == 1
    assert "adjacentVertex" in trav._steps[0]._label
    out = trav.values("name").to_list()
    assert out == ["jupiter"]


def test_has_id_no_match(g):
    pluto = _vid(g, "pluto")
    out = (
        g.traversal().V().has("name", "hercules")
        .out("father").has_id(pluto).to_list()
    )
    assert out == []


def test_is_vertex_rewrites(g):
    tx = g.new_transaction()
    jupiter_v = tx.get_vertex(_vid(g, "jupiter"))
    t = g.traversal()
    trav = t.V().has("name", "hercules").out("father").is_(jupiter_v)
    assert "adjacentVertex" in trav._steps[0]._label
    assert [v.value("name") for v in trav.to_list()] == ["jupiter"]


def test_adjacency_point_lookup_slice_is_bounded(g):
    """The rewrite must issue a NARROW slice (per target), not the label's
    whole neighborhood range."""
    jupiter = _vid(g, "jupiter")
    t = g.traversal()
    tx = t.tx
    seen = []
    orig = tx.backend_tx.edge_store_query

    def spy(q):
        seen.append(q)
        return orig(q)

    tx.backend_tx.edge_store_query = spy
    t.V().has("name", "hercules").out("father").has_id(jupiter).to_list()
    # the LAST query is the adjacency lookup: [start, increment(start)) with
    # the 8-byte target vid embedded after the (cat,type,dir,sklen) head
    q = seen[-1].slice
    # head = [cat:1][type:8][dir:1][sklen:1] = 11 bytes, then other_vid:8
    assert q.start[11:19] == jupiter.to_bytes(8, "big")


def test_rewrite_skipped_for_sorted_labels_and_edges(g):
    cerberus = _vid(g, "cerberus")
    t = g.traversal()
    # battled has a sort key -> other_vid is not at a fixed offset; the
    # rewrite still answers correctly via the fallback path
    out = (
        t.V().has("name", "hercules").out("battled").has_id(cerberus)
        .values("name").to_list()
    )
    assert out == ["cerberus"]
    # edge expansion (out_e) is not rewritten
    trav = t.V().out_e("father").has_id(999)
    assert "adjacentVertex" not in getattr(trav._steps[0], "_label", "")


def test_tx_added_edges_visible_to_adjacency(g):
    tx = g.new_transaction()
    h = tx.get_vertex(_vid(g, "hercules"))
    sphinx = tx.add_vertex("monster", name="sphinx")
    tx.add_edge(h, "pet", sphinx)
    edges = tx.adjacency_edges(h, Direction.OUT, ("pet",), {sphinx.id})
    assert len(edges) == 1 and edges[0].other(h).id == sphinx.id


def test_both_direction_adjacency(g):
    tx = g.new_transaction()
    jupiter = tx.get_vertex(_vid(g, "jupiter"))
    neptune_id = _vid(g, "neptune")
    edges = tx.adjacency_edges(
        jupiter, Direction.BOTH, ("brother",), {neptune_id}
    )
    # jupiter-brother-neptune exists in both orientations
    assert len(edges) == 2
