"""Round-5 config options: each test drives the BEHAVIOR the option claims
(reference: GraphDatabaseConfiguration.java option vocabulary)."""

import numpy as np
import pytest

from janusgraph_tpu.core.graph import open_graph


def test_fast_property_single_wide_slice():
    """query.fast-property=True fetches ONE wide slice for keyed property
    reads (cache-warming over-fetch); False slices per key."""
    g = open_graph({"storage.backend": "inmemory"})
    tx = g.new_transaction()
    v = tx.add_vertex(name="a", age=3, city="x")
    tx.commit()

    store = g.backend.edgestore
    tx = g.new_transaction()
    v = tx.get_vertex(v.id)
    tx.get_properties(v, "name")
    # the wide slice is reused for any later key: no new backend read
    m0 = store.metrics.misses
    tx.get_properties(v, "age")
    assert store.metrics.misses == m0
    g.close()

    g2 = open_graph({
        "storage.backend": "inmemory", "query.fast-property": False,
    })
    tx = g2.new_transaction()
    v = tx.add_vertex(name="a", age=3)
    tx.commit()
    tx = g2.new_transaction()
    v = tx.get_vertex(v.id)
    st = g2.backend.edgestore
    tx.get_properties(v, "name")
    miss0 = st.metrics.misses
    tx.get_properties(v, "age")  # per-key slice: a fresh miss
    assert st.metrics.misses > miss0
    g2.close()


def test_max_repeat_loops_bounds_cycles():
    g = open_graph({
        "storage.backend": "inmemory", "query.max-repeat-loops": 2,
    })
    tx = g.new_transaction()
    a, b = tx.add_vertex(), tx.add_vertex()
    tx.add_edge(a, "next", b)
    tx.add_edge(b, "next", a)  # a 2-cycle: an until-only loop never drains
    tx.commit()
    out = (
        g.traversal().V().repeat(
            lambda t: t.out("next"),
            until=lambda t: t.has("name", "nope"),
        ).to_list()
    )
    # bounded at 2 loops: traversers exit instead of spinning forever
    assert len(out) == 2
    g.close()


def test_storage_read_only_refuses_mutations():
    from janusgraph_tpu.exceptions import PermanentBackendError
    from janusgraph_tpu.storage.inmemory import InMemoryStoreManager

    mgr = InMemoryStoreManager()
    g = open_graph({"storage.backend": "inmemory"}, store_manager=mgr)
    tx = g.new_transaction()
    tx.add_vertex(name="pre")
    tx.commit()
    g.close()

    ro = open_graph(
        {"storage.backend": "inmemory", "storage.read-only": True},
        store_manager=mgr,
    )
    tx = ro.new_transaction()
    assert list(tx.vertices())  # reads fine
    # enforcement fires at the FIRST write — the id-block claim — before
    # any WAL precommit could leave a phantom entry
    with pytest.raises(PermanentBackendError, match="read-only"):
        tx.add_vertex()
    # log appends refuse too
    with pytest.raises(PermanentBackendError, match="read-only"):
        ro.log_manager.open_log("ulog_x").add(b"nope")
    ro.close()


def test_cache_clean_wait_blocks_readmission():
    import time

    from janusgraph_tpu.storage.cache import ExpirationCacheStore
    from janusgraph_tpu.storage.inmemory import InMemoryStoreManager
    from janusgraph_tpu.storage.kcvs import KeySliceQuery, SliceQuery

    mgr = InMemoryStoreManager()
    raw = mgr.open_database("t")
    store = ExpirationCacheStore(raw, clean_wait_seconds=0.2)
    txh = mgr.begin_transaction()
    q = KeySliceQuery(b"k", SliceQuery(b"a", b"z"))
    raw.mutate(b"k", [(b"c", b"1")], [], txh)
    store.get_slice(q, txh)
    assert store.get_slice(q, txh) and store.metrics.hits == 1
    store.mutate(b"k", [(b"c", b"2")], [], txh)  # invalidates + marks dirty
    store.get_slice(q, txh)
    h = store.metrics.hits
    store.get_slice(q, txh)  # NOT re-admitted inside the window
    assert store.metrics.hits == h
    time.sleep(0.25)
    store.get_slice(q, txh)  # window over: re-admitted...
    store.get_slice(q, txh)
    assert store.metrics.hits > h  # ...and hit


def test_frontier_knobs_reach_engine():
    from janusgraph_tpu.olap.frontier import FrontierEngine
    from janusgraph_tpu.olap.generators import rmat_csr
    from janusgraph_tpu.olap.tpu_executor import TPUExecutor

    csr = rmat_csr(8, 8)
    ex = TPUExecutor(
        csr, frontier_cc_min_edges=5, frontier_f_min=64, frontier_e_min=128,
    )
    assert ex.FRONTIER_CC_MIN_EDGES == 5
    eng = FrontierEngine(ex)
    assert eng.F_MIN == 64 and eng.E_MIN == 128


def test_remote_connect_timeout_and_id_retries_wire():
    from janusgraph_tpu.storage.inmemory import InMemoryStoreManager
    from janusgraph_tpu.storage.remote import (
        RemoteStoreManager,
        RemoteStoreServer,
    )

    server = RemoteStoreServer(InMemoryStoreManager()).start()
    host, port = server.address
    g = open_graph({
        "storage.backend": "remote",
        "storage.hostname": host, "storage.port": port,
        "storage.remote.connect-timeout-ms": 1234.0,
        "ids.authority.max-retries": 7,
    })
    assert isinstance(g.backend.manager, RemoteStoreManager)
    assert g.backend.manager.connect_timeout_s == pytest.approx(1.234)
    assert g.backend.id_authority.max_retries == 7
    g.close()
    server.stop()


def test_read_only_open_writes_nothing():
    """A read-only open must leave the store byte-identical: no instance
    registration, no global-config freeze writes, no id claims."""
    from janusgraph_tpu.storage.inmemory import InMemoryStoreManager

    mgr = InMemoryStoreManager()
    g = open_graph({"storage.backend": "inmemory"}, store_manager=mgr)
    tx = g.new_transaction()
    tx.add_vertex(name="pre")
    tx.commit()
    g.close()

    def snapshot():
        out = {}
        for name, store in mgr._stores.items():
            rows = {}
            for key, row in store._rows.items():
                rows[key] = (tuple(row.columns), tuple(row.values))
            out[name] = rows
        return out

    before = snapshot()
    ro = open_graph(
        {"storage.backend": "inmemory", "storage.read-only": True},
        store_manager=mgr,
    )
    tx = ro.new_transaction()
    assert len(list(tx.vertices())) == 1
    tx.rollback()
    ro.close()
    assert snapshot() == before


def test_batch2_options_wire_through():
    """Round-5 batch 2: slow-query counter, tx read-only default, server
    query-length cap, eviction-ack timeout."""
    import time as _t

    from janusgraph_tpu.util.metrics import metrics as mm

    g = open_graph({
        "storage.backend": "inmemory",
        "metrics.slow-query-threshold-ms": 0.0001,
        "tx.read-only-default": True,
        "schema.eviction-ack-timeout-ms": 750.0,
    })
    tx = g.new_transaction()          # defaults read-only now
    assert tx.read_only
    tx.rollback()
    tx = g.new_transaction(read_only=False)
    v = tx.add_vertex(name="n")
    tx.commit()

    before = mm.counter("query.slow").count
    g.traversal().V().has("name", "n").to_list()
    assert mm.counter("query.slow").count > before  # threshold ~0: fires

    # eviction-ack timeout actually reaches wait_for_acks (ms -> s)
    ml = g.management_logger
    captured = {}
    orig = ml.wait_for_acks
    ml.wait_for_acks = (
        lambda eid, exp, t: captured.setdefault("timeout_s", t) or True
    )
    try:
        g.management().broadcast_eviction(12345)
    finally:
        ml.wait_for_acks = orig
    assert captured["timeout_s"] == pytest.approx(0.75)

    # server query-length cap
    from janusgraph_tpu.server.manager import JanusGraphManager
    from janusgraph_tpu.server.server import JanusGraphServer, QueryTooLongError

    mgr = JanusGraphManager()
    mgr.put_graph("graph", g)
    srv = JanusGraphServer(manager=mgr, max_query_length=10)
    with pytest.raises(QueryTooLongError, match="max-query-length"):
        srv.execute("g.V().has('name','n').count()")
    g.close()


def test_set_vertex_id():
    """graph.set-vertex-id: caller-chosen vertex ids (reference:
    graph.set-vertex-id + IDManager.toVertexId)."""
    from janusgraph_tpu.exceptions import InvalidElementError

    g = open_graph({"storage.backend": "inmemory"})
    tx = g.new_transaction()
    with pytest.raises(InvalidElementError, match="set-vertex-id"):
        tx.add_vertex(vertex_id=g.idm.make_vertex_id(7, 3))
    tx.rollback()
    g.close()

    g = open_graph({
        "storage.backend": "inmemory", "graph.set-vertex-id": True,
    })
    tx = g.new_transaction()
    vid = g.idm.make_vertex_id(7, 3)
    v = tx.add_vertex(vertex_id=vid, name="pinned")
    assert v.id == vid
    w = tx.add_vertex(name="assigned")  # authority path still works
    tx.add_edge(v, "knows", w)
    tx.commit()

    tx = g.new_transaction()
    got = tx.get_vertex(vid)
    assert got is not None and got.value("name") == "pinned"
    from janusgraph_tpu.core.codecs import Direction

    assert [
        e.in_vertex.id
        for e in tx.get_edges(got, Direction.OUT, ("knows",))
    ] == [w.id]
    # duplicate refuses
    with pytest.raises(InvalidElementError, match="already exists"):
        tx.add_vertex(vertex_id=vid)
    # malformed refuses (schema-marked id)
    with pytest.raises(InvalidElementError, match="well-formed"):
        tx.add_vertex(vertex_id=-5)
    tx.rollback()
    g.close()


def test_set_vertex_id_edge_cases():
    """Custom-id guards: NORMAL family only, no removed-in-tx re-adds, no
    partitioned labels, and no label auto-creation on rejection."""
    from janusgraph_tpu.core.ids import VertexIDType
    from janusgraph_tpu.exceptions import InvalidElementError

    g = open_graph({
        "storage.backend": "inmemory", "graph.set-vertex-id": True,
    })
    tx = g.new_transaction()
    # partitioned-family id refused
    pid = g.idm.make_vertex_id(3, 0, VertexIDType.PARTITIONED)
    with pytest.raises(InvalidElementError, match="NORMAL"):
        tx.add_vertex(vertex_id=pid)
    # removed-in-tx id refused
    v = tx.add_vertex(vertex_id=g.idm.make_vertex_id(9, 1))
    tx.remove_vertex(v)
    with pytest.raises(InvalidElementError, match="removed in this"):
        tx.add_vertex(vertex_id=v.id)
    # rejection must not auto-create the label
    with pytest.raises(InvalidElementError, match="NORMAL"):
        tx.add_vertex(label="typo_label", vertex_id=pid)
    assert g.schema_cache.get_by_name("typo_label") is None
    # partitioned label refused for custom ids
    g.management().make_vertex_label("cut", partitioned=True)
    with pytest.raises(InvalidElementError, match="PARTITIONED"):
        tx.add_vertex(label="cut", vertex_id=g.idm.make_vertex_id(11, 1))
    tx.rollback()
    g.close()


def test_batch3_options_wire_through():
    """write-attempts cap, lock clean-expired, instance-id knobs, merged
    store metrics."""
    from janusgraph_tpu.exceptions import TemporaryBackendError
    from janusgraph_tpu.storage import backend_op

    # attempts cap trips before the time budget
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        raise TemporaryBackendError("still down")

    with pytest.raises(TemporaryBackendError):
        backend_op.execute(
            flaky, max_time_s=60.0, base_delay_s=0.001, max_attempts=3,
        )
    assert calls["n"] == 3

    # instance-id generation knobs
    import socket

    from janusgraph_tpu.core.config import generate_instance_id

    iid = generate_instance_id(suffix="rack7", use_hostname=True)
    assert iid.endswith("-rack7")
    assert socket.gethostname().replace(".", "-") in iid
    g = open_graph({
        "storage.backend": "inmemory",
        "graph.unique-instance-id-suffix": "z9",
    })
    assert g.instance_id.endswith("-z9")
    g.close()

    # merged store metrics bucket
    from janusgraph_tpu.util.metrics import metrics as mm

    g2 = open_graph({
        "storage.backend": "inmemory",
        "metrics.enabled": True, "metrics.merge-stores": True,
    })
    tx = g2.new_transaction()
    tx.add_vertex(name="m")
    tx.commit()
    names = {
        n for n in list(mm._timers) if n.startswith("storage.stores.")
    }
    assert names, "merged bucket metrics missing"
    g2.close()


def test_lock_clean_expired_removes_stale_claims():
    import time as _t

    from janusgraph_tpu.storage.inmemory import InMemoryStoreManager
    from janusgraph_tpu.storage.kcvs import KeySliceQuery, SliceQuery
    from janusgraph_tpu.storage.locking import (
        ConsistentKeyLocker,
        KeyColumn,
        LocalLockMediator,
        lock_row_key,
    )

    mgr = InMemoryStoreManager()
    store = mgr.open_database("locks")
    target = KeyColumn(b"k", b"c")
    row = lock_row_key(target)
    # a dead holder's EXPIRED claim
    stale_col = (1).to_bytes(8, "big") + b"deadrid1"
    store.mutate(row, [(stale_col, b"")], [], mgr.begin_transaction())

    locker = ConsistentKeyLocker(
        store, mgr.begin_transaction, b"livverid", LocalLockMediator(),
        wait_ms=0.0, expiry_ms=10_000.0, clean_expired=True,
    )
    tx = object()
    locker.write_lock(target, tx)
    locker.check_locks(tx)
    cols = [c for c, _ in store.get_slice(
        KeySliceQuery(row, SliceQuery()), mgr.begin_transaction()
    )]
    assert stale_col not in cols  # cleaned
    assert any(c.endswith(b"livverid") for c in cols)


def test_query_batch_toggle_and_renew_timeout():
    """query.batch=False expands per-vertex (no multiQuery prefetch);
    ids.renew-timeout-ms bounds the prefetch wait."""
    g = open_graph({"storage.backend": "inmemory", "query.batch": False})
    tx = g.new_transaction()
    a, b = tx.add_vertex(name="a"), tx.add_vertex(name="b")
    tx.add_edge(a, "knows", b)
    tx.commit()
    # correctness unchanged without the batch
    assert g.traversal().V().has("name", "a").out("knows").values(
        "name"
    ).to_list() == ["b"]
    assert g.id_assigner.renew_timeout_ms == 0.0
    g.close()

    g2 = open_graph({
        "storage.backend": "inmemory", "ids.renew-timeout-ms": 1234.0,
    })
    assert g2.id_assigner.renew_timeout_ms == 1234.0
    assert g2.id_assigner._relation_pool.renew_timeout_ms == 1234.0
    g2.close()

    # the timeout actually fires against a hung prefetch
    import threading

    import pytest as _pytest

    from janusgraph_tpu.exceptions import TemporaryBackendError
    from janusgraph_tpu.storage.idauthority import StandardIDPool

    class _HungAuthority:
        block_size = 10

        def get_id_block(self, ns, p):
            threading.Event().wait(10)  # never returns in test time

    pool = StandardIDPool(_HungAuthority(), 0, 0, renew_timeout_ms=50.0)
    # force an in-flight prefetch thread that never completes
    t = threading.Thread(target=lambda: threading.Event().wait(10), daemon=True)
    t.start()
    pool._prefetch_thread = t
    with _pytest.raises(TemporaryBackendError, match="renew-timeout"):
        pool.next_id()


# ---------------------------------------------------------------- r5 batch 5
def test_ignore_unknown_index_key():
    """query.ignore-unknown-index-key (reference default false): a
    graph-centric has() over a schema-unknown key raises; true treats it
    as unsatisfiable. merge_v's find path is exempt — an unknown key
    there IS the create path of the upsert."""
    from janusgraph_tpu.core.traversal import QueryError, T

    g = open_graph({"ids.authority-wait-ms": 0.0})
    t = g.traversal()
    v = t.add_v("person")
    t.commit()
    with pytest.raises(QueryError, match="unknown property key"):
        g.traversal().V().has("no_such_key", 1).to_list()
    # id point-lookups keep plain FILTER semantics (JanusGraphStep with
    # ids bypasses the graph-centric builder in the reference too)
    assert g.traversal().V(v.id).has("no_such_key", 1).to_list() == []
    # merge_v on a fresh key creates instead of raising
    made = g.traversal().merge_v({T.label: "person", "fresh_key": 1}).next()
    assert made.value("fresh_key") == 1
    g.close()

    g2 = open_graph({
        "ids.authority-wait-ms": 0.0,
        "query.ignore-unknown-index-key": True,
    })
    assert g2.traversal().V().has("no_such_key", 1).to_list() == []
    g2.close()


def test_scroll_page_size_config():
    """index.search.scroll-page-size drives query_stream paging."""
    g = open_graph({
        "ids.authority-wait-ms": 0.0,
        "index.search.scroll-page-size": 7,
    })
    assert g.index_providers["search"].scroll_page_size == 7
    g.close()


def test_log_slice_granularity_fixed():
    """log.slice-granularity-ms reaches KCVSLog row-key derivation."""
    g = open_graph({
        "ids.authority-wait-ms": 0.0,
        "log.slice-granularity-ms": 50,
    })
    log = g.log_manager.open_log("ulog_test")
    assert log._slice_ns == 50 * 1_000_000
    log.add_now(b"payload")
    log.flush()
    msgs = log.read_range(0)
    assert [m.content for m in msgs] == [b"payload"]
    g.close()


def test_frontier_tier_growth_config():
    """computer.frontier-tier-growth shapes the tier ladder."""
    from janusgraph_tpu.olap.frontier import _tier

    assert _tier(5000, 1 << 10, 1 << 20, 4) == 1 << 14
    assert _tier(5000, 1 << 10, 1 << 20, 2) == 1 << 13  # tighter fit
    from janusgraph_tpu.olap.generators import rmat_csr
    from janusgraph_tpu.olap.tpu_executor import TPUExecutor

    csr = rmat_csr(10, 8)
    ex = TPUExecutor(csr, frontier_tier_growth=2)
    from janusgraph_tpu.olap.frontier import FrontierEngine

    eng = FrontierEngine(ex)
    assert eng.GROWTH == 2
    # the sharded path honors it too
    from janusgraph_tpu.parallel import ShardedExecutor
    from janusgraph_tpu.parallel.sharded_frontier import (
        ShardedFrontierEngine,
    )

    sx = ShardedExecutor(csr, frontier_tier_growth=2)
    assert ShardedFrontierEngine(sx).GROWTH == 2


def test_remote_parallel_slice_factor():
    from janusgraph_tpu.storage.inmemory import InMemoryStoreManager
    from janusgraph_tpu.storage.remote import (
        RemoteStoreManager,
        RemoteStoreServer,
    )

    server = RemoteStoreServer(InMemoryStoreManager()).start()
    host, port = server.address
    try:
        mgr = RemoteStoreManager(
            host, port, pool_size=2, parallel_slice_factor=1
        )
        assert mgr.parallel_slice_factor == 1
        store = mgr.open_database("edgestore")
        tx = mgr.begin_transaction()
        from janusgraph_tpu.storage.kcvs import SliceQuery

        keys = [bytes([i]) * 4 for i in range(8)]
        for k in keys:
            store.mutate(k, [(b"c", b"v")], [], tx)
        # 8 keys > 1 * 2 conns -> parallel path; results must merge
        res = store.get_slice_multi(keys, SliceQuery(), tx)
        assert set(res.keys()) == set(keys)
        mgr.close()
    finally:
        server.stop()


def test_eviction_ack_poll_config():
    g = open_graph({
        "ids.authority-wait-ms": 0.0,
        "schema.eviction-ack-poll-ms": 1.0,
    })
    assert g.config.get("schema.eviction-ack-poll-ms") == 1.0
    # the poll path still reaches acks (single-instance: 0 expected acks
    # succeeds immediately; then an impossible expectation times out fast)
    mgmt = g.management()
    mgmt.make_property_key("k1", int)
    g.close()


def test_max_traversers_budget():
    """query.max-traversers: an exponentially exploding repeat().emit()
    raises instead of consuming the process."""
    from janusgraph_tpu.core import gods
    from janusgraph_tpu.core.traversal import AnonymousTraversal, QueryError

    __ = AnonymousTraversal()
    g = open_graph({
        "ids.authority-wait-ms": 0.0, "query.max-traversers": 500,
    })
    gods.load(g)
    try:
        t = g.traversal()
        with pytest.raises(QueryError, match="max-traversers"):
            # brother<->brother cycles double the frontier every loop
            t.V().repeat(__.both("brother"), emit=True).to_list()
        # bounded chains still work
        assert t.V().repeat(__.out("father"), times=2).to_list()
        # a plain wide step over the budget trips the per-step check
        g2 = open_graph({
            "ids.authority-wait-ms": 0.0, "query.max-traversers": 2,
        })
        gods.load(g2)
        try:
            with pytest.raises(QueryError, match="max-traversers"):
                g2.traversal().V().out("battled").to_list()
        finally:
            g2.close()
    finally:
        g.close()
