"""Multi-process distributed CSR loading (the Hadoop input-format analogue —
reference: HadoopInputFormat.java splits read by separate workers): N real
worker processes scan disjoint partition sets from a SHARED backend and the
parent merges; oracle = single-process load_csr.
"""

import numpy as np
import pytest

from janusgraph_tpu.core.bulk import bulk_add_edges, bulk_add_vertices
from janusgraph_tpu.core.graph import open_graph
from janusgraph_tpu.olap.csr import load_csr
from janusgraph_tpu.olap.distributed_load import distributed_load_csr
from janusgraph_tpu.storage.inmemory import InMemoryStoreManager
from janusgraph_tpu.storage.remote import RemoteStoreServer


def _seed(g, n=400, m=2500, seed=3):
    rng = np.random.default_rng(seed)
    vids = bulk_add_vertices(g, n, label="node")
    bulk_add_edges(
        g, "link", vids[rng.integers(0, n, m)], vids[rng.integers(0, n, m)]
    )
    return vids


def _csr_sets(csr):
    src = np.repeat(csr.vertex_ids, np.diff(csr.out_indptr))
    dst = csr.vertex_ids[csr.out_dst]
    return set(csr.vertex_ids.tolist()), set(zip(src.tolist(), dst.tolist()))


def test_distributed_matches_single_process_over_remote():
    server = RemoteStoreServer(InMemoryStoreManager()).start()
    host, port = server.address
    cfg = {
        "storage.backend": "remote",
        "storage.hostname": host,
        "storage.port": port,
    }
    g = open_graph(cfg)
    _seed(g)
    oracle = load_csr(g)
    g.close()

    csr = distributed_load_csr(cfg, num_workers=4)
    assert _csr_sets(csr) == _csr_sets(oracle)
    assert csr.num_edges == oracle.num_edges
    np.testing.assert_array_equal(csr.vertex_ids, oracle.vertex_ids)
    np.testing.assert_array_equal(csr.labels, oracle.labels)
    server.stop()


def test_distributed_over_local_directory(tmp_path):
    cfg = {
        "storage.backend": "local",
        "storage.directory": str(tmp_path / "store"),
    }
    g = open_graph(cfg)
    _seed(g, n=120, m=700, seed=9)
    oracle = load_csr(g)
    g.close()

    csr = distributed_load_csr(cfg, num_workers=3)
    assert _csr_sets(csr) == _csr_sets(oracle)


def test_cross_partition_edges_survive_the_split():
    """The property the merge exists for: edges whose src and dst live in
    DIFFERENT workers' partition sets must not be dropped."""
    server = RemoteStoreServer(InMemoryStoreManager()).start()
    host, port = server.address
    cfg = {
        "storage.backend": "remote",
        "storage.hostname": host,
        "storage.port": port,
    }
    g = open_graph(cfg)
    vids = _seed(g, n=300, m=1500)
    parts = {g.idm.get_partition_id(int(v)) for v in vids}
    assert len(parts) > 8  # spread over many partitions
    oracle = load_csr(g)
    g.close()
    csr = distributed_load_csr(cfg, num_workers=8)
    assert csr.num_edges == oracle.num_edges
    server.stop()


def test_rejects_private_backend():
    with pytest.raises(ValueError, match="SHARED backend"):
        distributed_load_csr({"storage.backend": "inmemory"})


def test_distributed_csr_runs_olap():
    server = RemoteStoreServer(InMemoryStoreManager()).start()
    host, port = server.address
    cfg = {
        "storage.backend": "remote",
        "storage.hostname": host,
        "storage.port": port,
    }
    g = open_graph(cfg)
    _seed(g, n=200, m=1000)
    g.close()
    csr = distributed_load_csr(cfg, num_workers=2)
    from janusgraph_tpu.olap.cpu_executor import CPUExecutor
    from janusgraph_tpu.olap.programs import PageRankProgram

    res = CPUExecutor(csr).run(PageRankProgram(max_iterations=10))
    assert abs(res["rank"].sum() - 1.0) < 1e-6
    server.stop()


def test_partition_bits_resolved_from_stored_config(tmp_path):
    """The FIXED partition count lives in the backend's global config; a
    caller dict omitting it must not silently lose partitions."""
    cfg_create = {
        "storage.backend": "local",
        "storage.directory": str(tmp_path / "pb7"),
        "ids.partition-bits": 7,
    }
    g = open_graph(cfg_create)
    _seed(g, n=300, m=1000, seed=5)
    oracle = load_csr(g)
    g.close()
    # caller omits partition-bits entirely: stored value (7) must win
    cfg_load = {
        "storage.backend": "local",
        "storage.directory": str(tmp_path / "pb7"),
    }
    csr = distributed_load_csr(cfg_load, num_workers=4)
    assert csr.num_vertices == oracle.num_vertices == 300
    assert csr.num_edges == oracle.num_edges
