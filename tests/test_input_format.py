"""Distributed input format + distributed reindex (reference:
HadoopInputFormat/HadoopRecordReader + JanusGraphVertexDeserializer;
MapReduceIndexManagement; AbstractInputFormatIT pattern)."""

import numpy as np
import pytest

from janusgraph_tpu.core import gods
from janusgraph_tpu.core.graph import open_graph
from janusgraph_tpu.olap.input_format import (
    DistributedIndexManagement,
    GraphInputFormat,
    load_shard_csrs,
)


@pytest.fixture(scope="module")
def gods_graph():
    g = open_graph({"ids.authority-wait-ms": 0.0})
    gods.load(g)
    yield g
    g.close()


def test_splits_cover_all_partitions(gods_graph):
    fmt = GraphInputFormat(gods_graph)
    splits = fmt.splits()
    nparts = gods_graph.idm.num_partitions
    assert sum(len(s.partitions) for s in splits) == nparts
    merged = fmt.splits(num_splits=3)
    assert len(merged) <= 3
    all_parts = sorted(p for s in merged for p in s.partitions)
    assert all_parts == list(range(nparts))


def test_read_all_star_vertices(gods_graph):
    fmt = GraphInputFormat(gods_graph)
    stars = list(fmt.read_all())
    assert len(stars) == 12
    by_name = {
        sv.properties.get("name", [None])[0]: sv for sv in stars
    }
    assert by_name["saturn"].label == "titan"
    herc = by_name["hercules"]
    assert herc.label == "demigod"
    labels = sorted(lbl for lbl, _o, _p in herc.edges)
    assert labels == ["battled", "battled", "battled", "father", "mother"]
    # edge property decoded (battled has time property)
    battled_props = [p for lbl, _o, p in herc.edges if lbl == "battled"]
    assert any("time" in p for p in battled_props)
    # total out-edges across all stars = total edges
    assert sum(len(sv.edges) for sv in stars) == 17


def test_split_reads_are_disjoint_and_complete(gods_graph):
    fmt = GraphInputFormat(gods_graph)
    seen = []
    for split in fmt.splits(num_splits=4):
        seen.extend(sv.vertex_id for sv in fmt.read_split(split))
    assert len(seen) == len(set(seen)) == 12


def test_load_shard_csrs(gods_graph):
    shards = load_shard_csrs(gods_graph, num_shards=4)
    assert sum(s.num_vertices for s in shards) == 12
    assert sum(s.num_edges for s in shards) <= 17  # cross-shard edges drop
    # single shard covering everything reproduces the full graph
    full = load_shard_csrs(gods_graph, num_shards=1)[0]
    assert full.num_vertices == 12 and full.num_edges == 17


def test_distributed_reindex(gods_graph):
    mgmt = gods_graph.management()
    if gods_graph.schema_cache.get_by_name("age_idx2") is None:
        mgmt.build_composite_index("age_idx2", ["age"])
    dim = DistributedIndexManagement(gods_graph, num_workers=3)
    metrics = dim.reindex("age_idx2")
    assert metrics.rows_processed >= 4  # vertices with an age property
    # the index answers queries afterwards
    src = gods_graph.traversal()
    res = src.V().has("age", 10000).values("name").to_list()
    assert res == ["saturn"]
    src.rollback()
