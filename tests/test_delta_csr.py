"""Incremental delta-CSR (ISSUE 14): commit-side change capture, fused
base+delta supersteps, zero-read materialization, compaction, spillover
delta refresh, and the staleness dedupe fix.

Contracts under test:
- capture completeness: materialize(base, overlay) is ARRAY-FOR-ARRAY
  identical to a fresh full load after any mix of edge adds/deletes and
  vertex add/removal (canonical-layout parity);
- base+delta fused results are bitwise-identical to the repacked CSR for
  the MIN family across {tpu, cpu, sharded} x {ell, hybrid}, and
  bitwise-identical to the numpy replay oracle for SUM;
- warm GraphComputer.submit() touches the store ZERO times;
- compaction folds the overlay at the threshold, off the superstep path;
- overlay/capture overflow falls back to a full repack, never to wrong
  numbers;
- spillover snapshot refresh is delta-apply (zero store reads) and stays
  read-your-writes; the staleness bound counts overlay lag, not commits.
"""

import numpy as np
import pytest

from janusgraph_tpu.core.codecs import Direction
from janusgraph_tpu.core.graph import open_graph
from janusgraph_tpu.olap import delta as D
from janusgraph_tpu.olap.csr import load_csr, load_csr_snapshot
from janusgraph_tpu.olap.cpu_executor import CPUExecutor
from janusgraph_tpu.olap.programs import (
    ConnectedComponentsProgram,
    PageRankProgram,
    ShortestPathProgram,
)
from janusgraph_tpu.olap.tpu_executor import TPUExecutor
from janusgraph_tpu.observability import flight_recorder, registry


def _counter(name):
    return registry.snapshot().get(name, {}).get("count", 0)


@pytest.fixture
def g():
    graph = open_graph({
        "schema.default": "auto",
        "computer.sharded-auto": False,
    })
    yield graph
    graph.close()


def seed_chain(g, n=30):
    tx = g.new_transaction()
    vs = [tx.add_vertex(name=f"v{i}") for i in range(n)]
    for i in range(n - 1):
        tx.add_edge(vs[i], "link", vs[i + 1])
    tx.commit()
    return vs


def seed_random(g, n=160, m=640, seed=11):
    rng = np.random.default_rng(seed)
    tx = g.new_transaction()
    vs = [tx.add_vertex() for _ in range(n)]
    for _ in range(m):
        a, b = rng.integers(0, n, 2)
        tx.add_edge(vs[int(a)], "link", vs[int(b)])
    tx.commit()
    return vs


def edge_burst(g, vs, seed=5, adds=24, dels=4):
    """Edge-only mutation burst (keeps index alignment for CC bitwise)."""
    rng = np.random.default_rng(seed)
    tx = g.new_transaction()
    for _ in range(adds):
        a, b = rng.integers(0, len(vs), 2)
        tx.add_edge(
            tx.get_vertex(vs[int(a)].id), "link",
            tx.get_vertex(vs[int(b)].id),
        )
    removed = 0
    for i in rng.permutation(len(vs)):
        if removed >= dels:
            break
        es = tx.get_edges(
            tx.get_vertex(vs[int(i)].id), Direction.OUT, ("link",)
        )
        if es:
            tx.remove_edge(es[0])
            removed += 1
    tx.commit()


def assert_arrays_equal(a, b):
    np.testing.assert_array_equal(a.vertex_ids, b.vertex_ids)
    np.testing.assert_array_equal(a.out_indptr, b.out_indptr)
    np.testing.assert_array_equal(a.in_indptr, b.in_indptr)
    np.testing.assert_array_equal(a.out_dst, b.out_dst)
    np.testing.assert_array_equal(a.in_src, b.in_src)


# ---------------------------------------------------------------- capture
def test_capture_completeness_mixed_mutations(g):
    vs = seed_chain(g)
    csr, epoch = load_csr_snapshot(g)
    tx = g.new_transaction()
    tx.add_edge(tx.get_vertex(vs[0].id), "link", tx.get_vertex(vs[29].id))
    e = tx.get_edges(tx.get_vertex(vs[4].id), Direction.OUT, ("link",))[0]
    tx.remove_edge(e)
    nv = tx.add_vertex(name="new")
    tx.add_edge(nv, "link", tx.get_vertex(vs[7].id))
    tx.commit()
    tx = g.new_transaction()
    tx.remove_vertex(tx.get_vertex(vs[20].id))
    tx.commit()
    ov, _upto = D.overlay_since(g, epoch)
    assert len(ov.new_vertices) == 1 and len(ov.removed) == 1
    # canonical-layout parity: byte-for-byte the arrays a full reload packs
    assert_arrays_equal(D.materialize(csr, ov, idm=g.idm), load_csr(g))


def test_capture_property_only_commit_is_structurally_empty(g):
    vs = seed_chain(g, n=5)
    _csr, epoch = load_csr_snapshot(g)
    tx = g.new_transaction()
    tx.get_vertex(vs[2].id).property("name", "renamed")
    tx.commit()
    ov, _ = D.overlay_since(g, epoch)
    assert ov.size == 0  # no structural records, nothing to refresh


def test_overlay_add_then_delete_nets_out(g):
    vs = seed_chain(g, n=6)
    csr, epoch = load_csr_snapshot(g)
    tx = g.new_transaction()
    tx.add_edge(tx.get_vertex(vs[0].id), "link", tx.get_vertex(vs[3].id))
    tx.commit()
    tx = g.new_transaction()
    e2 = tx.get_edges(tx.get_vertex(vs[0].id), Direction.OUT, ("link",))
    tx.remove_edge([x for x in e2 if x.in_vertex.id == vs[3].id][0])
    tx.commit()
    ov, _ = D.overlay_since(g, epoch)
    # multiset counting: the delete cancels the pending add — net zero
    assert len(ov.add) == 0 and len(ov.tomb) == 0 and ov.size == 0
    assert_arrays_equal(D.materialize(csr, ov, idm=g.idm), load_csr(g))


def test_capture_overflow_serves_none(g):
    vs = seed_chain(g, n=10)
    _csr, epoch = load_csr_snapshot(g)
    g.change_capture.limit = 4
    for i in range(8):
        tx = g.new_transaction()
        tx.add_edge(
            tx.get_vertex(vs[i % 9].id), "link",
            tx.get_vertex(vs[(i + 1) % 10].id),
        )
        tx.commit()
    assert g.change_capture.records_since(epoch) is None
    assert D.overlay_since(g, epoch) is None


# ----------------------------------------------------- fused bitwise matrix
@pytest.mark.parametrize("strategy", ["ell", "hybrid"])
@pytest.mark.parametrize("executor", ["tpu", "cpu"])
def test_base_plus_delta_bitwise_min_family(g, executor, strategy):
    """CC (undirected) and SSSP (directed) fused base+delta results are
    BITWISE-identical to runs over the freshly repacked CSR — min is
    exact and order-independent over the identical edge multiset."""
    vs = seed_random(g)
    csr, epoch = load_csr_snapshot(g)
    edge_burst(g, vs)
    ov, _ = D.overlay_since(g, epoch)
    assert ov.size > 0
    view = D.OverlayView(csr, ov)
    repack = load_csr(g)

    def run(graph, delta, program):
        if executor == "tpu":
            ex = TPUExecutor(graph, strategy=strategy, delta=delta)
        else:
            ex = CPUExecutor(graph, strategy=strategy, delta=delta)
        return ex.run(program)

    f = run(csr, view, ConnectedComponentsProgram(max_iterations=40))
    r = run(repack, None, ConnectedComponentsProgram(max_iterations=40))
    np.testing.assert_array_equal(f["component"], r["component"])

    seed_vid = int(csr.vertex_ids[5])
    si = int(np.searchsorted(repack.vertex_ids, seed_vid))
    f = run(csr, view, ShortestPathProgram(seed_index=5, max_iterations=40))
    r = run(
        repack, None, ShortestPathProgram(seed_index=si, max_iterations=40)
    )
    np.testing.assert_array_equal(f["distance"], r["distance"])


@pytest.mark.parametrize("strategy", ["ell", "hybrid"])
@pytest.mark.parametrize("executor", ["tpu", "cpu"])
def test_base_plus_delta_sum_close_to_repack(g, executor, strategy):
    vs = seed_random(g)
    csr, epoch = load_csr_snapshot(g)
    edge_burst(g, vs)
    ov, _ = D.overlay_since(g, epoch)
    view = D.OverlayView(csr, ov)
    repack = load_csr(g)
    if executor == "tpu":
        f = TPUExecutor(csr, strategy=strategy, delta=view).run(
            PageRankProgram(max_iterations=10)
        )
        r = TPUExecutor(repack, strategy=strategy).run(
            PageRankProgram(max_iterations=10)
        )
    else:
        f = CPUExecutor(csr, strategy=strategy, delta=view).run(
            PageRankProgram(max_iterations=10)
        )
        r = CPUExecutor(repack, strategy=strategy).run(
            PageRankProgram(max_iterations=10)
        )
    np.testing.assert_allclose(f["rank"], r["rank"], rtol=1e-5, atol=1e-7)


def test_fused_merge_matches_replay_oracle_bitwise():
    """The SUM contract: the jitted fused merge is bitwise-identical to
    the numpy replay oracle on the same inputs (np.add.at == XLA CPU
    scatter — the PR 9 contract), for every monoid, scalar and 2-D."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    npad, nb = 272, 256
    meta = {"n_base": nb, "n_pad": npad}

    def lane(cap, hi):
        src = np.full(cap, npad, np.int32)
        dst = np.full(cap, npad, np.int32)
        k = int(rng.integers(1, cap))
        src[:k] = rng.integers(0, hi, k)
        dst[:k] = rng.integers(0, hi, k)
        return src, dst

    a_s, a_d = lane(32, npad)
    t_s, t_d = lane(16, nb)
    l_s, l_d = lane(64, nb)
    dirty = np.zeros(npad, np.float32)
    dirty[np.unique(t_d[t_d < npad])] = 1.0
    lanes = {
        "add_src": a_s, "add_dst": a_d, "tomb_src": t_s, "tomb_dst": t_d,
        "live_src": l_s, "live_dst": l_d, "dirty": dirty,
    }
    for op in ("sum", "min", "max"):
        for shape in ((npad,), (npad, 4)):
            msgs = rng.standard_normal(shape).astype(np.float32)
            base = rng.standard_normal((nb,) + shape[1:]).astype(np.float32)
            want = D.replay_fused_aggregate(lanes, meta, msgs, base, op)
            jl = {k: jnp.asarray(v) for k, v in lanes.items()}
            got = jax.jit(
                lambda lv, m, b, _op=op: D.fused_delta_aggregate(
                    jnp, lv, meta, m, b, _op
                )
            )(jl, jnp.asarray(msgs), jnp.asarray(base))
            np.testing.assert_array_equal(np.asarray(got), want)


def test_fused_vertex_add_remove_semantics(g):
    """Vertex adds/removals ride the fused path: results are id-aligned
    float-close to the repacked run over the SURVIVING vertex set."""
    vs = seed_chain(g, n=40)
    csr, epoch = load_csr_snapshot(g)
    tx = g.new_transaction()
    nv = tx.add_vertex()
    tx.add_edge(nv, "link", tx.get_vertex(vs[0].id))
    tx.commit()
    tx = g.new_transaction()
    tx.remove_vertex(tx.get_vertex(vs[20].id))
    tx.commit()
    ov, _ = D.overlay_since(g, epoch)
    view = D.OverlayView(csr, ov)
    f = TPUExecutor(csr, delta=view).run(PageRankProgram(max_iterations=8))
    f, rv = D.compact_result(view, f)
    repack = load_csr(g)
    r = TPUExecutor(repack).run(PageRankProgram(max_iterations=8))
    assert set(int(v) for v in rv.vertex_ids) == set(
        int(v) for v in repack.vertex_ids
    )
    for vid in rv.vertex_ids:
        np.testing.assert_allclose(
            f["rank"][rv.index_of(int(vid))],
            r["rank"][repack.index_of(int(vid))],
            rtol=1e-5, atol=1e-7,
        )


# ---------------------------------------------------------------- sharded
@pytest.fixture(scope="module")
def mesh8():
    import jax
    from jax.sharding import Mesh

    devices = np.array(jax.devices()[:8])
    assert len(devices) == 8
    return Mesh(devices, ("p",))


def test_sharded_base_plus_delta_bitwise(g, mesh8):
    """The sharded path consumes the delta by materializing base+overlay
    (zero store reads) — the resulting arrays are identical to a repack,
    so the mesh run is bitwise-identical by construction. Asserted
    end-to-end: sharded-on-materialized == sharded-on-repacked."""
    from janusgraph_tpu.parallel import ShardedExecutor

    vs = seed_random(g, n=120, m=480)
    csr, epoch = load_csr_snapshot(g)
    edge_burst(g, vs, adds=16, dels=3)
    ov, _ = D.overlay_since(g, epoch)
    mat = D.materialize(csr, ov, idm=g.idm)
    repack = load_csr(g)
    assert_arrays_equal(mat, repack)
    f = ShardedExecutor(mat, mesh=mesh8).run(
        ConnectedComponentsProgram(max_iterations=40)
    )
    r = ShardedExecutor(repack, mesh=mesh8).run(
        ConnectedComponentsProgram(max_iterations=40)
    )
    np.testing.assert_array_equal(
        np.asarray(f["component"]), np.asarray(r["component"])
    )


def test_route_overlay_owner_shard_coupling(g):
    """Every delta record routes to exactly one shard — the owner of its
    aggregation-side (dst) row under the contiguous dst // Np layout the
    sharded executor and host_shard_range share."""
    vs = seed_random(g, n=100, m=400)
    csr, epoch = load_csr_snapshot(g)
    edge_burst(g, vs, adds=20, dels=4)
    ov, _ = D.overlay_since(g, epoch)
    view = D.OverlayView(csr, ov)
    S = 4
    routed = D.route_overlay(view, S)
    assert len(routed) == S
    Np = -(-view.n_pad // S)
    tot_add = tot_tomb = 0
    for r in routed:
        lo, hi = r["row_range"]
        assert lo == r["shard"] * Np
        assert np.all((r["add_dst"] >= lo) & (r["add_dst"] < lo + Np))
        assert np.all((r["tomb_dst"] >= lo) & (r["tomb_dst"] < lo + Np))
        tot_add += len(r["add_dst"])
        tot_tomb += len(r["tomb_dst"])
    assert tot_add == len(view.add_dst)
    assert tot_tomb == len(view.tomb_dst)
    # host coupling: the per-host slice is the union of its shards'
    hostr = D.route_for_host(view, S, process_id=0, num_processes=2)
    lo_s, hi_s = hostr["shards"]
    want = sum(len(routed[s]["add_dst"]) for s in range(lo_s, hi_s))
    assert len(hostr["add_dst"]) == want


# ------------------------------------------------------------ warm submit
def test_warm_submit_skips_scan_entirely(g):
    seed_chain(g, n=25)
    r1 = g.compute().program(PageRankProgram(max_iterations=5)).submit()
    calls = []
    store = g.backend.edgestore
    orig = store.get_keys
    store.get_keys = lambda *a, **k: (calls.append(1), orig(*a, **k))[1]
    try:
        r2 = g.compute().program(PageRankProgram(max_iterations=5)).submit()
    finally:
        store.get_keys = orig
    assert not calls, "warm submit re-scanned the store"
    np.testing.assert_array_equal(r1.states["rank"], r2.states["rank"])


def test_fused_submit_zero_store_reads(g):
    vs = seed_chain(g, n=25)
    g.compute().program(PageRankProgram(max_iterations=5)).submit()
    tx = g.new_transaction()
    tx.add_edge(
        tx.get_vertex(vs[3].id), "link", tx.get_vertex(vs[17].id)
    )
    tx.commit()
    store = g.backend.edgestore
    scans, slices = [], []
    ok, osl = store.get_keys, store.get_slice
    store.get_keys = lambda *a, **k: (scans.append(1), ok(*a, **k))[1]
    store.get_slice = lambda *a, **k: (slices.append(1), osl(*a, **k))[1]
    try:
        r = g.compute().program(PageRankProgram(max_iterations=5)).submit()
    finally:
        store.get_keys, store.get_slice = ok, osl
    assert not scans and not slices, (
        f"delta submit read the store: {len(scans)} scans, "
        f"{len(slices)} slices"
    )
    assert r.run_info.get("delta", {}).get("fused") is True
    # read-your-writes: the new edge affected the result
    assert abs(float(np.sum(r.states["rank"])) - 1.0) < 1e-5


# ------------------------------------------------------------- compaction
def test_compaction_threshold_folds_overlay():
    g = open_graph({
        "schema.default": "auto",
        "computer.sharded-auto": False,
        "computer.delta-compact-threshold": 4,
    })
    try:
        vs = seed_chain(g, n=20)
        g.compute().program(PageRankProgram(max_iterations=3)).submit()
        before = _counter("olap.delta.compactions")
        tx = g.new_transaction()
        for i in range(6):
            tx.add_edge(
                tx.get_vertex(vs[i].id), "link",
                tx.get_vertex(vs[(i + 7) % 20].id),
            )
        tx.commit()
        g.compute().program(PageRankProgram(max_iterations=3)).submit()
        assert _counter("olap.delta.compactions") == before + 1
        assert any(
            e.get("category") == "delta_compact"
            for e in flight_recorder.snapshot()["events"]
        )
        snap = g._delta_snapshot
        # folded: the base now carries the burst, overlay drained
        got = D.overlay_since(g, snap.epoch)
        assert got is not None and got[0].size == 0
        assert_arrays_equal(snap.csr, load_csr(g))
    finally:
        g.close()


def test_compaction_persists_snapshot_tmp_rename(tmp_path):
    path = str(tmp_path / "delta.snapshot.npz")
    g = open_graph({
        "schema.default": "auto",
        "computer.sharded-auto": False,
        "computer.delta-compact-threshold": 2,
        "computer.delta-snapshot-path": path,
    })
    try:
        vs = seed_chain(g, n=12)
        g.compute().program(PageRankProgram(max_iterations=3)).submit()
        tx = g.new_transaction()
        tx.add_edge(
            tx.get_vertex(vs[0].id), "link", tx.get_vertex(vs[5].id)
        )
        tx.add_edge(
            tx.get_vertex(vs[1].id), "link", tx.get_vertex(vs[6].id)
        )
        tx.commit()
        g.compute().program(PageRankProgram(max_iterations=3)).submit()
        loaded = D.load_snapshot(path)
        assert loaded is not None
        csr, _epoch = loaded
        assert_arrays_equal(csr, load_csr(g))
        # torn file -> cold start, never garbage
        with open(path, "wb") as f:
            f.write(b"\x00garbage")
        assert D.load_snapshot(path) is None
    finally:
        g.close()


def test_decide_delta_deterministic_and_overridable():
    from janusgraph_tpu.olap.autotune import decide_delta

    a = decide_delta(16_000_000, 1_000_000, "cpu")
    b = decide_delta(16_000_000, 1_000_000, "cpu")
    assert a == b
    t = a.compact_threshold
    assert t > 0 and (t & (t - 1)) == 0  # pow2 tier
    c = decide_delta(
        16_000_000, 1_000_000, "cpu",
        overrides={"compact_threshold": 777},
    )
    assert c.compact_threshold == 777 and c.source == "config"
    assert "materialize_s" in a.cells and "repack_s" in a.cells


# ----------------------------------------------------- overflow fallbacks
def test_capture_overflow_submit_falls_back_to_repack():
    g = open_graph({
        "schema.default": "auto",
        "computer.sharded-auto": False,
        "computer.delta-capture-limit": 4,
    })
    try:
        vs = seed_chain(g, n=20)
        g.compute().program(PageRankProgram(max_iterations=3)).submit()
        before = _counter("olap.delta.capture_overflow")
        tx = g.new_transaction()
        for i in range(12):
            tx.add_edge(
                tx.get_vertex(vs[i % 20].id), "link",
                tx.get_vertex(vs[(i + 3) % 20].id),
            )
        tx.commit()
        r = g.compute().program(PageRankProgram(max_iterations=3)).submit()
        assert _counter("olap.delta.capture_overflow") == before + 1
        # the fallback repack still sees every write
        assert r.csr.num_edges == load_csr(g).num_edges
    finally:
        g.close()


def test_executor_refuses_incompatible_programs(g):
    vs = seed_chain(g, n=10)
    csr, epoch = load_csr_snapshot(g)
    tx = g.new_transaction()
    tx.add_edge(tx.get_vertex(vs[0].id), "link", tx.get_vertex(vs[5].id))
    tx.commit()
    ov, _ = D.overlay_since(g, epoch)
    view = D.OverlayView(csr, ov)
    with pytest.raises(ValueError, match="scalar"):
        CPUExecutor(csr, strategy="scalar", delta=view)
    from janusgraph_tpu.olap.programs.olap_traversal import (
        OLAPTraversalProgram,
        steps_from_spec,
    )

    prog = OLAPTraversalProgram(
        steps_from_spec(g, [("out", ["link"]), ("out", ["link"])])
    )
    with pytest.raises(ValueError, match="default-edge-view"):
        TPUExecutor(csr, delta=view).run(prog)


# ------------------------------------------------- spillover delta refresh
def _promoted_planner(g, vs):
    """Promote the 2-hop count shape onto the spillover planner."""
    planner = g.spillover_planner
    planner.min_cost_ms = 0.0
    planner.min_seen = 1

    def burst():
        return g.traversal().V(vs[0].id).out("link").out("link").count()

    burst()
    burst()
    return planner, burst


def test_spillover_refresh_is_delta_apply_zero_row_reads(g):
    vs = seed_chain(g, n=40)
    planner, burst = _promoted_planner(g, vs)
    before = burst()
    assert planner._csr is not None  # spilled at least once
    refreshes0 = _counter("olap.spillover.delta_refreshes")
    tx = g.new_transaction()
    tx.add_edge(
        tx.get_vertex(vs[1].id), "link", tx.get_vertex(vs[30].id)
    )
    tx.commit()
    store = g.backend.edgestore
    slices = []
    osl = store.get_slice
    store.get_slice = lambda *a, **k: (slices.append(1), osl(*a, **k))[1]
    try:
        after = burst()
    finally:
        store.get_slice = osl
    # read-your-writes across commits: the spilled result sees the edge
    assert after == before + 1
    assert _counter("olap.spillover.delta_refreshes") == refreshes0 + 1
    assert not slices, (
        f"delta refresh re-read {len(slices)} rows from the store"
    )


def test_spillover_staleness_counts_overlay_lag_not_commits(g):
    """Satellite fix: repeated property-only commits (same row, zero
    structural change) used to bump the epoch once each and trip the
    staleness bound, forcing spurious full repacks. Lag now measures
    pending overlay records (deduped per (tx, row) at the tracker), so
    the snapshot refreshes in place."""
    vs = seed_chain(g, n=40)
    planner, burst = _promoted_planner(g, vs)
    planner.max_staleness = 4
    burst()
    stale0 = _counter("olap.spillover.stale")
    packs0 = _counter("olap.spillover.packs")
    for i in range(12):  # 3x the bound, all epoch bumps, zero structure
        tx = g.new_transaction()
        tx.get_vertex(vs[7].id).property("name", f"spin{i}")
        tx.commit()
    burst()
    assert _counter("olap.spillover.stale") == stale0
    assert _counter("olap.spillover.packs") == packs0
    snap = registry.snapshot()
    assert snap["olap.spillover.staleness"]["value"] == 0.0


def test_touched_count_since_dedupes_rows(g):
    vs = seed_chain(g, n=10)
    epoch = g.backend.mutation_epoch()
    for i in range(5):
        tx = g.new_transaction()
        tx.get_vertex(vs[3].id).property("name", f"r{i}")
        tx.commit()
    assert g.backend.mutation_epoch() - epoch == 5  # commits counted
    assert g.backend.touched_count_since(epoch) == 1  # rows deduped


# ------------------------------------------------------- metrics / SLO
def test_slo_freshness_spec_tracks_overlay_lag_unchanged(g):
    """The PR 13 freshness spec (gauge olap.spillover.staleness) tracks
    the delta-overlay lag with ZERO spec changes: stock default_specs,
    stock gauge name — the planner's snapshot path now feeds the gauge
    pending overlay records instead of raw commit counts."""
    import itertools

    from janusgraph_tpu.observability.slo import SLOEngine, default_specs
    from janusgraph_tpu.observability.timeseries import MetricsHistory

    vs = seed_chain(g, n=30)
    planner, burst = _promoted_planner(g, vs)
    burst()
    planner.max_staleness = 5  # lag 10 > 5 -> the stale path fires
    tx = g.new_transaction()
    for i in range(10):
        tx.add_edge(
            tx.get_vertex(vs[i].id), "link",
            tx.get_vertex(vs[(i + 11) % 30].id),
        )
    tx.commit()
    assert g.change_capture.depth_since(planner._epoch) == 10
    # the spilled attempt falls back stale — but first it published the
    # overlay lag through the UNCHANGED freshness gauge
    burst()
    assert registry.snapshot()["olap.spillover.staleness"]["value"] == 10.0
    spec = [
        s for s in default_specs(freshness_max_staleness=5.0)
        if s.kind == "freshness"
    ][0]
    assert spec.gauge == "olap.spillover.staleness"  # stock spec, untouched
    clock = itertools.count(1000.0, 1.0)
    h = MetricsHistory(
        registry, capacity=16, interval_s=1.0,
        clock=lambda: float(next(clock)),
        wall_clock=lambda: float(next(clock)),
    )
    eng = SLOEngine(h, [spec])
    h.sample()
    alert = eng.evaluate()[0]
    assert alert["name"] == "olap_freshness"
    assert alert["fast_burn"] > 1.0  # 10 pending records vs bound 5 burns


def test_delta_metrics_and_flight_event():
    g = open_graph({
        "schema.default": "auto",
        "computer.sharded-auto": False,
        "computer.delta-compact-threshold": 2,
    })
    try:
        vs = seed_chain(g, n=12)
        g.compute().program(PageRankProgram(max_iterations=3)).submit()
        tx = g.new_transaction()
        tx.add_edge(
            tx.get_vertex(vs[0].id), "link", tx.get_vertex(vs[6].id)
        )
        tx.add_edge(
            tx.get_vertex(vs[2].id), "link", tx.get_vertex(vs[8].id)
        )
        tx.commit()
        g.compute().program(PageRankProgram(max_iterations=3)).submit()
        snap = registry.snapshot()
        assert "olap.delta.overlay_depth" in snap
        assert snap["olap.delta.compactions"]["count"] >= 1
        ev = [
            e for e in flight_recorder.snapshot()["events"]
            if e.get("category") == "delta_compact"
        ]
        assert ev and ev[-1]["depth"] >= 2
    finally:
        g.close()


# --------------------------------------------------------- persistence etc
def test_save_load_snapshot_roundtrip(tmp_path, g):
    seed_chain(g, n=15)
    csr, epoch = load_csr_snapshot(g)
    path = str(tmp_path / "snap.npz")
    D.save_snapshot(path, csr, epoch)
    loaded = D.load_snapshot(path)
    assert loaded is not None
    csr2, e2 = loaded
    assert e2 == epoch
    assert_arrays_equal(csr, csr2)
