"""Propagation-blocked halo exchange (ISSUE 9): the sharded executor's
default fast path.

Contracts under test, on the 8-virtual-device CPU mesh:

* MIN-combiner programs (BFS/SSSP/CC) are BITWISE-identical between the
  blocked and eager exchanges (min is exactly order-insensitive), on both
  the dense and frontier paths.
* SUM programs (PageRank, dense feature blocks) are BITWISE-identical to
  the blocked plan's numpy replay oracle (halo.replay_superstep — the
  HybridPack-style same-arithmetic contract) and agree with the eager
  exchange and the scalar CPU oracle to float tolerance.
* Distributed CSR loading: per-host build_local blocks concatenate to the
  single-process plan, with only the compact pair metadata exchanged.
* Chaos interplay: dropped-halo-batch + preemption auto-resume stays
  bitwise under the batched exchange.
* decide_sharded is deterministic and its measured persistence is keyed
  by shard count.
* GraphComputer routing (computer.sharded-auto) picks the sharded
  executor on a mesh and records the decision in run_info["routing"].
"""

import numpy as np
import pytest

from janusgraph_tpu.olap import csr_from_edges, run_on
from janusgraph_tpu.olap.cpu_executor import CPUExecutor
from janusgraph_tpu.olap.programs import (
    ConnectedComponentsProgram,
    GCNForwardProgram,
    PageRankProgram,
    ShortestPathProgram,
)
from janusgraph_tpu.olap.vertex_program import Combiner, VertexProgram
from janusgraph_tpu.parallel import ShardedExecutor, halo
from janusgraph_tpu.parallel.sharded import ShardedCSR


def random_graph(n=170, m=700, seed=11, weights=False):
    rng = np.random.default_rng(seed)
    src = rng.integers(0, n, m).astype(np.int32)
    dst = rng.integers(0, n, m).astype(np.int32)
    w = rng.uniform(0.5, 2.0, m).astype(np.float32) if weights else None
    return csr_from_edges(n, src, dst, w)


@pytest.fixture(scope="module")
def mesh8():
    import jax
    from jax.sharding import Mesh

    devices = np.array(jax.devices()[:8])
    assert len(devices) == 8, "conftest must provide 8 virtual devices"
    return Mesh(devices, ("p",))


# ------------------------------------------------------ bitwise: MIN family
@pytest.mark.parametrize("agg", ["ell", "segment"])
@pytest.mark.parametrize("name,make", [
    ("bfs", lambda: ShortestPathProgram(seed_index=0)),
    ("sssp_w", lambda: ShortestPathProgram(seed_index=3, weighted=True)),
    ("cc", lambda: ConnectedComponentsProgram()),
])
def test_blocked_bitwise_min_family_dense_path(mesh8, agg, name, make):
    """Blocked vs eager, dense (non-frontier) supersteps: min/max merges
    are exactly order-insensitive, so the exchange restructure must not
    change a single bit."""
    g = random_graph(weights=True)
    blocked = ShardedExecutor(g, mesh=mesh8, exchange="blocked", agg=agg)
    eager = ShardedExecutor(g, mesh=mesh8)  # a2a + ell, the PR 8 default
    rb = blocked.run(make(), frontier="off")
    re_ = eager.run(make(), frontier="off")
    assert set(rb) == set(re_)
    for k in rb:
        np.testing.assert_array_equal(
            np.asarray(rb[k]), np.asarray(re_[k]), err_msg=f"{name}:{k}"
        )
    cpu = CPUExecutor(g).run(make())
    for k in rb:
        np.testing.assert_allclose(
            np.asarray(rb[k], np.float64), cpu[k], rtol=1e-5, atol=1e-6,
        )


def test_blocked_frontier_bitwise_and_collapsed_expansion(mesh8):
    """The frontier engine under the blocked exchange: sender-merged
    relaxation bins, bitwise-identical hops, remote expansion collapsed
    to one edge per used bin (strictly fewer max edges than eager)."""
    g = random_graph(n=190, m=900, seed=5, weights=True)
    blocked = ShardedExecutor(g, mesh=mesh8, exchange="blocked")
    eager = ShardedExecutor(g, mesh=mesh8)
    for make in (
        lambda: ShortestPathProgram(seed_index=0),
        lambda: ShortestPathProgram(seed_index=3, weighted=True),
    ):
        rb = blocked.run(make())
        assert blocked.last_run_info["path"] == "frontier"
        re_ = eager.run(make())
        for k in rb:
            np.testing.assert_array_equal(rb[k], re_[k])
    tb = blocked._frontier_engine.last_trace
    assert all(h["exchange"] == "blocked" for h in tb)
    # predecessor tracking needs per-source identity: falls back to eager
    rt = blocked.run(ShortestPathProgram(seed_index=0, track_paths=True))
    rte = eager.run(ShortestPathProgram(seed_index=0, track_paths=True))
    np.testing.assert_array_equal(rt["predecessor"], rte["predecessor"])
    assert blocked._frontier_engine.last_trace[0]["exchange"] == "a2a"


# --------------------------------------------- bitwise: replay oracle (SUM)
class _PassthroughProgram(VertexProgram):
    """apply() returns the aggregate unchanged, so the state after ONE
    superstep IS the aggregation of the setup values — the harness that
    pins the device kernel against halo.replay_superstep bit-for-bit."""

    compute_keys = ("x",)
    combiner = Combiner.SUM
    max_iterations = 1

    def __init__(self, op=Combiner.SUM, cols=0):
        self.combiner = op
        self.cols = cols

    def setup(self, graph, xp):
        n = graph.local_num_vertices
        base = (xp.arange(n) % 89 + 1.0) / 7.0
        if self.cols:
            x = base[:, None] * (xp.arange(self.cols)[None, :] + 1.0)
        else:
            x = base
        return {"x": x * xp.asarray(graph.active if self.cols == 0 else 1.0)}, {}

    def message(self, state, step, graph, xp):
        return state["x"]

    def apply(self, state, agg, step, mem, graph, xp):
        return {"x": agg}, {}

    def terminate(self, memory):
        return False


@pytest.mark.parametrize("agg", ["ell", "segment"])
@pytest.mark.parametrize("op", [Combiner.SUM, Combiner.MIN])
@pytest.mark.parametrize("weights", [False, True])
def test_blocked_superstep_bitwise_vs_numpy_replay(mesh8, agg, op, weights):
    """One full device superstep (gather → fused bin merge → all_to_all →
    receiver combine) is bitwise-identical to the plan's numpy replay —
    the CPU-oracle side of the blocked contract, for both aggregation
    formats and both combiners."""
    g = random_graph(n=210, m=860, seed=7, weights=weights)
    ex = ShardedExecutor(g, mesh=mesh8, exchange="blocked", agg=agg)
    prog = _PassthroughProgram(op=op)
    out = ex.run(prog, fused=False, frontier="off")
    sc = ex._sharded(False)
    plan = sc.blocked_plan
    setup_state, _ = prog.setup(
        type("V", (), {
            "local_num_vertices": sc.padded_n, "active": sc.active,
        })(), np,
    )
    outgoing = np.asarray(setup_state["x"], dtype=np.float32)
    expect = halo.replay_superstep(
        plan, outgoing, op, has_weight=sc.has_weight, agg=agg
    )
    np.testing.assert_array_equal(out["x"], expect[: sc.real_n])


@pytest.mark.parametrize("agg", ["ell", "segment"])
def test_blocked_dense_feature_block_bitwise_vs_replay(mesh8, agg):
    """The same replay contract for [n, d] feature-block messages — the
    dense tier's halo exchange ships whole merged rows."""
    g = random_graph(n=130, m=520, seed=9, weights=True)
    ex = ShardedExecutor(g, mesh=mesh8, exchange="blocked", agg=agg)
    prog = _PassthroughProgram(op=Combiner.SUM, cols=8)
    out = ex.run(prog, fused=False, frontier="off")
    sc = ex._sharded(False)
    setup_state, _ = prog.setup(
        type("V", (), {
            "local_num_vertices": sc.padded_n, "active": sc.active,
        })(), np,
    )
    outgoing = np.asarray(setup_state["x"], dtype=np.float32)
    expect = halo.replay_superstep(
        sc.blocked_plan, outgoing, Combiner.SUM,
        has_weight=sc.has_weight, agg=agg,
    )
    np.testing.assert_array_equal(out["x"], expect[: sc.real_n])


@pytest.mark.parametrize("agg", ["ell", "segment"])
def test_blocked_pagerank_and_dense_match_oracle(mesh8, agg):
    """Full programs across the exchange restructure: PageRank and a GCN
    forward pass agree with the eager exchange and the CPU oracle to
    float tolerance (SUM associates per source shard under blocking)."""
    g = random_graph(n=180, m=760, seed=3)
    mk = lambda: PageRankProgram(max_iterations=15, tol=0.0)  # noqa: E731
    rb = ShardedExecutor(g, mesh=mesh8, exchange="blocked", agg=agg).run(mk())
    re_ = ShardedExecutor(g, mesh=mesh8).run(mk())
    np.testing.assert_allclose(rb["rank"], re_["rank"], rtol=1e-5, atol=1e-8)
    cpu = CPUExecutor(g).run(mk())
    np.testing.assert_allclose(rb["rank"], cpu["rank"], rtol=1e-4, atol=1e-6)

    gcn = lambda: GCNForwardProgram(  # noqa: E731
        feature_dim=16, hidden_dim=16, out_dim=16, num_layers=2, seed=1
    )
    db = ShardedExecutor(g, mesh=mesh8, exchange="blocked", agg=agg).run(gcn())
    dc = CPUExecutor(g).run(gcn())
    np.testing.assert_allclose(db["h"], dc["h"], rtol=1e-4, atol=1e-5)


def test_sddmm_refused_on_sharded(mesh8):
    g = random_graph()
    prog = GCNForwardProgram(
        feature_dim=8, hidden_dim=8, out_dim=8, attention=True
    )
    with pytest.raises(NotImplementedError, match="sddmm"):
        ShardedExecutor(g, mesh=mesh8, exchange="blocked").run(prog)


# ----------------------------------------------------- distributed loading
def test_blocked_plan_distributed_build_matches_full():
    """Each host builds ONLY its shard range's blocks from its own edges
    plus the exchanged compact pair metadata; the concatenation equals
    the single-process plan array-for-array."""
    g = random_graph(n=220, m=900, seed=13, weights=True)
    S = 8
    sc = ShardedCSR(g, S, False)
    src, dst, w = halo.edges_from_sharded(sc)
    full = halo.BlockedPlan.build(src, dst, w, S, sc.shard_size)

    # the metadata handshake: every host contributes its owners' lists
    lists = {}
    for lo, hi in ((0, 3), (3, 8)):
        lists.update(halo.pair_dst_lists(
            src, dst, S, sc.shard_size, owner_range=(lo, hi)
        ))
    assert set(lists) == set(full.pair_lists)
    hc = halo.halo_tier(lists)
    assert hc == full.halo_cap

    parts = []
    for lo, hi in ((0, 3), (3, 8)):
        owner = src // sc.shard_size
        m = (owner >= lo) & (owner < hi)
        part = halo.BlockedPlan.build_local(
            src[m], dst[m], w[m], S, sc.shard_size, (lo, hi),
            hc, full.edges_per_owner, lists,
        )
        parts.append(part)
    for name in ("blk_src_loc", "blk_seg", "blk_bin_seg", "blk_valid",
                 "blk_weight", "recv_dst"):
        got = np.concatenate([getattr(p, name) for p in parts])
        np.testing.assert_array_equal(
            got, getattr(full, name), err_msg=name
        )
    assert (
        sum(p.edges_by_owner[0] for p in parts) > 0
    )


def test_host_shard_range_couples_to_partition_range():
    from janusgraph_tpu.parallel.multihost import (
        host_partition_range,
        host_shard_range,
    )

    assert host_shard_range(8, 0, 2) == host_partition_range(8, 0, 2)
    lo0, hi0 = host_shard_range(8, 0, 3)
    lo1, hi1 = host_shard_range(8, 1, 3)
    lo2, hi2 = host_shard_range(8, 2, 3)
    assert (lo0, hi2) == (0, 8) and hi0 == lo1 and hi1 == lo2


# ------------------------------------------------------------------- chaos
@pytest.mark.parametrize("agg", ["ell", "segment"])
def test_blocked_halo_drop_and_preempt_resume_bitwise(mesh8, tmp_path, agg):
    """The PR 8 chaos contract on the blocked-exchange path: a dropped
    halo batch AND a shard preemption mid-run, absorbed by cross-shard
    auto-resume, final state bitwise-identical to the fault-free twin."""
    from janusgraph_tpu.storage.faults import FaultPlan

    g = random_graph(n=160, m=640, seed=2)
    mk = lambda: PageRankProgram(max_iterations=12, tol=0.0)  # noqa: E731
    base = ShardedExecutor(g, mesh=mesh8, exchange="blocked", agg=agg).run(
        mk(), fused=False, checkpoint_every=3,
        shard_checkpoint_dir=str(tmp_path / f"{agg}-base"),
    )
    plan = FaultPlan(seed=5, halo_drop_at=4, shard_preempt_superstep=8)
    ex = ShardedExecutor(g, mesh=mesh8, exchange="blocked", agg=agg)
    out = ex.run(
        mk(), fused=False, checkpoint_every=3,
        shard_checkpoint_dir=str(tmp_path / f"{agg}-chaos"),
        fault_hook=plan.sharded_hook,
    )
    kinds = {e["kind"] for e in plan.journal}
    assert "halo_drop" in kinds and "shard_preempt" in kinds
    assert ex.last_run_info["resumes"] == 2
    for k in base:
        np.testing.assert_array_equal(np.asarray(base[k]), np.asarray(out[k]))


# ------------------------------------------------- measured per-shard walls
def test_measured_walls_feed_skew_report(mesh8):
    g = random_graph(n=200, m=800, seed=4)
    ex = ShardedExecutor(g, mesh=mesh8, exchange="blocked")
    ex.run(PageRankProgram(max_iterations=4, tol=0.0), fused=False)
    shards = ex.last_run_info["shards"]
    assert shards["cost_source"] == "measured"
    assert all(p["cost_source"] == "measured" for p in shards["per_shard"])
    assert all(
        p["measured_ms"] is not None and p["measured_ms"] >= 0.0
        for p in shards["per_shard"]
    )
    from janusgraph_tpu.observability import registry

    assert registry.gauge("olap.shard.skew.measured").value == 1.0

    off = ShardedExecutor(g, mesh=mesh8, shard_measure=False)
    off.run(PageRankProgram(max_iterations=4, tol=0.0), fused=False)
    shards = off.last_run_info["shards"]
    assert shards["cost_source"] == "plan"
    assert all(p["measured_ms"] is None for p in shards["per_shard"])
    assert registry.gauge("olap.shard.skew.measured").value == 0.0


def test_exchange_info_recorded(mesh8):
    g = random_graph(n=150, m=600, seed=6)
    ex = ShardedExecutor(g, mesh=mesh8, exchange="blocked")
    ex.run(PageRankProgram(max_iterations=3, tol=0.0), fused=False)
    info = ex.last_run_info["exchange"]
    assert info["mode"] == "blocked"
    assert info["batches_per_superstep"] == 1
    assert info["elems_per_superstep"] == 8 * ex._sharded(False).halo_cap
    assert info["bytes_per_superstep"] == info["elems_per_superstep"] * 4
    # pow2 tier contract (JG301 family)
    hc = info["width"]
    assert hc > 0 and (hc & (hc - 1)) == 0


# ----------------------------------------------------------------- autotune
def test_decide_sharded_deterministic_and_keyed_by_shard_count(tmp_path):
    from janusgraph_tpu.olap import autotune

    g = random_graph(n=240, m=1100, seed=8, weights=True)
    sc = ShardedCSR(g, 8, False)
    src, dst, _w = halo.edges_from_sharded(sc)
    widths = halo.pair_widths(src, dst, 8, sc.shard_size)
    stats = autotune.GraphStats.from_csr(g)
    d1 = autotune.decide_sharded(stats, "cpu", 8, widths)
    d2 = autotune.decide_sharded(stats, "cpu", 8, widths)
    assert d1.as_dict() == d2.as_dict()
    assert d1.shard_count == 8
    assert set(d1.modeled_ms) == {
        "a2a-ell", "a2a-segment", "blocked-ell", "blocked-segment",
        "ring-segment", "gather-segment",
    }
    # forcing via overrides pins the layout and flips the source label
    df = autotune.decide_sharded(
        stats, "cpu", 8, widths, overrides={"exchange": "blocked"}
    )
    assert (df.exchange, df.source) == ("blocked", "config")

    # persistence: the sharded record carries the layout and stays keyed
    # by shard count (an 8-chip record must not leak into 4-chip reads)
    path = str(tmp_path / "a.autotune.json")
    autotune.save_measured(
        path,
        {"strategy": "sharded-blocked-ell", "pad_ratio": 1.1,
         "superstep_ms": 2.5, "roofline_by_tier": None,
         "exchange": "blocked", "agg": "ell", "halo_cap": 64},
        shard_count=8,
    )
    rec = autotune.load_measured(path, shard_count=8)
    assert rec["exchange"] == "blocked" and rec["halo_cap"] == 64
    assert autotune.load_measured(path, shard_count=4) is None
    dm = autotune.decide_sharded(stats, "cpu", 8, widths, measured=rec)
    assert dm.source == "measured+model"


def test_auto_exchange_resolves_and_records(mesh8):
    g = random_graph(n=200, m=900, seed=12)
    ex = ShardedExecutor(g, mesh=mesh8, exchange="auto")
    ex.run(PageRankProgram(max_iterations=3, tol=0.0), fused=False)
    assert ex.exchange in ("a2a", "blocked", "ring", "gather")
    rec = ex.last_run_info["autotune"]
    assert rec["shard_count"] == 8
    assert rec["exchange"] == ex.exchange and rec["agg"] == ex.agg
    # deterministic: a fresh executor resolves identically
    ex2 = ShardedExecutor(g, mesh=mesh8, exchange="auto")
    ex2.run(PageRankProgram(max_iterations=3, tol=0.0), fused=False)
    assert (ex2.exchange, ex2.agg) == (ex.exchange, ex.agg)


# ------------------------------------------------------------------ routing
def test_sharded_auto_routing_records_run_info():
    from janusgraph_tpu.core import gods
    from janusgraph_tpu.core.graph import open_graph

    g = open_graph({"ids.authority-wait-ms": 0.0})
    try:
        gods.load(g)
        res = g.compute().program(
            PageRankProgram(max_iterations=6)
        ).submit()
        routing = res.run_info["routing"]
        assert routing["requested"] == "tpu"
        assert routing["routed"] == "sharded"
        assert "mesh of 8" in routing["reason"]
        assert res.run_info["exchange"]["batches_per_superstep"] == 1
        assert abs(res.states["rank"].sum() - 1.0) < 1e-4
    finally:
        g.close()


def test_sharded_auto_off_keeps_single_device():
    from janusgraph_tpu.core import gods
    from janusgraph_tpu.core.graph import open_graph

    g = open_graph({
        "ids.authority-wait-ms": 0.0, "computer.sharded-auto": False,
    })
    try:
        gods.load(g)
        res = g.compute().program(
            PageRankProgram(max_iterations=6)
        ).submit()
        assert res.run_info["routing"]["routed"] == "tpu"
    finally:
        g.close()


def test_sddmm_program_not_routed():
    """Attention (sddmm) dense programs stay on the single-device
    executor — the halo exchange cannot ship dst features."""
    from janusgraph_tpu.core import gods
    from janusgraph_tpu.core.graph import open_graph

    g = open_graph({"ids.authority-wait-ms": 0.0})
    try:
        gods.load(g)
        res = g.compute().program(GCNForwardProgram(
            feature_dim=8, hidden_dim=8, out_dim=8, attention=True,
        )).submit()
        routing = res.run_info["routing"]
        assert routing["routed"] == "tpu"
        assert routing["reason"] == "sddmm program"
    finally:
        g.close()
