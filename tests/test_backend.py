"""Backend orchestration tests.

Reference models: IDAuthorityTest.java:510 (concurrent allocators against
one shared store, in-process), KCVSCacheTest (hit/expiry/invalidation),
scan framework behavior (StandardScannerExecutor), BackendTransaction
mutation buffering.
"""

import threading

import pytest

from janusgraph_tpu.storage.backend import Backend
from janusgraph_tpu.storage.cache import ExpirationCacheStore
from janusgraph_tpu.storage.idauthority import ConsistentKeyIDAuthority, StandardIDPool
from janusgraph_tpu.storage.inmemory import InMemoryStoreManager
from janusgraph_tpu.storage.kcvs import KeySliceQuery, SliceQuery
from janusgraph_tpu.storage.scan import ScanJob, StandardScanner


# --------------------------------------------------------------- id authority
def test_id_blocks_disjoint_sequential(store_manager):
    store = store_manager.open_database("janusgraph_ids")
    tx = store_manager.begin_transaction()
    auth = ConsistentKeyIDAuthority(store, tx, block_size=100)
    blocks = [auth.get_id_block(0, 0) for _ in range(5)]
    ranges = [(b.start, b.start + b.size) for b in blocks]
    for i, (s, e) in enumerate(ranges):
        assert s < e
        for s2, e2 in ranges[i + 1 :]:
            assert e <= s2 or e2 <= s  # disjoint


def test_id_blocks_disjoint_concurrent_authorities(store_manager):
    """Multiple authorities (simulating separate graph instances) against one
    shared store must hand out globally disjoint blocks — the reference's
    IDAuthorityTest scenario."""
    store = store_manager.open_database("janusgraph_ids")
    tx = store_manager.begin_transaction()
    n_threads, blocks_per_thread = 6, 8
    out = []
    lock = threading.Lock()

    def worker(i):
        auth = ConsistentKeyIDAuthority(
            store, tx, block_size=50, uid=bytes([i]) * 16, max_retries=200
        )
        got = [auth.get_id_block(0, 3) for _ in range(blocks_per_thread)]
        with lock:
            out.extend(got)

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(n_threads)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert len(out) == n_threads * blocks_per_thread
    ids = set()
    for b in out:
        rng = set(range(b.start, b.start + b.size))
        assert not (ids & rng), "overlapping id blocks allocated"
        ids |= rng


def test_id_pool_unique_and_prefetching(store_manager):
    store = store_manager.open_database("janusgraph_ids")
    tx = store_manager.begin_transaction()
    auth = ConsistentKeyIDAuthority(store, tx, block_size=40)
    pool = StandardIDPool(auth, 0, 1)
    seen = set()
    lock = threading.Lock()

    def worker():
        local = [pool.next_id() for _ in range(100)]
        with lock:
            seen.update(local)

    ts = [threading.Thread(target=worker) for _ in range(4)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert len(seen) == 400  # all unique across threads


def test_id_namespaces_independent(store_manager):
    store = store_manager.open_database("janusgraph_ids")
    tx = store_manager.begin_transaction()
    auth = ConsistentKeyIDAuthority(store, tx, block_size=10)
    b_vertex = auth.get_id_block(ConsistentKeyIDAuthority.NS_VERTEX, 0)
    b_rel = auth.get_id_block(ConsistentKeyIDAuthority.NS_RELATION, 0)
    assert b_vertex.start == b_rel.start == 1  # separate counters


# --------------------------------------------------------------------- cache
def test_cache_hit_and_invalidation(store_manager):
    raw = store_manager.open_database("c")
    tx = store_manager.begin_transaction()
    cached = ExpirationCacheStore(raw, max_entries=10)
    raw.mutate(b"k", [(b"c1", b"v1")], [], tx)

    q = KeySliceQuery(b"k", SliceQuery())
    assert cached.get_slice(q, tx) == [(b"c1", b"v1")]
    assert cached.get_slice(q, tx) == [(b"c1", b"v1")]
    assert cached.metrics.hits == 1 and cached.metrics.misses == 1

    cached.mutate(b"k", [(b"c2", b"v2")], [], tx)  # write-through invalidates
    assert cached.get_slice(q, tx) == [(b"c1", b"v1"), (b"c2", b"v2")]
    assert cached.metrics.misses == 2


def test_cache_lru_eviction(store_manager):
    raw = store_manager.open_database("c")
    tx = store_manager.begin_transaction()
    cached = ExpirationCacheStore(raw, max_entries=3)
    for i in range(5):
        raw.mutate(b"k%d" % i, [(b"c", b"v")], [], tx)
        cached.get_slice(KeySliceQuery(b"k%d" % i, SliceQuery()), tx)
    assert len(cached._cache) == 3


def test_cache_result_isolated_from_caller_mutation(store_manager):
    raw = store_manager.open_database("c")
    tx = store_manager.begin_transaction()
    cached = ExpirationCacheStore(raw)
    raw.mutate(b"k", [(b"c1", b"v1")], [], tx)
    q = KeySliceQuery(b"k", SliceQuery())
    res = cached.get_slice(q, tx)
    res.append((b"zz", b"junk"))  # caller mutates its copy
    assert cached.get_slice(q, tx) == [(b"c1", b"v1")]


# ---------------------------------------------------------------------- scan
class CountingJob(ScanJob):
    def __init__(self, primary):
        self.primary = primary
        self.rows = []
        self.lock = threading.Lock()
        self.setup_called = self.teardown_called = False

    def get_queries(self):
        return [self.primary]

    def setup(self, metrics):
        self.setup_called = True

    def process(self, rows, metrics):
        with self.lock:
            self.rows.extend(rows)

    def teardown(self, metrics):
        self.teardown_called = True


def test_scan_all_rows(store_manager):
    store = store_manager.open_database("s")
    tx = store_manager.begin_transaction()
    for i in range(100):
        store.mutate(i.to_bytes(4, "big"), [(b"c", b"v%d" % i)], [], tx)
    job = CountingJob(SliceQuery())
    metrics = StandardScanner(store, tx).execute(job, batch_size=7)
    assert metrics.rows_processed == 100
    assert len(job.rows) == 100
    assert job.setup_called and job.teardown_called


def test_scan_partitioned_ranges_parallel(store_manager):
    store = store_manager.open_database("s")
    tx = store_manager.begin_transaction()
    for i in range(64):
        store.mutate(bytes([i]) + b"x", [(b"c", b"v")], [], tx)
    ranges = [(bytes([lo]), bytes([lo + 16])) for lo in range(0, 64, 16)]
    job = CountingJob(SliceQuery())
    metrics = StandardScanner(
        store, tx, ordered_scan=store_manager.features.ordered_scan
    ).execute(job, key_ranges=ranges, num_workers=4, batch_size=5)
    assert metrics.rows_processed == 64
    assert sorted(k for k, _ in job.rows) == sorted(bytes([i]) + b"x" for i in range(64))


def test_scan_skips_rows_without_primary(store_manager):
    store = store_manager.open_database("s")
    tx = store_manager.begin_transaction()
    store.mutate(b"a", [(b"\x01", b"v")], [], tx)
    store.mutate(b"b", [(b"\x99", b"v")], [], tx)
    job = CountingJob(SliceQuery(b"\x00", b"\x50"))
    StandardScanner(store, tx).execute(job)
    assert [k for k, _ in job.rows] == [b"a"]


# ------------------------------------------------------------------- backend
def test_backend_transaction_buffers_until_commit():
    backend = Backend(InMemoryStoreManager())
    tx = backend.begin_transaction()
    tx.mutate_edges(b"k1", [(b"c", b"v")], [])
    # not visible before commit
    assert backend.edgestore.get_slice(
        KeySliceQuery(b"k1", SliceQuery()), tx.store_tx
    ) == []
    tx.commit()
    tx2 = backend.begin_transaction()
    assert tx2.edge_store_query(KeySliceQuery(b"k1", SliceQuery())) == [(b"c", b"v")]


def test_backend_commit_invalidates_cache():
    backend = Backend(InMemoryStoreManager())
    tx = backend.begin_transaction()
    q = KeySliceQuery(b"k1", SliceQuery())
    assert tx.edge_store_query(q) == []  # caches the empty result
    tx.mutate_edges(b"k1", [(b"c", b"v")], [])
    tx.commit()
    tx2 = backend.begin_transaction()
    assert tx2.edge_store_query(q) == [(b"c", b"v")]


def test_backend_rollback_discards():
    backend = Backend(InMemoryStoreManager())
    tx = backend.begin_transaction()
    tx.mutate_edges(b"k1", [(b"c", b"v")], [])
    tx.rollback()
    tx2 = backend.begin_transaction()
    assert tx2.edge_store_query(KeySliceQuery(b"k1", SliceQuery())) == []


def test_backend_merge_order_within_tx():
    backend = Backend(InMemoryStoreManager())
    tx = backend.begin_transaction()
    tx.mutate_edges(b"k", [(b"c", b"v1")], [])
    tx.mutate_edges(b"k", [], [b"c"])  # later delete cancels earlier add
    tx.commit()
    tx2 = backend.begin_transaction()
    assert tx2.edge_store_query(KeySliceQuery(b"k", SliceQuery())) == []


def test_global_config_roundtrip():
    backend = Backend(InMemoryStoreManager())
    assert backend.get_global_config("cluster.id") is None
    backend.set_global_config("cluster.id", b"abc")
    assert backend.get_global_config("cluster.id") == b"abc"
