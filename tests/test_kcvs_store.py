"""KCVS contract suite — port of the reference's store-contract tests
(reference: janusgraph-backend-testutils .../diskstorage/KeyColumnValueStoreTest.java:
slice semantics, ordering, limits, deletions, getKeys, concurrency;
MultiWriteKeyColumnValueStoreTest.java: batched mutateMany).

Runs against the `store_manager` fixture so any backend can be substituted.
"""

import threading

import pytest

from janusgraph_tpu.storage.kcvs import (
    KCVMutation,
    KeyRangeQuery,
    KeySliceQuery,
    SliceQuery,
    entries_in_slice,
)


def col(i: int) -> bytes:
    return i.to_bytes(4, "big")


def key(i: int) -> bytes:
    return b"k" + i.to_bytes(4, "big")


@pytest.fixture
def store(store_manager):
    return store_manager.open_database("teststore")


@pytest.fixture
def tx(store_manager):
    return store_manager.begin_transaction()


def load(store, tx, nkeys=10, ncols=20):
    for k in range(nkeys):
        adds = [(col(c), b"v%d-%d" % (k, c)) for c in range(ncols)]
        store.mutate(key(k), adds, [], tx)


def test_slice_ordering_and_bounds(store, tx):
    load(store, tx)
    res = store.get_slice(KeySliceQuery(key(3), SliceQuery(col(5), col(15))), tx)
    assert [c for c, _ in res] == [col(i) for i in range(5, 15)]
    assert res[0][1] == b"v3-5"
    # ascending order guaranteed
    assert res == sorted(res)


def test_slice_limit(store, tx):
    load(store, tx)
    res = store.get_slice(
        KeySliceQuery(key(1), SliceQuery(col(0), col(20), limit=7)), tx
    )
    assert len(res) == 7
    assert res[-1][0] == col(6)


def test_slice_empty_row(store, tx):
    assert store.get_slice(KeySliceQuery(b"nope", SliceQuery()), tx) == []


def test_mutate_overwrites_and_deletes(store, tx):
    store.mutate(key(0), [(col(1), b"a"), (col(2), b"b")], [], tx)
    store.mutate(key(0), [(col(1), b"a2")], [col(2)], tx)
    res = store.get_slice(KeySliceQuery(key(0), SliceQuery()), tx)
    assert res == [(col(1), b"a2")]


def test_addition_wins_over_deletion_same_call(store, tx):
    # Matches reference semantics: within one mutate(), additions shadow
    # deletions of the same column.
    store.mutate(key(0), [(col(1), b"new")], [col(1)], tx)
    res = store.get_slice(KeySliceQuery(key(0), SliceQuery()), tx)
    assert res == [(col(1), b"new")]


def test_row_removed_when_empty(store, tx):
    store.mutate(key(0), [(col(1), b"a")], [], tx)
    store.mutate(key(0), [], [col(1)], tx)
    assert list(store.get_keys(SliceQuery(), tx)) == []


def test_get_slice_multi(store, tx):
    load(store, tx, nkeys=5, ncols=5)
    res = store.get_slice_multi([key(0), key(3), key(9)], SliceQuery(), tx)
    assert len(res[key(0)]) == 5
    assert len(res[key(3)]) == 5
    assert res[key(9)] == []


def test_get_keys_ordered(store_manager, store, tx):
    load(store, tx, nkeys=8, ncols=2)
    rows = list(store.get_keys(SliceQuery(), tx))
    if not store_manager.features.ordered_scan:
        assert sorted(k for k, _ in rows) == [key(i) for i in range(8)]
        pytest.skip("backend has unordered scans only (CQL-analogue)")
    assert [k for k, _ in rows] == [key(i) for i in range(8)]
    # range scan
    rows = list(store.get_keys(KeyRangeQuery(key(2), key(5), SliceQuery()), tx))
    assert [k for k, _ in rows] == [key(2), key(3), key(4)]


def test_get_keys_skips_rows_outside_slice(store, tx):
    store.mutate(key(0), [(col(1), b"a")], [], tx)
    store.mutate(key(1), [(col(99), b"b")], [], tx)
    rows = list(store.get_keys(SliceQuery(col(0), col(50)), tx))
    assert [k for k, _ in rows] == [key(0)]


def test_mutate_many_across_stores(store_manager):
    tx = store_manager.begin_transaction()
    muts = {
        "s1": {key(0): KCVMutation(additions=[(col(1), b"x")])},
        "s2": {key(0): KCVMutation(additions=[(col(2), b"y")])},
    }
    store_manager.mutate_many(muts, tx)
    s1 = store_manager.open_database("s1")
    s2 = store_manager.open_database("s2")
    assert s1.get_slice(KeySliceQuery(key(0), SliceQuery()), tx) == [(col(1), b"x")]
    assert s2.get_slice(KeySliceQuery(key(0), SliceQuery()), tx) == [(col(2), b"y")]


def test_snapshot_read_during_write(store, tx):
    """Readers must see a consistent row while a writer mutates (the
    copy-on-write swap guarantee)."""
    load(store, tx, nkeys=1, ncols=100)
    errors = []
    stop = threading.Event()

    def reader():
        while not stop.is_set():
            res = store.get_slice(KeySliceQuery(key(0), SliceQuery()), tx)
            cols = [c for c, _ in res]
            if cols != sorted(cols):
                errors.append("unsorted snapshot")

    t = threading.Thread(target=reader)
    t.start()
    for i in range(200):
        store.mutate(key(0), [(col(i % 100), b"w%d" % i)], [col((i * 7) % 100)], tx)
    stop.set()
    t.join()
    assert not errors


def test_concurrent_writers_distinct_keys(store, tx):
    def writer(base):
        for i in range(50):
            store.mutate(key(base * 100 + i), [(col(i), b"v")], [], tx)

    ts = [threading.Thread(target=writer, args=(b,)) for b in range(4)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert sum(1 for _ in store.get_keys(SliceQuery(), tx)) == 200


def test_entries_in_slice_helper():
    entries = [(col(i), b"v") for i in range(10)]
    q = SliceQuery(col(2), col(7), limit=3)
    assert entries_in_slice(entries, q) == [(col(i), b"v") for i in (2, 3, 4)]


def test_clear_storage(store_manager):
    tx = store_manager.begin_transaction()
    s = store_manager.open_database("x")
    s.mutate(b"k", [(b"c", b"v")], [], tx)
    store_manager.clear_storage()
    s2 = store_manager.open_database("x")
    assert list(s2.get_keys(SliceQuery(), tx)) == []
