"""Round-4 config vocabulary: each new option drives observable behavior
(reference: GraphDatabaseConfiguration.java registry; VERDICT r3 #8 'no
dead knobs')."""

import os

import numpy as np
import pytest

from janusgraph_tpu.core.graph import open_graph
from janusgraph_tpu.exceptions import ConfigurationError
from janusgraph_tpu.core.traversal import QueryError


def test_force_index_refuses_full_scan():
    g = open_graph({"schema.default": "auto", "query.force-index": True})
    tx = g.new_transaction()
    tx.add_vertex(name="a")
    tx.commit()
    with pytest.raises(QueryError, match="force-index"):
        g.traversal().V().to_list()
    g.close()


def test_index_result_cap_clamps():
    g = open_graph({
        "schema.default": "auto",
        "index.search.max-result-set-size": 3,
    })
    mgmt = g.management()
    mgmt.make_property_key("score", float)
    mgmt.build_mixed_index("scores", ["score"], backing="search")
    tx = g.new_transaction()
    for i in range(10):
        tx.add_vertex(score=float(i))
    tx.commit()
    from janusgraph_tpu.core.traversal import P

    hits = g.traversal().V().has("score", P.gte(0.0)).to_list()
    assert len(hits) == 3  # capped by index.search.max-result-set-size
    g.close()


def test_edgestore_cache_fraction():
    from janusgraph_tpu.storage.cache import ExpirationCacheStore

    g = open_graph({
        "cache.db-cache-size": 1000, "cache.edgestore-fraction": 0.6,
    })
    es, isx = g.backend.edgestore, g.backend.indexstore
    assert isinstance(es, ExpirationCacheStore)
    assert es._max == 600 and isx._max == 400
    g.close()


def test_backoff_per_client():
    """storage.backoff-* rides the remote CLIENT, not process globals —
    two graphs in one process keep their own tuning."""
    from janusgraph_tpu.storage.inmemory import InMemoryStoreManager
    from janusgraph_tpu.storage.remote import (
        RemoteStoreManager,
        RemoteStoreServer,
    )

    srv = RemoteStoreServer(InMemoryStoreManager()).start()
    host, port = srv.address
    g = open_graph({
        "storage.backend": "remote",
        "storage.hostname": host,
        "storage.port": port,
        "storage.backoff-base-ms": 5.0,
        "storage.backoff-max-ms": 100.0,
    })
    sm = g.backend.manager
    assert isinstance(sm, RemoteStoreManager)
    assert sm.backoff_base_s == 0.005 and sm.backoff_max_s == 0.1
    other = RemoteStoreManager(host, port)
    assert other.backoff_base_s is None  # untouched by g's settings
    g.close()
    srv.stop()


def test_replace_instance_if_exists():
    from janusgraph_tpu.storage.inmemory import InMemoryStoreManager

    sm = InMemoryStoreManager()
    g1 = open_graph({"graph.unique-instance-id": "node-a"}, store_manager=sm)
    # same id, same backend: refused by default...
    with pytest.raises(ConfigurationError, match="already registered"):
        open_graph({"graph.unique-instance-id": "node-a"}, store_manager=sm)
    # ...allowed with replace-instance-if-exists
    g2 = open_graph(
        {
            "graph.unique-instance-id": "node-a",
            "graph.replace-instance-if-exists": True,
        },
        store_manager=sm,
    )
    g2.close()
    g1.close()


def test_tx_metrics_group():
    from janusgraph_tpu.util.metrics import metrics

    metrics.reset()
    g = open_graph({"schema.default": "auto", "metrics.enabled": True})
    tx = g.new_transaction(metrics_group="ingest")
    tx.add_vertex(name="x")
    tx.commit()
    assert metrics.get_count("ingest.commit") == 1
    g.close()
    metrics.reset()


def test_periodic_csv_reporter(tmp_path):
    import time

    from janusgraph_tpu.util.metrics import metrics

    metrics.reset()
    g = open_graph({
        "schema.default": "auto",
        "metrics.enabled": True,
        "metrics.csv-interval-ms": 50.0,
        "metrics.csv-directory": str(tmp_path / "m"),
        "metrics.prefix": "jgt",
    })
    tx = g.new_transaction(metrics_group="load")
    tx.add_vertex(name="y")
    tx.commit()
    time.sleep(0.15)
    g.close()  # final flush
    files = os.listdir(tmp_path / "m")
    assert any("jgt.load.commit" in f for f in files)
    assert all(os.sep not in f for f in files)
    content = open(tmp_path / "m" / sorted(files)[0]).read()
    assert content.startswith("t,")
    metrics.reset()


def test_console_reporter_sink():
    from janusgraph_tpu.util.metrics import (
        MetricManager,
        PeriodicReporter,
    )

    mm = MetricManager()
    mm.counter("ops").inc(5)
    out = []
    rep = PeriodicReporter(mm, 10.0, "console", sink=out.append)
    rep.flush()
    assert out and "ops" in out[0]


def test_query_batch_size_chunks():
    calls = []
    g = open_graph({"schema.default": "auto", "query.batch-size": 2})
    tx = g.new_transaction()
    hub = tx.add_vertex(name="hub")
    for i in range(5):
        v = tx.add_vertex(name=f"v{i}")
        tx.add_edge(hub, "knows", v)
    tx.commit()
    tx2 = g.new_transaction()
    real = tx2.backend_tx.edge_store_multi_query

    def spy(keys, q):
        calls.append(len(keys))
        return real(keys, q)

    tx2.backend_tx.edge_store_multi_query = spy
    vs = [tx2.get_vertex(v.id) for v in g.traversal().V().to_list()]
    from janusgraph_tpu.core.codecs import Direction

    tx2.prefetch(vs, Direction.OUT, ())
    assert calls and max(calls) <= 2  # chunked at query.batch-size
    g.close()


def test_log_ttl_requires_capable_backend():
    # inmemory advertises cell TTL; ttl-wrapped logs open fine
    g = open_graph({"log.ttl-seconds": 60.0})
    log = g.log_manager.open_log("ulog_test")
    from janusgraph_tpu.storage.ttl import TTLKCVStore

    assert isinstance(log.store, TTLKCVStore)
    g.close()


def test_computer_frontier_off_via_config():
    from janusgraph_tpu.core import gods

    g = open_graph({"computer.frontier": "off", "computer.executor": "cpu"})
    gods.load(g)
    # facade path runs with the option plumbed (cpu executor ignores it)
    res = g.compute().traverse("out").submit()
    assert float(np.asarray(res.states["count"]).sum()) > 0
    g.close()
