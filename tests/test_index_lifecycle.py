"""Index lifecycle + OLAP maintenance job tests (reference:
ManagementSystem SchemaAction handling, IndexRepairJob/IndexRemoveJob,
GhostVertexRemover.java:44, GraphIndexStatusWatcher.java:102)."""

import pytest

from janusgraph_tpu.core.graph import open_graph
from janusgraph_tpu.core.management import SchemaAction
from janusgraph_tpu.core.traversal import P
from janusgraph_tpu.exceptions import SchemaViolationError


@pytest.fixture
def graph():
    g = open_graph({"schema.default": "auto"})
    yield g
    g.close()


def _seed(g, n=3):
    tx = g.new_transaction()
    vs = [tx.add_vertex(name=f"v{i}", rank=i) for i in range(n)]
    tx.commit()
    return [v.id for v in vs]


def test_disable_enable_composite(graph):
    vids = _seed(graph)
    mgmt = graph.management()
    mgmt.build_composite_index("byname", ["name"])
    tx = graph.new_transaction()
    assert graph.index_lookup(tx, "byname", ["v1"]) == [vids[1]]
    tx.rollback()

    mgmt.update_index("byname", SchemaAction.DISABLE_INDEX)
    assert graph.indexes["byname"].status == "DISABLED"
    # queries fall back to full scan and stay correct
    g = graph.traversal()
    assert len(g.V().has("name", "v1").to_list()) == 1
    # writes skip the disabled index
    tx = graph.new_transaction()
    nv = tx.add_vertex(name="v9")
    tx.commit()
    tx = graph.new_transaction()
    assert graph.index_lookup(tx, "byname", ["v9"]) == []
    tx.rollback()
    # REINDEX heals the gap and re-enables
    mgmt.update_index("byname", SchemaAction.REINDEX)
    assert graph.indexes["byname"].status == "ENABLED"
    tx = graph.new_transaction()
    assert graph.index_lookup(tx, "byname", ["v9"]) == [nv.id]
    tx.rollback()


def test_invalid_transitions(graph):
    mgmt = graph.management()
    mgmt.make_property_key("p", str)
    mgmt.build_composite_index("pi", ["p"])
    with pytest.raises(SchemaViolationError):
        mgmt.update_index("pi", SchemaAction.ENABLE_INDEX)  # already ENABLED
    with pytest.raises(SchemaViolationError):
        mgmt.update_index("pi", SchemaAction.REMOVE_INDEX)  # not DISABLED
    with pytest.raises(SchemaViolationError):
        mgmt.update_index("nope", SchemaAction.DISABLE_INDEX)


def test_remove_composite_index(graph):
    vids = _seed(graph)
    mgmt = graph.management()
    mgmt.build_composite_index("byname", ["name"])
    mgmt.update_index("byname", SchemaAction.DISABLE_INDEX)
    metrics = mgmt.update_index("byname", SchemaAction.REMOVE_INDEX)
    assert metrics.custom.get("index-entries-removed", 0) >= 3
    assert "byname" not in graph.indexes
    assert mgmt.await_graph_index_status("byname", "REMOVED", timeout_s=1.0)
    # name is reusable afterwards
    mgmt.build_composite_index("byname", ["name"])
    tx = graph.new_transaction()
    assert graph.index_lookup(tx, "byname", ["v0"]) == [vids[0]]
    tx.rollback()


def test_remove_mixed_index(graph):
    _seed(graph)
    mgmt = graph.management()
    mgmt.make_property_key("bio", str)
    tx = graph.new_transaction()
    tx.add_vertex(bio="some words here")
    tx.commit()
    mgmt.build_mixed_index("bios", ["bio"], backing="search")
    g = graph.traversal()
    assert len(g.V().has("bio", P.text_contains("words")).to_list()) == 1
    mgmt.update_index("bios", SchemaAction.DISABLE_INDEX)
    mgmt.update_index("bios", SchemaAction.REMOVE_INDEX)
    assert "bios" not in graph.indexes
    from janusgraph_tpu.core.predicates import Text
    from janusgraph_tpu.indexing import IndexQuery, PredicateCondition

    provider = graph.index_providers["search"]
    q = IndexQuery(PredicateCondition("bio", Text.CONTAINS, "words"))
    assert provider.query("bios", q) == []


def test_reindex_via_scan_framework(graph):
    """build_*_index backfill runs IndexRepairJob over the partition scan."""
    vids = _seed(graph, n=10)
    mgmt = graph.management()
    rows = mgmt.reindex_count = mgmt.build_composite_index("byrank", ["rank"])
    tx = graph.new_transaction()
    for i, vid in enumerate(vids):
        assert graph.index_lookup(tx, "byrank", [i]) == [vid]
    tx.rollback()


def test_ghost_vertex_remover(graph):
    vids = _seed(graph)
    # simulate a half-deleted vertex: strip its EXISTS cell but leave
    # property cells (what a concurrent delete under eventual consistency
    # leaves behind)
    es = graph.edge_serializer
    st = graph.system_types
    from janusgraph_tpu.storage.kcvs import KeySliceQuery

    btx = graph.backend.begin_transaction()
    key = graph.idm.get_key(vids[0])
    q = es.get_type_slice(st.EXISTS, False)
    cols = [c for c, _ in btx.edge_store_query(KeySliceQuery(key, q))]
    assert cols
    btx.mutate_edges(key, [], cols)
    btx.commit()
    graph.backend.clear_caches()

    mgmt = graph.management()
    metrics = mgmt.ghost_vertex_removal()
    assert metrics.custom.get("ghost-removed") == 1
    # the whole row is gone now
    btx = graph.backend.begin_transaction()
    from janusgraph_tpu.storage.kcvs import SliceQuery

    assert btx.edge_store_query(KeySliceQuery(key, SliceQuery())) == []
    # live vertices untouched
    tx = graph.new_transaction()
    assert tx.get_vertex(vids[1]) is not None
    tx.rollback()


def test_status_watcher(graph):
    mgmt = graph.management()
    mgmt.make_property_key("w", str)
    mgmt.build_composite_index("wi", ["w"])
    assert mgmt.await_graph_index_status("wi", "ENABLED", timeout_s=1.0)
    assert not mgmt.await_graph_index_status("wi", "DISABLED", timeout_s=0.05)


def test_status_survives_reopen():
    from janusgraph_tpu.storage.inmemory import InMemoryStoreManager

    sm = InMemoryStoreManager()
    g = open_graph({"schema.default": "auto"}, store_manager=sm)
    mgmt = g.management()
    mgmt.make_property_key("k", str)
    mgmt.build_composite_index("ki", ["k"])
    mgmt.update_index("ki", SchemaAction.DISABLE_INDEX)
    g.close()
    g2 = open_graph({"schema.default": "auto"}, store_manager=sm)
    assert g2.indexes["ki"].status == "DISABLED"
    g2.close()


def test_print_schema_overview():
    """reference: ManagementSystem.printSchema formatted output."""
    from janusgraph_tpu.core import gods
    from janusgraph_tpu.core.graph import open_graph

    g = open_graph()
    gods.load(g)
    out = g.management().print_schema()
    assert "--- property keys ---" in out
    assert "name" in out and "battled" in out
    assert "sortKey=time" in out           # battled's vertex-centric index
    assert "composite" in out and "ENABLED" in out
    assert "titan" in out
    g.close()


def test_print_schema_shows_modifiers_and_relation_indexes():
    from janusgraph_tpu.core.codecs import Consistency
    from janusgraph_tpu.core.graph import open_graph

    g = open_graph()
    m = g.management()
    m.make_property_key("session", str)
    m.set_ttl("session", 60)
    m.make_property_key("time", int)
    m.make_edge_label("battled")
    m.set_consistency("battled", Consistency.FORK)
    m.build_edge_index("battled", "byTime", ["time"])
    out = m.print_schema()
    assert "ttl=60s" in out
    assert "FORK" in out
    assert "byTime" in out and "on battled [time] BOTH REGISTERED" in out
    g.close()
