"""ID scheme tests (reference model: janusgraph-test
.../graphdb/idmanagement/* — id encoding round trips, key ordering,
partition extraction, canonical partitioned-vertex ids)."""

import pytest

from janusgraph_tpu.core.ids import IDManager, VertexIDType
from janusgraph_tpu.exceptions import InvalidIDError


@pytest.fixture
def idm():
    return IDManager(partition_bits=5)


def test_roundtrip_all_types(idm):
    for t in VertexIDType:
        partition = 0 if t.is_schema else 17
        vid = idm.make_vertex_id(42, partition, t)
        assert idm.id_type(vid) is t
        assert idm.get_count(vid) == 42
        assert idm.get_partition_id(vid) == partition


def test_key_roundtrip_and_partition_locality(idm):
    vids = [
        idm.make_vertex_id(c, p)
        for p in range(idm.num_partitions)
        for c in (1, 2, 1000)
    ]
    for vid in vids:
        assert idm.get_vertex_id(idm.get_key(vid)) == vid
    # keys sort by partition first: all partition-p keys contiguous
    keyed = sorted((idm.get_key(v), idm.get_partition_id(v)) for v in vids)
    partitions = [p for _, p in keyed]
    assert partitions == sorted(partitions)


def test_partition_key_range_covers_exactly(idm):
    for p in (0, 3, idm.num_partitions - 1):
        start, end = idm.partition_key_range(p)
        inside = idm.get_key(idm.make_vertex_id(99, p))
        assert start <= inside < end
        if p + 1 < idm.num_partitions:
            outside = idm.get_key(idm.make_vertex_id(1, p + 1))
            assert not (start <= outside < end)


def test_schema_ids(idm):
    sid = idm.make_schema_id(VertexIDType.USER_PROPERTY_KEY, 7)
    assert idm.is_schema_vertex_id(sid)
    assert not idm.is_user_vertex_id(sid)
    assert idm.get_partition_id(sid) == 0
    with pytest.raises(InvalidIDError):
        idm.make_vertex_id(7, 3, VertexIDType.VERTEX_LABEL)  # schema => partition 0
    with pytest.raises(InvalidIDError):
        idm.make_schema_id(VertexIDType.NORMAL, 7)


def test_normal_vs_schema_classification(idm):
    nid = idm.make_vertex_id(5, 2)
    assert idm.is_user_vertex_id(nid)
    assert not idm.is_schema_vertex_id(nid)
    assert not idm.is_partitioned_vertex_id(nid)


def test_partitioned_vertex_canonical(idm):
    count = 11
    copies = [
        idm.make_vertex_id(count, p, VertexIDType.PARTITIONED)
        for p in range(idm.num_partitions)
    ]
    canon = {idm.get_canonical_vertex_id(v) for v in copies}
    assert len(canon) == 1
    c = canon.pop()
    assert idm.get_partition_id(c) == count % idm.num_partitions
    # copies enumerable from any copy
    assert set(idm.partitioned_vertex_copies(copies[3])) == set(copies)
    # canonical of a normal vertex is itself
    nid = idm.make_vertex_id(5, 2)
    assert idm.get_canonical_vertex_id(nid) == nid


def test_bounds_checks(idm):
    with pytest.raises(InvalidIDError):
        idm.make_vertex_id(0, 0)
    with pytest.raises(InvalidIDError):
        idm.make_vertex_id(1, idm.num_partitions)
    with pytest.raises(InvalidIDError):
        idm.make_vertex_id(idm.max_count(VertexIDType.NORMAL) + 1, 0)
    big = idm.make_vertex_id(idm.max_count(VertexIDType.NORMAL), 0)
    assert big < (1 << 63)


def test_temporary_ids(idm):
    assert idm.is_temporary(-5)
    assert not idm.is_temporary(5)


def test_zero_partition_bits():
    idm = IDManager(partition_bits=0)
    vid = idm.make_vertex_id(3, 0)
    assert idm.get_partition_id(vid) == 0
    assert idm.get_vertex_id(idm.get_key(vid)) == vid


# ------------------------------------------------------- conflict avoidance
def test_conflict_avoidance_tagged_blocks_disjoint():
    """ConflictAvoidanceMode (reference: ConflictAvoidanceMode.java:76):
    tagged authorities never contend on a claim key and their blocks stripe
    the id space disjointly."""
    from janusgraph_tpu.storage.idauthority import (
        ConflictAvoidanceMode,
        ConsistentKeyIDAuthority,
    )
    from janusgraph_tpu.storage.inmemory import InMemoryStoreManager

    mgr = InMemoryStoreManager()
    store = mgr.open_database("janusgraph_ids")
    txh = mgr.begin_transaction()
    auths = [
        ConsistentKeyIDAuthority(
            store, txh, block_size=100, wait_ms=0.0,
            conflict_mode=ConflictAvoidanceMode.LOCAL_MANUAL,
            conflict_tag=t, conflict_tag_bits=2,
        )
        for t in (0, 1, 3)
    ]
    ranges = []
    for a in auths:
        for _ in range(3):
            blk = a.get_id_block(0, 0)
            ranges.append(range(blk.start, blk.start + blk.size))
    ids = [i for r in ranges for i in r]
    assert len(ids) == len(set(ids)), "tagged blocks overlap"

    with pytest.raises(ValueError, match="outside"):
        ConsistentKeyIDAuthority(
            store, txh, conflict_mode=ConflictAvoidanceMode.LOCAL_MANUAL,
            conflict_tag=4, conflict_tag_bits=2,
        )


def test_conflict_avoidance_config_wires_through():
    from janusgraph_tpu.core.graph import open_graph
    from janusgraph_tpu.storage.idauthority import ConflictAvoidanceMode

    g = open_graph({
        "storage.backend": "inmemory",
        "ids.authority.conflict-avoidance-mode": "global_auto",
        "ids.authority.conflict-avoidance-tag-bits": 3,
    })
    auth = g.backend.id_authority
    assert auth.conflict_mode is ConflictAvoidanceMode.GLOBAL_AUTO
    assert auth.num_tags == 8 and 0 <= auth.tag < 8
    tx = g.new_transaction()
    v = tx.add_vertex()
    tx.commit()
    tx2 = g.new_transaction()
    assert tx2.get_vertex(v.id) is not None  # striped ids resolve back
    tx2.rollback()
    g.close()
