"""Adapter-specific suites: persistent local store (WAL recovery, compaction
— reference: janusgraph-berkeleyje durability), TTL wrapper (reference:
TTLKCVSManager.java:119), sharded distributed manager (reference: CQL
token-partitioned store), and the order-preserving composite codec
(reference: OrderedKeyValueStoreAdapter.java:389)."""

import time

import numpy as np
import pytest

from janusgraph_tpu.exceptions import PermanentBackendError, TemporaryBackendError
from janusgraph_tpu.storage.inmemory import InMemoryStoreManager
from janusgraph_tpu.storage.kcvs import (
    KCVMutation,
    KeyRangeQuery,
    KeySliceQuery,
    SliceQuery,
)
from janusgraph_tpu.storage.kvstore import (
    decode_composite,
    encode_composite,
    encode_key,
)
from janusgraph_tpu.storage.localstore import LocalKVStoreManager, open_local_kcvs
from janusgraph_tpu.storage.sharded_store import ShardedStoreManager
from janusgraph_tpu.storage.ttl import TTLStoreManager


# ------------------------------------------------------------ composite codec
def test_composite_roundtrip_and_order():
    cases = [
        (b"a", b""),
        (b"a", b"\x00col"),
        (b"a\x00b", b"c"),
        (b"a\x00", b""),
        (b"", b"col"),
        (b"a\xff", b"z"),
    ]
    for k, c in cases:
        assert decode_composite(encode_composite(k, c)) == (k, c)
    # order preservation: composites of key a sort strictly between
    # composites of any smaller and larger key
    keys = sorted([b"a", b"a\x00", b"a\x00b", b"ab", b"a\xff", b"b"])
    encs = [encode_key(k) for k in keys]
    assert encs == sorted(encs)


# ------------------------------------------------------------ local store WAL
def test_local_store_survives_reopen(tmp_path):
    d = str(tmp_path / "db")
    mgr = open_local_kcvs(d, fsync=False)
    store = mgr.open_database("s")
    tx = mgr.begin_transaction()
    store.mutate(b"k1", [(b"c1", b"v1"), (b"c2", b"v2")], [], tx)
    store.mutate(b"k2", [(b"c1", b"x")], [], tx)
    store.mutate(b"k1", [], [b"c2"], tx)
    tx.commit()
    mgr.close()

    mgr2 = open_local_kcvs(d, fsync=False)
    s2 = mgr2.open_database("s")
    tx2 = mgr2.begin_transaction()
    assert s2.get_slice(KeySliceQuery(b"k1", SliceQuery()), tx2) == [(b"c1", b"v1")]
    assert s2.get_slice(KeySliceQuery(b"k2", SliceQuery()), tx2) == [(b"c1", b"x")]
    mgr2.close()


def test_local_store_compaction_preserves_data(tmp_path):
    d = str(tmp_path / "db")
    kv = LocalKVStoreManager(d, fsync=False)
    tx = kv.begin_transaction()
    s = kv.open_database("s")
    for i in range(50):
        s.insert(b"key%03d" % i, b"val%d" % i, tx)
    for i in range(0, 50, 2):
        s.delete(b"key%03d" % i, tx)
    tx.commit()
    kv.compact()
    # more writes after compaction land in the fresh WAL
    s.insert(b"zz", b"tail", tx)
    tx.commit()
    kv.close()

    kv2 = LocalKVStoreManager(d, fsync=False)
    s2 = kv2.open_database("s")
    rows = list(s2.scan(b"", None, kv2.begin_transaction()))
    assert len(rows) == 26
    assert (b"zz", b"tail") in rows
    assert all(int(k[3:]) % 2 == 1 for k, _ in rows if k != b"zz")
    kv2.close()


def test_local_store_torn_tail_record_ignored(tmp_path):
    d = str(tmp_path / "db")
    mgr = open_local_kcvs(d, fsync=False)
    store = mgr.open_database("s")
    tx = mgr.begin_transaction()
    store.mutate(b"k", [(b"c", b"v")], [], tx)
    tx.commit()
    mgr.close()
    # corrupt: append garbage (simulates a crash mid-append)
    import os

    with open(os.path.join(d, "store.wal"), "ab") as f:
        f.write(b"\x01\x02\x03garbage")
    mgr2 = open_local_kcvs(d, fsync=False)
    s2 = mgr2.open_database("s")
    assert s2.get_slice(
        KeySliceQuery(b"k", SliceQuery()), mgr2.begin_transaction()
    ) == [(b"c", b"v")]
    mgr2.close()


# ------------------------------------------------------------------- TTL
def test_ttl_expiry_and_purge():
    mgr = TTLStoreManager(InMemoryStoreManager(), default_ttl_seconds=0.05)
    s = mgr.open_database("s")
    tx = mgr.begin_transaction()
    s.mutate(b"k", [(b"c", b"v")], [], tx)
    assert s.get_slice(KeySliceQuery(b"k", SliceQuery()), tx) == [(b"c", b"v")]
    time.sleep(0.08)
    assert s.get_slice(KeySliceQuery(b"k", SliceQuery()), tx) == []
    assert list(s.get_keys(SliceQuery(), tx)) == []
    # the dead cell still occupies the wrapped store until purged
    assert s.purge_expired(tx) == 1
    assert s.purge_expired(tx) == 0
    mgr.close()


def test_ttl_zero_never_expires():
    mgr = TTLStoreManager(InMemoryStoreManager(), default_ttl_seconds=0.0)
    s = mgr.open_database("s")
    tx = mgr.begin_transaction()
    mgr.mutate_many({"s": {b"k": KCVMutation(additions=[(b"c", b"v")])}}, tx)
    assert s.get_slice(KeySliceQuery(b"k", SliceQuery()), tx) == [(b"c", b"v")]
    mgr.close()


def test_ttl_per_store_override():
    mgr = TTLStoreManager(
        InMemoryStoreManager(), default_ttl_seconds=0.0,
        per_store_ttl={"volatile": 0.01},
    )
    sv = mgr.open_database("volatile")
    sp = mgr.open_database("permanent")
    tx = mgr.begin_transaction()
    sv.mutate(b"k", [(b"c", b"v")], [], tx)
    sp.mutate(b"k", [(b"c", b"v")], [], tx)
    time.sleep(0.03)
    assert sv.get_slice(KeySliceQuery(b"k", SliceQuery()), tx) == []
    assert sp.get_slice(KeySliceQuery(b"k", SliceQuery()), tx) == [(b"c", b"v")]
    mgr.close()


# ---------------------------------------------------------------- sharded
def test_sharded_distributes_keys():
    mgr = ShardedStoreManager(num_nodes=4)
    s = mgr.open_database("s")
    tx = mgr.begin_transaction()
    for i in range(64):
        s.mutate(b"key%d" % i, [(b"c", b"v%d" % i)], [], tx)
    counts = [
        m.open_database("s").row_count() for m in mgr.nodes
    ]
    assert sum(counts) == 64
    assert all(c > 0 for c in counts)  # blake2b spreads 64 keys over 4 nodes
    # full scan sees all rows
    assert len(list(s.get_keys(SliceQuery(), tx))) == 64
    mgr.close()


def test_sharded_rejects_ordered_range_scan():
    mgr = ShardedStoreManager(num_nodes=2)
    s = mgr.open_database("s")
    tx = mgr.begin_transaction()
    with pytest.raises(PermanentBackendError):
        list(s.get_keys(KeyRangeQuery(b"a", b"z", SliceQuery()), tx))
    mgr.close()


def test_sharded_node_failure_and_heal():
    mgr = ShardedStoreManager(num_nodes=2)
    s = mgr.open_database("s")
    tx = mgr.begin_transaction()
    s.mutate(b"k1", [(b"c", b"v")], [], tx)
    down = next(
        i for i in range(2)
        if __import__("janusgraph_tpu.storage.sharded_store", fromlist=["_shard_of"])._shard_of(b"k1", 2) == i
    )
    mgr.fail_node(down)
    with pytest.raises(TemporaryBackendError):
        s.get_slice(KeySliceQuery(b"k1", SliceQuery()), tx)
    mgr.heal_node(down)
    assert s.get_slice(KeySliceQuery(b"k1", SliceQuery()), tx) == [(b"c", b"v")]
    mgr.close()


def test_sharded_mutate_many_routes_per_node():
    mgr = ShardedStoreManager(num_nodes=3)
    tx = mgr.begin_transaction()
    muts = {
        "a": {b"k%d" % i: KCVMutation(additions=[(b"c", b"v")]) for i in range(20)},
        "b": {b"q%d" % i: KCVMutation(additions=[(b"c", b"v")]) for i in range(20)},
    }
    mgr.mutate_many(muts, tx)
    sa, sb = mgr.open_database("a"), mgr.open_database("b")
    assert len(list(sa.get_keys(SliceQuery(), tx))) == 20
    assert len(list(sb.get_keys(SliceQuery(), tx))) == 20
    mgr.close()


# ------------------------------------------------- graph-level integration
def test_graph_persists_across_reopen_on_local_backend(tmp_path):
    from janusgraph_tpu.core import gods
    from janusgraph_tpu.core.graph import open_graph

    d = str(tmp_path / "graphdb")
    g = open_graph({
        "storage.backend": "local",
        "storage.directory": d,
        "ids.authority-wait-ms": 0.0,
    })
    gods.load(g)
    saturn_id = None
    tx = g.new_transaction()
    for v in tx.vertices():
        if v.value("name") == "saturn":
            saturn_id = v.id
    tx.rollback()
    g.close()

    g2 = open_graph({
        "storage.backend": "local",
        "storage.directory": d,
        "ids.authority-wait-ms": 0.0,
    })
    tx2 = g2.new_transaction()
    saturn = tx2.get_vertex(saturn_id)
    assert saturn is not None and saturn.value("name") == "saturn"
    # traversal over persisted edges
    grandchild = (
        g2.traversal().V().has("name", "saturn")
        .in_("father").in_("father").values("name").to_list()
    )
    assert grandchild == ["hercules"]
    tx2.rollback()
    g2.close()


def test_graph_olap_on_sharded_backend():
    from janusgraph_tpu.core import gods
    from janusgraph_tpu.core.graph import open_graph
    from janusgraph_tpu.olap import load_csr
    from janusgraph_tpu.olap.programs import PageRankProgram

    g = open_graph({
        "storage.backend": "sharded",
        "ids.authority-wait-ms": 0.0,
    })
    gods.load(g)
    csr = load_csr(g)  # exercises the unordered-scan fallback
    assert csr.num_vertices == 12 and csr.num_edges == 17
    res = g.compute().program(PageRankProgram(max_iterations=20)).submit()
    assert abs(sum(res.states["rank"]) - 1.0) < 1e-3
    g.close()
