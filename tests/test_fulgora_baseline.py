"""Fulgora-analogue baseline engine (olap/fulgora_baseline.py): the
reference's threaded per-vertex hash-map BSP architecture, checked for
rank parity against the vectorized CPU executor (reference:
FulgoraGraphComputer.java:210-230, FulgoraVertexMemory.java:91-99)."""

import numpy as np

from janusgraph_tpu.olap import csr_from_edges
from janusgraph_tpu.olap.cpu_executor import CPUExecutor
from janusgraph_tpu.olap.fulgora_baseline import (
    FulgoraAnalogueComputer,
    measure_fulgora_baseline,
)
from janusgraph_tpu.olap.programs import PageRankProgram


def _graph(n=300, m=1800, seed=17):
    rng = np.random.default_rng(seed)
    return csr_from_edges(
        n,
        rng.integers(0, n, m).astype(np.int32),
        rng.integers(0, n, m).astype(np.int32),
    )


def test_rank_parity_with_vectorized_executor():
    csr = _graph()
    iters = 12
    rank, _wall = FulgoraAnalogueComputer(csr, num_workers=3).pagerank(iters)
    ref = CPUExecutor(csr).run(PageRankProgram(max_iterations=iters, tol=0.0))
    np.testing.assert_allclose(rank, np.asarray(ref["rank"]), rtol=1e-6)
    assert abs(rank.sum() - 1.0) < 1e-6


def test_dangling_mass_redistributed():
    # star: all point at 0; vertex 0 is dangling
    n = 6
    src = np.arange(1, n, dtype=np.int32)
    dst = np.zeros(n - 1, dtype=np.int32)
    csr = csr_from_edges(n, src, dst)
    rank, _ = FulgoraAnalogueComputer(csr, num_workers=2).pagerank(30)
    ref = CPUExecutor(csr).run(PageRankProgram(max_iterations=30, tol=0.0))
    np.testing.assert_allclose(rank, np.asarray(ref["rank"]), rtol=1e-6)


def test_measure_shape():
    out = measure_fulgora_baseline(_graph(), iterations=2, num_workers=2)
    assert out["edges_per_sec"] > 0
    assert out["iterations"] == 2
