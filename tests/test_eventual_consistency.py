"""Eventual-consistency behavior across instances sharing one backend
(reference: JanusGraphEventualGraphTest.java:397 — without LOCK
consistency, concurrent writers both succeed and the later write wins;
cross-instance visibility is bounded by the cache TTL)."""

import time

from janusgraph_tpu.core.graph import open_graph
from janusgraph_tpu.storage.inmemory import InMemoryStoreManager


def _two_graphs(ttl_ms=60.0):
    sm = InMemoryStoreManager()
    a = open_graph(
        {"schema.default": "auto", "cache.db-cache-time-ms": ttl_ms,
         "graph.unique-instance-id": "inst-a"},
        store_manager=sm,
    )
    b = open_graph(
        {"schema.default": "auto", "cache.db-cache-time-ms": ttl_ms,
         "graph.unique-instance-id": "inst-b"},
        store_manager=sm,
    )
    return a, b


def test_unlocked_concurrent_writes_last_commit_wins():
    a, b = _two_graphs()
    tx = a.new_transaction()
    v = tx.add_vertex(name="x", score=0.0)
    tx.commit()
    vid = v.id

    # both instances read the committed state, then race updates with NO
    # LOCK consistency: both commits succeed (eventual semantics)
    ta = a.new_transaction()
    tb = b.new_transaction()
    va, vb = ta.get_vertex(vid), tb.get_vertex(vid)
    assert va.value("score") == 0.0 and vb.value("score") == 0.0
    va.property("score", 1.0)
    vb.property("score", 2.0)
    ta.commit()
    tb.commit()  # later writer: its cell lands last

    # the later commit's value is what the BACKEND holds; readers converge
    # once the bounded-staleness window passes
    time.sleep(0.12)
    for g in (a, b):
        tx = g.new_transaction()
        assert tx.get_vertex(vid).value("score") == 2.0, g.instance_id
        tx.rollback()
    a.close()
    b.close()


def test_cross_instance_visibility_bounded_by_cache_ttl():
    a, b = _two_graphs(ttl_ms=80.0)
    tx = a.new_transaction()
    v = tx.add_vertex(name="y", score=1.0)
    tx.commit()
    vid = v.id
    # warm B's store cache
    tb = b.new_transaction()
    assert tb.get_vertex(vid).value("score") == 1.0
    tb.rollback()
    # A updates; B may serve the stale cached row until the TTL lapses,
    # but NEVER past it (the staleness bound the TTL exists to enforce)
    tx = a.new_transaction()
    tx.get_vertex(vid).property("score", 5.0)
    tx.commit()
    time.sleep(0.1)
    tb = b.new_transaction()
    assert tb.get_vertex(vid).value("score") == 5.0
    tb.rollback()
    a.close()
    b.close()
