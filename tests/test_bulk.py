"""Columnar bulk load + columnar write-back: correctness and timing gates
(VERDICT r2 #4: load s18 from localstore < 30s, write back s18 < 10s).

The s18 gate is heavy (~4.2M edges); it runs when SLOW_TESTS=1 (the round's
evidence run) while the default suite exercises the same paths at s14.
"""

import os
import time

import numpy as np
import pytest

from janusgraph_tpu.core.bulk import bulk_add_edges, bulk_add_vertices
from janusgraph_tpu.core.graph import open_graph
from janusgraph_tpu.olap.csr import load_csr
from janusgraph_tpu.olap.tpu_executor import write_back


def rmat_edges(scale: int, edge_factor: int = 16, seed: int = 1):
    from janusgraph_tpu.olap.generators import rmat_edges as gen

    return gen(scale, edge_factor, seed=seed)


def _populate(graph, scale: int):
    n, src, dst = rmat_edges(scale)
    vids = bulk_add_vertices(graph, n)
    m = bulk_add_edges(graph, "link", vids[src], vids[dst])
    return vids, m


def test_bulk_load_roundtrip_small():
    g = open_graph()
    vids = bulk_add_vertices(g, 50, label="thing")
    src = np.arange(49)
    dst = np.arange(1, 50)
    bulk_add_edges(g, "next", vids[src], vids[dst])

    csr = load_csr(g)
    assert csr.num_vertices == 50
    assert csr.num_edges == 49
    # chain degree structure
    assert csr.out_degree.sum() == 49
    assert csr.out_degree.max() == 1
    # labels materialized
    thing = g.schema_cache.get_by_name("thing")
    assert set(csr.labels.tolist()) == {thing.id}
    # OLTP sees bulk vertices too
    from janusgraph_tpu.core.codecs import Direction

    tx = g.new_transaction()
    v = tx.get_vertex(int(vids[0]))
    assert v is not None and v.label == "thing"
    assert len(list(tx.get_edges(v, Direction.OUT, ()))) >= 1
    g.close()


def test_bulk_edges_visible_both_directions():
    g = open_graph()
    vids = bulk_add_vertices(g, 3)
    bulk_add_edges(g, "e", [vids[0], vids[1]], [vids[1], vids[2]])
    csr = load_csr(g)
    assert csr.num_edges == 2
    i0, i1, i2 = (csr.index_of(int(v)) for v in vids)
    assert csr.out_dst[csr.out_indptr[i0]] == i1
    assert csr.in_src[csr.in_indptr[i2]] == i1
    g.close()


def test_columnar_write_back_roundtrip():
    g = open_graph()
    vids = bulk_add_vertices(g, 40)
    bulk_add_edges(g, "e", vids[:-1], vids[1:])
    csr = load_csr(g)
    vals = np.linspace(0.0, 1.0, csr.num_vertices)
    write_back(g, csr, {"score": vals})
    tx = g.new_transaction()
    for i in (0, 17, 39):
        v = tx.get_vertex(int(csr.vertex_ids[i]))
        assert v.value("score") == pytest.approx(vals[i])
    g.close()


def test_columnar_write_back_indexed_key_falls_back():
    g = open_graph()
    mgmt = g.management()
    mgmt.make_property_key("score", float)
    mgmt.build_composite_index("by_score", ["score"])
    vids = bulk_add_vertices(g, 10)
    csr_like_ids = np.sort(vids)

    class FakeCSR:
        vertex_ids = csr_like_ids

    write_back(g, FakeCSR, {"score": np.arange(10, dtype=np.float64)})
    # index must see the values (the tx path maintains it)
    t = g.traversal()
    from janusgraph_tpu.core.traversal import P

    hits = t.V().has("score", 7.0).to_list()
    assert len(hits) == 1
    g.close()


def test_ingestion_timing_s14_default():
    """Default-suite timing gate at s14 (16k vertices, 262k edges), bounds
    scaled from the s18 targets (<30s load, <10s write-back at 16x size)."""
    g = open_graph()
    _populate(g, 14)

    t0 = time.perf_counter()
    csr = load_csr(g)
    load_s = time.perf_counter() - t0
    assert csr.num_edges > 200_000

    t0 = time.perf_counter()
    write_back(g, csr, {"rank": np.random.default_rng(0).random(csr.num_vertices)})
    wb_s = time.perf_counter() - t0

    print(f"\ns14: load_csr {load_s:.2f}s, write_back {wb_s:.2f}s")
    assert load_s < 30.0 / 8  # s14 is 1/16 of s18; allow 2x slack
    assert wb_s < 10.0 / 8
    g.close()


@pytest.mark.skipif(
    not os.environ.get("SLOW_TESTS"), reason="s18 gate: run with SLOW_TESTS=1"
)
def test_ingestion_timing_s18_gate(tmp_path):
    """The VERDICT r2 #4 'done' gate, against the persistent local store."""
    from janusgraph_tpu.storage.localstore import open_local_kcvs

    mgr = open_local_kcvs(str(tmp_path / "s18"), fsync=False)
    g = open_graph(store_manager=mgr)
    _populate(g, 18)

    t0 = time.perf_counter()
    csr = load_csr(g)
    load_s = time.perf_counter() - t0
    assert csr.num_vertices == 1 << 18

    t0 = time.perf_counter()
    write_back(g, csr, {"rank": np.random.default_rng(0).random(csr.num_vertices)})
    wb_s = time.perf_counter() - t0

    print(f"\ns18: load_csr {load_s:.2f}s, write_back {wb_s:.2f}s")
    assert load_s < 30.0, f"load_csr took {load_s:.1f}s (gate: 30s)"
    assert wb_s < 10.0, f"write_back took {wb_s:.1f}s (gate: 10s)"
    g.close()


def test_bulk_relation_ids_unique():
    """EXISTS/label/edge cells must never share relation ids (the invariant
    rel-id-keyed deletion filtering relies on)."""
    from janusgraph_tpu.core.codecs import Direction

    g = open_graph()
    vids = bulk_add_vertices(g, 20, label="n")
    bulk_add_edges(g, "e", vids[:-1], vids[1:])
    es = g.edge_serializer
    st = g.system_types
    seen = set()
    btx = g.backend.begin_transaction()
    from janusgraph_tpu.storage.kcvs import KeySliceQuery, SliceQuery

    by_rid: dict = {}
    for vid in vids:
        key = g.idm.get_key(int(vid))
        for col, val in g.backend.edgestore.get_slice(
            KeySliceQuery(key, SliceQuery(bytes([0]), bytes([4]))), btx.store_tx
        ):
            cat = col[0]
            if cat in (2, 3):  # edges: rel id = last 8 bytes of column
                rid = int.from_bytes(col[-8:], "big")
                kind = f"edge-dir{col[9]}"
            elif cat == 0 and len(val) >= 8:
                rid = int.from_bytes(val[:8], "big")
                kind = "exists"
            else:
                continue
            by_rid.setdefault(rid, []).append(kind)
    for rid, kinds in by_rid.items():
        # a user edge legitimately stores its rel id twice (OUT + IN cell);
        # anything else sharing an id is a collision
        assert kinds == ["exists"] or sorted(kinds) in (
            [f"edge-dir0"], [f"edge-dir1"],
            ["edge-dir0", "edge-dir1"],
        ), f"relation id {rid} shared by {kinds}"
    g.close()


def test_columnar_write_back_non_float_key_keeps_schema_type():
    """A pre-existing int-typed key must NOT get double-framed cells: the
    columnar path only handles float keys, everything else goes through the
    checked tx path."""
    from janusgraph_tpu.exceptions import SchemaViolationError

    g = open_graph()
    g.management().make_property_key("hops", int)
    vids = bulk_add_vertices(g, 5)

    class FakeCSR:
        vertex_ids = np.sort(vids)

    with pytest.raises(SchemaViolationError):
        write_back(g, FakeCSR, {"hops": np.arange(5, dtype=np.float64)})
    g.close()


def test_ingestion_timing_s16_localstore(tmp_path):
    """Always-on scale rung on the PERSISTENT local store (the s18 gate's
    backend at 1/4 size — CI exercises the WAL+snapshot scale path every
    run; VERDICT r4 weak #8)."""
    from janusgraph_tpu.storage.localstore import open_local_kcvs

    mgr = open_local_kcvs(str(tmp_path / "s16"), fsync=False)
    g = open_graph(store_manager=mgr)
    _populate(g, 16)

    t0 = time.perf_counter()
    csr = load_csr(g)
    load_s = time.perf_counter() - t0
    assert csr.num_vertices == 1 << 16 and csr.num_edges > 1_000_000

    t0 = time.perf_counter()
    write_back(
        g, csr, {"rank": np.random.default_rng(0).random(csr.num_vertices)}
    )
    wb_s = time.perf_counter() - t0
    print(f"\ns16/localstore: load_csr {load_s:.2f}s, write_back {wb_s:.2f}s")
    assert load_s < 30.0 / 4  # s16 is 1/4 of the s18 gate
    assert wb_s < 10.0 / 4
    g.close()
