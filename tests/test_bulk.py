"""Columnar bulk load + columnar write-back: correctness and timing gates
(VERDICT r2 #4: load s18 from localstore < 30s, write back s18 < 10s).

The s18 gate is heavy (~4.2M edges); it runs when SLOW_TESTS=1 (the round's
evidence run) while the default suite exercises the same paths at s14.
"""

import os
import time

import numpy as np
import pytest

from janusgraph_tpu.core.bulk import bulk_add_edges, bulk_add_vertices
from janusgraph_tpu.core.graph import open_graph
from janusgraph_tpu.olap.csr import load_csr
from janusgraph_tpu.olap.tpu_executor import write_back


def rmat_edges(scale: int, edge_factor: int = 16, seed: int = 1):
    from janusgraph_tpu.olap.generators import rmat_edges as gen

    return gen(scale, edge_factor, seed=seed)


def _populate(graph, scale: int):
    n, src, dst = rmat_edges(scale)
    vids = bulk_add_vertices(graph, n)
    m = bulk_add_edges(graph, "link", vids[src], vids[dst])
    return vids, m


def test_bulk_load_roundtrip_small():
    g = open_graph()
    vids = bulk_add_vertices(g, 50, label="thing")
    src = np.arange(49)
    dst = np.arange(1, 50)
    bulk_add_edges(g, "next", vids[src], vids[dst])

    csr = load_csr(g)
    assert csr.num_vertices == 50
    assert csr.num_edges == 49
    # chain degree structure
    assert csr.out_degree.sum() == 49
    assert csr.out_degree.max() == 1
    # labels materialized
    thing = g.schema_cache.get_by_name("thing")
    assert set(csr.labels.tolist()) == {thing.id}
    # OLTP sees bulk vertices too
    from janusgraph_tpu.core.codecs import Direction

    tx = g.new_transaction()
    v = tx.get_vertex(int(vids[0]))
    assert v is not None and v.label == "thing"
    assert len(list(tx.get_edges(v, Direction.OUT, ()))) >= 1
    g.close()


def test_bulk_edges_visible_both_directions():
    g = open_graph()
    vids = bulk_add_vertices(g, 3)
    bulk_add_edges(g, "e", [vids[0], vids[1]], [vids[1], vids[2]])
    csr = load_csr(g)
    assert csr.num_edges == 2
    i0, i1, i2 = (csr.index_of(int(v)) for v in vids)
    assert csr.out_dst[csr.out_indptr[i0]] == i1
    assert csr.in_src[csr.in_indptr[i2]] == i1
    g.close()


def test_columnar_write_back_roundtrip():
    g = open_graph()
    vids = bulk_add_vertices(g, 40)
    bulk_add_edges(g, "e", vids[:-1], vids[1:])
    csr = load_csr(g)
    vals = np.linspace(0.0, 1.0, csr.num_vertices)
    write_back(g, csr, {"score": vals})
    tx = g.new_transaction()
    for i in (0, 17, 39):
        v = tx.get_vertex(int(csr.vertex_ids[i]))
        assert v.value("score") == pytest.approx(vals[i])
    g.close()


def test_columnar_write_back_indexed_key_falls_back():
    g = open_graph()
    mgmt = g.management()
    mgmt.make_property_key("score", float)
    mgmt.build_composite_index("by_score", ["score"])
    vids = bulk_add_vertices(g, 10)
    csr_like_ids = np.sort(vids)

    class FakeCSR:
        vertex_ids = csr_like_ids

    write_back(g, FakeCSR, {"score": np.arange(10, dtype=np.float64)})
    # index must see the values (the tx path maintains it)
    t = g.traversal()
    from janusgraph_tpu.core.traversal import P

    hits = t.V().has("score", 7.0).to_list()
    assert len(hits) == 1
    g.close()


@pytest.mark.parametrize("scale,backend,divisor,slow", [
    (14, "inmemory", 8, False),     # 1/16 of s18; 2x slack
    (16, "localstore", 2, False),   # 1/4 of s18; 2x slack — the WAL+
                                    # snapshot scale path runs EVERY CI run
    (18, "localstore", 1, True),    # the VERDICT r2 #4 'done' gate
])
def test_ingestion_timing(tmp_path, scale, backend, divisor, slow):
    """One parametrized populate->load_csr->write_back timing gate, bounds
    linearly scaled from the s18 targets (<30s load, <10s write-back) with
    2x slack at the smaller rungs (fixed overheads dominate there)."""
    if slow and not os.environ.get("SLOW_TESTS"):
        pytest.skip("s18 gate: run with SLOW_TESTS=1")
    if backend == "localstore":
        from janusgraph_tpu.storage.localstore import open_local_kcvs

        mgr = open_local_kcvs(str(tmp_path / f"s{scale}"), fsync=False)
        g = open_graph(store_manager=mgr)
    else:
        g = open_graph()
    _populate(g, scale)

    t0 = time.perf_counter()
    csr = load_csr(g)
    load_s = time.perf_counter() - t0
    assert csr.num_vertices == 1 << scale
    assert csr.num_edges > (1 << scale) * 12

    t0 = time.perf_counter()
    write_back(
        g, csr, {"rank": np.random.default_rng(0).random(csr.num_vertices)}
    )
    wb_s = time.perf_counter() - t0
    print(f"\ns{scale}/{backend}: load_csr {load_s:.2f}s, "
          f"write_back {wb_s:.2f}s")
    assert load_s < 30.0 / divisor, f"load {load_s:.1f}s vs {30/divisor}s"
    assert wb_s < 10.0 / divisor, f"write_back {wb_s:.1f}s vs {10/divisor}s"
    g.close()
