"""ISSUE 4: distributed trace propagation + black-box flight recorder.

Unit surface: TraceContext codecs, span trace identity, the flight
recorder ring/dump, structured-log trace correlation. Acceptance
surface: a seeded chaos plan produces a flight dump holding the injected
fault, breaker transition, and recovery events in timestamp order, with
the same seed reproducing the same event sequence (wall-clock fields
masked); /healthz embeds the flight block and the ok->degraded flip
triggers a dump; OLAP runs carry compile-cache and device-memory depth.
"""

import json
import os

import pytest

from janusgraph_tpu.observability import (
    TraceContext,
    flight_recorder,
    get_logger,
    registry,
    tracer,
)
from janusgraph_tpu.observability import logging as slog


@pytest.fixture(autouse=True)
def _reset():
    registry.reset()
    tracer.reset()
    flight_recorder.reset()
    slog.reset()
    slog.configure(stream=None)
    yield
    registry.reset()
    tracer.reset()
    flight_recorder.reset()
    slog.reset()
    slog.configure(stream=None)
    tracer.configure(slow_threshold_ms=100.0, max_roots=256, slow_buffer=128)


# ------------------------------------------------------------- trace context
def test_trace_context_binary_roundtrip():
    ctx = TraceContext(0x1234ABCD5678EF01, 0x42, sampled=True)
    back = TraceContext.from_bytes(ctx.to_bytes())
    assert (back.trace_id, back.span_id, back.sampled) == (
        ctx.trace_id, ctx.span_id, True
    )
    unsampled = TraceContext.from_bytes(
        TraceContext(7, 9, sampled=False).to_bytes()
    )
    assert not unsampled.sampled


def test_trace_context_header_roundtrip_and_rejection():
    ctx = TraceContext(0xDEADBEEF, 0xFEED, sampled=True)
    h = ctx.to_header()
    back = TraceContext.from_header(h)
    assert back.trace_id == ctx.trace_id and back.span_id == ctx.span_id
    # malformed headers degrade to None, never raise
    for bad in ("", "zz", "01-xyz-abc-01", "99-" + h[3:], None,
                "01-0000000000000000-0000000000000001-01"):
        assert TraceContext.from_header(bad) is None
    assert TraceContext.from_bytes(b"short") is None


def test_spans_carry_and_inherit_trace_identity():
    with tracer.span("outer") as o:
        assert o.trace_id != 0 and o.span_id != 0
        with tracer.span("inner") as i:
            assert i.trace_id == o.trace_id
            assert i.span_id != o.span_id
    d = tracer.recent("outer")[-1].to_dict()
    assert d["trace_id"] == f"{o.trace_id:016x}"
    assert d["children"][0]["trace_id"] == d["trace_id"]


def test_child_span_joins_remote_parent_and_find_trace():
    with tracer.span("client") as c:
        ctx = tracer.current_context()
    with tracer.child_span(ctx, "server") as s:
        assert s.trace_id == c.trace_id
        assert s.parent_span_id == c.span_id
    trees = tracer.find_trace(f"{c.trace_id:016x}")
    assert {t.name for t in trees} == {"client", "server"}
    # child_span with no context is a plain local root
    with tracer.child_span(None, "standalone") as alone:
        assert alone.trace_id not in (0, c.trace_id)


def test_unsampled_context_suppresses_root_retention():
    ctx = TraceContext(0xABC, 0xDEF, sampled=False)
    with tracer.child_span(ctx, "quiet"):
        pass
    assert tracer.find_trace(0xABC) == []


# ----------------------------------------------------------- flight recorder
def test_flight_ring_counts_and_bound():
    flight_recorder.configure(capacity=8)
    try:
        for i in range(20):
            flight_recorder.record("fault", kind="read", n=i)
        assert flight_recorder.occupancy == 8
        assert flight_recorder.counts()["fault"] == 20
        events = flight_recorder.events()
        assert [e["n"] for e in events] == list(range(12, 20))
        seqs = [e["seq"] for e in events]
        assert seqs == sorted(seqs)
    finally:
        flight_recorder.configure(capacity=512)


def test_flight_dump_writes_ordered_json(tmp_path):
    flight_recorder.record("fault", kind="write", n=0)
    flight_recorder.record("breaker", name="b", from_state="closed",
                           to_state="open")
    path = flight_recorder.dump(
        reason="test", path=str(tmp_path / "dump.json")
    )
    assert path and os.path.exists(path)
    payload = json.loads(open(path).read())
    assert payload["reason"] == "test"
    cats = [e["category"] for e in payload["events"]]
    assert cats == ["fault", "breaker"]
    ts = [e["ts"] for e in payload["events"]]
    assert ts == sorted(ts)
    assert flight_recorder.health_block()["last_dump"] == path
    assert registry.get_count("flight.dumps") == 1


def test_slow_spans_feed_the_flight_recorder():
    tracer.configure(slow_threshold_ms=1e-6)
    with tracer.span("glacial.op"):
        pass
    events = flight_recorder.events("slow_span")
    assert events and events[-1]["name"] == "glacial.op"
    assert "trace_id" in events[-1]


# --------------------------------------------------------- structured logging
def test_structured_log_injects_ambient_trace():
    log = get_logger("test.site")
    with tracer.span("op") as sp:
        rec = log.warning("thing-happened", detail=7)
    assert rec["trace_id"] == f"{sp.trace_id:016x}"
    assert rec["span_id"] == f"{sp.span_id:016x}"
    assert rec["logger"] == "test.site" and rec["detail"] == 7
    outside = log.info("no-span")
    assert "trace_id" not in outside
    ring = slog.recent()
    assert [r["event"] for r in ring] == ["thing-happened", "no-span"]
    assert slog.recent(level="warning")[0]["event"] == "thing-happened"


def test_structured_log_stream_emission():
    import io

    buf = io.StringIO()
    slog.configure(stream=buf)
    get_logger("emit").error("boom", code=3)
    line = buf.getvalue().strip()
    rec = json.loads(line)
    assert rec["level"] == "error" and rec["event"] == "boom"
    assert rec["code"] == 3


# ------------------------------------------------ seeded chaos determinism
def _masked(events):
    """Event sequence with clock (and id-ish) fields removed — the
    deterministic projection two same-seed runs must agree on."""
    out = []
    for e in events:
        m = {k: v for k, v in e.items()
             if k not in ("ts", "mono", "seq", "trace_id", "span_id",
                          "tx_id", "message")}
        out.append(m)
    return out


def _chaos_soak(tmp_path, tag, seed=42, txs=40):
    """One seeded OLTP soak through injected faults with a torn commit,
    then reopen + torn-commit recovery (the PR 3 chaos recipe)."""
    from janusgraph_tpu.core.graph import JanusGraphTPU
    from janusgraph_tpu.exceptions import (
        InjectedCrashError,
        TemporaryBackendError,
    )
    from janusgraph_tpu.storage.inmemory import InMemoryStoreManager

    base = {
        "ids.authority-wait-ms": 0.0,
        "locks.wait-ms": 0.0,
        "tx.log-tx": True,
        "tx.max-commit-time-ms": 0.0,
        "storage.backoff-base-ms": 1.0,
        "storage.backoff-max-ms": 2.0,
        "metrics.flight-dump-dir": str(tmp_path),
    }
    chaos = {
        **base,
        "storage.faults.enabled": True,
        "storage.faults.seed": seed,
        "storage.faults.read-error-rate": 0.05,
        "storage.faults.write-error-rate": 0.05,
        "storage.faults.torn-mutation-at": txs // 2,
    }
    mgr = InMemoryStoreManager()
    graph = JanusGraphTPU(chaos, store_manager=mgr)
    mgmt = graph.management()
    mgmt.make_property_key("uid", int)
    mgmt.build_composite_index(f"byUid_{tag}", ["uid"], unique=True)

    def write(g, i):
        for attempt in range(12):
            tx = g.new_transaction()
            try:
                tx.add_vertex(uid=i)
                tx.commit()
                return
            except TemporaryBackendError:
                if tx.is_open:
                    tx.rollback()
                if attempt == 11:
                    raise

    crashed_at = None
    for i in range(txs):
        try:
            write(graph, i)
        except InjectedCrashError:
            crashed_at = i
            break
    assert crashed_at is not None, "torn commit never fired"
    # crash: reopen over the same store; recovery heals the torn tx
    graph2 = JanusGraphTPU(base, store_manager=mgr)
    assert graph2.last_torn_recovery["replayed"]
    graph2.close()


def test_seeded_chaos_flight_dump_is_ordered_and_reproducible(tmp_path):
    """Acceptance: the dump contains injected-fault, breaker-transition,
    and recovery events in timestamp order; the same seed reproduces the
    same event sequence once wall-clock fields are masked."""
    from janusgraph_tpu.exceptions import TemporaryBackendError
    from janusgraph_tpu.storage.circuit import CircuitBreaker

    def one_run(tag):
        flight_recorder.reset()
        _chaos_soak(tmp_path / tag, tag)
        # deterministic breaker episode rides the same timeline: trip it
        # open, then let a probe close it again
        br = CircuitBreaker(f"chaos-{tag}", failure_threshold=2,
                            reset_timeout_s=0.0)

        def fail():
            raise TemporaryBackendError("down")

        for _ in range(2):
            with pytest.raises(TemporaryBackendError):
                br.call(fail)
        assert br.call(lambda: "up") == "up"
        path = flight_recorder.dump(
            reason="chaos-test", path=str(tmp_path / f"{tag}.json")
        )
        return json.loads(open(path).read())["events"]

    first = one_run("a")
    cats = {e["category"] for e in first}
    assert {"fault", "breaker", "torn_recovery"} <= cats, cats
    ts = [e["ts"] for e in first]
    assert ts == sorted(ts)
    # breaker episode: open on failures, closed again by the probe
    transitions = [
        (e["from_state"], e["to_state"])
        for e in first if e["category"] == "breaker"
    ]
    assert ("closed", "open") in transitions
    assert transitions[-1][1] == "closed"

    second = one_run("b")

    def comparable(events):
        # breaker names carry the run tag; normalize before comparing
        out = []
        for e in _masked(events):
            if "name" in e and isinstance(e["name"], str):
                e = dict(e, name=e["name"].replace("chaos-a", "X")
                         .replace("chaos-b", "X"))
            out.append(e)
        return out

    assert comparable(first) == comparable(second)


# --------------------------------------------------------- healthz + server
def test_healthz_flight_block_and_degraded_dump(tmp_path):
    from janusgraph_tpu.exceptions import TemporaryBackendError
    from janusgraph_tpu.server.server import _HEALTH_STATE, healthz_snapshot
    from janusgraph_tpu.storage.circuit import CircuitBreaker

    flight_recorder.configure(dump_dir=str(tmp_path))
    try:
        _HEALTH_STATE["status"] = None
        flight_recorder.record("fault", kind="read", n=1)
        snap = healthz_snapshot()
        assert snap["status"] == "ok"
        fl = snap["flight"]
        assert fl["occupancy"] >= 1 and fl["counts"]["fault"] == 1
        assert fl["last_dump"] is None
        # trip a breaker: ok -> degraded must record + dump exactly once
        br = CircuitBreaker("healthz-flight", failure_threshold=1,
                            reset_timeout_s=60.0)
        with pytest.raises(TemporaryBackendError):
            br.call(lambda: (_ for _ in ()).throw(
                TemporaryBackendError("down")
            ))
        snap = healthz_snapshot()
        assert snap["status"] == "degraded"
        dump_path = snap["flight"]["last_dump"]
        assert dump_path and os.path.exists(dump_path)
        events = json.loads(open(dump_path).read())["events"]
        assert any(e["category"] == "health" for e in events)
        assert any(e["category"] == "breaker" for e in events)
        # staying degraded does NOT dump again
        again = healthz_snapshot()
        assert again["flight"]["last_dump"] == dump_path
        assert sum(
            1 for e in flight_recorder.events("health")
        ) == 1
    finally:
        registry.set_gauge("breaker.healthz-flight.state", 0.0)
        _HEALTH_STATE["status"] = None
        flight_recorder.configure(dump_dir="")


def test_server_error_triggers_flight_dump(tmp_path):
    from janusgraph_tpu.core.graph import open_graph
    from janusgraph_tpu.server import JanusGraphManager, JanusGraphServer
    from janusgraph_tpu.driver.client import JanusGraphClient, RemoteError

    flight_recorder.configure(dump_dir=str(tmp_path))
    g = open_graph({"ids.authority-wait-ms": 0.0})
    m = JanusGraphManager()
    m.put_graph("graph", g)
    s = JanusGraphServer(manager=m).start()
    try:
        client = JanusGraphClient(port=s.port)
        # division by zero inside evaluation = an unhandled server error
        with pytest.raises(RemoteError):
            client.submit("g.V().limit(1 / 0)")
        events = flight_recorder.events("server_error")
        assert events, "unhandled error not black-boxed"
        assert flight_recorder.health_block()["last_dump"]
        # client errors (sandbox rejection) must NOT dump
        before = len(flight_recorder.events("server_error"))
        with pytest.raises(RemoteError):
            client.submit("import os")
        assert len(flight_recorder.events("server_error")) == before
    finally:
        s.stop()
        g.close()
        flight_recorder.configure(dump_dir="")


# ------------------------------------------------------------- OLAP depth
def test_olap_run_record_carries_depth_telemetry():
    from janusgraph_tpu.core import gods
    from janusgraph_tpu.core.graph import open_graph
    from janusgraph_tpu.olap.programs import PageRankProgram

    g = open_graph({"ids.authority-wait-ms": 0.0})
    try:
        gods.load(g)
        g.compute().program(
            PageRankProgram(max_iterations=3, tol=0.0)
        ).submit()
        rec = registry.last_run("olap")
        cc = rec["compile_cache"]
        assert cc["misses"] >= 1  # first run always compiles
        assert cc["hits"] + cc["misses"] == len(rec["superstep_records"])
        dm = rec["device_memory"]
        assert dm["source"] in ("device", "host-estimate")
        assert dm["bytes_in_use"] >= 0
        slowest = rec["slowest_superstep"]
        assert slowest["wall_ms"] >= 0
        # the exemplar points at a real retained span
        assert len(slowest["span_id"]) == 16
        snap = registry.snapshot()
        assert "olap.device.bytes_in_use" in snap
        assert registry.get_count("olap.compile_cache.misses") >= 1
    finally:
        g.close()


def test_cli_flight_and_trace_commands(capsys):
    from janusgraph_tpu.cli import main as cli_main

    flight_recorder.record("fault", kind="read", n=0)
    assert cli_main(["flight"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["counts"]["fault"] == 1
    with tracer.span("cli.traced"):
        pass
    root = tracer.recent("cli.traced")[-1]
    tid = f"{root.trace_id:016x}"
    assert cli_main(["trace", tid]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["spans"][0]["name"] == "cli.traced"
    # unknown trace id -> exit 1, bad hex -> exit 2
    assert cli_main(["trace", "0000000000000001"]) == 1
    capsys.readouterr()
    assert cli_main(["trace", "not-hex"]) == 2


# --------------------------------------------------- remote index stitching
def test_remote_index_ops_join_the_callers_trace():
    """The index tier stitches like the storage tier: ops issued inside a
    span produce index.remote.* spans on the server side sharing the
    caller's trace_id, and an old-featured index server degrades."""
    import time

    from janusgraph_tpu.indexing import (
        InMemoryIndexProvider,
        RemoteIndexProvider,
        RemoteIndexServer,
    )
    from janusgraph_tpu.indexing.provider import (
        IndexQuery,
        KeyInformation,
        Mapping,
        PredicateCondition,
    )
    from janusgraph_tpu.core.predicates import predicate_by_name

    server = RemoteIndexServer(InMemoryIndexProvider()).start()
    host, port = server.address
    provider = RemoteIndexProvider(hostname=host, port=port)
    try:
        info = KeyInformation(str, Mapping.STRING, "SINGLE")
        with tracer.span("index.client") as root:
            provider.register("store", "name", info)
            hits = provider.query("store", IndexQuery(
                PredicateCondition("name", predicate_by_name("eq"), "x")
            ))
        assert hits == []
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            remote = [
                r for r in tracer.find_trace(root.trace_id)
                if r.name.startswith("index.remote.")
            ]
            if len(remote) >= 2:
                break
            time.sleep(0.01)
        names = {s.name for s in remote}
        assert {"index.remote.register", "index.remote.query"} <= names
        for s in remote:
            assert s.parent_span_id == root.span_id
    finally:
        provider.close()
        server.stop()

    # old-featured index server: byte-compatible, unstitched
    old = RemoteIndexServer(
        InMemoryIndexProvider(), trace_propagation=False
    ).start()
    p2 = RemoteIndexProvider(hostname=old.address[0], port=old.address[1])
    try:
        with tracer.span("index.old") as root2:
            p2.register("store", "name", KeyInformation(
                str, Mapping.STRING, "SINGLE"
            ))
        assert p2._remote_trace is False
        assert not [
            r for r in tracer.find_trace(root2.trace_id)
            if r.name.startswith("index.remote.")
        ]
    finally:
        p2.close()
        old.stop()
