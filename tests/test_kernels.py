"""Aggregation-kernel parity: ELL / Pallas strategies vs the segment path
and the scalar CPU oracle (kernels are drop-in replacements for the
reference's combiner hash-map, FulgoraVertexMemory.java:91-99)."""

import numpy as np
import pytest

from janusgraph_tpu.olap import csr_from_edges, run_on
from janusgraph_tpu.olap.kernels import (
    ELLPack,
    ell_aggregate,
    make_segsum_plan,
    pallas_sorted_segment_sum,
)
from janusgraph_tpu.olap.programs import (
    ConnectedComponentsProgram,
    PageRankProgram,
    ShortestPathProgram,
    TraversalCountProgram,
)
from janusgraph_tpu.olap.tpu_executor import TPUExecutor
from janusgraph_tpu.olap.vertex_program import Combiner, EdgeTransform


def random_graph(n=180, m=700, seed=11, weights=False):
    rng = np.random.default_rng(seed)
    src = rng.integers(0, n, m).astype(np.int32)
    dst = rng.integers(0, n, m).astype(np.int32)
    w = rng.uniform(0.5, 2.0, m).astype(np.float32) if weights else None
    return csr_from_edges(n, src, dst, w)


# ------------------------------------------------------------------ unit level
@pytest.mark.parametrize("op", [Combiner.SUM, Combiner.MIN, Combiner.MAX])
def test_ell_aggregate_matches_numpy(op):
    import jax.numpy as jnp

    rng = np.random.default_rng(3)
    n, m = 97, 450
    src = rng.integers(0, n, m)
    dst = rng.integers(0, n, m)
    w = rng.uniform(0.1, 2.0, m).astype(np.float32)
    msgs = rng.uniform(-1, 1, n).astype(np.float32)

    pack = ELLPack(src, dst, w, n)
    got = np.asarray(
        ell_aggregate(jnp, pack, jnp.asarray(msgs), op, EdgeTransform.MUL_WEIGHT)
    )

    ident = Combiner.IDENTITY[op]
    want = np.full(n, ident, dtype=np.float64)
    for s, d, wt in zip(src, dst, w):
        v = msgs[s] * wt
        if op == Combiner.SUM:
            want[d] += v
        elif op == Combiner.MIN:
            want[d] = min(want[d], v)
        else:
            want[d] = max(want[d], v)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_ell_aggregate_2d_messages():
    import jax.numpy as jnp

    rng = np.random.default_rng(4)
    n, m, k = 60, 240, 5
    src = rng.integers(0, n, m)
    dst = rng.integers(0, n, m)
    msgs = rng.uniform(0, 1, (n, k)).astype(np.float32)

    pack = ELLPack(src, dst, None, n)
    got = np.asarray(ell_aggregate(jnp, pack, jnp.asarray(msgs), Combiner.SUM))
    want = np.zeros((n, k))
    for s, d in zip(src, dst):
        want[d] += msgs[s]
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_ell_supernode_jumbo_bucket():
    """A hub vertex with degree above max_capacity row-splits into multiple
    capacity-sized rows folded by the rows-sized segment reduce."""
    import jax.numpy as jnp

    n = 40
    hub_deg = 70
    src = np.concatenate([np.arange(hub_deg) % (n - 1) + 1, [0, 0]])
    dst = np.concatenate([np.zeros(hub_deg, dtype=np.int64), [1, 2]])
    pack = ELLPack(src, dst, None, n, max_capacity=16)
    msgs = np.ones(n, dtype=np.float32)
    got = np.asarray(ell_aggregate(jnp, pack, jnp.asarray(msgs), Combiner.SUM))
    assert got[0] == hub_deg
    assert got[1] == 1 and got[2] == 1


def test_pallas_sorted_segment_sum_matches():
    import jax.numpy as jnp

    rng = np.random.default_rng(9)
    num_segments = 2500  # > one output tile, exercises multi-tile grid
    m = 9000
    seg = np.sort(rng.integers(0, num_segments, m))
    data = rng.uniform(-1, 1, m).astype(np.float32)

    plan = make_segsum_plan(seg, num_segments)
    got = np.asarray(
        pallas_sorted_segment_sum(jnp.asarray(data), plan, interpret=True)
    )
    want = np.bincount(seg, weights=data.astype(np.float64), minlength=num_segments)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_pallas_segment_sum_empty_segments_tail():
    """Segments with no edges (including whole empty tiles) read zero."""
    import jax.numpy as jnp

    seg = np.array([0, 0, 5, 1030], dtype=np.int64)  # tile 0 and tile 1
    data = np.array([1.0, 2.0, 3.0, 4.0], dtype=np.float32)
    plan = make_segsum_plan(seg, 4000)
    got = np.asarray(
        pallas_sorted_segment_sum(jnp.asarray(data), plan, interpret=True)
    )
    assert got[0] == 3.0 and got[5] == 3.0 and got[1030] == 4.0
    assert got.sum() == 10.0
    assert got.shape == (4000,)


# ------------------------------------------------------------- program parity
STRATEGY_PROGRAMS = [
    ("pagerank", lambda: PageRankProgram(max_iterations=20)),
    ("sssp_weighted", lambda: ShortestPathProgram(seed_index=0, weighted=True)),
    ("cc", lambda: ConnectedComponentsProgram()),
    ("khop", lambda: TraversalCountProgram(hops=3)),
]


@pytest.mark.parametrize("strategy", ["ell", "hybrid", "pallas"])
@pytest.mark.parametrize(
    "name,make", STRATEGY_PROGRAMS, ids=[p[0] for p in STRATEGY_PROGRAMS]
)
def test_strategy_parity_vs_cpu_oracle(strategy, name, make):
    g = random_graph(weights=True)
    cpu = run_on(g, make(), "cpu")
    ex = TPUExecutor(g, strategy=strategy)
    got = ex.run(make())
    assert set(cpu) == set(got)
    for k in cpu:
        np.testing.assert_allclose(
            np.asarray(got[k], dtype=np.float64),
            cpu[k],
            rtol=1e-4,
            atol=1e-5,
            err_msg=f"{strategy}:{name}:{k}",
        )


# ------------------------------------------------------- fused vs host loop
@pytest.mark.parametrize(
    "name,make", STRATEGY_PROGRAMS, ids=[p[0] for p in STRATEGY_PROGRAMS]
)
def test_fused_whole_run_matches_host_loop(name, make):
    g = random_graph(seed=21, weights=True)
    ex = TPUExecutor(g, strategy="ell")
    host = ex.run(make(), fused=False)
    fused = ex.run(make(), fused=True)
    for k in host:
        np.testing.assert_allclose(
            np.asarray(fused[k]), np.asarray(host[k]), rtol=1e-5, atol=1e-6,
            err_msg=f"fused:{name}:{k}",
        )


def test_fused_early_termination_device():
    """CC on a tiny path graph converges long before max_iterations; the
    on-device while_loop must stop at the fixpoint (same result)."""
    src = np.array([0, 1, 2, 3], dtype=np.int32)
    dst = np.array([1, 2, 3, 4], dtype=np.int32)
    g = csr_from_edges(6, src, dst, None)
    ex = TPUExecutor(g, strategy="ell")
    res = ex.run(ConnectedComponentsProgram(max_iterations=100), fused=True)
    comp = np.asarray(res["component"])
    assert (comp[:5] == comp[0]).all() and comp[5] != comp[0]


def test_sharded_fused_matches_host_loop():
    from janusgraph_tpu.parallel import ShardedExecutor

    g = random_graph(seed=33, weights=True)
    ex = ShardedExecutor(g)
    host = ex.run(PageRankProgram(max_iterations=15), fused=False)
    fused = ex.run(PageRankProgram(max_iterations=15), fused=True)
    np.testing.assert_allclose(fused["rank"], host["rank"], rtol=1e-5, atol=1e-7)
