"""Overload defense: cost-aware admission control, deadline propagation,
graded degradation (ISSUE 10 / ROADMAP #4 robustness half).

Covers the full vertical: AIMD limit convergence, queue-bound shedding
with Retry-After, /healthz exemption, brownout ladder hysteresis + flight
events, deadline wire compat (store + index, both directions), zero
storage retries past an expired deadline, driver retry-budget exhaustion,
the seeded overload fault kind, and an end-to-end saturated-server run
asserting goodput > 0 with no hung connections.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from janusgraph_tpu.core.deadline import deadline_scope, remaining_ms
from janusgraph_tpu.core.graph import open_graph
from janusgraph_tpu.driver import JanusGraphClient
from janusgraph_tpu.driver.client import RemoteError, RetryBudget
from janusgraph_tpu.exceptions import (
    DeadlineExceededError,
    ServerOverloadedError,
    TemporaryBackendError,
)
from janusgraph_tpu.observability import flight_recorder, registry
from janusgraph_tpu.server import JanusGraphManager, JanusGraphServer
from janusgraph_tpu.server.admission import (
    AdmissionController,
    AIMDLimiter,
    BrownoutLadder,
    RUNG_CHEAP_ONLY,
    RUNG_REFUSE_OLAP,
    ShedError,
    query_shape,
)
from janusgraph_tpu.server import admission as admission_mod
from janusgraph_tpu.storage import backend_op
from janusgraph_tpu.storage.faults import FaultPlan
from janusgraph_tpu.storage.inmemory import InMemoryStoreManager
from janusgraph_tpu.storage.remote import (
    RemoteStoreManager,
    RemoteStoreServer,
)


def _counter(name):
    m = registry.snapshot().get(name)
    return m["count"] if m else 0


# ---------------------------------------------------------------- fixtures
@pytest.fixture
def small_graph():
    g = open_graph({"ids.authority-wait-ms": 0.0})
    tx = g.new_transaction()
    for _ in range(4):
        tx.add_vertex()
    tx.commit()
    yield g
    g.close()


@pytest.fixture
def server(small_graph):
    m = JanusGraphManager()
    m.put_graph("graph", small_graph)
    s = JanusGraphServer(manager=m).start()
    yield s
    s.stop()


# ------------------------------------------------------------------- AIMD
def test_aimd_limit_converges_under_latency_step():
    lim = AIMDLimiter(initial=4, min_limit=1, max_limit=16, window=8,
                      threshold=2.0)
    # healthy phase: ~10ms latencies -> additive increase toward the cap
    for _ in range(8 * 6):
        lim.observe(10.0)
    grown = lim.limit
    assert grown > 4
    assert lim.baseline_ms is not None and lim.baseline_ms < 20.0
    # latency step (5x the baseline): multiplicative decrease to the floor
    for _ in range(8 * 20):
        lim.observe(lim.baseline_ms * 5.0)
    assert lim.limit == 1
    # recovery: healthy latencies grow the limit again
    for _ in range(8 * 4):
        lim.observe(10.0)
    assert lim.limit > 1


def test_aimd_baseline_does_not_inflate_under_overload():
    lim = AIMDLimiter(initial=4, window=4, threshold=2.0)
    for _ in range(8):
        lim.observe(10.0)
    base = lim.baseline_ms
    for _ in range(40):
        lim.observe(500.0)  # overloaded windows must not move the baseline
    assert lim.baseline_ms == base


# ------------------------------------------------------- queue + shedding
def test_queue_bound_sheds_with_retry_after():
    ctl = AdmissionController(
        initial_limit=1, min_limit=1, max_limit=1, queue_bound=1,
        retry_after_base_s=0.25, retry_after_max_s=8.0,
    )
    first = ctl.acquire(price_ms=1.0)      # takes the only slot
    queued = []

    def waiter():
        t = ctl.acquire(price_ms=2.0)
        queued.append(t)
        ctl.release(t, 1.0)

    th = threading.Thread(target=waiter)
    th.start()
    for _ in range(100):
        if ctl.queue_depth == 1:
            break
        time.sleep(0.01)
    assert ctl.queue_depth == 1
    # the queue is at its bound: the next arrival is shed, with a
    # jittered Retry-After inside the configured envelope
    with pytest.raises(ShedError) as ei:
        ctl.acquire(price_ms=3.0)
    assert ei.value.reason == "queue-full"
    assert 0.0 < ei.value.retry_after_s <= 8.0
    ctl.release(first, 1.0)  # frees the slot -> the queued waiter runs
    th.join(timeout=5)
    assert queued, "queued request was never granted"


def test_cost_priority_queue_grants_cheapest_first():
    ctl = AdmissionController(
        initial_limit=1, min_limit=1, max_limit=1, queue_bound=8,
    )
    first = ctl.acquire(price_ms=1.0)
    order = []
    started = []

    def waiter(price, tag):
        started.append(tag)
        t = ctl.acquire(price_ms=price)
        order.append(tag)
        ctl.release(t, 1.0)

    expensive = threading.Thread(target=waiter, args=(100.0, "expensive"))
    expensive.start()
    while ctl.queue_depth < 1:
        time.sleep(0.01)
    cheap = threading.Thread(target=waiter, args=(1.0, "cheap"))
    cheap.start()
    while ctl.queue_depth < 2:
        time.sleep(0.01)
    ctl.release(first, 1.0)
    expensive.join(timeout=5)
    cheap.join(timeout=5)
    # the cheap request overtook the earlier-queued expensive one
    assert order[0] == "cheap"


def test_queued_request_times_out_with_deadline():
    ctl = AdmissionController(initial_limit=1, max_limit=1, queue_bound=4)
    first = ctl.acquire(price_ms=1.0)
    t0 = time.monotonic()
    with pytest.raises(DeadlineExceededError):
        ctl.acquire(price_ms=1.0, timeout_s=0.2)
    assert time.monotonic() - t0 < 2.0
    ctl.release(first, 1.0)


# ------------------------------------------------------------ brownout
def test_brownout_ladder_hysteresis_and_flight_events():
    clock = {"t": 0.0}
    ladder = BrownoutLadder(
        window_s=5.0, enter_sheds=3, exit_s=10.0, dwell_s=2.0,
        clock=lambda: clock["t"],
    )
    flight_recorder.reset()
    # two sheds inside the window: below the enter threshold, rung holds
    ladder.note_shed(); ladder.note_shed()
    assert ladder.rung == 0
    clock["t"] = 3.0
    ladder.note_shed()
    assert ladder.rung == 1  # third shed within 5s -> enter rung 1
    # a fresh burst escalates again, but only after the dwell
    clock["t"] = 3.5
    ladder.note_shed(); ladder.note_shed(); ladder.note_shed()
    assert ladder.rung == 1  # dwell (2s since transition) not yet passed
    clock["t"] = 6.0
    ladder.note_shed(); ladder.note_shed(); ladder.note_shed()
    assert ladder.rung == 2
    # healthy ticks do NOT de-escalate until exit_s shed-free + dwell
    clock["t"] = 10.0
    ladder.note_healthy()
    assert ladder.rung == 2
    clock["t"] = 17.0  # >= 10s since the last shed (t=6.0 burst)
    ladder.note_healthy()
    assert ladder.rung == 1
    clock["t"] = 17.5
    ladder.note_healthy()
    assert ladder.rung == 1  # dwell again: no instant double-exit
    clock["t"] = 20.0
    ladder.note_healthy()
    assert ladder.rung == 0
    events = flight_recorder.events("brownout")
    dirs = [(e["rung"], e["direction"]) for e in events]
    assert (1, "enter") in dirs and (2, "enter") in dirs
    assert (1, "exit") in dirs and (0, "exit") in dirs


def test_brownout_rung3_admits_only_known_cheap_digests():
    ctl = AdmissionController(
        initial_limit=4, max_limit=4, queue_bound=4,
        default_cost_ms=25.0, cheap_cost_ms=5.0,
    )
    # pin the ladder at rung 3 (pretend sheds are landing right now and a
    # transition just happened, so neither healthy ticks nor the
    # underload rule can de-escalate inside the dwell during this test)
    ctl.brownout.rung = RUNG_CHEAP_ONLY
    ctl.brownout._last_shed = time.monotonic() + 3600.0
    ctl.brownout._last_transition = time.monotonic() + 3600.0
    cheap_q = "g.V(1).out('knows').count()"
    heavy_q = "g.V().both().both().both().to_list()"
    for _ in range(3):  # teach the price book both shapes
        d, _, _ = ctl.price(cheap_q)
        ctl.observe_cost(d, cheap_q, 2.0)
        d, _, _ = ctl.price(heavy_q)
        ctl.observe_cost(d, heavy_q, 300.0)
    digest, price, known = ctl.price(cheap_q)
    assert known and price <= 5.0
    t = ctl.acquire(price_ms=price, known=known, digest=digest)
    ctl.release(t, 2.0)
    # a known-expensive shape is refused at the door
    digest, price, known = ctl.price(heavy_q)
    with pytest.raises(ShedError) as ei:
        ctl.acquire(price_ms=price, known=known, digest=digest)
    assert ei.value.reason == "brownout-cheap-only"
    # an unknown shape pays the default price -> also refused
    digest, price, known = ctl.price("g.V().has('x','y').values('z')")
    assert not known
    with pytest.raises(ShedError):
        ctl.acquire(price_ms=price, known=known, digest=digest)


def test_brownout_rung3_deescalates_instead_of_livelocking():
    # a rung-3 ladder shedding EVERYTHING while capacity sits idle must
    # step down (ladder-induced sheds), not pin goodput at zero forever
    ctl = AdmissionController(
        initial_limit=4, max_limit=4, queue_bound=4,
        brownout_dwell_s=0.0,
    )
    ctl.brownout.rung = RUNG_CHEAP_ONLY
    ctl.brownout._last_shed = time.monotonic()
    with pytest.raises(ShedError):
        ctl.acquire(price_ms=25.0, known=False)
    # the shed hit an idle server -> the ladder stepped down one rung
    assert ctl.brownout.rung == RUNG_CHEAP_ONLY - 1
    events = flight_recorder.events("brownout")
    assert any(
        e["direction"] == "exit" and "idle capacity" in e["reason"]
        for e in events
    )


def test_olap_submit_refused_under_brownout(small_graph):
    from janusgraph_tpu.olap.programs import PageRankProgram

    ctl = AdmissionController()
    ctl.brownout.rung = RUNG_REFUSE_OLAP
    admission_mod.set_active(ctl)
    try:
        with pytest.raises(ServerOverloadedError):
            small_graph.compute().program(
                PageRankProgram(max_iterations=1)
            ).submit()
    finally:
        admission_mod.set_active(None)
    # with no active controller, embedded OLAP is never throttled
    res = small_graph.compute().program(
        PageRankProgram(max_iterations=1)
    ).submit()
    assert res is not None


def test_query_shape_strips_literals():
    # literals (strings, numbers, whitespace) never change the shape...
    assert query_shape("g.V(1).out('a')") == query_shape("g.V(2).out('b')")
    assert query_shape("g.V( 1 )") == query_shape("g.V(1)")
    assert query_shape(
        "g.V(42).has('name', 'saturn').out('father')"
    ) == query_shape("g.V(7).has('age', 'zeus').out('mother')")
    # ...but the step chain does
    assert query_shape("g.V(1).out('a')") != query_shape(
        "g.V(1).out('a').out('b')"
    )


# ------------------------------------------------ server-level shedding
def _slow_server(graph, sleep_s, **kw):
    m = JanusGraphManager()
    m.put_graph("graph", graph)
    server = JanusGraphServer(manager=m, **kw)

    real_execute = server.execute

    def slow_execute(query, graph_name=None):
        time.sleep(sleep_s)
        return real_execute(query, graph_name)

    server.execute = slow_execute
    return server.start()


def test_healthz_and_observability_bypass_admission_while_shedding(
    small_graph,
):
    ctl = AdmissionController(
        initial_limit=1, min_limit=1, max_limit=1, queue_bound=0,
    )
    server = _slow_server(
        small_graph, 0.3, admission=ctl, request_timeout_s=30.0,
    )
    try:
        base = f"http://127.0.0.1:{server.port}"
        # saturate the single slot
        t = threading.Thread(
            target=lambda: JanusGraphClient(
                port=server.port, retry_budget_capacity=0,
            ).submit("g.V().count()"),
        )
        t.start()
        time.sleep(0.1)  # the slot is taken; queue bound is 0
        # user traffic is shed with a REAL 503 + Retry-After + status=shed
        body = json.dumps({"gremlin": "g.V().count()"}).encode()
        req = urllib.request.Request(
            base + "/gremlin", data=body, method="POST",
            headers={"Content-Type": "application/json"},
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req)
        assert ei.value.code == 503
        assert ei.value.headers.get("Retry-After") is not None
        shed_payload = json.loads(ei.value.read())
        assert shed_payload["status"]["status"] == "shed"
        assert shed_payload["status"]["retry_after_s"] > 0
        # ... while every observability endpoint still answers
        for path in ("/healthz", "/metrics", "/telemetry", "/flight",
                     "/profile", "/timeseries"):
            try:
                resp = urllib.request.urlopen(base + path, timeout=5)
                code = resp.getcode()
            except urllib.error.HTTPError as e:
                code = e.code  # /healthz may 503 when degraded — fine
            assert code in (200, 503), path
        # the /healthz admission block reports the front door's state,
        # and its status field says degraded/ok — never "shed"
        try:
            hz = json.loads(
                urllib.request.urlopen(base + "/healthz", timeout=5).read()
            )
        except urllib.error.HTTPError as e:
            hz = json.loads(e.read())
        assert hz["status"] in ("ok", "degraded")
        assert hz["admission"]["limit"] == 1
        assert hz["admission"]["shed"] >= 1
        assert hz["admission"]["queue_bound"] == 0
        # the slo block (ISSUE 13) rides alongside the admission block
        # even mid-shed: the burn-rate engine's verdict is part of the
        # same self-describing health surface
        assert "slo" in hz
        assert hz["slo"]["specs"] >= 1
        assert hz["slo"]["worst"] in ("ok", "ticket", "page")
        for alert in hz["slo"]["alerts"]:
            assert {"name", "kind", "severity", "fast_burn",
                    "slow_burn"} <= set(alert)
        t.join(timeout=10)
    finally:
        server.stop()


def test_request_timeout_is_an_evaluation_deadline(small_graph):
    # server.request-timeout-s is the DEFAULT deadline when the client
    # sends none: a slow evaluation returns a structured timeout instead
    # of a hung connection / late success
    server = _slow_server(
        small_graph, 0.5, request_timeout_s=0.2, admission_enabled=False,
    )
    try:
        client = JanusGraphClient(port=server.port)
        with pytest.raises(RemoteError) as ei:
            client.submit("g.V().count()")
        assert ei.value.code == 504
        assert ei.value.status == "timeout"
    finally:
        server.stop()


def test_client_deadline_rides_ws_field(small_graph):
    server = _slow_server(
        small_graph, 0.4, request_timeout_s=30.0, admission_enabled=False,
    )
    try:
        ws = JanusGraphClient(port=server.port).ws()
        with pytest.raises(RemoteError) as ei:
            ws.submit("g.V().count()", deadline_ms=100)
        assert ei.value.code == 504 and ei.value.status == "timeout"
        # without a deadline the same query succeeds
        assert ws.submit("g.V().count()") == 4
        ws.close()
    finally:
        server.stop()


# ------------------------------------------------------ driver retry budget
def test_retry_budget_token_bucket():
    b = RetryBudget(capacity=2, refill_per_s=0.0)
    assert b.take() and b.take()
    assert not b.take()
    b2 = RetryBudget(capacity=0, refill_per_s=10.0)
    assert not b2.take()  # capacity 0 = never retry


def test_driver_retry_budget_exhaustion(small_graph):
    ctl = AdmissionController(
        initial_limit=1, max_limit=1, queue_bound=0,
        retry_after_base_s=0.05, retry_after_max_s=0.1,
        brownout_enter_sheds=10_000,  # keep the ladder quiet
    )
    server = _slow_server(
        small_graph, 0.5, admission=ctl, request_timeout_s=30.0,
    )
    try:
        # hold the only slot so every submit below is shed
        holder = threading.Thread(
            target=lambda: JanusGraphClient(
                port=server.port, retry_budget_capacity=0,
            ).submit("g.V().count()"),
        )
        holder.start()
        time.sleep(0.15)
        client = JanusGraphClient(
            port=server.port,
            retry_budget_capacity=2, retry_budget_refill_per_s=0.0,
        )
        shed0 = _counter("server.admission.shed")
        with pytest.raises(RemoteError) as ei:
            client.submit("g.V(1).id()")
        assert ei.value.code == 503 and ei.value.status == "shed"
        assert ei.value.retry_after_s is not None
        # 1 initial + 2 budgeted retries = 3 sheds, then the budget is dry
        assert _counter("server.admission.shed") - shed0 == 3
        assert client.retry_budget.tokens < 1.0
        holder.join(timeout=10)
    finally:
        server.stop()


# ----------------------------------------------------- deadline: backend_op
def test_backend_op_zero_attempts_past_expired_deadline():
    calls = []

    def op():
        calls.append(1)
        raise TemporaryBackendError("flaky")

    retries0 = _counter("storage.backend_op.retries")
    with deadline_scope(20):
        time.sleep(0.03)  # let the budget expire
        with pytest.raises(DeadlineExceededError):
            backend_op.execute(op, max_time_s=5.0)
    assert calls == []  # zero dispatches, zero retries
    assert _counter("storage.backend_op.retries") == retries0


def test_backend_op_stops_retrying_when_deadline_expires_midway():
    calls = []

    def op():
        calls.append(1)
        raise TemporaryBackendError("flaky")

    t0 = time.monotonic()
    with deadline_scope(150):
        with pytest.raises(DeadlineExceededError):
            backend_op.execute(
                op, max_time_s=30.0, base_delay_s=0.02, max_delay_s=0.05,
            )
    # gave up at the deadline, nowhere near the 30s retry budget
    assert time.monotonic() - t0 < 2.0
    assert len(calls) >= 1


def test_remote_request_with_expired_deadline_does_zero_storage_retries():
    # the acceptance criterion: a request whose deadline is spent performs
    # ZERO storage-layer retries, asserted via storage.backend_op.retries
    server = RemoteStoreServer(InMemoryStoreManager()).start()
    host, port = server.address
    mgr = RemoteStoreManager(host, port)
    try:
        store = mgr.open_database("edgestore")
        txh = mgr.begin_transaction()
        from janusgraph_tpu.storage.kcvs import KeySliceQuery, SliceQuery

        q = KeySliceQuery(b"k", SliceQuery(b"", None, None))
        retries0 = _counter("storage.backend_op.retries")
        with deadline_scope(10):
            time.sleep(0.02)
            with pytest.raises(DeadlineExceededError):
                store.get_slice(q, txh)
        assert _counter("storage.backend_op.retries") == retries0
        # outside the scope the same read works
        assert store.get_slice(q, txh) == []
    finally:
        mgr.close()
        server.stop()


# --------------------------------------------- deadline: wire negotiation
class _DeadlineProbeManager(InMemoryStoreManager):
    """Records the ambient deadline budget seen by each served read."""

    def __init__(self):
        super().__init__()
        self.seen = []

    def open_database(self, name):
        mgr = self
        store = super().open_database(name)
        probe = store.get_slice

        class _Probe:
            def __getattr__(self, item):
                return getattr(store, item)

            def get_slice(self, query, txh):
                mgr.seen.append(remaining_ms())
                return probe(query, txh)

        return _Probe()


def _one_read(mgr):
    from janusgraph_tpu.storage.kcvs import KeySliceQuery, SliceQuery

    store = mgr.open_database("edgestore")
    return store.get_slice(
        KeySliceQuery(b"k", SliceQuery(b"", None, None)),
        mgr.begin_transaction(),
    )


def test_store_deadline_wire_compat_both_directions():
    probe = _DeadlineProbeManager()
    # new client <-> new server: the budget crosses the wire
    server = RemoteStoreServer(probe).start()
    host, port = server.address
    new_client = RemoteStoreManager(host, port)
    try:
        with deadline_scope(5_000):
            _one_read(new_client)
        assert probe.seen[-1] is not None and 0 < probe.seen[-1] <= 5_000
        # outside a scope: no flag, no ambient deadline server-side
        _one_read(new_client)
        assert probe.seen[-1] is None
        # old client (pre-deadline) x new server: byte-compatible, no
        # deadline arrives
        old_client = RemoteStoreManager(
            host, port, deadline_propagation=False,
        )
        with deadline_scope(5_000):
            _one_read(old_client)
        assert probe.seen[-1] is None
        old_client.close()
    finally:
        new_client.close()
        server.stop()
    # new client x old server (pre-deadline features): byte-compatible,
    # the client never flags frames
    probe2 = _DeadlineProbeManager()
    old_server = RemoteStoreServer(probe2, deadline_propagation=False).start()
    host2, port2 = old_server.address
    client2 = RemoteStoreManager(host2, port2)
    try:
        with deadline_scope(5_000):
            assert _one_read(client2) == []
        assert probe2.seen[-1] is None
        assert client2._remote_deadline is False
    finally:
        client2.close()
        old_server.stop()


def test_store_server_refuses_op_with_spent_budget():
    # a frame that ARRIVES with 0 remaining budget is refused permanently
    # before touching the store (crafted directly: the client-side guard
    # would normally refuse first)
    import struct as _struct

    from janusgraph_tpu.storage.remote import (
        _DEADLINE_FLAG,
        _OP_EXISTS,
        _Conn,
        encode_deadline_prefix,
    )

    server = RemoteStoreServer(InMemoryStoreManager()).start()
    host, port = server.address
    conn = _Conn(host, port)
    try:
        body = encode_deadline_prefix(0)
        status, payload, _ = conn.request(_OP_EXISTS | _DEADLINE_FLAG, body)
        assert status == 2  # permanent: never replayed
        assert b"DeadlineExceededError" in payload
        # same op with budget: serves normally
        status, payload, _ = conn.request(
            _OP_EXISTS | _DEADLINE_FLAG, encode_deadline_prefix(5_000)
        )
        assert status == 0
    finally:
        if conn.sock is not None:
            conn.sock.close()
        server.stop()


def test_index_deadline_wire_compat_both_directions():
    from janusgraph_tpu.core.predicates import Cmp
    from janusgraph_tpu.indexing.memindex import InMemoryIndexProvider
    from janusgraph_tpu.indexing.provider import (
        IndexEntry,
        IndexMutation,
        IndexQuery,
        KeyInformation,
        Mapping,
        PredicateCondition,
    )
    from janusgraph_tpu.indexing.remote import (
        RemoteIndexProvider,
        RemoteIndexServer,
    )

    info = KeyInformation(str, Mapping.STRING, "SINGLE")
    q = IndexQuery(PredicateCondition("name", Cmp.EQUAL, "zeus"))

    def _roundtrip(provider):
        provider.register("idx", "name", info)
        m = IndexMutation(is_new=True)
        m.additions.append(IndexEntry("name", "zeus"))
        provider.mutate({"idx": {"d1": m}}, {"idx": {"name": info}})
        return provider.query("idx", q)

    # new client x new server (deadline negotiated ON), and an old
    # (pre-deadline) client against the same new server
    server = RemoteIndexServer(InMemoryIndexProvider()).start()
    host, port = server.address
    try:
        new_client = RemoteIndexProvider(hostname=host, port=port)
        with deadline_scope(5_000):
            assert _roundtrip(new_client) == ["d1"]
        assert new_client._remote_deadline is True
        old_client = RemoteIndexProvider(
            hostname=host, port=port, deadline_propagation=False,
        )
        with deadline_scope(5_000):
            assert old_client.query("idx", q) == ["d1"]
        new_client.close()
        old_client.close()
    finally:
        server.stop()
    # new client x old server: the third capability byte is absent, the
    # client negotiates the deadline OFF and stays byte-compatible
    old_server = RemoteIndexServer(
        InMemoryIndexProvider(), deadline_propagation=False,
    ).start()
    host2, port2 = old_server.address
    try:
        client2 = RemoteIndexProvider(hostname=host2, port=port2)
        with deadline_scope(5_000):
            assert _roundtrip(client2) == ["d1"]
        assert client2._remote_deadline is False
        # trace/ledger negotiation is unaffected by the missing byte
        assert client2._remote_trace is True
        client2.close()
    finally:
        old_server.stop()


# -------------------------------------------------------- overload fault
def test_overload_fault_kind_is_seeded_and_journaled():
    def run(seed):
        plan = FaultPlan(
            seed=seed, overload_at=2, overload_ops=3,
            overload_latency_ms=5.0,
        )
        t0 = time.perf_counter()
        for _ in range(8):
            plan.before_read("edgestore")
        wall = time.perf_counter() - t0
        return plan.journal, wall

    j1, wall = run(7)
    j2, _ = run(7)
    assert j1 == j2  # same seed -> byte-equal journal
    storms = [e for e in j1 if e["kind"] == "overload"]
    assert storms == [{
        "kind": "overload", "n": 2, "store": "edgestore", "ops": 3,
        "ms": 5.0,
    }]
    assert wall >= 0.014  # 3 reads stalled ~5ms each


def test_overload_fault_from_config():
    g = open_graph({
        "ids.authority-wait-ms": 0.0,
        "storage.faults.enabled": True,
        "storage.faults.overload-at": 0,
        "storage.faults.overload-ops": 2,
        "storage.faults.overload-latency-ms": 1.0,
    })
    try:
        assert g.fault_plan.overload_at == 0
        assert g.fault_plan.overload_ops == 2
        assert g.fault_plan.overload_latency_ms == 1.0
    finally:
        g.close()


# ------------------------------------------------------- e2e saturation
def test_saturated_server_keeps_goodput_and_never_hangs(small_graph):
    ctl = AdmissionController(
        initial_limit=2, min_limit=1, max_limit=4, queue_bound=4,
        retry_after_base_s=0.02, retry_after_max_s=0.1,
    )
    server = _slow_server(
        small_graph, 0.02, admission=ctl, request_timeout_s=10.0,
    )
    results = {"ok": 0, "shed": 0, "other": 0, "hung": 0}
    lock = threading.Lock()

    def closed_loop():
        client = JanusGraphClient(
            port=server.port, retry_budget_capacity=0,
        )
        for _ in range(6):
            try:
                client.submit("g.V().count()", deadline_ms=8_000)
                out = "ok"
            except RemoteError as e:
                out = "shed" if e.status == "shed" else "other"
                if out == "shed":
                    assert e.retry_after_s is not None  # every shed
            except Exception:  # noqa: BLE001 - hang/timeout bucket
                out = "hung"
            with lock:
                results[out] += 1

    threads = [threading.Thread(target=closed_loop) for _ in range(16)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    try:
        assert all(not t.is_alive() for t in threads), "hung client threads"
        assert time.monotonic() - t0 < 60
        total = sum(results.values())
        assert total == 16 * 6
        assert results["hung"] == 0
        assert results["other"] == 0
        # goodput survives 16-way closed-loop load against a limit of <=4
        assert results["ok"] > 0
        assert results["shed"] > 0  # offered load really exceeded capacity
    finally:
        server.stop()


def test_aimd_baseline_floor_anchor_under_gradual_ramp():
    """ISSUE 11 regression: a gradual latency ramp must NOT ratchet the
    healthy-window baseline upward until overload reads as normal (the
    boiling-frog hole found re-tuning the limiter for pipelined storage
    latencies). The baseline stays anchored to the best demonstrated
    window median, so the multiplicative decrease eventually fires."""
    from janusgraph_tpu.server.admission import AIMDLimiter

    lim = AIMDLimiter(initial=8, max_limit=64, window=4, threshold=2.0)
    # healthy start: ~10 ms medians seed floor and baseline
    for _ in range(3):
        for _ in range(4):
            lim.observe(10.0)
    assert lim.baseline_ms is not None and lim.baseline_ms <= 12.6
    start_limit = lim.limit
    # creeping congestion: +15% latency per window for 20 windows —
    # each window looks "almost healthy" vs the previous one
    latency = 10.0
    decreased = False
    for _ in range(20):
        latency *= 1.15
        before = lim.limit
        for _ in range(4):
            lim.observe(latency)
        if lim.limit < before:
            decreased = True
    assert decreased, (
        f"limit never decreased on a gradual ramp (baseline inflated to "
        f"{lim.baseline_ms:.1f} ms)"
    )
    # the anchor held: baseline stays within the floor cap of the best
    # median (floor decays 2%/window — bounded, not unbounded EWMA drift)
    assert lim.baseline_ms <= lim.floor_ms * AIMDLimiter.BASELINE_FLOOR_CAP
