"""Config system tests: typed options, mutability levels, KCVS-backed global
config, instance registry (reference: ConfigOption.java mutability semantics,
KCVSConfiguration, StandardJanusGraph instance registration)."""

import pytest

from janusgraph_tpu.core.config import (
    REGISTRY,
    GraphConfiguration,
    Mutability,
    describe_options,
)
from janusgraph_tpu.core.graph import open_graph
from janusgraph_tpu.exceptions import ConfigurationError
from janusgraph_tpu.storage.inmemory import InMemoryStoreManager


def test_unknown_option_rejected():
    with pytest.raises(ConfigurationError, match="unknown configuration"):
        open_graph({"storage.bogus": 1}).close()


def test_type_checked():
    with pytest.raises(ConfigurationError, match="expected int"):
        open_graph({"ids.block-size": "a lot"}).close()


def test_verifier_rejects():
    with pytest.raises(ConfigurationError, match="invalid value"):
        open_graph({"ids.partition-bits": 99}).close()


def test_defaults_apply():
    g = open_graph()
    assert g.config.get("cache.db-cache") is True
    assert g.config.get("ids.partition-bits") == 5
    g.close()


def test_fixed_option_frozen_across_instances():
    mgr = InMemoryStoreManager()
    g1 = open_graph({"ids.partition-bits": 4}, )
    g1.close()
    # same manager: second opener with a conflicting FIXED value fails
    g1 = __import__("janusgraph_tpu.core.graph", fromlist=["JanusGraphTPU"]).JanusGraphTPU(
        {"ids.partition-bits": 4}, store_manager=mgr
    )
    with pytest.raises(ConfigurationError, match="FIXED"):
        __import__("janusgraph_tpu.core.graph", fromlist=["JanusGraphTPU"]).JanusGraphTPU(
            {"ids.partition-bits": 6}, store_manager=mgr
        )
    g1.close()


def test_global_option_set_via_management():
    g = open_graph()
    mgmt = g.management()
    mgmt.set_config("tx.log-tx", True)
    assert g.config.get("tx.log-tx") is True
    g.close()


def test_global_offline_requires_single_instance():
    mgr = InMemoryStoreManager()
    from janusgraph_tpu.core.graph import JanusGraphTPU

    g1 = JanusGraphTPU({}, store_manager=mgr)
    g2 = JanusGraphTPU({}, store_manager=mgr)
    with pytest.raises(ConfigurationError, match="GLOBAL_OFFLINE"):
        g1.management().set_config("ids.block-size", 777)
    g2.close()
    g1.management().set_config("ids.block-size", 777)
    assert g1.config.get("ids.block-size") == 777
    g1.close()


def test_local_option_not_settable_globally():
    g = open_graph()
    with pytest.raises(ConfigurationError, match="LOCAL"):
        g.management().set_config("storage.backend", "other")
    g.close()


def test_instance_registry_and_force_close():
    mgr = InMemoryStoreManager()
    from janusgraph_tpu.core.graph import JanusGraphTPU

    g1 = JanusGraphTPU({}, store_manager=mgr)
    g2 = JanusGraphTPU({}, store_manager=mgr)
    mgmt = g1.management()
    ids = set(mgmt.open_instances())
    assert {g1.instance_id, g2.instance_id} <= ids
    # duplicate registration of a live id fails
    with pytest.raises(ConfigurationError, match="already registered"):
        JanusGraphTPU(
            {"graph.unique-instance-id": g2.instance_id}, store_manager=mgr
        )
    # evict the (simulated stale) second instance
    mgmt.force_close_instance(g2.instance_id)
    assert g2.instance_id not in mgmt.open_instances()
    g1.close()


def test_maskable_local_overrides_stored():
    mgr = InMemoryStoreManager()
    from janusgraph_tpu.core.graph import JanusGraphTPU

    g1 = JanusGraphTPU({}, store_manager=mgr)
    g1.config.set_global("cache.db-cache-size", 1000)
    assert g1.config.get("cache.db-cache-size") == 1000
    g1.close()
    g2 = JanusGraphTPU({"cache.db-cache-size": 2000}, store_manager=mgr)
    assert g2.config.get("cache.db-cache-size") == 2000  # local masks stored
    g2.close()


def test_describe_options_covers_registry():
    doc = describe_options()
    for path in REGISTRY:
        assert path in doc
    assert "global_offline" in doc


def test_mutability_coverage():
    kinds = {o.mutability for o in REGISTRY.values()}
    assert {
        Mutability.LOCAL,
        Mutability.MASKABLE,
        Mutability.GLOBAL,
        Mutability.GLOBAL_OFFLINE,
        Mutability.FIXED,
    } <= kinds


def test_registry_breadth():
    """≥40 registered options (reference has ~140 at
    GraphDatabaseConfiguration.java; the breadth that matters — cache,
    locks, logs, ids, computer, scan — is covered)."""
    from janusgraph_tpu.core.config import REGISTRY

    assert len(REGISTRY) >= 40, sorted(REGISTRY)


def test_computer_options_flow_to_executor():
    from janusgraph_tpu.core.graph import open_graph

    g = open_graph({
        "computer.executor": "cpu",
        "computer.strategy": "segment",
        "computer.ell-max-capacity": 64,
    })
    comp = g.compute()
    assert comp.executor_kind == "cpu"
    # strategy/capacity flow through run_on for tpu executors
    from janusgraph_tpu.olap.computer import run_on
    from janusgraph_tpu.olap import csr_from_edges
    from janusgraph_tpu.olap.programs import PageRankProgram

    csr = csr_from_edges(6, [0, 1, 2], [1, 2, 3])
    out = run_on(csr, PageRankProgram(max_iterations=3),
                 executor="tpu", strategy="segment", ell_max_capacity=64)
    assert "rank" in out
    g.close()


def test_scan_options_consumed(tmp_path):
    from janusgraph_tpu.core.graph import open_graph

    g = open_graph({"storage.scan-batch-size": 7,
                    "storage.scan-parallelism": 2})
    assert g.config.get("storage.scan-batch-size") == 7
    tx = g.new_transaction()
    for _ in range(5):
        tx.add_vertex()
    tx.commit()
    from janusgraph_tpu.olap.jobs import GhostVertexRemover, run_scan_job

    metrics = run_scan_job(g, GhostVertexRemover(g))
    assert metrics is not None
    g.close()


def test_ids_renew_percentage_reaches_pools():
    from janusgraph_tpu.core.graph import open_graph

    g = open_graph({"ids.renew-percentage": 0.5})
    assert g.id_assigner._relation_pool.RENEW_FRACTION == 0.5
    g.close()
