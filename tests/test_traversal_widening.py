"""Widened OLTP traversal vocabulary — the reference docs' Graph-of-the-Gods
queries (reference: docs/getting-started/basic-usage.md traversal examples,
step semantics from TinkerPop as rewritten by
graphdb/tinkerpop/optimize/strategy/JanusGraphLocalQueryOptimizerStrategy.java)
run verbatim modulo snake_case: as_/select/path, union/coalesce/choose,
where(P-on-tag)/where(traversal)/not_/is_, project/group with by()
modulators, repeat(...).until/emit, simple_path, fold/unfold.
"""

import pytest

from janusgraph_tpu.core import gods
from janusgraph_tpu.core.graph import open_graph
from janusgraph_tpu.core.traversal import P
from janusgraph_tpu.exceptions import QueryError


@pytest.fixture()
def g():
    graph = open_graph()
    gods.load(graph)
    yield graph.traversal()
    graph.close()


def names(xs):
    return sorted(xs)


# ---- docs' basic queries -----------------------------------------------------

def test_grandfather_via_double_in(g):
    # g.V().has('name','saturn').in('father').in('father').values('name')
    out = g.V().has("name", "saturn").in_("father").in_("father").values("name").to_list()
    assert out == ["hercules"]


def test_out_two_labels(g):
    out = g.V().has("name", "hercules").out("father", "mother").values("name").to_list()
    assert names(out) == ["alcmene", "jupiter"]


def test_edge_property_filter_mid_traversal(g):
    # g.V(hercules).outE('battled').has('time', gt(1)).inV().values('name')
    out = (
        g.V().has("name", "hercules")
        .out_e("battled").has("time", P.gt(1)).in_v().values("name").to_list()
    )
    assert names(out) == ["cerberus", "hydra"]


# ---- as_/select/path/where --------------------------------------------------

def test_where_neq_tag_excludes_self(g):
    # g.V(pluto).as('x').out('lives').in('lives').where(neq('x')).values('name')
    cohab = (
        g.V().has("name", "pluto").as_("x")
        .out("lives").in_("lives").where(P.neq("x")).values("name").to_list()
    )
    assert names(cohab) == ["cerberus"]


def test_select_two_tags_by_name(g):
    # g.V(pluto).out('brother').as('god').out('lives').as('place')
    #  .select('god','place').by('name')
    rows = (
        g.V().has("name", "pluto").out("brother").as_("god")
        .out("lives").as_("place")
        .select("god", "place").by("name").to_list()
    )
    assert sorted((r["god"], r["place"]) for r in rows) == [
        ("jupiter", "sky"), ("neptune", "sea"),
    ]


def test_select_single_tag(g):
    rows = (
        g.V().has("name", "hercules").as_("h").out("battled")
        .select("h").by("name").to_list()
    )
    assert rows == ["hercules"] * 3


def test_path_by_name(g):
    out = (
        g.V().has("name", "hercules").out("father").out("father")
        .path().by("name").to_list()
    )
    assert out == [("hercules", "jupiter", "saturn")]


def test_path_raw_objects(g):
    p = g.V().has("name", "saturn").in_("father").path().next()
    assert [v.value("name") for v in p] == ["saturn", "jupiter"]


def test_simple_path_removes_cycles(g):
    # jupiter -brother-> pluto -brother-> jupiter revisits; simple_path drops
    out = (
        g.V().has("name", "jupiter").out("brother").out("brother")
        .simple_path().values("name").to_list()
    )
    assert "jupiter" not in out


# ---- union / coalesce / choose ----------------------------------------------

def test_union_parents(g):
    out = (
        g.V().has("name", "hercules")
        .union(lambda t: t.out("father"), lambda t: t.out("mother"))
        .values("name").to_list()
    )
    assert names(out) == ["alcmene", "jupiter"]


def test_coalesce_first_nonempty_wins(g):
    # hercules has no pet -> falls through to father
    out = (
        g.V().has("name", "hercules")
        .coalesce(lambda t: t.out("pet"), lambda t: t.out("father"))
        .values("name").to_list()
    )
    assert out == ["jupiter"]
    # pluto HAS a pet -> first branch wins
    out = (
        g.V().has("name", "pluto")
        .coalesce(lambda t: t.out("pet"), lambda t: t.out("father"))
        .values("name").to_list()
    )
    assert out == ["cerberus"]


def test_optional_keeps_original_when_empty(g):
    out = (
        g.V().has("name", "hercules").optional_(lambda t: t.out("pet"))
        .values("name").to_list()
    )
    assert out == ["hercules"]


def test_choose_predicate_branches(g):
    # gods get their name; everything else its label
    out = (
        g.V().has("age", P.gt(100))
        .choose(
            lambda t: t.has_label("god"),
            lambda t: t.values("name"),
            lambda t: t.label_(),
        ).to_list()
    )
    assert names(out) == ["jupiter", "neptune", "pluto", "titan"]


def test_choose_value_predicate(g):
    out = (
        g.V().has_label("god").values("age")
        .choose(P.gte(4500), lambda t: t.is_(P.gte(4500)), lambda t: t)
        .to_list()
    )
    assert sorted(out) == [4000, 4500, 5000]


# ---- where(traversal) / not_ / is_ ------------------------------------------

def test_where_subtraversal_filter(g):
    out = g.V().where(lambda t: t.out("battled")).values("name").to_list()
    assert out == ["hercules"]


def test_not_subtraversal(g):
    monsters = (
        g.V().has_label("monster").not_(lambda t: t.in_("pet"))
        .values("name").to_list()
    )
    assert names(monsters) == ["hydra", "nemean"]  # cerberus is a pet


# ---- project / group / fold -------------------------------------------------

def test_project_with_by_modulators(g):
    row = (
        g.V().has("name", "hercules")
        .project("name", "battles")
        .by("name")
        .by(lambda t: t.out("battled").count_())
        .next()
    )
    assert row == {"name": "hercules", "battles": 3}


def test_group_by_label_collects_names(g):
    m = (
        g.V().has("age", P.gt(0))
        .group().by(lambda t: t.label_()).by("name").next()
    )
    assert names(m["god"]) == ["jupiter", "neptune", "pluto"]
    assert m["titan"] == ["saturn"]
    assert m["human"] == ["alcmene"]


def test_fold_unfold_roundtrip(g):
    folded = g.V().has_label("god").values("name").fold().next()
    assert names(folded) == ["jupiter", "neptune", "pluto"]
    out = (
        g.V().has_label("god").values("name").fold().unfold().to_list()
    )
    assert names(out) == ["jupiter", "neptune", "pluto"]


# ---- repeat/until/emit ------------------------------------------------------

def test_repeat_until_ancestor_root(g):
    # climb father edges until there is no further father -> saturn
    out = (
        g.V().has("name", "hercules")
        .repeat(
            lambda t: t.out("father"),
            until=lambda t: t.not_(lambda s: s.out("father")),
        ).values("name").to_list()
    )
    assert out == ["saturn"]


def test_repeat_emit_collects_intermediates(g):
    out = (
        g.V().has("name", "hercules")
        .repeat(lambda t: t.out("father"), times=2, emit=True)
        .values("name").to_list()
    )
    assert names(out) == ["jupiter", "saturn"]


def test_repeat_times_only_backcompat(g):
    out = (
        g.V().has("name", "hercules")
        .repeat(lambda t: t.out("father"), times=2).values("name").to_list()
    )
    assert out == ["saturn"]


def test_repeat_until_max_loops_guard(g):
    # brother edges cycle forever; max_loops bounds the walk
    out = (
        g.V().has("name", "jupiter")
        .repeat(
            lambda t: t.out("brother"),
            until=lambda t: t.has("name", "nobody"),
            max_loops=3,
        ).count()
    )
    assert out > 0  # exhausted loop bound, traversers exit


# ---- misc ---------------------------------------------------------------

def test_order_with_by_modulator(g):
    out = g.V().has_label("god").order().by("age", reverse=True).values("name").to_list()
    assert out == ["jupiter", "neptune", "pluto"]


def test_by_without_modulatable_step_raises(g):
    with pytest.raises(QueryError, match="by"):
        g.V().out("father").by("name")


def test_anonymous_traversal_cannot_execute(g):
    from janusgraph_tpu.core.traversal import GraphTraversal

    anon = GraphTraversal(g, None)
    with pytest.raises(QueryError):
        anon.to_list()


# ---- match() ----------------------------------------------------------------

def test_match_grandfather(g):
    from janusgraph_tpu.core.traversal import __

    rows = (
        g.V().has("name", "hercules")
        .match(
            __.as_("me").out("father").as_("dad"),
            __.as_("dad").out("father").as_("granddad"),
        )
        .select("granddad").by("name")
        .to_list()
    )
    assert rows == ["saturn"]


def test_match_existence_filter_pattern(g):
    from janusgraph_tpu.core.traversal import __

    # gods who both live somewhere and have a brother
    rows = (
        g.V().has_label("god")
        .match(
            __.as_("g").out("lives").as_("home"),
            __.as_("g").out("brother"),
        )
        .select("g").by("name")
        .dedup()
        .to_list()
    )
    assert sorted(rows) == ["jupiter", "neptune", "pluto"]


def test_match_binding_consistency(g):
    from janusgraph_tpu.core.traversal import __

    # 'brother of my brother' constrained back to an existing binding:
    # jupiter's brothers' brothers include jupiter himself
    rows = (
        g.V().has("name", "jupiter")
        .match(
            __.as_("a").out("brother").as_("b"),
            __.as_("b").out("brother").as_("a"),
        )
        .select("b").by("name")
        .dedup()
        .to_list()
    )
    assert sorted(rows) == ["neptune", "pluto"]


def test_match_out_of_order_patterns_solved_by_boundness(g):
    from janusgraph_tpu.core.traversal import __

    # first pattern's start is the incoming object; second listed pattern
    # references 'dad' before the pattern that binds it — the solver must
    # pick the bound-start pattern first
    rows = (
        g.V().has("name", "hercules")
        .match(
            __.as_("me").out("father").as_("dad"),
            __.as_("granddad").has("name", "saturn"),
            __.as_("dad").out("father").as_("granddad"),
        )
        .select("dad").by("name")
        .to_list()
    )
    assert rows == ["jupiter"]


def test_match_disconnected_raises(g):
    from janusgraph_tpu.core.traversal import __

    with pytest.raises(ValueError):
        g.V().has("name", "hercules").match(
            __.as_("me").out("father").as_("dad"),
            __.as_("stranger").out("lives").as_("where"),
        ).to_list()


def test_match_requires_as_start(g):
    from janusgraph_tpu.core.traversal import __

    with pytest.raises(ValueError):
        g.V().match(__.out("father")).to_list()


def test_match_pretagged_anchor(g):
    from janusgraph_tpu.core.traversal import __

    # the traverser arrives pre-tagged; the first listed pattern's start is
    # bound by a LATER pattern — the current object must NOT be force-bound
    rows = (
        g.V().has("name", "hercules").as_("me")
        .match(
            __.as_("dad").out("father").as_("granddad"),
            __.as_("me").out("father").as_("dad"),
        )
        .select("granddad").by("name")
        .to_list()
    )
    assert rows == ["saturn"]


# ---- side-effect + sampling steps -------------------------------------------

def test_aggregate_cap(g):
    rows = (
        g.V().has_label("god").values("name").aggregate("x")
        .cap("x").next()
    )
    assert sorted(rows) == ["jupiter", "neptune", "pluto"]


def test_store_is_aggregate(g):
    rows = g.V().has_label("titan").values("name").store("t").cap("t").next()
    assert rows == ["saturn"]


def test_aggregate_with_where_subtraversal(g):
    from janusgraph_tpu.core.traversal import P, __

    # 'gods except jupiter' via aggregate + where(neq tag) pattern analogue
    rows = (
        g.V().has("name", "jupiter").as_("j")
        .both("brother").where(P.neq("j"))
        .values("name").dedup().to_list()
    )
    assert sorted(rows) == ["neptune", "pluto"]


def test_tail_skip_sample_coin(g):
    names = g.V().has_label("god").values("name").order().to_list()
    assert g.V().has_label("god").values("name").order().tail(1).to_list() == names[-1:]
    assert g.V().has_label("god").values("name").order().skip(1).to_list() == names[1:]
    assert len(g.V().has_label("god").sample(2, seed=7).to_list()) == 2
    kept = g.V().has_label("god").coin(1.0, seed=7).to_list()
    assert len(kept) == 3
    assert g.V().has_label("god").coin(0.0, seed=7).to_list() == []


def test_aggregate_does_not_accumulate_across_runs(g):
    t = g.V().has_label("god").values("name").aggregate("x").cap("x")
    first = t.next()
    t2 = g.V().has_label("god").values("name").aggregate("x").cap("x")
    again = t2.next()
    assert len(first) == 3 and len(again) == 3


def test_where_within_tag_membership(g):
    """where(P.within/without(tags...)): each name is an as_() tag; the
    current object is tested against the BOUND objects (TinkerPop
    where-predicate semantics; was silently empty before)."""
    from janusgraph_tpu.core.traversal import P

    t = g  # the fixture IS the traversal source
    # jupiter's brothers joined with jupiter's father: father is NOT a
    # brother, so within('f') keeps nothing, without('f') keeps both
    got = (
        t.V().has("name", "jupiter").out("father").as_("f")
        .in_("father").out("brother")
        .where(P.without("f")).dedup().values("name").to_list()
    )
    assert sorted(got) == ["neptune", "pluto"]
    same = (
        t.V().has("name", "jupiter").as_("j").out("brother")
        .out("brother").where(P.within("j")).dedup()
        .values("name").to_list()
    )
    assert same == ["jupiter"]  # brother-of-brother includes jupiter
