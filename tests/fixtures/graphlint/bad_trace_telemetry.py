"""JG106: telemetry recording inside jit-traced code. Every call below
runs at TRACE time — the counter bumps once per compile instead of once
per superstep, and a span timing a traced body measures tracing, not
execution."""

import jax

from janusgraph_tpu.observability import span
from janusgraph_tpu.util.metrics import metrics


@jax.jit
def superstep(state):
    metrics.counter("olap.superstep").inc()  # expect: JG106
    with span("olap.superstep.body", step=0):  # expect: JG106
        out = state * 2.0
    metrics.timer("olap.superstep.wall").update(3)  # expect: JG106
    return out


def body(state):
    with metrics.time("olap.agg"):  # expect: JG106
        return state + 1.0


fn = jax.jit(body)
