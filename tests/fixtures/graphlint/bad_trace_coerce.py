"""JG101 fixture: Python coercion of / branching on traced values.

Never imported — parsed by graphlint only (tests/test_static_analysis.py).
"""
import jax
import jax.numpy as jnp


@jax.jit
def coerces(x, y):
    a = float(x)  # expect: JG101
    b = int(x + y)  # expect: JG101
    c = bool(y)  # expect: JG101
    return a + b + c


@jax.jit
def branches(x, flag):
    if x > 0:  # expect: JG101
        return x
    while flag:  # expect: JG101
        x = x - 1
    assert x >= 0  # expect: JG101
    return x


@jax.jit
def clean(x, w):
    # none of these may fire: static attrs, is-checks, identity on host vals
    if x.ndim == 3:
        x = x.sum(axis=-1)
    if w is not None:
        x = x * w
    return jnp.where(x > 0, x, 0.0)


def step(state, k):
    y = state * k
    if y.sum() > 0:  # expect: JG101
        return y
    return -y


_compiled = jax.jit(step)
