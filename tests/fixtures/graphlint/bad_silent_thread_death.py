"""JG112 fixture: background-thread run loops that die or swallow
silently.

A daemon thread's run loop that either has no broad except (the first
exception kills the thread with no record) or swallows broad exceptions
with a do-nothing handler (`except Exception: pass`) leaves every
consumer of the thread's output reading a stale ring that looks
healthy. The loop must RECORD the failure — flight event, log call,
counter, stored error — before dying or continuing.
"""

import threading


class NakedLoopBad:
    """No broad except at all: the first sample() exception kills the
    sampler silently."""

    def __init__(self):
        self._stop = threading.Event()

    def _loop(self):  # expect: JG112
        while not self._stop.wait(1.0):
            self.sample()

    def start(self):
        t = threading.Thread(target=self._loop, daemon=True)
        t.start()

    def sample(self):
        pass


class SwallowingLoopBad:
    """Broad except whose body is only pass: failures vanish, and a
    continuously-failing loop burns CPU invisibly forever."""

    def __init__(self):
        self._stop = threading.Event()

    def start(self):
        def _loop():
            while not self._stop.wait(1.0):
                try:
                    self.tick()
                except Exception:  # expect: JG112
                    pass

        threading.Thread(target=_loop, daemon=True).start()

    def tick(self):
        pass


class RecordingLoopGood:
    """Broad except that records before continuing: compliant."""

    def __init__(self, sink):
        self._stop = threading.Event()
        self._sink = sink

    def _loop(self):
        while not self._stop.wait(1.0):
            try:
                self.tick()
            except Exception as e:  # records: compliant
                self._sink(f"loop error: {e}")

    def start(self):
        threading.Thread(target=self._loop, daemon=True).start()

    def tick(self):
        pass


class StoringLoopGood:
    """Broad except that stores the error for later surfacing (the
    prefetch idiom): an assignment is a record, not a swallow."""

    def __init__(self):
        self._stop = threading.Event()
        self._error = None

    def _loop(self):
        while not self._stop.wait(1.0):
            try:
                self.tick()
            except Exception as e:  # surfaced on next read
                self._error = e

    def start(self):
        threading.Thread(target=self._loop, daemon=True).start()

    def tick(self):
        pass


class JoinedWorkerGood:
    """A joined (non-daemon) fork-join worker is exempt: its exceptions
    are the spawner's problem at join() time."""

    def run_partitions(self, parts):
        def worker(part):
            for item in part:
                self.process(item)

        threads = [
            threading.Thread(target=worker, args=(p,)) for p in parts
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    def process(self, item):
        pass


class BoundedPumpGood:
    """A daemon pump over a finite work list is fork-join shaped — its
    lifetime is bounded by its input, not a forever-loop — so it is
    exempt like a joined worker."""

    def start_pump(self, items, sink):
        def _pump():
            for item in items:
                sink(item)

        threading.Thread(target=_pump, daemon=True).start()


class NoLoopGood:
    """A one-shot daemon target without a loop is exempt — nothing runs
    long enough to be a lying sampler."""

    def fire_and_forget(self):
        threading.Thread(target=self.once, daemon=True).start()

    def once(self):
        self.process()

    def process(self):
        pass
