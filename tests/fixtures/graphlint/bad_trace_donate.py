"""JG104 fixture: donated buffer reused after the call (parse-only)."""
import jax


def run(step_fn, state, other):
    step = jax.jit(step_fn, donate_argnums=(0,))
    new_state = step(state, other)
    stale = state + 1  # expect: JG104
    return new_state, stale


def fine(step_fn, state, other):
    step = jax.jit(step_fn, donate_argnums=(0,))
    state = step(state, other)  # rebinding the name: not a reuse
    return state + other
