"""JG209 fixture: multi-hop adjacency expansion as nested per-vertex
store reads.

The row-wise 2-hop walk: the inner expansion issues one store round per
NEIGHBOR of the outer expansion — the shape the multiquery prefetch
batch and the OLAP spillover planner (olap/spillover.py) both exist to
retire.
"""
from janusgraph_tpu.core.codecs import Direction


def two_hop_rowwise(tx, vertices):
    out = []
    for v in vertices:
        for e in tx.get_edges(v, Direction.OUT, ("knows",)):
            w = e.other(v)
            for e2 in tx.get_edges(w, Direction.OUT, ("knows",)):  # expect: JG209
                out.append(e2.other(w))
    return out


def friends_of_friends(tx, seed):
    hits = []
    for e in tx.get_edges(seed, Direction.BOTH, ()):
        friend = e.other(seed)
        hits.extend(tx.adjacency_edges(friend, Direction.OUT, ("knows",), {seed.id}))  # expect: JG209
    return hits


def one_hop_is_fine(tx, vertices):
    # single-level per-vertex enumeration (the export shape): no nested
    # adjacency read, not flagged
    out = []
    for v in vertices:
        for e in tx.get_edges(v, Direction.OUT, ()):
            out.append(e)
    return out


def batched_is_fine(tx, vertices):
    # the engine's own path: ONE multiquery prefetch batch, then the
    # per-vertex reads hit the warmed row cache
    tx.prefetch(vertices, Direction.OUT, ("knows",))
    out = []
    for v in vertices:
        out.extend(tx.get_edges(v, Direction.OUT, ("knows",)))
    return out
