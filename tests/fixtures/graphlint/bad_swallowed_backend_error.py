"""JG204 fixture: except clauses that swallow backend errors.

A dropped TemporaryBackendError silently loses the retry/recovery path —
the caller sees success while the operation never happened.
"""

from janusgraph_tpu.exceptions import (
    BackendError,
    TemporaryBackendError,
    TemporaryLockingError,
)
from janusgraph_tpu.storage import backend_op


def swallow_temporary(op):
    try:
        return op()
    except TemporaryBackendError:  # expect: JG204
        return None


def swallow_in_tuple(op):
    try:
        return op()
    except (ValueError, BackendError) as e:  # expect: JG204
        print("ignoring", e)


def swallow_lock_error(op):
    try:
        return op()
    except TemporaryLockingError:  # expect: JG204
        pass


def ok_reraise(op):
    try:
        return op()
    except TemporaryBackendError:
        raise


def ok_wrap_and_raise(op):
    try:
        return op()
    except BackendError as e:
        raise RuntimeError("backend gone") from e


def ok_routed_through_guard(op):
    try:
        return op()
    except TemporaryBackendError:
        return backend_op.execute(op, max_time_s=1.0)


def ok_unrelated(op):
    try:
        return op()
    except ValueError:
        return None
