"""JG404 fixture: non-daemon threads with no join/stop path
(parse-only)."""
import threading


def fire_and_forget(fn):
    t = threading.Thread(target=fn)  # expect: JG404
    t.start()
    return t


def explicit_non_daemon(fn):
    t = threading.Thread(target=fn, daemon=False)  # expect: JG404
    t.start()
    return t


def forked_and_joined(fn):
    # structured fork-join in the same function: must NOT fire
    t = threading.Thread(target=fn)
    t.start()
    t.join()


def daemonized(fn):
    # reaped at interpreter exit: must NOT fire
    threading.Thread(target=fn, daemon=True).start()


class Leaky:
    def start(self):
        self._t = threading.Thread(target=self._loop)  # expect: JG404
        self._t.start()

    def _loop(self):
        pass


class Managed:
    # the enclosing class joins from a shutdown-family method: must NOT fire
    def start(self):
        self._t = threading.Thread(target=self._loop)
        self._t.start()

    def _loop(self):
        pass

    def stop(self):
        self._t.join(timeout=2.0)
