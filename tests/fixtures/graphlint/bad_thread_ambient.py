"""JG402 fixture: ambient contextvar scope read on a fresh pool thread
without an explicit handoff (parse-only)."""
from concurrent.futures import ThreadPoolExecutor

from janusgraph_tpu.core.deadline import remaining_ms
from janusgraph_tpu.observability import capture_scope, ledger_scope, span


def work(item):
    with span("work", item=item):  # expect: JG402
        return remaining_ms()  # expect: JG402


def work_scoped(item):
    # re-enters its own ambience: a fresh thread below this is fine
    with ledger_scope("work"):
        return remaining_ms()  # must NOT fire


def run_all(items):
    with ThreadPoolExecutor(max_workers=4) as pool:
        return list(pool.map(work, items))


def run_scoped(items):
    with ThreadPoolExecutor(max_workers=4) as pool:
        return list(pool.map(work_scoped, items))  # must NOT fire


def run_wrapped(items):
    # wrapped target: the handoff is explicit, no entry at all
    with ThreadPoolExecutor(max_workers=4) as pool:
        return list(pool.map(capture_scope(work), items))
