"""JG102 fixture: numpy calls inside jit bodies (parse-only fixture)."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def uses_numpy(x):
    y = np.asarray(x)  # expect: JG102
    z = np.concatenate([y, y])  # expect: JG102
    return jnp.asarray(z)


def host_side(x):
    # numpy on host (not a traced context): must NOT fire
    return np.asarray(x).sum()


def kernel_body(a, b):
    return a + np.float32(b)  # expect: JG102


_fn = jax.jit(kernel_body)
