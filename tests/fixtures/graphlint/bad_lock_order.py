"""JG202 fixture: inconsistent lock acquisition order (parse-only).

The cycle closes across two methods, so the exact report line depends on
edge ordering — the test asserts at file granularity (expect-file).
"""
# expect-file: JG202
import threading


class TwoLocks:
    def __init__(self):
        self._alpha_lock = threading.Lock()
        self._beta_lock = threading.Lock()

    def forward(self):
        with self._alpha_lock:
            with self._beta_lock:
                return 1

    def backward(self):
        with self._beta_lock:
            with self._alpha_lock:
                return 2
