"""JG105 fixture: host syncs inside jit bodies (parse-only fixture)."""
import jax


@jax.jit
def syncs(x, y):
    a = x.item()  # expect: JG105
    b = y.tolist()  # expect: JG105
    x.block_until_ready()  # expect: JG105
    c = jax.device_get(x)  # expect: JG105
    return a, b, c


def host(x):
    # host-side sync is fine: must NOT fire
    return x.item()
