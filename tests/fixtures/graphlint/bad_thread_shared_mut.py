"""JG401 fixture: attribute mutated from both a background thread and
the request path with no common lock (parse-only)."""
import threading


class Sampler:
    def __init__(self):
        self._lock = threading.Lock()
        self.windows = []
        self.seq = 0
        self.total = 0

    def start(self):
        t = threading.Thread(target=self._loop, daemon=True)
        t.start()

    def _loop(self):
        self.seq += 1  # expect: JG401
        self.windows.append(self.seq)  # expect: JG401
        with self._lock:
            self.total += 1  # guarded on BOTH sides: must NOT fire

    def reset(self):
        # the request-path side guards what the sampler thread does not
        with self._lock:
            self.seq = 0
            self.windows.clear()
            self.total = 0

    def rebuild(self):
        # receiver built fresh in this function: never shared, must NOT fire
        staging = []
        staging.append(1)
        scratch = Sampler()
        scratch.seq = 99
        return staging, scratch
