"""JG301 fixture: propagation-blocked halo-bin capacity tiers (parse-only).

The blocked exchange pads every (src-shard → dst-shard) merged-destination
bin to ONE pow2 capacity tier so a single lax.all_to_all split — and one
compiled executable — serves the whole mesh; a non-pow2 literal silently
breaks the uniform-split contract. 0 means auto-pick (halo_tier sizes the
tier from the widest pair) and is allowed.
"""
import numpy as np


def build_halo_plan(num_shards, widest):
    halo_cap = 100  # expect: JG301
    send_bin = 3 * 64  # expect: JG301
    good_cap = 256
    auto_halo_cap = 0  # auto-pick: allowed
    bins = np.zeros((num_shards, good_cap), dtype=np.float32)
    return halo_cap, send_bin, auto_halo_cap, bins


def exchange_bins(bins, exchange_tier=48):  # expect: JG301
    return bins[:, :exchange_tier]
