"""JG110 fixture: metric names built from non-literal parts.

The registry never evicts a metric name, so a name derived from an
unbounded value domain (vertex ids, raw query text, user input) grows
the registry, the /metrics exposition, and every history window without
bound — the label-cardinality explosion, caught at the construction
site.
"""

from janusgraph_tpu.observability import registry


def per_query_counter_bad(query_text):
    # raw query text: unbounded domain -> unbounded metric names
    registry.counter(f"query.{query_text}.count").inc()  # expect: JG110


def per_vertex_gauge_bad(vertex_id, rank):
    registry.set_gauge(f"rank.{vertex_id}", rank)  # expect: JG110


def concat_name_bad(user, ms):
    registry.timer("request.user." + user).update(ms)  # expect: JG110


def concat_chain_bad(prefix, shard):
    registry.histogram(prefix + ".shard." + shard).observe(1.0)  # expect: JG110


def nested_fstring_concat_bad(key):
    registry.gauge("cache." + f"{key}.hits").set(1.0)  # expect: JG110


def literal_name_good():
    # a literal name is always fine
    registry.counter("tx.commit").inc()


def literal_fstring_good():
    # an f-string WITHOUT interpolation builds nothing dynamic
    registry.counter(f"tx.commit").inc()  # noqa: F541


def constant_concat_good():
    # adjacent constants concatenated are still one literal domain
    registry.counter("server." + "admission.shed").inc()


def variable_passthrough_good(name):
    # a bare variable is not flagged: the rule targets the construction
    # idiom, and registry plumbing passes names through legitimately
    registry.counter(name).inc()


def bounded_digest_suppressed_good(digest):
    # the justified case: digests are bounded by the top-K-evicted price
    # book (metrics.digest-top-k), so the label set is finite
    # graphlint: disable=JG110 -- digest is the bounded, top-K-evicted price-book label
    registry.timer(f"server.request.digest.{digest}").update(1000)
