"""JG207 fixture: synchronous remote round-trips inside loops.

The one-op-per-round-trip shape over a remote store: every iteration
pays a full wire RTT (the PR 1 framing the pipelined mux retires).
"""
import struct


def fetch_all_sequentially(store_client, keys):
    results = {}
    for key in keys:
        payload, _fields = store_client._call_ledger(2, key)  # expect: JG207
        results[key] = payload
    return results


def probe_until_ready(conn):
    ready = False
    while not ready:
        status, payload, _sock = conn.request(9, b"")  # expect: JG207
        ready = payload == b"\x01"
    return ready


def write_rows(client, rows):
    for key, value in rows:
        client._call(4, struct.pack(">I", len(key)) + key + value)  # expect: JG207


def batched_is_fine(store, keys, slice_query):
    # the fix: ONE batched wire op for the whole key set
    return store.get_slice_multi(keys, slice_query, None)


def deferred_submission_is_fine(mux, items):
    futures = []
    for item in items:
        # deferred/pipelined submission: the call below returns a future,
        # no round-trip blocks the loop body
        futures.append(mux.submit(item))
    return [f.result() for f in futures]
