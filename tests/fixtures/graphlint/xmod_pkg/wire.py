"""Cross-module lock discipline, module 2: the blocking wire layer,
plus the other half of the lock-order cycle (parse-only)."""
import socket
import threading

_wire_lock = threading.Lock()


def fetch_remote(key):
    conn = socket.create_connection(("localhost", 9), 1.0)
    conn.sendall(key)
    return conn.recv(64)


def wire_lock_section():
    with _wire_lock:
        return 1


def locked_callback(reg):
    with _wire_lock:
        return reg.refresh("x")  # expect: JG403, JG202
