"""Cross-module lock discipline, module 1: holds its own lock while
calling into the wire layer (parse-only)."""
import threading

from .wire import fetch_remote, wire_lock_section


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self.cache = {}

    def refresh(self, key):
        with self._lock:
            value = fetch_remote(key)  # expect: JG403
            self.cache[key] = value
        return value

    def locked_section(self):
        # acquires the wire lock while holding ours: one half of the
        # cross-module lock-order cycle (the JG202 fires in wire.py)
        with self._lock:
            return wire_lock_section()

    def read(self, key):
        # lock released before the blocking call: must NOT fire
        with self._lock:
            cached = self.cache.get(key)
        if cached is None:
            cached = fetch_remote(key)
        return cached
