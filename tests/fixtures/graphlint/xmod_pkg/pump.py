"""Cross-module thread-entry mutation, module 2: the spawner. The
entry def runs on a fresh thread and reaches Buffer.collect in racy.py
(parse-only)."""
import threading

from .racy import Buffer


def pump_loop(buf, items):
    for item in items:
        buf.collect(item)


def start_pump(items):
    buf = Buffer()
    t = threading.Thread(target=pump_loop, args=(buf, items), daemon=True)
    t.start()
    return buf, t
