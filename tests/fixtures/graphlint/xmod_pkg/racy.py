"""Cross-module thread-entry mutation, module 1: the shared state. The
thread that mutates it is spawned in pump.py — reachability must cross
the module boundary for JG401 to connect the sites (parse-only)."""
import threading


class Buffer:
    def __init__(self):
        self._lock = threading.Lock()
        self.pending = []

    def collect(self, item):
        self.pending.append(item)  # expect: JG401

    def flush(self):
        with self._lock:
            drained = list(self.pending)
            self.pending.clear()
        return drained
