"""Two-module taint chain, module 2: helpers that are only traced
because kernels.gather_rows (another module) calls them from jit
(parse-only)."""
import numpy as np


def coerce_rows(rows):
    dense = np.asarray(rows)  # expect: JG102
    return dense * 2


def host_summary(table):
    # only ever called from host context: must NOT fire
    return np.asarray(table).sum()
