"""Cross-module graphlint fixtures: findings here only exist when the
whole package is analyzed together (taint chains, lock cycles, and
thread reachability all cross module boundaries)."""
