"""Two-module taint chain, module 1: the jit kernel. The helper it
calls lives in helpers.py — taint must cross the module boundary for
the numpy coercion there to be flagged (parse-only)."""
import jax
import jax.numpy as jnp

from .helpers import coerce_rows, host_summary


@jax.jit
def gather_rows(table, idx):
    rows = jnp.take(table, idx, axis=0)
    return coerce_rows(rows)


def report(table):
    # host context: calling the helper here must NOT taint it
    return host_summary(table)
