"""JG301 fixture: non-power-of-two capacity tiers (parse-only)."""

E_CAP = 3000  # expect: JG301
F_MIN = 1000  # expect: JG301
MAX_EDGES = 1 << 30  # pow2: must NOT fire


class Engine:
    E_MIN = 1 << 13  # pow2: must NOT fire
    ROW_CAP = 24  # expect: JG301


def pack(edges, max_capacity=10000):  # expect: JG301
    return edges[:max_capacity]


def expand(idx, E_cap=1 << 14):  # pow2 default: must NOT fire
    return idx[:E_cap]
