"""JG111 fixture: time.time() subtraction used as a duration.

The wall clock steps under NTP slew/step, so a wall-clock delta used as
a duration can go negative or jump by seconds — a negative "latency"
fed into a histogram, a backoff, or an SLO window silently corrupts the
statistic. Interval math must use time.monotonic()/perf_counter; wall
stamps subtracted for event stamping or cross-process OFFSET math are
exempt via `# graphlint: wallclock -- why`.
"""

import time


def direct_delta_bad():
    start = time.time()
    work()
    return time.time() - start  # expect: JG111


def stored_stamps_bad():
    t0 = time.time()
    work()
    t1 = time.time()
    elapsed = t1 - t0  # expect: JG111
    return elapsed


def deadline_remaining_bad(deadline_wall):
    # remaining-budget math against a wall deadline is still interval
    # math: an NTP step mid-request shrinks or inflates the budget
    return deadline_wall - time.time()  # expect: JG111


def monotonic_delta_good():
    start = time.monotonic()
    work()
    return time.monotonic() - start


def perf_counter_good():
    t0 = time.perf_counter()
    work()
    return time.perf_counter() - t0


def stamp_only_good():
    # a wall stamp recorded into an event is fine — only SUBTRACTION
    # as a duration is the hazard
    return {"ts": time.time()}


def offset_math_exempt_good(peer_wall, rtt_s):
    # cross-process clock-offset estimation subtracts wall STAMPS by
    # design (the rtt operand was measured on the monotonic clock)
    send_wall = time.time()
    # graphlint: wallclock -- NTP midpoint offset math over wall stamps, not a duration
    return peer_wall - (send_wall + rtt_s / 2.0)


def rebased_stamp_exempt_good(duration_ms):
    return time.time() - duration_ms / 1e3  # graphlint: wallclock -- reconstructs a wall START STAMP from a monotonic-measured duration


def work():
    pass
