"""JG301/JG302 fixture: hybrid-tail padding invariants (parse-only).

The hybrid format's tail path cuts hub edge ranges into static
`tail_chunk`-wide tiers; a non-pow2 chunk width breaks the aligned-subtree
bitwise contract, and a bare-literal sentinel fill drifts from the packer.
"""
import numpy as np


def build_tail(rows, degs, sentinel):
    tail_chunk = 100  # expect: JG301
    chunk_width = 3 * 64  # expect: JG301
    good_chunk = 128
    idx = np.full((rows, good_chunk), 4096, dtype=np.int32)  # expect: JG302
    ok = np.full((rows, good_chunk), sentinel, dtype=np.int32)
    return tail_chunk, chunk_width, idx, ok


def split_tail(starts, degs, t_chunk=48):  # expect: JG301
    return starts // t_chunk, degs % t_chunk
