"""JG103 fixture: retrace hazards (parse-only fixture)."""
import jax


def make(fn, axes):
    # non-constant static_argnums: retraces per distinct value
    return jax.jit(fn, static_argnums=axes)  # expect: JG103


def per_item(fns, xs):
    out = []
    for f, x in zip(fns, xs):
        g = jax.jit(f)  # expect: JG103
        out.append(g(x))
    return out


def fine(fn):
    # constant literal argnums: must NOT fire
    return jax.jit(fn, static_argnums=(0, 2))
