"""JG304 fixture: dense-tier feature-dim padding tiers (parse-only).

The dense-feature tier pads [n, d] blocks to power-of-two lane tiers so
the SDDMM tree-dot and the dense transform's tree-matmul contract over
complete adjacent-pair trees; a non-pow2 padded width breaks the bitwise
contract and mis-tiles the VPU/MXU lanes. The LOGICAL feature dim may be
anything — only the padded tier is constrained; 0 means auto-pick.
"""
import numpy as np


def pad_block(h, feature_dim=12):  # logical dim: any value is fine
    d_pad = 48  # expect: JG304
    out = np.zeros((h.shape[0], d_pad), dtype=np.float32)
    out[:, :feature_dim] = h
    return out


def build_program(feature_dim=100):
    dim_tier = 96  # expect: JG304
    feature_tier = 24  # expect: JG304
    auto_tier = 0  # 0 = pick from FEATURE_TIERS, allowed
    good_pad = 128
    return dim_tier, feature_tier, auto_tier, good_pad


def layer(h, w, gcn_dim_tier=20):  # expect: JG304
    lane_width = 40  # expect: JG304
    return h[:, :lane_width] @ w
