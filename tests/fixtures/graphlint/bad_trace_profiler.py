"""JG108: profiler / resource-ledger / cost-model calls inside jit-traced
code. Each call below fires at TRACE time — one ledger accrual (or digest
observation, or cost harvest) per compile instead of per execution, with
trace-time values."""

import jax

from janusgraph_tpu.observability.profiler import (
    accrue,
    current_ledger,
    digest_table,
    estimate_superstep_cost,
)


@jax.jit
def superstep(state):
    accrue(cells_read=1)  # expect: JG108
    digest_table.observe("ab12cd34", "V>out>count", 1.0)  # expect: JG108
    return state * 2.0


def body(state):
    ledger = current_ledger()  # expect: JG108
    ledger.add(bytes_read=4)  # expect: JG108
    cost = estimate_superstep_cost(8, 16)  # expect: JG108
    return state + cost["flops"]


fn = jax.jit(body)
