"""JG302 fixture: literal padding fills instead of the sentinel (parse-only)."""
import numpy as np


def pad_indices(rows, cap, sentinel):
    bad = np.full((rows, cap), 999, dtype=np.int32)  # expect: JG302
    also_bad = np.full((rows, cap), 1 << 20)  # expect: JG302
    good = np.full((rows, cap), sentinel, dtype=np.int32)
    zeros = np.full((rows, cap), 0, dtype=np.int32)  # identity: fine
    minus = np.full((rows, cap), -1, dtype=np.int32)  # conventional: fine
    floats = np.full((rows, cap), 3.5, dtype=np.float32)  # float: fine
    return bad, also_bad, good, zeros, minus, floats
