"""JG305 fixture: direct writes to checkpoint/manifest paths.

The committed name must only ever receive a complete file via os.replace;
open(path, "w") on it can tear mid-write.
"""

import json
import os
import tempfile


def save_state_bad(checkpoint_path, payload):
    with open(checkpoint_path, "w") as f:  # expect: JG305
        json.dump(payload, f)


def save_manifest_bad(run_dir, body):
    f = open(run_dir + "/manifest.json", "w")  # expect: JG305
    try:
        json.dump(body, f)
    finally:
        f.close()


def append_bad(path_to_ckpt_manifest, line):
    with open(path_to_ckpt_manifest, "a") as f:  # expect: JG305
        f.write(line)


def save_state_good(checkpoint_path, payload):
    # the atomic discipline: tmp sibling, then rename onto the committed
    # name — the tmp-suffixed intermediate is exempt by design
    d = os.path.dirname(os.path.abspath(checkpoint_path)) or "."
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".json.tmp")
    with os.fdopen(fd, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, checkpoint_path)


def read_good(checkpoint_path):
    # reads are harmless — only write modes commit torn bytes
    with open(checkpoint_path) as f:
        return json.load(f)
