"""JG301 fixture: delta-CSR overlay capacity tiers (parse-only).

The incremental delta-CSR overlay (olap/delta.py) pads its add/tombstone/
live lanes and the extra-vertex domain to pow2 capacity tiers so a single
compiled superstep executable serves every overlay that fits the tier; a
non-pow2 literal silently breaks the static-shape contract and the
tier-reuse economics. 0 means auto-pick (overlay_tier sizes the tier from
the lane) and is allowed.
"""
import numpy as np


def build_overlay_lanes(num_records):
    delta_cap = 100  # expect: JG301
    add_delta_bin = 3 * 16  # expect: JG301
    good_delta_cap = 256
    auto_delta_cap = 0  # auto-pick: allowed
    lanes = np.zeros((num_records, good_delta_cap), dtype=np.int32)
    return delta_cap, add_delta_bin, auto_delta_cap, lanes


def pad_overlay(records, overlay_tier=48):  # expect: JG301
    return records[:overlay_tier]
