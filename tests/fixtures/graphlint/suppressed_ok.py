"""Suppression fixture: every violation here is disabled (parse-only).

The test asserts graphlint reports ZERO live findings for this file, and
that --show-suppressed surfaces them as suppressed.
"""
import threading
import time

E_CAP = 3000  # graphlint: disable=JG301 -- test fixture: tier chosen by hardware table

_lock = threading.Lock()


def poll():
    with _lock:
        # graphlint: disable=JG203 -- test fixture: bounded 1ms wait by design
        time.sleep(0.001)
