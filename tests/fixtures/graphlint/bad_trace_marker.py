"""Marker fixture: `# graphlint: traced` opts helpers into the trace rules,
`# graphlint: host` opts helpers out of traced propagation (parse-only)."""
import jax
import numpy as np


# graphlint: traced
def marked_helper(xp, msgs):
    pad = np.zeros(4)  # expect: JG102
    return xp.asarray(pad) + msgs


# graphlint: host -- builds static numpy constants on purpose
def host_constants(k):
    return np.arange(k)  # must NOT fire: host-marked, numpy is the point


@jax.jit
def body(x):
    masks = host_constants(4)
    return x * masks
