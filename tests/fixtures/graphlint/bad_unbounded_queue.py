"""JG206 fixture: unbounded queues/deques in overload-sensitive code.

An unbounded buffer between a producer and a slower consumer converts
backpressure into memory growth — the serving path bounds every queue
(or sheds) instead.
"""

import collections
import queue
from collections import deque
from queue import Queue


def request_backlog_bad():
    return Queue()  # expect: JG206


def request_backlog_bad_qualified():
    return queue.Queue()  # expect: JG206


def backlog_explicitly_unbounded():
    # maxsize=0 is the explicitly-unbounded spelling, not a bound
    return Queue(maxsize=0)  # expect: JG206


def event_ring_bad():
    return deque()  # expect: JG206


def event_ring_bad_qualified():
    return collections.deque([1, 2, 3])  # expect: JG206


def event_ring_bad_none():
    return deque([], maxlen=None)  # expect: JG206


def request_backlog_good():
    # bounded: arrivals past the bound block (or the caller sheds)
    return Queue(maxsize=64)


def event_ring_good():
    # bounded ring, the in-tree idiom for every telemetry buffer
    return deque(maxlen=512)


def event_ring_good_positional():
    # deque's maxlen may ride as the second positional argument
    return deque([], 256)


def work_queue_structurally_bounded(n):
    # a BFS frontier enqueues each vertex at most once: the bound is the
    # vertex count itself — the justified-suppression case
    # graphlint: disable=JG206 -- each vertex enqueued at most once; bounded by n
    return deque(range(n))
