"""JG113 fixture: blocking / unaccounted queue puts in fan-out loops.

One producer iterating subscriber queues must never block on a slow
consumer (convoy) and must account every drop: an uncaught queue.Full
unwinds the loop mid-fan-out and later subscribers silently miss the
event; a swallowed one hides the drop.
"""

import queue
from queue import Full, Queue


def publish_blocking_bad(subscribers, event):
    for sub in subscribers:
        sub.q.put(event)  # expect: JG113


def publish_blocking_kw_true_bad(subscribers, event):
    for sub in subscribers:
        sub.q.put(event, block=True)  # expect: JG113


def publish_nowait_unguarded_bad(subscribers, event):
    for sub in subscribers:
        sub.q.put_nowait(event)  # expect: JG113


def publish_nowait_swallowed_bad(subscribers, event):
    for sub in subscribers:
        try:
            sub.q.put_nowait(event)  # expect: JG113
        except Full:
            pass  # drop hidden: nothing observable survives


def publish_nonblocking_unguarded_bad(subscribers, event):
    for sub in subscribers:
        sub.q.put(event, block=False)  # expect: JG113


def publish_wrong_guard_bad(subscribers, event):
    for sub in subscribers:
        try:
            sub.q.put_nowait(event)  # expect: JG113
        except ValueError:
            # catches the wrong thing: queue.Full still unwinds the loop
            subscribers.remove(sub)


def publish_accounted_good(subscribers, event, dropped):
    # the contract: never block, and a slow consumer costs itself data
    for sub in subscribers:
        try:
            sub.q.put_nowait(event)
        except Full:
            dropped[sub.name] = dropped.get(sub.name, 0) + 1


def publish_accounted_qualified_good(subscribers, event, recorder):
    for sub in subscribers:
        try:
            sub.q.put(event, block=False)
        except queue.Full:
            recorder.record("stream", "drop", subscriber=sub.name)


def publish_bounded_timeout_good(subscribers, event, log):
    # timeout bounds the wait (convoy priced), Full still accounted
    for sub in subscribers:
        try:
            sub.q.put(event, timeout=0.05)
        except Full:
            log.warning("dropped event for %s", sub.name)


def single_put_outside_loop_good(q, event):
    # not a fan-out: one queue, one put — backpressure is the point
    q = Queue(maxsize=8)
    q.put(event)
    return q
