"""JG303 fixture: data-dependent shapes inside jit bodies (parse-only)."""
import jax
import jax.numpy as jnp


@jax.jit
def dynamic(mask, x):
    idx = jnp.nonzero(mask)  # expect: JG303
    hits = jnp.where(mask)  # expect: JG303
    labels = jnp.unique(x)  # expect: JG303
    return idx, hits, labels


@jax.jit
def fixed(mask, x):
    # static-size forms: must NOT fire
    idx = jnp.nonzero(mask, size=128, fill_value=0)[0]
    sel = jnp.where(mask, x, 0.0)
    return idx, sel
