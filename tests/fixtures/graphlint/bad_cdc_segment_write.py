"""JG305 fixture (PR 18 extension): direct writes to CDC log paths.

Sealed CDC segments and the CDC manifest carry digest-embedded headers
and commit via tmp + rename (storage/cdc.py); open(path, "w") on a
``*-segment`` / ``*.cdc*`` name can tear mid-write and silently break
replay — the loss lands exactly where followers expect integrity.
"""

import json
import os
import tempfile


def seal_segment_bad(path, payload):
    with open(path + ".segment", "wb") as f:  # expect: JG305
        f.write(payload)


def seal_named_segment_bad(log_dir, seq, payload):
    f = open(log_dir + "/cdc-%06d.segment" % seq, "wb")  # expect: JG305
    try:
        f.write(payload)
    finally:
        f.close()


def write_cdc_manifest_bad(log_dir, body):
    with open(log_dir + "/manifest.cdc.json", "w") as f:  # expect: JG305
        json.dump(body, f)


def seal_segment_good(segment_path, payload):
    # the atomic discipline: tmp sibling in the target directory, then
    # rename onto the committed name — complete-or-absent, never torn
    d = os.path.dirname(os.path.abspath(segment_path)) or "."
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".segment.tmp")
    with os.fdopen(fd, "wb") as f:
        f.write(payload)
    os.replace(tmp, segment_path)


def append_tail_good(log_dir, frame):
    # the active tail is the uncommitted intermediate by DESIGN: its
    # .tmp name marks it torn-tolerant (recovery drops the torn suffix)
    with open(log_dir + "/cdc-tail.tmp", "ab") as f:
        f.write(frame)


def read_segment_good(segment_path):
    # reads are harmless — only write modes commit torn bytes
    with open(segment_path, "rb") as f:
        return f.read()
