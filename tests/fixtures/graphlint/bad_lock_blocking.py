"""JG203 fixture: blocking calls while holding a lock (parse-only)."""
import socket
import threading
import time


class Client:
    def __init__(self):
        self._lock = threading.Lock()
        self.sock = None

    def direct(self):
        with self._lock:
            time.sleep(0.5)  # expect: JG203

    def rpc_under_lock(self, payload):
        with self._lock:
            self.sock.sendall(payload)  # expect: JG203

    def transitive(self):
        with self._lock:
            return self._slow_io()  # expect: JG203

    def _slow_io(self):
        time.sleep(1.0)
        # timeout keeps this fixture JG208-clean: the smell under test is
        # the blocking call WHILE HOLDING A LOCK (JG203), not the socket
        return socket.create_connection(("localhost", 1), 1.0)

    def fine(self):
        with self._lock:
            value = self._fast()
        time.sleep(0.01)  # after release: must NOT fire
        return value

    def _fast(self):
        return 1
