"""JG208 fixture: outbound socket/HTTP calls without an explicit timeout.

A router probe, gossip round, or drain handoff that waits forever on a
dead or partitioned peer hangs the fleet thread that made it — every
remote hop bounds its wait (server/fleet.py does; this file shows the
shapes that don't).
"""

import socket
import urllib.request
from socket import create_connection
from urllib.request import urlopen

import requests


def probe_replica_bad(url):
    with urlopen(url) as resp:  # expect: JG208
        return resp.read()


def probe_replica_bad_qualified(url):
    with urllib.request.urlopen(url) as resp:  # expect: JG208
        return resp.read()


def probe_replica_explicitly_unbounded(url):
    # timeout=None is the explicitly-unbounded spelling, not a bound
    with urlopen(url, timeout=None) as resp:  # expect: JG208
        return resp.read()


def gossip_connect_bad(host, port):
    return create_connection((host, port))  # expect: JG208


def gossip_connect_bad_qualified(host, port):
    return socket.create_connection((host, port))  # expect: JG208


def handoff_bad(url, body):
    return requests.post(url, json=body)  # expect: JG208


def probe_replica_good(url):
    # bounded: a dead peer costs one timeout, never a hung prober
    with urlopen(url, timeout=2.0) as resp:
        return resp.read()


def gossip_connect_good(host, port):
    # deadline may ride the positional slot too
    return create_connection((host, port), 2.0)


def handoff_good(url, body):
    return requests.post(url, json=body, timeout=(2.0, 5.0))


def watchdog_owned_socket(host, port):
    # an outer watchdog provably tears this socket down: the justified-
    # suppression case
    # graphlint: disable=JG208 -- the epoch watchdog closes this socket after connect_timeout_s of silence
    return create_connection((host, port))
