"""JG107: structured-log / flight-recorder calls inside jit-traced code.
Each call below fires at TRACE time — one flight event (or log record)
per compile instead of per execution, stamped with trace-time values."""

import jax

from janusgraph_tpu.observability import flight_recorder, get_logger

logger = get_logger("olap")


@jax.jit
def superstep(state):
    flight_recorder.record("olap_resume", step=0)  # expect: JG107
    logger.info("superstep-start", step=0)  # expect: JG107
    return state * 2.0


def body(state):
    out = state + 1.0
    flight_recorder.dump(reason="mid-superstep")  # expect: JG107
    logger.error("superstep-failed")  # expect: JG107
    return out


fn = jax.jit(body)
