"""JG201 fixture: bare acquire without guaranteed release (parse-only)."""
import threading


class Pool:
    def __init__(self):
        self._lock = threading.Lock()

    def leaky(self):
        self._lock.acquire()  # expect: JG201
        self.do_work()
        self._lock.release()

    def fine_with(self):
        with self._lock:
            self.do_work()

    def fine_try_finally(self):
        self._lock.acquire()
        try:
            self.do_work()
        finally:
            self._lock.release()

    def fine_reacquire(self):
        with self._lock:
            self._lock.release()
            try:
                self.do_work()
            finally:
                self._lock.acquire()

    def do_work(self):
        pass
