"""Incremental CSR refresh (SURVEY.md §7 hard part (e)): OLTP mutations fold
into an existing CSR snapshot via the backend's mutation-epoch tracker —
only touched rows are re-read, no full store scan. Oracle: a fresh full
load_csr after the same mutations.
"""

import numpy as np
import pytest

from janusgraph_tpu.core.graph import open_graph
from janusgraph_tpu.olap.csr import load_csr, load_csr_snapshot, refresh_csr


def assert_csr_equal(a, b):
    """Structural equality up to within-row edge order (the order of edges
    inside one adjacency row depends on scan order and is not part of the
    CSR contract — aggregation monoids are order-independent)."""
    np.testing.assert_array_equal(a.vertex_ids, b.vertex_ids)
    np.testing.assert_array_equal(a.out_indptr, b.out_indptr)
    np.testing.assert_array_equal(a.in_indptr, b.in_indptr)

    def rows(indptr, arr):
        return [
            np.sort(arr[indptr[i]:indptr[i + 1]]).tolist()
            for i in range(len(indptr) - 1)
        ]

    assert rows(a.out_indptr, a.out_dst) == rows(b.out_indptr, b.out_dst)
    assert rows(a.in_indptr, a.in_src) == rows(b.in_indptr, b.in_src)
    if a.labels is not None and b.labels is not None:
        np.testing.assert_array_equal(a.labels, b.labels)


@pytest.fixture
def g():
    graph = open_graph({"schema.default": "auto"})
    yield graph
    graph.close()


def seed(g, n=30):
    tx = g.new_transaction()
    vs = [tx.add_vertex(name=f"v{i}") for i in range(n)]
    for i in range(n - 1):
        tx.add_edge(vs[i], "link", vs[i + 1])
    tx.commit()
    return vs


def test_refresh_noop_without_mutations(g):
    seed(g)
    csr, epoch = load_csr_snapshot(g)
    refreshed, e2 = refresh_csr(g, csr, epoch)
    assert refreshed is csr  # zero touched rows: same snapshot handed back
    assert e2 >= epoch


def test_refresh_after_edge_addition(g):
    vs = seed(g)
    csr, epoch = load_csr_snapshot(g)
    tx = g.new_transaction()
    tx.add_edge(tx.get_vertex(vs[0].id), "link", tx.get_vertex(vs[29].id))
    tx.commit()
    refreshed, _ = refresh_csr(g, csr, epoch)
    assert_csr_equal(refreshed, load_csr(g))
    assert refreshed.num_edges == csr.num_edges + 1


def test_refresh_after_vertex_addition(g):
    seed(g)
    csr, epoch = load_csr_snapshot(g)
    tx = g.new_transaction()
    nv = tx.add_vertex(name="new")
    tx.add_edge(nv, "link", tx.get_vertex(int(csr.vertex_ids[0])))
    tx.commit()
    refreshed, _ = refresh_csr(g, csr, epoch)
    assert_csr_equal(refreshed, load_csr(g))
    assert refreshed.num_vertices == csr.num_vertices + 1


def test_refresh_after_edge_removal(g):
    vs = seed(g)
    csr, epoch = load_csr_snapshot(g)
    tx = g.new_transaction()
    v0 = tx.get_vertex(vs[4].id)
    from janusgraph_tpu.core.codecs import Direction

    e = tx.get_edges(v0, Direction.OUT, ("link",))[0]
    tx.remove_edge(e)
    tx.commit()
    refreshed, _ = refresh_csr(g, csr, epoch)
    assert_csr_equal(refreshed, load_csr(g))
    assert refreshed.num_edges == csr.num_edges - 1


def test_refresh_after_vertex_removal(g):
    vs = seed(g)
    csr, epoch = load_csr_snapshot(g)
    tx = g.new_transaction()
    tx.remove_vertex(tx.get_vertex(vs[10].id))
    tx.commit()
    refreshed, _ = refresh_csr(g, csr, epoch)
    assert_csr_equal(refreshed, load_csr(g))
    assert refreshed.num_vertices == csr.num_vertices - 1


def test_refresh_chain_of_epochs(g):
    vs = seed(g)
    csr, epoch = load_csr_snapshot(g)
    for round_ in range(3):
        tx = g.new_transaction()
        nv = tx.add_vertex(name=f"r{round_}")
        tx.add_edge(nv, "link", tx.get_vertex(vs[round_].id))
        tx.commit()
        csr, epoch = refresh_csr(g, csr, epoch)
    assert_csr_equal(csr, load_csr(g))


def test_refresh_reads_only_touched_rows(g):
    vs = seed(g, n=50)
    csr, epoch = load_csr_snapshot(g)
    tx = g.new_transaction()
    tx.add_edge(tx.get_vertex(vs[7].id), "link", tx.get_vertex(vs[9].id))
    tx.commit()

    calls = []
    store = g.backend.edgestore
    orig = store.get_slice

    def spy(q, txh):
        calls.append(q.key)
        return orig(q, txh)

    store.get_slice = spy
    refresh_csr(g, csr, epoch)
    store.get_slice = orig
    # both endpoint rows were touched (OUT cell + IN cell), nothing else
    assert len(calls) == 2


def test_refresh_runs_olap(g):
    vs = seed(g)
    csr, epoch = load_csr_snapshot(g)
    tx = g.new_transaction()
    tx.add_edge(tx.get_vertex(vs[29].id), "link", tx.get_vertex(vs[0].id))
    tx.commit()
    csr2, _ = refresh_csr(g, csr, epoch)
    from janusgraph_tpu.olap.cpu_executor import CPUExecutor
    from janusgraph_tpu.olap.programs import PageRankProgram

    res = CPUExecutor(csr2).run(PageRankProgram(max_iterations=10))
    assert abs(res["rank"].sum() - 1.0) < 1e-6


def test_refresh_rejects_filtered_snapshot(g):
    seed(g)
    from janusgraph_tpu.olap.csr import load_csr_snapshot as snap

    csr, epoch = snap(g, edge_labels=["link"])
    tx = g.new_transaction()
    tx.add_vertex()
    tx.commit()
    with pytest.raises(ValueError, match="unfiltered"):
        refresh_csr(g, csr, epoch)


def test_refresh_tracker_overflow_falls_back_to_full_reload(g):
    vs = seed(g)
    csr, epoch = load_csr_snapshot(g)
    g.backend._epoch_track_limit = 4  # force overflow
    tx = g.new_transaction()
    for i in range(8):
        tx.add_edge(tx.get_vertex(vs[i].id), "link", tx.get_vertex(vs[i + 10].id))
        tx.commit()
        tx = g.new_transaction()
    refreshed, _ = refresh_csr(g, csr, epoch)
    assert_csr_equal(refreshed, load_csr(g))


def test_adjacency_self_loop_both_parity(g):
    from janusgraph_tpu.core.codecs import Direction

    tx = g.new_transaction()
    v = tx.add_vertex(name="loop")
    tx.add_edge(v, "link", v)
    pre = tx.adjacency_edges(tx.get_vertex(v.id) or v, Direction.BOTH,
                             ("link",), {v.id})
    assert len(pre) == 2  # uncommitted: two incidences, like get_edges
    tx.commit()
    tx2 = g.new_transaction()
    post = tx2.adjacency_edges(tx2.get_vertex(v.id), Direction.BOTH,
                               ("link",), {v.id})
    assert len(post) == 2  # committed: OUT + IN cells
