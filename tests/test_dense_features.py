"""ISSUE 7 gate: dense-feature vertex-program tier (fused SDDMM–SpMM).

Four contracts:

1. **Bitwise identity** — GCN forward and embedding-update runs are
   bit-for-bit equal across {TPUExecutor, CPUExecutor} x {ell, hybrid}
   for every message mode (copy / weighted / sddmm): the fused dense
   kernels reduce through the shared fixed adjacent-pair tree and every
   product feeding an add is fp-fenced, so no backend contraction (fused
   multiply-add) can change bits.
2. **Resumability** — a preempted dense run auto-resumes from the
   checkpoint and finishes bitwise-identical to a fault-free run, on
   both executors.
3. **Autotune** — decide() is deterministic in its new feature-dim
   input, records the padded tier, and the executor persists measured
   records across lifetimes (computer.autotune-persist).
4. **Observability** — run_info carries per-superstep `mxu_flops` /
   `mxu_utilization` and a run-level `mxu` block on both executors.
"""

from __future__ import annotations

import numpy as np
import pytest

from janusgraph_tpu.olap import csr_from_edges, run_on
from janusgraph_tpu.olap.cpu_executor import CPUExecutor
from janusgraph_tpu.olap.features.dense_program import (
    DenseVertexProgram,
    MessageMode,
)
from janusgraph_tpu.olap.features.kernels import (
    FEATURE_TIERS,
    ell_row_dsts,
    hybrid_row_dsts,
    pad_features,
    pick_feature_tier,
    sddmm_ell_aggregate,
    sddmm_hybrid_aggregate,
    sddmm_segment_aggregate,
    tree_dot,
    tree_matmul,
)
from janusgraph_tpu.olap.kernels import ELLPack, HybridPack
from janusgraph_tpu.olap.programs.embedding import EmbeddingUpdateProgram
from janusgraph_tpu.olap.programs.gcn import GCNForwardProgram
from janusgraph_tpu.olap.tpu_executor import TPUExecutor


def skewed_graph(n=400, m=6000, seed=3, weights=False):
    """Heavy-tailed destinations so the hybrid pack has a real tail."""
    rng = np.random.default_rng(seed)
    dst = (rng.zipf(1.35, m) % n).astype(np.int64)
    src = rng.integers(0, n, m).astype(np.int64)
    w = rng.uniform(0.25, 2.0, m).astype(np.float32) if weights else None
    return csr_from_edges(n, src, dst, w)


# ----------------------------------------------------------- kernel units
def test_pick_feature_tier_ladder():
    assert pick_feature_tier(1) == 8
    assert pick_feature_tier(8) == 8
    assert pick_feature_tier(9) == 16
    assert pick_feature_tier(512) == 512
    assert pick_feature_tier(513) == 1024  # past the ladder: next pow2
    assert pick_feature_tier(12, forced=64) == 64
    with pytest.raises(ValueError):
        pick_feature_tier(0)
    with pytest.raises(ValueError):
        pick_feature_tier(12, forced=48)  # not pow2
    with pytest.raises(ValueError):
        pick_feature_tier(100, forced=64)  # truncates the logical dim


def test_pad_features_zero_padding():
    h = np.ones((5, 12), dtype=np.float32)
    p = pad_features(h, 16)
    assert p.shape == (5, 16)
    np.testing.assert_array_equal(p[:, :12], h)
    np.testing.assert_array_equal(p[:, 12:], 0.0)
    with pytest.raises(ValueError):
        pad_features(h, 8)  # would truncate
    with pytest.raises(ValueError):
        pad_features(np.ones(5, dtype=np.float32), 8)  # not 2-D


def test_tree_dot_is_fixed_tree():
    """Chunked evaluation of aligned pow2 sub-ranges equals the subtree
    fold — the property that makes the SDDMM coefficient layout-blind."""
    rng = np.random.default_rng(0)
    a = rng.standard_normal((7, 64)).astype(np.float32)
    b = rng.standard_normal((7, 64)).astype(np.float32)
    whole = tree_dot(np, a, b)
    parts = np.stack(
        [
            tree_dot(np, a[:, j * 16:(j + 1) * 16], b[:, j * 16:(j + 1) * 16])
            for j in range(4)
        ],
        axis=1,
    )
    from janusgraph_tpu.olap.kernels import tree_reduce

    np.testing.assert_array_equal(tree_reduce(np, parts, "sum"), whole)


def test_tree_matmul_matches_reference_and_jit():
    """Deterministic tree contraction: close to the BLAS result, bitwise
    equal between the numpy path and the jitted path (the fp fence), and
    row-chunking never changes bits."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(2)
    h = rng.standard_normal((333, 32)).astype(np.float32)
    w = rng.standard_normal((32, 16)).astype(np.float32)
    out = tree_matmul(np, h, w)
    np.testing.assert_allclose(out, h @ w, rtol=1e-5, atol=1e-5)
    jout = np.asarray(jax.jit(lambda h, w: tree_matmul(jnp, h, w))(h, w))
    np.testing.assert_array_equal(out, jout)
    with pytest.raises(ValueError):
        tree_matmul(np, h[:, :20], w[:20])  # non-pow2 contraction width


def test_sddmm_aggregate_layouts_bitwise_and_vs_dense():
    """ELL and hybrid fused SDDMM+SpMM agree bit-for-bit (numpy and jit),
    and both match a dense reference to float tolerance."""
    import jax
    import jax.numpy as jnp

    g = skewed_graph()
    n = g.num_vertices
    src = g.in_src.astype(np.int64)
    dst = np.repeat(np.arange(n, dtype=np.int64), np.diff(g.in_indptr))
    rng = np.random.default_rng(1)
    msgs = rng.standard_normal((n, 16)).astype(np.float32)

    ell = ELLPack(src, dst, None, n)
    erows = ell_row_dsts(src, dst, n)
    hyb = HybridPack(g.in_src.astype(np.int64), dst, None, n,
                     hub_cutoff=16, tail_chunk=16)
    hrows = hybrid_row_dsts(src, dst, n, hub_cutoff=16, tail_chunk=16)

    a = sddmm_ell_aggregate(np, ell, erows, msgs)
    b = sddmm_hybrid_aggregate(np, hyb, hrows, msgs)
    np.testing.assert_array_equal(a, b)

    ell_d = ELLPack(src, dst, None, n).device_put(jnp)
    erows_d = [jnp.asarray(r) for r in erows]
    aj = np.asarray(
        jax.jit(lambda m: sddmm_ell_aggregate(jnp, ell_d, erows_d, m))(msgs)
    )
    np.testing.assert_array_equal(a, aj)
    hyb_d = HybridPack(src, dst, None, n,
                       hub_cutoff=16, tail_chunk=16).device_put(jnp)
    hrows_d = {k: [jnp.asarray(r) for r in v] for k, v in hrows.items()}
    bj = np.asarray(
        jax.jit(lambda m: sddmm_hybrid_aggregate(jnp, hyb_d, hrows_d, m))(msgs)
    )
    np.testing.assert_array_equal(a, bj)

    # dense reference: sum_e <h_src, h_dst> h_src per destination
    ref = np.zeros_like(msgs, dtype=np.float64)
    m64 = msgs.astype(np.float64)
    for s, d in zip(src, dst):
        ref[d] += m64[s] * float(np.dot(m64[s], m64[d]))
    np.testing.assert_allclose(a, ref, rtol=1e-3, atol=1e-4)

    seg = sddmm_segment_aggregate(np, msgs, src, dst, n)
    np.testing.assert_allclose(seg, ref, rtol=1e-3, atol=1e-4)


def test_sddmm_rejects_bad_shapes():
    msgs = np.ones((4, 12), dtype=np.float32)  # 12 not a lane tier
    with pytest.raises(ValueError):
        sddmm_segment_aggregate(
            np, msgs, np.zeros(2, np.int64), np.zeros(2, np.int64), 4
        )
    g = skewed_graph(n=32, m=100)
    n = g.num_vertices
    src = g.in_src.astype(np.int64)
    dst = np.repeat(np.arange(n, dtype=np.int64), np.diff(g.in_indptr))
    ell = ELLPack(src, dst, None, n)
    rows = ell_row_dsts(src, dst, n)
    ok = np.ones((n, 16), dtype=np.float32)
    with pytest.raises(ValueError):
        sddmm_ell_aggregate(np, ell, rows, ok, op="min")  # SUM-only
    with pytest.raises(ValueError):
        sddmm_ell_aggregate(np, ell, rows[:-1], ok)  # pack drift


# ---------------------------------------------- program-level constraints
def test_dense_program_validation():
    with pytest.raises(ValueError):
        GCNForwardProgram(attention=True, weighted=True)

    class BadSddmm(DenseVertexProgram):
        message_mode = MessageMode.SDDMM
        combiner = "min"

    with pytest.raises(ValueError):
        BadSddmm(feature_dim=8)

    p = GCNForwardProgram(feature_dim=12)
    assert p.d_pad == 16
    p.set_dim_tier(64)
    assert p.d_pad == 64
    assert p._w_stack.shape == (2, 64, 64)
    with pytest.raises(ValueError):
        EmbeddingUpdateProgram(mode="bogus")


def test_sddmm_undirected_rejected_on_both_executors():
    g = skewed_graph(n=64, m=400)
    p = EmbeddingUpdateProgram(feature_dim=8, max_iterations=1, mode="sddmm")
    p.undirected = True
    with pytest.raises(ValueError, match="in-CSR"):
        TPUExecutor(g, strategy="ell").run(p)
    with pytest.raises(ValueError, match="in-CSR"):
        CPUExecutor(g, strategy="ell").run(p)


# ------------------------------------------------- executor bitwise matrix
GCN_MODES = [
    ("copy", {}, False),
    ("attention", {"attention": True}, False),
    ("weighted", {"weighted": True}, True),
]
EMB_MODES = [
    ("copy", {"mode": MessageMode.COPY}, False),
    ("sddmm", {"mode": MessageMode.SDDMM}, False),
    ("weighted", {"mode": MessageMode.WEIGHTED}, True),
]


def _run_matrix(make, key, weights):
    g = skewed_graph(weights=weights)
    ref = np.asarray(TPUExecutor(g, strategy="ell").run(make())[key])
    runs = {
        "tpu-hybrid": TPUExecutor(
            g, strategy="hybrid", hub_cutoff=16, tail_chunk=16
        ).run(make())[key],
        "cpu-ell": CPUExecutor(g, strategy="ell").run(make())[key],
        "cpu-hybrid": CPUExecutor(g, strategy="hybrid").run(make())[key],
    }
    assert ref.dtype == np.float32
    for lbl, r in runs.items():
        np.testing.assert_array_equal(np.asarray(r), ref, err_msg=lbl)
    # the scalar per-edge loop is the independent semantic oracle
    oracle = CPUExecutor(g).run(make())[key]
    np.testing.assert_allclose(
        ref.astype(np.float64), oracle, rtol=1e-3, atol=1e-4,
        err_msg="scalar-oracle",
    )


@pytest.mark.parametrize(
    "name,kw,weights", GCN_MODES, ids=[m[0] for m in GCN_MODES]
)
def test_gcn_forward_bitwise_matrix(name, kw, weights):
    """2-layer GCN forward: device and CPU-oracle runs are bitwise equal
    on the ELL and hybrid formats, for every message mode."""
    _run_matrix(
        lambda: GCNForwardProgram(
            feature_dim=12, hidden_dim=12, out_dim=8, num_layers=2,
            seed=5, **kw
        ),
        "h", weights,
    )


@pytest.mark.parametrize(
    "name,kw,weights", EMB_MODES, ids=[m[0] for m in EMB_MODES]
)
def test_embedding_update_bitwise_matrix(name, kw, weights):
    """node2vec-style embedding update: same bitwise matrix, with the
    negative-sampling table as a dense side input."""
    _run_matrix(
        lambda: EmbeddingUpdateProgram(
            feature_dim=16, max_iterations=3, seed=9, **kw
        ),
        "emb", weights,
    )


def test_gcn_explicit_weights_and_activation():
    """User-provided layer weights land in the padded stacks and drive
    the output; identity activation and tanh accepted, junk rejected."""
    rng = np.random.default_rng(0)
    ws = [rng.standard_normal((6, 6)).astype(np.float32) for _ in range(2)]
    g = skewed_graph(n=64, m=500)
    p = GCNForwardProgram(
        feature_dim=6, hidden_dim=6, out_dim=6, num_layers=2,
        weights=ws, activation="identity",
    )
    assert p.d_pad == 8
    np.testing.assert_array_equal(p._w_stack[0, :6, :6], ws[0])
    out = TPUExecutor(g, strategy="ell").run(p)["h"]
    assert np.isfinite(np.asarray(out)).all()
    with pytest.raises(ValueError):
        GCNForwardProgram(weights=[np.ones((3, 3))] * 2, feature_dim=6)
    from janusgraph_tpu.olap.features.kernels import dense_transform

    with pytest.raises(ValueError):
        dense_transform(np, np.ones((2, 8), np.float32),
                        np.ones((8, 8), np.float32), activation="gelu")


# ------------------------------------------- checkpoint/preemption resume
@pytest.mark.parametrize("executor", ["cpu", "tpu"])
def test_preempted_gcn_resumes_bitwise_identical(executor, tmp_path):
    """A dense program preempted mid-run auto-resumes from its checkpoint
    and produces bitwise-identical final feature blocks."""
    from janusgraph_tpu.storage.faults import FaultPlan

    g = skewed_graph(n=128, m=1500)
    mk = lambda: GCNForwardProgram(  # noqa: E731
        feature_dim=12, hidden_dim=12, out_dim=8, num_layers=4, seed=5
    )
    baseline = run_on(g, mk(), executor)

    plan = FaultPlan(seed=77, preempt_superstep=2)
    faulted = run_on(
        g, mk(), executor,
        checkpoint_path=str(tmp_path / f"gcn_{executor}.npz"),
        checkpoint_every=1, fault_hook=plan.olap_hook,
    )
    assert any(e["kind"] == "superstep" for e in plan.journal)
    for key in baseline:
        assert baseline[key].dtype == faulted[key].dtype
        np.testing.assert_array_equal(baseline[key], faulted[key],
                                      err_msg=key)


# -------------------------------------------------- autotune: feature dim
def test_decide_feature_dim_deterministic_and_recorded():
    from janusgraph_tpu.olap.autotune import GraphStats, decide

    g = skewed_graph()
    stats = GraphStats.from_csr(g)
    d0 = decide(stats, "cpu")
    assert d0.feature_dim == 0 and d0.feature_tier is None
    d1 = decide(stats, "cpu", feature_dim=12)
    d2 = decide(stats, "cpu", feature_dim=12)
    assert d1 == d2
    assert d1.feature_dim == 12 and d1.feature_tier == 16
    assert d1.as_dict()["feature_tier"] == 16
    # the tier scales modeled message traffic
    assert d1.modeled_ms["ell"] > d0.modeled_ms["ell"]
    # the override pins the tier
    d3 = decide(stats, "cpu", overrides={"feature_dim_tier": 64},
                feature_dim=12)
    assert d3.feature_tier == 64


def test_executor_keys_decisions_by_feature_tier():
    """A dense run's decision is cached separately from scalar runs (the
    tier changes modeled bytes), and run_info records the feature tier."""
    g = skewed_graph()
    ex = TPUExecutor(g, strategy="auto")
    p = GCNForwardProgram(feature_dim=12, hidden_dim=12, out_dim=8,
                          num_layers=2)
    ex.run(p)
    info = ex.last_run_info
    assert info["autotune"]["feature_tier"] == 16
    assert (False, 16) in ex._autotune_decisions
    from janusgraph_tpu.olap.programs.pagerank import PageRankProgram

    ex.run(PageRankProgram(max_iterations=2))
    assert (False, 0) in ex._autotune_decisions
    assert ex.last_run_info["autotune"]["feature_tier"] is None


def test_forced_dim_tier_flows_from_executor():
    g = skewed_graph(n=64, m=500)
    p = GCNForwardProgram(feature_dim=12, hidden_dim=12, out_dim=8)
    ex = TPUExecutor(g, strategy="ell", features_dim_tier=32)
    out = ex.run(p)
    assert p.d_pad == 32
    assert np.asarray(out["h"]).shape == (64, 32)


# ------------------------------------------ autotune: measured persistence
def test_measured_record_roundtrip(tmp_path):
    from janusgraph_tpu.olap.autotune import load_measured, save_measured

    path = str(tmp_path / "m.json")
    assert load_measured(path) is None
    save_measured(path, {"strategy": "hybrid", "pad_ratio": 1.02,
                         "superstep_ms": 12.5})
    rec = load_measured(path)
    assert rec["pad_ratio"] == 1.02 and rec["superstep_ms"] == 12.5
    # unreadable/garbage files degrade to None, never raise
    with open(path, "w") as f:
        f.write("{not json")
    assert load_measured(path) is None
    save_measured(path, {"strategy": "x"})  # missing calibration fields
    assert load_measured(path) is None


def test_autotune_persists_across_executor_lifetimes(tmp_path):
    """The ROADMAP #2 leftover: a run with a checkpoint path serializes
    its measured record next to the checkpoint, and the NEXT executor
    lifetime's decision is calibrated by it (source=measured+model)."""
    from janusgraph_tpu.olap.autotune import load_measured
    from janusgraph_tpu.olap.programs.pagerank import PageRankProgram

    g = skewed_graph()
    ck = str(tmp_path / "pr.npz")
    ex1 = TPUExecutor(g, strategy="auto")
    ex1.run(PageRankProgram(max_iterations=3), checkpoint_path=ck,
            checkpoint_every=2)
    rec = load_measured(ck + ".autotune.json")
    assert rec is not None and rec["superstep_ms"] > 0

    ex2 = TPUExecutor(g, strategy="auto")
    ex2.run(PageRankProgram(max_iterations=2), checkpoint_path=ck,
            checkpoint_every=2)
    assert ex2.last_run_info["autotune"]["source"] == "measured+model"

    # config off: no record is written
    ck2 = str(tmp_path / "pr2.npz")
    ex3 = TPUExecutor(g, strategy="auto", autotune_persist=False)
    ex3.run(PageRankProgram(max_iterations=2), checkpoint_path=ck2,
            checkpoint_every=2)
    assert load_measured(ck2 + ".autotune.json") is None


# -------------------------------------------------------- mxu observability
def test_mxu_fields_in_run_info_both_executors():
    g = skewed_graph(n=128, m=1500)
    mk = lambda: GCNForwardProgram(  # noqa: E731
        feature_dim=12, hidden_dim=12, out_dim=8, num_layers=2
    )
    for ex, info in (
        (TPUExecutor(g, strategy="ell"), None),
        (CPUExecutor(g, strategy="ell"), None),
    ):
        ex.run(mk())
        info = ex.last_run_info
        mxu = info["mxu"]
        assert mxu["peak_mxu_flops"] > 0
        assert mxu["per_superstep_flops"] > 0
        assert mxu["mean_utilization"] is not None
        for r in info["superstep_records"]:
            assert r["mxu_flops"] > 0
            assert r["mxu_utilization"] is not None

    # scalar programs carry no mxu block
    from janusgraph_tpu.olap.programs.pagerank import PageRankProgram

    ex = TPUExecutor(g, strategy="ell")
    ex.run(PageRankProgram(max_iterations=2))
    assert "mxu" not in ex.last_run_info


def test_device_peaks_mxu_column():
    from janusgraph_tpu.observability.profiler import (
        configure_roofline,
        device_peaks,
    )

    for kind in ("TPU v4", "TPU v5e", "cpu"):
        peaks = device_peaks(kind)
        assert peaks["peak_mxu_flops"] > 0, kind
    try:
        configure_roofline(peak_mxu_flops=123.0)
        assert device_peaks("cpu")["peak_mxu_flops"] == 123.0
        assert device_peaks("cpu")["source"] == "config"
    finally:
        configure_roofline(peak_mxu_flops=0.0)


# ------------------------------------------------------- end-to-end submit
def _feature_graph(n=24, **cfg):
    from janusgraph_tpu.core.graph import JanusGraphTPU
    from janusgraph_tpu.storage.inmemory import InMemoryStoreManager

    g = JanusGraphTPU(
        {"ids.authority-wait-ms": 0.0, **cfg},
        store_manager=InMemoryStoreManager(),
    )
    tx = g.new_transaction()
    vs = [tx.add_vertex() for _ in range(n)]
    for i in range(n):
        tx.add_edge(vs[i], "knows", vs[(i + 1) % n])
        if i % 3 == 0:
            tx.add_edge(vs[i], "knows", vs[0])
        if i % 4 == 1:
            tx.add_edge(vs[i], "knows", vs[(i * i + 2) % n])
    tx.commit()
    return g


@pytest.mark.parametrize("executor", ["cpu", "tpu"])
def test_gcn_and_embedding_through_submit(executor):
    """The acceptance path: both shipped dense programs run end-to-end
    through GraphComputer.submit() on both executors, honoring the
    computer.features-* keys (forced 32-lane tier here)."""
    g = _feature_graph(**{"computer.features-dim-tier": 32})
    try:
        res = g.compute(executor=executor).program(
            GCNForwardProgram(feature_dim=12, hidden_dim=12, out_dim=8)
        ).submit()
        h = np.asarray(res.states["h"])
        assert h.shape == (res.csr.num_vertices, 32)
        assert np.isfinite(h).all()
        # padded columns stay zero through the layers
        np.testing.assert_array_equal(h[:, 12:], 0.0)

        res2 = g.compute(executor=executor).program(
            EmbeddingUpdateProgram(feature_dim=16, max_iterations=2)
        ).submit()
        emb = np.asarray(res2.states["emb"])
        assert emb.shape == (res2.csr.num_vertices, 32)
        assert np.isfinite(emb).all()
    finally:
        g.close()


def test_native_matmul_config_flows_to_program():
    g = _feature_graph(**{"computer.features-native-matmul": True})
    try:
        p = GCNForwardProgram(feature_dim=8, hidden_dim=8, out_dim=8)
        assert p.native_matmul is False
        res = g.compute(executor="cpu").program(p).submit()
        assert p.native_matmul is True
        assert np.isfinite(np.asarray(res.states["h"])).all()
    finally:
        g.close()
