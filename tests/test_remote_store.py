"""Remote KCVS adapter: a real networked storage backend (the cql/hbase
analogue — reference: CQLStoreManager.java speaking a wire protocol to
remote storage nodes). The KCVS contract itself runs via the conftest
'remote' parameterization; here: retry/backoff on transient failures
(reference: BackendOperation.java), a multi-node remote cluster (sharded
composite behind the server), graph end-to-end over the socket, and
streamed scans.
"""

import threading
import time

import numpy as np
import pytest

from janusgraph_tpu.exceptions import TemporaryBackendError
from janusgraph_tpu.storage import backend_op
from janusgraph_tpu.storage.inmemory import InMemoryStoreManager
from janusgraph_tpu.storage.kcvs import KeySliceQuery, SliceQuery
from janusgraph_tpu.storage.remote import RemoteStoreManager, RemoteStoreServer


@pytest.fixture
def served():
    server = RemoteStoreServer(InMemoryStoreManager()).start()
    host, port = server.address
    client = RemoteStoreManager(host, port)
    yield server, client
    client.close()
    server.stop()


def test_features_marked_distributed(served):
    _server, client = served
    f = client.features
    assert f.distributed
    assert not f.transactional  # autocommit per request (the CQL model)
    assert f.multi_query and f.batch_mutation


def test_roundtrip_and_multi_slice(served):
    _server, client = served
    store = client.open_database("edgestore")
    tx = client.begin_transaction()
    store.mutate(b"k1", [(b"a", b"1"), (b"b", b"2")], [], tx)
    store.mutate(b"k2", [(b"a", b"3")], [], tx)
    got = store.get_slice(KeySliceQuery(b"k1", SliceQuery(b"a", b"c")), tx)
    assert got == [(b"a", b"1"), (b"b", b"2")]
    multi = store.get_slice_multi([b"k1", b"k2"], SliceQuery(b"a", b"b"), tx)
    assert multi[b"k1"] == [(b"a", b"1")]
    assert multi[b"k2"] == [(b"a", b"3")]


def test_scan_streams_rows(served):
    _server, client = served
    store = client.open_database("edgestore")
    tx = client.begin_transaction()
    for i in range(500):
        store.mutate(f"k{i:04d}".encode(), [(b"c", str(i).encode())], [], tx)
    rows = list(store.get_keys(SliceQuery(b"", None), tx))
    assert len(rows) == 500
    assert rows[0][0] == b"k0000"  # in-memory backend scans ordered


def test_retry_replays_transient_failures(served):
    server, client = served
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise TemporaryBackendError("transient")
        return "ok"

    assert backend_op.execute(flaky, max_time_s=5.0) == "ok"
    assert calls["n"] == 3


def test_client_survives_server_restart(served):
    server, client = served
    store = client.open_database("edgestore")
    tx = client.begin_transaction()
    store.mutate(b"k", [(b"a", b"1")], [], tx)
    host, port = server.address
    backing = server.manager
    server.stop()

    # restart on the same port shortly after; the client's retry/backoff
    # redials and replays (reference: BackendOperation temporary-failure
    # replay semantics)
    def restart():
        time.sleep(0.5)
        RemoteStoreServer(backing, host=host, port=port).start()

    threading.Thread(target=restart, daemon=True).start()
    got = store.get_slice(KeySliceQuery(b"k", SliceQuery(b"", None)), tx)
    assert got == [(b"a", b"1")]


def test_multi_node_remote_cluster():
    """Sharded composite behind the server = N-node remote cluster."""
    from janusgraph_tpu.storage.sharded_store import ShardedStoreManager

    server = RemoteStoreServer(ShardedStoreManager(num_nodes=3)).start()
    host, port = server.address
    client = RemoteStoreManager(host, port)
    store = client.open_database("edgestore")
    tx = client.begin_transaction()
    for i in range(64):
        store.mutate(f"key{i}".encode(), [(b"c", b"v")], [], tx)
    rows = list(store.get_keys(SliceQuery(b"", None), tx))
    assert len(rows) == 64
    # node failure surfaces as a temporary error over the wire
    server.manager.fail_node(1)
    with pytest.raises(TemporaryBackendError):
        for i in range(64):
            store.get_slice(
                KeySliceQuery(f"key{i}".encode(), SliceQuery(b"", None)), tx
            )
    server.manager.heal_node(1)
    client.close()
    server.stop()


def test_graph_end_to_end_over_remote():
    from janusgraph_tpu.core.graph import open_graph
    from janusgraph_tpu.core import gods
    from janusgraph_tpu.olap.csr import load_csr

    server = RemoteStoreServer(InMemoryStoreManager()).start()
    host, port = server.address
    g = open_graph({
        "storage.backend": "remote",
        "storage.hostname": host,
        "storage.port": port,
    })
    gods.load(g)
    t = g.traversal()
    assert t.V().has("name", "hercules").out("father").values("name").to_list() == ["jupiter"]
    csr = load_csr(g)
    assert csr.num_vertices == 12 and csr.num_edges == 17
    g.close()
    server.stop()


def test_cli_storage_server_cross_process(tmp_path):
    """Two real processes: `janusgraph_tpu storage-server` serving a
    persistent store, a graph client over the wire (the reference's
    deployment shape: storage nodes + graph instances)."""
    import re
    import subprocess
    import sys

    from pathlib import Path

    repo_root = Path(__file__).resolve().parents[1]
    proc = subprocess.Popen(
        [sys.executable, "-m", "janusgraph_tpu", "storage-server",
         "--port", "0", "--directory", str(tmp_path / "srv")],
        stdout=subprocess.PIPE, text=True, cwd=str(repo_root),
    )
    try:
        line = proc.stdout.readline()
        m = re.search(r"listening on ([\d.]+):(\d+)", line)
        assert m, line
        host, port = m.group(1), int(m.group(2))
        from janusgraph_tpu.core.graph import open_graph

        g = open_graph({
            "storage.backend": "remote",
            "storage.hostname": host,
            "storage.port": port,
        })
        tx = g.new_transaction()
        v = tx.add_vertex(name="networked")
        tx.commit()
        assert g.traversal().V().has("name", "networked").count() == 1
        g.close()
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def test_parallel_multi_slice_fanout():
    """storage.parallel-backend-ops: big multi-key reads split across the
    connection pool (reference: Backend.java:215-221 client-side executor);
    results identical to the serial path."""
    from janusgraph_tpu.storage.inmemory import InMemoryStoreManager
    from janusgraph_tpu.storage.kcvs import SliceQuery

    backing = InMemoryStoreManager()
    server = RemoteStoreServer(backing).start()
    host, port = server.address
    par = RemoteStoreManager(host, port, pool_size=4, parallel_ops=True)
    ser = RemoteStoreManager(host, port, pool_size=4, parallel_ops=False)
    try:
        store_w = par.open_database("t")
        txh = par.begin_transaction()
        keys = [f"k{i:03}".encode() for i in range(40)]
        for i, k in enumerate(keys):
            store_w.mutate(k, [(b"c", str(i).encode())], [], txh)
        q = SliceQuery()
        a = par.open_database("t").get_slice_multi(keys, q, txh)
        b = ser.open_database("t").get_slice_multi(keys, q, txh)
        assert set(a) == set(keys)
        for k in keys:
            assert list(a[k]) == list(b[k])
        assert list(a[keys[3]])  # non-empty payload round-tripped
    finally:
        par.close()
        ser.close()
        server.stop()


def test_trace_stitches_across_remote_store(served):
    """ISSUE 4: ops issued inside a client span produce server-side spans
    sharing the client's trace_id, parented under the client span — one
    stitched trace across the storage wire."""
    from janusgraph_tpu.observability import tracer

    _server, client = served
    store = client.open_database("edgestore")
    tx = client.begin_transaction()
    with tracer.span("client.root") as root:
        store.mutate(b"k", [(b"a", b"1")], [], tx)
        store.get_slice(KeySliceQuery(b"k", SliceQuery(b"", None)), tx)
        list(store.get_keys(SliceQuery(b"", None), tx))  # streamed scan too
    deadline = time.monotonic() + 2.0
    while time.monotonic() < deadline:
        remote_spans = [
            r for r in tracer.find_trace(root.trace_id)
            if r.name.startswith("store.remote.")
        ]
        if len(remote_spans) >= 3:
            break
        time.sleep(0.01)
    names = {s.name for s in remote_spans}
    assert {"store.remote.mutate", "store.remote.getSlice",
            "store.remote.scanAll"} <= names, names
    # every server-side span is a child of the CLIENT's span, same trace
    for s in remote_spans:
        assert s.trace_id == root.trace_id
        assert s.parent_span_id == root.span_id
    # and the ids round-trip through the JSON exposition surface
    d = remote_spans[0].to_dict()
    assert d["trace_id"] == f"{root.trace_id:016x}"
    assert d["parent_span_id"] == f"{root.span_id:016x}"


def test_trace_degrades_against_old_featured_server():
    """ISSUE 4 acceptance: a new client against an old-featured server
    (no trace bit in _OP_FEATURES) interoperates byte-compatibly and
    degrades to unstitched spans — no flagged frames are ever sent."""
    from janusgraph_tpu.observability import tracer

    server = RemoteStoreServer(
        InMemoryStoreManager(), trace_propagation=False
    ).start()
    client = RemoteStoreManager(*server.address)
    try:
        store = client.open_database("edgestore")
        tx = client.begin_transaction()
        with tracer.span("client.old-server") as root:
            store.mutate(b"k", [(b"a", b"1")], [], tx)
            got = store.get_slice(
                KeySliceQuery(b"k", SliceQuery(b"", None)), tx
            )
        assert got == [(b"a", b"1")]  # the op itself is unaffected
        assert client._remote_trace is False  # negotiated OFF
        assert not [
            r for r in tracer.find_trace(root.trace_id)
            if r.name.startswith("store.remote.")
        ]
    finally:
        client.close()
        server.stop()


def test_old_client_against_new_server_interoperates(served):
    """The other direction of the mixed pair: a client that never sets the
    trace flag (trace_propagation=False — byte-identical frames to a
    pre-trace client) against a new server."""
    from janusgraph_tpu.observability import tracer

    server, _ = served
    host, port = server.address
    old_client = RemoteStoreManager(host, port, trace_propagation=False)
    try:
        store = old_client.open_database("edgestore")
        tx = old_client.begin_transaction()
        with tracer.span("client.legacy") as root:
            store.mutate(b"lk", [(b"a", b"1")], [], tx)
            got = store.get_slice(
                KeySliceQuery(b"lk", SliceQuery(b"", None)), tx
            )
        assert got == [(b"a", b"1")]
        # the server saw unflagged frames: nothing joined the trace
        assert not [
            r for r in tracer.find_trace(root.trace_id)
            if r.name.startswith("store.remote.")
        ]
        # the negotiated feature bit is still visible to capable clients
        assert old_client.features.multi_query
    finally:
        old_client.close()


def test_remote_graph_refuses_pickle_by_default():
    """attributes.allow-pickle=auto disables object-pickle frames over a
    remote store (a compromised peer must not execute code on read) but
    keeps them for in-process graphs; 'true' opts back in explicitly."""
    from janusgraph_tpu.core.graph import open_graph
    from janusgraph_tpu.core.attributes import SerializerError

    server = RemoteStoreServer(InMemoryStoreManager()).start()
    host, port = server.address
    remote_cfg = {
        "storage.backend": "remote",
        "storage.hostname": host,
        "storage.port": port,
    }
    g = open_graph(remote_cfg)
    assert not g.serializer.allow_pickle
    with pytest.raises(SerializerError, match="fallback disabled"):
        g.serializer.write_object(complex(1, 2))
    g.close()

    g = open_graph(dict(remote_cfg, **{"attributes.allow-pickle": "true"}))
    assert g.serializer.allow_pickle
    g.close()
    server.stop()

    local = open_graph({"storage.backend": "inmemory"})
    assert local.serializer.allow_pickle
    local.close()
    forced = open_graph({
        "storage.backend": "inmemory", "attributes.allow-pickle": "false",
    })
    assert not forced.serializer.allow_pickle
    forced.close()
