"""Frontier-compacted SSSP/BFS (olap/frontier.py).

Parity gates: the frontier path must be step-for-step identical to both the
scalar CPU oracle and the dense TPU BSP path (frontier="off") — the
ShortestPath special-case must never change results, only cost (reference
model: FulgoraGraphComputer.java:249-253 special-casing ShortestPath).
"""

import numpy as np
import pytest

from janusgraph_tpu.olap import csr_from_edges
from janusgraph_tpu.olap.cpu_executor import CPUExecutor
from janusgraph_tpu.olap.frontier import _tier
from janusgraph_tpu.olap.programs import ShortestPathProgram
from janusgraph_tpu.olap.programs.shortest_path import reconstruct_path
from janusgraph_tpu.olap.tpu_executor import TPUExecutor


def random_graph(n=300, m=1500, seed=7, weights=False):
    rng = np.random.default_rng(seed)
    src = rng.integers(0, n, m).astype(np.int32)
    dst = rng.integers(0, n, m).astype(np.int32)
    w = rng.uniform(0.5, 2.0, m).astype(np.float32) if weights else None
    return csr_from_edges(n, src, dst, w)


def supernode_graph(n=400, seed=3):
    """Vertex 0 is a hub (out-edges to everyone), many deg-0 vertices, plus
    a sparse tail — exercises deg-0 collapse in the ownership scatter and
    uneven tier growth."""
    rng = np.random.default_rng(seed)
    hub_dst = np.arange(1, n // 2, dtype=np.int32)
    hub_src = np.zeros(len(hub_dst), dtype=np.int32)
    tail_src = rng.integers(1, n // 2, 200).astype(np.int32)
    tail_dst = rng.integers(0, n, 200).astype(np.int32)
    return csr_from_edges(
        n,
        np.concatenate([hub_src, tail_src]),
        np.concatenate([hub_dst, tail_dst]),
    )


def _dist(res):
    d = np.asarray(res["distance"])
    return np.where(d >= 1e17, np.inf, d)


CASES = [
    ("bfs", dict()),
    ("bfs_undirected", dict(undirected=True)),
    ("weighted", dict(weighted=True)),
    ("weighted_undirected", dict(weighted=True, undirected=True)),
    ("tracked", dict(track_paths=True)),
]


@pytest.mark.parametrize("name,kw", CASES, ids=[c[0] for c in CASES])
def test_frontier_matches_cpu_and_dense(name, kw):
    csr = random_graph(weights=kw.get("weighted", False))
    prog = lambda: ShortestPathProgram(seed_index=0, **kw)  # noqa: E731
    cpu = CPUExecutor(csr).run(prog())
    dense = TPUExecutor(csr, frontier="off").run(prog())
    ex = TPUExecutor(csr)
    assert ex._frontier_eligible(prog(), "auto")
    sparse = ex.run(prog())
    np.testing.assert_allclose(_dist(sparse), _dist(cpu), rtol=1e-6)
    np.testing.assert_allclose(_dist(sparse), _dist(dense), rtol=1e-6)
    if "predecessor" in sparse:
        np.testing.assert_array_equal(
            sparse["predecessor"], dense["predecessor"]
        )


def test_frontier_supernode_deg0():
    csr = supernode_graph()
    prog = lambda: ShortestPathProgram(seed_index=0)  # noqa: E731
    cpu = CPUExecutor(csr).run(prog())
    sparse = TPUExecutor(csr).run(prog())
    np.testing.assert_allclose(_dist(sparse), _dist(cpu), rtol=1e-6)


def test_per_run_frontier_override():
    """One executor serves both paths: run(frontier='off') forces dense."""
    csr = random_graph(n=80, m=300)
    ex = TPUExecutor(csr)
    sparse = ex.run(ShortestPathProgram(seed_index=0))
    dense = ex.run(ShortestPathProgram(seed_index=0), frontier="off")
    np.testing.assert_allclose(_dist(sparse), _dist(dense), rtol=1e-6)


@pytest.mark.parametrize("max_iter", [0, 1, 2, 3])
def test_frontier_step_parity_at_cutoff(max_iter):
    """Per-superstep parity, not just fixpoint parity: truncated runs must
    agree with the dense path at every intermediate hop."""
    csr = random_graph(n=120, m=500, seed=11)
    mk = lambda: ShortestPathProgram(seed_index=0, max_iterations=max_iter)  # noqa: E731
    dense = TPUExecutor(csr, frontier="off").run(mk())
    sparse = TPUExecutor(csr).run(mk())
    np.testing.assert_allclose(_dist(sparse), _dist(dense), rtol=1e-6)


def test_frontier_weighted_cutoff_parity():
    csr = random_graph(n=120, m=500, seed=13, weights=True)
    for it in (1, 2, 4):
        mk = lambda: ShortestPathProgram(  # noqa: E731
            seed_index=5, weighted=True, max_iterations=it
        )
        dense = TPUExecutor(csr, frontier="off").run(mk())
        sparse = TPUExecutor(csr).run(mk())
        np.testing.assert_allclose(_dist(sparse), _dist(dense), rtol=1e-6)


def test_frontier_path_reconstruction():
    csr = random_graph(n=150, m=700, seed=19)
    res = TPUExecutor(csr).run(
        ShortestPathProgram(seed_index=0, track_paths=True)
    )
    dist = _dist(res)
    reached = [v for v in range(csr.num_vertices) if np.isfinite(dist[v])]
    assert len(reached) > 1
    for v in reached[:20]:
        path = reconstruct_path(res, v)
        assert path is not None and path[0] == 0 and path[-1] == v
        assert len(path) == int(dist[v]) + 1
        # every hop is a real edge
        for a, b in zip(path, path[1:]):
            row = csr.out_dst[csr.out_indptr[a]:csr.out_indptr[a + 1]]
            assert b in row.tolist()


def test_frontier_line_graph_many_hops():
    """Tiny frontier (1 vertex) for many hops — the compaction sweet spot;
    also crosses tier boundaries as the hop index grows."""
    n = 40
    src = np.arange(n - 1, dtype=np.int32)
    dst = np.arange(1, n, dtype=np.int32)
    csr = csr_from_edges(n, src, dst)
    res = TPUExecutor(csr).run(ShortestPathProgram(seed_index=0))
    np.testing.assert_allclose(_dist(res), np.arange(n, dtype=np.float32))


def test_frontier_isolated_seed_and_empty_graph():
    csr = csr_from_edges(5, np.zeros(0, np.int32), np.zeros(0, np.int32))
    res = TPUExecutor(csr).run(ShortestPathProgram(seed_index=2))
    d = _dist(res)
    assert d[2] == 0 and np.all(np.isinf(np.delete(d, 2)))


def test_frontier_off_and_subclass_fall_back_dense():
    csr = random_graph(n=50, m=200)
    ex = TPUExecutor(csr, frontier="off")
    assert ex._frontier_cfg == "off"

    class Custom(ShortestPathProgram):
        pass

    # subclasses may override message/apply — never special-case them
    assert not TPUExecutor(csr)._frontier_eligible(Custom(seed_index=0), "auto")


def test_tier_ladder():
    assert _tier(1, 1 << 10, 1 << 20) == 1 << 10
    assert _tier((1 << 10) + 1, 1 << 10, 1 << 20) == 1 << 12
    assert _tier(1 << 19, 1 << 10, 1 << 20) == 1 << 20
    # hi below the pow-4 ladder: clamps to hi (callers ensure hi >= need)
    assert _tier(100, 1 << 10, 500) == 500


# --------------------------------------------------------- frontier CC
def test_frontier_cc_auto_heuristic():
    """Under 'auto', small-graph CC keeps the fused dense path (host-RTT
    per frontier superstep would dominate); 'always' forces frontier."""
    from janusgraph_tpu.olap.programs import ConnectedComponentsProgram

    small = random_graph(n=50, m=120)
    ex = TPUExecutor(small)
    assert not ex._frontier_eligible(ConnectedComponentsProgram(), "auto")
    assert ex._frontier_eligible(ConnectedComponentsProgram(), "always")
    # BFS keeps frontier at every size
    assert ex._frontier_eligible(ShortestPathProgram(seed_index=0), "auto")


def test_frontier_cc_matches_cpu_and_dense():
    from janusgraph_tpu.olap.programs import ConnectedComponentsProgram

    csr = random_graph(n=250, m=600, seed=23)
    mk = lambda: ConnectedComponentsProgram(max_iterations=100)  # noqa: E731
    cpu = CPUExecutor(csr).run(mk())
    dense = TPUExecutor(csr, frontier="off").run(mk())
    ex = TPUExecutor(csr, frontier="always")
    assert ex._frontier_eligible(mk(), "always")
    sparse = ex.run(mk())
    np.testing.assert_array_equal(
        np.asarray(sparse["component"]), np.asarray(cpu["component"])
    )
    np.testing.assert_array_equal(
        np.asarray(sparse["component"]), np.asarray(dense["component"])
    )


def test_frontier_cc_step_cutoff_parity():
    from janusgraph_tpu.olap.programs import ConnectedComponentsProgram

    csr = random_graph(n=120, m=260, seed=29)
    for it in (1, 2, 3):
        mk = lambda: ConnectedComponentsProgram(max_iterations=it)  # noqa: E731
        dense = TPUExecutor(csr, frontier="off").run(mk())
        sparse = TPUExecutor(csr, frontier="always").run(mk())
        np.testing.assert_array_equal(
            np.asarray(sparse["component"]), np.asarray(dense["component"])
        )


def test_frontier_cc_disconnected_and_isolated():
    from janusgraph_tpu.olap.programs import ConnectedComponentsProgram

    # two chains + isolated vertices
    src = np.array([0, 1, 5, 6], np.int32)
    dst = np.array([1, 2, 6, 7], np.int32)
    csr = csr_from_edges(10, src, dst)
    res = TPUExecutor(csr, frontier="always").run(ConnectedComponentsProgram())
    comp = np.asarray(res["component"])
    assert comp[0] == comp[1] == comp[2] == 0
    assert comp[5] == comp[6] == comp[7] == 5
    for iso in (3, 4, 8, 9):
        assert comp[iso] == iso


def test_frontier_cc_on_ldbc_proxy():
    from janusgraph_tpu.olap.generators import ldbc_snb_csr
    from janusgraph_tpu.olap.programs import ConnectedComponentsProgram

    csr = ldbc_snb_csr(11)
    mk = lambda: ConnectedComponentsProgram(max_iterations=64)  # noqa: E731
    sparse = TPUExecutor(csr, frontier="always").run(mk())
    cpu = CPUExecutor(csr).run(mk())
    np.testing.assert_array_equal(
        np.asarray(sparse["component"]), np.asarray(cpu["component"])
    )


def test_frontier_fuzz_vs_dense():
    """Property sweep: random graphs x seeds x cutoffs — the frontier path
    must match the dense path everywhere, not just on the curated cases."""
    from janusgraph_tpu.olap.programs import ConnectedComponentsProgram

    rng = np.random.default_rng(101)
    for trial in range(6):
        n = int(rng.integers(20, 400))
        m = int(rng.integers(0, 6 * n))
        weights = bool(rng.integers(0, 2))
        csr = csr_from_edges(
            n,
            rng.integers(0, n, m).astype(np.int32),
            rng.integers(0, n, m).astype(np.int32),
            rng.uniform(0.1, 3.0, m).astype(np.float32) if weights else None,
        )
        seed = int(rng.integers(0, n))
        it = int(rng.integers(1, 12))
        und = bool(rng.integers(0, 2))
        mk = lambda: ShortestPathProgram(  # noqa: B023,E731
            seed_index=seed, weighted=weights, undirected=und,
            max_iterations=it,
        )
        dense = TPUExecutor(csr, frontier="off").run(mk())
        sparse = TPUExecutor(csr, frontier="always").run(mk())
        np.testing.assert_allclose(
            _dist(sparse), _dist(dense), rtol=1e-6,
            err_msg=f"trial={trial} n={n} m={m} w={weights} und={und} it={it}",
        )
        cc_d = TPUExecutor(csr, frontier="off").run(
            ConnectedComponentsProgram(max_iterations=64)
        )
        cc_s = TPUExecutor(csr, frontier="always").run(
            ConnectedComponentsProgram(max_iterations=64)
        )
        np.testing.assert_array_equal(
            np.asarray(cc_s["component"]), np.asarray(cc_d["component"]),
            err_msg=f"cc trial={trial} n={n} m={m}",
        )


def test_frontier_always_refuses_checkpointing(tmp_path):
    csr = random_graph(n=50, m=200)
    ex = TPUExecutor(csr, frontier="always")
    with pytest.raises(ValueError, match="checkpoint"):
        ex.run(
            ShortestPathProgram(seed_index=0),
            checkpoint_path=str(tmp_path / "ck"),
            checkpoint_every=2,
        )
    # auto quietly uses the (checkpointable) dense path
    res = TPUExecutor(csr).run(
        ShortestPathProgram(seed_index=0),
        checkpoint_path=str(tmp_path / "ck2"),
        checkpoint_every=2,
    )
    assert "distance" in res


def test_last_run_info_records_paths_and_tiers():
    csr = random_graph(n=200, m=900, seed=31)
    ex = TPUExecutor(csr)
    ex.run(ShortestPathProgram(seed_index=0, max_iterations=4))
    info = ex.last_run_info
    assert info["path"] == "frontier"
    assert 1 <= info["supersteps"] <= 4
    assert info["tiers"][0]["frontier"] == 1  # hop 0: the seed alone
    assert all(t["E_cap"] >= t["edges"] for t in info["tiers"])
    from janusgraph_tpu.olap.programs import PageRankProgram

    ex.run(PageRankProgram(max_iterations=5, tol=0.0))
    assert ex.last_run_info["path"] == "fused"
    assert ex.last_run_info["supersteps"] == 5
