"""ConsistencyModifier (reference: core/schema/ConsistencyModifier.java,
ManagementSystem.setConsistency): LOCK serializes concurrent writers of a
type via the consistent-key locker with expected-value checks; FORK turns
in-place edge updates into delete + re-add under a fresh relation id.
Two JanusGraphTPU instances over ONE store manager stand in for two
cluster nodes (SURVEY.md §4's multi-node-without-a-cluster technique)."""

import pytest

from janusgraph_tpu.core.codecs import Consistency
from janusgraph_tpu.core.graph import open_graph
from janusgraph_tpu.exceptions import SchemaViolationError
from janusgraph_tpu.storage.inmemory import InMemoryStoreManager


def test_consistency_roundtrip_and_validation():
    g = open_graph()
    g.management().make_property_key("serial", int)
    g.management().make_edge_label("follows")
    g.management().set_consistency("serial", Consistency.LOCK)
    g.management().set_consistency("follows", Consistency.FORK)
    assert g.management().get_consistency("serial") is Consistency.LOCK
    assert g.management().get_consistency("follows") is Consistency.FORK
    with pytest.raises(SchemaViolationError):
        g.management().set_consistency("serial", Consistency.FORK)
    g.close()


def test_consistency_persists_across_reopen():
    mgr = InMemoryStoreManager()
    g = open_graph(store_manager=mgr)
    g.management().make_property_key("serial", int)
    g.management().set_consistency("serial", Consistency.LOCK)
    g.close()
    g2 = open_graph(store_manager=mgr)
    assert g2.management().get_consistency("serial") is Consistency.LOCK
    g2.close()


def test_lock_consistency_detects_concurrent_write():
    mgr = InMemoryStoreManager()
    g1 = open_graph(store_manager=mgr)
    g1.management().make_property_key("serial", int)
    g1.management().set_consistency("serial", Consistency.LOCK)
    tx = g1.new_transaction()
    v = tx.add_vertex()
    v.property("serial", 1)
    tx.commit()

    g2 = open_graph(store_manager=mgr)
    # both instances read then write the same LOCK-consistency property
    tx1 = g1.new_transaction()
    tx2 = g2.new_transaction()
    v1 = tx1.get_vertex(v.id)
    v2 = tx2.get_vertex(v.id)
    v1.property("serial", 2)
    v2.property("serial", 3)
    tx1.commit()  # first writer wins
    with pytest.raises(Exception):
        tx2.commit()  # claim/expected-value must reject the stale writer
    g3 = open_graph(store_manager=mgr)
    tx3 = g3.new_transaction()
    assert tx3.get_vertex(v.id).value("serial") == 2
    for g in (g1, g2, g3):
        g.close()


def test_lock_consistency_sequential_commits_ok():
    mgr = InMemoryStoreManager()
    g = open_graph(store_manager=mgr)
    g.management().make_property_key("serial", int)
    g.management().set_consistency("serial", Consistency.LOCK)
    tx = g.new_transaction()
    v = tx.add_vertex()
    v.property("serial", 1)
    tx.commit()
    for i in (2, 3, 4):
        txi = g.new_transaction()
        txi.get_vertex(v.id).property("serial", i)
        txi.commit()
    assert g.new_transaction().get_vertex(v.id).value("serial") == 4
    g.close()


def _edge_between(tx, out_id, label):
    from janusgraph_tpu.core.codecs import Direction

    [e] = tx.get_vertex(out_id).edges(Direction.OUT, label)
    return e


def test_fork_edge_update_takes_new_relation_id():
    g = open_graph()
    mgmt = g.management()
    mgmt.make_property_key("since", int)
    mgmt.make_edge_label("follows")
    mgmt.set_consistency("follows", Consistency.FORK)
    tx = g.new_transaction()
    a, b = tx.add_vertex(), tx.add_vertex()
    e = tx.add_edge(a, "follows", b, since=1)
    tx.commit()
    old_id = e.id

    tx2 = g.new_transaction()
    e2 = _edge_between(tx2, a.id, "follows")
    ne = tx2.set_edge_property(e2, "since", 2)
    assert ne.id != old_id  # forked: fresh relation id
    tx2.commit()

    tx3 = g.new_transaction()
    e3 = _edge_between(tx3, a.id, "follows")
    assert e3.value("since") == 2 and e3.id == ne.id
    g.close()


def test_default_edge_update_keeps_relation_id():
    g = open_graph()
    mgmt = g.management()
    mgmt.make_property_key("since", int)
    mgmt.make_edge_label("knows")
    tx = g.new_transaction()
    a, b = tx.add_vertex(), tx.add_vertex()
    e = tx.add_edge(a, "knows", b, since=1)
    tx.commit()

    tx2 = g.new_transaction()
    e2 = _edge_between(tx2, a.id, "knows")
    ne = tx2.set_edge_property(e2, "since", 2)
    assert ne.id == e.id  # in-place semantics
    tx2.commit()

    tx3 = g.new_transaction()
    e3 = _edge_between(tx3, a.id, "knows")
    assert e3.value("since") == 2 and e3.id == e.id
    g.close()


def test_chained_updates_through_stale_handle():
    """Repeated set_property through the ORIGINAL edge handle must compose:
    the handle forwards to its live replacement (found by review: the
    second update previously rebuilt from the stale property map)."""
    g = open_graph()
    mgmt = g.management()
    mgmt.make_property_key("a", int)
    mgmt.make_property_key("b", int)
    mgmt.make_edge_label("knows")
    tx = g.new_transaction()
    u, w = tx.add_vertex(), tx.add_vertex()
    tx.add_edge(u, "knows", w)
    tx.commit()

    tx2 = g.new_transaction()
    from janusgraph_tpu.core.codecs import Direction

    [e2] = tx2.get_vertex(u.id).edges(Direction.OUT, "knows")
    e2.set_property("a", 1)
    e2.set_property("b", 2)  # via the now-stale original handle
    tx2.commit()

    tx3 = g.new_transaction()
    [e3] = tx3.get_vertex(u.id).edges(Direction.OUT, "knows")
    assert e3.value("a") == 1 and e3.value("b") == 2
    g.close()


def test_lock_consistency_over_remote_backend():
    """The distributed story end-to-end: two graph instances whose shared
    state lives behind the networked KCVS server — lock claims, expected
    values, and the data cells all ride the wire (reference analogue: two
    JanusGraph nodes on one Cassandra cluster using consistent-key
    locking)."""
    from janusgraph_tpu.storage.inmemory import InMemoryStoreManager
    from janusgraph_tpu.storage.remote import (
        RemoteStoreManager,
        RemoteStoreServer,
    )

    server = RemoteStoreServer(InMemoryStoreManager()).start()
    try:
        host, port = server.address
        g1 = open_graph(store_manager=RemoteStoreManager(host=host, port=port))
        g1.management().make_property_key("serial", int)
        g1.management().set_consistency("serial", Consistency.LOCK)
        tx = g1.new_transaction()
        v = tx.add_vertex()
        v.property("serial", 1)
        tx.commit()

        g2 = open_graph(store_manager=RemoteStoreManager(host=host, port=port))
        tx1, tx2 = g1.new_transaction(), g2.new_transaction()
        tx1.get_vertex(v.id).property("serial", 2)
        tx2.get_vertex(v.id).property("serial", 3)
        tx1.commit()
        with pytest.raises(Exception):
            tx2.commit()
        g3 = open_graph(store_manager=RemoteStoreManager(host=host, port=port))
        assert g3.new_transaction().get_vertex(v.id).value("serial") == 2
        for g in (g1, g2, g3):
            g.close()
    finally:
        server.stop()
