"""Fault-tolerant multi-chip OLAP (ISSUE 8): sharded checkpoints,
distributed chaos, cross-shard auto-resume.

The acceptance contract: any injected shard-level failure — shard
preemption mid-superstep, collective timeout, dropped halo batch, a torn
manifest or slice write — costs at most one checkpoint interval, and the
auto-resumed run finishes with final state BITWISE-identical to a
fault-free run on the same executor/format. Fast cases here are tier-1;
the full soak is marked ``slow``.
"""

import json
import os

import numpy as np
import pytest

from janusgraph_tpu.olap import csr_from_edges
from janusgraph_tpu.olap.cpu_executor import CPUExecutor
from janusgraph_tpu.olap.programs import PageRankProgram, ShortestPathProgram
from janusgraph_tpu.olap.sharded_checkpoint import (
    load_sharded_checkpoint,
    save_sharded_checkpoint,
    shard_ranges,
)
from janusgraph_tpu.parallel import ShardedExecutor
from janusgraph_tpu.storage.faults import FaultPlan


def random_graph(n=150, m=600, seed=13):
    rng = np.random.default_rng(seed)
    src = rng.integers(0, n, m).astype(np.int32)
    dst = rng.integers(0, n, m).astype(np.int32)
    return csr_from_edges(n, src, dst)


@pytest.fixture(scope="module")
def mesh8():
    import jax
    from jax.sharding import Mesh

    devices = np.array(jax.devices()[:8])
    assert len(devices) == 8, "conftest must provide 8 virtual devices"
    return Mesh(devices, ("p",))


def _pagerank(iters=10):
    return PageRankProgram(max_iterations=iters, tol=0.0)


def _bitwise_equal(a, b):
    assert set(a) == set(b)
    for k in a:
        assert np.array_equal(np.asarray(a[k]), np.asarray(b[k])), k


# ---------------------------------------------------------------- format
def test_sharded_checkpoint_roundtrip(tmp_path):
    d = str(tmp_path / "ck")
    state = {
        "x": np.arange(23, dtype=np.float32),
        "y": np.arange(23, dtype=np.float64) * 0.5,
    }
    save_sharded_checkpoint(d, state, {"m": 3.5}, 7, num_shards=4)
    loaded = load_sharded_checkpoint(d)
    assert loaded is not None
    lstate, lmem, steps = loaded
    assert steps == 7 and lmem == {"m": 3.5}
    for k in state:
        assert np.array_equal(lstate[k], state[k])
        assert lstate[k].dtype == state[k].dtype
    # slice layout on disk matches the contiguous-range convention
    ranges = shard_ranges(23, 4)
    assert ranges[0][0] == 0 and ranges[-1][1] == 23
    assert all(
        os.path.exists(os.path.join(d, f"shard-{s}.npz")) for s in range(4)
    )


def test_manifest_torn_write_falls_back_to_prev(tmp_path):
    from janusgraph_tpu.observability import registry

    d = str(tmp_path / "ck")
    st1 = {"x": np.arange(10, dtype=np.float32)}
    st2 = {"x": np.arange(10, dtype=np.float32) * 2}
    save_sharded_checkpoint(d, st1, {"m": 1.0}, 2, num_shards=4)
    save_sharded_checkpoint(d, st2, {"m": 2.0}, 4, num_shards=4)
    before = registry.get_count("olap.checkpoint.manifest_fallback")
    with open(os.path.join(d, "manifest.json"), "r+b") as f:
        f.truncate(17)  # the torn write
    lstate, lmem, steps = load_sharded_checkpoint(d)
    assert steps == 2 and lmem == {"m": 1.0}
    assert np.array_equal(lstate["x"], st1["x"])
    assert registry.get_count("olap.checkpoint.manifest_fallback") == before + 1


def test_torn_slice_write_falls_back(tmp_path):
    d = str(tmp_path / "ck")
    st1 = {"x": np.arange(12, dtype=np.float32)}
    st2 = {"x": np.arange(12, dtype=np.float32) + 100.0}
    save_sharded_checkpoint(d, st1, {}, 2, num_shards=3)
    save_sharded_checkpoint(d, st2, {}, 4, num_shards=3)
    # tear ONE slice of the newest checkpoint: its digest no longer
    # matches the manifest, so the whole checkpoint must roll back one
    # interval (slice .prev twins still carry the older manifest's bytes)
    with open(os.path.join(d, "shard-1.npz"), "r+b") as f:
        f.truncate(9)
    lstate, _m, steps = load_sharded_checkpoint(d)
    assert steps == 2
    assert np.array_equal(lstate["x"], st1["x"])


def test_manifest_digest_rejects_edit(tmp_path):
    d = str(tmp_path / "ck")
    save_sharded_checkpoint(
        d, {"x": np.ones(4, np.float32)}, {}, 1, num_shards=2
    )
    mpath = os.path.join(d, "manifest.json")
    with open(mpath) as f:
        body = json.load(f)
    body["steps"] = 999  # tampered field, stale digest
    import tempfile

    fd, tmp = tempfile.mkstemp(dir=d, suffix=".json.tmp")
    with os.fdopen(fd, "w") as f:
        json.dump(body, f)
    os.replace(tmp, mpath)
    assert load_sharded_checkpoint(d) is None  # no .prev exists either


# ------------------------------------------------- cross-shard auto-resume
@pytest.mark.parametrize("exchange,agg", [
    ("a2a", "ell"), ("a2a", "segment"),
    ("blocked", "ell"), ("blocked", "segment"),
])
def test_shard_preempt_resume_bitwise_sharded(mesh8, tmp_path, exchange, agg):
    g = random_graph()
    base_ex = ShardedExecutor(g, mesh=mesh8, exchange=exchange, agg=agg)
    base = base_ex.run(
        _pagerank(), fused=False, checkpoint_every=3,
        shard_checkpoint_dir=str(tmp_path / "base"),
    )
    plan = FaultPlan(seed=21, shard_preempt_superstep=5)
    ex = ShardedExecutor(g, mesh=mesh8, exchange=exchange, agg=agg)
    out = ex.run(
        _pagerank(), fused=False, checkpoint_every=3,
        shard_checkpoint_dir=str(tmp_path / "chaos"),
        fault_hook=plan.sharded_hook,
    )
    _bitwise_equal(base, out)
    assert ex.last_run_info["resumes"] >= 1
    assert ex.last_run_info["resume_ms"] > 0
    assert ex.last_run_info["checkpoint"]["format"] == "sharded"
    assert [e["kind"] for e in plan.journal] == ["shard_preempt"]
    assert plan.journal[0]["shard"] < 8


@pytest.mark.parametrize("exchange", ["a2a", "blocked"])
def test_collective_timeout_and_halo_drop_resume(mesh8, tmp_path, exchange):
    g = random_graph(seed=17)
    base = ShardedExecutor(g, mesh=mesh8, exchange=exchange).run(
        _pagerank(), fused=False, checkpoint_every=2,
        shard_checkpoint_dir=str(tmp_path / "base"),
    )
    plan = FaultPlan(seed=3, collective_timeout_at=4, halo_drop_at=7)
    ex = ShardedExecutor(g, mesh=mesh8, exchange=exchange)
    out = ex.run(
        _pagerank(), fused=False, checkpoint_every=2,
        shard_checkpoint_dir=str(tmp_path / "chaos"),
        fault_hook=plan.sharded_hook,
    )
    _bitwise_equal(base, out)
    kinds = [e["kind"] for e in plan.journal]
    assert "collective" in kinds and "halo_drop" in kinds
    assert ex.last_run_info["resumes"] == 2


def test_fused_path_resumes_from_manifest(mesh8, tmp_path):
    g = random_graph(seed=29)
    base = ShardedExecutor(g, mesh=mesh8).run(
        _pagerank(12), fused=True, checkpoint_every=4,
        shard_checkpoint_dir=str(tmp_path / "base"),
    )
    plan = FaultPlan(seed=5, shard_preempt_superstep=6)
    ex = ShardedExecutor(g, mesh=mesh8)
    out = ex.run(
        _pagerank(12), fused=True, checkpoint_every=4,
        shard_checkpoint_dir=str(tmp_path / "chaos"),
        fault_hook=plan.sharded_hook,
    )
    _bitwise_equal(base, out)
    assert ex.last_run_info["path"] == "fused"
    assert ex.last_run_info["resumes"] >= 1


@pytest.mark.parametrize("strategy", ["ell", "hybrid"])
def test_cpu_executor_sharded_format_bitwise(tmp_path, strategy):
    g = random_graph(n=70, m=280, seed=9)
    base = CPUExecutor(g, strategy=strategy).run(
        _pagerank(8), checkpoint_every=2,
        shard_checkpoint_dir=str(tmp_path / "base"), checkpoint_shards=4,
    )
    plan = FaultPlan(seed=11, preempt_superstep=4)
    out = CPUExecutor(g, strategy=strategy).run(
        _pagerank(8), checkpoint_every=2,
        shard_checkpoint_dir=str(tmp_path / "chaos"), checkpoint_shards=4,
        fault_hook=plan.olap_hook,
    )
    _bitwise_equal(base, out)


def test_checkpoint_portable_between_executors(mesh8, tmp_path):
    """A manifest written by the mesh executor restores on the CPU oracle
    (and the formats agree on the real-row convention)."""
    g = random_graph(n=90, m=360, seed=31)
    d = str(tmp_path / "ck")
    ex = ShardedExecutor(g, mesh=mesh8)
    ex.run(
        _pagerank(6), fused=False, checkpoint_every=6,
        shard_checkpoint_dir=d,
    )
    loaded = load_sharded_checkpoint(d)
    assert loaded is not None
    lstate, _m, steps = loaded
    assert steps == 6
    assert lstate["rank"].shape[0] == g.num_vertices
    # CPU oracle resumes from the mesh-written manifest and just returns
    # the restored state (max_iterations already reached)
    out = CPUExecutor(g).run(
        _pagerank(6), checkpoint_every=6, shard_checkpoint_dir=d,
        resume=True,
    )
    assert np.array_equal(out["rank"], np.asarray(lstate["rank"], np.float64))


def test_frontier_run_restarts_on_preemption(mesh8):
    """Frontier-compacted runs carry no checkpoint: auto-resume restarts
    the (short, deterministic) run from scratch."""
    g = random_graph(seed=41)
    prog = lambda: ShortestPathProgram(seed_index=0)  # noqa: E731
    base = ShardedExecutor(g, mesh=mesh8).run(prog(), frontier="always")
    fired = {"n": 0}

    def hook(step):
        if step == 1 and fired["n"] == 0:
            fired["n"] += 1
            from janusgraph_tpu.exceptions import ShardPreempted

            raise ShardPreempted("injected")

    ex = ShardedExecutor(g, mesh=mesh8)
    out = ex.run(prog(), frontier="always", fault_hook=hook)
    _bitwise_equal(base, out)
    assert ex.last_run_info["resumes"] == 1


# --------------------------------------------------- determinism + skew
def test_distributed_journal_reproducibility(mesh8, tmp_path):
    g = random_graph(seed=19)

    def chaos_run(sub):
        plan = FaultPlan(
            seed=77, shard_preempt_superstep=4, collective_timeout_at=7,
            straggler_ms=1.0, straggler_rate=0.3,
        )
        ex = ShardedExecutor(g, mesh=mesh8)
        out = ex.run(
            _pagerank(8), fused=False, checkpoint_every=2,
            shard_checkpoint_dir=str(tmp_path / sub),
            fault_hook=plan.sharded_hook,
        )
        return plan.journal, out

    j1, o1 = chaos_run("a")
    j2, o2 = chaos_run("b")
    assert j1 == j2  # same seed -> byte-equal fault sequence
    assert len(j1) > 0
    _bitwise_equal(o1, o2)


def test_straggler_skew_report_and_gauge(mesh8, tmp_path):
    from janusgraph_tpu.observability import flight_recorder, registry

    g = random_graph(seed=23)
    plan = FaultPlan(seed=1, straggler_ms=2.0, straggler_rate=1.0)
    ex = ShardedExecutor(g, mesh=mesh8)
    ex.run(
        _pagerank(4), fused=False,
        fault_hook=plan.sharded_hook,
    )
    shards = ex.last_run_info["shards"]
    assert shards["count"] == 8
    assert shards["straggler_events"] > 0
    assert shards["straggler_ms_total"] > 0
    assert shards["skew"] >= 1.0
    assert len(shards["per_shard"]) == 8
    per = shards["per_shard"][shards["slowest_shard"]]
    assert per["ledger"]["cells_read"] == per["edges"]
    assert per["roofline"]["flops"] > 0
    # the gauge + a shard_skew flight event are on the record
    snap = registry.snapshot()
    assert snap["olap.shard.skew"]["value"] >= 1.0
    assert any(
        e["category"] == "shard_skew" for e in flight_recorder.events()
    )


def test_per_shard_roofline_blocks_without_faults(mesh8):
    g = random_graph(seed=37)
    ex = ShardedExecutor(g, mesh=mesh8)
    ex.run(_pagerank(4), fused=True)
    shards = ex.last_run_info["shards"]
    assert shards["straggler_events"] == 0
    assert sum(p["edges"] for p in shards["per_shard"]) == g.num_edges
    assert sum(p["vertices"] for p in shards["per_shard"]) == g.num_vertices
    for p in shards["per_shard"]:
        assert {"flops", "bytes_accessed", "operational_intensity"} <= set(
            p["roofline"]
        )


def test_healthz_sharded_block(mesh8, tmp_path):
    from janusgraph_tpu.server.server import healthz_snapshot

    g = random_graph(seed=43)
    plan = FaultPlan(seed=2, shard_preempt_superstep=3)
    ex = ShardedExecutor(g, mesh=mesh8)
    ex.run(
        _pagerank(6), fused=False, checkpoint_every=2,
        shard_checkpoint_dir=str(tmp_path / "ck"),
        fault_hook=plan.sharded_hook,
    )
    snap = healthz_snapshot()
    sharded = snap["sharded"]
    assert sharded["faults"]["shard_preempt"] >= 1
    assert sharded["resumes"] >= 1
    assert sharded["skew"] is not None


# -------------------------------------------- measured-record persistence
def test_autotune_measured_keyed_by_shard_count(tmp_path):
    from janusgraph_tpu.olap import autotune

    path = str(tmp_path / "ck.autotune.json")
    autotune.save_measured(
        path, {"strategy": "hybrid", "pad_ratio": 1.01,
               "superstep_ms": 75.0, "roofline_by_tier": None},
        shard_count=1,
    )
    autotune.save_measured(
        path, {"strategy": "sharded-a2a-ell", "pad_ratio": 1.2,
               "superstep_ms": 12.0, "roofline_by_tier": None},
        shard_count=8,
    )
    one = autotune.load_measured(path, shard_count=1)
    eight = autotune.load_measured(path, shard_count=8)
    assert one["superstep_ms"] == 75.0 and one["strategy"] == "hybrid"
    assert eight["superstep_ms"] == 12.0
    assert autotune.load_measured(path, shard_count=4) is None


def test_autotune_measured_v1_backcompat(tmp_path):
    from janusgraph_tpu.olap import autotune

    path = str(tmp_path / "old.autotune.json")
    with open(path, "w") as f:
        json.dump({
            "version": 1, "strategy": "ell", "pad_ratio": 1.4,
            "superstep_ms": 88.0, "roofline_by_tier": None,
        }, f)
    rec = autotune.load_measured(path, shard_count=1)
    assert rec["superstep_ms"] == 88.0
    assert autotune.load_measured(path, shard_count=8) is None
    # a multi-chip save upgrades the file WITHOUT clobbering the v1 record
    autotune.save_measured(
        path, {"strategy": "sharded-a2a-ell", "pad_ratio": 1.1,
               "superstep_ms": 9.0, "roofline_by_tier": None},
        shard_count=8,
    )
    assert autotune.load_measured(path, shard_count=1)["superstep_ms"] == 88.0
    assert autotune.load_measured(path, shard_count=8)["superstep_ms"] == 9.0


def test_sharded_run_persists_measured_record(mesh8, tmp_path):
    from janusgraph_tpu.olap import autotune

    g = random_graph(seed=47)
    d = str(tmp_path / "ck")
    ex = ShardedExecutor(g, mesh=mesh8)
    ex.run(
        _pagerank(4), fused=False, checkpoint_every=2,
        shard_checkpoint_dir=d,
    )
    persisted = ex.last_run_info["autotune_persist"]
    assert persisted["shard_count"] == 8
    assert persisted["calibrated"] is False
    rec = autotune.load_measured(persisted["path"], shard_count=8)
    assert rec is not None and rec["strategy"] == "sharded-a2a-ell"
    # single-device slot untouched
    assert autotune.load_measured(persisted["path"], shard_count=1) is None
    # a second lifetime sees its own layout's calibration
    ex2 = ShardedExecutor(g, mesh=mesh8)
    ex2.run(
        _pagerank(4), fused=False, checkpoint_every=2,
        shard_checkpoint_dir=d,
    )
    assert ex2.last_run_info["autotune_persist"]["calibrated"] is True


# ----------------------------------------------------------------- soak
@pytest.mark.slow
def test_multichip_chaos_soak(mesh8, tmp_path):
    """The full seeded soak: shard preemption + collective timeout + halo
    drop + straggler skew + one torn manifest write mid-run, across both
    agg formats, each bitwise-identical to its fault-free twin and
    journal-reproducible."""
    g = random_graph(n=200, m=900, seed=53)
    for agg in ("ell", "segment"):
        base = ShardedExecutor(g, mesh=mesh8, agg=agg).run(
            _pagerank(16), fused=False, checkpoint_every=3,
            shard_checkpoint_dir=str(tmp_path / f"{agg}-base"),
        )
        journals = []
        for trial in range(2):
            d = str(tmp_path / f"{agg}-t{trial}")
            plan = FaultPlan(
                seed=99, shard_preempt_superstep=5,
                collective_timeout_at=9, halo_drop_at=13,
                straggler_ms=1.0, straggler_rate=0.2,
            )
            saves = {"n": 0}
            orig_hook = plan.sharded_hook

            def hook(step, num_shards):
                # tear the manifest once, after the first few saves — the
                # next resume must land on .prev
                if step == 8 and saves["n"] == 0:
                    mpath = os.path.join(d, "manifest.json")
                    if os.path.exists(mpath):
                        saves["n"] += 1
                        with open(mpath, "r+b") as f:
                            f.truncate(11)
                return orig_hook(step, num_shards)

            ex = ShardedExecutor(g, mesh=mesh8, agg=agg)
            out = ex.run(
                _pagerank(16), fused=False, checkpoint_every=3,
                shard_checkpoint_dir=d, fault_hook=hook,
            )
            _bitwise_equal(base, out)
            assert ex.last_run_info["resumes"] >= 3
            journals.append(plan.journal)
        assert journals[0] == journals[1]
