"""Server + driver suites: query endpoint (HTTP/WS), auth, multi-graph
management, and client-side serialization (reference:
AbstractGremlinServerIntegrationTest pattern — a real server started
in-process; JanusGraphSONModule/GraphBinary serializer tests)."""

import json

import numpy as np
import pytest

from janusgraph_tpu.core import gods
from janusgraph_tpu.core.graph import open_graph
from janusgraph_tpu.driver import (
    JanusGraphClient,
    RelationIdentifier,
    binary_dumps,
    binary_loads,
    graphson_dumps,
    graphson_loads,
)
from janusgraph_tpu.server import (
    ConfiguredGraphFactory,
    CredentialsAuthenticator,
    HMACAuthenticator,
    JanusGraphManager,
    JanusGraphServer,
)
from janusgraph_tpu.server.auth import AuthenticationError


@pytest.fixture
def gods_graph():
    g = open_graph({"ids.authority-wait-ms": 0.0})
    gods.load(g)
    yield g
    g.close()


@pytest.fixture
def manager(gods_graph):
    m = JanusGraphManager()
    m.put_graph("graph", gods_graph)
    return m


@pytest.fixture
def server(manager):
    s = JanusGraphServer(manager=manager).start()
    yield s
    s.stop()


# ------------------------------------------------------------- serialization
def test_graphson_scalar_roundtrip():
    for v in (42, 3.5, "x", True, None, [1, "a"], {"k": 7}, {1, 2}):
        assert graphson_loads(graphson_dumps(v)) == v


def test_graphbinary_scalar_roundtrip():
    for v in (42, -7, 3.5, "héllo", True, None, b"\x00\xff", [1, [2, 3]],
              {"k": 7, "j": [1]}, {1, 2}):
        assert binary_loads(binary_dumps(v)) == v


def test_relation_identifier_roundtrip():
    rid = RelationIdentifier(123456, 789, 42, 1011)
    assert RelationIdentifier.parse(str(rid)) == rid
    assert binary_loads(binary_dumps(rid)) == rid
    assert graphson_loads(graphson_dumps(rid)) == rid


def test_element_serialization(gods_graph):
    src = gods_graph.traversal()
    saturn = src.V().has("name", "saturn").next()
    gs = json.loads(graphson_dumps(saturn))
    assert gs["@type"] == "g:Vertex"
    back = graphson_loads(graphson_dumps(saturn))
    assert back.id == saturn.id and back.properties["name"] == ["saturn"]

    edge = src.V().has("name", "hercules").out_e("father").next()
    be = binary_loads(binary_dumps(edge))
    assert be.label == "father"
    assert be.id.out_vertex_id == edge.out_vertex.id
    src.rollback()


# -------------------------------------------------------------------- server
def test_http_query_roundtrip(server):
    client = JanusGraphClient(port=server.port)
    assert client.health()
    names = client.submit("g.V().has('name', 'saturn').in_('father').values('name')")
    assert names == ["jupiter"]
    count = client.submit("g.V().count()")
    assert count == 12


def test_http_query_with_predicates(server):
    client = JanusGraphClient(port=server.port)
    res = client.submit("g.V().has('age', P.gt(100)).values('name')")
    assert set(res) >= {"saturn", "jupiter"}


def test_http_vertex_results_are_typed(server):
    client = JanusGraphClient(port=server.port)
    vs = client.submit("g.V().has('name', 'saturn')")
    assert len(vs) == 1 and vs[0].properties["name"] == ["saturn"]


def test_http_error_surfaces(server):
    client = JanusGraphClient(port=server.port)
    from janusgraph_tpu.driver.client import RemoteError

    with pytest.raises(RemoteError):
        client.submit("g.V().nonexistent_step()")


def test_sandbox_blocks_builtins(server):
    client = JanusGraphClient(port=server.port)
    from janusgraph_tpu.driver.client import RemoteError

    with pytest.raises(RemoteError):
        client.submit("__import__('os').system('true')")


def test_websocket_session(server):
    client = JanusGraphClient(port=server.port)
    ws = client.ws()
    try:
        assert ws.submit("g.V().count()") == 12
        names = ws.submit("g.V().has('name','jupiter').out('brother').values('name')")
        assert set(names) == {"neptune", "pluto"}
    finally:
        ws.close()


# ---------------------------------------------------------------------- auth
def test_auth_flow():
    creds_graph = open_graph({"ids.authority-wait-ms": 0.0})
    creds = CredentialsAuthenticator(creds_graph)
    creds.create_user("alice", "s3cret")
    assert creds.authenticate("alice", "s3cret") == "alice"
    with pytest.raises(AuthenticationError):
        creds.authenticate("alice", "wrong")
    with pytest.raises(AuthenticationError):
        creds.authenticate("bob", "s3cret")

    hmac_auth = HMACAuthenticator(creds, token_ttl_seconds=60)
    token = hmac_auth.issue_token("alice", "s3cret")
    assert hmac_auth.verify_token(token) == "alice"
    with pytest.raises(AuthenticationError):
        hmac_auth.verify_token(token[:-4] + "AAAA")
    creds_graph.close()


def test_server_requires_auth(manager):
    creds_graph = open_graph({"ids.authority-wait-ms": 0.0})
    creds = CredentialsAuthenticator(creds_graph)
    creds.create_user("alice", "pw")
    auth = HMACAuthenticator(creds)
    server = JanusGraphServer(manager=manager, authenticator=auth).start()
    try:
        import urllib.error

        anon = JanusGraphClient(port=server.port)
        with pytest.raises(urllib.error.HTTPError):
            anon.submit("g.V().count()")

        basic = JanusGraphClient(port=server.port, username="alice", password="pw")
        assert basic.submit("g.V().count()") == 12

        basic.fetch_token()
        assert basic.token is not None
        token_client = JanusGraphClient(port=server.port, token=basic.token)
        assert token_client.submit("g.V().count()") == 12
        # ws with token
        ws = token_client.ws()
        try:
            assert ws.submit("g.V().count()") == 12
        finally:
            ws.close()
    finally:
        server.stop()
        creds_graph.close()


# ---------------------------------------------------- multi-graph management
def test_manager_registry_and_suppliers():
    m = JanusGraphManager()
    opened = []

    def supplier():
        g = open_graph({"ids.authority-wait-ms": 0.0})
        opened.append(g)
        return g

    m.put_graph_supplier("lazy", supplier)
    assert "lazy" in m.graph_names()
    assert not opened
    g = m.get_graph("lazy")
    assert opened == [g]
    assert m.get_graph("lazy") is g  # cached
    m.close_all()


def test_configured_graph_factory():
    mgmt_graph = open_graph({"ids.authority-wait-ms": 0.0})
    mgr = JanusGraphManager()
    factory = ConfiguredGraphFactory(mgmt_graph, manager=mgr)

    factory.create_configuration({
        "graph.graphname": "social",
        "storage.backend": "inmemory",
        "ids.authority-wait-ms": 0.0,
    })
    assert factory.graph_names() == ["social"]
    g = factory.open("social")
    src = g.traversal()
    v = src.add_v()
    v.property("name", "n0") if g.schema_cache.get_by_name("name") else None
    src.commit()
    assert factory.open("social") is g  # registry-cached

    # template-based creation
    factory.create_template_configuration({
        "storage.backend": "inmemory", "ids.authority-wait-ms": 0.0,
    })
    g2 = factory.create("friends")
    assert set(factory.graph_names()) == {"social", "friends"}
    assert mgr.get_graph("friends") is g2

    factory.drop("friends")
    assert factory.graph_names() == ["social"]
    assert mgr.get_graph("friends") is None

    from janusgraph_tpu.exceptions import ConfigurationError

    with pytest.raises(ConfigurationError):
        factory.create_configuration({"graph.graphname": "social"})
    mgmt_graph.close()
    mgr.close_all()


def test_server_multi_graph_dispatch(manager):
    other = open_graph({"ids.authority-wait-ms": 0.0})
    src = other.traversal()
    mgmt = other.management()
    mgmt.make_property_key("name", str)
    v = src.add_v()
    v.property("name", "solo")
    src.commit()
    manager.put_graph("other", other)
    server = JanusGraphServer(manager=manager).start()
    try:
        client = JanusGraphClient(port=server.port)
        assert set(client.graphs()) == {"graph", "other"}
        assert client.submit("g.V().count()", graph="other") == 1
        assert client.submit("g.V().count()") == 12
        # cross-graph namespace: g_<name> bindings
        assert client.submit("g_other.V().values('name')") == ["solo"]
    finally:
        server.stop()
        other.close()


def test_sandbox_blocks_attribute_escapes(server):
    client = JanusGraphClient(port=server.port)
    from janusgraph_tpu.driver.client import RemoteError

    for evil in (
        "().__class__.__base__.__subclasses__()",
        "g.__init__.__globals__",
        "[c for c in [1]]",          # comprehensions rejected
        "(lambda: 1)()",             # lambdas rejected
        "g.V().to_list().__len__()",
    ):
        with pytest.raises(RemoteError):
            client.submit(evil)


def test_hmac_token_format_robust():
    """Tokens verify across many issues (the sig is hex, never split-broken)."""
    creds_graph = open_graph({"ids.authority-wait-ms": 0.0})
    creds = CredentialsAuthenticator(creds_graph)
    creds.create_user("u|ser", "pw")  # pipe in username is fine
    auth = HMACAuthenticator(creds)
    for _ in range(50):
        t = auth.issue_token("u|ser", "pw")
        assert auth.verify_token(t) == "u|ser"
    creds_graph.close()


def test_anonymous_traversal_bodies_over_the_wire(server):
    """Lambdas are (rightly) rejected by the sandbox; the `__` builder is
    the sanctioned body form (TinkerPop's anonymous traversal), covering
    repeat/until, union, coalesce, where(traversal), and project by()."""
    c = JanusGraphClient("127.0.0.1", server.port)
    assert c.submit(
        "g.V().has('name','hercules')"
        ".repeat(__.out('father'), times=2).values('name').to_list()"
    ) == ["saturn"]
    assert sorted(c.submit(
        "g.V().has('name','hercules')"
        ".union(__.out('father'), __.out('mother')).values('name').to_list()"
    )) == ["alcmene", "jupiter"]
    assert c.submit(
        "g.V().has('name','hercules')"
        ".coalesce(__.out('pet'), __.out('father')).values('name').to_list()"
    ) == ["jupiter"]
    assert c.submit(
        "g.V().where(__.out('battled')).values('name').to_list()"
    ) == ["hercules"]
    assert c.submit(
        "g.V().has('name','hercules')"
        ".repeat(__.out('father'), until=__.not_(__.out('father')))"
        ".values('name').to_list()"
    ) == ["saturn"]
    # other dunder names stay rejected
    from janusgraph_tpu.driver.client import RemoteError

    with pytest.raises(RemoteError, match="disallowed"):
        c.submit("__import__('os')")


def test_anonymous_builder_in_python_api():
    from janusgraph_tpu.core import gods
    from janusgraph_tpu.core.graph import open_graph
    from janusgraph_tpu.core.traversal import __

    g = open_graph()
    gods.load(g)
    t = g.traversal()
    out = (
        t.V().has("name", "hercules")
        .project("name", "battles").by("name").by(__.out("battled").count_())
        .next()
    )
    assert out == {"name": "hercules", "battles": 3}
    g.close()


def test_typed_graphson_roundtrip_new_datatypes(manager, server):
    """Framework datatypes survive the wire TYPED, not stringified
    (reference: JanusGraphSONModule registered serializers)."""
    import numpy as np
    from datetime import timedelta

    from janusgraph_tpu.core.attributes import Char, Instant

    g = manager.get_graph("graph")
    mgmt = g.management()
    mgmt.make_property_key("born", Instant)
    mgmt.make_property_key("grade", Char)
    mgmt.make_property_key("scores", np.ndarray)
    mgmt.make_property_key("dur", timedelta)
    tx = g.new_transaction()
    v = tx.add_vertex(name="typed")
    v.property("born", Instant(1000, 5))
    v.property("grade", Char("B"))
    v.property("scores", np.array([1.5, 2.5]))
    v.property("dur", timedelta(seconds=90))
    tx.commit()

    c = JanusGraphClient("127.0.0.1", server.port)
    vm = c.submit("g.V().has('name','typed').value_map().to_list()")[0]
    assert vm["born"] == [Instant(1000, 5)]
    assert vm["grade"] == ["B"] and isinstance(vm["grade"][0], Char)
    np.testing.assert_array_equal(vm["scores"][0], [1.5, 2.5])
    assert vm["dur"] == [timedelta(seconds=90)]


def test_graphbinary_typed_roundtrip_new_datatypes():
    """The binary codec keeps the same typed vocabulary as GraphSON."""
    import numpy as np
    from datetime import date, datetime, time as dtime, timedelta

    from janusgraph_tpu.core.attributes import Char, Instant
    from janusgraph_tpu.driver.graphbinary import binary_dumps, binary_loads

    samples = [
        Instant(1000, 5),
        Char("Q"),
        timedelta(days=200000, microseconds=1),  # lossy under float seconds
        datetime(2026, 7, 30, 1, 2, 3, 4),
        date(2026, 7, 30),
        dtime(23, 59, 58, 999999),
    ]
    for v in samples:
        got = binary_loads(binary_dumps(v))
        assert got == v and type(got) is type(v), v
    arr = np.arange(6, dtype=np.float32).reshape(2, 3)
    got = binary_loads(binary_dumps(arr))
    assert got.dtype == arr.dtype and got.shape == arr.shape
    np.testing.assert_array_equal(got, arr)
    # non-numeric dtypes degrade to strings, never crash
    weird = np.array([b"x"], dtype="|S1")
    assert isinstance(binary_loads(binary_dumps(weird)), str)


def test_graphson_duration_lossless_and_weird_arrays():
    import json

    import numpy as np
    from datetime import timedelta

    from janusgraph_tpu.driver.graphson import graphson_dumps, graphson_loads

    big = timedelta(days=200000, microseconds=1)
    assert graphson_loads(graphson_dumps(big)) == big
    # datetime64/complex arrays must not 500 the response
    for weird in (
        np.array(["2026-01-01"], dtype="datetime64[s]"),
        np.array([1 + 2j]),
    ):
        json.loads(graphson_dumps(weird))  # serializes without raising


def test_driver_geoshape_round_trips_all_kinds():
    """Every Geoshape kind crosses both driver codecs typed (reference:
    JanusGraphSONModule + GraphBinary Geoshape serializers)."""
    from janusgraph_tpu.core.predicates import Geoshape
    from janusgraph_tpu.driver.graphbinary import binary_dumps, binary_loads
    from janusgraph_tpu.driver.graphson import graphson_dumps, graphson_loads

    shapes = (
        Geoshape.point(1, 2),
        Geoshape.circle(1, 2, 50.0),
        Geoshape.box(0, 0, 2, 2),
        Geoshape.polygon([(0, 0), (0, 3), (3, 0)]),
        Geoshape.line([(0, 0), (1, 1)]),
        Geoshape.multipoint([(0, 0), (2, 2)]),
        Geoshape.multilinestring([[(0, 0), (1, 1)], [(2, 2), (3, 3)]]),
        Geoshape.multipolygon(
            [[(0, 0), (0, 2), (2, 2), (2, 0)], [(5, 5), (5, 7), (7, 6)]]
        ),
        Geoshape.geometry_collection(
            [Geoshape.point(1, 1), Geoshape.line([(0, 0), (4, 4)])]
        ),
    )
    for s in shapes:
        assert graphson_loads(graphson_dumps(s)) == s, s.kind
        assert binary_loads(binary_dumps(s)) == s, s.kind


def test_graphson_direction_roundtrip():
    """elementMap endpoint keys ride the wire as g:Direction (GraphSON 3.0
    DirectionSerializer), not degraded g:Int64 0/1."""
    from janusgraph_tpu.core.codecs import Direction
    from janusgraph_tpu.driver.graphson import graphson_dumps, graphson_loads

    m = {Direction.OUT: {"id": 1}, Direction.IN: {"id": 2}, "label": "x"}
    wire = graphson_dumps(m)
    assert '"g:Direction"' in wire and '"OUT"' in wire
    back = graphson_loads(wire)
    assert back[Direction.OUT] == {"id": 1} and back[Direction.IN] == {"id": 2}


def test_graphbinary_direction_roundtrip():
    from janusgraph_tpu.core.codecs import Direction
    from janusgraph_tpu.driver.graphbinary import binary_dumps, binary_loads

    m = {Direction.OUT: {"id": 1}, Direction.IN: {"id": 2}, "label": "x"}
    back = binary_loads(binary_dumps(m))
    assert back[Direction.OUT] == {"id": 1} and back[Direction.IN] == {"id": 2}
    assert isinstance(next(iter(back)), Direction)


def test_sharded_composite_of_remote_nodes_refuses_pickle():
    """network_attached propagates through the sharded composite, so
    allow-pickle=auto stays off when any node is a network client."""
    from janusgraph_tpu.storage.inmemory import InMemoryStoreManager
    from janusgraph_tpu.storage.remote import (
        RemoteStoreManager,
        RemoteStoreServer,
    )
    from janusgraph_tpu.storage.sharded_store import ShardedStoreManager

    servers = [RemoteStoreServer(InMemoryStoreManager()).start()
               for _ in range(2)]
    addrs = [s.address for s in servers]
    mgr = ShardedStoreManager(
        num_nodes=2, node_factory=lambda i: RemoteStoreManager(
            host=addrs[i][0], port=addrs[i][1]),
    )
    assert mgr.features.network_attached
    from janusgraph_tpu.core.graph import open_graph

    g = open_graph({"storage.backend": "inmemory"}, store_manager=mgr)
    assert not g.serializer.allow_pickle
    g.close()
    for s in servers:
        s.stop()


def test_gremlin_dialect_compat():
    """REAL Gremlin text (camelCase + reserved-word steps + bare
    predicates) runs against the endpoint; the python dialect is
    untouched (server/gremlin_compat.py token-level rewrite)."""
    from janusgraph_tpu.core import gods
    from janusgraph_tpu.core.graph import open_graph
    from janusgraph_tpu.server.gremlin_compat import translate
    from janusgraph_tpu.server.manager import JanusGraphManager
    from janusgraph_tpu.server.server import JanusGraphServer

    g = open_graph()
    gods.load(g)
    mgr = JanusGraphManager()
    mgr.put_graph("graph", g)
    srv = JanusGraphServer(manager=mgr)

    assert sorted(srv.execute(
        "g.V().has('name','hercules').outE('battled').inV().values('name')"
    )) == ["cerberus", "hydra", "nemean"]
    assert srv.execute(
        "g.V().as('a').out('father').in('father').where(neq('a')).count()"
    ) == 0  # hercules is jupiter's only child here
    assert srv.execute("g.V().hasLabel('titan').values('name')") == ["saturn"]
    assert sorted(srv.execute(
        "g.V().has('age', gt(3000)).values('name')"
    )) == ["jupiter", "neptune", "pluto", "saturn"]
    em = srv.execute(
        "g.V().has('name','hercules').outE('battled').elementMap().limit(1)"
    )
    assert em[0]["label"] == "battled"
    # string literals with step-looking content stay untouched
    assert srv.execute("g.V().has('name', 'outE').count()") == 0
    # python dialect passes through unchanged
    assert srv.execute(
        "g.V().has('name','hercules').out_e('battled').in_v().count()"
    ) == 3
    # bare anonymous steps (Gremlin-Groovy static imports)
    assert sorted(srv.execute(
        "g.V().where(out('father')).values('name')"
    )) == ["hercules", "jupiter"]
    assert srv.execute(
        "g.V().has('name','hercules').where(not(out('mother'))).count()"
    ) == 0
    assert srv.execute(
        "g.V().has('reason', textContainsPhrase('loves waves')).count()"
    ) >= 0
    one = translate("g.V().in('x').as('a').outE('y')")
    assert translate(one) == one  # idempotent: a second pass is a no-op
    g.close()


def test_gremlin_dialect_over_http():
    import json
    import urllib.request

    from janusgraph_tpu.core import gods
    from janusgraph_tpu.core.graph import open_graph
    from janusgraph_tpu.server.manager import JanusGraphManager
    from janusgraph_tpu.server.server import JanusGraphServer

    g = open_graph()
    gods.load(g)
    mgr = JanusGraphManager()
    mgr.put_graph("graph", g)
    srv = JanusGraphServer(manager=mgr).start()
    req = urllib.request.Request(
        f"http://127.0.0.1:{srv.port}/gremlin",
        data=json.dumps({
            "gremlin": "g.V().hasLabel('god').has('age', gt(4000)).values('name')"
        }).encode(),
        headers={"Content-Type": "application/json"},
    )
    body = json.loads(urllib.request.urlopen(req).read())
    assert body["status"]["code"] == 200
    got = set(body["result"]["data"]["@value"])  # typed g:List envelope
    assert got == {"jupiter", "neptune"}  # saturn is a titan
    srv.stop()
    g.close()


def test_gremlin_dialect_computer_steps_equivalence(manager):
    """Both spellings of the OLAP computer steps agree (checked once —
    too expensive for the random fuzz pool)."""
    srv = JanusGraphServer(manager=manager)
    a = srv.execute(
        "g.V().pageRank().order().by('pagerank', reverse=True)"
        ".limit(3).values('name')"
    )
    b = srv.execute(
        "g.V().page_rank().order('pagerank', reverse=True)"
        ".limit(3).values('name')"
    )
    assert a == b and len(a) == 3
    ca = srv.execute("g.V().connectedComponent().values('component')")
    cb = srv.execute("g.V().connected_component().values('component')")
    assert ca == cb and len(ca) == 12


def test_gremlin_dialect_fuzz_equivalence():
    """Random step chains rendered in BOTH spellings (Gremlin camelCase /
    python snake_case) must return identical results through the server —
    the broad guarantee behind the dialect rewrite."""
    import random

    from janusgraph_tpu.core import gods
    from janusgraph_tpu.core.graph import open_graph
    from janusgraph_tpu.server.manager import JanusGraphManager
    from janusgraph_tpu.server.server import JanusGraphServer

    g = open_graph()
    gods.load(g)
    mgr = JanusGraphManager()
    mgr.put_graph("graph", g)
    srv = JanusGraphServer(manager=mgr)

    # (gremlin spelling, python spelling) step pool; {0} = edge label
    steps = [
        ("out('{0}')", "out('{0}')"),
        ("in('{0}')", "in_('{0}')"),
        ("both('{0}')", "both('{0}')"),
        ("outE('{0}').inV()", "out_e('{0}').in_v()"),
        ("inE('{0}').outV()", "in_e('{0}').out_v()"),
        ("hasLabel('god')", "has_label('god')"),
        ("has('age', gt(100))", "has('age', P.gt(100))"),
        ("hasNot('age')", "has_not('age')"),
        ("simplePath()", "simple_path()"),
        ("dedup()", "dedup()"),
        ("limit(5)", "limit(5)"),
        ("where(out('{0}'))", "where(__.out('{0}'))"),
        # round-5 additions (the OLAP computer steps are checked once,
        # directly, below — a computer run per random chain is too slow)
        ("repeat(out('{0}')).times(2)", "repeat(__.out('{0}'), times=2)"),
        # emit bounded by times: an unbounded emit on a cyclic label
        # (brother<->brother) doubles the frontier each loop up to
        # query.max-repeat-loops = 2^64 traversers (TinkerPop text
        # explodes identically; real queries pair emit with times/until)
        ("repeat(out('{0}')).emit().times(3)",
         "repeat(__.out('{0}'), emit=True, times=3)"),
        ("order().by('age')", "order('age')"),
    ]
    labels = ["father", "brother", "battled", "lives", "pet", "mother"]
    rng = random.Random(20260731)
    for _ in range(40):
        chain = rng.sample(steps, rng.randint(1, 4))
        lbls = [rng.choice(labels) for _ in chain]
        gq = "g.V()." + ".".join(
            s[0].format(l) for s, l in zip(chain, lbls)
        ) + ".values('name')"
        pq = "g.V()." + ".".join(
            s[1].format(l) for s, l in zip(chain, lbls)
        ) + ".values('name')"
        assert sorted(srv.execute(gq)) == sorted(srv.execute(pq)), gq
    g.close()


def test_gremlin_addv_insert_form(gods_graph, manager):
    """g.addV('person').property('name','marko') — the canonical Gremlin
    insert — works over the endpoint, and add_v_ composes with add_e_."""
    srv = JanusGraphServer(manager=manager)
    out = srv.execute(
        "g.addV('person').property('name','marko').values('name')"
    )
    assert out == ["marko"]
    # sessionless auto-commit (server.auto-commit, default on): the
    # mutation persists across requests like the reference Gremlin Server
    assert srv.execute("g.V().has('name','marko').count()") == 1
    t = gods_graph.traversal()
    v = t.add_v_("person").property("name", "ada").next()
    t.add_v_("person").property("name", "bob").add_e_("knows").to_(
        v
    ).iterate()
    t.tx.commit()
    assert gods_graph.traversal().V().has("name", "bob").out(
        "knows"
    ).values("name").to_list() == ["ada"]


def test_gremlin_addv_lazy_and_upsert(gods_graph, manager):
    """Review regressions: addV is lazy (no phantom vertex when the chain
    fails at build time; one vertex per execution) and the canonical
    coalesce-upsert works over the endpoint."""
    srv = JanusGraphServer(manager=manager)
    t = gods_graph.traversal()
    before = len(t.V().to_list())
    # build-time failure leaves NO vertex behind
    import pytest as _p

    from janusgraph_tpu.core.traversal import QueryError

    with _p.raises(QueryError):
        gods_graph.traversal().add_v_("ghost").property(None)
    assert len(gods_graph.traversal().V().to_list()) == before
    # one vertex PER EXECUTION
    trav = gods_graph.traversal().add_v_("dup")
    a = trav.next()
    b = trav.next()
    assert a.id != b.id
    # the canonical Gremlin upsert over the endpoint
    out = srv.execute(
        "g.V().has('name','nosuch').fold()"
        ".coalesce(__.unfold(), __.addV('person')).label()"
    )
    assert out == ["person"]
    out2 = srv.execute(
        "g.V().has('name','hercules').fold()"
        ".coalesce(__.unfold(), __.addV('person')).values('name')"
    )
    assert out2 == ["hercules"]


def test_server_auto_commit_and_read_only_mode(gods_graph, manager):
    """server.auto-commit: sessionless requests commit on success (the
    reference Gremlin Server's default); auto_commit=False makes the
    endpoint read-only (every request rolls back); errors roll back."""
    srv = JanusGraphServer(manager=manager)
    srv.execute("g.mergeV({T.label: 'god', 'name': 'fortuna'})"
                ".onCreate({'age': 7}).iterate()")
    assert srv.execute("g.V().has('name','fortuna').values('age')") == [7]
    # merge across requests matches (no duplicate)
    srv.execute("g.mergeV({T.label: 'god', 'name': 'fortuna'}).iterate()")
    assert srv.execute("g.V().has('name','fortuna').count()") == 1
    # a FAILING request rolls back its mutation: the vertex is created in
    # the tx, then next() on the empty expansion raises at execution time
    with pytest.raises(Exception):
        srv.execute("g.addV('person').property('name','ghost')"
                    ".out('nothing').next()")
    assert srv.execute("g.V().has('name','ghost').count()") == 0
    # read-only endpoint
    ro = JanusGraphServer(manager=manager, auto_commit=False)
    ro.execute("g.addV('person').property('name','volatile').iterate()")
    assert ro.execute("g.V().has('name','volatile').count()") == 0


def test_ws_session_transaction_semantics(server):
    """In-session WS requests share ONE transaction (the reference
    Gremlin Server's session mode): uncommitted writes are visible to
    later session requests but not to sessionless ones; g.commit()
    persists; a close without commit rolls back."""
    client = JanusGraphClient(port=server.port)
    ws = client.ws(session=True)
    try:
        ws.submit("g.addV('person').property('name','sess1').iterate()")
        # visible in-session, invisible sessionless (uncommitted)
        assert ws.submit("g.V().has('name','sess1').count()") == 1
        assert client.submit("g.V().has('name','sess1').count()") == 0
        ws.submit("g.commit()")
        assert client.submit("g.V().has('name','sess1').count()") == 1
        # a second uncommitted write rolls back on close
        ws.submit("g.addV('person').property('name','sess2').iterate()")
        assert ws.submit("g.V().has('name','sess2').count()") == 1
    finally:
        ws.close()
    import time

    for _ in range(50):  # close is async on the server thread
        if client.submit("g.V().has('name','sess2').count()") == 0:
            break
        time.sleep(0.05)
    assert client.submit("g.V().has('name','sess2').count()") == 0
    assert client.submit("g.V().has('name','sess1').count()") == 1


def test_ws_session_read_only_and_cross_graph(manager, gods_graph):
    """Review regressions: read-only endpoints refuse sessions (explicit
    g.commit() would bypass the guarantee); later session messages may
    reference g_<name> sources the first message didn't."""
    other = open_graph({"ids.authority-wait-ms": 0.0})
    manager.put_graph("other", other)
    srv = JanusGraphServer(manager=manager).start()
    try:
        client = JanusGraphClient(port=srv.port)
        ws = client.ws(session=True)
        try:
            assert ws.submit("g.V().count()") == 12
            # a LATER message referencing g_other still resolves
            assert ws.submit("g_other.V().count()") == 0
        finally:
            ws.close()
    finally:
        srv.stop()

    ro = JanusGraphServer(manager=manager, auto_commit=False).start()
    try:
        from janusgraph_tpu.driver.client import RemoteError

        ws = JanusGraphClient(port=ro.port).ws(session=True)
        try:
            with pytest.raises(RemoteError, match="read-only"):
                ws.submit("g.V().count()")
        finally:
            ws.close()
    finally:
        ro.stop()
        other.close()


def test_ws_session_merge_upsert_flow(server):
    """Round-5 features composed: mergeV upserts inside ONE session
    transaction — intermediate state visible in-session only, one commit
    persists the batch atomically."""
    client = JanusGraphClient(port=server.port)
    ws = client.ws(session=True)
    try:
        for name in ("minerva", "vulcan", "minerva"):  # dup merges once
            ws.submit(
                "g.mergeV({T.label: 'god', 'name': '%s'})"
                ".onCreate({'age': 1}).iterate()" % name
            )
        assert ws.submit(
            "g.V().hasLabel('god').has('age', 1).count()") == 2
        assert client.submit(
            "g.V().hasLabel('god').has('age', 1).count()") == 0
        ws.submit("g.commit()")
    finally:
        ws.close()
    assert client.submit("g.V().hasLabel('god').has('age', 1).count()") == 2
    assert client.submit("g.V().has('name','minerva').count()") == 1


# ------------------------------------------------- distributed tracing (ISSUE 4)
def test_driver_query_yields_one_stitched_trace_over_remote_store():
    """Acceptance: one OLTP query through the driver against a
    remote-store-backed server yields ONE trace — client root span,
    server span, and >=1 store-op span all sharing the same trace_id,
    visible in the /telemetry snapshot."""
    import time
    import urllib.request

    from janusgraph_tpu.observability import tracer
    from janusgraph_tpu.storage.inmemory import InMemoryStoreManager
    from janusgraph_tpu.storage.remote import RemoteStoreServer

    store_server = RemoteStoreServer(InMemoryStoreManager()).start()
    host, port = store_server.address
    g = open_graph({
        "storage.backend": "remote",
        "storage.hostname": host,
        "storage.port": port,
        "ids.authority-wait-ms": 0.0,
    })
    m = JanusGraphManager()
    m.put_graph("graph", g)
    server = JanusGraphServer(manager=m).start()
    client = JanusGraphClient(port=server.port)
    try:
        tx = g.new_transaction()
        tx.add_vertex(name="stitched")
        tx.commit()
        assert client.submit("g.V().has('name','stitched').count()") == 1
        roots = [r for r in tracer.recent() if r.name == "driver.submit"]
        assert roots, "no client root span"
        root = roots[-1]
        # the storage-server handler finishes its span just after replying
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            trace = tracer.find_trace(root.trace_id)
            have_server = any(s.name == "server.request" for s in trace)
            store_ops = [
                s for s in trace if s.name.startswith("store.remote.")
            ]
            if have_server and store_ops:
                break
            time.sleep(0.01)
        assert have_server, [s.name for s in trace]
        assert store_ops, [s.name for s in trace]
        for s in trace:
            assert s.trace_id == root.trace_id
        # the whole stitched trace is inspectable via GET /telemetry
        with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/telemetry"
        ) as resp:
            payload = json.loads(resp.read().decode())
        want = f"{root.trace_id:016x}"
        names = {
            s["name"] for s in payload["spans"]
            if s.get("trace_id") == want
        }
        assert "driver.submit" in names
        assert "server.request" in names
        assert any(n.startswith("store.remote.") for n in names), names
    finally:
        server.stop()
        g.close()
        store_server.stop()


def test_server_response_echoes_trace_id(server):
    """The response status carries the trace id so callers can pull the
    stitched trace by id (`janusgraph_tpu trace <id>`)."""
    from janusgraph_tpu.observability import tracer

    client = JanusGraphClient(port=server.port)
    assert client.submit("g.V().count()") == 12
    root = [r for r in tracer.recent() if r.name == "driver.submit"][-1]
    assert root.attrs.get("server_trace") == f"{root.trace_id:016x}"


def test_ws_session_trace_stitches(server):
    from janusgraph_tpu.observability import tracer

    client = JanusGraphClient(port=server.port)
    ws = client.ws()
    try:
        assert ws.submit("g.V().count()") == 12
    finally:
        ws.close()
    roots = [
        r for r in tracer.recent()
        if r.name == "driver.submit" and r.attrs.get("transport") == "ws"
    ]
    assert roots
    trace = tracer.find_trace(roots[-1].trace_id)
    servers = [s for s in trace if s.name == "server.request"]
    assert servers and servers[-1].parent_span_id == roots[-1].span_id
