"""GraphSON export/import (reference: TinkerPop io()/GraphSONWriter the
reference inherits — graph.io(graphson()) — as functions over the public
API): full round trip with typed properties incl. Geoshape, id remapping,
batched commits."""

import io as _io

import pytest

from janusgraph_tpu.core import gods
from janusgraph_tpu.core.graph import open_graph
from janusgraph_tpu.core.io import export_graphson, import_graphson
from janusgraph_tpu.core.predicates import Geoshape


def test_gods_round_trip(tmp_path):
    src = open_graph({"ids.authority-wait-ms": 0.0})
    gods.load(src)
    path = str(tmp_path / "gods.graphson")
    counts = export_graphson(src, path)
    assert counts == {"vertices": 12, "edges": 17}

    dst = open_graph({"schema.default": "auto", "ids.authority-wait-ms": 0.0})
    got = import_graphson(dst, path)
    assert got == counts
    ts, td = src.traversal(), dst.traversal()
    assert td.V().count() == 12 and td.E().count() == 17
    # structure survives: same traversal answers on both graphs
    for q in (
        lambda t: t.V().has("name", "hercules").out("battled").count(),
        lambda t: t.V().has("age", __import__(
            "janusgraph_tpu.core.traversal", fromlist=["P"]
        ).P.gt(3000)).count(),
        lambda t: sorted(
            v.value("name") for v in
            t.V().has("name", "jupiter").out("brother").to_list()
        ),
    ):
        assert q(ts) == q(td)
    # labels survive
    assert sorted(td.V().label().to_list()) == sorted(ts.V().label().to_list())
    src.close()
    dst.close()


def test_typed_values_and_id_remap(tmp_path):
    g = open_graph({"schema.default": "auto"})
    tx = g.new_transaction()
    a = tx.add_vertex(name="a", area=Geoshape.multipolygon(
        [[(0, 0), (0, 2), (2, 2), (2, 0)]]
    ), score=1.5)
    b = tx.add_vertex(name="b")
    tx.add_edge(a, "near", b, distance=3.25)
    tx.commit()
    buf = _io.StringIO()
    export_graphson(g, buf)
    buf.seek(0)
    g2 = open_graph({"schema.default": "auto"})
    import_graphson(g2, buf)
    va = g2.traversal().V().has("name", "a").next()
    assert va.value("area") == Geoshape.multipolygon(
        [[(0, 0), (0, 2), (2, 2), (2, 0)]]
    )
    assert va.value("score") == 1.5
    e = g2.traversal().V().has("name", "a").out_e("near").to_list()[0]
    assert e.value("distance") == 3.25
    g.close()
    g2.close()


def test_multivalued_and_label_named_properties(tmp_path):
    """LIST-cardinality keys keep every entry and a property literally
    named 'label' survives (the kwargs-collision regression)."""
    from janusgraph_tpu.core.codecs import Cardinality

    g = open_graph({"schema.default": "auto"})
    m = g.management()
    m.make_property_key("tag", str, Cardinality.LIST)
    tx = g.new_transaction()
    v = tx.add_vertex(name="multi")
    v.property("tag", "a")
    v.property("tag", "b")
    tx.add_property(v, "label", "weird-key")
    tx.commit()
    buf = _io.StringIO()
    export_graphson(g, buf)
    buf.seek(0)
    g2 = open_graph({"schema.default": "auto"})
    import_graphson(g2, buf)
    v2 = g2.traversal().V().has("name", "multi").next()
    assert sorted(p.value for p in v2.properties("tag")) == ["a", "b"]
    assert v2.value("label") == "weird-key"
    g.close()
    g2.close()


def test_batched_import_streams(tmp_path):
    g = open_graph({"schema.default": "auto"})
    tx = g.new_transaction()
    vs = [tx.add_vertex(idx=i) for i in range(25)]
    for i in range(24):
        tx.add_edge(vs[i], "next", vs[i + 1])
    tx.commit()
    path = str(tmp_path / "chain.graphson")
    export_graphson(g, path)
    g2 = open_graph({"schema.default": "auto"})
    got = import_graphson(g2, path, batch_size=7)  # forces mid-stream commits
    assert got == {"vertices": 25, "edges": 24}
    assert g2.traversal().V().count() == 25
    assert g2.traversal().E().count() == 24
    g.close()
    g2.close()


def test_partial_import_reports_committed_counts():
    """A malformed record mid-file aborts the import, but earlier batches
    are already durable — the exception carries the committed counts so
    callers can detect and clean up (core/io.py docstring contract)."""
    import io as _io
    import json

    import pytest

    from janusgraph_tpu.core.graph import open_graph

    lines = [
        json.dumps({"kind": "vertex", "original_id": i, "label": "vertex",
                    "properties": []})
        for i in range(5)
    ]
    lines.append(json.dumps({"kind": "edge", "label": "x",
                             "out": 999, "in": 998, "properties": {}}))
    g = open_graph({"storage.backend": "inmemory"})
    with pytest.raises(ValueError, match="unknown vertex") as ei:
        import_graphson(g, _io.StringIO("\n".join(lines)), batch_size=2)
    # batches of 2: 4 vertices durably committed before the bad edge
    assert ei.value.committed == {"vertices": 4, "edges": 0}
    tx = g.new_transaction()
    assert sum(1 for _ in tx.vertices()) == 4
    tx.rollback()
    g.close()
