"""GraphSON export/import (reference: TinkerPop io()/GraphSONWriter the
reference inherits — graph.io(graphson()) — as functions over the public
API): full round trip with typed properties incl. Geoshape, id remapping,
batched commits."""

import io as _io

import pytest

from janusgraph_tpu.core import gods
from janusgraph_tpu.core.graph import open_graph
from janusgraph_tpu.core.io import export_graphson, import_graphson
from janusgraph_tpu.core.predicates import Geoshape


def test_gods_round_trip(tmp_path):
    src = open_graph({"ids.authority-wait-ms": 0.0})
    gods.load(src)
    path = str(tmp_path / "gods.graphson")
    counts = export_graphson(src, path)
    assert counts == {"vertices": 12, "edges": 17}

    dst = open_graph({"schema.default": "auto", "ids.authority-wait-ms": 0.0})
    got = import_graphson(dst, path)
    assert got == counts
    ts, td = src.traversal(), dst.traversal()
    assert td.V().count() == 12 and td.E().count() == 17
    # structure survives: same traversal answers on both graphs
    for q in (
        lambda t: t.V().has("name", "hercules").out("battled").count(),
        lambda t: t.V().has("age", __import__(
            "janusgraph_tpu.core.traversal", fromlist=["P"]
        ).P.gt(3000)).count(),
        lambda t: sorted(
            v.value("name") for v in
            t.V().has("name", "jupiter").out("brother").to_list()
        ),
    ):
        assert q(ts) == q(td)
    # labels survive
    assert sorted(td.V().label().to_list()) == sorted(ts.V().label().to_list())
    src.close()
    dst.close()


def test_typed_values_and_id_remap(tmp_path):
    g = open_graph({"schema.default": "auto"})
    tx = g.new_transaction()
    a = tx.add_vertex(name="a", area=Geoshape.multipolygon(
        [[(0, 0), (0, 2), (2, 2), (2, 0)]]
    ), score=1.5)
    b = tx.add_vertex(name="b")
    tx.add_edge(a, "near", b, distance=3.25)
    tx.commit()
    buf = _io.StringIO()
    export_graphson(g, buf)
    buf.seek(0)
    g2 = open_graph({"schema.default": "auto"})
    import_graphson(g2, buf)
    va = g2.traversal().V().has("name", "a").next()
    assert va.value("area") == Geoshape.multipolygon(
        [[(0, 0), (0, 2), (2, 2), (2, 0)]]
    )
    assert va.value("score") == 1.5
    e = g2.traversal().V().has("name", "a").out_e("near").to_list()[0]
    assert e.value("distance") == 3.25
    g.close()
    g2.close()


def test_multivalued_and_label_named_properties(tmp_path):
    """LIST-cardinality keys keep every entry and a property literally
    named 'label' survives (the kwargs-collision regression)."""
    from janusgraph_tpu.core.codecs import Cardinality

    g = open_graph({"schema.default": "auto"})
    m = g.management()
    m.make_property_key("tag", str, Cardinality.LIST)
    tx = g.new_transaction()
    v = tx.add_vertex(name="multi")
    v.property("tag", "a")
    v.property("tag", "b")
    tx.add_property(v, "label", "weird-key")
    tx.commit()
    buf = _io.StringIO()
    export_graphson(g, buf)
    buf.seek(0)
    g2 = open_graph({"schema.default": "auto"})
    import_graphson(g2, buf)
    v2 = g2.traversal().V().has("name", "multi").next()
    assert sorted(p.value for p in v2.properties("tag")) == ["a", "b"]
    assert v2.value("label") == "weird-key"
    g.close()
    g2.close()


def test_batched_import_streams(tmp_path):
    g = open_graph({"schema.default": "auto"})
    tx = g.new_transaction()
    vs = [tx.add_vertex(idx=i) for i in range(25)]
    for i in range(24):
        tx.add_edge(vs[i], "next", vs[i + 1])
    tx.commit()
    path = str(tmp_path / "chain.graphson")
    export_graphson(g, path)
    g2 = open_graph({"schema.default": "auto"})
    got = import_graphson(g2, path, batch_size=7)  # forces mid-stream commits
    assert got == {"vertices": 25, "edges": 24}
    assert g2.traversal().V().count() == 25
    assert g2.traversal().E().count() == 24
    g.close()
    g2.close()


def test_partial_import_reports_committed_counts():
    """A malformed record mid-file aborts the import, but earlier batches
    are already durable — the exception carries the committed counts so
    callers can detect and clean up (core/io.py docstring contract)."""
    import io as _io
    import json

    import pytest

    from janusgraph_tpu.core.graph import open_graph

    lines = [
        json.dumps({"kind": "vertex", "original_id": i, "label": "vertex",
                    "properties": []})
        for i in range(5)
    ]
    lines.append(json.dumps({"kind": "edge", "label": "x",
                             "out": 999, "in": 998, "properties": {}}))
    g = open_graph({"storage.backend": "inmemory"})
    with pytest.raises(ValueError, match="unknown vertex") as ei:
        import_graphson(g, _io.StringIO("\n".join(lines)), batch_size=2)
    # batches of 2: 4 vertices durably committed before the bad edge
    assert ei.value.committed == {"vertices": 4, "edges": 0}
    tx = g.new_transaction()
    assert sum(1 for _ in tx.vertices()) == 4
    tx.rollback()
    g.close()


def test_graphml_round_trip(tmp_path):
    """GraphML (TinkerPop labelV/labelE convention) round-trips primitive
    properties, labels, and topology with their types."""
    from janusgraph_tpu.core.graph import open_graph
    from janusgraph_tpu.core.io import export_graphml, import_graphml

    src = open_graph()
    tx = src.new_transaction()
    a = tx.add_vertex("person", name="ada", age=36, score=2.5, vip=True)
    b = tx.add_vertex("person", name="bob", age=40)
    c = tx.add_vertex("city", name="london")
    e = tx.add_edge(a, "knows", b, since=1840)
    tx.add_edge(a, "lives", c)
    tx.commit()
    path = str(tmp_path / "small.graphml")
    counts = export_graphml(src, path)
    assert counts == {"vertices": 3, "edges": 2}

    dst = open_graph()
    got = import_graphml(dst, path)
    assert got == counts
    t = dst.traversal()
    ada = t.V().has("name", "ada").next()
    assert ada.label == "person"
    assert ada.value("age") == 36          # long stays int
    assert ada.value("score") == 2.5       # double stays float
    assert ada.value("vip") is True        # boolean stays bool
    assert t.V().has("name", "ada").out("lives").values(
        "name"
    ).to_list() == ["london"]
    ek = t.V().has("name", "ada").out_e("knows").to_list()
    assert ek[0].value("since") == 1840
    src.close()
    dst.close()


def test_graphml_tinkerpop_shape_and_limits(tmp_path):
    """Imports the exact key/labelV/labelE shape TinkerPop's GraphMLWriter
    emits (the reference's grateful-dead.xml demo format); non-primitive
    values refuse with a pointer at GraphSON."""
    import io as _io

    import pytest

    from janusgraph_tpu.core.graph import open_graph
    from janusgraph_tpu.core.io import export_graphml, import_graphml

    xml = (
        '<?xml version="1.0" ?>'
        '<graphml xmlns="http://graphml.graphdrawing.org/xmlns">'
        '<key id="labelV" for="node" attr.name="labelV" attr.type="string"/>'
        '<key id="name" for="node" attr.name="name" attr.type="string"/>'
        '<key id="performances" for="node" attr.name="performances" '
        'attr.type="int"/>'
        '<key id="labelE" for="edge" attr.name="labelE" attr.type="string"/>'
        '<key id="weight" for="edge" attr.name="weight" attr.type="int"/>'
        '<graph id="G" edgedefault="directed">'
        '<node id="1"><data key="labelV">song</data>'
        '<data key="name">HEY BO DIDDLEY</data>'
        '<data key="performances">5</data></node>'
        '<node id="2"><data key="labelV">artist</data>'
        '<data key="name">Garcia</data></node>'
        '<edge source="1" target="2"><data key="labelE">sungBy</data>'
        '<data key="weight">3</data></edge>'
        "</graph></graphml>"
    )
    g = open_graph()
    got = import_graphml(g, _io.BytesIO(xml.encode()))
    assert got == {"vertices": 2, "edges": 1}
    t = g.traversal()
    song = t.V().has("name", "HEY BO DIDDLEY").next()
    assert song.label == "song" and song.value("performances") == 5
    e = t.V().has("name", "HEY BO DIDDLEY").out_e("sungBy").to_list()
    assert len(e) == 1 and e[0].value("weight") == 3
    g.close()

    rich = open_graph()
    tx = rich.new_transaction()
    tx.add_vertex(spot=__import__(
        "janusgraph_tpu.core.predicates", fromlist=["Geoshape"]
    ).Geoshape.point(1, 2))
    tx.commit()
    import io as _io2

    with pytest.raises(ValueError, match="primitive"):
        export_graphml(rich, _io2.StringIO())
    rich.close()


def test_graphml_edge_cases():
    """Review regressions: empty-string values survive, xs:boolean lexical
    forms parse, repeated keys refuse under SINGLE auto-schema but import
    under a pre-created LIST key, quotes in keys stay well-formed."""
    import io as _io

    import pytest

    from janusgraph_tpu.core.codecs import Cardinality
    from janusgraph_tpu.core.graph import open_graph
    from janusgraph_tpu.core.io import export_graphml, import_graphml

    xml = (
        '<?xml version="1.0" ?><graphml>'
        '<key id="labelV" for="node" attr.name="labelV" attr.type="string"/>'
        '<key id="s" for="node" attr.name="s" attr.type="string"/>'
        '<key id="ok" for="node" attr.name="ok" attr.type="boolean"/>'
        '<graph edgedefault="directed">'
        '<node id="1"><data key="labelV">x</data>'
        "<data key=\"s\"></data><data key=\"ok\">1</data></node>"
        "</graph></graphml>"
    )
    g = open_graph()
    import_graphml(g, _io.BytesIO(xml.encode()))
    v = g.traversal().V().next()
    assert v.value("s") == "" and v.value("ok") is True
    g.close()

    # repeated key without LIST schema refuses
    dup = (
        '<graphml><key id="nick" for="node" attr.name="nick" '
        'attr.type="string"/><graph>'
        '<node id="1"><data key="nick">a</data><data key="nick">b</data>'
        "</node></graph></graphml>"
    )
    g2 = open_graph()
    with pytest.raises(ValueError, match="SINGLE"):
        import_graphml(g2, _io.BytesIO(dup.encode()))
    g2.close()
    # ...but imports fine under a pre-created LIST key
    g3 = open_graph()
    g3.management().make_property_key("nick", str, Cardinality.LIST)
    import_graphml(g3, _io.BytesIO(dup.encode()))
    v = g3.traversal().V().next()
    assert sorted(p.value for p in v.properties("nick")) == ["a", "b"]
    g3.close()

    # quote-bearing keys round-trip well-formed
    g4 = open_graph()
    tx = g4.new_transaction()
    tx.add_vertex(**{'odd"key': "v"})
    tx.commit()
    buf = _io.StringIO()
    export_graphml(g4, buf)
    g5 = open_graph()
    import_graphml(g5, _io.BytesIO(buf.getvalue().encode()))
    assert g5.traversal().V().next().value('odd"key') == "v"
    g4.close()
    g5.close()


def test_graph_io_facade(tmp_path):
    """graph.io('graphml').write/read — the TinkerPop io() shape."""
    import pytest

    from janusgraph_tpu.core.graph import open_graph
    from janusgraph_tpu.exceptions import ConfigurationError

    g = open_graph()
    tx = g.new_transaction()
    a, b = tx.add_vertex(name="p"), tx.add_vertex(name="q")
    tx.add_edge(a, "r", b)
    tx.commit()
    for fmt, ext in (("graphson", "json"), ("graphml", "xml")):
        path = str(tmp_path / f"g.{ext}")
        assert g.io(fmt).write(path) == {"vertices": 2, "edges": 1}
        dst = open_graph()
        assert dst.io(fmt).read(path) == {"vertices": 2, "edges": 1}
        assert dst.traversal().V().has("name", "p").out("r").values(
            "name"
        ).to_list() == ["q"]
        dst.close()
    with pytest.raises(ConfigurationError, match="unknown io format"):
        g.io("gryo")
    g.close()


def test_graphml_review_regressions(tmp_path):
    """Reserved-key refusal preserves existing files; edges may precede
    nodes; repeated edge keys refuse; big imports stay bounded (container
    clearing exercised via a small batch_size)."""
    import io as _io

    import pytest

    from janusgraph_tpu.core.graph import open_graph
    from janusgraph_tpu.core.io import export_graphml, import_graphml

    # failed export must NOT truncate the existing destination
    path = str(tmp_path / "keep.graphml")
    open(path, "w").write("precious")
    g = open_graph()
    tx = g.new_transaction()
    tx.add_vertex(labelV="oops")  # reserved name
    tx.commit()
    with pytest.raises(ValueError, match="reserved"):
        export_graphml(g, path)
    assert open(path).read() == "precious"
    g.close()

    # edge-before-node order (spec-valid) defers and resolves
    xml = (
        "<graphml>"
        '<key id="labelV" for="node" attr.name="labelV" attr.type="string"/>'
        '<key id="labelE" for="edge" attr.name="labelE" attr.type="string"/>'
        "<graph>"
        '<edge source="1" target="2"><data key="labelE">r</data></edge>'
        '<node id="1"><data key="labelV">x</data></node>'
        '<node id="2"><data key="labelV">x</data></node>'
        "</graph></graphml>"
    )
    g2 = open_graph()
    got = import_graphml(g2, _io.BytesIO(xml.encode()), batch_size=1)
    assert got == {"vertices": 2, "edges": 1}
    assert len(g2.traversal().V().out_e("r").to_list()) == 1
    g2.close()

    # repeated edge key refuses
    dup = (
        "<graphml><graph>"
        '<node id="1"/><node id="2"/>'
        '<edge source="1" target="2"><data key="w">1</data>'
        '<data key="w">2</data></edge>'
        "</graph></graphml>"
    )
    g3 = open_graph()
    with pytest.raises(ValueError, match="repeats key"):
        import_graphml(g3, _io.BytesIO(dup.encode()))
    g3.close()


def test_traversal_io_step_spelling(tmp_path):
    """g.io(path).read()/.write(): the TinkerPop IoStep spelling over the
    graph.io() facade; format inferred from the extension."""
    import pytest

    from janusgraph_tpu.core import gods
    from janusgraph_tpu.core.graph import open_graph

    g = open_graph({"ids.authority-wait-ms": 0.0})
    gods.load(g)
    t = g.traversal()
    out = str(tmp_path / "gods.json")
    counts = t.io(out).write()
    assert counts["vertices"] == 12
    # graphml inferred from the extension — gods carries Geoshapes,
    # which GraphML (primitives only) refuses, proving the format took
    xml = str(tmp_path / "gods.xml")
    with pytest.raises(ValueError, match="GraphML"):
        t.io(xml).write()

    g2 = open_graph({"ids.authority-wait-ms": 0.0})
    got = g2.traversal().io(out).read()
    assert got["vertices"] == 12
    assert g2.traversal().V().count() == 12
    g.close(); g2.close()
