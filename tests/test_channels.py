"""EdgeChannel (typed edge view) tests — end-to-end across all executors.

The reference compiles each MessageScope's traversal (e.g. __.out('knows'))
into a distinct per-superstep slice query (reference:
graphdb/olap/computer/VertexProgramScanJob.java:114-135, FulgoraUtil.java:47);
here a channel is an array mask over per-edge type ids, realized as a
channel-specific ELL pack (single chip) or a channel-specific sharded edge
view (mesh). Parity gate: a two-label program whose supersteps alternate
channels must agree across CPU oracle, TPUExecutor, the 8-device mesh, and
an independent numpy re-implementation.
"""

import numpy as np
import pytest

from janusgraph_tpu.olap import csr_from_edges, run_on
from janusgraph_tpu.olap.csr import channel_edges
from janusgraph_tpu.olap.cpu_executor import CPUExecutor
from janusgraph_tpu.olap.tpu_executor import TPUExecutor
from janusgraph_tpu.olap.vertex_program import (
    Combiner,
    EdgeChannel,
    VertexProgram,
)
from janusgraph_tpu.parallel import ShardedExecutor

INF = 1e18


class AlternatingChannelProgram(VertexProgram):
    """Min-distance relaxation that is only allowed to cross label-0 edges on
    even supersteps and label-1 edges on odd ones — the per-scope-traversal
    pattern (different edge label per message round)."""

    compute_keys = ("dist",)
    combiner = Combiner.MIN
    setup_only_params = ("seed_index",)
    edge_channels = {
        "even": EdgeChannel(direction="out", labels=(0,)),
        "odd": EdgeChannel(direction="out", labels=(1,)),
    }

    def __init__(self, seed_index=0, max_iterations=4):
        self.seed_index = seed_index
        self.max_iterations = max_iterations

    def channel_for(self, superstep):
        return "even" if superstep % 2 == 0 else "odd"

    def setup(self, graph, xp):
        idx = xp.arange(graph.local_num_vertices) + graph.global_offset
        dist = xp.where(idx == self.seed_index, 0.0, INF)
        return {"dist": dist}, {"changed": (Combiner.SUM, xp.asarray(1.0))}

    def message(self, state, superstep, graph, xp):
        return state["dist"] + 1.0

    def apply(self, state, aggregated, superstep, memory_in, graph, xp):
        new = xp.minimum(state["dist"], aggregated)
        changed = xp.sum(xp.where(new < state["dist"], 1.0, 0.0))
        return {"dist": new}, {"changed": (Combiner.SUM, changed)}

    def terminate(self, memory):
        return memory.get("changed", 1.0) == 0.0


def two_label_graph(n=150, m=800, seed=7):
    rng = np.random.default_rng(seed)
    src = rng.integers(0, n, m).astype(np.int32)
    dst = rng.integers(0, n, m).astype(np.int32)
    et = rng.integers(0, 2, m).astype(np.int32)
    return csr_from_edges(n, src, dst, edge_types=et), (src, dst, et)


def numpy_alternating_reference(n, src, dst, et, seed_index, steps):
    """Independent re-implementation: per-step label-masked relaxation."""
    dist = np.full(n, INF)
    dist[seed_index] = 0.0
    for step in range(steps):
        lab = 0 if step % 2 == 0 else 1
        m = et == lab
        agg = np.full(n, INF)
        np.minimum.at(agg, dst[m], dist[src[m]] + 1.0)
        dist = np.minimum(dist, agg)
    return dist


@pytest.fixture(scope="module")
def mesh8():
    import jax
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()[:8]), ("p",))


def test_channel_edges_filters_labels_and_direction():
    g, (src, dst, et) = two_label_graph()
    s0, d0, _ = channel_edges(g, EdgeChannel(direction="out", labels=(0,)))
    assert len(s0) == int((et == 0).sum())
    # reversed view swaps the aggregation side
    s_in, d_in, _ = channel_edges(g, EdgeChannel(direction="in", labels=(0,)))
    assert sorted(zip(s0.tolist(), d0.tolist())) == sorted(
        zip(d_in.tolist(), s_in.tolist())
    )
    s_b, _d, _ = channel_edges(g, EdgeChannel(direction="both", labels=(0,)))
    assert len(s_b) == 2 * len(s0)
    # all labels when labels=None
    s_all, _d, _ = channel_edges(g, EdgeChannel(direction="out"))
    assert len(s_all) == g.num_edges


def test_channel_without_type_arrays_fails_loudly():
    g = csr_from_edges(4, [0, 1], [1, 2])
    with pytest.raises(ValueError, match="type arrays"):
        channel_edges(g, EdgeChannel(direction="out", labels=(0,)))


def test_alternating_channels_parity_all_executors(mesh8):
    g, (src, dst, et) = two_label_graph()
    steps = 4
    ref = numpy_alternating_reference(g.num_vertices, src, dst, et, 0, steps)

    cpu = CPUExecutor(g).run(AlternatingChannelProgram(0, steps))
    np.testing.assert_allclose(cpu["dist"], ref)

    tpu = TPUExecutor(g).run(AlternatingChannelProgram(0, steps))
    np.testing.assert_allclose(np.asarray(tpu["dist"], np.float64), ref)

    mesh = ShardedExecutor(g, mesh=mesh8).run(AlternatingChannelProgram(0, steps))
    np.testing.assert_allclose(np.asarray(mesh["dist"], np.float64), ref)


def test_channels_actually_restrict_traversal(mesh8):
    # path 0 -(label0)-> 1 -(label1)-> 2 -(label0)-> 3; plus a same-label
    # chain 0 -(label0)-> 4 -(label0)-> 5 that the alternation must NOT
    # follow past the first hop on step parity
    src = np.array([0, 1, 2, 0, 4], dtype=np.int32)
    dst = np.array([1, 2, 3, 4, 5], dtype=np.int32)
    et = np.array([0, 1, 0, 0, 0], dtype=np.int32)
    g = csr_from_edges(6, src, dst, edge_types=et)
    res = CPUExecutor(g).run(AlternatingChannelProgram(0, 3))
    d = res["dist"]
    # alternating path reaches 1 (step0, label0), 2 (step1, label1),
    # 3 (step2, label0); 4 is reached at step0 but 4->5 is label0 and only
    # steps 0/2 allow label0: step2 relaxes 4->5 too
    assert d[1] == 1.0 and d[2] == 2.0 and d[3] == 3.0
    assert d[4] == 1.0
    assert d[5] == 2.0  # relaxed at step 2 (label0 allowed again)
    # with only 1 step, 5 is unreachable
    res1 = CPUExecutor(g).run(AlternatingChannelProgram(0, 1))
    assert res1["dist"][5] >= INF


def test_undirected_channel_both_direction(mesh8):
    g, (src, dst, et) = two_label_graph(n=80, m=300, seed=3)

    class BothProgram(AlternatingChannelProgram):
        edge_channels = {
            "even": EdgeChannel(direction="both", labels=(0,)),
            "odd": EdgeChannel(direction="both", labels=(1,)),
        }

    # independent reference with symmetric closure
    def ref_both(steps):
        dist = np.full(g.num_vertices, INF)
        dist[0] = 0.0
        for step in range(steps):
            lab = step % 2
            m = et == lab
            agg = np.full(g.num_vertices, INF)
            np.minimum.at(agg, dst[m], dist[src[m]] + 1.0)
            np.minimum.at(agg, src[m], dist[dst[m]] + 1.0)
            dist = np.minimum(dist, agg)
        return dist

    steps = 4
    ref = ref_both(steps)
    for result in (
        CPUExecutor(g).run(BothProgram(0, steps)),
        TPUExecutor(g).run(BothProgram(0, steps)),
        ShardedExecutor(g, mesh=mesh8).run(BothProgram(0, steps)),
    ):
        np.testing.assert_allclose(np.asarray(result["dist"], np.float64), ref)


def test_gather_ell_combination_rejected(mesh8):
    g, _ = two_label_graph(n=40, m=100)
    with pytest.raises(ValueError, match="a2a"):
        ShardedExecutor(g, mesh=mesh8, exchange="gather", agg="ell")


def test_load_csr_carries_edge_types(tmp_path):
    from janusgraph_tpu.core.graph import open_graph

    g = open_graph()
    mgmt = g.management()
    knows = mgmt.make_edge_label("knows")
    likes = mgmt.make_edge_label("likes")
    tx = g.new_transaction()
    a = tx.add_vertex()
    b = tx.add_vertex()
    c = tx.add_vertex()
    a.add_edge("knows", b)
    b.add_edge("likes", c)
    tx.commit()

    from janusgraph_tpu.olap.csr import load_csr

    csr = load_csr(g)
    assert csr.in_edge_type is not None and csr.out_edge_type is not None
    assert set(csr.out_edge_type.tolist()) == {knows.id, likes.id}
    # a channel restricted to 'knows' has exactly one edge
    s, d, _ = channel_edges(
        csr, EdgeChannel(direction="out", labels=(knows.id,))
    )
    assert len(s) == 1
    g.close()
