"""Round-4 step-vocabulary additions: local/tree/sack/subgraph/cyclic_path/
has_not with TinkerPop 3.4.6 semantics (reference: the TinkerPop step
library the reference inherits, pom.xml:72; strategies registered at
StandardJanusGraph.java:102-116)."""

import pytest

from janusgraph_tpu.core import gods
from janusgraph_tpu.core.graph import open_graph
from janusgraph_tpu.core.traversal import AnonymousTraversal, QueryError

__ = AnonymousTraversal()


@pytest.fixture()
def g():
    graph = open_graph({"ids.authority-wait-ms": 0.0})
    gods.load(graph)
    yield graph
    graph.close()


# ----------------------------------------------------------------- has_not
def test_has_not(g):
    t = g.traversal()
    # monsters/locations have no age property
    no_age = {v.value("name") for v in t.V().has_not("age").to_list()}
    assert "nemean" in no_age and "sky" in no_age
    assert "jupiter" not in no_age
    # complement partitions the vertex set
    with_age = {v.value("name") for v in t.V().has("age").to_list()}
    assert no_age | with_age == {
        v.value("name") for v in t.V().to_list()
    }
    assert not (no_age & with_age)


# ------------------------------------------------------------- cyclic_path
def test_cyclic_path_complements_simple_path(g):
    t = g.traversal()
    both = t.V().out("brother").out("brother").path().to_list()
    cyclic = t.V().out("brother").out("brother").cyclic_path().path().to_list()
    simple = t.V().out("brother").out("brother").simple_path().path().to_list()
    assert len(cyclic) + len(simple) == len(both)
    assert len(cyclic) > 0
    # every cyclic path revisits its start (brother is symmetric)
    for p in (x.obj if hasattr(x, "obj") else x for x in cyclic):
        ids = [o.id for o in p]
        assert len(ids) != len(set(ids))


# ------------------------------------------------------------------- local
def test_local_scopes_limit_per_traverser(g):
    t = g.traversal()
    # global limit: 2 edges TOTAL; local limit: 2 per source vertex
    global_n = len(t.V().out_e().limit(2).to_list())
    local_n = len(t.V().local(lambda s: s.out_e().limit(2)).to_list())
    assert global_n == 2
    # per-source cap: every vertex contributes min(out_degree, 2)
    expect = sum(
        min(2, len(g.traversal().V(v.id).out_e().to_list()))
        for v in t.V().to_list()
    )
    assert local_n == expect > global_n


def test_local_fold_per_traverser(g):
    # fold() inside local gives per-vertex grouping
    folded = g.traversal().V().has("name", "jupiter").local(
        lambda s: s.out("brother").fold()
    ).to_list()
    assert len(folded) == 1 and len(folded[0]) == 2


# -------------------------------------------------------------------- tree
def test_tree_nests_paths(g):
    t = g.traversal()
    tree = t.V().has("name", "hercules").out("battled").tree().to_list()[0]
    assert len(tree) == 1
    herc = next(iter(tree))
    assert herc.value("name") == "hercules"
    children = tree[herc]
    assert {v.value("name") for v in children} == {
        "nemean", "hydra", "cerberus"
    }
    assert all(sub == {} for sub in children.values())


def test_tree_with_by_key(g):
    t = g.traversal()
    tree = (
        t.V().has("name", "jupiter").out("brother").out("lives")
        .tree().by("name").to_list()[0]
    )
    assert set(tree) == {"jupiter"}
    assert set(tree["jupiter"]) == {"neptune", "pluto"}
    assert set(tree["jupiter"]["pluto"]) == {"tartarus"}


# -------------------------------------------------------------------- sack
def test_sack_accumulates(g):
    from janusgraph_tpu.core.traversal import GraphTraversalSource

    src = GraphTraversalSource(g).with_sack(0)
    res = (
        src.V().has("name", "hercules")
        .out_e("battled").sack(lambda s, v: s + v).by("time")
        .in_v().sack().to_list()
    )
    # battled edge times: 1, 2, 12 — one traverser each
    assert sorted(res) == [1, 2, 12]
    # sack() with no fn after with_sack returns the initial value
    res0 = src.V().has("name", "jupiter").sack().to_list()
    assert res0 == [0]


def test_sack_mutable_initial_does_not_alias():
    g2 = open_graph({"ids.authority-wait-ms": 0.0, "schema.default": "auto"})
    tx = g2.new_transaction()
    a = tx.add_vertex(name="a")
    b = tx.add_vertex(name="b")
    tx.commit()
    from janusgraph_tpu.core.traversal import GraphTraversalSource

    src = GraphTraversalSource(g2).with_sack(list)
    sacks = src.V().sack(lambda s, v: s + [v.value("name")]).sack().to_list()
    assert sorted(tuple(s) for s in sacks) == [("a",), ("b",)]
    g2.close()


# ---------------------------------------------------------------- subgraph
def test_subgraph_materializes_induced_graph(g):
    t = g.traversal()
    sg = t.V().out_e("battled").subgraph("sg").cap("sg").to_list()[0]
    names = {v.value("name") for v in sg.traversal().V().to_list()}
    assert names == {"hercules", "nemean", "hydra", "cerberus"}
    edges = sg.traversal().E().to_list()
    assert len(edges) == 3
    assert all(e.label == "battled" for e in edges)
    # edge properties survive
    times = sorted(e.value("time") for e in edges)
    assert times == [1, 2, 12]
    sg.close()


def test_subgraph_rejects_vertex_frontier(g):
    with pytest.raises(QueryError, match="edge traversers"):
        g.traversal().V().subgraph("x").to_list()


def test_sack_splits_across_branches(g):
    """TinkerPop split semantics: a branch's sack updates must stay
    invisible to sibling branches (union hands each branch the same
    parent traverser)."""
    from janusgraph_tpu.core.traversal import GraphTraversalSource

    src = GraphTraversalSource(g).with_sack(0)
    res = src.V().has("name", "jupiter").union(
        lambda t: t.sack(lambda s, _v: s + 1).sack(),
        lambda t: t.sack(),
    ).to_list()
    assert res == [1, 0]


def test_sack_survives_match(g):
    from janusgraph_tpu.core.traversal import GraphTraversalSource

    src = GraphTraversalSource(g).with_sack(7)
    res = (
        src.V().has("name", "hercules")
        .match(__.as_("a").out("father").as_("b"))
        .sack().to_list()
    )
    assert res == [7]


def test_subgraph_preserves_list_cardinality():
    from janusgraph_tpu.core.codecs import Cardinality

    g2 = open_graph({"ids.authority-wait-ms": 0.0, "schema.default": "auto"})
    mgmt = g2.management()
    mgmt.make_property_key("nickname", str, Cardinality.LIST)
    tx = g2.new_transaction()
    a = tx.add_vertex(name="a")
    a.property("nickname", "ace")
    a.property("nickname", "alpha")
    b = tx.add_vertex(name="b")
    tx.add_edge(a, "knows", b)
    tx.commit()
    sg = g2.traversal().V().out_e("knows").subgraph("s").cap("s").to_list()[0]
    va = sg.traversal().V().has("name", "a").next()
    nicks = sorted(p.value for p in va.properties("nickname"))
    assert nicks == ["ace", "alpha"]
    sg.close()
    g2.close()


def test_subgraph_multivalue_single_first_ordering():
    """Regression: a key seen single-valued FIRST must still copy as LIST
    when another endpoint holds several values (pre-scan, not first-wins)."""
    from janusgraph_tpu.core.codecs import Cardinality

    g2 = open_graph({"ids.authority-wait-ms": 0.0, "schema.default": "auto"})
    mgmt = g2.management()
    mgmt.make_property_key("nickname", str, Cardinality.LIST)
    tx = g2.new_transaction()
    a = tx.add_vertex(name="a")
    a.property("nickname", "only")       # single-valued on a
    b = tx.add_vertex(name="b")
    b.property("nickname", "bee")
    b.property("nickname", "buzz")       # multi-valued on b
    tx.add_edge(a, "knows", b)           # a (out) copies BEFORE b (in)
    tx.commit()
    sg = g2.traversal().V().out_e("knows").subgraph("s").cap("s").to_list()[0]
    vb = sg.traversal().V().has("name", "b").next()
    assert sorted(p.value for p in vb.properties("nickname")) == [
        "bee", "buzz"
    ]
    va = sg.traversal().V().has("name", "a").next()
    assert [p.value for p in va.properties("nickname")] == ["only"]
    sg.close()
    g2.close()


def test_label_step(g):
    labels = set(g.traversal().V().label().to_list())
    assert "god" in labels and "monster" in labels


def test_element_map(g):
    m = (
        g.traversal().V().has("name", "hercules").element_map().to_list()[0]
    )
    assert m["label"] == "demigod" and m["name"] == "hercules"
    assert m["age"] == 30 and "id" in m
    only_name = (
        g.traversal().V().has("name", "hercules")
        .element_map("name").to_list()[0]
    )
    assert set(only_name) == {"id", "label", "name"}
    # edges carry endpoint summaries under Direction keys (TinkerPop shape)
    em = (
        g.traversal().V().has("name", "hercules")
        .out_e("battled").element_map().to_list()[0]
    )
    assert em["label"] == "battled"
    from janusgraph_tpu.core.codecs import Direction

    assert em[Direction.OUT]["label"] == "demigod"
    assert em[Direction.IN]["label"] == "monster"
    # non-element traversers refuse loudly
    with pytest.raises(QueryError, match="element_map"):
        g.traversal().V().values("name").element_map().to_list()


def test_drop_step_vertices_edges_properties(g):
    t = g.traversal()
    n_before = t.V().count()
    # drop edges first (battled), then a vertex, then a property
    src = t.V().has("name", "hercules").next()
    assert t.V().has_id(src.id).out_e("battled").count() == 3
    tx = t.tx
    t.V().has_id(src.id).out_e("battled").drop().to_list()
    tx.commit()
    t2 = g.traversal()
    assert t2.V().has_id(src.id).out_e("battled").count() == 0
    t2.V().has("name", "nemean").drop().to_list()
    t2.tx.commit()
    t3 = g.traversal()
    assert t3.V().count() == n_before - 1
    t3.V().has("name", "jupiter").properties("age").drop().to_list()
    t3.tx.commit()
    assert g.traversal().V().has("name", "jupiter").next().value("age") is None


def test_property_step_mutates_elements(g):
    """TinkerPop PropertyStep: g.V().has(...).property('k', v) updates
    through the traversal; SINGLE cardinality replaces; edges update too."""
    t = g.traversal()
    # vertex property (SINGLE: replaces)
    t.V().has("name", "hercules").property("age", 31).iterate()
    t.tx.commit()
    assert g.traversal().V().has("name", "hercules").values(
        "age"
    ).to_list() == [31]
    # multiple kwargs at once
    t2 = g.traversal()
    t2.V().has("name", "hercules").property(None, None, age=32).iterate()
    t2.tx.commit()
    assert g.traversal().V().has("name", "hercules").values(
        "age"
    ).to_list() == [32]
    # edge property
    t3 = g.traversal()
    t3.V().has("name", "hercules").out_e("battled").property(
        "place_name", "arena"
    ).iterate()
    t3.tx.commit()
    vals = (
        g.traversal().V().has("name", "hercules").out_e("battled")
        .values("place_name").to_list()
    )
    assert vals == ["arena", "arena", "arena"]
    # non-element traversers refuse
    import pytest as _p

    with _p.raises(QueryError, match="property"):
        g.traversal().V().values("name").property("x", 1).to_list()
    # same-traversal visibility + drop() must act on the LIVE edge
    vals = (
        g.traversal().V().has("name", "hercules").out_e("battled")
        .property("place_name", "pit").values("place_name").to_list()
    )
    assert vals == ["pit", "pit", "pit"]
    td = g.traversal()
    td.V().has("name", "hercules").out_e("battled").property(
        "x", 1
    ).drop().iterate()
    td.tx.commit()
    assert g.traversal().V().has("name", "hercules").out_e(
        "battled"
    ).to_list() == []


def test_add_e_step_wires_edges(g):
    """TinkerPop AddEdgeStep: g.V().has(...).add_e_('l').to_(target) — one
    edge per traverser; targets as Vertex, as_() tag, or sub-traversal."""
    t = g.traversal()
    jup = t.V().has("name", "jupiter").next()
    # vertex target + property
    t2 = g.traversal()
    t2.V().has("name", "hercules").add_e_("admires", since=2020).to_(
        jup
    ).iterate()
    t2.tx.commit()
    edges = g.traversal().V().has("name", "hercules").out_e(
        "admires"
    ).to_list()
    assert len(edges) == 1 and edges[0].value("since") == 2020
    assert edges[0].in_vertex.value("name") == "jupiter"

    # sub-traversal target, from_ overriding the out endpoint
    t3 = g.traversal()
    t3.V().has("name", "hercules").add_e_("patron").from_(
        __.out("father")
    ).to_(__.out("mother")).iterate()
    t3.tx.commit()
    e = g.traversal().V().has("name", "jupiter").out_e("patron").to_list()
    assert len(e) == 1 and e[0].in_vertex.value("name") == "alcmene"

    # as_() tag target
    t4 = g.traversal()
    t4.V().has("name", "pluto").as_("p").out("brother").add_e_(
        "rival"
    ).to_("p").iterate()
    t4.tx.commit()
    rivals = {
        e.out_vertex.value("name")
        for e in g.traversal().V().has("name", "pluto").in_e("rival").to_list()
    }
    assert rivals == {"jupiter", "neptune"}

    # errors: missing to_, ambiguous sub-traversal, non-vertex frontier
    with pytest.raises(QueryError, match="to_"):
        g.traversal().V().add_e_("x").to_list()
    with pytest.raises(QueryError, match="exactly one"):
        tt = g.traversal()
        tt.V().has("name", "jupiter").add_e_("x").to_(
            __.out("brother")  # two brothers
        ).to_list()
    with pytest.raises(QueryError, match="vertex traversers"):
        g.traversal().V().values("name").add_e_("x").to_(jup).to_list()


def test_add_e_and_property_handle_liveness(g):
    """Review regressions: other_v() after add_e_ sees the anchor vertex;
    edge-tagged endpoints refuse; path()/select() after edge property()
    carry the LIVE replacement."""
    t = g.traversal()
    jup = t.V().has("name", "jupiter").next()
    # other_v() works right after add_e_
    others = (
        g.traversal().V().has("name", "hercules")
        .add_e_("cheers").to_(jup).other_v().values("name").to_list()
    )
    assert others == ["jupiter"]
    # edge-tagged endpoint refuses loudly instead of corrupting
    with pytest.raises(QueryError, match="must be a vertex"):
        (
            g.traversal().V().has("name", "hercules").out_e("battled")
            .as_("e").out_v().add_e_("weird").to_("e").iterate()
        )
    # path()/select() read the live post-property edge
    p = (
        g.traversal().V().has("name", "hercules").out_e("battled")
        .property("pp", 7).path().to_list()[0]
    )
    assert p[-1].value("pp") == 7
    sel = (
        g.traversal().V().has("name", "hercules").out_e("battled")
        .as_("e").property("qq", 8).select("e").to_list()
    )
    assert all(e.value("qq") == 8 for e in sel)


# ------------------------------------------------- chained repeat modulators
def test_repeat_chained_modulators(g):
    """The REAL Gremlin loop spellings: repeat(...).times(n) /
    .until(...) / .emit() as POST-modulators (TinkerPop RepeatStep
    modulation), equivalent to the kwarg forms."""
    t = g.traversal()
    chained = t.V().has("name", "saturn").repeat(
        __.in_("father")
    ).times(2).values("name").to_list()
    kwarg = t.V().has("name", "saturn").repeat(
        __.in_("father"), times=2
    ).values("name").to_list()
    assert chained == kwarg == ["hercules"]

    got = t.V().has("name", "hercules").repeat(__.out("father")).until(
        __.has("name", "saturn")
    ).values("name").to_list()
    assert got == ["saturn"]

    emitted = t.V().has("name", "saturn").repeat(
        __.in_("father")
    ).emit().values("name").to_list()
    assert set(emitted) == {"jupiter", "hercules"}

    # until + emit combined, chained in either order
    both = t.V().has("name", "hercules").repeat(__.out("father")).emit(
    ).until(__.has("name", "saturn")).values("name").to_list()
    assert set(both) == {"jupiter", "saturn"}


def test_repeat_modulator_window_rules(g):
    from janusgraph_tpu.core.traversal import QueryError

    t = g.traversal()
    # bare repeat with no control raises at EXECUTION
    with pytest.raises(QueryError, match="times\\(\\)/until\\(\\)/emit"):
        t.V().repeat(__.out("father")).to_list()
    # modulators without a preceding repeat raise at build
    with pytest.raises(QueryError, match="must follow repeat"):
        t.V().times(2)
    with pytest.raises(QueryError, match="must follow repeat"):
        t.V().until(__.has("name", "x"))
    # a step between repeat and the modulator closes the window
    with pytest.raises(QueryError, match="must follow repeat"):
        t.V().repeat(__.out("father")).count_().times(2)


def test_emit_predicate_filter(g):
    """emit(predicate): the Gremlin emit(has(...)) filter form."""
    t = g.traversal()
    only = t.V().has("name", "saturn").repeat(__.in_("father")).emit(
        __.has("name", "hercules")
    ).values("name").to_list()
    assert only == ["hercules"]


def test_has_on_label_name_is_unknown_key(g):
    """A has() key colliding with a vertex/edge LABEL name is still an
    unknown PROPERTY key (the check is PropertyKey-specific)."""
    from janusgraph_tpu.core.traversal import QueryError

    t = g.traversal()
    with pytest.raises(QueryError, match="unknown property key"):
        t.V().has("god", 1).to_list()  # 'god' is a vertex label
    with pytest.raises(QueryError, match="unknown property key"):
        t.V().has("father", 1).to_list()  # 'father' is an edge label


def test_frontier_tier_growth_guard():
    from janusgraph_tpu.olap.frontier import _tier

    with pytest.raises(ValueError, match="growth"):
        _tier(5000, 1 << 10, 1 << 20, 1)


# ------------------------------------------------------------------- math()
def test_math_step(g):
    """TinkerPop MathStep: '_' = incoming value, tag variables, by()
    extraction, whitelisted functions, sandboxed expressions."""
    t = g.traversal()
    vals = t.V().has("name", "jupiter").values("age").math("_ / 1000").to_list()
    assert vals == [5.0]
    # tag variables with by() extraction
    got = (
        t.V().has("name", "jupiter").as_("a")
        .out("brother").as_("b")
        .math("a - b").by("age")
        .to_list()
    )
    assert set(got) == {500, 1000}  # 5000 - 4500, 5000 - 4000
    # functions
    assert t.V().has("name", "jupiter").values("age").math(
        "sqrt(_) + abs(-1)"
    ).to_list() == [5000 ** 0.5 + 1]
    # by() binds in SOURCE left-to-right order even under nesting
    # (ast.walk is BFS and would yield c before a/b, swapping specs):
    # a -> by('age'), b -> by('age'), c (a numeric tag) -> identity by()
    got = (
        t.V().has("name", "jupiter").as_("a")
        .out("brother").has("name", "neptune").as_("b")
        .values("age").as_("c")
        .math("(a + b) * c").by("age").by("age").by()
        .to_list()
    )
    assert got == [(5000 + 4500) * 4500]


def test_math_step_sandbox(g):
    from janusgraph_tpu.core.traversal import QueryError

    t = g.traversal()
    for bad in ("__import__('os')", "_.denominator", "'x' + 'y'",
                "a if a else 0", "[1,2][0]", "lambda: 1",
                "sqrt", "sqrt + 1", "_ + True"):
        with pytest.raises(QueryError):
            t.V().values("age").math(bad)
    # runtime evaluation errors surface as QueryError uniformly
    with pytest.raises(QueryError, match="ZeroDivision"):
        t.V().has("name", "jupiter").values("age").math("_ / 0").to_list()
    with pytest.raises(QueryError, match="math"):
        t.V().has("name", "jupiter").values("age").math(
            "sqrt(0 - _)"
        ).to_list()
    # unbound tag at execution
    with pytest.raises(QueryError, match="not a bound"):
        t.V().has("name", "jupiter").math("zz + 1").to_list()
    # non-numeric value at execution
    with pytest.raises(QueryError, match="not a number"):
        t.V().has("name", "jupiter").values("name").math("_ + 1").to_list()


# ----------------------------------------------- traversal-embedded OLAP
def test_page_rank_step(g):
    """g.V().pageRank(): OLAP ranks flow into the OLTP traversal as a
    transient property (TinkerPop pageRank() through the computer)."""
    t = g.traversal()
    top = (
        t.V().page_rank()
        .order("pagerank", reverse=True).limit(2)
        .values("name").to_list()
    )
    # jupiter is the gods graph's hub; ranks exist on every vertex
    assert len(top) == 2
    ranks = t.V().page_rank().values("pagerank").to_list()
    assert len(ranks) == 12 and all(r > 0 for r in ranks)
    assert abs(sum(ranks) - 1.0) < 1e-3
    # transient: other traversals (even from the same source) never see
    # the overlay, and nothing was written to the tx or the schema
    t2 = g.traversal()
    assert t2.V().has_label("god").value_map("pagerank").to_list()[0] == {}
    assert g.schema_cache.get_by_name("pagerank") is None
    # read-only transactions can run the computer steps (pure reads)
    from janusgraph_tpu.core.traversal import GraphTraversalSource

    ro = GraphTraversalSource(g, g.new_transaction(read_only=True))
    ranks_ro = ro.V().page_rank().values("pagerank").to_list()
    assert len(ranks_ro) == 12


def test_page_rank_overlay_semantics(g):
    """Overlay SHADOWS stored same-key properties, is visible to
    sub-traversal bodies, and honors the TinkerPop alpha overload."""
    t = g.traversal()
    vals = t.V().page_rank().values("pagerank").to_list()
    assert len(vals) == 12
    # overlay SHADOWS a stored same-key property: one value per vertex
    gods_with_age = t.V().has("age").count()
    shadowed = t.V().has("age").page_rank(key="age").values("age").to_list()
    assert len(shadowed) == gods_with_age  # no duplicates
    assert all(v < 1 for v in shadowed)  # ranks, not the stored ages
    vm = t.V().has("age").page_rank(key="age").value_map("age").to_list()
    assert all(len(m["age"]) == 1 for m in vm)
    # the overlay does NOT leak into later traversals from the SAME source
    later = t.V().has("name", "jupiter").values("age").to_list()
    assert later == [5000]
    # no-arg value_map/values surface the annotated key in-traversal
    full = t.V().page_rank().has("name", "jupiter").value_map().to_list()
    assert "pagerank" in full[0]
    # sub-traversal by() form sees the overlay
    via_body = (
        t.V().page_rank()
        .order().by(lambda x: x.values("pagerank"), reverse=True)
        .limit(1).values("name").to_list()
    )
    via_key = (
        t.V().page_rank()
        .order("pagerank", reverse=True).limit(1).values("name").to_list()
    )
    assert via_body == via_key
    # alpha overload
    r_none = t.V().page_rank("pagerank", iterations=30).values(
        "pagerank").to_list()
    r_low = t.V().page_rank(0.5, iterations=30).values(
        "pagerank").to_list()
    assert r_none != r_low  # damping changed the fixpoint
    # empty frontier short-circuits the compute entirely (barrier guard)
    assert t.V().has("name", "nobody-with-this-name").page_rank(
    ).to_list() == []


def test_connected_component_step(g):
    t = g.traversal()
    comps = t.V().connected_component().values("component").to_list()
    assert len(comps) == 12
    assert len(set(comps)) == 1  # gods graph is one connected component
    # the component id is a real member vertex id
    assert comps[0] in {v.id for v in t.V().to_list()}


def test_shortest_path_step(g):
    """TinkerPop shortestPath(): per-source BFS paths via the OLAP
    predecessor-tracking program."""
    t = g.traversal()
    paths = t.V().has("name", "hercules").shortest_path().to_list()
    assert paths and all(p[0].value("name") == "hercules" for p in paths)
    by_target = {p[-1].value("name"): p for p in paths}
    # hercules -> jupiter is one hop (father)
    assert len(by_target["jupiter"]) == 2
    # hercules -> saturn is two hops (father.father)
    assert len(by_target["saturn"]) == 3
    # target filter narrows the emitted paths
    only = t.V().has("name", "hercules").shortest_path(
        target=__.has("name", "saturn")
    ).to_list()
    assert len(only) == 1 and only[0][-1].value("name") == "saturn"
    # every path is a genuine edge chain
    tx = t.tx
    from janusgraph_tpu.core.codecs import Direction

    for p in only:
        for a, b in zip(p, p[1:]):
            nbrs = {e.other(a).id
                    for e in tx.get_edges(a, Direction.BOTH, ())}
            assert b.id in nbrs


def test_page_rank_step_on_sharded_executor():
    """The computer steps honor computer.executor: ranks computed on the
    8-virtual-device sharded mesh flow into the same OLTP overlay."""
    graph = open_graph({
        "ids.authority-wait-ms": 0.0, "computer.executor": "sharded",
    })
    gods.load(graph)
    try:
        t = graph.traversal()
        ranks = t.V().page_rank().values("pagerank").to_list()
        assert len(ranks) == 12 and abs(sum(ranks) - 1.0) < 1e-3
        # parity with the cpu-executor result
        g2 = open_graph({
            "ids.authority-wait-ms": 0.0, "computer.executor": "cpu",
        })
        try:
            gods.load(g2)
            r2 = sorted(g2.traversal().V().page_rank().values(
                "pagerank").to_list())
            assert all(
                abs(a - b) < 1e-6 for a, b in zip(sorted(ranks), r2)
            )
        finally:
            g2.close()
    finally:
        graph.close()


def test_order_missing_key_sorts_last_both_directions(g):
    """Vertices missing the order key sort LAST under both directions
    (regression: the (is-None, val) tuple put them FIRST under
    reverse=True — visible when uncommitted vertices lack a pageRank
    snapshot value)."""
    t = g.traversal()
    t.add_v_("god").property("name", "nameless-ageless").iterate()
    asc = t.V().order("age").values("name").to_list()
    desc = t.V().order("age", reverse=True).values("name").to_list()
    # monsters/locations/the new vertex have no age: always at the end
    no_age = {v.value("name") for v in t.V().has_not("age").to_list()}
    k = len(no_age)
    assert set(asc[-k:]) == no_age
    assert set(desc[-k:]) == no_age
    assert asc[:-k] == list(reversed(desc[:-k]))
    # the by()-modulated branch behaves identically
    desc_by = t.V().order().by("age", reverse=True).values(
        "name").to_list()
    assert set(desc_by[-k:]) == no_age
    assert desc_by[:-k] == desc[:-k]


def test_shortest_path_weighted(g):
    """shortest_path(weight_key=...): Dijkstra-equivalent paths over an
    edge property (battled edges carry 'time')."""
    t = g.traversal()
    paths = t.V().has("name", "hercules").shortest_path(
        weight_key="time", max_hops=50
    ).to_list()
    assert paths
    # weighted reach includes battled monsters; each path is a real chain
    names = {p[-1].value("name") for p in paths}
    assert "nemean" in names or "hydra" in names
    # a typo'd weight key fails eagerly with the real cause
    from janusgraph_tpu.core.traversal import QueryError

    with pytest.raises(QueryError, match="not a property key"):
        t.V().has("name", "hercules").shortest_path(
            weight_key="tmie"
        ).to_list()


def test_loops_and_barrier(g):
    """loops() reads repeat() depth (until(loops().is_(n)) bounds);
    barrier() is the documented batch-model no-op."""
    t = g.traversal()
    got = (
        t.V().has("name", "saturn")
        .repeat(__.in_("father")).until(__.loops().is_(2))
        .values("name").to_list()
    )
    # exactly 2 hops up the father chain from saturn
    assert got == ["hercules"]
    assert t.V().barrier().count() == 12
    # depth visible via emit too: emitted traversers carry their depth
    depths = (
        t.V().has("name", "saturn").repeat(__.in_("father")).emit()
        .loops().to_list()
    )
    assert sorted(depths) == [1, 2]


def test_loops_depth_semantics(g):
    """Review repros: depth survives map steps (child), emitted depths
    are frozen per round (no aliasing rewrite), and the kwarg times form
    matches the chained spelling."""
    t = g.traversal()
    # filter-only body + emit: depths are per-round, not all-final
    depths = t.V().has("name", "saturn").repeat(
        __.in_("father")
    ).emit().out_e("father").loops().to_list()
    # jupiter (depth 1) and hercules (depth 2) each have an out-father
    # edge; the depth rides through the edge expansion
    assert sorted(depths) == [1, 2]
    # depth survives map steps after the loop
    d2 = t.V().has("name", "saturn").repeat(__.in_("father")).emit(
    ).values("name").loops().to_list()
    assert sorted(d2) == [1, 2]
    # kwarg times == chained times for loops()
    a = t.V().has("name", "saturn").repeat(
        __.in_("father"), times=2
    ).loops().to_list()
    b = t.V().has("name", "saturn").repeat(
        __.in_("father")
    ).times(2).loops().to_list()
    assert a == b == [2]
    # aliasing: filter-only body emits each round's own depth
    fa = t.V().has("name", "jupiter").repeat(__.has("age")).emit(
    ).times(3).loops().to_list()
    assert sorted(fa) == [1, 2, 3]
    # barrier accepts TinkerPop's size argument
    assert t.V().barrier(2500).count() == 12


def test_round5_small_steps(g):
    """identity/none/map_/flat_map/key/value/has_key/has_value/
    peer_pressure — the remaining TinkerPop step-library vocabulary."""
    t = g.traversal()
    assert t.V().identity().count() == 12
    assert t.V().none().to_list() == []
    assert sorted(
        t.V().has_label("god").map_(lambda v: v.value("name")).to_list()
    ) == ["jupiter", "neptune", "pluto"]
    assert sorted(
        t.V().has("name", "jupiter").flat_map(
            lambda v: v.value("name")
        ).to_list()
    ) == sorted("jupiter")
    # property-traverser steps
    ks = t.V().has("name", "saturn").properties().key().to_list()
    assert set(ks) == {"name", "age"}
    vs = t.V().has("name", "saturn").properties("age").value().to_list()
    assert vs == [10000]
    assert t.V().properties().has_key("age").count() == len(
        t.V().has("age").to_list()
    )
    assert t.V().properties().has_value("saturn").value().to_list() == [
        "saturn"
    ]
    from janusgraph_tpu.core.predicates import Cmp  # noqa: F401
    from janusgraph_tpu.core.traversal import P

    assert t.V().properties("age").has_value(P.gt(9000)).count() == 1
    # key()/value() on non-properties raise
    from janusgraph_tpu.core.traversal import QueryError

    with pytest.raises(QueryError):
        t.V().key().to_list()
    # peerPressure computer step: cluster ids are member VERTEX ids
    clusters = t.V().peer_pressure().values("cluster").to_list()
    vids = {v.id for v in t.V().to_list()}
    assert len(clusters) == 12 and set(clusters) <= vids
    # brothers end up co-clustered with high probability on this tiny
    # graph; at minimum the key exists for every vertex and is stable
    again = t.V().peer_pressure().values("cluster").to_list()
    assert clusters == again


def test_map_flatmap_traversal_bodies(g):
    """map(traversal)/flatMap(traversal) — the only form expressible over
    the text endpoint (the sandbox rejects lambdas)."""
    t = g.traversal()
    names = t.V().has("name", "jupiter").flat_map(
        __.out("brother")
    ).values("name").to_list()
    assert sorted(names) == ["neptune", "pluto"]
    firsts = t.V().has_label("god").map_(__.values("name")).to_list()
    assert sorted(firsts) == ["jupiter", "neptune", "pluto"]
    # map drops traversers whose body yields nothing
    assert t.V().has_label("monster").map_(
        __.out("father")
    ).to_list() == []


def test_branch_option_fail_property_map(g):
    """branch().option() multiway dispatch with Pick tokens; fail();
    propertyMap() with VertexProperty values."""
    from janusgraph_tpu.core.traversal import Pick, QueryError

    t = g.traversal()
    got = (
        t.V().has_label("god")
        .branch(__.values("name"))
        .option("jupiter", __.out("brother").values("name"))
        .option(Pick.none, __.constant("other-god"))
        .to_list()
    )
    assert sorted(got) == ["neptune", "other-god", "other-god", "pluto"]
    # Pick.any fires alongside the matched option
    got2 = (
        t.V().has("name", "jupiter")
        .branch(__.label())
        .option("god", __.constant("matched"))
        .option(Pick.any, __.constant("always"))
        .to_list()
    )
    assert sorted(got2) == ["always", "matched"]
    # fail()
    with pytest.raises(QueryError, match="no monsters allowed"):
        t.V().has_label("monster").fail("no monsters allowed").to_list()
    assert t.V().has_label("nosuchlabel").fail().to_list() == []
    # propertyMap: values are VertexProperty objects (meta reachable)
    pm = t.V().has("name", "saturn").property_map("name").next()
    from janusgraph_tpu.core.elements import VertexProperty

    assert isinstance(pm["name"][0], VertexProperty)
    assert pm["name"][0].value == "saturn"


def test_step_window_and_prev_regressions(g):
    """Review repros: prev survives map_/flat_map; property_map handles
    edges; misplaced merge modulators raise; GraphTraversal args raise
    cleanly."""
    from janusgraph_tpu.core.traversal import QueryError, T

    t = g.traversal()
    names = (
        t.V().has("name", "jupiter").out_e("brother")
        .flat_map(__.identity()).other_v().values("name").to_list()
    )
    assert sorted(names) == ["neptune", "pluto"]
    em = t.V().has("name", "hercules").out_e("battled").property_map(
    ).to_list()
    assert em and all("time" in m for m in em)
    # a step between merge and its modulator closes the window
    with pytest.raises(QueryError, match="must follow"):
        t.merge_v({T.label: "god", "name": "x"}).identity().on_create(
            {"age": 1}
        )
    # non-anonymous traversal argument is a clean type error
    with pytest.raises((QueryError, TypeError)):
        t.V().map_(t.V()).to_list()


def test_to_bulk_set_and_element(g):
    t = g.traversal()
    bulk = t.V().out("brother").values("name").to_bulk_set()
    assert bulk["jupiter"] == 2  # two brothers point back at jupiter
    owners = t.V().properties("age").element().dedup().count()
    assert owners == len(t.V().has("age").to_list())
