"""Test harness configuration.

Tests run on CPU with 8 virtual XLA devices so multi-chip sharding paths
(Mesh/shard_map over partitions) are exercised without TPU hardware — the
"multi-node without a cluster" technique, mirroring the reference's pattern
of opening several store managers against one backend in a single JVM
(reference: janusgraph-backend-testutils .../IDAuthorityTest.java,
LogTest.java).
"""

import os

# Must be set before the first backend initialization. Forced (not
# setdefault): the ambient environment (axon sitecustomize) points JAX at
# the real TPU and registers that backend at interpreter start, but the
# suite needs the 8-virtual-device CPU mesh; backends initialize lazily, so
# repointing the config here — before any jax.devices() call — wins.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

from janusgraph_tpu.storage.inmemory import InMemoryStoreManager  # noqa: E402


def _make_backend(kind: str, tmp_path):
    if kind == "inmemory":
        return InMemoryStoreManager()
    if kind == "local":
        from janusgraph_tpu.storage.localstore import open_local_kcvs

        return open_local_kcvs(str(tmp_path / "localstore"), fsync=False)
    if kind == "sharded":
        from janusgraph_tpu.storage.sharded_store import ShardedStoreManager

        return ShardedStoreManager(num_nodes=3)
    if kind == "ttl":
        from janusgraph_tpu.storage.ttl import TTLStoreManager

        # ttl=0 (never expires): exercises the value framing transparently
        return TTLStoreManager(InMemoryStoreManager(), default_ttl_seconds=0.0)
    if kind == "remote":
        # a REAL networked backend: every store op crosses a TCP socket to
        # an in-process server (the cql/hbase-analogue adapter)
        from janusgraph_tpu.storage.remote import (
            RemoteStoreManager,
            RemoteStoreServer,
        )

        server = RemoteStoreServer(InMemoryStoreManager()).start()
        host, port = server.address
        mgr = RemoteStoreManager(host, port)
        orig_close = mgr.close

        def close_with_server():
            orig_close()
            server.stop()

        mgr.close = close_with_server
        return mgr
    raise ValueError(kind)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running soak/stress cases excluded from tier-1 "
        "(-m 'not slow')",
    )


@pytest.fixture(params=["inmemory", "local", "sharded", "ttl", "remote"])
def store_manager(request, tmp_path):
    """Parameterization point for backend-contract suites: every backend
    must pass the same abstract suites (the reference's
    backend-testutils pattern: abstract suites subclassed per backend)."""
    mgr = _make_backend(request.param, tmp_path)
    yield mgr
    mgr.close()
