"""Test harness configuration.

Tests run on CPU with 8 virtual XLA devices so multi-chip sharding paths
(Mesh/shard_map over partitions) are exercised without TPU hardware — the
"multi-node without a cluster" technique, mirroring the reference's pattern
of opening several store managers against one backend in a single JVM
(reference: janusgraph-backend-testutils .../IDAuthorityTest.java,
LogTest.java).
"""

import os

# Must be set before the first backend initialization. Forced (not
# setdefault): the ambient environment (axon sitecustomize) points JAX at
# the real TPU and registers that backend at interpreter start, but the
# suite needs the 8-virtual-device CPU mesh; backends initialize lazily, so
# repointing the config here — before any jax.devices() call — wins.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

from janusgraph_tpu.storage.inmemory import InMemoryStoreManager  # noqa: E402


@pytest.fixture
def store_manager():
    """Parameterization point for backend-contract suites: every backend
    must pass the same abstract suites (the reference's
    backend-testutils pattern)."""
    mgr = InMemoryStoreManager()
    yield mgr
    mgr.close()
