"""Attribute serializer tests (reference model: janusgraph-test serializer
suites — round trips and the order-preserving encodings that back sort keys
and composite-index keys)."""

import random
import struct
import uuid
from datetime import datetime, timezone

import pytest

from janusgraph_tpu.core.attributes import (
    GeoshapePoint,
    Serializer,
    SerializerError,
)


@pytest.fixture
def ser():
    return Serializer()


SAMPLES = [
    True,
    False,
    0,
    -1,
    2**62,
    -(2**62),
    3.14159,
    -0.0,
    float("inf"),
    "hello",
    "ünïcødé ✓",
    "",
    b"\x00\xff raw",
    datetime(2026, 7, 29, 12, 0, tzinfo=timezone.utc),
    uuid.uuid5(uuid.NAMESPACE_DNS, "janusgraph-tpu"),
    [1.0, 2.5, -3.0],
    GeoshapePoint(37.97, 23.72),
]


@pytest.mark.parametrize("value", SAMPLES, ids=[repr(v)[:30] for v in SAMPLES])
def test_framed_roundtrip(ser, value):
    data = ser.write_object(value)
    out, consumed = ser.read_object(data)
    assert out == value
    assert consumed == len(data)
    assert type(out) is type(value)


def test_bool_not_confused_with_int(ser):
    assert ser.read_object(ser.write_object(True))[0] is True
    assert ser.read_object(ser.write_object(1))[0] == 1
    assert type(ser.read_object(ser.write_object(1))[0]) is int


def test_ordered_long_sorts(ser):
    rng = random.Random(7)
    values = [rng.randint(-(2**62), 2**62) for _ in range(200)] + [0, 1, -1]
    encs = [(ser.write_ordered(v), v) for v in values]
    assert [v for _, v in sorted(encs)] == sorted(values)


def test_ordered_double_sorts(ser):
    rng = random.Random(8)
    values = [rng.uniform(-1e9, 1e9) for _ in range(200)] + [0.0, -0.5, 1e-300]
    encs = [(ser.write_ordered(v), v) for v in values]
    assert [v for _, v in sorted(encs)] == sorted(values)


def test_ordered_string_sorts_and_terminates(ser):
    values = ["", "a", "ab", "b", "ba", "z"]
    encs = [(ser.write_ordered(v), v) for v in values]
    assert [v for _, v in sorted(encs)] == sorted(values)
    with pytest.raises(SerializerError):
        ser.write_ordered("bad\x00nul")


def test_unknown_type_rejected(ser):
    class Foo:
        pass

    with pytest.raises(SerializerError):
        ser.write_object(Foo())


def test_unknown_id_rejected(ser):
    with pytest.raises(SerializerError):
        ser.read_object(struct.pack(">H", 9999) + b"x")


def test_mid_stream_fixed_width(ser):
    """Fixed-width framed values can be decoded mid-stream (needed for
    property cells where the value follows a relation-id header)."""
    data = ser.write_object(42) + b"trailing"
    value, consumed = ser.read_object(data)
    assert value == 42
    assert data[consumed:] == b"trailing"
