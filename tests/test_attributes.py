"""Attribute serializer tests (reference model: janusgraph-test serializer
suites — round trips and the order-preserving encodings that back sort keys
and composite-index keys)."""

import random
import struct
import uuid
from datetime import datetime, timezone

import pytest

from janusgraph_tpu.core.attributes import (
    GeoshapePoint,
    Serializer,
    SerializerError,
)


@pytest.fixture
def ser():
    return Serializer()


SAMPLES = [
    True,
    False,
    0,
    -1,
    2**62,
    -(2**62),
    3.14159,
    -0.0,
    float("inf"),
    "hello",
    "ünïcødé ✓",
    "",
    b"\x00\xff raw",
    datetime(2026, 7, 29, 12, 0, tzinfo=timezone.utc),
    uuid.uuid5(uuid.NAMESPACE_DNS, "janusgraph-tpu"),
    [1.0, 2.5, -3.0],
    GeoshapePoint(37.97, 23.72),
]


@pytest.mark.parametrize("value", SAMPLES, ids=[repr(v)[:30] for v in SAMPLES])
def test_framed_roundtrip(ser, value):
    data = ser.write_object(value)
    out, consumed = ser.read_object(data)
    assert out == value
    assert consumed == len(data)
    assert type(out) is type(value)


def test_bool_not_confused_with_int(ser):
    assert ser.read_object(ser.write_object(True))[0] is True
    assert ser.read_object(ser.write_object(1))[0] == 1
    assert type(ser.read_object(ser.write_object(1))[0]) is int


def test_ordered_long_sorts(ser):
    rng = random.Random(7)
    values = [rng.randint(-(2**62), 2**62) for _ in range(200)] + [0, 1, -1]
    encs = [(ser.write_ordered(v), v) for v in values]
    assert [v for _, v in sorted(encs)] == sorted(values)


def test_ordered_double_sorts(ser):
    rng = random.Random(8)
    values = [rng.uniform(-1e9, 1e9) for _ in range(200)] + [0.0, -0.5, 1e-300]
    encs = [(ser.write_ordered(v), v) for v in values]
    assert [v for _, v in sorted(encs)] == sorted(values)


def test_ordered_string_sorts_and_terminates(ser):
    values = ["", "a", "ab", "b", "ba", "z"]
    encs = [(ser.write_ordered(v), v) for v in values]
    assert [v for _, v in sorted(encs)] == sorted(values)
    with pytest.raises(SerializerError):
        ser.write_ordered("bad\x00nul")


def test_unknown_type_rejected(ser):
    # unpicklable (local class) objects still fail loudly; picklable unknown
    # types now ride the object fallback (reference: ObjectSerializer id 1,
    # StandardSerializer.java:78) — see test_serializer_parity.py
    class Foo:
        pass

    with pytest.raises(SerializerError):
        ser.write_object(Foo())


def test_unknown_id_rejected(ser):
    with pytest.raises(SerializerError):
        ser.read_object(struct.pack(">H", 9999) + b"x")


def test_mid_stream_fixed_width(ser):
    """Fixed-width framed values can be decoded mid-stream (needed for
    property cells where the value follows a relation-id header)."""
    data = ser.write_object(42) + b"trailing"
    value, consumed = ser.read_object(data)
    assert value == 42
    assert data[consumed:] == b"trailing"


# ---- widened registry (reference: StandardSerializer.java:78-132 breadth) ----

import numpy as np
from datetime import date, time, timedelta

from janusgraph_tpu.core.attributes import (
    Char,
    Instant,
    USER_TYPE_ID_START,
)


WIDE_SAMPLES = [
    np.int8(-7), np.int16(-30000), np.int32(2**30), np.int64(-(2**60)),
    np.float32(1.5), Char("x"),
    Instant(1_722_000_000, 123_456_789),
    timedelta(days=2, seconds=3, microseconds=7),
    date(2026, 7, 29), time(23, 59, 58, 999_999),
    ["alpha", "beta", ""],
    "x" * 500,  # long string -> compressed path
]


@pytest.mark.parametrize(
    "value", WIDE_SAMPLES, ids=[repr(v)[:30] for v in WIDE_SAMPLES]
)
def test_wide_framed_roundtrip(ser, value):
    data = ser.write_object(value)
    out, consumed = ser.read_object(data)
    assert out == value
    assert consumed == len(data)


ARRAYS = [
    np.array([True, False, True]),
    np.arange(-4, 4, dtype=np.int8),
    np.arange(10, dtype=np.int16).reshape(2, 5),
    np.arange(6, dtype=np.int32),
    np.array([2**40, -(2**40)], dtype=np.int64),
    np.linspace(0, 1, 7, dtype=np.float32),
    np.linspace(-1, 1, 5, dtype=np.float64).reshape(5, 1),
    np.frombuffer(b"\x01\x02\xff", dtype=np.uint8),
]


@pytest.mark.parametrize("arr", ARRAYS, ids=[str(a.dtype) for a in ARRAYS])
def test_ndarray_roundtrip_per_dtype(ser, arr):
    out, _ = ser.read_object(ser.write_object(arr))
    assert out.dtype == arr.dtype
    assert out.shape == arr.shape
    np.testing.assert_array_equal(out, arr)


def test_compressed_string_shorter_and_lossless(ser):
    s = "janusgraph " * 100
    data = ser.write_object(s)
    assert len(data) < len(s.encode())  # actually compressed
    assert ser.read_object(data)[0] == s


def test_enum_roundtrip_framework_enums(ser):
    from janusgraph_tpu.core.codecs import Cardinality, Direction, Multiplicity
    from janusgraph_tpu.core.management import SchemaAction

    for member in (
        Direction.OUT, Cardinality.LIST, Multiplicity.MANY2ONE,
        SchemaAction.REINDEX,
    ):
        out, _ = ser.read_object(ser.write_object(member))
        assert out is member


def test_user_enum_registration(ser):
    from enum import Enum

    class Color(Enum):
        RED = 1
        GREEN = 2

    ser.register_enum(Color, USER_TYPE_ID_START)
    out, _ = ser.read_object(ser.write_object(Color.GREEN))
    assert out is Color.GREEN


@pytest.mark.parametrize("vals,caster", [
    ([-5, -1, 0, 1, 100], lambda v: np.int8(v)),
    ([-30000, -7, 0, 12345], lambda v: np.int16(v)),
    ([-(2**30), -1, 0, 2**30], lambda v: np.int32(v)),
    ([-2.5, -0.0, 0.0, 1.5, 1e30], lambda v: np.float32(v)),
    ([Instant(-5, 0), Instant(0, 1), Instant(0, 999), Instant(7, 0)],
     lambda v: v),
    ([date(1990, 1, 1), date(2026, 7, 29), date(3000, 12, 31)], lambda v: v),
], ids=["int8", "int16", "int32", "float32", "instant", "date"])
def test_wide_ordered_encoding_sorts(ser, vals, caster):
    """Byte-lexicographic order of write_ordered == natural order."""
    vals = [caster(v) for v in vals]
    encs = [ser.write_ordered(v) for v in vals]
    assert encs == sorted(encs)


def test_char_rejects_multichar():
    with pytest.raises(SerializerError):
        Char("ab")


def test_instant_nanosecond_precision_roundtrip(ser):
    a = Instant(100, 1)
    b = Instant(100, 2)
    assert ser.read_object(ser.write_object(a))[0] == a
    assert ser.write_ordered(a) < ser.write_ordered(b)  # ns ordering visible


def test_instant_datetime_conversion():
    dt = datetime(2026, 7, 29, 12, 0, 0, 500, tzinfo=timezone.utc)
    i = Instant.of(dt)
    assert i.to_datetime() == dt


# ---- BigInteger / Decimal (reference: StandardSerializer BigInteger &
# BigDecimal registrations, StandardSerializer.java:78-132) -------------------

def test_bigint_roundtrip(ser):
    for v in (2**64, -(2**100), 2**500, -(2**63) - 1, 1 << 63):
        got, _ = ser.read_object(ser.write_object(v))
        assert got == v


def test_small_int_still_long(ser):
    data = ser.write_object(42)
    import struct
    (tid,) = struct.unpack(">H", data[:2])
    assert tid == 2  # LongSerializer keeps the int64 range


def test_bigint_ordered_sorts(ser):
    from janusgraph_tpu.core.attributes import BigIntegerSerializer
    big = BigIntegerSerializer()
    vals = [-(2**200), -(2**70), -(1 << 63) - 5, -1, 0, 1,
            (1 << 63) + 5, 2**70, 2**200]
    encs = [big.write_ordered(v) for v in vals]
    assert encs == sorted(encs)
    for v, e in zip(vals, encs):
        assert big.read_ordered(e) == v


def test_decimal_roundtrip_preserves_scale(ser):
    from decimal import Decimal
    for s in ("1.50", "-0.003", "12345678901234567890.123456789", "0", "1E+10"):
        v = Decimal(s)
        got, _ = ser.read_object(ser.write_object(v))
        assert got == v and str(got) == s


def test_decimal_ordered_sorts(ser):
    from decimal import Decimal
    from janusgraph_tpu.core.attributes import DecimalSerializer
    d = DecimalSerializer()
    vals = [Decimal(s) for s in
            ("-1000.5", "-2.5", "-2.4999", "-0.001", "0", "0.0005",
             "1", "1.0001", "2.5", "99", "100", "1E+20")]
    encs = [d.write_ordered(v) for v in vals]
    assert encs == sorted(encs)
    for v, e in zip(vals, encs):
        assert d.read_ordered(e) == v  # numerically equal


def test_decimal_ordered_beyond_context_precision(ser):
    from decimal import Decimal
    from janusgraph_tpu.core.attributes import DecimalSerializer
    d = DecimalSerializer()
    a = Decimal("1." + "0" * 29 + "1")
    b = Decimal("1." + "0" * 29 + "2")
    ea, eb = d.write_ordered(a), d.write_ordered(b)
    assert ea < eb and d.read_ordered(ea) == a and d.read_ordered(eb) == b
