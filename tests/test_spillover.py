"""OLTP->OLAP spillover (ISSUE 12): hot multi-hop traversal shapes
compile to frontier supersteps over a cached CSR snapshot, set-equal to
the step-by-step walk — including mid-transaction (tx-overlay
reconciliation), under brownout (transparent fallback), and across
snapshot staleness (refresh within the bound, refusal beyond it).

Oracle everywhere: the SAME traversal with the planner disabled (the
row-by-row walk). The digest table is process-global, so every test
resets it and uses its own graph/planner.
"""

import os

import pytest

from janusgraph_tpu.core.graph import open_graph
from janusgraph_tpu.observability import flight_recorder, registry
from janusgraph_tpu.observability.profiler import digest_table
from janusgraph_tpu.olap import spillover as sp


SPILL_CFG = {
    "schema.default": "auto",
    "computer.spillover": True,
    # promote on the FIRST observation so tests teach a shape with one
    # row-wise run and spill from the second on
    "computer.spillover-min-cost-ms": 0.0,
    "computer.spillover-min-seen": 1,
    "computer.sharded-auto": False,
}


def _social_graph(extra_cfg=None):
    g = open_graph({**SPILL_CFG, **(extra_cfg or {})})
    tx = g.new_transaction()
    people = [tx.add_vertex("person", name=f"p{i}") for i in range(12)]
    places = [tx.add_vertex("place", name=f"c{i}") for i in range(3)]
    import random

    rng = random.Random(11)
    for i, v in enumerate(people):
        for j in rng.sample(range(12), 4):
            tx.add_edge(v, "knows", people[j])
        tx.add_edge(v, "lives", places[i % 3])
    # a self-loop and a parallel edge: multiplicity edge cases the count
    # vector must reproduce exactly
    tx.add_edge(people[0], "knows", people[0])
    tx.add_edge(people[1], "knows", people[2])
    tx.add_edge(people[1], "knows", people[2])
    tx.commit()
    return g, [v.id for v in people], [v.id for v in places]


def _spill_count():
    return registry.snapshot().get(
        "olap.spillover.spilled", {}
    ).get("count", 0)


def _ab(g, build, as_count=False):
    """(row result, spilled result, engaged): run once to teach the
    digest table, then A/B the spilled run against the disabled-planner
    walk. List results compare as sorted lists (set/multiset equality is
    the contract; order is not)."""
    planner = g.spillover_planner
    planner.enabled = True
    run = (lambda t: t.count()) if as_count else (lambda t: t.to_list())
    run(build())  # teach
    before = _spill_count()
    spilled = run(build())
    engaged = _spill_count() > before
    planner.enabled = False
    try:
        row = run(build())
    finally:
        planner.enabled = True
    if not as_count:
        row, spilled = sorted(map(repr, row)), sorted(map(repr, spilled))
    return row, spilled, engaged


@pytest.fixture(autouse=True)
def _fresh_tables():
    digest_table.reset()
    yield
    digest_table.reset()


# ----------------------------------------------------------- set equality
@pytest.mark.parametrize("chain", [
    lambda t: t.V().out("knows").out("knows"),
    lambda t: t.V().out("knows").out("knows").out("knows"),
    lambda t: t.V().in_("knows").in_("knows"),
    lambda t: t.V().both("knows").both("knows"),
    lambda t: t.V().out().out(),
    lambda t: t.V().out("knows").out("lives"),
    lambda t: t.V().out("knows").out("knows").dedup(),
    lambda t: t.V().out("knows").out("knows").id_(),
    lambda t: t.V().out("knows").out("knows").dedup().id_(),
    lambda t: t.V().has_label("person").out("knows").out("knows"),
    lambda t: t.V().out("knows").has_label("person").out("lives"),
])
def test_spilled_results_set_equal(chain):
    g, _people, _places = _social_graph()
    try:
        row, spilled, engaged = _ab(g, lambda: chain(g.traversal()))
        assert engaged, "spillover did not engage on a promoted shape"
        assert row == spilled
    finally:
        g.close()


def test_spilled_count_terminal_and_count_step(extra=None):
    g, people, _places = _social_graph()
    try:
        row, spilled, engaged = _ab(
            g,
            lambda: g.traversal().V().out("knows").out("knows"),
            as_count=True,
        )
        assert engaged and row == spilled
        # count as a STEP: spilled chain yields one int traverser
        row2, spilled2, engaged2 = _ab(
            g,
            lambda: g.traversal().V().out("knows").out("knows").count_(),
        )
        assert engaged2 and row2 == spilled2
        # seeded start with DUPLICATE ids: seed multiplicity preserved
        row3, spilled3, engaged3 = _ab(
            g,
            lambda: g.traversal().V(
                people[0], people[0], people[1]
            ).out("knows").out("knows"),
            as_count=True,
        )
        assert engaged3 and row3 == spilled3
        # trailing edge expansion with a count terminal
        row4, spilled4, engaged4 = _ab(
            g,
            lambda: g.traversal().V().out("knows").out_e("knows"),
            as_count=True,
        )
        assert engaged4 and row4 == spilled4
    finally:
        g.close()


# -------------------------------------------------- tx-overlay read-your-writes
def test_overlay_uncommitted_adds_and_deletes():
    """The acceptance case: the SAME transaction holds uncommitted adds
    AND deletes on the traversed edges — the spilled result must be
    read-your-writes set-equal to the row walk."""
    from janusgraph_tpu.core.codecs import Direction
    from janusgraph_tpu.core.traversal import GraphTraversalSource

    g, people, _places = _social_graph()
    try:
        # teach + promote the shape on a clean tx first
        _ab(g, lambda: g.traversal().V().out("knows").out("knows"))
        tx = g.new_transaction()
        v0 = tx.get_vertex(people[0])
        v1 = tx.get_vertex(people[1])
        # uncommitted adds: a brand-new vertex wired into the traversed
        # label, plus a fresh edge between committed vertices
        nv = tx.add_vertex("person", name="fresh")
        tx.add_edge(v0, "knows", nv)
        tx.add_edge(nv, "knows", v1)
        tx.add_edge(v1, "knows", v0)
        # uncommitted deletes: one committed edge instance (parallel
        # edges stay count-correct), and a whole vertex
        es = tx.get_edges(v1, Direction.OUT, ("knows",))
        tx.remove_edge(es[0])
        tx.remove_vertex(tx.get_vertex(people[11]))

        def build():
            return GraphTraversalSource(g, tx).V().out("knows").out("knows")

        planner = g.spillover_planner
        before = _spill_count()
        spilled = build().count()
        assert _spill_count() > before, "overlay run did not spill"
        planner.enabled = False
        try:
            row = build().count()
        finally:
            planner.enabled = True
        assert spilled == row
        # the run record carries the overlay block
        info = registry.last_run("olap.spillover")
        block = info["spillover"]
        assert block["fallback"] is None
        assert block["overlay"]["added"] == 3
        assert block["overlay"]["new_vertices"] == 1
        assert block["overlay"]["removed"] == 1
        assert block["overlay"]["deleted"] >= 1
        # dedup'd endpoints too, not just totals
        before = _spill_count()
        spilled_ids = sorted(build().dedup().id_().to_list())
        planner.enabled = False
        try:
            row_ids = sorted(build().dedup().id_().to_list())
        finally:
            planner.enabled = True
        assert spilled_ids == row_ids
    finally:
        g.close()


def test_overlay_overflow_falls_back():
    g, people, _places = _social_graph(
        {"computer.spillover-max-overlay": 2}
    )
    try:
        _ab(g, lambda: g.traversal().V().out("knows").out("knows"))
        from janusgraph_tpu.core.traversal import GraphTraversalSource

        tx = g.new_transaction()
        v0 = tx.get_vertex(people[0])
        for i in range(4):
            tx.add_edge(v0, "knows", tx.get_vertex(people[i + 1]))
        before = _spill_count()
        c = GraphTraversalSource(g, tx).V().out("knows").out("knows").count()
        assert _spill_count() == before, "overflowed overlay still spilled"
        g.spillover_planner.enabled = False
        try:
            row = GraphTraversalSource(g, tx).V().out(
                "knows"
            ).out("knows").count()
        finally:
            g.spillover_planner.enabled = True
        assert c == row
        events = flight_recorder.events("spillover_fallback")
        assert any(
            e.get("reason") == "overlay-overflow" for e in events
        )
    finally:
        g.close()


# ----------------------------------------------------------- fallback paths
def test_unsupported_step_falls_back_transparently():
    """A promoted digest whose chain carries an unsupported step runs
    row-by-row with a spillover_fallback flight event and zero errors."""
    g, _people, _places = _social_graph()
    try:
        def build():
            return g.traversal().V().out("knows").out("knows").values("name")

        build().to_list()  # teach: digest observed once
        # force-promote the digest so the refusal is event-worthy
        shape, digest = sp.traversal_digest(build())
        planner = g.spillover_planner
        with planner._lock:
            assert planner._check_promotion(digest, shape)
        before = flight_recorder.counts().get("spillover_fallback", 0)
        spilled_view = build().to_list()
        planner.enabled = False
        try:
            row_view = build().to_list()
        finally:
            planner.enabled = True
        assert sorted(spilled_view) == sorted(row_view)
        events = flight_recorder.events("spillover_fallback")
        assert flight_recorder.counts()["spillover_fallback"] > before
        assert any(
            e["digest"] == digest
            and str(e.get("reason", "")).startswith("unsupported:")
            for e in events
        )
    finally:
        g.close()


def test_rung2_brownout_falls_back_transparently():
    """Brownout rung 2 refuses OLAP submits — the spilled path must fall
    back to the row walk (same results, flight event, zero errors)."""
    from janusgraph_tpu.server import admission as adm

    g, _people, _places = _social_graph()
    try:
        def build():
            return g.traversal().V().out("knows").out("knows")

        row, spilled, engaged = _ab(g, build)
        assert engaged and row == spilled
        ctl = adm.AdmissionController()
        ctl.brownout.rung = adm.RUNG_REFUSE_OLAP
        adm.set_active(ctl)
        try:
            before = _spill_count()
            browned = build().to_list()
            assert _spill_count() == before, "spilled during rung-2 brownout"
            g.spillover_planner.enabled = False
            try:
                row2 = build().to_list()
            finally:
                g.spillover_planner.enabled = True
            assert sorted(map(repr, browned)) == sorted(map(repr, row2))
            assert any(
                e.get("reason") == "brownout"
                for e in flight_recorder.events("spillover_fallback")
            )
        finally:
            adm.set_active(None)
        # ladder cleared: the next run spills again
        before = _spill_count()
        build().to_list()
        assert _spill_count() > before
    finally:
        g.close()


def test_staleness_guard_refuses_then_repacks():
    g, people, _places = _social_graph(
        {"computer.spillover-max-staleness": 0}
    )
    try:
        def build():
            return g.traversal().V().out("knows").out("knows")

        row, spilled, engaged = _ab(g, build, as_count=True)
        assert engaged and row == spilled
        # a committed write from ANOTHER tx after the pack
        tx = g.new_transaction()
        tx.add_edge(
            tx.get_vertex(people[0]), "knows", tx.get_vertex(people[5])
        )
        tx.commit()
        stale_before = registry.snapshot().get(
            "olap.spillover.stale", {}
        ).get("count", 0)
        c1 = build().count()  # falls back: snapshot beyond the bound
        assert registry.snapshot()["olap.spillover.stale"]["count"] == (
            stale_before + 1
        )
        packs_before = registry.snapshot()["olap.spillover.packs"]["count"]
        before = _spill_count()
        c2 = build().count()  # repacked: spills again, fresh snapshot
        assert _spill_count() > before
        assert registry.snapshot()["olap.spillover.packs"]["count"] == (
            packs_before + 1
        )
        g.spillover_planner.enabled = False
        try:
            row2 = build().count()
        finally:
            g.spillover_planner.enabled = True
        assert c1 == c2 == row2
    finally:
        g.close()


def test_refresh_within_staleness_bound():
    g, people, _places = _social_graph(
        {"computer.spillover-max-staleness": 10_000}
    )
    try:
        def build():
            return g.traversal().V().out("knows").out("knows")

        _ab(g, build, as_count=True)
        tx = g.new_transaction()
        tx.add_edge(
            tx.get_vertex(people[2]), "knows", tx.get_vertex(people[3])
        )
        tx.commit()
        before = _spill_count()
        c = build().count()
        assert _spill_count() > before
        assert registry.snapshot()[
            "olap.spillover.refreshes"
        ]["count"] >= 1
        g.spillover_planner.enabled = False
        try:
            row = build().count()
        finally:
            g.spillover_planner.enabled = True
        assert c == row
    finally:
        g.close()


# -------------------------------------------------------------- promotion
def test_promotion_policy_thresholds():
    g, _people, _places = _social_graph({
        "computer.spillover-min-seen": 3,
        "computer.spillover-min-cost-ms": 0.0,
    })
    try:
        def build():
            return g.traversal().V().out("knows").out("knows")

        base = _spill_count()
        for _ in range(2):
            build().to_list()
        assert _spill_count() == base, "promoted below min-seen"
        build().to_list()  # 3rd observation crosses min-seen
        before = _spill_count()
        build().to_list()
        assert _spill_count() > before
        shape, digest = sp.traversal_digest(build())
        assert digest in sp.promoted_digests()
        snap = g.spillover_planner.promotion_snapshot()
        assert digest in snap and snap[digest]["spilled"] >= 1
    finally:
        g.close()


def test_min_cost_gate_keeps_cheap_shapes_on_row_path():
    g, _people, _places = _social_graph({
        "computer.spillover-min-cost-ms": 1e9,
        "computer.spillover-min-seen": 1,
    })
    try:
        base = _spill_count()
        for _ in range(3):
            g.traversal().V().out("knows").out("knows").to_list()
        assert _spill_count() == base
    finally:
        g.close()


def test_single_hop_never_considered():
    g, _people, _places = _social_graph()
    try:
        base = _spill_count()
        for _ in range(3):
            g.traversal().V().out("knows").to_list()
        assert _spill_count() == base
    finally:
        g.close()


# ---------------------------------------------------------- observability
def test_healthz_spillover_block_and_profile_marking():
    from janusgraph_tpu.server.server import healthz_snapshot

    g, _people, _places = _social_graph()
    try:
        row, spilled, engaged = _ab(
            g, lambda: g.traversal().V().out("knows").out("knows")
        )
        assert engaged
        block = healthz_snapshot()["spillover"]
        assert block["spilled"] >= 1
        assert block["packs"] >= 1
        assert block["promotions"] >= 1
        assert block["promoted_digests"], "promoted census empty"
        # GET /profile marks promoted digests — same data source
        promoted = sp.promoted_digests()
        assert set(block["promoted_digests"]) <= promoted
        info = registry.last_run("olap.spillover")
        assert info["spillover"]["digest"] in promoted
        assert info["spillover"]["hops"] == 2
        assert info["spillover"]["wall_ms"] > 0
    finally:
        g.close()


def test_profile_endpoint_marks_promoted_digests():
    """End to end over HTTP: /profile rows carry the promoted flag."""
    import json
    import urllib.request

    from janusgraph_tpu.server.manager import JanusGraphManager
    from janusgraph_tpu.server.server import JanusGraphServer

    g, _people, _places = _social_graph()
    mgr = JanusGraphManager()
    mgr.put_graph("graph", g)
    server = JanusGraphServer(manager=mgr, admission_enabled=False).start()
    try:
        _ab(g, lambda: g.traversal().V().out("knows").out("knows"))
        with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/profile"
        ) as r:
            payload = json.loads(r.read())
        marked = {
            d["digest"]: d["promoted"] for d in payload["digests"]
        }
        assert any(marked.values()), f"no promoted digest in {marked}"
    finally:
        server.stop()
        g.close()


# ------------------------------------------------------------- price book
def test_price_book_persists_across_graph_reopen(tmp_path):
    ckpt = os.path.join(str(tmp_path), "ck")
    cfg = {**SPILL_CFG, "computer.checkpoint-path": ckpt}
    digest_table.reset()
    g = open_graph(cfg)
    tx = g.new_transaction()
    vs = [tx.add_vertex("person") for _ in range(4)]
    tx.add_edge(vs[0], "knows", vs[1])
    tx.commit()
    g.traversal().V().out("knows").out("knows").count()
    top = digest_table.top(5)
    assert top, "digest table empty after a traversal"
    g.close()
    assert os.path.exists(ckpt + ".pricebook.json")
    digest_table.reset()
    assert digest_table.top(5) == []
    # same backing manager is gone (inmemory), but the PRICE BOOK warm
    # start is about the table, not the data: reopen loads it
    g2 = open_graph(cfg)
    try:
        warmed = {e["digest"]: e for e in digest_table.top(10)}
        assert top[0]["digest"] in warmed
        assert warmed[top[0]["digest"]]["count"] == top[0]["count"]
        assert digest_table.mean_cost_ms(top[0]["digest"]) is not None
    finally:
        g2.close()


def test_price_book_server_table_roundtrip(tmp_path):
    from janusgraph_tpu.observability.profiler import (
        DigestTable,
        load_price_book,
        restore_digest_records,
        save_price_book,
    )

    path = os.path.join(str(tmp_path), "pb.json")
    t = DigestTable()
    for _ in range(5):
        t.observe("abcd1234", "server>g.V().out()", 12.5, cells=100)
    save_price_book(path, {"server": t})
    # a second save of ANOTHER table must preserve the first
    t2 = DigestTable()
    t2.observe("ffff0000", "full-scan>out", 3.0)
    save_price_book(path, {"oltp": t2})
    tables = load_price_book(path)
    assert set(tables) == {"server", "oltp"}
    restored = DigestTable()
    assert restore_digest_records(restored, tables["server"]) == 1
    assert restored.mean_cost_ms("abcd1234") == pytest.approx(12.5)
    top = restored.top(1)[0]
    assert top["count"] == 5 and top["p50_ms"] > 0
    # live entries outrank the file on merge
    restore_digest_records(restored, tables["server"])
    assert restored.top(1)[0]["count"] == 5


# --------------------------------------------------------------- planner unit
def test_recognize_vocabulary():
    g, people, _places = _social_graph()
    try:
        t = g.traversal().V().out("knows").out("knows")
        plan, reason = sp.recognize(t)
        assert plan is not None and len(plan.hops) == 2
        # property has() head is unsupported
        t = g.traversal().V().has("name", "p0").out("knows").out("knows")
        plan, reason = sp.recognize(t)
        assert plan is None and reason.startswith("seed-filter")
        # mid-chain order() is unsupported
        t = g.traversal().V().out("knows").out("knows").order()
        plan, reason = sp.recognize(t)
        assert plan is None
        # repeat() is unsupported (no _expand_meta on the repeat step)
        t = g.traversal().V().repeat(lambda x: x.out("knows"), times=2)
        plan, reason = sp.recognize(t)
        assert plan is None
        # edge expansion mid-chain is unsupported
        t = g.traversal().V().out_e("knows").in_v()
        plan, reason = sp.recognize(t)
        assert plan is None
    finally:
        g.close()


def test_spillover_disabled_config():
    g, _people, _places = _social_graph({"computer.spillover": False})
    try:
        assert g.spillover_planner is None
        base = _spill_count()
        for _ in range(3):
            g.traversal().V().out("knows").out("knows").count()
        assert _spill_count() == base
    finally:
        g.close()
