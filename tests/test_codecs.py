"""Relation cell codec tests (reference model: janusgraph-test
.../graphdb/EdgeSerializerTest.java + IDHandler bounds semantics: write/parse
round trips, slice bounds as column ranges, bulk vectorized decode)."""

import numpy as np
import pytest

from janusgraph_tpu.core.attributes import Serializer
from janusgraph_tpu.core.codecs import (
    EDGE_COL_FIXED,
    Cardinality,
    CodecError,
    Direction,
    EdgeSerializer,
    RelationCategory,
    RelationIdentifier,
    TypeInfo,
)
from janusgraph_tpu.core.ids import IDManager, VertexIDType


@pytest.fixture
def idm():
    return IDManager(partition_bits=5)


@pytest.fixture
def es(idm):
    return EdgeSerializer(Serializer(), idm)


@pytest.fixture
def type_ids(idm):
    return {
        "knows": idm.make_schema_id(VertexIDType.USER_EDGE_LABEL, 1),
        "name": idm.make_schema_id(VertexIDType.USER_PROPERTY_KEY, 2),
        "sys_exists": idm.make_schema_id(VertexIDType.SYSTEM_PROPERTY_KEY, 1),
        "weight": idm.make_schema_id(VertexIDType.USER_PROPERTY_KEY, 3),
    }


def schema_for(type_ids, cardinality=Cardinality.SINGLE):
    infos = {
        type_ids["knows"]: TypeInfo(type_ids["knows"], True),
        type_ids["name"]: TypeInfo(type_ids["name"], False, cardinality),
        type_ids["sys_exists"]: TypeInfo(type_ids["sys_exists"], False),
        type_ids["weight"]: TypeInfo(type_ids["weight"], False),
    }
    return infos.__getitem__


def test_edge_roundtrip(es, idm, type_ids):
    other = idm.make_vertex_id(123, 4)
    entry = es.write_edge(
        type_ids["knows"], Direction.OUT, other, relation_id=77,
        inline_properties={type_ids["weight"]: 0.5},
    )
    rc = es.parse_relation(entry, schema_for(type_ids))
    assert rc.is_edge
    assert rc.type_id == type_ids["knows"]
    assert rc.direction == Direction.OUT
    assert rc.other_vertex_id == other
    assert rc.relation_id == 77
    assert rc.properties == {type_ids["weight"]: 0.5}


def test_edge_no_props_is_fixed_width(es, idm, type_ids):
    entry = es.write_edge(type_ids["knows"], Direction.IN, idm.make_vertex_id(9, 0), 5)
    assert len(entry[0]) == EDGE_COL_FIXED
    assert entry[1] == b""


@pytest.mark.parametrize("card", [Cardinality.SINGLE, Cardinality.LIST, Cardinality.SET])
def test_property_roundtrip_all_cardinalities(es, type_ids, card):
    entry = es.write_property(type_ids["name"], 31, "saturn", card)
    rc = es.parse_relation(entry, schema_for(type_ids, card))
    assert not rc.is_edge
    assert rc.value == "saturn"
    assert rc.relation_id == 31
    assert rc.type_id == type_ids["name"]


def test_list_property_distinct_columns(es, type_ids):
    e1 = es.write_property(type_ids["name"], 1, "a", Cardinality.LIST)
    e2 = es.write_property(type_ids["name"], 2, "a", Cardinality.LIST)
    assert e1[0] != e2[0]  # same value, different relation -> distinct cells


def test_set_property_value_in_column(es, type_ids):
    e1 = es.write_property(type_ids["name"], 1, "a", Cardinality.SET)
    e2 = es.write_property(type_ids["name"], 2, "a", Cardinality.SET)
    assert e1[0] == e2[0]  # same value -> same column -> set semantics


def test_category_bounds_partition_columns(es, idm, type_ids):
    """Every written column falls in exactly the slice ranges that should
    contain it — bounds are the query compiler's contract."""
    other = idm.make_vertex_id(5, 1)
    edge_col = es.write_edge(type_ids["knows"], Direction.OUT, other, 1)[0]
    prop_col = es.write_property(type_ids["name"], 2, "x")[0]
    sys_col = es.write_property(type_ids["sys_exists"], 3, True)[0]

    rel = es.get_bounds(RelationCategory.RELATION)
    prop = es.get_bounds(RelationCategory.PROPERTY)
    edge = es.get_bounds(RelationCategory.EDGE)
    sys_prop = es.get_bounds(RelationCategory.PROPERTY, system=True)

    assert rel.contains(edge_col) and rel.contains(prop_col) and rel.contains(sys_col)
    assert prop.contains(prop_col) and not prop.contains(edge_col)
    assert edge.contains(edge_col) and not edge.contains(prop_col)
    assert sys_prop.contains(sys_col) and not sys_prop.contains(prop_col)


def test_type_slice_selects_type_and_direction(es, idm, type_ids):
    other = idm.make_vertex_id(5, 1)
    out_col = es.write_edge(type_ids["knows"], Direction.OUT, other, 1)[0]
    in_col = es.write_edge(type_ids["knows"], Direction.IN, other, 2)[0]

    both = es.get_type_slice(type_ids["knows"], True)
    out_only = es.get_type_slice(type_ids["knows"], True, Direction.OUT)
    in_only = es.get_type_slice(type_ids["knows"], True, Direction.IN)

    assert both.contains(out_col) and both.contains(in_col)
    assert out_only.contains(out_col) and not out_only.contains(in_col)
    assert in_only.contains(in_col) and not in_only.contains(out_col)


def test_sort_key_slice(es, idm, type_ids):
    """Fixed-width ordered sort keys make prefix ranges exact index scans."""
    ser = Serializer()
    other = idm.make_vertex_id(5, 1)
    cols = {}
    for t in (10, 20, 30):
        sk = ser.write_ordered(t)
        cols[t] = es.write_edge(type_ids["knows"], Direction.OUT, other, t, sort_key=sk)[0]
    sk20 = ser.write_ordered(20)
    q = es.get_type_slice(
        type_ids["knows"], True, Direction.OUT,
        sort_key_prefix=sk20, sort_key_len=len(sk20),
    )
    assert q.contains(cols[20])
    assert not q.contains(cols[10]) and not q.contains(cols[30])
    # sorted order of columns == numeric order of sort keys
    assert sorted(cols.values()) == [cols[10], cols[20], cols[30]]


def test_bulk_decode_matches_scalar_parse(es, idm, type_ids):
    rng = np.random.default_rng(3)
    entries = []
    expected = []
    for i in range(500):
        other = idm.make_vertex_id(int(rng.integers(1, 10000)), int(rng.integers(0, 32)))
        d = Direction.OUT if rng.integers(0, 2) == 0 else Direction.IN
        rel = int(rng.integers(1, 1 << 40))
        entries.append(es.write_edge(type_ids["knows"], d, other, rel))
        expected.append((type_ids["knows"], int(d), other, rel))
    tids, dirs, others, rels = es.bulk_decode_edges([c for c, _ in entries])
    got = list(zip(tids.tolist(), dirs.tolist(), others.tolist(), rels.tolist()))
    assert got == expected


def test_bulk_decode_empty(es):
    tids, dirs, others, rels = es.bulk_decode_edges([])
    assert len(tids) == len(dirs) == len(others) == len(rels) == 0


def test_relation_identifier_roundtrip():
    rid = RelationIdentifier(5, 100, 9, 200)
    assert RelationIdentifier.parse(str(rid)) == rid
    with pytest.raises(CodecError):
        RelationIdentifier.parse("1-2-3")


def test_write_edge_rejects_both_direction(es, type_ids, idm):
    with pytest.raises(CodecError):
        es.write_edge(type_ids["knows"], Direction.BOTH, idm.make_vertex_id(1, 0), 1)
