"""Two-process jax.distributed smoke test (VERDICT r3 #6).

Real multi-host hardware is unavailable here, but the multi-controller
RUNTIME is exercisable on localhost: a coordinator + 2 worker processes,
each contributing 2 virtual CPU devices to one 4-device global mesh
(reference analogue: SparkGraphComputer executors over Hadoop input splits,
HadoopInputFormat.java:34 — here the executors are JAX processes and the
splits are host_partition_range blocks).

Asserts, inside each process: init_multihost wiring, the global mesh
spanning BOTH processes' devices, host_partition_range's disjoint cover,
and a tiny power-iteration PageRank whose per-superstep psum crosses the
process boundary, checked against a numpy oracle.
"""

import os
import socket
import subprocess
import sys

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = r"""
import os, sys
pid = int(sys.argv[1]); port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
sys.path.insert(0, %(repo)r)

from janusgraph_tpu.parallel.multihost import (
    global_mesh,
    host_partition_range,
    init_multihost,
)

got_pid = init_multihost(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=pid
)
assert got_pid == pid

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map

assert jax.process_count() == 2
assert jax.process_index() == pid
mesh = global_mesh()
assert mesh.devices.size == 4, mesh.devices  # 2 local x 2 processes

# disjoint contiguous cover of 8 storage partitions across the 2 hosts
lo, hi = host_partition_range(8)
assert (lo, hi) == ((0, 4) if pid == 0 else (4, 8))

# tiny PageRank power iteration: A column-sharded, rank shard per device,
# psum combines partial mat-vecs ACROSS processes every superstep
n = 16
rng = np.random.default_rng(0)
A = (rng.random((n, n)) < 0.3).astype(np.float32)
A = A / np.maximum(A.sum(axis=0, keepdims=True), 1.0)
x0 = np.full((n,), 1.0 / n, dtype=np.float32)

def superstep(a_blk, x_blk):
    return jax.lax.psum(a_blk @ x_blk, "p")

step = jax.jit(
    shard_map(
        superstep, mesh=mesh,
        in_specs=(P(None, "p"), P("p")), out_specs=P(None),
    )
)
A_sh = jax.device_put(A, NamedSharding(mesh, P(None, "p")))
x = jax.device_put(x0, NamedSharding(mesh, P("p")))
for _ in range(5):
    full = step(A_sh, x)
    x = jax.device_put(np.asarray(full), NamedSharding(mesh, P("p")))

expect = x0.copy()
for _ in range(5):
    expect = A @ expect
np.testing.assert_allclose(np.asarray(full), expect, rtol=1e-5)
print(f"OK pid={pid} sum={float(np.asarray(full).sum()):.6f}", flush=True)
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_distributed_mesh(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(_WORKER % {"repo": _REPO})
    port = _free_port()
    env = {
        k: v for k, v in os.environ.items()
        # scrub the single-process test harness flags; workers set their own
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "PYTHONPATH")
    }
    env["PYTHONPATH"] = _REPO
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(pid), str(port)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=240)
            outs.append((p.returncode, out.decode(), err.decode()))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rc, out, err in outs:
        assert rc == 0, f"worker failed rc={rc}\nstdout:{out}\nstderr:{err}"
        assert "OK pid=" in out
    # both processes computed the identical global result
    sums = {line.split("sum=")[1] for rc, out, _ in outs
            for line in out.splitlines() if "sum=" in line}
    assert len(sums) == 1
