"""Two-process jax.distributed smoke test (VERDICT r3 #6).

Real multi-host hardware is unavailable here, but the multi-controller
RUNTIME is exercisable on localhost: a coordinator + 2 worker processes,
each contributing 2 virtual CPU devices to one 4-device global mesh
(reference analogue: SparkGraphComputer executors over Hadoop input splits,
HadoopInputFormat.java:34 — here the executors are JAX processes and the
splits are host_partition_range blocks).

Asserts, inside each process: init_multihost wiring, the global mesh
spanning BOTH processes' devices, host_partition_range's disjoint cover,
and a tiny power-iteration PageRank whose per-superstep psum crosses the
process boundary, checked against a numpy oracle.
"""

import os
import socket
import subprocess
import sys

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = r"""
import os, sys
pid = int(sys.argv[1]); port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
sys.path.insert(0, %(repo)r)

from janusgraph_tpu.parallel.multihost import (
    global_mesh,
    host_partition_range,
    init_multihost,
)

got_pid = init_multihost(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=pid
)
assert got_pid == pid

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map

assert jax.process_count() == 2
assert jax.process_index() == pid
mesh = global_mesh()
assert mesh.devices.size == 4, mesh.devices  # 2 local x 2 processes

# disjoint contiguous cover of 8 storage partitions across the 2 hosts
lo, hi = host_partition_range(8)
assert (lo, hi) == ((0, 4) if pid == 0 else (4, 8))

# tiny PageRank power iteration: A column-sharded, rank shard per device,
# psum combines partial mat-vecs ACROSS processes every superstep
n = 16
rng = np.random.default_rng(0)
A = (rng.random((n, n)) < 0.3).astype(np.float32)
A = A / np.maximum(A.sum(axis=0, keepdims=True), 1.0)
x0 = np.full((n,), 1.0 / n, dtype=np.float32)

def superstep(a_blk, x_blk):
    return jax.lax.psum(a_blk @ x_blk, "p")

step = jax.jit(
    shard_map(
        superstep, mesh=mesh,
        in_specs=(P(None, "p"), P("p")), out_specs=P(None),
    )
)
A_sh = jax.device_put(A, NamedSharding(mesh, P(None, "p")))
x = jax.device_put(x0, NamedSharding(mesh, P("p")))
for _ in range(5):
    full = step(A_sh, x)
    x = jax.device_put(np.asarray(full), NamedSharding(mesh, P("p")))

expect = x0.copy()
for _ in range(5):
    expect = A @ expect
np.testing.assert_allclose(np.asarray(full), expect, rtol=1e-5)
print(f"OK pid={pid} sum={float(np.asarray(full).sum()):.6f}", flush=True)
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_distributed_mesh(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(_WORKER % {"repo": _REPO})
    port = _free_port()
    env = {
        k: v for k, v in os.environ.items()
        # scrub the single-process test harness flags; workers set their own
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "PYTHONPATH")
    }
    env["PYTHONPATH"] = _REPO
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(pid), str(port)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=240)
            outs.append((p.returncode, out.decode(), err.decode()))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rc, out, err in outs:
        assert rc == 0, f"worker failed rc={rc}\nstdout:{out}\nstderr:{err}"
        assert "OK pid=" in out
    # both processes computed the identical global result
    sums = {line.split("sum=")[1] for rc, out, _ in outs
            for line in out.splitlines() if "sum=" in line}
    assert len(sums) == 1


_PROD_WORKER = r"""
import json, os, sys, time
pid = int(sys.argv[1]); port = sys.argv[2]
store_port = int(sys.argv[3]); tmpdir = sys.argv[4]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
sys.path.insert(0, %(repo)r)

from janusgraph_tpu.parallel.multihost import (
    global_mesh,
    host_partition_range,
    init_multihost,
)

init_multihost(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=pid
)
import jax
import numpy as np

mesh = global_mesh()
assert mesh.devices.size == 4

# 1. each host scans ONLY its own storage partitions from the SHARED
# remote backend — the production loader worker entry (the input-split
# read of distributed_load.py)
from janusgraph_tpu.olap.distributed_load import _worker_main

cfg = {
    "storage.backend": "remote",
    "storage.hostname": "127.0.0.1",
    "storage.port": store_port,
}
probe_partitions = 32  # ids.partition-bits default 5
lo, hi = host_partition_range(probe_partitions)
mine = os.path.join(tmpdir, f"part{pid}.npz")
rc = _worker_main([
    "--config", json.dumps(cfg),
    "--partitions", ",".join(str(p) for p in range(lo, hi)),
    "--out", mine,
])
assert rc == 0
open(mine + ".done", "w").close()

# 2. barrier on the peer's split, then merge — every host ends up with the
# identical global CSR (the shard_map inputs must agree across processes)
other = os.path.join(tmpdir, f"part{1 - pid}.npz")
deadline = time.monotonic() + 120
while not os.path.exists(other + ".done"):
    if time.monotonic() > deadline:
        raise RuntimeError("peer split never arrived")
    time.sleep(0.2)

from janusgraph_tpu.core.ids import IDManager
from janusgraph_tpu.olap.csr import build_csr_from_raw

raws = []
for path in sorted([mine, other]):
    with np.load(path) as z:
        raws.append({
            "vertex_id_list": z["vertex_id_list"],
            "vertex_labels": z["vertex_labels"],
            "src": z["src"],
            "dst": z["dst"],
            "etype": z["etype"] if bool(z["has_etype"][0]) else None,
            "weights": None,
            "raw_props": {},
        })
csr = build_csr_from_raw(IDManager(partition_bits=5), raws)

# 3. the PRODUCTION executor on the 2-process global mesh: fused span
# (while_loop inside shard_map, boundary a2a + psum barrier in the body)
from janusgraph_tpu.olap.programs import PageRankProgram
from janusgraph_tpu.parallel import ShardedExecutor

ex = ShardedExecutor(csr, mesh=mesh)
res = ex.run(PageRankProgram(max_iterations=8, tol=0.0), fused=True)

# 4. parity against the single-process oracle, computed locally
from janusgraph_tpu.olap.cpu_executor import CPUExecutor

oracle = CPUExecutor(csr).run(PageRankProgram(max_iterations=8, tol=0.0))
np.testing.assert_allclose(
    np.asarray(res["rank"], np.float64), oracle["rank"],
    rtol=1e-4, atol=1e-6,
)
print(
    f"OK pid={pid} n={csr.num_vertices} m={csr.num_edges} "
    f"ranksum={float(np.asarray(res['rank']).sum()):.6f}", flush=True,
)
"""


def test_two_process_production_sharded_executor(tmp_path):
    """VERDICT r4 #3: the production ShardedExecutor end-to-end across a
    REAL process boundary — distributed_load splits read per host from a
    shared remote backend, merged CSR, fused PageRank on the 2-process
    global mesh, parity with the single-process oracle."""
    import numpy as np

    from janusgraph_tpu.core.graph import open_graph
    from janusgraph_tpu.storage.inmemory import InMemoryStoreManager
    from janusgraph_tpu.storage.remote import RemoteStoreServer

    server = RemoteStoreServer(InMemoryStoreManager()).start()
    host, store_port = server.address
    g = open_graph({
        "storage.backend": "remote",
        "storage.hostname": host,
        "storage.port": store_port,
    })
    rng = np.random.default_rng(42)
    tx = g.new_transaction()
    vs = [tx.add_vertex() for _ in range(120)]
    for _ in range(500):
        a, b = rng.integers(0, len(vs), 2)
        if a != b:
            tx.add_edge(vs[a], "link", vs[b])
    tx.commit()
    g.close()

    script = tmp_path / "prod_worker.py"
    script.write_text(_PROD_WORKER % {"repo": _REPO})
    port = _free_port()
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "PYTHONPATH")
    }
    env["PYTHONPATH"] = _REPO
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(pid), str(port),
             str(store_port), str(tmp_path)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=300)
            outs.append((p.returncode, out.decode(), err.decode()))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        server.stop()
    for rc, out, err in outs:
        assert rc == 0, f"worker failed rc={rc}\nstdout:{out}\nstderr:{err}"
        assert "OK pid=" in out
    sums = {line.split("ranksum=")[1] for _rc, out, _e in outs
            for line in out.splitlines() if "ranksum=" in line}
    assert len(sums) == 1
