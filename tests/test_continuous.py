"""Continuous profiling plane (PR 19): sampling profiler, stall
watchdog, and anomaly forensics bundles.

Covers the acceptance surface of the plane end to end:

- flame windows seal in lockstep with MetricsHistory windows (same
  ``seq``), and the sampler's self-cost is accounted wall AND cpu;
- ``flamediff`` produces a deterministic ranking (byte-identical
  across runs) and benchdiff attaches top frame deltas on regress;
- a seeded ``stalled-lock`` fault drives waiter -> watchdog
  ``lock_convoy`` flight event naming the owner's holding frame -> a
  complete forensics bundle, all under fake clocks (no wall sleeps in
  the detection path), with a byte-reproducible fault journal;
- bundles are tmp+rename atomic, retention-bounded, rate-limited, and
  a torn bundle on disk is skipped rather than fatal;
- the /debug/profile, /debug/stacks, /debug/bundle endpoints and the
  ``flame --live`` / ``bundle`` CLI verbs serve the same data;
- /healthz carries the profiler block and flips to degraded when the
  sampler thread dies while enabled (a lying profiler);
- JG112 (silent thread death) is registered and fires on the fixture.
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from janusgraph_tpu.observability import (
    bundle_writer,
    flight_recorder,
    history,
    registry,
    sampling_profiler,
    slo_engine,
    watchdog,
)
from janusgraph_tpu.observability.continuous import (
    BundleWriter,
    InstrumentedLock,
    SamplingProfiler,
    StallWatchdog,
    flame_from_artifact,
    flamediff,
)
from janusgraph_tpu.storage.faults import FaultPlan

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class _Clock:
    """Manually-advanced monotonic clock for deterministic stall tests."""

    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


@pytest.fixture(autouse=True)
def _reset_plane():
    """Every test starts and leaves with pristine plane singletons."""
    for step in (
        sampling_profiler.stop, sampling_profiler.reset,
        watchdog.stop, watchdog.reset,
        bundle_writer.reset, flight_recorder.reset, registry.reset,
    ):
        step()
    bundle_writer.configure(directory="", min_interval_s=30.0)
    bundle_writer.directory = ""
    yield
    for step in (
        sampling_profiler.stop, sampling_profiler.reset,
        watchdog.stop, watchdog.reset,
        bundle_writer.reset, flight_recorder.reset, registry.reset,
    ):
        step()
    bundle_writer.directory = ""


@contextlib.contextmanager
def _parked_thread(name: str = "parked"):
    """A background thread blocked in a recognisable frame."""
    release = threading.Event()

    def _park_here():
        release.wait(30.0)

    t = threading.Thread(target=_park_here, name=name, daemon=True)
    t.start()
    try:
        yield t
    finally:
        release.set()
        t.join(timeout=5.0)


# ------------------------------------------------------------- sampler
def test_sample_once_folds_other_threads_not_self():
    p = SamplingProfiler()
    with _parked_thread():
        folded = p.sample_once()
        assert folded >= 1
        merged = p.merged_stacks()
    assert merged, "pending stacks should be visible before sealing"
    assert any("_park_here" in stack for stack in merged)
    # the sampler never profiles the thread doing the sampling
    assert not any("sample_once" in stack for stack in merged)


def test_flame_windows_align_with_history_window_seq():
    history.reset()
    p = SamplingProfiler()
    p.configure(hz=1.0)
    p.start()
    try:
        with _parked_thread():
            p.sample_once()
            w1 = history.sample()
            p.sample_once()
            w2 = history.sample()
        seqs = [w["seq"] for w in p.windows()]
        # every history window sealed a flame window with the SAME seq
        assert seqs[-2:] == [w1["seq"], w2["seq"]]
    finally:
        p.stop()
        history.reset()


def test_sampler_overhead_accounted_wall_and_cpu():
    clk = _Clock(50.0)
    p = SamplingProfiler(clock=clk)
    p.configure(hz=100.0)
    p.start()
    try:
        with _parked_thread():
            deadline = time.monotonic() + 5.0
            while p.status()["samples"] < 3:
                assert time.monotonic() < deadline, "sampler never sampled"
                time.sleep(0.01)
    finally:
        p.stop()
    clk.advance(10.0)  # 10 fake seconds elapsed -> tiny honest pct
    st = p.status()
    assert st["samples"] >= 3
    assert st["died"] is None
    assert st["overhead_wall_pct"] > 0.0
    assert 0.0 <= st["overhead_cpu_pct"] < 5.0
    # wall cost includes cpu cost plus time descheduled
    assert st["overhead_wall_pct"] >= st["overhead_cpu_pct"]


def test_seal_window_tags_seq_and_resets_pending():
    p = SamplingProfiler()
    with _parked_thread():
        p.sample_once()
    w = p.seal_window(seq=7)
    assert w["seq"] == 7
    assert w["samples"] == 1
    assert w["stacks"]
    assert p.status()["windows_sealed"] == 1
    # pending was folded into the window, not duplicated
    w2 = p.seal_window(seq=8)
    assert w2["samples"] == 0 and w2["stacks"] == {}


def test_window_ring_is_bounded():
    p = SamplingProfiler(max_windows=3)
    for seq in range(6):
        p.seal_window(seq=seq)
    assert [w["seq"] for w in p.windows()] == [3, 4, 5]


# ----------------------------------------------------------- flamediff
def test_flamediff_ranking_is_deterministic():
    old = {"stacks": {"a;b": 100, "a;c": 50, "d": 10}}
    new = {"stacks": {"a;b": 70, "a;c": 90, "d": 10, "e": 25}}
    r1 = flamediff(old, new)
    r2 = flamediff(old, new)
    assert json.dumps(r1, sort_keys=True) == json.dumps(r2, sort_keys=True)
    # frame weights: a 150->160, b 100->70, c 50->90, d flat, e 0->25
    assert [r["frame"] for r in r1] == ["c", "b", "e", "a"]
    assert r1[0] == {
        "frame": "c", "old_us": 50.0, "new_us": 90.0,
        "delta_us": 40.0, "delta_pct": 80.0,
    }
    assert [r["frame"] for r in flamediff(old, new, top=2)] == ["c", "b"]


def test_flamediff_tie_breaks_on_frame_name():
    rows = flamediff({"x": 10, "y": 30}, {"x": 20, "y": 20})
    assert [r["frame"] for r in rows] == ["x", "y"]


def test_flamediff_recursion_charges_frame_once():
    # a recursive stack must not double-charge the repeated frame
    rows = flamediff({"f;g;f": 100}, {"f;g;f": 300})
    by_frame = {r["frame"]: r for r in rows}
    assert by_frame["f"]["delta_us"] == 200.0
    assert by_frame["g"]["delta_us"] == 200.0


def test_flame_from_artifact_shapes():
    assert flame_from_artifact({"stacks": {"a": 1}}) == {"a": 1.0}
    assert flame_from_artifact({"flame": {"a;b": 2}}) == {"a;b": 2.0}
    assert flame_from_artifact(
        {"flame": {"stacks": {"c": 3}}}
    ) == {"c": 3.0}
    assert flame_from_artifact({"a": 1, "b": 2.5}) == {"a": 1.0, "b": 2.5}
    assert flame_from_artifact({"a": "text"}) is None
    assert flame_from_artifact(None) is None


def test_benchdiff_attaches_frame_deltas_on_regress():
    from janusgraph_tpu.observability.benchdiff import compare

    old = {
        "stage": "saturate",
        "peak_goodput_per_s": 400.0,
        "goodput_2x_over_peak": 0.95,
        "flame": {"a;b": 100, "a;c": 50},
    }
    new = dict(old)
    new["peak_goodput_per_s"] = 200.0
    new["flame"] = {"a;b": 300, "a;c": 50}
    got = compare(old, new)
    assert got["verdict"] == "regress"
    deltas = got["frame_deltas"]
    assert 0 < len(deltas) <= 3
    assert deltas[0]["frame"] == "a"  # |delta| tie with b -> name order
    # identical artifacts: no regression, no frame_deltas key
    assert "frame_deltas" not in compare(old, dict(old))


# ------------------------------------------ watchdog: seeded stall path
def test_seeded_stalled_lock_fires_convoy_with_owner_frame(tmp_path):
    """The acceptance path: seeded stalled-lock fault -> blocked waiter
    -> watchdog flights a lock_convoy naming the owner's holding frame
    -> a complete forensics bundle lands atomically.  Fake clocks
    everywhere; the only real waiting is thread synchronisation."""
    clk = _Clock(100.0)
    wd = StallWatchdog(clock=clk)
    wd.configure(stall_s=5.0)
    bundle_writer.configure(directory=str(tmp_path), min_interval_s=0.0)
    plan = FaultPlan(seed=1234, stall_lock_at=0, stall_lock_ms=250.0)
    lk = InstrumentedLock("stall-test", watchdog=wd, clock=clk)
    held = threading.Event()
    release = threading.Event()

    def _holding_frame():
        release.wait(30.0)

    def _holder():
        assert plan.stalled_lock(lock=lk.name) == 250.0
        with lk:
            held.set()
            _holding_frame()

    th = threading.Thread(target=_holder, name="holder", daemon=True)
    th.start()
    assert held.wait(5.0)
    tw = threading.Thread(
        target=lambda: (lk.acquire(), lk.release()),
        name="waiter", daemon=True,
    )
    tw.start()
    deadline = time.monotonic() + 5.0
    while lk.state()["waiters"] < 1:
        assert time.monotonic() < deadline, "waiter never registered"
        time.sleep(0.005)
    sampling_profiler.sample_once()  # snapshot the owner's stack

    # below stall_s: nothing fires yet
    clk.advance(2.0)
    assert wd.check() == []
    # past stall_s: exactly one edge-triggered convoy event
    clk.advance(4.0)
    fired = wd.check()
    assert len(fired) == 1
    ev = fired[0]
    assert ev["category"] == "lock_convoy"
    assert ev["lock"] == "stall-test"
    assert ev["waiter"] == "waiter"
    assert ev["owner"] == "holder"
    assert ev["wait_s"] >= 5.0
    assert "_holding_frame" in ev["owner_stack"]
    # the wait-for edge names both parties (flighted as a string field)
    assert "waiter" in ev["wait_for"] and "holder" in ev["wait_for"]
    # edge-triggered: the same episode never re-fires
    clk.advance(10.0)
    assert wd.check() == []
    assert wd.state()["events"] == 1

    # the convoy shipped a complete atomic bundle
    bundle = bundle_writer.latest()
    assert bundle is not None
    assert bundle["reason"] == "lock-convoy"
    for key in (
        "ts", "pid", "flame_windows", "profiler", "flight",
        "timeseries", "stacks", "requests", "watchdog",
    ):
        assert key in bundle
    convoy_evs = [
        e for e in bundle["flight"]["events"]
        if e["category"] == "lock_convoy"
    ]
    assert len(convoy_evs) == 1
    assert not [
        n for n in os.listdir(tmp_path) if n.endswith(".tmp")
    ], "no torn temp files after capture"

    release.set()
    th.join(5.0)
    tw.join(5.0)
    # the waiter was granted -> the key re-arms for the next episode
    assert lk.state()["owner"] is None and lk.state()["waiters"] == 0
    wd.check()
    assert ("lock", "stall-test") not in {
        k[:2] for k in wd._flagged
    }


def test_seeded_fault_journal_is_byte_reproducible():
    def drive(seed: int):
        plan = FaultPlan(
            seed=seed, stall_lock_at=1, stall_lock_ms=75.0,
            wedge_thread_at=2,
        )
        out = []
        for _ in range(4):
            out.append(plan.stalled_lock(lock="l"))
            out.append(plan.wedge_thread())
        return out, json.dumps(plan.journal, sort_keys=True)

    out1, j1 = drive(9)
    out2, j2 = drive(9)
    assert out1 == out2
    assert j1 == j2, "journal must be byte-equal for the same seed"
    # one-shot semantics: each fault fires exactly once at its index
    assert [v for v in out1 if isinstance(v, float) and v > 0] == [75.0]
    assert out1.count(True) == 1
    kinds = [e["kind"] for e in json.loads(j1)]
    assert kinds == ["stalled_lock", "wedged_thread"]


def test_seeded_wedged_thread_progress_stall(tmp_path):
    """wedged-thread fault: the worker stops advancing its progress
    counter while still 'active' -> the watchdog flights a stall."""
    clk = _Clock(0.0)
    wd = StallWatchdog(clock=clk)
    wd.configure(stall_s=5.0)
    bundle_writer.configure(directory=str(tmp_path), min_interval_s=0.0)
    plan = FaultPlan(seed=3, wedge_thread_at=1)
    state = {"done": 0, "wedged": False}

    def _step():
        if plan.wedge_thread():
            state["wedged"] = True
        if not state["wedged"]:
            state["done"] += 1

    wd.register_progress(
        "worker", lambda: {"active": 1, "progress": state["done"]}
    )
    _step()  # advances (n=0 < at)
    wd.check()  # baseline: progress=1 recorded
    clk.advance(3.0)
    _step()  # wedges at n=1: progress frozen from here on
    wd.check()  # value unchanged? no — 1 -> 1: starts the stuck timer
    clk.advance(6.0)
    fired = wd.check()
    assert [e["category"] for e in fired] == ["stall"]
    assert fired[0]["source"] == "worker"
    assert fired[0]["stuck_s"] >= 5.0
    assert bundle_writer.latest()["reason"] == "stall"
    # edge-triggered until progress resumes
    clk.advance(10.0)
    assert wd.check() == []
    state["wedged"] = False
    _step()
    wd.check()  # progress moved: re-arms
    _step()  # freeze again at a new value? no — keeps advancing
    assert wd.state()["events"] == 1
    assert plan.journal == [{"kind": "wedged_thread", "n": 1}]


def test_progress_source_exception_does_not_kill_scan():
    wd = StallWatchdog(clock=_Clock(0.0))

    def _bad():
        raise RuntimeError("boom")

    wd.register_progress("bad", _bad)
    assert wd.check() == []  # no raise, no stall
    errs = flight_recorder.events("thread_error")
    assert any("bad" in e["error"] for e in errs)


def test_instrumented_lock_tracks_owner_and_context_manager():
    wd = StallWatchdog(clock=_Clock(0.0))
    lk = InstrumentedLock("ctx", watchdog=wd)
    assert lk.state()["owner"] is None
    with lk:
        st = lk.state()
        assert st["owner"] == threading.current_thread().name
        assert st["waiters"] == 0
    assert lk.state()["owner"] is None
    # timeout on a contended acquire returns False and deregisters
    with lk:
        got = {}

        def _try():
            got["ok"] = lk.acquire(timeout=0.05)

        t = threading.Thread(target=_try, daemon=True)
        t.start()
        t.join(5.0)
    assert got["ok"] is False
    assert lk.state()["waiters"] == 0


# ------------------------------------------------------------- bundles
def test_bundle_retention_rate_limit_and_atomicity(tmp_path):
    clk = _Clock(0.0)
    bw = BundleWriter(
        directory=str(tmp_path), retention=3, min_interval_s=30.0,
        clock=clk,
    )
    paths = []
    for _ in range(5):
        clk.advance(60.0)
        paths.append(bw.capture(reason="test"))
    assert all(paths)
    names = sorted(os.listdir(tmp_path))
    assert len(names) == 3, "retention prunes oldest bundles"
    assert names == [os.path.basename(p) for p in paths[-3:]]
    assert not [n for n in names if n.endswith(".tmp")]
    # rate limit: a capture inside min_interval_s is suppressed...
    assert bw.capture(reason="test") is None
    assert bw.status()["suppressed"] == 1
    # ...unless forced (the CLI / ?capture=1 path)
    assert bw.capture(reason="manual", force=True) is not None
    assert bw.written == 6


def test_bundle_capture_disabled_without_directory():
    bw = BundleWriter(directory="", min_interval_s=0.0)
    assert bw.capture(reason="noop") is None
    assert bw.status()["dir"] is None


def test_latest_skips_torn_bundle(tmp_path):
    bw = BundleWriter(directory=str(tmp_path), min_interval_s=0.0)
    good = bw.capture(reason="good", force=True)
    assert good is not None
    # a writer killed mid-write leaves a torn newest file (sorts after
    # every pid-numbered bundle): latest() must skip it
    torn = os.path.join(str(tmp_path), "bundle-zzz-torn.json")
    with open(torn, "w") as fh:
        fh.write('{"reason": "torn"')
    got = bw.latest()
    assert got is not None
    assert got["reason"] == "good"
    assert got["path"] == good
    os.remove(good)
    assert bw.latest() is None


def test_bundle_is_complete_and_json_clean(tmp_path):
    with _parked_thread():
        bw = BundleWriter(directory=str(tmp_path), min_interval_s=0.0)
        path = bw.capture(reason="unit", force=True)
    assert path is not None
    with open(path) as fh:
        bundle = json.load(fh)
    assert set(bundle) >= {
        "reason", "ts", "pid", "flame_windows", "profiler", "flight",
        "timeseries", "stacks", "requests", "watchdog",
    }
    assert bundle["pid"] == os.getpid()
    assert any("_park_here" in "\n".join(v) for v in bundle["stacks"].values())


# --------------------------------------------------- endpoints and CLI
@pytest.fixture
def debug_server(tmp_path):
    from janusgraph_tpu.core.graph import open_graph
    from janusgraph_tpu.server import JanusGraphManager, JanusGraphServer

    g = open_graph({"ids.authority-wait-ms": 0.0})
    m = JanusGraphManager()
    m.put_graph("graph", g)
    s = JanusGraphServer(manager=m, bundle_dir=str(tmp_path)).start()
    yield s
    s.stop()
    g.close()
    history.reset()
    slo_engine.reset()
    import janusgraph_tpu.server.server as server_mod

    with server_mod._HEALTH_LOCK:
        server_mod._HEALTH_STATE["status"] = None


def _get(base: str, path: str) -> bytes:
    return urllib.request.urlopen(base + path, timeout=5).read()


def test_debug_endpoints_serve_profile_stacks_and_bundle(debug_server):
    base = "http://127.0.0.1:%d" % debug_server.port
    h = json.loads(_get(base, "/healthz"))
    prof = h["profiler"]
    assert prof["enabled"] is True and prof["alive"] is True
    assert prof["died"] is None
    assert "watchdog" in prof and "bundles" in prof
    # let the 20 Hz sampler collect a few stacks
    deadline = time.monotonic() + 5.0
    while sampling_profiler.status()["samples"] < 3:
        assert time.monotonic() < deadline, "server sampler never sampled"
        time.sleep(0.02)
    text = _get(base, "/debug/profile").decode()
    assert text.strip(), "live flame text should not be empty"
    assert " " in text.splitlines()[0]  # "stack weight_us" lines
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(base, "/debug/profile?window=x")
    assert ei.value.code == 400
    stacks = json.loads(_get(base, "/debug/stacks"))
    assert stacks["stacks"], "every live thread appears in the dump"
    # no bundle on disk yet -> 404 with a hint
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(base, "/debug/bundle")
    assert ei.value.code == 404
    bundle = json.loads(_get(base, "/debug/bundle?capture=1"))
    assert bundle["reason"] == "manual"
    for key in ("flame_windows", "flight", "stacks", "watchdog", "requests"):
        assert key in bundle
    # subsequent plain GET serves the bundle just captured
    again = json.loads(_get(base, "/debug/bundle"))
    assert again["path"] == bundle["path"]


def test_healthz_degrades_when_sampler_dies(debug_server):
    base = "http://127.0.0.1:%d" % debug_server.port
    assert json.loads(_get(base, "/healthz"))["profiler"]["alive"] is True
    # simulate a wedged/killed sampler thread: still enabled, not alive
    sampling_profiler._stop.set()
    sampling_profiler._thread.join(timeout=5.0)
    # a degraded /healthz is a 503 whose body carries the diagnosis
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(base, "/healthz")
    assert ei.value.code == 503
    h = json.loads(ei.value.read())
    assert h["profiler"]["enabled"] is True
    assert h["profiler"]["alive"] is False
    assert h["status"] == "degraded", "a dead sampler is a lying profiler"


def test_cli_flame_live_and_bundle(tmp_path, capsys):
    from janusgraph_tpu.cli import main

    with _parked_thread():
        sampling_profiler.sample_once()
        assert main(["flame", "--live"]) == 0
    out = capsys.readouterr().out
    assert "_park_here" in out
    # no trace id and no --live is a usage error
    assert main(["flame"]) == 2
    capsys.readouterr()
    # bundle --capture writes then prints the bundle
    bundle_writer.configure(directory=str(tmp_path), min_interval_s=0.0)
    assert main(["bundle", "--capture"]) == 0
    got = json.loads(capsys.readouterr().out)
    assert got["reason"] == "cli"


def test_cli_flame_live_empty_profiler_fails(capsys):
    from janusgraph_tpu.cli import main

    assert main(["flame", "--live"]) == 1
    assert "no samples" in capsys.readouterr().err


# ---------------------------------------------------------------- JG112
def test_jg112_registered_and_fires_on_fixture():
    from janusgraph_tpu.analysis import RULES, analyze_paths

    assert "JG112" in RULES
    path = os.path.join(
        REPO, "tests", "fixtures", "graphlint",
        "bad_silent_thread_death.py",
    )
    findings = [
        f for f in analyze_paths([path]) if f.rule_id == "JG112"
    ]
    assert sorted(f.line for f in findings) == [22, 46]


def test_plane_daemons_record_rather_than_die_silently():
    """The plane's own daemons obey JG112: a poisoned sample loop
    flights a thread_error and marks died instead of vanishing."""
    p = SamplingProfiler()
    p.configure(hz=200.0)
    # poison the sample counter so sample_once raises in the run loop
    p._samples = None
    p.start()
    try:
        deadline = time.monotonic() + 5.0
        while p._died is None:
            assert time.monotonic() < deadline, "sampler never recorded death"
            time.sleep(0.01)
    finally:
        p._samples = 0
        p.stop()
    errs = flight_recorder.events("thread_error")
    assert any(e["thread"] == "profiler-sampler" for e in errs)
