"""Native C++ kernel parity vs the numpy fallbacks (janusgraph_tpu/native).
The suite passes with or without a compiler; parity tests only run when the
native library built."""

import numpy as np
import pytest

from janusgraph_tpu import native


def test_loader_reports_availability():
    # in this image g++ exists, so the native path must come up
    assert native.available() in (True, False)


@pytest.mark.skipif(not native.available(), reason="no native lib")
def test_build_csr_matches_numpy():
    rng = np.random.default_rng(2)
    n, m = 500, 4000
    src = rng.integers(0, n, m).astype(np.int32)
    dst = rng.integers(0, n, m).astype(np.int32)

    oi, od, op, ii, isrc, ip = native.build_csr(n, src, dst)

    ref_op = np.argsort(src, kind="stable")
    ref_ip = np.argsort(dst, kind="stable")
    np.testing.assert_array_equal(od, dst[ref_op])
    np.testing.assert_array_equal(isrc, src[ref_ip])
    np.testing.assert_array_equal(op, ref_op)
    np.testing.assert_array_equal(ip, ref_ip)
    ref_oi = np.zeros(n + 1, dtype=np.int64)
    np.add.at(ref_oi, src.astype(np.int64) + 1, 1)
    np.testing.assert_array_equal(oi, np.cumsum(ref_oi))


@pytest.mark.skipif(not native.available(), reason="no native lib")
def test_segment_ids_matches_numpy():
    indptr = np.array([0, 2, 2, 5, 9], dtype=np.int64)
    got = native.segment_ids(indptr, 9)
    np.testing.assert_array_equal(
        got, np.repeat(np.arange(4, dtype=np.int32), np.diff(indptr))
    )


@pytest.mark.skipif(not native.available(), reason="no native lib")
def test_rmat_edges_shape_and_determinism():
    r1 = native.rmat_edges(10, 4096, seed=7)
    r2 = native.rmat_edges(10, 4096, seed=7)
    assert r1 is not None
    np.testing.assert_array_equal(r1[0], r2[0])
    np.testing.assert_array_equal(r1[1], r2[1])
    assert r1[0].max() < 1024 and r1[0].min() >= 0
    # rmat skew: some vertex repeats far above uniform expectation
    counts = np.bincount(r1[1], minlength=1024)
    assert counts.max() > 3 * counts.mean()


def test_ellpack_native_or_fallback_parity():
    """ELLPack built with native fill must equal the pure-numpy build."""
    import os
    from janusgraph_tpu.olap.kernels import ELLPack

    rng = np.random.default_rng(3)
    n, m = 120, 900
    src = rng.integers(0, n, m).astype(np.int64)
    dst = rng.integers(0, n, m).astype(np.int64)
    w = rng.uniform(0.1, 1.0, m).astype(np.float32)

    pack = ELLPack(src, dst, w, n)
    # force the numpy fallback by monkeypatching availability
    orig = native.ell_fill
    try:
        native.ell_fill = lambda *a, **k: False
        pack_np = ELLPack(src, dst, w, n)
    finally:
        native.ell_fill = orig
    assert len(pack.buckets) == len(pack_np.buckets)
    for (i1, w1, v1, rs1, ns1), (i2, w2, v2, rs2, ns2) in zip(
        pack.buckets, pack_np.buckets
    ):
        np.testing.assert_array_equal(i1, i2)
        np.testing.assert_allclose(w1, w2)
        np.testing.assert_array_equal(v1, v2)
        assert ns1 == ns2
        if rs1 is None:
            assert rs2 is None
        else:
            np.testing.assert_array_equal(rs1, rs2)
    np.testing.assert_array_equal(pack.unpermute, pack_np.unpermute)


def test_csr_from_edges_uses_native_consistently():
    from janusgraph_tpu.olap import csr_from_edges

    rng = np.random.default_rng(4)
    n, m = 64, 300
    src = rng.integers(0, n, m).astype(np.int32)
    dst = rng.integers(0, n, m).astype(np.int32)
    w = rng.uniform(0, 1, m).astype(np.float32)
    csr = csr_from_edges(n, src, dst, w)
    assert csr.num_edges == m
    # weight alignment: edge k in in-order is (src[p], dst[p]) with weight w[p]
    seg = np.repeat(np.arange(n), np.diff(csr.in_indptr))
    total = 0.0
    for s, d, wt in zip(src, dst, w):
        total += wt
    assert abs(csr.in_edge_weight.sum() - total) < 1e-3
    assert abs(csr.out_edge_weight.sum() - total) < 1e-3
