"""OLAP traversal execution (TraversalVertexProgram analogue — reference:
BASELINE config #5 3-hop counts via TraversalVertexProgram through Fulgora):
a step chain compiles into channel-per-superstep BSP over traverser-count
state. Oracle: the OLTP traversal DSL on the same graph.
"""

import numpy as np
import pytest

from janusgraph_tpu.core import gods
from janusgraph_tpu.core.graph import open_graph
from janusgraph_tpu.olap.csr import load_csr
from janusgraph_tpu.olap.cpu_executor import CPUExecutor
from janusgraph_tpu.olap.programs import OLAPTraversalProgram, steps_from_spec
from janusgraph_tpu.olap.tpu_executor import TPUExecutor
from janusgraph_tpu.parallel import ShardedExecutor


@pytest.fixture(scope="module")
def mesh8():
    import jax
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()[:8]), ("p",))


@pytest.fixture()
def g():
    graph = open_graph()
    gods.load(graph)
    yield graph
    graph.close()


def oltp_count(g, spec, seed_name=None):
    t = g.traversal()
    trav = t.V() if seed_name is None else t.V().has("name", seed_name)
    for item in spec:
        direction, labels = (item, ()) if isinstance(item, str) else (
            item[0], item[1] or ()
        )
        trav = {"out": trav.out, "in": trav.in_, "both": trav.both}[direction](
            *labels
        )
    return trav.count()


@pytest.mark.parametrize("spec", [
    [("out", ["father"]), ("out", ["father"])],
    [("out", ["brother"]), ("out", ["lives"])],
    [("out", None), ("in", None)],
    [("both", ["brother"]), ("both", ["brother"]), ("both", ["brother"])],
    [("in", ["battled"])],
])
def test_olap_traversal_counts_match_oltp(g, spec, mesh8):
    csr = load_csr(g)
    prog = lambda: OLAPTraversalProgram(steps_from_spec(g, spec))
    expect = oltp_count(g, spec)
    for runner in (
        lambda p: CPUExecutor(csr).run(p),
        lambda p: TPUExecutor(csr).run(p),
        lambda p: ShardedExecutor(csr, mesh=mesh8).run(p),
    ):
        res = runner(prog())
        assert int(np.asarray(res["count"]).sum()) == expect, spec


def test_olap_traversal_seeded(g):
    csr = load_csr(g)
    herc = csr.index_of(g.traversal().V().has("name", "hercules").next().id)
    prog = OLAPTraversalProgram(
        steps_from_spec(g, [("out", ["battled"])]), seed_indices=[herc]
    )
    res = CPUExecutor(csr).run(prog)
    assert int(res["count"].sum()) == 3
    # per-destination counts = group-count by vertex
    names = {
        csr.index_of(v.id): v.value("name")
        for v in g.new_transaction().vertices()
    }
    hit = {names[i] for i in np.nonzero(res["count"])[0]}
    assert hit == {"nemean", "hydra", "cerberus"}


def test_multi_hop_multiplicities_counted(g):
    """Traverser COUNTS, not reachability: revisits multiply."""
    csr = load_csr(g)
    # jupiter <-> neptune <-> pluto brothers: 3 hops from all vertices
    spec = [("out", ["brother"])] * 3
    expect = oltp_count(g, spec)
    res = CPUExecutor(csr).run(
        OLAPTraversalProgram(steps_from_spec(g, spec))
    )
    assert int(res["count"].sum()) == expect


def test_random_graph_khop_parity(mesh8):
    from janusgraph_tpu.olap import csr_from_edges

    rng = np.random.default_rng(4)
    n, m = 200, 900
    src = rng.integers(0, n, m).astype(np.int32)
    dst = rng.integers(0, n, m).astype(np.int32)
    et = rng.integers(0, 2, m).astype(np.int32)
    csr = csr_from_edges(n, src, dst, edge_types=et)

    # numpy oracle: count matrix-vector products with label masks
    def oracle(specs):
        counts = np.ones(n)
        for d, lab in specs:
            msk = np.ones(m, bool) if lab is None else np.isin(et, lab)
            nxt = np.zeros(n)
            if d in ("out", "both"):
                np.add.at(nxt, dst[msk], counts[src[msk]])
            if d in ("in", "both"):
                np.add.at(nxt, src[msk], counts[dst[msk]])
            counts = nxt
        return counts

    from janusgraph_tpu.olap.programs.olap_traversal import TraversalStep

    spec = [("out", (0,)), ("both", (1,)), ("in", None)]
    steps = [TraversalStep(d, lab) for d, lab in spec]
    expect = oracle(spec)
    for res in (
        CPUExecutor(csr).run(OLAPTraversalProgram(steps)),
        TPUExecutor(csr).run(OLAPTraversalProgram(steps)),
        ShardedExecutor(csr, mesh=mesh8).run(OLAPTraversalProgram(steps)),
    ):
        np.testing.assert_allclose(
            np.asarray(res["count"], np.float64), expect, rtol=1e-5
        )


def test_compute_traverse_facade(g):
    res = g.compute(executor="cpu").traverse(
        ("out", ["father"]), ("out", ["father"])
    ).submit()
    assert int(np.asarray(res.states["count"]).sum()) == oltp_count(
        g, [("out", ["father"]), ("out", ["father"])]
    )


def test_executor_reuse_does_not_alias_channels(g, mesh8):
    """Regression: two programs with the same generic channel names (s0...)
    on ONE reused executor must not share channel packs/views."""
    csr = load_csr(g)
    out_father = steps_from_spec(g, [("out", ["father"])])
    in_battled = steps_from_spec(g, [("in", ["battled"])])
    for ex in (TPUExecutor(csr), ShardedExecutor(csr, mesh=mesh8)):
        a = ex.run(OLAPTraversalProgram(out_father))
        b = ex.run(OLAPTraversalProgram(in_battled))
        assert int(np.asarray(a["count"]).sum()) == 2   # father edges
        assert int(np.asarray(b["count"]).sum()) == 3   # battled edges


def test_program_cache_key_value_equal(g):
    a = OLAPTraversalProgram(steps_from_spec(g, [("out", ["father"])]))
    b = OLAPTraversalProgram(steps_from_spec(g, [("out", ["father"])]))
    c = OLAPTraversalProgram(steps_from_spec(g, [("in", ["father"])]))
    assert a.cache_key() == b.cache_key()
    assert a.cache_key() != c.cache_key()


def test_unknown_label_raises(g):
    with pytest.raises(ValueError, match="unknown edge label"):
        steps_from_spec(g, [("out", ["knowz"])])


def test_channel_cache_bounded_and_eviction_safe(g, mesh8):
    """Eviction must actually FIRE (more distinct views than the cap) and
    both the LRU and the compiled-fn pruning must leave behavior exact."""
    csr = load_csr(g)
    labels = ["father", "mother", "brother", "battled", "lives", "pet"]
    # 12 distinct channel values (6 labels x 2 directions) > cap
    specs = [[(d, [lab])] for lab in labels for d in ("out", "in")]

    ex = TPUExecutor(csr)
    ex.CHANNEL_CACHE_SIZE = 4
    for spec in specs:
        ex.run(OLAPTraversalProgram(steps_from_spec(g, spec)))
    assert len(ex._channel_packs) <= 4
    # the FIRST spec was evicted long ago: rebuild must be exact
    res = ex.run(OLAPTraversalProgram(steps_from_spec(g, [("in", ["battled"])])))
    assert int(np.asarray(res["count"]).sum()) == 3

    sx = ShardedExecutor(csr, mesh=mesh8)
    sx.CHANNEL_CACHE_SIZE = 4
    for spec in specs[:6]:
        sx.run(OLAPTraversalProgram(steps_from_spec(g, spec)))
    assert len(sx._channel_views) <= 4
    res = sx.run(OLAPTraversalProgram(steps_from_spec(g, [("out", ["father"])])))
    assert int(np.asarray(res["count"]).sum()) == 2


# ------------------------------------------------------------- filtered OLAP
def oltp_filtered_count(g, seed_filters, spec):
    """OLTP oracle for filtered chains: g.V().has(...).out().has(...)..."""
    from janusgraph_tpu.core.traversal import P

    trav = g.traversal().V()
    for key, pred, val in seed_filters or ():
        trav = trav.has(key, P._of(pred, val, pred.name))
    for item in spec:
        direction = item[0] if not isinstance(item, str) else item
        labels = () if isinstance(item, str) else (item[1] or ())
        filters = item[2] if not isinstance(item, str) and len(item) > 2 else ()
        trav = {"out": trav.out, "in": trav.in_, "both": trav.both}[direction](
            *labels
        )
        for key, pred, val in filters:
            trav = trav.has(key, P._of(pred, val, pred.name))
    return trav.count()


def test_filtered_traversal_matches_oltp_gods(g, mesh8):
    """VERDICT r3 #4 gate: filtered multi-hop parity vs OLTP on gods."""
    from janusgraph_tpu.core.predicates import Cmp
    from janusgraph_tpu.olap.programs.olap_traversal import (
        build_olap_traversal,
    )

    csr = load_csr(g, property_keys=("age",))
    cases = [
        # demigod/god endpoints older than 100
        ((), [("out", ["father"], [("age", Cmp.GREATER_THAN, 100)])]),
        # start from old vertices, walk two hops
        ([("age", Cmp.GREATER_THAN, 100)],
         [("out", ["brother"]), ("out", ["lives"])]),
        # filter mid-chain between hops
        ((), [("out", None, [("age", Cmp.GREATER_THAN_EQUAL, 30)]),
              ("out", None)]),
    ]
    for seed_filters, spec in cases:
        expect = oltp_filtered_count(g, seed_filters, spec)
        prog = lambda: build_olap_traversal(  # noqa: E731
            g, csr, spec, seed_filters=seed_filters
        )
        for runner in (
            lambda p: CPUExecutor(csr).run(p),
            lambda p: TPUExecutor(csr).run(p),
            lambda p: ShardedExecutor(csr, mesh=mesh8).run(p),
        ):
            res = runner(prog())
            assert int(np.asarray(res["count"]).sum()) == expect, (
                seed_filters, spec
            )


def test_filtered_traversal_random_graph(mesh8):
    """Filter parity on a random property graph vs a numpy oracle."""
    from janusgraph_tpu.core.predicates import Cmp
    from janusgraph_tpu.olap import csr_from_edges
    from janusgraph_tpu.olap.programs.olap_traversal import (
        OLAPTraversalProgram,
        PropertyFilter,
        TraversalStep,
        evaluate_filter_mask,
    )

    rng = np.random.default_rng(9)
    n, m = 150, 700
    src = rng.integers(0, n, m).astype(np.int32)
    dst = rng.integers(0, n, m).astype(np.int32)
    score = rng.uniform(0, 10, n)
    csr = csr_from_edges(n, src, dst)
    csr.properties["score"] = score

    def oracle():
        counts = np.ones(n)
        nxt = np.zeros(n)
        np.add.at(nxt, dst, counts[src])
        nxt *= score > 5.0
        counts = nxt
        nxt = np.zeros(n)
        np.add.at(nxt, dst, counts[src])
        return nxt

    flt = (PropertyFilter("score", Cmp.GREATER_THAN, 5.0),)
    mask = evaluate_filter_mask(csr, flt)
    np.testing.assert_array_equal(mask, (score > 5.0).astype(np.float32))
    steps = [TraversalStep("out", None, flt), TraversalStep("out")]
    masks = np.stack(
        [mask, np.ones(n, dtype=np.float32)], axis=1
    )
    expect = oracle()
    for res in (
        CPUExecutor(csr).run(OLAPTraversalProgram(steps, step_masks=masks)),
        TPUExecutor(csr).run(OLAPTraversalProgram(steps, step_masks=masks)),
        ShardedExecutor(csr, mesh=mesh8).run(
            OLAPTraversalProgram(steps, step_masks=masks)
        ),
    ):
        np.testing.assert_allclose(
            np.asarray(res["count"], np.float64), expect, rtol=1e-5
        )


def test_group_count_by_label(g):
    """Terminal parity vs OLTP groupCount().by(label)."""
    from janusgraph_tpu.olap.programs.olap_traversal import (
        build_olap_traversal,
        group_count_by_label,
    )

    csr = load_csr(g)
    res = CPUExecutor(csr).run(build_olap_traversal(g, csr, ["out"]))
    got = group_count_by_label(g, csr, res["count"])
    # OLTP oracle
    expect = {}
    for v in g.traversal().V().out().to_list():
        lbl = v.label
        expect[lbl] = expect.get(lbl, 0) + 1
    assert got == {k: float(v) for k, v in expect.items()}


def test_text_filter_masks(g):
    """Non-numeric predicates (Text) work through the scalar path."""
    from janusgraph_tpu.core.predicates import Text
    from janusgraph_tpu.olap.programs.olap_traversal import (
        PropertyFilter,
        evaluate_filter_mask,
    )

    csr = load_csr(g, property_keys=("name",))
    mask = evaluate_filter_mask(
        csr, (PropertyFilter("name", Text.CONTAINS_PREFIX, "her"),)
    )
    names = csr.properties["name"]
    assert {names[i] for i in np.nonzero(mask)[0]} == {"hercules"}


def test_compute_traverse_filtered_facade(g):
    """compute().traverse() with filters builds masks at submit() — a
    filter-bearing spec must never run unfiltered (silent wrong counts)."""
    from janusgraph_tpu.core.predicates import Cmp
    from janusgraph_tpu.olap.programs.olap_traversal import (
        OLAPTraversalProgram,
        TraversalStep,
        PropertyFilter,
    )

    spec = ("out", ["father"], [("age", Cmp.GREATER_THAN, 100)])
    expect = oltp_filtered_count(g, (), [spec])
    res = g.compute().traverse(spec).submit()
    assert int(np.asarray(res.states["count"]).sum()) == expect
    # direct construction without masks refuses filter-bearing steps
    with pytest.raises(ValueError, match="build_olap_traversal"):
        OLAPTraversalProgram(
            (TraversalStep("out", None,
                           (PropertyFilter("age", Cmp.GREATER_THAN, 1),)),)
        )


def test_program_supersedes_earlier_traverse(g):
    """compute().traverse(...).program(p) runs p — program() must clear the
    deferred traverse spec, not let submit() silently rebuild over it."""
    from janusgraph_tpu.olap.programs.pagerank import PageRankProgram

    c = g.compute(executor="cpu").traverse(("out", ["father"]))
    c.program(PageRankProgram(max_iterations=3))
    res = c.submit()
    assert "rank" in res.states and "count" not in res.states


# -------------------------------------------------------------------- paths
# OLAP path()/select(): device reach masks + host backward enumeration
# (olap_traversal.enumerate_paths; VERDICT r4 #4, SURVEY §7 hard part (a)).


def oltp_paths(g, chain):
    trav = g.traversal().V()
    for direction, labels in chain:
        trav = {"out": trav.out, "in": trav.in_, "both": trav.both}[
            direction
        ](*(labels or ()))
    return sorted(
        tuple(v.id for v in p) for p in trav.path().to_list()
    )


@pytest.mark.parametrize("chain", [
    [("out", ["father"]), ("out", ["father"])],
    [("out", ["battled"]), ("in", ["battled"]), ("out", ["father"])],
    [("both", ["brother"]), ("out", ["lives"])],
])
def test_olap_paths_match_oltp_gods(g, chain):
    res = g.compute(executor="cpu").traverse(
        *[(d, l) for d, l in chain], paths=True
    ).submit()
    got = sorted(res.paths())
    want = oltp_paths(g, chain)
    assert got == want
    # the device count prices the enumeration exactly
    assert len(got) == int(np.asarray(res.states["count"]).sum())


def test_olap_paths_random_graph_all_executors(mesh8):
    from janusgraph_tpu.olap.csr import csr_from_edges
    from janusgraph_tpu.olap.programs.olap_traversal import (
        TraversalStep,
        enumerate_paths,
    )

    rng = np.random.default_rng(17)
    n, m = 60, 200
    src = rng.integers(0, n, m).astype(np.int32)
    dst = rng.integers(0, n, m).astype(np.int32)
    csr = csr_from_edges(n, src, dst)
    seeds = tuple(int(s) for s in rng.choice(n, 5, replace=False))
    prog = OLAPTraversalProgram(
        (TraversalStep("out"), TraversalStep("out"), TraversalStep("out")),
        seed_indices=seeds, record_reach=True,
    )
    # numpy oracle: explicit 3-hop chain enumeration
    adj = [[] for _ in range(n)]
    for s, d in zip(src, dst):
        adj[s].append(int(d))
    want = sorted(
        (a, b, c, d)
        for a in seeds for b in adj[a] for c in adj[b] for d in adj[c]
    )
    for make in (
        lambda: CPUExecutor(csr).run(prog),
        lambda: TPUExecutor(csr).run(prog),
        lambda: ShardedExecutor(csr, mesh=mesh8).run(prog),
    ):
        states = make()
        got = sorted(enumerate_paths(csr, prog, states))
        # vertex ids == indices for csr_from_edges-built graphs
        assert got == want
        assert len(got) == int(np.asarray(states["count"]).sum())


def test_olap_paths_respect_filters(g):
    """A mid-chain has()-filter (arrival-vertex property) must prune
    enumerated paths exactly like the OLTP filter step."""
    from janusgraph_tpu.core.predicates import Cmp
    from janusgraph_tpu.core.traversal import P

    res = g.compute(executor="cpu").traverse(
        ("out", ["battled"], [("name", Cmp.NOT_EQUAL, "hydra")]),
        ("in", ["battled"]),
        paths=True,
    ).submit()
    got = sorted(res.paths())
    trav = (
        g.traversal().V().out("battled")
        .has("name", P._of(Cmp.NOT_EQUAL, "hydra", "neq"))
        .in_("battled").path().to_list()
    )
    want = sorted(tuple(v.id for v in p) for p in trav)
    assert got == want and got  # non-empty: the filter prunes, not empties


def test_olap_select_labeled_steps(g):
    res = g.compute(executor="cpu").traverse(
        ("out", ["father"], (), "f"),
        ("out", ["father"], (), "gf"),
        paths=True, source_as="me",
    ).submit()
    rows = sorted(
        (d["me"], d["f"], d["gf"]) for d in res.select("me", "f", "gf")
    )
    assert rows == oltp_paths(
        g, [("out", ["father"]), ("out", ["father"])]
    )
    with pytest.raises(ValueError, match="match no as"):
        list(res.select("nope"))


def test_olap_paths_limit_and_missing_reach(g):
    res = g.compute(executor="cpu").traverse(
        ("out", ["battled"]), ("in", ["battled"]), paths=True
    ).submit()
    all_paths = list(res.paths())
    assert list(res.paths(limit=2)) == all_paths[:2]
    plain = g.compute(executor="cpu").traverse(("out", ["father"])).submit()
    with pytest.raises(ValueError, match="paths=True"):
        plain.paths()


def test_olap_paths_limit_zero_and_duplicate_label(g):
    res = g.compute(executor="cpu").traverse(
        ("out", ["father"]), paths=True
    ).submit()
    assert list(res.paths(limit=0)) == []
    dup = g.compute(executor="cpu").traverse(
        ("out", None, (), "x"), ("out", None, (), "x"), paths=True
    ).submit()
    with pytest.raises(ValueError, match="duplicate as"):
        list(dup.select("x"))


# --------------------------------------------------------------------- sack
# OLAP-side sack (withSack().sack(op).by(weight)): per-column edge
# transforms carry [count, sack(, w*count)] through one BSP run.


def test_olap_sack_matches_enumeration_all_executors(mesh8):
    from janusgraph_tpu.olap.csr import csr_from_edges
    from janusgraph_tpu.olap.programs.olap_traversal import TraversalStep

    rng = np.random.default_rng(5)
    n, m = 60, 200
    src = rng.integers(0, n, m).astype(np.int32)
    dst = rng.integers(0, n, m).astype(np.int32)
    w = rng.uniform(0.5, 2.0, m).astype(np.float32)
    csr = csr_from_edges(n, src, dst, weights=w)

    adj = [[] for _ in range(n)]
    for s, d, wt in zip(src, dst, w):
        adj[s].append((int(d), float(wt)))
    per_v_sum = np.zeros(n)
    per_v_mult = np.zeros(n)
    for a in range(n):
        for b, w1 in adj[a]:
            for c, w2 in adj[b]:
                per_v_sum[c] += w1 + w2
                per_v_mult[c] += w1 * w2

    steps = (TraversalStep("out"), TraversalStep("out"))
    for make in (
        lambda p: CPUExecutor(csr).run(p),
        lambda p: TPUExecutor(csr).run(p),
        lambda p: ShardedExecutor(csr, mesh=mesh8).run(p),
    ):
        rs = make(OLAPTraversalProgram(steps, sack="sum"))
        np.testing.assert_allclose(
            np.asarray(rs["sack"], np.float64), per_v_sum,
            rtol=1e-3, atol=1e-4,
        )
        rm = make(OLAPTraversalProgram(steps, sack="mult"))
        np.testing.assert_allclose(
            np.asarray(rm["sack"], np.float64), per_v_mult,
            rtol=1e-3, atol=1e-4,
        )


def test_olap_sack_matches_oltp_oracle(g):
    """g.withSack(0).V().outE('battled').sack(sum w).inV() — OLTP folds
    per traverser; the OLAP total sack mass must agree."""
    csr = load_csr(g, weight_key="time")
    prog = OLAPTraversalProgram(
        steps_from_spec(g, [("out", ["battled"])]), sack="sum",
    )
    res = CPUExecutor(csr).run(prog)
    olap_total = float(np.asarray(res["sack"], np.float64).sum())

    # OLTP oracle via edge iteration (sack == sum of traversed weights)
    tx = g.new_transaction()
    from janusgraph_tpu.core.codecs import Direction

    total = 0.0
    for v in tx.vertices():
        for e in tx.get_edges(v, Direction.OUT, ("battled",)):
            total += float(e.value("time"))
    tx.rollback()
    assert olap_total == pytest.approx(total, rel=1e-6)


def test_olap_sack_with_filters_and_facade(g):
    """Facade: compute().weight('time').traverse(..., sack='sum') — step
    filters drop rejected traversers' sack mass too."""
    from janusgraph_tpu.core.predicates import Cmp

    res = g.compute(executor="cpu").weight("time").traverse(
        ("out", ["battled"], [("name", Cmp.EQUAL, "hydra")]),
        sack="sum",
    ).submit()
    # only the hercules->hydra battle (time=2) survives the filter
    tx = g.new_transaction()
    from janusgraph_tpu.core.codecs import Direction

    want = 0.0
    for v in tx.vertices():
        for e in tx.get_edges(v, Direction.OUT, ("battled",)):
            if e.in_vertex.value("name") == "hydra":
                want += float(e.value("time"))
    tx.rollback()
    assert float(
        np.asarray(res.states["sack"], np.float64).sum()
    ) == pytest.approx(want, rel=1e-6)
    assert np.asarray(res.states["count"]).sum() == 1


def test_olap_sack_tiny_weight_exact_and_unweighted_refused(g):
    """Per-column MUL must stay exact for |w-1| below f32 eps (the
    where-select form), and sack on a weightless CSR fails fast."""
    import numpy as np

    from janusgraph_tpu.olap.vertex_program import (
        EdgeTransform,
        apply_edge_transform,
    )

    msgs = np.ones((1, 2), np.float32)
    w = np.asarray([1e-8], np.float32)
    out = apply_edge_transform(
        np, msgs, w, EdgeTransform.NONE,
        (EdgeTransform.NONE, EdgeTransform.MUL_WEIGHT),
    )
    assert out[0, 0] == 1.0 and out[0, 1] == np.float32(1e-8)

    from janusgraph_tpu.olap.programs.olap_traversal import (
        build_olap_traversal,
    )

    csr = load_csr(g)  # no weight_key -> no weight column
    with pytest.raises(ValueError, match="weight"):
        build_olap_traversal(g, csr, [("out", ["battled"])], sack="sum")


def test_compute_facade_sharded_executor(g):
    """graph.compute(executor='sharded'): the mesh executor behind the
    same facade (computer.executor config or explicit arg), with
    computer.exchange/agg selecting the comm/agg strategy."""
    from janusgraph_tpu.olap.programs import PageRankProgram

    res = g.compute(executor="sharded").traverse(
        ("out", ["father"]), ("out", ["father"])
    ).submit()
    assert int(np.asarray(res.states["count"]).sum()) == oltp_count(
        g, [("out", ["father"]), ("out", ["father"])]
    )
    # config-driven default executor + ring exchange
    g.config.local["computer.executor"] = "sharded"
    g.config.local["computer.exchange"] = "ring"
    g.config.local["computer.agg"] = "segment"
    res2 = g.compute().program(
        PageRankProgram(max_iterations=5, tol=0.0)
    ).submit()
    cpu = g.compute(executor="cpu").program(
        PageRankProgram(max_iterations=5, tol=0.0)
    ).submit()
    np.testing.assert_allclose(
        np.asarray(res2.states["rank"], np.float64),
        np.asarray(cpu.states["rank"], np.float64), rtol=1e-4, atol=1e-6,
    )


def test_sack_on_weightless_csr_refused_by_every_executor(g, mesh8):
    """The guard lives at run() entry, not just the builder: direct
    OLAPTraversalProgram construction cannot silently fold w=1."""
    csr = load_csr(g)  # weightless
    prog = OLAPTraversalProgram(
        steps_from_spec(g, [("out", ["battled"])]), sack="sum",
    )
    for ex in (
        CPUExecutor(csr), TPUExecutor(csr),
        ShardedExecutor(csr, mesh=mesh8),
    ):
        with pytest.raises(ValueError, match="no edge weights"):
            ex.run(prog)
