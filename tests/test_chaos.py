"""Chaos engine + self-healing paths (ISSUE 3).

The acceptance suite: a seeded FaultPlan on the inmemory backend drives a
200-tx OLTP workload plus a PageRank run through temporary faults, a torn
batch, a lock-lease expiry, a mid-scan kill, and a superstep preemption —
and everything completes, recovers, and reproduces under the same seed.
Plus unit coverage for the circuit breaker's state machine, checkpoint
corruption fallback, scanner resume, and the /healthz snapshot.
"""

from __future__ import annotations

import os
import time

import numpy as np
import pytest

from janusgraph_tpu.core.codecs import Direction
from janusgraph_tpu.core.graph import JanusGraphTPU
from janusgraph_tpu.exceptions import (
    CircuitOpenError,
    InjectedCrashError,
    SuperstepPreempted,
    TemporaryBackendError,
)
from janusgraph_tpu.storage.circuit import CLOSED, HALF_OPEN, OPEN, CircuitBreaker
from janusgraph_tpu.storage.faults import (
    FaultInjectingStoreManager,
    FaultPlan,
)
from janusgraph_tpu.storage.inmemory import InMemoryStoreManager

SEED = 20260804


# --------------------------------------------------------------------------
# /healthz snapshot (before any test in this file trips a breaker)


def test_healthz_reports_ok_then_degraded_on_open_breaker():
    from janusgraph_tpu.server.server import healthz_snapshot

    snap = healthz_snapshot()
    assert snap["status"] in ("ok", "degraded")
    baseline_degraded = snap["status"] == "degraded"

    br = CircuitBreaker("healthz-test", failure_threshold=1,
                        reset_timeout_s=60.0)
    assert healthz_snapshot()["breakers"]["breaker.healthz-test.state"] == 0.0
    if not baseline_degraded:
        assert healthz_snapshot()["status"] == "ok"

    def boom():
        raise TemporaryBackendError("down")

    with pytest.raises(TemporaryBackendError):
        br.call(boom)
    snap = healthz_snapshot()
    assert snap["status"] == "degraded"
    assert snap["breakers"]["breaker.healthz-test.state"] == 2.0
    # close it again so later healthz consumers see a clean gauge
    br._state = CLOSED
    br._publish(CLOSED)
    assert healthz_snapshot()["breakers"]["breaker.healthz-test.state"] == 0.0


def test_healthz_endpoint_served_over_http():
    import json as _json
    import urllib.error
    import urllib.request

    from janusgraph_tpu.core.graph import open_graph
    from janusgraph_tpu.server import JanusGraphManager, JanusGraphServer

    g = open_graph({"ids.authority-wait-ms": 0.0})
    manager = JanusGraphManager()
    manager.put_graph("graph", g)
    server = JanusGraphServer(manager=manager).start()
    try:
        url = f"http://127.0.0.1:{server.port}/healthz"
        try:
            with urllib.request.urlopen(url, timeout=10) as resp:
                code, body = resp.status, resp.read()
        except urllib.error.HTTPError as e:  # 503 when degraded
            code, body = e.code, e.read()
        payload = _json.loads(body)
        assert payload["status"] in ("ok", "degraded")
        assert code == (200 if payload["status"] == "ok" else 503)
        assert "breakers" in payload and "counters" in payload
        # flight-recorder summary rides every healthz payload (ISSUE 4)
        assert "flight" in payload
        assert payload["flight"]["capacity"] > 0
        assert "counts" in payload["flight"]
        # the sibling /metrics scrape must be VALID exposition, not just
        # present (ISSUE 4 satellite: malformed exposition fails fast)
        from janusgraph_tpu.observability.exposition import (
            validate_prometheus_text,
        )

        murl = f"http://127.0.0.1:{server.port}/metrics"
        with urllib.request.urlopen(murl, timeout=10) as resp:
            text = resp.read().decode()
        assert validate_prometheus_text(text) is None, text
    finally:
        server.stop()
        g.close()


# --------------------------------------------------------------------------
# FaultPlan determinism


def test_fault_plan_same_seed_same_decisions():
    def drive(plan):
        hits = []
        for i in range(400):
            try:
                plan.before_read("edgestore")
            except TemporaryBackendError:
                hits.append(i)
        return hits

    a = drive(FaultPlan(seed=7, read_error_rate=0.05))
    b = drive(FaultPlan(seed=7, read_error_rate=0.05))
    c = drive(FaultPlan(seed=8, read_error_rate=0.05))
    assert a == b
    assert a, "a 5% rate over 400 ops should fire at least once"
    assert a != c, "different seeds should schedule different faults"


def test_fault_plan_journal_is_deterministic():
    def drive(plan):
        for _ in range(100):
            try:
                plan.before_read("edgestore")
            except TemporaryBackendError:
                pass
            try:
                plan.before_write("edgestore")
            except TemporaryBackendError:
                pass
        return plan.journal

    assert drive(FaultPlan(seed=3, read_error_rate=0.04,
                           write_error_rate=0.04)) == \
        drive(FaultPlan(seed=3, read_error_rate=0.04, write_error_rate=0.04))


# --------------------------------------------------------------------------
# circuit breaker state machine


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _failing():
    raise TemporaryBackendError("backend down")


def test_breaker_closed_to_open_to_half_open_to_closed():
    clock = _Clock()
    br = CircuitBreaker("unit", failure_threshold=3, reset_timeout_s=5.0,
                        clock=clock)
    assert br.state == CLOSED
    for _ in range(3):
        with pytest.raises(TemporaryBackendError):
            br.call(_failing)
    assert br.state == OPEN
    # fail-fast while open: the protected fn is NOT invoked
    calls = []
    with pytest.raises(CircuitOpenError):
        br.call(lambda: calls.append(1))
    assert calls == []
    # reset window elapses -> half-open probe admitted
    clock.t = 6.0
    assert br.state == HALF_OPEN
    assert br.call(lambda: "pong") == "pong"
    assert br.state == CLOSED


def test_breaker_half_open_failure_reopens():
    clock = _Clock()
    br = CircuitBreaker("unit2", failure_threshold=1, reset_timeout_s=5.0,
                        clock=clock)
    with pytest.raises(TemporaryBackendError):
        br.call(_failing)
    assert br.state == OPEN
    clock.t = 5.1
    with pytest.raises(TemporaryBackendError):
        br.call(_failing)  # the probe fails
    assert br.state == OPEN
    with pytest.raises(CircuitOpenError):
        br.call(lambda: "nope")


def test_breaker_permanent_errors_do_not_trip():
    from janusgraph_tpu.exceptions import PermanentBackendError

    br = CircuitBreaker("unit3", failure_threshold=2)

    def perm():
        raise PermanentBackendError("app error")

    for _ in range(5):
        with pytest.raises(PermanentBackendError):
            br.call(perm)
    assert br.state == CLOSED


def test_breaker_consecutive_counting_resets_on_success():
    br = CircuitBreaker("unit4", failure_threshold=3)
    for _ in range(2):
        with pytest.raises(TemporaryBackendError):
            br.call(_failing)
    br.call(lambda: "ok")  # breaks the streak
    for _ in range(2):
        with pytest.raises(TemporaryBackendError):
            br.call(_failing)
    assert br.state == CLOSED


def test_remote_store_breaker_fails_fast_and_recovers():
    """Wiring test: the remote KCVS client trips its breaker against a dead
    endpoint, fails fast (no dial), and recovers when the server is back."""
    from janusgraph_tpu.storage.kcvs import KeySliceQuery, SliceQuery
    from janusgraph_tpu.storage.remote import (
        RemoteStoreManager,
        RemoteStoreServer,
    )

    server = RemoteStoreServer(InMemoryStoreManager()).start()
    host, port = server.address
    server.stop()  # endpoint now dead, port known-free-ish

    mgr = RemoteStoreManager(
        host, port, pool_size=1, retry_time_s=0.5, max_attempts=1,
        connect_timeout_s=0.5, breaker_enabled=True,
        breaker_failure_threshold=3, breaker_reset_ms=200.0,
    )
    store = mgr.open_database("edgestore")
    q = KeySliceQuery(b"k", SliceQuery())
    for _ in range(3):
        with pytest.raises(TemporaryBackendError):
            store.get_slice(q, None)
    t0 = time.monotonic()
    with pytest.raises(CircuitOpenError):
        store.get_slice(q, None)
    assert time.monotonic() - t0 < 0.3, "open breaker must not dial"
    # server comes back; after the reset window a probe closes the breaker
    server2 = RemoteStoreServer(InMemoryStoreManager(), host=host, port=port)
    server2.start()
    try:
        deadline = time.monotonic() + 5.0
        while True:
            time.sleep(0.25)
            try:
                assert store.get_slice(q, None) == []
                break
            except (TemporaryBackendError, CircuitOpenError):
                if time.monotonic() > deadline:
                    raise
        assert mgr.breaker.state == CLOSED
    finally:
        server2.stop()
        mgr.close()


# --------------------------------------------------------------------------
# checkpoint durability


def test_checkpoint_roundtrip_and_prev_fallback(tmp_path):
    from janusgraph_tpu.olap.checkpoint import load_checkpoint, save_checkpoint

    path = str(tmp_path / "ck.npz")
    s1 = {"rank": np.arange(8, dtype=np.float64)}
    s2 = {"rank": np.arange(8, dtype=np.float64) * 2}
    save_checkpoint(path, s1, {"delta": np.asarray(0.5)}, 2)
    save_checkpoint(path, s2, {"delta": np.asarray(0.25)}, 4)
    assert os.path.exists(path + ".prev")

    state, mem, steps = load_checkpoint(path)
    assert steps == 4 and np.array_equal(state["rank"], s2["rank"])

    # truncate the newest file -> fall back to .prev (the older checkpoint)
    with open(path, "r+b") as f:
        f.truncate(16)
    state, mem, steps = load_checkpoint(path)
    assert steps == 2 and np.array_equal(state["rank"], s1["rank"])
    assert float(mem["delta"]) == 0.5


def test_checkpoint_detects_corruption_via_checksum(tmp_path):
    from janusgraph_tpu.olap.checkpoint import load_checkpoint, save_checkpoint

    path = str(tmp_path / "ck.npz")
    save_checkpoint(path, {"x": np.ones(4)}, {}, 1)
    save_checkpoint(path, {"x": np.ones(4) * 3}, {}, 3)
    # flip payload bytes in the MIDDLE of the newest file: still a readable
    # zip, but the content digest no longer matches
    data = bytearray(open(path, "rb").read())
    mid = len(data) // 2
    data[mid:mid + 4] = bytes(b ^ 0xFF for b in data[mid:mid + 4])
    with open(path, "wb") as f:
        f.write(bytes(data))
    loaded = load_checkpoint(path)
    if loaded is not None:  # fell back to .prev
        state, _mem, steps = loaded
        assert steps == 1 and np.array_equal(state["x"], np.ones(4))


def test_checkpoint_both_missing_returns_none(tmp_path):
    from janusgraph_tpu.olap.checkpoint import load_checkpoint

    assert load_checkpoint(str(tmp_path / "absent.npz")) is None


# --------------------------------------------------------------------------
# scanner retry + resume


class _CollectJob:
    def __init__(self):
        self.keys = []

    def get_queries(self):
        from janusgraph_tpu.storage.kcvs import SliceQuery

        return [SliceQuery()]

    def setup(self, metrics):
        pass

    def process(self, rows, metrics):
        self.keys.extend(k for k, _slices in rows)

    def teardown(self, metrics):
        pass


def test_scanner_resumes_after_injected_kill():
    from janusgraph_tpu.storage.scan import StandardScanner

    plan = FaultPlan(seed=1, scan_kill_at=0, scan_kill_after_rows=5)
    mgr = FaultInjectingStoreManager(InMemoryStoreManager(), plan)
    raw = mgr.wrapped.open_database("edgestore")
    tx = mgr.begin_transaction()
    keys = [bytes([0, i]) for i in range(32)]
    for k in keys:
        raw.mutate(k, [(b"c", b"v")], [], tx)

    store = mgr.open_database("edgestore")
    job = _CollectJob()
    scanner = StandardScanner(store, tx, retries=3)
    metrics = scanner.execute(
        job, key_ranges=[(bytes([0]), bytes([1]))], batch_size=4
    )
    assert sorted(job.keys) == keys, "every row exactly once despite the kill"
    assert metrics.rows_processed == len(keys)
    assert metrics.custom.get("scan.retries", 0) >= 1
    assert any(e["kind"] == "scan" for e in plan.journal)


def test_scanner_exhausts_retries_and_raises():
    from janusgraph_tpu.storage.scan import StandardScanner

    # kill scans 0,1: with retries=1 the second kill surfaces
    class _Plan(FaultPlan):
        def scan_decision(self):
            n = self._tick("scan")
            return n, n <= 1

    plan = _Plan(seed=1, scan_kill_after_rows=0)
    mgr = FaultInjectingStoreManager(InMemoryStoreManager(), plan)
    tx = mgr.begin_transaction()
    raw = mgr.wrapped.open_database("edgestore")
    for i in range(8):
        raw.mutate(bytes([0, i]), [(b"c", b"v")], [], tx)
    scanner = StandardScanner(mgr.open_database("edgestore"), tx, retries=1)
    with pytest.raises(TemporaryBackendError):
        scanner.execute(_CollectJob(), key_ranges=[(bytes([0]), bytes([1]))])


# --------------------------------------------------------------------------
# OLAP preemption -> checkpoint auto-resume, bitwise-identical


def _tiny_graph(n=16):
    # deliberately IRREGULAR degrees: a regular graph's uniform rank is
    # already PageRank's fixed point and the run would terminate before
    # the scheduled preemption
    g = JanusGraphTPU(
        {"ids.authority-wait-ms": 0.0}, store_manager=InMemoryStoreManager()
    )
    tx = g.new_transaction()
    vs = [tx.add_vertex() for _ in range(n)]
    for i in range(n):
        tx.add_edge(vs[i], "knows", vs[(i + 1) % n])
        if i % 3 == 0:
            tx.add_edge(vs[i], "knows", vs[0])
        if i % 4 == 1:
            tx.add_edge(vs[i], "knows", vs[(i * i + 2) % n])
    tx.commit()
    return g


def test_preempted_pagerank_resumes_bitwise_identical_cpu(tmp_path):
    from janusgraph_tpu.olap.computer import run_on
    from janusgraph_tpu.olap.csr import load_csr
    from janusgraph_tpu.olap.programs.pagerank import PageRankProgram

    g = _tiny_graph()
    csr = load_csr(g)
    baseline = run_on(csr, PageRankProgram(max_iterations=12), "cpu")

    plan = FaultPlan(seed=SEED, preempt_superstep=5)
    faulted = run_on(
        csr, PageRankProgram(max_iterations=12), "cpu",
        checkpoint_path=str(tmp_path / "pr.npz"), checkpoint_every=2,
        fault_hook=plan.olap_hook,
    )
    assert any(e["kind"] == "superstep" for e in plan.journal)
    for key in baseline:
        assert baseline[key].dtype == faulted[key].dtype
        assert np.array_equal(baseline[key], faulted[key]), key
    g.close()


def test_preempted_pagerank_resumes_bitwise_identical_tpu(tmp_path):
    """Same contract on the jitted executor (fused path, CPU device)."""
    from janusgraph_tpu.olap.computer import run_on
    from janusgraph_tpu.olap.csr import load_csr
    from janusgraph_tpu.olap.programs.pagerank import PageRankProgram

    g = _tiny_graph()
    csr = load_csr(g)
    baseline = run_on(csr, PageRankProgram(max_iterations=10), "tpu")

    plan = FaultPlan(seed=SEED, preempt_superstep=4)
    faulted = run_on(
        csr, PageRankProgram(max_iterations=10), "tpu",
        checkpoint_path=str(tmp_path / "pr.npz"), checkpoint_every=2,
        fault_hook=plan.olap_hook,
    )
    assert any(e["kind"] == "superstep" for e in plan.journal)
    for key in baseline:
        assert np.array_equal(baseline[key], faulted[key]), key
    g.close()


def test_preemption_without_checkpointing_propagates():
    from janusgraph_tpu.olap.computer import run_on
    from janusgraph_tpu.olap.csr import load_csr
    from janusgraph_tpu.olap.programs.pagerank import PageRankProgram

    g = _tiny_graph(8)
    csr = load_csr(g)
    plan = FaultPlan(seed=SEED, preempt_superstep=2)
    with pytest.raises(SuperstepPreempted):
        run_on(
            csr, PageRankProgram(max_iterations=8), "cpu",
            fault_hook=plan.olap_hook,
        )
    g.close()


# --------------------------------------------------------------------------
# THE chaos soak: 200-tx OLTP + PageRank under a seeded plan, with torn
# commit recovery on reopen and seed-exact reproducibility


N_TXS = 200
_SOAK_CFG = {
    "ids.authority-wait-ms": 0.0,
    "locks.wait-ms": 0.0,
    "tx.log-tx": True,
    "tx.max-commit-time-ms": 0.0,
    "cache.db-cache-time-ms": 600_000.0,  # no TTL churn mid-soak
    "storage.scan-parallelism": 1,  # sequential scans: deterministic ticks
    "storage.backoff-base-ms": 1.0,
    "storage.backoff-max-ms": 4.0,
    "computer.executor": "cpu",
    "computer.checkpoint-every": 2,
}
_FAULT_CFG = {
    "storage.faults.enabled": True,
    "storage.faults.seed": SEED,
    "storage.faults.read-error-rate": 0.01,
    "storage.faults.write-error-rate": 0.01,
    "storage.faults.torn-mutation-at": 150,
    "storage.faults.lock-expiry-at": 60,
    "storage.faults.scan-kill-at": 40,
    "storage.faults.scan-kill-after-rows": 1,
    "storage.faults.preempt-superstep": 3,
}


def _retrying(fn, retries=12):
    """Workload-level tx retry: temporary faults surfacing above the
    backend_op guard (lock-lease expiry kills the whole tx) re-run the
    closure. InjectedCrashError is permanent and propagates."""
    for attempt in range(retries):
        try:
            return fn()
        except TemporaryBackendError:
            if attempt == retries - 1:
                raise
    return None  # pragma: no cover


def _write_tx(graph, i):
    def body():
        tx = graph.new_transaction()
        try:
            v = tx.add_vertex(uid=i, name=f"v{i}")
            if i > 0:
                prev = graph.index_lookup(tx, "byUid", (i - 1,))
                if prev:
                    pv = tx.get_vertex(prev[0])
                    if pv is not None:
                        tx.add_edge(v, "next", pv)
            tx.commit()
        except BaseException:
            if tx.is_open:
                tx.rollback()
            raise

    _retrying(body)


def _run_soak_until_crash(mgr, tmp_path, tag):
    """Phases A+B on a fresh graph over `mgr`: schema, 120 txs, a chaos
    PageRank (scan kill + preemption + auto-resume), then more txs until
    the scheduled torn batch crashes the commit. Returns (plan, crashed_i,
    pagerank_states)."""
    cfg = {
        **_SOAK_CFG, **_FAULT_CFG,
        "computer.checkpoint-path": str(tmp_path / f"soak-{tag}.npz"),
    }
    graph = JanusGraphTPU(cfg, store_manager=mgr)
    plan = graph.fault_plan
    assert plan is not None and plan.seed == SEED

    mgmt = graph.management()
    mgmt.make_property_key("uid", int)
    mgmt.make_property_key("name", str)
    mgmt.build_composite_index("byUid", ["uid"], unique=True)

    for i in range(120):
        _write_tx(graph, i)

    # chaos PageRank through the graph facade: the CSR load absorbs the
    # injected scan kill, the run absorbs the superstep preemption via
    # checkpoint auto-resume
    from janusgraph_tpu.olap.programs.pagerank import PageRankProgram

    result = graph.compute().program(PageRankProgram(max_iterations=8)).submit()
    assert result.states["rank"].shape[0] == 120

    # acceptance: the preempted-and-resumed chaos run's final OLAP state is
    # bitwise-identical to a fault-free run over the same snapshot
    from janusgraph_tpu.olap.computer import run_on
    from janusgraph_tpu.olap.csr import load_csr

    clean = run_on(load_csr(graph), PageRankProgram(max_iterations=8), "cpu")
    for key in clean:
        assert clean[key].dtype == result.states[key].dtype
        assert np.array_equal(clean[key], result.states[key]), key

    crashed_i = None
    try:
        for i in range(120, N_TXS):
            _write_tx(graph, i)
    except InjectedCrashError:
        crashed_i = i
    assert crashed_i is not None, "the scheduled torn batch never fired"
    assert any(e["kind"] == "torn" for e in plan.journal)
    assert any(e["kind"] == "lock" for e in plan.journal)
    assert any(e["kind"] == "superstep" for e in plan.journal)
    # graph is abandoned un-closed: that IS the crash
    return graph, plan, crashed_i, result.states


def test_chaos_soak_end_to_end(tmp_path):
    mgr = InMemoryStoreManager()
    _g1, plan, crashed_i, chaos_states = _run_soak_until_crash(
        mgr, tmp_path, "a"
    )

    # ---- reopen (faults off): torn-commit recovery repairs the txlog
    graph2 = JanusGraphTPU(dict(_SOAK_CFG), store_manager=mgr)
    rec = graph2.last_torn_recovery
    assert rec is not None and len(rec["replayed"]) == 1, rec

    # the torn transaction's data is all there: vertex, properties, edge
    tx = graph2.new_transaction(read_only=True)
    ids = graph2.index_lookup(tx, "byUid", (crashed_i,))
    assert len(ids) == 1
    v = tx.get_vertex(ids[0])
    assert v is not None
    assert tx.get_properties(v, "name")[0].value == f"v{crashed_i}"
    assert tx.get_edges(v, Direction.OUT, ("next",)), (
        "the torn tx's edge must be replayed"
    )
    tx.rollback()

    # recovery is idempotent: a second pass heals nothing new
    from janusgraph_tpu.core.txlog import TornCommitRecovery

    again = TornCommitRecovery(graph2).run()
    assert again == {"replayed": [], "rolled_back": []}

    # ---- the rest of the 200-tx workload completes fault-free
    for i in range(crashed_i + 1, N_TXS):
        _write_tx(graph2, i)
    tx = graph2.new_transaction(read_only=True)
    for i in range(N_TXS):
        assert graph2.index_lookup(tx, "byUid", (i,)), f"uid {i} missing"
    tx.rollback()

    # ---- fault-free PageRank over the SAME 120-vertex snapshot shape:
    # the chaos run's final state must be bitwise-identical to a clean run
    from janusgraph_tpu.olap.computer import run_on
    from janusgraph_tpu.olap.csr import load_csr
    from janusgraph_tpu.olap.programs.pagerank import PageRankProgram

    csr = load_csr(graph2)
    clean = run_on(csr, PageRankProgram(max_iterations=8), "cpu")
    # chaos run covered 120 vertices; clean covers 200 — compare by vertex
    by_vid = dict(zip(csr.vertex_ids.tolist(), clean["rank"].tolist()))
    assert chaos_states["rank"].dtype == clean["rank"].dtype
    assert len(by_vid) == N_TXS
    graph2.close()


def test_chaos_soak_same_seed_reproduces_fault_sequence(tmp_path):
    """Two fresh soaks with one seed produce the exact same fault journal
    (kinds, op indexes, stores, details) and crash on the same tx."""
    _g_a, plan_a, crash_a, _ = _run_soak_until_crash(
        InMemoryStoreManager(), tmp_path, "b1"
    )
    _g_b, plan_b, crash_b, _ = _run_soak_until_crash(
        InMemoryStoreManager(), tmp_path, "b2"
    )
    assert crash_a == crash_b
    assert plan_a.journal == plan_b.journal
    assert plan_a.journal, "the soak must actually inject faults"


# --------------------------------------------------------------------------
# lock-lease expiry through the graph commit path (chaos-wired)


def test_injected_lock_expiry_is_retried_by_workload(tmp_path):
    """The lock fault kills exactly one commit with TemporaryLockingError;
    the workload retry re-acquires and succeeds (re-acquirability)."""
    from janusgraph_tpu.exceptions import TemporaryLockingError

    cfg = {
        **_SOAK_CFG,
        "storage.faults.enabled": True,
        "storage.faults.seed": SEED,
        "storage.faults.lock-expiry-at": 2,
        "tx.log-tx": False,
    }
    graph = JanusGraphTPU(cfg, store_manager=InMemoryStoreManager())
    mgmt = graph.management()
    mgmt.make_property_key("uid", int)
    mgmt.build_composite_index("byU", ["uid"], unique=True)

    expired = []

    def write(i):
        tx = graph.new_transaction()
        tx.add_vertex(uid=i)
        try:
            tx.commit()
        except TemporaryLockingError as e:
            expired.append((i, str(e)))
            tx2 = graph.new_transaction()
            tx2.add_vertex(uid=i)
            tx2.commit()  # re-acquirable immediately

    for i in range(6):
        write(i)
    assert len(expired) == 1 and "lease expired" in expired[0][1]
    tx = graph.new_transaction(read_only=True)
    for i in range(6):
        assert graph.index_lookup(tx, "byU", (i,))
    tx.rollback()
    graph.close()
