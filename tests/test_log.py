"""Durable log bus + WAL + CDC + recovery + schema broadcast tests
(reference test model: LogTest.java:385 — multiple log managers in one
process against one backend; StandardTransactionLogProcessor recovery
semantics)."""

import time

import pytest

from janusgraph_tpu.core.graph import JanusGraphTPU, open_graph
from janusgraph_tpu.core.txlog import (
    ChangeRecord,
    LogTxStatus,
    decode_changes,
    decode_tx_entry,
    encode_changes,
    encode_tx_entry,
    TxLogEntry,
)
from janusgraph_tpu.storage.inmemory import InMemoryStoreManager
from janusgraph_tpu.storage.log import KCVSLog, LogManager, ReadMarker


def make_log(mgr=None, name="testlog", **kw):
    mgr = mgr or InMemoryStoreManager()
    return (
        KCVSLog(
            name,
            mgr.open_database(name),
            mgr.begin_transaction,
            b"sender01",
            read_interval_ms=5.0,
            **kw,
        ),
        mgr,
    )


class TestKCVSLog:
    def test_write_read_roundtrip(self):
        log, _ = make_log()
        t0 = time.time_ns()
        for i in range(10):
            log.add(b"msg%d" % i)
        log.flush()
        msgs = log.read_range(t0 - 1)
        assert sorted(m.content for m in msgs) == [b"msg%d" % i for i in range(10)]
        # time-ordered
        assert [m.timestamp_ns for m in msgs] == sorted(
            m.timestamp_ns for m in msgs
        )
        log.close()

    def test_messages_spread_over_buckets(self):
        from janusgraph_tpu.storage.kcvs import KeyRangeQuery, SliceQuery

        log, mgr = make_log(num_buckets=4)
        for i in range(40):
            log.add(b"m%d" % i)
        log.flush()
        store = mgr.open_database("testlog")
        stx = mgr.begin_transaction()
        buckets = {
            key[0]
            for key, _ in store.get_keys(
                KeyRangeQuery(b"\x00", b"\xff", SliceQuery()), stx
            )
        }
        assert buckets == {0, 1, 2, 3}  # round-robin hit every bucket
        assert len(log.read_range(0)) == 40
        log.close()

    def test_registered_reader_receives(self):
        log, _ = make_log()
        got = []
        log.register_reader(ReadMarker.from_epoch(), lambda m: got.append(m.content))
        log.add(b"hello")
        log.add(b"world")
        log.flush()
        deadline = time.monotonic() + 2.0
        while len(got) < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert sorted(got) == [b"hello", b"world"]
        log.close()

    def test_reader_from_now_skips_history(self):
        log, _ = make_log()
        log.add_now(b"old")
        time.sleep(0.01)
        got = []
        log.register_reader(ReadMarker.from_now(), lambda m: got.append(m.content))
        time.sleep(0.05)
        log.add_now(b"new")
        deadline = time.monotonic() + 2.0
        while not got and time.monotonic() < deadline:
            time.sleep(0.01)
        assert got == [b"new"]
        log.close()

    def test_two_managers_one_store(self):
        """Second log manager over the same backing store sees messages —
        the log IS the cross-instance bus."""
        mgr = InMemoryStoreManager()
        a, _ = make_log(mgr, "shared")
        b = KCVSLog(
            "shared",
            mgr.open_database("shared"),
            mgr.begin_transaction,
            b"sender02",
            read_interval_ms=5.0,
        )
        t0 = time.time_ns()
        a.add_now(b"from-a")
        msgs = b.read_range(t0 - 1)
        assert [m.content for m in msgs] == [b"from-a"]
        assert msgs[0].sender == b"sender01"
        a.close()
        b.close()


class TestTxEntryCodec:
    def test_changes_roundtrip(self):
        changes = [
            ChangeRecord("edge", True, 11, 22, 33, 44),
            ChangeRecord("property", False, 55, 0, 66, 77, b"\x00\x04abcd"),
        ]
        assert decode_changes(encode_changes(changes)) == changes

    def test_entry_roundtrip(self):
        e = TxLogEntry(
            123,
            LogTxStatus.PRECOMMIT,
            [ChangeRecord("edge", True, 1, 2, 3, 4)],
            "mylog",
        )
        d = decode_tx_entry(encode_tx_entry(e))
        assert (d.tx_id, d.status, d.changes, d.user_log) == (
            123, LogTxStatus.PRECOMMIT, e.changes, "mylog",
        )
        # status-only entries carry no payload
        s = decode_tx_entry(
            encode_tx_entry(TxLogEntry(9, LogTxStatus.PRIMARY_SUCCESS))
        )
        assert (s.tx_id, s.status, s.changes) == (9, LogTxStatus.PRIMARY_SUCCESS, [])


class TestWAL:
    def test_commit_writes_wal_markers(self):
        g = open_graph({"ids.authority-wait-ms": 0.0})
        g.management().set_config("tx.log-tx", True)
        t0 = time.time_ns()
        tx = g.new_transaction()
        a = tx.add_vertex()
        b = tx.add_vertex()
        tx.add_property(a, "name", "zeus")
        tx.add_edge(a, "knows", b)
        tx.commit()
        entries = [
            decode_tx_entry(m.content, m.timestamp_ns)
            for m in g.log_manager.open_log("txlog").read_range(t0 - 1)
        ]
        statuses = [e.status for e in entries]
        assert statuses == [
            LogTxStatus.PRECOMMIT,
            # flush point: past here a crash can tear the batch, and
            # TornCommitRecovery rolls the tx forward on reopen
            LogTxStatus.PREFLUSH,
            LogTxStatus.PRIMARY_SUCCESS,
            LogTxStatus.SECONDARY_SUCCESS,
        ]
        pre = entries[0]
        kinds = sorted(c.kind for c in pre.changes)
        assert kinds == ["edge", "property"]
        assert all(c.added for c in pre.changes)
        g.close()

    def test_wal_disabled_by_default(self):
        g = open_graph({"ids.authority-wait-ms": 0.0})
        tx = g.new_transaction()
        tx.add_vertex()
        tx.commit()
        assert g.log_manager.open_log("txlog").read_range(0) == []
        g.close()


class TestCDC:
    def test_change_processor_sees_commits(self):
        g = open_graph({"ids.authority-wait-ms": 0.0})
        states = []
        g.open_log_processor("audit").add_processor(states.append).build(
            ReadMarker.from_epoch()
        )
        tx = g.new_transaction(log_identifier="audit")
        v = tx.add_vertex()
        tx.add_property(v, "name", "hera")
        tx.commit()
        deadline = time.monotonic() + 2.0
        while not states and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(states) == 1
        st = states[0]
        assert len(st.added) == 1 and not st.deleted
        assert st.added[0].kind == "property"
        # the payload is self-contained: decode the value
        val, _ = g.serializer.read_object(st.added[0].value_enc)
        assert val == "hera"
        g.close()

    def test_deletions_captured(self):
        g = open_graph({"ids.authority-wait-ms": 0.0})
        tx = g.new_transaction()
        v = tx.add_vertex()
        p = tx.add_property(v, "name", "ares")
        tx.commit()
        states = []
        g.open_log_processor("audit2").add_processor(states.append).build(
            ReadMarker.from_epoch()
        )
        tx = g.new_transaction(log_identifier="audit2")
        v2 = tx.get_vertex(v.id)
        tx.remove_property(tx.get_properties(v2, "name")[0])
        tx.commit()
        deadline = time.monotonic() + 2.0
        while not states and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(states) == 1
        assert states[0].deleted and not states[0].added
        g.close()


class TestRecovery:
    def test_heals_missing_secondary(self):
        g = open_graph({"ids.authority-wait-ms": 0.0})
        # commit a tx whose secondary (user-log) write is injected to fail
        tx = g.new_transaction(log_identifier="feed")
        v = tx.add_vertex()
        tx.add_property(v, "name", "apollo")
        tx._fail_secondary_for_test = True
        tx.commit()
        ulog = g.log_manager.open_log("ulog_feed")
        assert ulog.read_range(0) == []  # delivery failed
        statuses = [
            decode_tx_entry(m.content).status
            for m in g.log_manager.open_log("txlog").read_range(0)
        ]
        assert LogTxStatus.SECONDARY_FAILURE in statuses
        # recovery replays it (max-commit-time 0: everything is overdue)
        healed = g.start_transaction_recovery().run(max_commit_time_ms=0.0)
        assert len(healed) == 1
        msgs = ulog.read_range(0)
        assert len(msgs) == 1
        entry = decode_tx_entry(msgs[0].content)
        assert entry.changes and entry.changes[0].kind == "property"
        # txlog now shows the healed marker
        statuses = [
            decode_tx_entry(m.content).status
            for m in g.log_manager.open_log("txlog").read_range(0)
        ]
        assert LogTxStatus.SECONDARY_SUCCESS in statuses
        # idempotent: second run heals nothing
        assert g.start_transaction_recovery().run(max_commit_time_ms=0.0) == []
        g.close()

    def test_in_flight_tx_not_healed(self):
        g = open_graph({"ids.authority-wait-ms": 0.0})
        tx = g.new_transaction(log_identifier="feed")
        tx.add_vertex()
        tx._fail_secondary_for_test = True
        tx.commit()
        # generous max-commit-time: the tx is still considered in flight
        healed = g.start_transaction_recovery().run(max_commit_time_ms=60_000.0)
        assert healed == []
        g.close()


class TestSchemaBroadcast:
    def test_eviction_reaches_other_instance(self):
        mgr = InMemoryStoreManager()
        g1 = JanusGraphTPU({"ids.authority-wait-ms": 0.0}, store_manager=mgr)
        g2 = JanusGraphTPU({"ids.authority-wait-ms": 0.0}, store_manager=mgr)
        pk = g1.management().make_property_key("name", str)
        idx = g1.management().build_composite_index("byName", ["name"])
        # g2 opened first: knows nothing of the new index
        assert "byName" not in g2.indexes
        ok = g1.management().broadcast_eviction(idx.id)
        assert ok  # both instances acked
        deadline = time.monotonic() + 2.0
        while "byName" not in g2.indexes and time.monotonic() < deadline:
            time.sleep(0.01)
        assert "byName" in g2.indexes
        g1.close()
        g2.close()

    def test_consistency_change_reaches_other_instance(self):
        """set_consistency's eviction broadcast refreshes the OTHER
        instance's schema cache, so its next commit honors the LOCK
        modifier (the cluster-agreement half of ConsistencyModifier)."""
        from janusgraph_tpu.core.codecs import Consistency

        mgr = InMemoryStoreManager()
        g1 = JanusGraphTPU({"ids.authority-wait-ms": 0.0}, store_manager=mgr)
        g2 = JanusGraphTPU({"ids.authority-wait-ms": 0.0}, store_manager=mgr)
        g1.management().make_property_key("serial", int)
        g1.management().broadcast_eviction(
            g1.schema_cache.get_by_name("serial").id
        )
        deadline = time.monotonic() + 2.0
        while (
            g2.schema_cache.get_by_name("serial") is None
            and time.monotonic() < deadline
        ):
            time.sleep(0.01)
        g1.management().set_consistency("serial", Consistency.LOCK)
        deadline = time.monotonic() + 2.0
        while (
            getattr(
                g2.schema_cache.get_by_name("serial"), "consistency", None
            ) is not Consistency.LOCK
            and time.monotonic() < deadline
        ):
            time.sleep(0.01)
        assert g2.management().get_consistency("serial") is Consistency.LOCK
        g1.close()
        g2.close()


def test_log_timestamp_provider_resolution():
    """graph.timestamps governs the resolution of log message stamps
    (reference: TimestampProviders + KCVSLog timestamping)."""
    import time

    from janusgraph_tpu.core.graph import open_graph

    g = open_graph({
        "storage.backend": "inmemory", "graph.timestamps": "milli",
    })
    log = g.log_manager.open_log("testlog")
    log.add(b"hello")
    log.flush()
    msgs = log.read_range(0)
    assert msgs and all(m.timestamp_ns % 1_000_000 == 0 for m in msgs)
    g.close()
