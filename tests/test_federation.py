"""Fleet observability federation (ISSUE 17): merged telemetry,
offset-corrected forensics, fleet SLOs.

Covers the acceptance list:

- partial scrapes: a dead/unreachable replica yields ``partial: true``
  and the EXACT missing-replica list, never a silently complete window,
- merge semantics: counters sum, gauges stay keyed per replica,
  histogram bucket vectors add — and the merged percentiles are BITWISE
  equal to recomputing from the concatenated per-replica vectors,
- clock-offset estimation + correction: a synthetic two-replica event
  sequence with injected ±500 ms wall skew comes back in true causal
  order,
- the fleet incident report: kill -> mark_dead phases extracted across
  rings, Chrome-trace document validates, in-process ring sharing dedups,
- fleet-level SLOs on the merged windows: deterministic on a fake
  clock, with the cross-replica p99 outlier detector raising a
  ``replica_outlier`` flight event and burning the ticket rung.
"""

from __future__ import annotations

import json
import threading
import time

import pytest

from janusgraph_tpu.observability import flight_recorder
from janusgraph_tpu.observability.federation import (
    ClockOffsets,
    FleetFederation,
    fleet_default_specs,
    merge_incident_events,
    merge_series,
)
from janusgraph_tpu.observability.metrics_core import (
    Histogram,
    TelemetryRegistry,
)
from janusgraph_tpu.observability.timeline import validate_chrome_trace
from janusgraph_tpu.observability.timeseries import MetricsHistory
from janusgraph_tpu.server.fleet import FleetRouter


class _Replica:
    """One synthetic replica: its own registry + history ring, a wall
    clock that can be skewed, and canned flight events."""

    def __init__(self, name, skew_s=0.0):
        self.name = name
        self.skew_s = skew_s
        self.true_wall = 1_000_000.0
        self.mono = 50.0
        self.registry = TelemetryRegistry()
        self.history = MetricsHistory(
            registry=self.registry, interval_s=1.0,
            clock=lambda: self.mono,
            wall_clock=lambda: self.true_wall + self.skew_s,
        )
        self.flight_events = []
        self.unreachable = False
        #: streaming capability bit served at /watch/info (PR 20)
        self.watch = False
        #: canned /debug/bundle body (the bundle dict itself)
        self.bundle = None
        #: fail ONLY the full-backlog re-fetch (the heal path)
        self.fail_full = False

    def observe(self, name, ms_values):
        t = self.registry.timer(name)
        for ms in ms_values:
            t.update(int(ms * 1e6))

    def advance(self, s=1.0):
        self.true_wall += s
        self.mono += s

    def payload(self, path):
        if self.unreachable:
            raise ConnectionError(f"{self.name} unreachable")
        if path.startswith("/timeseries"):
            if self.fail_full and "window=" not in path:
                raise ConnectionError(f"{self.name} full scrape torn")
            last = 0
            if "window=" in path:
                last = int(path.split("window=")[1].split("&")[0])
            payload = json.loads(json.dumps(self.history.scrape(last=last)))
            # a real replica process reports ITS OWN identity; the
            # shared-process default would collapse all synthetic
            # replicas onto one producer cursor
            payload["replica"] = self.name
            return payload
        if path.startswith("/flight"):
            return {"events": [dict(e) for e in self.flight_events]}
        if path.startswith("/telemetry"):
            return {"metrics": self.registry.snapshot()}
        if path.startswith("/watch/info"):
            return {
                "watch": self.watch,
                "replica": self.name,
                "now": self.true_wall + self.skew_s,
                "streams": ["flight", "window", "slo", "flame", "bundle"],
                "cursors": {},
            }
        if path.startswith("/debug/bundle"):
            return self.bundle
        raise AssertionError(f"unexpected path {path}")


def _fleet(replicas, dead=(), **fed_kw):
    """An offline router + federation over synthetic replicas, on fake
    clocks (zero-RTT scrapes: the offset estimate is exactly the skew)."""
    by_port = {}
    router = FleetRouter(fetch=lambda url, timeout: {})
    for i, rep in enumerate(replicas):
        port = 9300 + i
        router.add_replica(rep.name, "127.0.0.1", port)
        by_port[port] = rep
    for name in dead:
        router.mark_dead(name, reason="test")

    calls = []

    def fetch(url, timeout):
        calls.append(url)
        rest = url.split("127.0.0.1:", 1)[1]
        port, path = rest.split("/", 1)
        return by_port[int(port)].payload("/" + path)

    clock = {"t": 10.0}
    wall = {"t": 2_000_000.0}
    fed_kw.setdefault("interval_s", 1.0)
    fed = FleetFederation(
        router, fetch=fetch,
        clock=lambda: clock["t"], wall_clock=lambda: wall["t"],
        **fed_kw,
    )
    fed._test_calls = calls
    fed._test_clock = clock
    fed._test_wall = wall
    return router, fed


# ---------------------------------------------------------------------------
# partial scrapes
# ---------------------------------------------------------------------------

class TestPartialScrapes:
    def test_unreachable_replica_marks_window_partial(self):
        reps = [_Replica("r0"), _Replica("r1"), _Replica("r2")]
        for rep in reps:
            rep.observe("server.request.wall", [2.0, 3.0])
            rep.history.sample()
        reps[1].unreachable = True
        _router, fed = _fleet(reps)
        w = fed.tick()
        assert w["partial"] is True
        assert w["missing"] == ["r1"]
        assert w["replicas"] == ["r0", "r2"]
        view = fed.timeseries_view()
        assert view["partial"] is True
        assert view["missing"] == ["r1"]

    def test_dead_replica_is_missing_without_a_fetch(self):
        reps = [_Replica("r0"), _Replica("r1")]
        for rep in reps:
            rep.history.sample()
        _router, fed = _fleet(reps, dead=("r1",))
        w = fed.tick()
        assert w["partial"] is True and w["missing"] == ["r1"]
        # a crashed replica must not cost one timeout per tick
        assert not any("9301" in u for u in fed._test_calls)

    def test_full_scrape_is_not_partial(self):
        reps = [_Replica("r0"), _Replica("r1")]
        for rep in reps:
            rep.history.sample()
        _router, fed = _fleet(reps)
        w = fed.tick()
        assert w["partial"] is False and w["missing"] == []

    def test_shared_producer_ring_counts_once(self):
        """An in-process fleet serves ONE shared history ring from every
        port; the producer-keyed cursor must merge each window once."""
        reps = [_Replica("r0"), _Replica("r1")]
        reps[0].registry.counter("tx.commit").inc(4)
        reps[0].history.sample()
        reps[1].payload = reps[0].payload  # same process, same ring
        _router, fed = _fleet(reps)
        w = fed.tick()
        assert w["counters"]["tx.commit"] == 4
        assert w["replicas"] == ["r0", "r1"]
        assert w["partial"] is False

    def test_scrape_cursor_never_remerges_a_window(self):
        reps = [_Replica("r0")]
        reps[0].registry.counter("server.admission.admitted").inc(5)
        reps[0].history.sample()
        _router, fed = _fleet(reps)
        w1 = fed.tick()
        assert w1["counters"].get("server.admission.admitted") == 5
        # nothing new on the replica: the same retained window must not
        # be double-counted into the next fleet window
        w2 = fed.tick()
        assert "server.admission.admitted" not in w2["counters"]


# ---------------------------------------------------------------------------
# merge semantics
# ---------------------------------------------------------------------------

class TestMergeSemantics:
    def test_counters_sum_and_gauges_stay_keyed(self):
        reps = [_Replica("r0"), _Replica("r1")]
        reps[0].registry.counter("server.admission.admitted").inc(7)
        reps[1].registry.counter("server.admission.admitted").inc(5)
        reps[0].registry.set_gauge("admission.limit", 8)
        reps[1].registry.set_gauge("admission.limit", 16)
        for rep in reps:
            rep.history.sample()
        _router, fed = _fleet(reps)
        w = fed.tick()
        assert w["counters"]["server.admission.admitted"] == 12
        assert w["gauges"]["admission.limit"] == {"r0": 8, "r1": 16}

    def test_fleet_percentiles_bitwise_equal_concatenated_vectors(self):
        """Acceptance: fleet-windowed p50/p95/p99 == recomputing from the
        element-wise concatenation (sum) of the per-replica bucket delta
        vectors — exact, not approximate."""
        reps = [_Replica("r0"), _Replica("r1"), _Replica("r2")]
        reps[0].observe("server.request.wall",
                        [1.0, 2.0, 4.0, 8.0, 100.0])
        reps[1].observe("server.request.wall", [0.5, 0.5, 3.0, 250.0])
        reps[2].observe("server.request.wall", [16.0] * 10)
        for rep in reps:
            rep.history.sample()
        _router, fed = _fleet(reps)
        w = fed.tick()
        merged = w["series"]["server.request.wall"]
        per_replica = [
            rep.history.windows()[-1]["series"]["server.request.wall"]
            for rep in reps
        ]
        width = max(len(e["buckets"]) for e in per_replica)
        concat = [0] * width
        for e in per_replica:
            for i, v in enumerate(e["buckets"]):
                concat[i] += v
        hi = max(e["max"] for e in per_replica)
        assert merged["buckets"] == concat
        assert merged["count"] == sum(e["count"] for e in per_replica)
        assert sum(merged["buckets"]) == merged["count"]
        for q, key in ((0.50, "p50"), (0.95, "p95"), (0.99, "p99")):
            assert merged[key] == Histogram.percentile_of(concat, q, hi)

    def test_merge_series_empty_and_sparse(self):
        assert merge_series([]) is None
        assert merge_series([{"count": 0, "buckets": []}]) is None

    def test_metrics_view_merges_current_snapshots(self):
        reps = [_Replica("r0"), _Replica("r1")]
        reps[0].registry.counter("tx.commit").inc(3)
        reps[1].registry.counter("tx.commit").inc(4)
        reps[0].registry.set_gauge("admission.limit", 8)
        reps[1].observe("server.request.wall", [5.0])
        _router, fed = _fleet(reps)
        view = fed.metrics_view()
        assert view["partial"] is False
        m = view["metrics"]
        assert m["tx.commit"]["count"] == 7
        assert m["admission.limit"]["value"] == {"r0": 8}
        assert "r1" in m["server.request.wall"]["by_replica"]


# ---------------------------------------------------------------------------
# clock offsets + incident ordering under skew
# ---------------------------------------------------------------------------

class TestSkewCorrection:
    def test_offset_estimate_equals_injected_skew_at_zero_rtt(self):
        reps = [_Replica("r0", skew_s=0.5), _Replica("r1", skew_s=-0.5)]
        for rep in reps:
            rep.history.sample()
        _router, fed = _fleet(reps)
        # zero-RTT fake clocks: offset = peer_wall - send_wall exactly
        fed.tick()
        est = fed.offsets.snapshot()
        skew0 = est["r0"]["offset_s"] - est["r1"]["offset_s"]
        assert abs(skew0 - 1.0) < 1e-6, (
            "±500 ms of injected skew must be recovered exactly"
        )

    def test_min_rtt_sample_wins(self):
        co = ClockOffsets()
        co.observe("r", send_wall=100.0, rtt_s=0.2, peer_wall=100.9)
        co.observe("r", send_wall=200.0, rtt_s=0.01, peer_wall=200.505)
        co.observe("r", send_wall=300.0, rtt_s=0.5, peer_wall=301.0)
        est = co.snapshot()["r"]
        assert est["rtt_s"] == 0.01 and est["samples"] == 3
        assert abs(est["offset_s"] - 0.5) < 1e-9

    def test_incident_orders_two_replica_sequence_under_skew(self):
        """Acceptance: kill (on the +500 ms replica) then mark_dead (on
        the -500 ms replica) — raw wall stamps invert the order, the
        offset-corrected merge restores it."""
        reps = [_Replica("r0", skew_s=0.5), _Replica("r1", skew_s=-0.5)]
        for rep in reps:
            rep.history.sample()
        # true order: kill at t=+100.0 on r0, mark_dead at t=+100.2 on
        # r1, rejoin at t=+100.4 on r1, warmup at t=+100.6 on r0 — each
        # event stamped on ITS replica's (skewed) wall clock, so the
        # raw stamps sort r1's events before r0's kill
        base = reps[0].true_wall
        reps[0].flight_events = [
            {"seq": 1, "ts": base + 100.0 + 0.5, "mono": 1.0,
             "category": "fault", "kind": "replica_kill",
             "replica": "r0"},
            {"seq": 2, "ts": base + 100.6 + 0.5, "mono": 1.6,
             "category": "fleet", "action": "warmup", "replica": "r0"},
        ]
        reps[1].flight_events = [
            {"seq": 1, "ts": base + 100.2 - 0.5, "mono": 2.2,
             "category": "fleet", "action": "dead", "replica": "r1"},
            {"seq": 2, "ts": base + 100.4 - 0.5, "mono": 2.4,
             "category": "fleet", "action": "rejoin", "replica": "r1"},
        ]
        _router, fed = _fleet(reps)
        fed.tick()  # estimate offsets
        # drop the frontend's own ring (replica-join events carry real
        # wall stamps that don't belong on this synthetic timeline)
        flight_recorder.reset()
        report = fed.incident(window_s=0)
        lanes = [e["lane"] for e in report["events"]]
        assert lanes == ["r0", "r1", "r1", "r0"], (
            f"raw-stamp order leaked through: {lanes}"
        )
        phases = [p["phase"] for p in report["phases"]]
        assert phases == ["kill", "mark_dead", "re_pin", "warm_up"]
        ts = [e["ts_corrected"] for e in report["events"]]
        assert ts == sorted(ts)
        # corrected onto the FRONTEND's timeline (wall = 2_000_000)
        assert abs(ts[0] - (fed._test_wall["t"] + 100.0)) < 1e-6
        validate_chrome_trace(report["trace"])
        # one lane per replica in the trace metadata
        assert set(report["trace"]["otherData"]["lanes"]) >= {"r0", "r1"}

    def test_incident_dedups_shared_ring_events(self):
        """In-process fleets share ONE flight ring: the same event
        scraped from N replicas' /flight must appear once."""
        flight_recorder.reset()
        shared = [
            {"seq": 7, "ts": 100.0, "mono": 1.0, "category": "fault",
             "kind": "replica_kill", "replica": "r0"},
        ]
        reps = [_Replica("r0"), _Replica("r1")]
        reps[0].flight_events = shared
        reps[1].flight_events = shared
        for rep in reps:
            rep.history.sample()
        _router, fed = _fleet(reps)
        fed.tick()
        report = fed.incident(window_s=0)
        kills = [e for e in report["events"]
                 if e.get("kind") == "replica_kill"]
        assert len(kills) == 1

    def test_incident_partial_when_a_ring_is_unreachable(self):
        flight_recorder.reset()
        reps = [_Replica("r0"), _Replica("r1")]
        for rep in reps:
            rep.history.sample()
        _router, fed = _fleet(reps)
        fed.tick()
        reps[1].unreachable = True
        report = fed.incident(window_s=0)
        assert report["partial"] is True
        assert report["missing"] == ["r1"]

    def test_window_bounds_the_lookback(self):
        co = ClockOffsets()
        events = [
            {"seq": 1, "ts": 10.0, "category": "fault", "source": "r0",
             "replica": "r0"},
            {"seq": 2, "ts": 95.0, "category": "fault", "source": "r0",
             "replica": "r0"},
        ]
        out = merge_incident_events(events, co, now_wall=100.0,
                                    window_s=30.0)
        assert [e["ts"] for e in out] == [95.0]


# ---------------------------------------------------------------------------
# fleet SLOs + outlier detection (fake clock, deterministic)
# ---------------------------------------------------------------------------

class TestFleetSLOs:
    def _outlier_fleet(self):
        reps = [_Replica("r0"), _Replica("r1"), _Replica("r2")]
        _router, fed = _fleet(
            reps,
            outlier_factor=2.0, outlier_min_count=10,
            slo_specs=fleet_default_specs(
                fast_windows=1, slow_windows=1,
            ),
        )
        return reps, fed

    def _load(self, reps, sick=None):
        for rep in reps:
            ms = 400.0 if rep.name == sick else 2.0
            rep.observe("server.request.wall", [ms] * 25)
            rep.registry.counter("server.admission.admitted").inc(25)
            rep.advance()
            rep.history.sample()

    def test_outlier_replica_raises_flight_event_and_burn(self):
        flight_recorder.reset()
        reps, fed = self._outlier_fleet()
        self._load(reps, sick="r1")
        w = fed.tick()
        assert [o["replica"] for o in w["outliers"]] == ["r1"]
        events = flight_recorder.events("replica_outlier")
        assert events and events[-1]["replica"] == "r1"
        assert events[-1]["threshold_factor"] == 2.0
        # the outlier budget burned IN this window (not the next one)
        assert w["counters"].get(
            "fleet.federation.outlier_windows"
        ) == 1
        # 100% bad over a 1% budget at 1-window hysteresis: ticket rung
        snap = fed.slo.snapshot()
        outlier_alerts = [a for a in snap["alerts"]
                         if a["name"] == "fleet_latency_outlier"]
        assert outlier_alerts and outlier_alerts[0]["severity"] in (
            "ticket", "page"
        )

    def test_healthy_fleet_raises_no_outlier(self):
        flight_recorder.reset()
        reps, fed = self._outlier_fleet()
        self._load(reps, sick=None)
        w = fed.tick()
        assert w["outliers"] == []
        assert flight_recorder.events("replica_outlier") == []

    def test_below_min_count_replicas_are_excluded(self):
        flight_recorder.reset()
        reps, fed = self._outlier_fleet()
        # the sick replica has too few observations to judge
        reps[0].observe("server.request.wall", [2.0] * 25)
        reps[1].observe("server.request.wall", [400.0] * 3)
        reps[2].observe("server.request.wall", [2.0] * 25)
        for rep in reps:
            rep.history.sample()
        w = fed.tick()
        assert w["outliers"] == []

    def test_fleet_availability_spec_reads_summed_admission(self):
        reps = [_Replica("r0"), _Replica("r1")]
        _router, fed = _fleet(
            reps,
            slo_specs=fleet_default_specs(fast_windows=1, slow_windows=1),
        )
        for _ in range(3):
            for rep in reps:
                rep.registry.counter("server.admission.admitted").inc(40)
                rep.registry.counter("server.admission.shed").inc(60)
                rep.advance()
                rep.history.sample()
            fed.tick()
        snap = fed.slo.snapshot()
        avail = [a for a in snap["alerts"]
                 if a["name"] == "fleet_availability"]
        assert avail and avail[0]["severity"] == "page", (
            "60% shed across the fleet must page fleet availability"
        )

    def test_slo_sequence_deterministic_on_fake_clock(self):
        """Acceptance: same synthetic inputs -> byte-equal slo_burn
        flight sequence (clock fields masked), twice."""

        def run():
            flight_recorder.reset()
            reps, fed = self._outlier_fleet()
            for round_i in range(4):
                self._load(reps, sick="r1" if round_i >= 2 else None)
                fed.tick()
            return [
                {k: v for k, v in e.items()
                 if k not in ("ts", "mono", "seq")}
                for e in flight_recorder.events("slo_burn")
            ]

        first, second = run(), run()
        assert first, "the storm must produce slo_burn transitions"
        assert json.dumps(first, sort_keys=True) == json.dumps(
            second, sort_keys=True
        )

    def test_router_counters_fold_into_fleet_windows(self):
        from janusgraph_tpu.observability import registry

        reps = [_Replica("r0")]
        reps[0].history.sample()
        _router, fed = _fleet(reps)
        registry.counter("fleet.router.routed").inc(9)
        w = fed.tick()
        assert w["counters"].get("fleet.router.routed", 0) >= 9
        # deltas, not cumulative: a quiet tick re-adds nothing
        w2 = fed.tick()
        assert "fleet.router.routed" not in w2["counters"]


# ---------------------------------------------------------------------------
# scrape payload plumbing
# ---------------------------------------------------------------------------

class TestScrapePayload:
    def test_history_scrape_keeps_bucket_vectors(self):
        rep = _Replica("r3")
        rep.observe("server.request.wall", [4.0, 8.0])
        rep.history.sample()
        payload = rep.history.scrape()
        w = payload["windows"][-1]
        assert "buckets" in w["series"]["server.request.wall"]
        assert payload["now"] == pytest.approx(rep.true_wall)
        assert payload["last_seq"] == w["seq"]

    def test_overhead_gauge_and_timer_account_each_tick(self):
        from janusgraph_tpu.observability import registry

        reps = [_Replica("r0")]
        reps[0].history.sample()
        _router, fed = _fleet(reps)
        before = registry.get_count("fleet.federation.ticks")
        fed.tick()
        assert registry.get_count("fleet.federation.ticks") == before + 1
        # the overhead gauge is refreshed every tick
        _c, _t, _h, gauges = registry.metric_objects()
        assert "fleet.federation.overhead_ms" in gauges


# --------------------------------------------- cursor-gap heal (ISSUE 20)
class TestCursorGapHeal:
    def _burst(self, rep, n, counter="app.burst"):
        for _ in range(n):
            rep.registry.counter(counter).inc()
            rep.advance()
            rep.history.sample()

    def test_burst_past_bounded_tail_heals_with_one_full_refetch(self):
        """A window burst longer than the bounded scrape tail opens a
        cursor gap: counted once, healed by ONE full-backlog re-fetch,
        and zero windows are lost from the fleet merge."""
        from janusgraph_tpu.observability import registry

        rep = _Replica("r0")
        rep.history.sample()
        _router, fed = _fleet([rep], scrape_window=2)
        fed.tick()  # bootstrap: full backlog, cursor lands at seq 1
        gaps0 = registry.get_count("fleet.federation.cursor_gaps")
        heals0 = registry.get_count("fleet.federation.cursor_heals")
        calls0 = len(fed._test_calls)
        self._burst(rep, 6)  # seqs 2..7 — tail of 2 reaches back to 6
        fed.tick()
        assert registry.get_count("fleet.federation.cursor_gaps") == gaps0 + 1
        assert (
            registry.get_count("fleet.federation.cursor_heals") == heals0 + 1
        )
        # exactly two fetches this tick: the bounded scrape + the heal
        tick_calls = fed._test_calls[calls0:]
        assert len(tick_calls) == 2
        assert "window=2" in tick_calls[0]
        assert tick_calls[1].endswith("/timeseries?raw=1")
        # zero lost: every burst increment survived into fleet windows
        merged = sum(
            w["counters"].get("app.burst", 0)
            for w in fed.history.windows()
        )
        assert merged == 6
        # and the cursor is fully caught up — the next tick re-merges
        # nothing and opens no new gap
        fed.tick()
        assert registry.get_count("fleet.federation.cursor_gaps") == gaps0 + 1
        assert sum(
            w["counters"].get("app.burst", 0)
            for w in fed.history.windows()
        ) == 6

    def test_failed_heal_is_counted_and_the_tail_still_merges(self):
        """When the heal re-fetch itself fails the gap stands (counted,
        not retried in-tick) and the bounded tail merges as-is."""
        from janusgraph_tpu.observability import registry

        rep = _Replica("r0")
        rep.history.sample()
        _router, fed = _fleet([rep], scrape_window=2)
        fed.tick()
        rep.fail_full = True  # tears ONLY the full-backlog heal fetch
        fails0 = registry.get_count("fleet.federation.cursor_heal_failures")
        self._burst(rep, 6)
        fed.tick()
        assert (
            registry.get_count("fleet.federation.cursor_heal_failures")
            == fails0 + 1
        )
        # the tail (2 windows) merged; the 4 gap windows are lost and
        # that loss is exactly what the gap counter priced
        merged = sum(
            w["counters"].get("app.burst", 0)
            for w in fed.history.windows()
        )
        assert merged == 2
        assert fed._last_seq[rep.name] == rep.history.last_seq()


# ------------------------------------------ push transport (ISSUE 20)
class _FakeWatchSession:
    """Injectable push channel peer: the test feeds frames, the
    federation's reader thread drains them.  ``fail=True`` simulates a
    killed replica (recv raises, the channel records the death)."""

    def __init__(self, url, subscribe):
        self.url = url
        self.subscribe = subscribe
        self.frames = []
        self._lock = threading.Lock()
        self.closed = False
        self.fail = False

    def feed(self, *frames):
        with self._lock:
            self.frames.extend(frames)

    def recv(self, timeout=1.0):
        if self.fail:
            raise ConnectionError("replica killed mid-stream")
        with self._lock:
            if self.frames:
                return self.frames.pop(0)
        time.sleep(0.002)
        return None

    def close(self):
        self.closed = True


def _push_fleet(replicas, **fed_kw):
    """A push-enabled fleet whose watch sessions are test-fed."""
    sessions = []

    def factory(url, subscribe, timeout_s):
        s = _FakeWatchSession(url, subscribe)
        sessions.append(s)
        return s

    router, fed = _fleet(
        replicas, push_enabled=True, watch_factory=factory, **fed_kw
    )
    return router, fed, sessions


def _wait(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while not pred():
        assert time.monotonic() < deadline, "condition never held"
        time.sleep(0.005)


def _app_series(window):
    """The merged series minus the plane's own self-cost metrics —
    those time REAL work (sample duration in ns) and differ between
    otherwise-identical twin replicas."""
    return {
        k: v for k, v in window["series"].items()
        if not k.startswith("observability.")
    }


def _app_by_replica(window):
    return {
        k: v for k, v in window["by_replica"].items()
        if not k.startswith("observability.")
    }


def _window_frames(rep, since=0):
    return [
        {"type": "event", "stream": "window", "seq": w["seq"], "data": w}
        for w in json.loads(json.dumps(rep.history.windows()))
        if w["seq"] > since
    ]


class TestPushTransport:
    def test_capable_replica_negotiates_and_windows_merge_identically(self):
        """Cell 1 of the interop matrix: push frontend x push replica.
        The replica is served from pushed frames — never scraped — and
        the merged series are byte-identical to what the PR 17 poll
        path produces over a twin replica."""
        from janusgraph_tpu.observability import registry

        def twin(name):
            rep = _Replica(name)
            rep.observe("server.request.wall", [4.0, 8.0, 16.0])
            rep.registry.counter("app.ops").inc(7)
            rep.history.sample()
            return rep

        push_rep, poll_rep = twin("r0"), twin("r0")
        push_rep.watch = True
        _r1, fed_push, sessions = _push_fleet([push_rep])
        _r2, fed_poll = _fleet([poll_rep])

        w_poll = fed_poll.tick()
        fed_push.tick()  # negotiates; nothing buffered yet
        assert len(sessions) == 1
        assert sessions[0].subscribe["cursors"] == {"window": 0}
        assert fed_push.push_status()["channels"]["r0"]["connected"]
        # the replica was NEVER scraped: only the capability probe ran
        assert [c for c in fed_push._test_calls if "/timeseries" in c] == []
        assert registry.get_count("fleet.federation.push_negotiated") >= 1

        sessions[0].feed(*_window_frames(push_rep))
        channel = fed_push._push["r0"]
        _wait(lambda: channel.state()["windows_seen"] == 1)
        w_push = fed_push.tick()
        # byte-compatible merge: same source windows -> same series
        assert _app_series(w_push) == _app_series(w_poll)
        assert _app_by_replica(w_push) == _app_by_replica(w_poll)
        assert (
            w_push["counters"]["app.ops"] == w_poll["counters"]["app.ops"]
        )
        assert [c for c in fed_push._test_calls if "/timeseries" in c] == []

    def test_poll_only_peer_keeps_the_exact_scrape_path(self):
        """Cells 2-4: a peer that refuses the capability — and any
        frontend with push disabled — runs the byte-exact PR 17 poll
        path: same fetch URLs, same merged windows."""
        from janusgraph_tpu.observability import registry

        def twin(name):
            rep = _Replica(name)
            rep.observe("server.request.wall", [3.0, 9.0])
            rep.history.sample()
            return rep

        old_rep, plain_rep = twin("r0"), twin("r0")  # watch=False: poll-only
        refused0 = registry.get_count("fleet.federation.push_refused")
        _r1, fed_push, sessions = _push_fleet([old_rep])
        _r2, fed_poll = _fleet([plain_rep])
        w1_push, w1_poll = fed_push.tick(), fed_poll.tick()
        for rep in (old_rep, plain_rep):
            rep.observe("server.request.wall", [5.0])
            rep.advance()
            rep.history.sample()
        w2_push, w2_poll = fed_push.tick(), fed_poll.tick()

        assert sessions == []  # no channel was ever opened
        assert registry.get_count(
            "fleet.federation.push_refused"
        ) == refused0 + 1
        assert fed_push.push_status()["poll_only"] == ["r0"]
        # byte-exact scrape path: identical URLs once the one-shot
        # capability probe is set aside (and it is never re-probed)
        push_urls = [
            c for c in fed_push._test_calls if "/watch/info" not in c
        ]
        assert push_urls == fed_poll._test_calls
        assert sum("/watch/info" in c for c in fed_push._test_calls) == 1
        for wp, wq in ((w1_push, w1_poll), (w2_push, w2_poll)):
            assert _app_series(wp) == _app_series(wq)
            assert _app_by_replica(wp) == _app_by_replica(wq)

    def test_unanswered_probe_is_retried_not_refused(self):
        """A probe the replica never ANSWERS (mid-restart, network) is
        a transport failure, not a capability refusal — the peer must
        renegotiate when it comes back, not be poll-only forever."""
        from janusgraph_tpu.observability import registry

        rep = _Replica("r0")
        rep.watch = True
        rep.history.sample()
        _router, fed, sessions = _push_fleet([rep])
        rep.unreachable = True
        fails0 = registry.get_count(
            "fleet.federation.push_connect_failures"
        )
        fed.tick()
        assert fed.push_status()["poll_only"] == []
        assert registry.get_count(
            "fleet.federation.push_connect_failures"
        ) == fails0 + 1
        assert sessions == []
        rep.unreachable = False
        fed.tick()  # came back: the capability negotiates NOW
        assert len(sessions) == 1
        assert fed.push_status()["channels"]["r0"]["connected"]

    def test_reconnect_resumes_from_cursors_zero_dup_zero_lost(self):
        """Kill the stream mid-flight: the dropped channel is flighted,
        renegotiated the SAME tick with resume cursors (window AND
        flight), and across the kill every window merges exactly once."""
        from janusgraph_tpu.observability import registry

        rep = _Replica("r0")
        rep.watch = True
        rep.registry.counter("app.ops").inc()
        rep.history.sample()
        _router, fed, sessions = _push_fleet([rep])
        fed.tick()
        sessions[0].feed(*_window_frames(rep))
        sessions[0].feed({
            "type": "event", "stream": "flight", "seq": 41,
            "data": {"seq": 41, "replica": "r0", "ts": rep.true_wall,
                     "category": "compaction", "action": "start"},
        })
        channel = fed._push["r0"]
        _wait(lambda: channel.state()["windows_seen"] == 1)
        _wait(lambda: channel.state()["events_seen"] == 1)
        fed.tick()

        lost0 = registry.get_count("fleet.federation.push_lost")
        sessions[0].fail = True  # kill: reader thread records the death
        _wait(lambda: not channel.connected)
        for _ in range(3):
            rep.registry.counter("app.ops").inc()
            rep.advance()
            rep.history.sample()
        fed.tick()  # drops the dead channel AND renegotiates, same tick
        assert registry.get_count("fleet.federation.push_lost") == lost0 + 1
        assert len(sessions) == 2
        # resume cursors: past the last merged window and pushed event
        assert sessions[1].subscribe["cursors"] == {
            "window": 1, "flight": 41,
        }
        sessions[1].feed(*_window_frames(rep, since=1))
        channel2 = fed._push["r0"]
        _wait(lambda: channel2.state()["windows_seen"] == 3)
        fed.tick()
        # zero dup / zero lost across the kill: 4 increments in, 4 out
        merged = sum(
            w["counters"].get("app.ops", 0)
            for w in fed.history.windows()
        )
        assert merged == 4
        events = [
            e for e in flight_recorder.snapshot()["events"]
            if e["category"] == "fleet"
            and e.get("action") in ("push_on", "push_lost")
            and e.get("replica") == "r0"
        ]
        assert [e["action"] for e in events[-3:]] == [
            "push_on", "push_lost", "push_on",
        ]

    def test_bundle_announcement_ships_off_host(self):
        """A pushed ``bundle`` flight event triggers one rate-bounded
        off-host fetch; the bundle outlives its replica in the
        frontend's store and torn replies are skipped, not stored."""
        from janusgraph_tpu.observability import registry

        rep = _Replica("r0")
        rep.watch = True
        rep.bundle = {
            "reason": "stall", "ts": 1.0, "path": "/tmp/b1.json",
            "flight": [], "timeseries": [],
        }
        rep.history.sample()
        _router, fed, sessions = _push_fleet([rep])
        fed.tick()

        def announce(seq):
            sessions[-1].feed({
                "type": "event", "stream": "flight", "seq": seq,
                "data": {"seq": seq, "replica": "r0", "ts": rep.true_wall,
                         "category": "bundle", "reason": "stall",
                         "path": "/tmp/b1.json"},
            })

        shipped0 = registry.get_count("fleet.federation.bundles_shipped")
        announce(1)
        _wait(lambda: fed.bundles.get("r0") is not None)
        got = fed.bundles.get("r0")
        assert got["bundle"]["reason"] == "stall"
        assert got["path"] == "/tmp/b1.json"
        assert registry.get_count(
            "fleet.federation.bundles_shipped"
        ) == shipped0 + 1
        # inside the rate bound: announced again, NOT fetched again
        announce(2)
        _wait(lambda: registry.get_count(
            "fleet.federation.bundle_rate_limited"
        ) >= 1)
        assert fed.bundles.status()["fetched"] == 1
        # past the bound, a torn reply (error body) is skipped-counted
        fed._test_clock["t"] += 60.0
        rep.bundle = {"status": 404, "error": "no bundle"}
        fails0 = registry.get_count("fleet.federation.bundle_fetch_failures")
        announce(3)
        _wait(lambda: registry.get_count(
            "fleet.federation.bundle_fetch_failures"
        ) == fails0 + 1)
        assert fed.bundles.status()["fetched"] == 1
        # the good bundle is still the one retrievable off-host
        assert fed.bundles.get("r0")["bundle"]["reason"] == "stall"


# --------------------------------------- watchdog progress (ISSUE 20)
class TestWatchdogSources:
    def test_wedged_federation_tick_fires_stall(self, tmp_path):
        """start() auto-registers the tick loop as a watchdog progress
        source; a tick that stops completing (wedged scrape) freezes
        the counter and fires exactly one edge-triggered stall."""
        from janusgraph_tpu.observability.continuous import (
            StallWatchdog, bundle_writer,
        )

        clk = {"t": 0.0}
        wd = StallWatchdog(clock=lambda: clk["t"])
        wd.configure(stall_s=5.0)
        bundle_writer.configure(directory=str(tmp_path), min_interval_s=0.0)
        rep = _Replica("r0")
        rep.history.sample()
        _router, fed = _fleet([rep], watchdog=wd)
        fed.start(interval_s=3600.0)  # the loop thread sleeps; we tick
        try:
            fed.tick()
            assert wd.check() == []  # baseline
            clk["t"] += 3.0
            fed.tick()
            assert wd.check() == []  # progress advanced: re-arms
            clk["t"] += 2.0
            assert wd.check() == []  # frozen, but under stall_s
            clk["t"] += 4.0  # 6 s since the last completed tick
            fired = wd.check()
            assert [e["category"] for e in fired] == ["stall"]
            assert fired[0]["source"] == "fleet.federation.tick"
            assert fired[0]["stuck_s"] >= 5.0
            # edge-triggered: the same wedge never re-fires
            clk["t"] += 10.0
            assert wd.check() == []
        finally:
            fed.stop()
        # stop() unregisters — a stopped fleet is not a stall
        assert "fleet.federation.tick" not in wd._progress

    def test_cdc_follower_auto_registers_pull_progress(
        self, tmp_path, monkeypatch
    ):
        """bootstrap() self-registers the pull loop with the watchdog
        singleton (no manual wiring); the progress value advances only
        when a pull COMPLETES, so a wedged replay freezes it."""
        from janusgraph_tpu.observability.continuous import (
            watchdog_singleton,
        )
        from janusgraph_tpu.olap import sharded_checkpoint
        from janusgraph_tpu.server.fleet import CDCFollower

        class _CSR:
            num_vertices = 3
            num_edges = 2

        class _Src:
            def cursor_for_epoch(self, epoch):
                return 0

            def replay_from(self, cursor):
                return [], cursor

        monkeypatch.setattr(
            sharded_checkpoint, "load_csr_checkpoint",
            lambda d: (_CSR(), 0),
        )
        wd = watchdog_singleton()
        f = CDCFollower(_Src(), str(tmp_path), name="wd-probe")
        try:
            assert f.bootstrap()
            assert "fleet.cdc.wd-probe" in wd._progress
            p = f._progress()
            assert p["active"] == 1  # serving follower: active work
            f.pull()
            assert f._progress()["progress"] == p["progress"] + 1
            # promotion flips the role: the source reports inactive
            # (a leader that stops pulling is not a stall)
            f.role = "leader"
            assert f._progress()["active"] == 0
        finally:
            f.unregister_watchdog()
        assert "fleet.cdc.wd-probe" not in wd._progress
