"""merge_v/merge_e (TinkerPop 3.6 MergeVertexStep/MergeEdgeStep — the
declarative upsert surface reached through the reference's TinkerPop
dependency), plus inject()/constant() and the T structure tokens."""

import pytest

from janusgraph_tpu.core import gods
from janusgraph_tpu.core.codecs import Direction
from janusgraph_tpu.core.graph import open_graph
from janusgraph_tpu.core.traversal import AnonymousTraversal, QueryError, T

__ = AnonymousTraversal()


@pytest.fixture()
def g():
    graph = open_graph({"ids.authority-wait-ms": 0.0})
    gods.load(graph)
    yield graph
    graph.close()


# ------------------------------------------------------------------ merge_v
def test_merge_v_matches_existing(g):
    t = g.traversal()
    before = len(t.V().to_list())
    hits = t.merge_v({T.label: "god", "name": "jupiter"}).to_list()
    assert len(hits) == 1 and hits[0].value("name") == "jupiter"
    assert len(t.V().to_list()) == before  # nothing created


def test_merge_v_creates_when_absent(g):
    t = g.traversal()
    v = t.merge_v({T.label: "god", "name": "janus"}).next()
    assert v.label == "god" and v.value("name") == "janus"
    # second run matches the vertex just created — idempotent upsert
    again = t.merge_v({T.label: "god", "name": "janus"}).to_list()
    assert len(again) == 1 and again[0].id == v.id


def test_merge_v_on_create_on_match(g):
    t = g.traversal()
    v = (
        t.merge_v({T.label: "god", "name": "minerva"})
        .on_create({"age": 100})
        .on_match({"seen": True})
        .next()
    )
    assert v.value("age") == 100  # created: on_create applied
    assert not [p for p in g.new_transaction().get_properties(v, "seen")]
    v2 = (
        t.merge_v({T.label: "god", "name": "minerva"})
        .on_create({"age": 999})
        .on_match({"seen": True})
        .next()
    )
    assert v2.id == v.id
    assert v2.value("age") == 100  # matched: on_create NOT applied
    assert v2.value("seen") is True  # on_match applied


def test_merge_v_by_id_token(g):
    t = g.traversal()
    jup = t.V().has("name", "jupiter").next()
    assert t.merge_v({T.id: jup.id}).next().id == jup.id
    # a miss on T.id attempts creation, and T.id creation is not supported
    with pytest.raises(QueryError):
        t.merge_v({"name": "nobody-here"}).on_create({T.id: 123}).next()


def test_merge_v_lazy_no_phantom(g):
    t = g.traversal()
    before = len(t.V().to_list())
    t.merge_v({T.label: "god", "name": "phantom"})  # never executed
    assert len(t.V().to_list()) == before


def test_merge_v_mid_traversal_stream_of_maps(g):
    t = g.traversal()
    made = (
        t.inject({T.label: "titan", "name": "kronos"},
                 {T.label: "titan", "name": "rhea"})
        .merge_v()
        .to_list()
    )
    assert {v.value("name") for v in made} == {"kronos", "rhea"}
    assert all(v.label == "titan" for v in made)


def test_merge_v_tid_creation_is_idempotent():
    """A T.id-keyed merge that misses creates WITH that id (under
    graph.set-vertex-id), so re-running the same merge matches instead of
    duplicating."""
    graph = open_graph({
        "ids.authority-wait-ms": 0.0, "graph.set-vertex-id": True,
    })
    try:
        t = graph.traversal()
        vid = graph.idm.make_vertex_id(7, 3)
        v1 = t.merge_v({T.id: vid, "name": "pinned"}).next()
        assert v1.id == vid
        v2 = t.merge_v({T.id: vid, "name": "pinned"}).next()
        assert v2.id == vid
        assert len(t.V().has("name", "pinned").to_list()) == 1
    finally:
        graph.close()


def test_merge_v_tid_creation_without_config_raises(g):
    from janusgraph_tpu.exceptions import InvalidElementError

    t = g.traversal()
    vid = g.idm.make_vertex_id(7, 3)
    with pytest.raises(InvalidElementError, match="set-vertex-id"):
        t.merge_v({T.id: vid, "name": "nope"}).next()


# ------------------------------------------------------------------ merge_e
def test_merge_e_matches_existing(g):
    t = g.traversal()
    jup = t.V().has("name", "jupiter").next()
    nep = t.V().has("name", "neptune").next()
    before = len(t.V().has("name", "jupiter").out_e("brother").to_list())
    e = t.merge_e(
        {Direction.OUT: jup, Direction.IN: nep, T.label: "brother"}
    ).next()
    assert e.label == "brother" and e.in_vertex.id == nep.id
    after = len(t.V().has("name", "jupiter").out_e("brother").to_list())
    assert after == before  # matched, not created


def test_merge_e_creates_with_on_create(g):
    t = g.traversal()
    jup = t.V().has("name", "jupiter").next()
    sky = t.V().has("name", "sky").next()
    e = (
        t.merge_e({Direction.OUT: jup, Direction.IN: sky.id,
                   T.label: "rules"})
        .on_create({"since": "always"})
        .next()
    )
    assert e.property_values().get("since") == "always"
    # re-merge matches (property equality is NOT part of this match map)
    e2 = t.merge_e(
        {Direction.OUT: jup, Direction.IN: sky.id, T.label: "rules"}
    ).on_match({"checked": 1}).next()
    assert e2.property_values().get("checked") == 1


def test_merge_e_mid_traversal_defaults_to_incoming_vertex(g):
    t = g.traversal()
    plu = t.V().has("name", "pluto").next()
    es = (
        t.V().has("name", "jupiter")
        .merge_e({Direction.IN: plu, T.label: "brother"})
        .to_list()
    )
    assert len(es) == 1 and es[0].out_vertex.value("name") == "jupiter"


def test_merge_e_requires_label(g):
    t = g.traversal()
    jup = t.V().has("name", "jupiter").next()
    with pytest.raises(QueryError):
        t.merge_e({Direction.OUT: jup, Direction.IN: jup}).next()


def test_merge_e_label_from_on_create(g):
    """on_create may supply what the match map lacks; a label-less match
    map matches edges of ANY label between the endpoints."""
    t = g.traversal()
    jup = t.V().has("name", "jupiter").next()
    nep = t.V().has("name", "neptune").next()
    # brother edge already exists jupiter->neptune: label-less map matches
    e = (
        t.merge_e({Direction.OUT: jup, Direction.IN: nep})
        .on_create({T.label: "admires"})
        .next()
    )
    assert e.label == "brother"  # matched, on_create label unused
    # no edge jupiter->tartarus: creation takes on_create's label
    tart = t.V().has("name", "tartarus").next()
    e2 = (
        t.merge_e({Direction.OUT: jup, Direction.IN: tart})
        .on_create({T.label: "admires"})
        .next()
    )
    assert e2.label == "admires"
    # conflicting on_create label is an error, matching merge_v
    with pytest.raises(QueryError):
        t.merge_e({Direction.OUT: jup, Direction.IN: tart,
                   T.label: "admires"}).on_create({T.label: "other"}).next()


def test_merge_on_create_cannot_override_match_keys(g):
    """on_create overriding a merge-map key would create an element that
    does not match its own merge map (duplicating on every re-run) —
    rejected eagerly, and eagerly also means the error does NOT depend on
    whether a match happens to exist."""
    t = g.traversal()
    with pytest.raises(QueryError, match="override merge-map"):
        t.merge_v({T.label: "person", "name": "x"}).on_create(
            {"name": "y"}
        ).next()
    # eager validation: same error even though 'jupiter' EXISTS (the
    # match path would never consult on_create)
    with pytest.raises(QueryError, match="cannot set T.id"):
        t.merge_v({"name": "jupiter"}).on_create({T.id: 1}).next()
    jup = t.V().has("name", "jupiter").next()
    nep = t.V().has("name", "neptune").next()
    with pytest.raises(QueryError, match="override merge-map"):
        t.merge_e({Direction.OUT: jup, Direction.IN: nep,
                   T.label: "brother", "w": 1}).on_create({"w": 2}).next()


def test_merge_e_by_tid(g):
    """merge_e T.id: RelationIdentifier point lookup; misses cannot
    create (edge ids are not user-assignable)."""
    t = g.traversal()
    e = t.V().has("name", "jupiter").out_e("brother").next()
    hit = t.merge_e({T.id: e.identifier}).on_match({"w": 9}).next()
    assert hit.id == e.id and hit.property_values().get("w") == 9
    # string form of the identifier works too
    hit2 = t.merge_e({T.id: str(e.identifier)}).next()
    assert hit2.id == e.id
    # conflicting label in the match map = no match -> empty, not create
    assert t.merge_e(
        {T.id: e.identifier, T.label: "other"}
    ).to_list() == []
    # a missing id is an error (cannot create with a chosen edge id)
    from janusgraph_tpu.core.codecs import RelationIdentifier

    missing = RelationIdentifier(999999, e.out_vertex.id, e.type_id,
                                 e.in_vertex.id)
    with pytest.raises(QueryError, match="cannot"):
        t.merge_e({T.id: missing}).next()


def test_e_start_by_id(g):
    """E(rid) point lookup (graph.edges(ids) parity)."""
    t = g.traversal()
    e = t.V().has("name", "jupiter").out_e("brother").next()
    assert t.E(e.identifier).next().id == e.id
    assert t.E(str(e.identifier)).next().id == e.id
    assert t.E(e).next().id == e.id
    # two id args -> two traversers (both resolve to the same edge)
    got = t.E(e.identifier, str(e.identifier)).to_list()
    assert len(got) == 2 and {x.id for x in got} == {e.id}



# ------------------------------------------------------------- inject/const
def test_inject_start_and_mid(g):
    t = g.traversal()
    assert t.inject(1, 2, 3).to_list() == [1, 2, 3]
    vals = t.V().has("name", "jupiter").inject("x").to_list()
    assert vals[-1] == "x" and len(vals) == 2


def test_constant(g):
    t = g.traversal()
    out = t.V().has_label("god").constant("fixed").to_list()
    assert out and set(out) == {"fixed"}


# ----------------------------------------------------------- gremlin dialect
def test_gremlin_text_merge_spelling():
    from janusgraph_tpu.server.gremlin_compat import translate

    q = "g.mergeV({T.label: 'god', 'name': 'x'}).onCreate({'age': 1})"
    out = translate(q)
    assert "merge_v" in out and "on_create" in out
    assert "'god'" in out  # string literals untouched


def test_merge_e_tid_respects_endpoints_and_eager_validation(g):
    """T.id merge still honors endpoint constraints in the map, and
    on_create validation fires before the lookup (data-state-independent
    errors)."""
    t = g.traversal()
    e = t.V().has("name", "jupiter").out_e("brother").next()
    wrong = t.V().has("name", "hercules").next()
    assert t.merge_e(
        {T.id: e.identifier, Direction.OUT: wrong}
    ).to_list() == []
    assert t.merge_e(
        {T.id: e.identifier, Direction.IN: e.in_vertex}
    ).next().id == e.id
    with pytest.raises(QueryError, match="cannot set T.id"):
        t.merge_e({T.id: e.identifier}).on_create({T.id: 1}).next()
    # non-rid T.id values get a clean QueryError, not internal errors
    with pytest.raises(QueryError, match="RelationIdentifier"):
        t.merge_e({T.id: e.id}).next()
    with pytest.raises(QueryError, match="edge id"):
        t.E("garbage").to_list()


def test_has_id_accepts_relation_identifier(g):
    """E().has_id(rid) round-trips the id_() contract."""
    t = g.traversal()
    e = t.V().has("name", "jupiter").out_e("brother").next()
    rid = t.E(e.identifier).id_().next()
    assert t.E().has_id(rid).next().id == e.id
    assert t.E().has_id(e).next().id == e.id


def test_merge_v_race_unique_index():
    """Racing upserts: both transactions miss and create; a UNIQUE
    composite index refuses the second commit (the reference's guard),
    and the loser's retry matches the winner."""
    from janusgraph_tpu.exceptions import SchemaViolationError

    g = open_graph({"ids.authority-wait-ms": 0.0})
    mgmt = g.management()
    mgmt.make_property_key("name", str)
    mgmt.make_vertex_label("user")
    mgmt.build_composite_index("byName", ["name"], unique=True)
    try:
        t1, t2 = g.traversal(), g.traversal()
        t1.merge_v({T.label: "user", "name": "alice"}).next()
        t2.merge_v({T.label: "user", "name": "alice"}).next()
        t1.commit()
        with pytest.raises(SchemaViolationError, match="unique"):
            t2.commit()
        # the loser retries in a fresh tx and MATCHES the winner's vertex
        winner = g.traversal().V().has("name", "alice").next()
        retry = g.traversal().merge_v(
            {T.label: "user", "name": "alice"}
        ).next()
        assert retry.id == winner.id
        assert len(g.traversal().V().has("name", "alice").to_list()) == 1
    finally:
        g.close()
