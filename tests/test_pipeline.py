"""Pipelined async wire framing (ISSUE 11, storage/pipeline.py).

Covers the tentpole contract: old/new byte-compat across every feature-
bit combination (trace x ledger x deadline x pipeline), out-of-order
completion on one connection, coalescing (merged multi-gets and batched
mutates, demuxed per op), per-op deadline expiry mid-pipeline, fault
injection mid-pipeline (breaker counts the failed op only, siblings
complete), the adaptive sync/pipelined routing gate, the driver's WS
multiplexing, and a threaded e2e throughput acceptance run against a
latency-simulated storage node.
"""

import socket
import struct
import threading
import time

import pytest

from janusgraph_tpu.exceptions import (
    DeadlineExceededError,
    PermanentBackendError,
    TemporaryBackendError,
)
from janusgraph_tpu.storage.inmemory import InMemoryStoreManager
from janusgraph_tpu.storage.kcvs import KeySliceQuery, SliceQuery
from janusgraph_tpu.storage.pipeline import PIPELINE_FLAG, WireOp
from janusgraph_tpu.storage.remote import (
    _OP_BATCH,
    _OP_GET_SLICE,
    RemoteStoreManager,
    RemoteStoreServer,
)


def _force_pipeline(mgr):
    """Bypass the adaptive gate: route every eligible op pipelined."""
    mgr._should_pipeline = lambda: True
    return mgr


class _HookStore:
    """Store wrapper calling a hook before every read (blocking /
    failing / latency faults at the serving node)."""

    def __init__(self, inner, hook):
        self._inner = inner
        self._hook = hook

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def get_slice(self, query, txh):
        self._hook(query.key)
        return self._inner.get_slice(query, txh)

    def get_slice_multi(self, keys, sq, txh):
        self._hook(keys[0] if keys else b"")
        return self._inner.get_slice_multi(keys, sq, txh)

    def mutate(self, key, adds, dels, txh):
        self._hook(key)
        return self._inner.mutate(key, adds, dels, txh)


class _HookManager:
    def __init__(self, inner, hook):
        self._inner = inner
        self._hook = hook

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def open_database(self, name):
        return _HookStore(self._inner.open_database(name), self._hook)


def _gs_body(store: str, key: bytes) -> bytes:
    out = []
    sb = store.encode()
    out.append(struct.pack(">I", len(sb)) + sb)
    out.append(struct.pack(">I", len(key)) + key)
    out.append(struct.pack(">I", 0) + struct.pack(">I", 0)
               + struct.pack(">i", -1))
    return b"".join(out)


def _recv_frame(sock):
    head = b""
    while len(head) < 5:
        head += sock.recv(5 - len(head))
    (blen,) = struct.unpack(">I", head[:4])
    payload = b""
    while len(payload) < blen:
        payload += sock.recv(blen - len(payload))
    return head[4], payload


# ----------------------------------------------------------- basic contract
def test_negotiation_and_pipelined_roundtrip():
    server = RemoteStoreServer(InMemoryStoreManager()).start()
    mgr = _force_pipeline(RemoteStoreManager(*server.address))
    try:
        store = mgr.open_database("edgestore")
        store.mutate(b"k", [(b"a", b"1")], [], None)
        assert mgr._remote_pipeline is True
        got = store.get_slice(KeySliceQuery(b"k", SliceQuery(b"", None)), None)
        assert got == [(b"a", b"1")]
        # the ops actually rode pipelined frames
        assert mgr._mux is not None and mgr._mux._conns[0]._epoch is not None
        from janusgraph_tpu.observability import registry

        mgr._mux.flush_stats()
        snap = registry.snapshot()
        assert snap.get("storage.remote.pipeline.ops", {}).get("count", 0) >= 2
    finally:
        mgr.close()
        server.stop()


@pytest.mark.parametrize("trace", [True, False])
@pytest.mark.parametrize("ledger", [True, False])
@pytest.mark.parametrize("deadline", [True, False])
@pytest.mark.parametrize("pipeline", [True, False])
def test_wire_compat_matrix(trace, ledger, deadline, pipeline):
    """New client against every server feature-bit combination: the op
    stream stays byte-compatible, the client negotiates each capability
    independently, and un-negotiated bits are never sent."""
    from janusgraph_tpu.core.deadline import deadline_scope
    from janusgraph_tpu.observability import tracer
    from janusgraph_tpu.observability.profiler import ledger_scope

    server = RemoteStoreServer(
        InMemoryStoreManager(), trace_propagation=trace, ledger_echo=ledger,
        deadline_propagation=deadline, pipeline=pipeline,
    ).start()
    mgr = _force_pipeline(RemoteStoreManager(*server.address))
    try:
        store = mgr.open_database("edgestore")
        with tracer.span("compat.root"):
            with ledger_scope():
                with deadline_scope(5_000.0):
                    store.mutate(b"k", [(b"a", b"1")], [], None)
                    got = store.get_slice(
                        KeySliceQuery(b"k", SliceQuery(b"", None)), None
                    )
        assert got == [(b"a", b"1")]
        assert mgr._remote_trace is trace
        assert mgr._remote_ledger is ledger
        assert mgr._remote_deadline is deadline
        assert mgr._remote_pipeline is pipeline
    finally:
        mgr.close()
        server.stop()


def test_old_client_against_new_server():
    """The other direction: a pipeline-disabled client (byte-identical
    frames to a pre-pipeline client) interoperates with a new server."""
    server = RemoteStoreServer(InMemoryStoreManager()).start()
    mgr = RemoteStoreManager(*server.address, pipeline=False)
    try:
        store = mgr.open_database("edgestore")
        store.mutate(b"k", [(b"a", b"1")], [], None)
        got = store.get_slice(KeySliceQuery(b"k", SliceQuery(b"", None)), None)
        assert got == [(b"a", b"1")]
        assert mgr._mux is None  # the mux never engaged
    finally:
        mgr.close()
        server.stop()


def test_pipelined_frame_against_old_server_is_unknown_op():
    """A 0x10-flagged frame against a pipeline=False server behaves
    byte-identically to a real old server: unknown op, permanent."""
    server = RemoteStoreServer(InMemoryStoreManager(), pipeline=False).start()
    sock = socket.create_connection(server.address)
    try:
        body = struct.pack(">I", 1) + _gs_body("edgestore", b"k")
        sock.sendall(
            struct.pack(">IB", len(body), _OP_GET_SLICE | PIPELINE_FLAG)
            + body
        )
        status, payload = _recv_frame(sock)
        assert status == 2  # permanent, unflagged (old framing)
        assert b"unknown op" in payload
    finally:
        sock.close()
        server.stop()


# ------------------------------------------------- out-of-order completion
def test_out_of_order_completion_on_one_connection():
    """A batch carrier's sub-ops complete out of order: the fast op's
    response (by request id) arrives while the slow sibling is still
    blocked server-side."""
    release = threading.Event()
    entered = threading.Event()

    def hook(key):
        if key == b"slow":
            entered.set()
            assert release.wait(5.0)

    backing = _HookManager(InMemoryStoreManager(), hook)
    server = RemoteStoreServer(backing, pipeline_workers=4).start()
    sock = socket.create_connection(server.address)
    try:
        subs = []
        for rid, key in ((1, b"slow"), (2, b"fast")):
            sub_body = struct.pack(">I", rid) + _gs_body("edgestore", key)
            subs.append(
                struct.pack(
                    ">IB", len(sub_body), _OP_GET_SLICE | PIPELINE_FLAG
                ) + sub_body
            )
        body = struct.pack(">I", len(subs)) + b"".join(subs)
        sock.sendall(
            struct.pack(">IB", len(body), _OP_BATCH | PIPELINE_FLAG) + body
        )
        status, payload = _recv_frame(sock)
        assert status & PIPELINE_FLAG
        (rid,) = struct.unpack_from(">I", payload, 0)
        assert rid == 2, "fast op must complete before the blocked one"
        assert entered.is_set()
        release.set()
        status, payload = _recv_frame(sock)
        (rid,) = struct.unpack_from(">I", payload, 0)
        assert rid == 1
    finally:
        release.set()
        sock.close()
        server.stop()


# ----------------------------------------------------------------- merging
def test_coalesced_ops_merge_and_demux_per_op():
    """Same-slice getSlice ops queued together merge into ONE
    getSliceMulti wire frame; each caller still gets exactly its own
    key's entries. Mutates merge into one mutateMany."""
    server = RemoteStoreServer(InMemoryStoreManager()).start()
    mgr = _force_pipeline(RemoteStoreManager(*server.address))
    try:
        store = mgr.open_database("edgestore")
        for i in range(6):
            store.mutate(f"k{i}".encode(), [(b"c", str(i).encode())], [], None)
        mux = mgr._mux
        conn = mux._conns[0]
        ep = conn._epoch
        # build a queued batch by hand and encode it: deterministic merge
        from janusgraph_tpu.storage.pipeline import OpFuture, _Entry

        sl = struct.pack(">I", 0) + struct.pack(">I", 0) + struct.pack(">i", -1)
        entries = []
        for i in range(4):
            key = f"k{i}".encode()
            body = _gs_body("edgestore", key)
            item = WireOp(
                _OP_GET_SLICE, 0, b"", body,
                merge=("gs", "edgestore", key, sl),
            )
            e = _Entry(item, OpFuture())
            entries.append(e)
        buf, nops = conn._encode_batch(ep, entries)
        assert nops == 4
        # ONE wire frame, not a carrier of four: the merged multi
        raw_op = buf[4]
        assert raw_op & ~0xF0 == 3  # _OP_GET_SLICE_MULTI
        ep.sock.sendall(buf)
        deadline = time.monotonic() + 5.0
        while any(not e.fut.done() for e in entries):
            assert time.monotonic() < deadline
            conn._recv_one(ep)  # drive the receive loop ourselves
        for i, e in enumerate(entries):
            payload, fields = e.fut.result(1.0)
            from janusgraph_tpu.storage.remote import _Reader, _decode_entries

            got = _decode_entries(_Reader(payload))
            assert got == [(b"c", str(i).encode())]
            assert fields is None  # merged ops count client-side
        from janusgraph_tpu.observability import registry

        mgr._mux.flush_stats()
        snap = registry.snapshot()
        assert snap["storage.remote.pipeline.merged_ops"]["count"] >= 4
    finally:
        mgr.close()
        server.stop()


def test_threaded_pipelined_correctness_and_coalescing():
    """16 threads of mixed reads/writes over the pipelined path: every
    op's result is exact, and the wire carried fewer frames than ops
    (coalescing engaged)."""
    server = RemoteStoreServer(InMemoryStoreManager()).start()
    mgr = _force_pipeline(RemoteStoreManager(*server.address))
    errs = []

    def worker(i):
        try:
            store = mgr.open_database("edgestore")
            for j in range(40):
                k = f"w{i}-{j:02d}".encode()
                store.mutate(k, [(b"c", str(j).encode())], [], None)
                got = store.get_slice(
                    KeySliceQuery(k, SliceQuery(b"", None)), None
                )
                assert got == [(b"c", str(j).encode())], got
        except Exception as e:  # noqa: BLE001 - surfaced via errs
            errs.append(e)

    try:
        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(16)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert errs == []
        from janusgraph_tpu.observability import registry

        mgr._mux.flush_stats()
        snap = registry.snapshot()
        ops = snap["storage.remote.pipeline.ops"]["count"]
        frames = snap["storage.remote.pipeline.wire_frames"]["count"]
        assert ops >= 16 * 40 * 2
        assert frames <= ops  # never more frames than ops
    finally:
        mgr.close()
        server.stop()


# ------------------------------------------------------------- deadlines
def test_per_op_deadline_expiry_mid_pipeline():
    """An op whose budget is spent while a slow sibling holds the
    server's (single) pipeline worker is refused by the server with a
    permanent deadline error — and the sibling completes fine."""
    release = threading.Event()

    def hook(key):
        if key == b"slow":
            assert release.wait(5.0)

    backing = _HookManager(InMemoryStoreManager(), hook)
    server = RemoteStoreServer(backing, pipeline_workers=1).start()
    sock = socket.create_connection(server.address)
    try:
        from janusgraph_tpu.storage.remote import (
            _DEADLINE_FLAG,
            encode_deadline_prefix,
        )

        subs = []
        sub1 = struct.pack(">I", 1) + _gs_body("edgestore", b"slow")
        subs.append(struct.pack(
            ">IB", len(sub1), _OP_GET_SLICE | PIPELINE_FLAG) + sub1)
        # 80 ms budget, queued behind a ~300 ms sibling
        sub2 = (struct.pack(">I", 2) + encode_deadline_prefix(80.0)
                + _gs_body("edgestore", b"fast"))
        subs.append(struct.pack(
            ">IB", len(sub2),
            _OP_GET_SLICE | _DEADLINE_FLAG | PIPELINE_FLAG) + sub2)
        body = struct.pack(">I", 2) + b"".join(subs)
        sock.sendall(
            struct.pack(">IB", len(body), _OP_BATCH | PIPELINE_FLAG) + body
        )
        time.sleep(0.3)
        release.set()
        replies = {}
        for _ in range(2):
            status, payload = _recv_frame(sock)
            (rid,) = struct.unpack_from(">I", payload, 0)
            replies[rid] = (status & 0x0F, payload[4:])
        assert replies[1][0] == 0  # the slow sibling completed OK
        assert replies[2][0] == 2  # permanent: never replayed
        assert b"Deadline" in replies[2][1] or b"deadline" in replies[2][1]
    finally:
        release.set()
        sock.close()
        server.stop()


def test_deadline_expired_in_send_queue_client_side():
    """An op whose deadline lapses before the pipelined send is refused
    client-side (counter + DeadlineExceededError), no wire dispatch."""
    server = RemoteStoreServer(InMemoryStoreManager()).start()
    mgr = _force_pipeline(RemoteStoreManager(*server.address))
    try:
        mux = mgr._mux_for(_OP_GET_SLICE)
        from janusgraph_tpu.storage.remote import _DEADLINE_FLAG

        item = WireOp(
            _OP_GET_SLICE, _DEADLINE_FLAG, b"",
            _gs_body("edgestore", b"k"),
            expires_at=time.monotonic() - 0.001,
        )
        fut = mux.submit(item)
        with pytest.raises(DeadlineExceededError):
            fut.result(2.0)
    finally:
        mgr.close()
        server.stop()


# ------------------------------------------------------ faults and breaker
def test_fault_mid_pipeline_fails_only_its_op_and_breaker_counts_one():
    """A serving-node fault on one in-flight op: the sibling completes,
    the failed op surfaces its own error, and the client breaker counts
    exactly that op (stays CLOSED below threshold)."""
    from janusgraph_tpu.storage.circuit import CLOSED, OPEN

    def hook(key):
        if key == b"bad":
            raise TemporaryBackendError("injected serving-node fault")

    backing = _HookManager(InMemoryStoreManager(), hook)
    server = RemoteStoreServer(backing).start()
    mgr = _force_pipeline(RemoteStoreManager(
        *server.address, max_attempts=1, retry_time_s=0.2,
        breaker_enabled=True, breaker_failure_threshold=2,
        breaker_reset_ms=10_000.0,
    ))
    try:
        store = mgr.open_database("edgestore")
        store.mutate(b"good", [(b"a", b"1")], [], None)
        results = {}

        def read(key):
            try:
                results[key] = store.get_slice(
                    KeySliceQuery(key, SliceQuery(b"", None)), None
                )
            except Exception as e:  # noqa: BLE001 - asserted below
                results[key] = e

        threads = [
            threading.Thread(target=read, args=(k,))
            for k in (b"good", b"bad", b"good")
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert results[b"good"] == [(b"a", b"1")]
        assert isinstance(results[b"bad"], TemporaryBackendError)
        # ONE failed op = AT MOST one breaker failure (threshold 2 not
        # reached): the carrier frame did not multiply the event
        assert mgr.breaker.state == CLOSED
        # consecutive bad ops trip it: per-op accounting, not per-frame
        from janusgraph_tpu.exceptions import CircuitOpenError

        for _ in range(2):
            with pytest.raises(
                (TemporaryBackendError, CircuitOpenError, PermanentBackendError)
            ):
                store.get_slice(
                    KeySliceQuery(b"bad", SliceQuery(b"", None)), None
                )
        assert mgr.breaker.state == OPEN
    finally:
        mgr.close()
        server.stop()


def test_connection_loss_fails_inflight_and_recovers():
    """Killing the server fails every in-flight pipelined op with a
    temporary error; the retry guard replays against the restarted
    server over a fresh epoch."""
    backing = InMemoryStoreManager()
    server = RemoteStoreServer(backing).start()
    host, port = server.address
    mgr = _force_pipeline(RemoteStoreManager(host, port, retry_time_s=8.0))
    try:
        store = mgr.open_database("edgestore")
        store.mutate(b"k", [(b"a", b"1")], [], None)
        server.stop()

        def restart():
            time.sleep(0.4)
            RemoteStoreServer(backing, host=host, port=port).start()

        threading.Thread(target=restart, daemon=True).start()
        got = store.get_slice(KeySliceQuery(b"k", SliceQuery(b"", None)), None)
        assert got == [(b"a", b"1")]
    finally:
        mgr.close()


# ---------------------------------------------------------- adaptive gate
def test_adaptive_gate_keeps_sequential_callers_on_sync_path():
    """A sequential caller never engages the mux (zero extra cost), and
    a fast backend stays sync even under concurrency."""
    server = RemoteStoreServer(InMemoryStoreManager()).start()
    mgr = RemoteStoreManager(*server.address)
    try:
        store = mgr.open_database("edgestore")
        for i in range(20):
            store.mutate(f"k{i}".encode(), [(b"a", b"1")], [], None)
        assert mgr._mux is None  # never engaged
        assert not mgr._should_pipeline()
    finally:
        mgr.close()
        server.stop()


def test_adaptive_gate_engages_on_latency_dominated_concurrency():
    def hook(_key):
        time.sleep(0.002)

    backing = _HookManager(InMemoryStoreManager(), hook)
    server = RemoteStoreServer(backing, pipeline_workers=16).start()
    mgr = RemoteStoreManager(*server.address)
    try:
        store = mgr.open_database("edgestore")

        def worker(i):
            for j in range(8):
                store.mutate(f"g{i}-{j}".encode(), [(b"a", b"1")], [], None)
                store.get_slice(
                    KeySliceQuery(f"g{i}-{j}".encode(), SliceQuery(b"", None)),
                    None,
                )

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(12)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert mgr._op_ewma_s > mgr._PIPELINE_LATENCY_GATE_S
        from janusgraph_tpu.observability import registry

        if mgr._mux is not None:
            mgr._mux.flush_stats()
        snap = registry.snapshot()
        assert snap.get(
            "storage.remote.pipeline.ops", {}
        ).get("count", 0) > 0, "mux should have engaged under latency"
    finally:
        mgr.close()
        server.stop()


# -------------------------------------------------- observability plumbing
def test_trace_and_ledger_attribute_to_individual_pipelined_ops():
    from janusgraph_tpu.observability import tracer
    from janusgraph_tpu.observability.profiler import ledger_scope

    server = RemoteStoreServer(InMemoryStoreManager()).start()
    mgr = _force_pipeline(RemoteStoreManager(*server.address))
    try:
        store = mgr.open_database("edgestore")
        with tracer.span("pipe.root") as root:
            with ledger_scope() as led:
                store.mutate(b"k", [(b"a", b"12345")], [], None)
                store.get_slice(
                    KeySliceQuery(b"k", SliceQuery(b"", None)), None
                )
        assert led.to_dict().get("cells_read", 0) >= 1  # echo merged
        deadline = time.monotonic() + 2.0
        names = set()
        while time.monotonic() < deadline:
            names = {
                s.name for s in tracer.find_trace(root.trace_id)
                if s.name.startswith("store.remote.")
            }
            if len(names) >= 2:
                break
            time.sleep(0.01)
        assert {"store.remote.mutate", "store.remote.getSlice"} <= names
    finally:
        mgr.close()
        server.stop()


def test_healthz_pipeline_block():
    server = RemoteStoreServer(InMemoryStoreManager()).start()
    mgr = _force_pipeline(RemoteStoreManager(*server.address))
    try:
        store = mgr.open_database("edgestore")
        store.mutate(b"k", [(b"a", b"1")], [], None)
        from janusgraph_tpu.server.server import healthz_snapshot

        mgr._mux.flush_stats()
        block = healthz_snapshot()["pipeline"]
        assert "storage.remote" in block
        entry = block["storage.remote"]
        assert entry["ops"] >= 1
        assert entry["wire_frames"] >= 1
        assert "coalesce_ratio" in entry
        assert "in_flight" in entry
    finally:
        mgr.close()
        server.stop()


def test_negotiation_fallback_flight_event():
    from janusgraph_tpu.observability import flight_recorder

    flight_recorder.reset()
    server = RemoteStoreServer(InMemoryStoreManager(), pipeline=False).start()
    mgr = RemoteStoreManager(*server.address)
    mgr._should_pipeline = lambda: True  # want pipelining; server refuses
    try:
        store = mgr.open_database("edgestore")
        store.mutate(b"k", [(b"a", b"1")], [], None)
        events = flight_recorder.events("pipeline_fallback")
        assert events and events[0]["protocol"] == "storage.remote"
    finally:
        mgr.close()
        server.stop()


# ------------------------------------------------------------ index tier
def test_index_pipelined_queries_and_capability_byte():
    from janusgraph_tpu.indexing.memindex import InMemoryIndexProvider
    from janusgraph_tpu.indexing.provider import (
        IndexQuery,
        KeyInformation,
        Mapping,
        PredicateCondition,
    )
    from janusgraph_tpu.core.predicates import predicate_by_name
    from janusgraph_tpu.indexing.remote import (
        RemoteIndexProvider,
        RemoteIndexServer,
    )

    server = RemoteIndexServer(InMemoryIndexProvider()).start()
    host, port = server.address
    client = RemoteIndexProvider(hostname=host, port=port)
    client._should_pipeline = lambda: True
    try:
        info = KeyInformation(str, Mapping.STRING, "SINGLE")
        client.register("vidx", "name", info)
        from janusgraph_tpu.indexing.provider import IndexEntry, IndexMutation

        m = IndexMutation(is_new=True)
        m.additions.append(IndexEntry("name", "hercules"))
        client.mutate({"vidx": {"d1": m}}, {"vidx": {"name": info}})
        assert client._remote_pipeline is True
        q = IndexQuery(
            PredicateCondition("name", predicate_by_name("eq"), "hercules")
        )
        hits = client.query("vidx", q)
        assert hits == ["d1"]
        from janusgraph_tpu.observability import registry

        client._mux.flush_stats()
        snap = registry.snapshot()
        assert snap.get(
            "index.remote.pipeline.ops", {}
        ).get("count", 0) >= 1
    finally:
        client.close()
        server.stop()


def test_index_old_featured_server_negotiates_pipeline_off():
    from janusgraph_tpu.indexing.memindex import InMemoryIndexProvider
    from janusgraph_tpu.indexing.remote import (
        RemoteIndexProvider,
        RemoteIndexServer,
    )

    server = RemoteIndexServer(
        InMemoryIndexProvider(), pipeline=False
    ).start()
    host, port = server.address
    client = RemoteIndexProvider(hostname=host, port=port)
    try:
        client.features()
        assert client._remote_pipeline is False
        assert client.exists() in (True, False)  # plain op unaffected
    finally:
        client.close()
        server.stop()


# -------------------------------------------------------- driver WS mux
def test_ws_multiplexed_submits_share_one_socket():
    from janusgraph_tpu.core.graph import open_graph
    from janusgraph_tpu.driver import JanusGraphClient
    from janusgraph_tpu.server import JanusGraphManager, JanusGraphServer

    graph = open_graph({"storage.backend": "inmemory"})
    tx = graph.new_transaction()
    ids = [tx.add_vertex(name=f"v{i}").id for i in range(8)]
    tx.commit()
    manager = JanusGraphManager()
    manager.put_graph("graph", graph)
    server = JanusGraphServer(manager=manager, admission_enabled=False).start()
    try:
        client = JanusGraphClient(port=server.port)
        ws = client.ws(multiplex=True)
        results = {}
        errs = []

        def worker(i):
            try:
                results[i] = ws.submit(f"g.V({ids[i]}).values('name')")
            except Exception as e:  # noqa: BLE001 - surfaced below
                errs.append(e)

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert errs == []
        for i in range(8):
            assert results[i] == [f"v{i}"]
        ws.close()
        # serial (non-multiplexed) session still works against the same
        # server — the old driver behavior
        ws2 = client.ws(multiplex=False)
        assert ws2.submit(f"g.V({ids[0]}).values('name')") == ["v0"]
        ws2.close()
    finally:
        server.stop()
        graph.close()


# --------------------------------------------- e2e throughput acceptance
def test_threaded_e2e_pipelined_beats_sync_under_storage_latency():
    """The acceptance shape: against a storage node with real (simulated
    2 ms) per-op service time and the DEFAULT connection budgets, the
    pipelined path sustains well above the synchronous framing — many
    in-flight ops share few sockets instead of convoying on the pool."""
    def hook(_key):
        time.sleep(0.002)

    def run(pipeline):
        backing = _HookManager(InMemoryStoreManager(), hook)
        server = RemoteStoreServer(backing, pipeline_workers=48).start()
        mgr = RemoteStoreManager(*server.address, pipeline=pipeline)
        store = mgr.open_database("edgestore")
        errs = []

        def worker(i):
            try:
                for j in range(10):
                    k = f"t{i}-{j}".encode()
                    store.mutate(k, [(b"c", b"v")], [], None)
                    got = store.get_slice(
                        KeySliceQuery(k, SliceQuery(b"", None)), None
                    )
                    assert got == [(b"c", b"v")]
            except Exception as e:  # noqa: BLE001 - surfaced below
                errs.append(e)

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(24)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        mgr.close()
        server.stop()
        assert errs == []
        return wall

    sync_wall = run(False)
    pipe_wall = run(True)
    # measured ~3x on this host; 1.4x keeps the assertion robust to CI
    # noise while still proving the protocol does its job
    assert pipe_wall * 1.4 < sync_wall, (
        f"pipelined {pipe_wall:.2f}s vs sync {sync_wall:.2f}s"
    )
