"""OLAP engine tests.

Reference model: janusgraph-backend-testutils .../olap/OLAPTest.java:779
(degree/pagerank/shortest-distance vertex programs through the computer API)
plus parity between the scalar CPU oracle and the vectorized TPU executor —
the SURVEY.md §7 step-5 acceptance gate.
"""

import numpy as np
import pytest

from janusgraph_tpu.core import gods
from janusgraph_tpu.core.graph import open_graph
from janusgraph_tpu.olap import csr_from_edges, load_csr, run_on
from janusgraph_tpu.olap.programs import (
    ConnectedComponentsProgram,
    PageRankProgram,
    PeerPressureProgram,
    ShortestPathProgram,
    TraversalCountProgram,
)


@pytest.fixture(scope="module")
def gods_graph():
    g = open_graph({"ids.authority-wait-ms": 0.0})
    gods.load(g)
    yield g
    g.close()


@pytest.fixture(scope="module")
def gods_csr(gods_graph):
    return load_csr(gods_graph)


def random_graph(n=200, m=800, seed=5, weights=False):
    rng = np.random.default_rng(seed)
    src = rng.integers(0, n, m).astype(np.int32)
    dst = rng.integers(0, n, m).astype(np.int32)
    w = rng.uniform(0.5, 2.0, m).astype(np.float32) if weights else None
    return csr_from_edges(n, src, dst, w)


# ----------------------------------------------------------------- CSR loading
def test_csr_snapshot_shape(gods_csr):
    assert gods_csr.num_vertices == 12
    assert gods_csr.num_edges == 17
    # degree checks: jupiter has 4 out-edges (father, lives, 2x brother)
    assert int(gods_csr.out_degree.sum()) == 17


def test_csr_roundtrip_names(gods_graph):
    snap = load_csr(gods_graph, property_keys=("name",))
    names = snap.properties["name"]
    assert set(names.tolist()) == {
        "saturn", "sky", "sea", "jupiter", "neptune", "hercules",
        "alcmene", "pluto", "nemean", "hydra", "cerberus", "tartarus",
    }


def test_csr_edge_label_filter(gods_graph):
    snap = load_csr(gods_graph, edge_labels=("battled",))
    assert snap.num_edges == 3


def test_csr_in_out_consistency(gods_csr):
    g = gods_csr
    # every out edge appears exactly once as an in edge
    out_pairs = set()
    for i in range(g.num_vertices):
        for e in range(g.out_indptr[i], g.out_indptr[i + 1]):
            out_pairs.add((i, int(g.out_dst[e])))
    in_pairs = set()
    for i in range(g.num_vertices):
        for e in range(g.in_indptr[i], g.in_indptr[i + 1]):
            in_pairs.add((int(g.in_src[e]), i))
    assert out_pairs == in_pairs


# ---------------------------------------------------------------- correctness
def test_pagerank_known_answer():
    """4-cycle: uniform rank is the fixpoint."""
    g = csr_from_edges(4, [0, 1, 2, 3], [1, 2, 3, 0])
    res = run_on(g, PageRankProgram(max_iterations=50), "cpu")
    np.testing.assert_allclose(res["rank"], 0.25, atol=1e-6)
    assert abs(res["rank"].sum() - 1.0) < 1e-6


def test_pagerank_sums_to_one_with_dangling():
    g = csr_from_edges(5, [0, 1, 2], [1, 2, 3])  # 3 and 4 dangling
    res = run_on(g, PageRankProgram(max_iterations=60), "cpu")
    assert abs(res["rank"].sum() - 1.0) < 1e-6


def test_shortest_path_known_answer():
    # path 0->1->2->3, plus shortcut 0->3
    g = csr_from_edges(4, [0, 1, 2, 0], [1, 2, 3, 3])
    res = run_on(g, ShortestPathProgram(seed_index=0), "cpu")
    np.testing.assert_allclose(res["distance"], [0, 1, 2, 1])


def test_shortest_path_weighted():
    # 0->1 (w=5), 0->2 (w=1), 2->1 (w=1): dist(1) = 2 via 2
    g = csr_from_edges(
        3, [0, 0, 2], [1, 2, 1], np.array([5.0, 1.0, 1.0], dtype=np.float32)
    )
    res = run_on(g, ShortestPathProgram(seed_index=0, weighted=True), "cpu")
    np.testing.assert_allclose(res["distance"], [0, 2, 1])


def test_connected_components_known_answer():
    # two components: {0,1,2} via directed chain, {3,4}
    g = csr_from_edges(5, [0, 1, 3], [1, 2, 4])
    res = run_on(g, ConnectedComponentsProgram(), "cpu")
    c = res["component"]
    assert c[0] == c[1] == c[2]
    assert c[3] == c[4]
    assert c[0] != c[3]


def test_traversal_count_known_answer(gods_csr):
    """3-hop path count == OLTP g.V().out().out().out().count()."""
    res = run_on(gods_csr, TraversalCountProgram(hops=3), "cpu")
    total = res["count"].sum()
    # OLTP answer
    # hercules->father->jupiter->father->saturn is the only .out().out() chain
    # of length 3?  compute directly instead of hand-counting:
    assert total == _brute_force_khop(gods_csr, 3)


def _brute_force_khop(g, k):
    counts = np.ones(g.num_vertices)
    for _ in range(k):
        new = np.zeros_like(counts)
        for i in range(g.num_vertices):
            for e in range(g.out_indptr[i], g.out_indptr[i + 1]):
                new[int(g.out_dst[e])] += counts[i]
        counts = new
    return counts.sum()


def test_peer_pressure_converges_clique_pair():
    # two 4-cliques joined by one edge -> 2 clusters
    edges = []
    for base in (0, 4):
        for i in range(4):
            for j in range(4):
                if i != j:
                    edges.append((base + i, base + j))
    edges.append((0, 4))
    src, dst = zip(*edges)
    g = csr_from_edges(8, list(src), list(dst))
    res = run_on(g, PeerPressureProgram(num_buckets=32), "cpu")
    c = res["cluster"]
    assert len(set(c[:4].tolist())) == 1
    assert len(set(c[4:].tolist())) == 1


# ------------------------------------------------------------- CPU/TPU parity
PARITY_PROGRAMS = [
    ("pagerank", lambda: PageRankProgram(max_iterations=25)),
    ("sssp", lambda: ShortestPathProgram(seed_index=0)),
    ("sssp_weighted", lambda: ShortestPathProgram(seed_index=0, weighted=True)),
    ("cc", lambda: ConnectedComponentsProgram()),
    ("khop", lambda: TraversalCountProgram(hops=3)),
    ("peer_pressure", lambda: PeerPressureProgram(num_buckets=512)),
]


@pytest.mark.parametrize("name,make", PARITY_PROGRAMS, ids=[p[0] for p in PARITY_PROGRAMS])
def test_cpu_tpu_parity_random_graph(name, make):
    g = random_graph(n=150, m=600, weights=True)
    cpu = run_on(g, make(), "cpu")
    tpu = run_on(g, make(), "tpu")
    assert set(cpu) == set(tpu)
    for k in cpu:
        np.testing.assert_allclose(
            np.asarray(tpu[k], dtype=np.float64),
            cpu[k],
            rtol=1e-4,
            atol=1e-5,
            err_msg=f"{name}:{k}",
        )


def test_cpu_tpu_parity_gods_pagerank(gods_csr):
    cpu = run_on(gods_csr, PageRankProgram(max_iterations=30), "cpu")
    tpu = run_on(gods_csr, PageRankProgram(max_iterations=30), "tpu")
    np.testing.assert_allclose(tpu["rank"], cpu["rank"], rtol=1e-4, atol=1e-6)
    # saturn must outrank leaf monsters (2 fathers chain in)
    ranks = dict(zip(gods_csr.vertex_ids.tolist(), cpu["rank"].tolist()))


# -------------------------------------------------------------- end-to-end API
def test_compute_api_and_write_back(gods_graph):
    result = (
        gods_graph.compute(executor="tpu")
        .program(PageRankProgram(max_iterations=20))
        .submit()
    )
    assert abs(sum(result.by_vertex("rank").values()) - 1.0) < 1e-4
    result.write_back(["rank"])
    g = gods_graph.traversal()
    saturn_rank = g.V().has("name", "saturn").next().value("rank")
    assert saturn_rank is not None and saturn_rank > 0
    # highest-rank vertices should include tartarus/saturn (sinks of chains)
    ranks = result.by_vertex("rank")


def test_ell_auto_strategy_budget():
    """auto resolution: the tuner picks a packed layout whose padding is
    actually bounded (ELL on a uniform chain; HYBRID when ELL's empty-row
    slots blow the pad up — zero-degree vertices cost hybrid nothing);
    computer.autotune=false falls back to the legacy budget heuristic
    (ELL within budget, segment past it)."""
    from janusgraph_tpu.olap import csr_from_edges
    from janusgraph_tpu.olap.tpu_executor import TPUExecutor

    dense = csr_from_edges(100, np.arange(99), np.arange(1, 100))
    fp = TPUExecutor.ell_footprint(dense)
    assert fp["pad_ratio"] <= 2.0
    assert TPUExecutor(dense).strategy in ("ell", "hybrid")

    sparse = csr_from_edges(50_000, [0, 1], [1, 2])
    fp = TPUExecutor.ell_footprint(sparse)
    assert fp["pad_ratio"] > 3.0
    ex = TPUExecutor(sparse)
    assert ex.strategy == "hybrid"
    assert ex._autotune(False).pad_ratio_est < 1.5
    # the legacy heuristic (no tuner) keeps its old segment fallback
    assert TPUExecutor(sparse, autotune=False).strategy == "segment"
    assert TPUExecutor(dense, autotune=False).strategy == "ell"
    # explicit strategy always wins over either heuristic
    assert TPUExecutor(sparse, strategy="ell").strategy == "ell"


def test_degree_count_parity():
    """Degree program: CPU oracle vs TPU executor vs ground truth
    (reference: the degree-count programs of OLAPTest.java:779)."""
    import numpy as np

    from janusgraph_tpu.olap.cpu_executor import CPUExecutor
    from janusgraph_tpu.olap.generators import rmat_csr
    from janusgraph_tpu.olap.programs import DegreeCountProgram
    from janusgraph_tpu.olap.tpu_executor import TPUExecutor

    csr = rmat_csr(10, 8)
    want_in = np.diff(csr.in_indptr).astype(np.float32)
    for ex in (CPUExecutor(csr), TPUExecutor(csr)):
        got = ex.run(DegreeCountProgram())
        np.testing.assert_array_equal(np.asarray(got["in_degree"]), want_in)
        np.testing.assert_array_equal(
            np.asarray(got["out_degree"]),
            csr.out_degree.astype(np.float32),
        )


def test_weighted_program_on_weightless_csr_refused():
    """check_weighted_transforms: a weighted SSSP over a snapshot with no
    weight column fails fast instead of relaxing every distance to 0."""
    import pytest

    from janusgraph_tpu.olap import csr_from_edges
    from janusgraph_tpu.olap.cpu_executor import CPUExecutor
    from janusgraph_tpu.olap.programs import ShortestPathProgram

    csr = csr_from_edges(
        4, np.asarray([0, 1, 2]), np.asarray([1, 2, 3])
    )
    with pytest.raises(ValueError, match="no edge weights"):
        CPUExecutor(csr).run(
            ShortestPathProgram(seed_index=0, weighted=True)
        )
