"""ID placement strategies (reference: placement/PropertyPlacementStrategy
.java:110, SimpleBulkPlacementStrategy.java:130): property-hash co-location
vs round-robin spread, wired through ids.placement config.
"""

import pytest

from janusgraph_tpu.core.graph import open_graph
from janusgraph_tpu.core.placement import (
    PropertyPlacementStrategy,
    SimpleBulkPlacementStrategy,
    make_placement_strategy,
    stable_hash,
)
from janusgraph_tpu.exceptions import ConfigurationError


def test_simple_spreads_round_robin():
    s = SimpleBulkPlacementStrategy()
    got = [s.partition_for(None, None, 4) for _ in range(8)]
    assert got == [0, 1, 2, 3, 0, 1, 2, 3]


def test_property_colocates_same_value():
    s = PropertyPlacementStrategy("region")
    parts = {
        s.partition_for(None, {"region": "emea"}, 32) for _ in range(10)
    }
    assert len(parts) == 1
    # missing key falls back to spread (round robin over calls)
    a = s.partition_for(None, {}, 4)
    b = s.partition_for(None, {}, 4)
    assert (a, b) == (0, 1)


def test_stable_hash_is_process_independent():
    assert stable_hash("emea") == stable_hash("emea")
    assert stable_hash(b"x") == stable_hash(b"x")
    assert stable_hash(42) == stable_hash(42)


def test_graph_level_property_placement():
    g = open_graph({
        "ids.placement": "property",
        "ids.placement-key": "region",
        "schema.default": "auto",
    })
    tx = g.new_transaction()
    emea = [tx.add_vertex(region="emea", name=f"e{i}") for i in range(6)]
    apac = [tx.add_vertex(region="apac", name=f"a{i}") for i in range(6)]
    tx.commit()
    p_emea = {g.idm.get_partition_id(v.id) for v in emea}
    p_apac = {g.idm.get_partition_id(v.id) for v in apac}
    assert len(p_emea) == 1, "same region value must co-locate"
    assert len(p_apac) == 1
    g.close()


def test_property_strategy_requires_key():
    with pytest.raises(ConfigurationError):
        make_placement_strategy("property", "")
    with pytest.raises(ConfigurationError):
        make_placement_strategy("nope")


def test_default_graph_keeps_round_robin_spread():
    g = open_graph()
    tx = g.new_transaction()
    vs = [tx.add_vertex() for _ in range(8)]
    tx.commit()
    parts = [g.idm.get_partition_id(v.id) for v in vs]
    assert len(set(parts)) > 1  # spread, not all in one partition
    g.close()
