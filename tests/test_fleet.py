"""Fault-tolerant serving fleet (ISSUE 15): router, drain, gossip, warm-up.

Covers the acceptance list:

- router hash/least-loaded selection determinism (offline, injected
  health),
- sticky-session pinning + drain handoff (zero lost sessions),
- retry-elsewhere under shed with retry-budget accounting,
- gossip convergence on a fake clock (bounded rounds, no threads),
- warm-up-from-checkpoint byte-equivalence vs a scanned snapshot with
  zero edgestore reads,
- the 3-replica chaos cell: kill one replica mid-traffic, zero errors to
  well-budgeted callers, goodput >= 0.6x pre-kill during failover,
- the new seeded fleet fault kinds (deterministic, journal-reproducible),
- per-replica identity threading (flight / logs / metrics / healthz),
- the warm-submit executor cache (PR 14 REMAINING) and its invalidation.
"""

from __future__ import annotations

import json
import threading
import time

import numpy as np
import pytest

from janusgraph_tpu.core.graph import JanusGraphTPU
from janusgraph_tpu.driver.client import RemoteError
from janusgraph_tpu.server import (
    FleetFrontend,
    FleetRouter,
    JanusGraphManager,
    JanusGraphServer,
    StateGossip,
)
from janusgraph_tpu.server.admission import AdmissionController
from janusgraph_tpu.server.fleet import (
    DEAD,
    NoReplicaAvailable,
    SERVING,
    export_snapshot,
    warm_replica,
)
from janusgraph_tpu.storage.faults import FaultPlan
from janusgraph_tpu.storage.inmemory import InMemoryStoreManager

BASE_CFG = {"ids.authority-wait-ms": 0.0, "locks.wait-ms": 0.0}


def _offline_router(**kw):
    """A router whose probes never touch the network."""
    kw.setdefault("fetch", lambda url, timeout: {})
    return FleetRouter(**kw)


def _seed_graph(graph, n=32):
    graph.management().make_edge_label("knows")
    tx = graph.new_transaction()
    ids = [tx.add_vertex().id for _ in range(n)]
    for i in range(n):
        tx.add_edge(
            tx.get_vertex(ids[i]), "knows",
            tx.get_vertex(ids[(i * 7 + 1) % n]),
        )
    tx.commit()
    return ids


# ---------------------------------------------------------------------------
# router selection
# ---------------------------------------------------------------------------

class TestRouterSelection:
    def test_consistent_hash_is_deterministic(self):
        r1 = _offline_router()
        r2 = _offline_router()
        for r in (r1, r2):
            for i in range(4):
                r.add_replica(f"r{i}", "127.0.0.1", 9000 + i)
        for key in ("a", "b", "digest-xyz", "42", ""):
            names1 = [h.name for h in r1.candidates_for(key)]
            names2 = [h.name for h in r2.candidates_for(key)]
            assert names1 == names2
            # every serving replica appears exactly once (failover tail)
            assert sorted(names1) == ["r0", "r1", "r2", "r3"]

    def test_keys_spread_across_replicas(self):
        r = _offline_router()
        for i in range(4):
            r.add_replica(f"r{i}", "127.0.0.1", 9000 + i)
        first = {
            r.candidates_for(str(k))[0].name for k in range(64)
        }
        assert len(first) == 4, "vnode ring failed to spread keys"

    def test_least_loaded_tie_break_uses_admission_block(self):
        r = _offline_router(candidates=2)
        for i in range(2):
            r.add_replica(f"r{i}", "127.0.0.1", 9000 + i)
        key = next(
            k for k in range(256)
            if r.candidates_for(str(k))[0].name == "r0"
        )
        # saturate r0's admission block: the tie-break must now prefer r1
        r.replicas()["r0"].health = {
            "status": "ok",
            "admission": {"limit": 8, "in_flight": 8, "queue_depth": 4,
                          "queue_bound": 8, "brownout_rung": 2},
            "slo": {"paging": []},
        }
        r.replicas()["r1"].health = {
            "status": "ok",
            "admission": {"limit": 8, "in_flight": 0, "queue_depth": 0,
                          "queue_bound": 8, "brownout_rung": 0},
            "slo": {"paging": []},
        }
        assert r.candidates_for(str(key))[0].name == "r1"

    def test_slo_burn_weighs_into_load_score(self):
        r = _offline_router()
        r.add_replica("r0", "127.0.0.1", 9000)
        h = r.replicas()["r0"]
        h.health = {"status": "ok", "admission": {}, "slo": {"paging": []}}
        base = h.load_score()
        h.health = {
            "status": "ok", "admission": {},
            "slo": {"paging": ["availability"]},
        }
        assert h.load_score() > base

    def test_dead_and_draining_replicas_are_skipped(self):
        r = _offline_router()
        for i in range(3):
            r.add_replica(f"r{i}", "127.0.0.1", 9000 + i)
        r.mark_dead("r0")
        r.replicas()["r1"].state = "draining"
        for k in range(16):
            assert r.candidates_for(str(k))[0].name == "r2"

    def test_routing_key_strips_literals(self):
        k1 = FleetRouter.routing_key("g.V(1).out('knows').count()")
        k2 = FleetRouter.routing_key("g.V(999).out('knows').count()")
        k3 = FleetRouter.routing_key("g.V(1).in('knows').count()")
        assert k1 == k2 and k1 != k3


# ---------------------------------------------------------------------------
# retry-elsewhere + budget accounting (offline, injected clients)
# ---------------------------------------------------------------------------

class _FakeClient:
    def __init__(self, behavior):
        self.behavior = behavior  # name -> callable or value
        self.calls = 0

    def submit(self, query, graph=None, deadline_ms=None):
        self.calls += 1
        out = self.behavior()
        if isinstance(out, Exception):
            raise out
        return out


class TestRetryElsewhere:
    def _router(self, behaviors, **kw):
        clients = {}

        def factory(handle):
            clients[handle.name] = _FakeClient(behaviors[handle.name])
            return clients[handle.name]

        kw.setdefault("backoff_base_s", 0.001)
        kw.setdefault("backoff_max_s", 0.002)
        r = _offline_router(client_factory=factory, **kw)
        for name in behaviors:
            r.add_replica(name, "127.0.0.1", 9000)
        return r, clients

    def test_shed_retries_on_another_replica(self):
        shed = RemoteError(503, "shed", status="shed",
                           retry_after_s=0.001)
        behaviors = {"r0": lambda: shed, "r1": lambda: 7,
                     "r2": lambda: 7}
        r, clients = self._router(behaviors)
        from janusgraph_tpu.observability import registry

        before = registry.get_count("fleet.router.retries")
        for k in range(8):
            assert r.submit("q", key=str(k)) == 7
        assert registry.get_count("fleet.router.retries") > before
        # the shedding replica was tried and abandoned, never looped on
        assert clients.get("r0") is None or clients["r0"].calls <= 8

    def test_budget_exhaustion_surfaces_the_error(self):
        shed = RemoteError(503, "shed", status="shed",
                           retry_after_s=0.001)
        behaviors = {"r0": lambda: shed, "r1": lambda: shed}
        r, _clients = self._router(
            behaviors, retry_budget_capacity=1.0,
            retry_budget_refill_per_s=0.0,
        )
        with pytest.raises(NoReplicaAvailable):
            r.submit("q", key="k")
        assert r.retry_budget.tokens < 1.0

    def test_connect_failure_marks_replica_dead_and_fails_over(self):
        behaviors = {
            "r0": lambda: ConnectionRefusedError("refused"),
            "r1": lambda: 42,
        }
        r, _clients = self._router(behaviors)
        # two consecutive connect failures = dead (crash detection)
        assert r.submit("q", key="a") == 42
        assert r.submit("q", key="b") == 42
        dead_after = 0
        for k in range(6):
            assert r.submit("q", key=str(k)) == 42
            if r.replicas()["r0"].state == DEAD:
                dead_after += 1
        assert r.replicas()["r0"].state == DEAD
        # flight event distinguishes crash from drain
        from janusgraph_tpu.observability import flight_recorder

        deaths = [
            e for e in flight_recorder.events("fleet")
            if e.get("action") == "dead" and e.get("replica") == "r0"
        ]
        assert deaths and deaths[-1]["reason"] in ("connect", "probe")

    def test_evaluation_errors_are_not_rerouted(self):
        bad = RemoteError(500, "NameError: nope", status=None)
        calls = {"n": 0}

        def r0():
            calls["n"] += 1
            return bad

        behaviors = {"r0": r0, "r1": r0}
        r, _clients = self._router(behaviors)
        with pytest.raises(RemoteError):
            r.submit("q", key="k")
        assert calls["n"] == 1, "a caller error must fail ONCE, not N times"

    def test_deadline_bounds_retry_elsewhere(self):
        shed = RemoteError(503, "shed", status="shed", retry_after_s=5.0)
        behaviors = {"r0": lambda: shed, "r1": lambda: shed}
        r, _clients = self._router(behaviors)
        t0 = time.monotonic()
        with pytest.raises(NoReplicaAvailable):
            r.submit("q", key="k", deadline_ms=50.0)
        assert time.monotonic() - t0 < 2.0, (
            "honoring a 5s Retry-After past a 50ms deadline"
        )

    def test_failover_is_one_stitched_trace(self):
        """ISSUE 17 (d): the retry-elsewhere hop keeps the originating
        request's trace context — one driver query through a failover is
        ONE trace: a fleet.route span joined to the caller's context,
        with one fleet.attempt child per replica tried (replica id +
        verdict), not N orphan traces."""
        from janusgraph_tpu.observability import TraceContext, tracer

        shed = RemoteError(503, "shed", status="shed",
                           retry_after_s=0.001)
        behaviors = {"r0": lambda: shed, "r1": lambda: shed,
                     "r2": lambda: shed}
        r, _clients = self._router(behaviors)
        # the first candidate sheds, every other replica serves: exactly
        # one retry-elsewhere hop (clients build lazily, so mutating the
        # factory-captured dict before submit is enough)
        first = r.candidates_for("stitch")[0].name
        for name in behaviors:
            if name != first:
                behaviors[name] = lambda: 11
        caller_ctx = TraceContext(trace_id=0xABCDEF0123456789,
                                  span_id=0x42)
        assert r.submit("q", key="stitch", trace_ctx=caller_ctx) == 11
        roots = tracer.find_trace(caller_ctx.trace_id)
        routes = [s for s in roots if s.name == "fleet.route"]
        assert routes, "fleet.route did not join the caller's trace"
        route = routes[-1]
        # joined, not copied: the remote parent id is preserved
        assert route.parent_span_id == caller_ctx.span_id
        attempts = [c for c in route.children
                    if c.name == "fleet.attempt"]
        assert len(attempts) >= 2, (
            "a failed-over request must carry one attempt child per "
            "replica tried"
        )
        verdicts = [a.attrs.get("verdict") for a in attempts]
        replicas = [a.attrs.get("replica") for a in attempts]
        assert verdicts[0] == "shed" and verdicts[-1] == "ok"
        assert replicas[0] == first
        assert all(isinstance(x, str) and x for x in replicas)
        # the retriable hop is tagged as such
        assert attempts[0].attrs.get("retry_elsewhere") is True

    def test_submit_without_context_still_traces(self):
        """No caller context: fleet.route is a plain local root — the
        receive site never branches on propagation."""
        from janusgraph_tpu.observability import tracer

        behaviors = {"r0": lambda: 5, "r1": lambda: 5}
        r, _clients = self._router(behaviors)
        assert r.submit("q", key="k") == 5
        routes = [s for s in tracer.recent("fleet.route")]
        assert routes
        assert routes[-1].attrs.get("verdict") == "ok"


# ---------------------------------------------------------------------------
# sticky sessions + drain
# ---------------------------------------------------------------------------

class TestStickyAndDrain:
    def test_pin_is_stable_and_survives_unrelated_churn(self):
        r = _offline_router()
        for i in range(3):
            r.add_replica(f"r{i}", "127.0.0.1", 9000 + i)
        pin = r.pin("sess-1").name
        for _ in range(5):
            assert r.pin("sess-1").name == pin
        other = next(n for n in ("r0", "r1", "r2") if n != pin)
        r.mark_dead(other)
        assert r.pin("sess-1").name == pin

    def test_drain_hands_off_sticky_sessions_and_loses_none(self):
        r = _offline_router()
        for i in range(3):
            r.add_replica(f"r{i}", "127.0.0.1", 9000 + i)
        keys = [f"sess-{k}" for k in range(24)]
        before = {k: r.pin(k).name for k in keys}
        victim = before[keys[0]]
        on_victim = [k for k, n in before.items() if n == victim]
        assert on_victim, "test needs at least one pinned session"
        report = r.drain(victim)
        assert report["sessions_handed_off"] == len(on_victim)
        after = {k: r.pin(k) for k in keys}
        # zero lost: every session still resolves, none to the victim
        assert all(h is not None for h in after.values())
        assert all(h.name != victim for h in after.values())
        # sessions NOT on the victim kept their pin (no global reshuffle)
        for k, n in before.items():
            if n != victim:
                assert after[k].name == n

    def test_crash_failover_repins_immediately(self):
        r = _offline_router()
        for i in range(2):
            r.add_replica(f"r{i}", "127.0.0.1", 9000 + i)
        pin = r.pin("s").name
        r.mark_dead(pin)
        moved = r.pin("s")
        assert moved is not None and moved.name != pin


class TestServerDrain:
    def test_draining_server_sheds_new_work_finishes_sessions(self):
        mgr = InMemoryStoreManager()
        graph = JanusGraphTPU(dict(BASE_CFG), store_manager=mgr)
        ids = _seed_graph(graph, n=8)
        m = JanusGraphManager()
        m.put_graph("graph", graph)
        server = JanusGraphServer(
            manager=m, history_enabled=False, slo_enabled=False,
            replica_name="r0",
        ).start()
        try:
            from janusgraph_tpu.driver import JanusGraphClient

            client = JanusGraphClient(port=server.port)
            ws = client.ws(session=True)
            try:
                assert ws.submit(f"g.V({ids[0]}).count()") == 1
                assert server.open_sessions == 1
                # drain with the session still open: phase one refuses
                # NEW sessionless work but the session keeps working
                done = {}

                def _drain():
                    done["remaining"] = server.drain(timeout_s=5.0)

                th = threading.Thread(target=_drain)
                th.start()
                time.sleep(0.1)
                with pytest.raises(RemoteError) as ei:
                    client.submit("g.V().count()")
                assert ei.value.status == "draining"
                # the in-flight session still runs to completion
                assert ws.submit(f"g.V({ids[1]}).count()") == 1
            finally:
                ws.close()
            th.join(timeout=6.0)
            assert done.get("remaining") == 0, (
                "graceful drain must end with zero open sessions"
            )
            # healthz reports the drain state without flipping degraded
            payload = json.loads(
                __import__("urllib.request", fromlist=["urlopen"]).urlopen(
                    f"http://127.0.0.1:{server.port}/healthz", timeout=5
                ).read()
            )
            assert payload["draining"] is True
            assert payload["replica"] == "r0"
            assert payload["open_sessions"] == 0
        finally:
            server.stop()
            graph.close()

    def test_draining_server_refuses_new_sessions(self):
        mgr = InMemoryStoreManager()
        graph = JanusGraphTPU(dict(BASE_CFG), store_manager=mgr)
        _seed_graph(graph, n=4)
        m = JanusGraphManager()
        m.put_graph("graph", graph)
        server = JanusGraphServer(
            manager=m, history_enabled=False, slo_enabled=False,
        ).start()
        try:
            server.drain(timeout_s=0.1)
            from janusgraph_tpu.driver import JanusGraphClient

            ws = JanusGraphClient(port=server.port).ws(session=True)
            try:
                with pytest.raises(RemoteError) as ei:
                    ws.submit("g.V().count()")
                assert ei.value.status == "draining"
            finally:
                ws.close()
        finally:
            server.stop()
            graph.close()


# ---------------------------------------------------------------------------
# gossip
# ---------------------------------------------------------------------------

class TestGossip:
    def _mesh(self, n, fanout=1):
        """N gossip agents wired directly (no HTTP), fake clock."""
        clock = {"t": 0.0}
        agents = {}

        def post(url, body, timeout_s):
            # url is "<peer>/gossip"
            peer = agents[url.split("/")[0]]
            peer.merge(body)
            return peer.local_digest()

        for i in range(n):
            name = f"r{i}"
            agents[name] = StateGossip(
                name, AdmissionController(), fanout=fanout,
                clock=lambda: clock["t"], post=post,
            )
        for i in range(n):
            agents[f"r{i}"].set_peers(
                [f"r{j}" for j in range(n) if j != i]
            )
        return agents, clock

    def test_price_book_converges_within_bounded_rounds(self):
        n, fanout = 4, 1
        agents, clock = self._mesh(n, fanout=fanout)
        ctl0 = agents["r0"].admission
        digest, _, _ = ctl0.price("g.V().out('x').count()")
        ctl0.observe_cost(digest, "g.V().out('x').count()", 250.0)
        # bound: with push-pull at fanout f on a full mesh, every peer
        # has the fact after ceil((N-1)/f) rounds of the ORIGIN plus one
        # relay sweep of everyone else
        rounds = -(-(n - 1) // fanout) + 1
        for step in range(rounds):
            clock["t"] += 1.0
            for name in sorted(agents):
                agents[name].tick()
        for name, agent in agents.items():
            assert agent.admission.price_book.mean_cost_ms(
                digest
            ) == pytest.approx(250.0), f"{name} did not converge"

    def test_local_measurements_win_over_gossip(self):
        agents, clock = self._mesh(2)
        a, b = agents["r0"], agents["r1"]
        d, _, _ = a.admission.price("g.V().count()")
        a.admission.observe_cost(d, "g.V().count()", 100.0)
        b.admission.observe_cost(d, "g.V().count()", 5.0)
        a.tick()
        b.tick()
        assert b.admission.price_book.mean_cost_ms(d) == pytest.approx(
            5.0
        ), "a stale gossiped record must not clobber a live measurement"

    def test_brownout_rung_propagates_to_peer_state(self):
        agents, clock = self._mesh(3, fanout=2)
        r0 = agents["r0"]
        r0.admission.brownout.rung = 2
        clock["t"] = 7.0
        r0.tick()
        for name in ("r1", "r2"):
            st = agents[name].peer_state.get("r0")
            assert st is not None and st["rung"] == 2
            assert st["ts"] == 7.0  # fake clock stamped

    def test_gossip_over_http_endpoint(self):
        mgr = InMemoryStoreManager()
        graph = JanusGraphTPU(dict(BASE_CFG), store_manager=mgr)
        _seed_graph(graph, n=4)
        servers, gossips, graphs = [], [], [graph]
        try:
            for i in range(2):
                g = graph if i == 0 else JanusGraphTPU(
                    dict(BASE_CFG), store_manager=mgr
                )
                if i > 0:
                    graphs.append(g)
                m = JanusGraphManager()
                m.put_graph("graph", g)
                s = JanusGraphServer(
                    manager=m, history_enabled=False, slo_enabled=False,
                    replica_name=f"r{i}",
                ).start()
                gos = StateGossip(f"r{i}", s.admission, timeout_s=5.0)
                s.gossip = gos
                servers.append(s)
                gossips.append(gos)
            urls = [f"http://127.0.0.1:{s.port}" for s in servers]
            for i, gos in enumerate(gossips):
                gos.set_peers([u for j, u in enumerate(urls) if j != i])
            d, _, _ = servers[0].admission.price("g.V().both().count()")
            servers[0].admission.observe_cost(
                d, "g.V().both().count()", 99.0
            )
            assert gossips[0].tick() == 1
            assert servers[1].admission.price_book.mean_cost_ms(
                d
            ) == pytest.approx(99.0)
        finally:
            for s in servers:
                s.stop()
            for g in graphs:
                g.close()


# ---------------------------------------------------------------------------
# warm-up from checkpoints
# ---------------------------------------------------------------------------

class TestWarmup:
    def _cfg(self):
        return dict(BASE_CFG, **{
            "computer.delta": True, "metrics.enabled": True,
        })

    def test_warmup_byte_identical_and_zero_edgestore_reads(self, tmp_path):
        from janusgraph_tpu.olap import delta as delta_mod
        from janusgraph_tpu.olap.csr import load_csr_snapshot
        from janusgraph_tpu.util.metrics import metrics

        mgr = InMemoryStoreManager()
        g1 = JanusGraphTPU(self._cfg(), store_manager=mgr)
        _seed_graph(g1, n=64)
        info = export_snapshot(g1, str(tmp_path), num_shards=3)
        assert info["rows"] == 64

        g2 = JanusGraphTPU(self._cfg(), store_manager=mgr)
        metrics.reset()
        assert warm_replica(g2, str(tmp_path)) is True
        # the acceptance counter: zero edgestore reads on the warm path
        snap = metrics.snapshot()
        touched = [
            k for k in snap
            if "edgestore" in k and snap[k].get("count")
        ]
        assert touched == [], f"warm path touched storage: {touched}"
        csr_warm = delta_mod.get_snapshot(g2).csr
        csr_scan, _epoch = load_csr_snapshot(g2)
        for field in ("vertex_ids", "out_indptr", "out_dst",
                      "in_indptr", "in_src", "out_degree"):
            a = getattr(csr_warm, field)
            b = getattr(csr_scan, field)
            assert a.dtype == b.dtype and a.tobytes() == b.tobytes(), (
                f"{field} not byte-identical to the scanned snapshot"
            )
        for field in ("labels", "out_edge_type", "in_edge_type"):
            a = getattr(csr_warm, field)
            b = getattr(csr_scan, field)
            assert (a is None) == (b is None)
            if a is not None:
                assert a.tobytes() == b.tobytes()
        g1.close()
        g2.close()

    def test_warm_submit_after_warmup_skips_the_scan(self, tmp_path):
        from janusgraph_tpu.olap.programs.pagerank import PageRankProgram
        from janusgraph_tpu.util.metrics import metrics

        mgr = InMemoryStoreManager()
        g1 = JanusGraphTPU(self._cfg(), store_manager=mgr)
        _seed_graph(g1, n=48)
        export_snapshot(g1, str(tmp_path))
        r_cold = g1.compute(executor="cpu").program(
            PageRankProgram(max_iterations=4)
        ).submit()
        g2 = JanusGraphTPU(self._cfg(), store_manager=mgr)
        assert warm_replica(g2, str(tmp_path))
        metrics.reset()
        r_warm = g2.compute(executor="cpu").program(
            PageRankProgram(max_iterations=4)
        ).submit()
        snap = metrics.snapshot()
        touched = [
            k for k in snap
            if "edgestore" in k and snap[k].get("count")
        ]
        assert touched == []
        assert np.array_equal(
            np.asarray(r_cold.states["rank"]),
            np.asarray(r_warm.states["rank"]),
        )
        g1.close()
        g2.close()

    def test_torn_manifest_falls_back_to_prev(self, tmp_path):
        from janusgraph_tpu.olap.sharded_checkpoint import (
            load_csr_checkpoint,
        )

        mgr = InMemoryStoreManager()
        g1 = JanusGraphTPU(self._cfg(), store_manager=mgr)
        _seed_graph(g1, n=16)
        export_snapshot(g1, str(tmp_path), num_shards=2)
        export_snapshot(g1, str(tmp_path), num_shards=2)  # .prev exists
        mpath = tmp_path / "manifest.json"
        mpath.write_text('{"torn":')
        out = load_csr_checkpoint(str(tmp_path))
        assert out is not None, "torn manifest must fall back to .prev"
        assert out[0].num_vertices == 16
        g1.close()

    def test_warmup_without_files_is_a_clean_miss(self, tmp_path):
        mgr = InMemoryStoreManager()
        g = JanusGraphTPU(self._cfg(), store_manager=mgr)
        _seed_graph(g, n=4)
        assert warm_replica(g, str(tmp_path / "nope")) is False
        g.close()


# ---------------------------------------------------------------------------
# warm-submit executor cache (PR 14 REMAINING)
# ---------------------------------------------------------------------------

class TestExecutorCache:
    def _graph(self):
        mgr = InMemoryStoreManager()
        # pin the single-device executor: under the suite's 8 virtual
        # devices sharded-auto would route AROUND the warm cache (the
        # sharded executor consumes materialized snapshots only)
        return JanusGraphTPU(
            dict(BASE_CFG, **{
                "computer.delta": True, "computer.sharded-auto": False,
            }),
            store_manager=mgr,
        )

    def test_warm_submits_reuse_the_executor(self):
        from janusgraph_tpu.observability import registry
        from janusgraph_tpu.olap.programs.pagerank import PageRankProgram

        g = self._graph()
        ids = _seed_graph(g, n=40)
        r1 = g.compute(executor="tpu").program(
            PageRankProgram(max_iterations=3)
        ).submit()
        hits0 = registry.get_count("olap.executor.cache_hits")
        r2 = g.compute(executor="tpu").program(
            PageRankProgram(max_iterations=3)
        ).submit()
        assert registry.get_count(
            "olap.executor.cache_hits"
        ) == hits0 + 1
        assert np.array_equal(
            np.asarray(r1.states["rank"]), np.asarray(r2.states["rank"])
        )
        # a pending overlay rides the SAME cached executor fused
        tx = g.new_transaction()
        tx.add_edge(
            tx.get_vertex(ids[0]), "knows", tx.get_vertex(ids[9])
        )
        tx.commit()
        r3 = g.compute(executor="tpu").program(
            PageRankProgram(max_iterations=3)
        ).submit()
        assert registry.get_count(
            "olap.executor.cache_hits"
        ) == hits0 + 2
        assert r3.run_info.get("delta", {}).get("fused") is True
        g.close()

    def test_fused_results_match_fresh_executor(self):
        """The cached-executor fused run must equal a cold executor's run
        over the same graph state (the delta bitwise contract holds
        through set_delta)."""
        from janusgraph_tpu.olap.programs.degree import (
            DegreeCountProgram,
        )

        g = self._graph()
        ids = _seed_graph(g, n=32)
        g.compute(executor="tpu").program(DegreeCountProgram()).submit()
        tx = g.new_transaction()
        tx.add_edge(
            tx.get_vertex(ids[2]), "knows", tx.get_vertex(ids[3])
        )
        tx.commit()
        warm = g.compute(executor="tpu").program(
            DegreeCountProgram()
        ).submit()
        # cold oracle: fresh graph handle over the same storage, full scan
        g2 = JanusGraphTPU(
            dict(BASE_CFG), store_manager=g.backend.manager
        )
        cold = g2.compute(executor="cpu").program(
            DegreeCountProgram()
        ).submit()
        warm_by_v = warm.by_vertex("out_degree")
        cold_by_v = cold.by_vertex("out_degree")
        assert warm_by_v == cold_by_v
        g.close()
        g2.close()

    def test_compaction_invalidates_the_cache(self):
        from janusgraph_tpu.olap import delta as delta_mod
        from janusgraph_tpu.olap.programs.degree import (
            DegreeCountProgram,
        )

        g = self._graph()
        _seed_graph(g, n=16)
        g.compute(executor="tpu").program(DegreeCountProgram()).submit()
        snap = delta_mod.get_snapshot(g)
        gen = snap.generation
        key = next(iter(snap._executors))
        snap.adopt(snap.csr, snap.epoch)  # any base swap invalidates
        assert snap.generation == gen + 1
        assert snap.cached_executor(key) is None
        g.close()


# ---------------------------------------------------------------------------
# seeded fleet fault kinds
# ---------------------------------------------------------------------------

class TestFleetFaultKinds:
    def test_kill_and_restart_fire_once_at_scheduled_ticks(self):
        plan = FaultPlan(seed=7, replica_kill_at=3, replica_restart_at=6)
        events = []
        for _ in range(10):
            events.extend(plan.fleet_hook(3))
        kinds = [e["kind"] for e in events]
        assert kinds == ["replica_kill", "replica_restart"]
        assert all(
            e["replica"] == plan.replica_target(3) for e in events
        )

    def test_same_seed_reproduces_the_journal(self):
        def run(seed):
            plan = FaultPlan(
                seed=seed, replica_kill_at=2, replica_restart_at=5,
            )
            for _ in range(8):
                plan.fleet_hook(3)
            return plan.journal

        assert run(11) == run(11)
        # target choice is seed-dependent (pure in the seed)
        t = {FaultPlan(seed=s).replica_target(5) for s in range(32)}
        assert len(t) > 1

    def test_explicit_target_overrides_hash(self):
        plan = FaultPlan(seed=1, replica_target=2)
        assert plan.replica_target(3) == 2

    def test_partition_window_fails_storage_on_target_only(self):
        from janusgraph_tpu.exceptions import InjectedFaultError

        def mk(index):
            plan = FaultPlan(
                seed=3, replica_partition_at=2, replica_partition_ops=4,
                replica_target=1,
            )
            plan.arm_replica(index, 3)
            return plan

        target = mk(1)
        other = mk(0)
        failures = 0
        for n in range(10):
            try:
                target.before_read("edgestore")
            except InjectedFaultError:
                failures += 1
            other.before_read("edgestore")  # never raises
        assert failures == 4, "window must cover exactly partition-ops"
        assert any(
            e["kind"] == "replica_partition" for e in target.journal
        )
        assert other.journal == []

    def test_from_config_reads_the_new_keys(self):
        from janusgraph_tpu.core.graph import open_graph

        g = open_graph({
            "ids.authority-wait-ms": 0.0,
            "storage.faults.enabled": True,
            "storage.faults.replica-kill-at": 5,
            "storage.faults.replica-restart-at": 9,
            "storage.faults.replica-partition-at": 2,
            "storage.faults.replica-partition-ops": 3,
            "storage.faults.replica-target": 1,
        })
        try:
            plan = g.fault_plan
            assert plan.replica_kill_at == 5
            assert plan.replica_restart_at == 9
            assert plan.replica_partition_at == 2
            assert plan.replica_partition_ops == 3
            assert plan.replica_target(4) == 1
        finally:
            g.close()


# ---------------------------------------------------------------------------
# per-replica identity threading
# ---------------------------------------------------------------------------

class TestReplicaIdentity:
    def test_flight_logs_and_metrics_carry_the_tag(self):
        from janusgraph_tpu.observability import (
            flight_recorder,
            get_logger,
            prometheus_text,
            registry,
            set_replica,
        )
        from janusgraph_tpu.observability.logging import recent

        set_replica("replica-9")
        try:
            event = flight_recorder.record("fleet", action="test")
            assert event["replica"] == "replica-9"
            get_logger("test.fleet").info("tagged-record")
            rec = [
                r for r in recent() if r["event"] == "tagged-record"
            ][-1]
            assert rec["replica"] == "replica-9"
            text = prometheus_text(registry)
            assert 'janusgraph_replica_info{replica="replica_9"} 1' in text
        finally:
            set_replica("")
        # untagged: records revert to the pre-fleet shape
        event = flight_recorder.record("fleet", action="test2")
        assert "replica" not in event

    def test_fleet_healthz_quorum_aggregation(self):
        r = _offline_router()
        for i in range(3):
            r.add_replica(f"r{i}", "127.0.0.1", 9000 + i)
        assert r.healthz()["status"] == "ok"
        r.mark_dead("r0")
        assert r.healthz()["status"] == "ok", "one dead of 3 is not quorum"
        r.replicas()["r1"].health = {"status": "degraded"}
        payload = r.healthz()
        assert payload["status"] == "degraded"
        assert payload["quorum_bad"] == 2
        assert payload["replicas"]["r0"]["state"] == DEAD


# ---------------------------------------------------------------------------
# the 3-replica chaos cell
# ---------------------------------------------------------------------------

class TestChaosCell:
    def test_kill_one_replica_mid_traffic(self):
        """Three replicas over one backend; kill one mid-traffic. Zero
        errors surface to well-budgeted callers and fleet goodput stays
        >= 0.6x the pre-kill level during the failover window."""
        mgr = InMemoryStoreManager()
        graphs = [
            JanusGraphTPU(dict(BASE_CFG), store_manager=mgr)
            for _ in range(3)
        ]
        ids = _seed_graph(graphs[0], n=48)
        router = FleetRouter(
            retry_budget_capacity=1e6, retry_budget_refill_per_s=1e6,
            backoff_base_s=0.002, backoff_max_s=0.02,
        )
        servers = {}
        for i, g in enumerate(graphs):
            m = JanusGraphManager()
            m.put_graph("graph", g)
            s = JanusGraphServer(
                manager=m, history_enabled=False, slo_enabled=False,
                replica_name=f"r{i}",
            ).start()
            servers[f"r{i}"] = s
            router.add_replica(f"r{i}", "127.0.0.1", s.port)
        router.probe()
        # the probe loop is part of the deployment: crash detection must
        # not depend solely on per-request connect failures
        router.start_probes(interval_s=0.2)
        stop = threading.Event()
        lock = threading.Lock()
        ok_times = []
        errors = []

        def _worker(w):
            rng = w * 97 + 13
            while not stop.is_set():
                rng = (rng * 1103515245 + 12345) & 0x7FFFFFFF
                vid = ids[rng % len(ids)]
                try:
                    router.submit(
                        f"g.V({vid}).out('knows').count()",
                        deadline_ms=10_000, key=str(vid),
                    )
                    with lock:
                        ok_times.append(time.monotonic())
                except Exception as e:  # noqa: BLE001 - any surfaced error fails
                    with lock:
                        errors.append(f"{type(e).__name__}: {e}")

        threads = [
            threading.Thread(target=_worker, args=(w,)) for w in range(6)
        ]
        t_start = time.monotonic()
        for th in threads:
            th.start()
        try:
            time.sleep(1.2)
            t_kill = time.monotonic()
            servers["r1"].stop()  # hard stop: the crash path
            time.sleep(2.2)
            t_end = time.monotonic()
        finally:
            stop.set()
            for th in threads:
                th.join(timeout=10.0)
            hung = sum(1 for th in threads if th.is_alive())
            router.stop()
            for name, s in servers.items():
                if name != "r1":
                    s.stop()
            for g in graphs:
                g.close()
        assert errors == [], f"errors surfaced to budgeted callers: {errors[:3]}"
        assert hung == 0
        with lock:
            times = list(ok_times)
        # the acceptance bound is goodput WITHIN the drain window, so the
        # failover window opens a detection beat after the kill (the
        # probe loop needs two misses to declare death; requests landing
        # on the corpse in that beat retry elsewhere and complete late)
        pre = [t for t in times if t_start + 0.2 <= t < t_kill]
        during = [t for t in times if t_kill + 0.6 <= t < t_end]
        pre_rate = len(pre) / max(1e-9, t_kill - (t_start + 0.2))
        during_rate = len(during) / max(1e-9, t_end - (t_kill + 0.6))
        assert pre_rate > 0
        assert during_rate >= 0.6 * pre_rate, (
            f"goodput collapsed: {during_rate:.0f}/s vs "
            f"pre-kill {pre_rate:.0f}/s"
        )
        # the dead replica was detected and marked
        assert router.replicas()["r1"].state == DEAD
        assert router.replicas()["r0"].state == SERVING


# ---------------------------------------------------------------------------
# frontend
# ---------------------------------------------------------------------------

class TestFrontend:
    def test_frontend_routes_and_serves_fleet_healthz(self):
        import urllib.request

        mgr = InMemoryStoreManager()
        graph = JanusGraphTPU(dict(BASE_CFG), store_manager=mgr)
        ids = _seed_graph(graph, n=8)
        m = JanusGraphManager()
        m.put_graph("graph", graph)
        server = JanusGraphServer(
            manager=m, history_enabled=False, slo_enabled=False,
        ).start()
        router = FleetRouter()
        router.add_replica("r0", "127.0.0.1", server.port)
        router.probe()
        frontend = FleetFrontend(router).start()
        try:
            body = json.dumps(
                {"gremlin": f"g.V({ids[0]}).out('knows').count()"}
            ).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{frontend.port}/gremlin",
                data=body, method="POST",
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=5) as resp:
                payload = json.loads(resp.read())
            assert payload["status"]["code"] == 200
            with urllib.request.urlopen(
                f"http://127.0.0.1:{frontend.port}/healthz", timeout=5
            ) as resp:
                health = json.loads(resp.read())
            assert health["status"] == "ok"
            assert "r0" in health["replicas"]
            with urllib.request.urlopen(
                f"http://127.0.0.1:{frontend.port}/assign?session=s1",
                timeout=5,
            ) as resp:
                assign = json.loads(resp.read())
            assert assign["replica"] == "r0"
            assert assign["port"] == server.port
        finally:
            frontend.stop()
            router.stop()
            server.stop()
            graph.close()
