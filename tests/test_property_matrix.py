"""Systematic property semantics matrix: cardinality x datatype through
write -> commit -> reload -> index paths (reference model:
JanusGraphTest.java's wide datatype/cardinality matrix)."""

import datetime
import uuid

import pytest

from janusgraph_tpu.core.codecs import Cardinality
from janusgraph_tpu.core.graph import open_graph
from janusgraph_tpu.core.predicates import Geoshape
from janusgraph_tpu.storage.inmemory import InMemoryStoreManager

VALUES = [
    ("s", str, "héllo ✓", "other"),
    ("i", int, 42, -7),
    ("f", float, 2.5, -0.125),
    ("b", bool, True, False),
    ("by", bytes, b"\x00\xff", b"raw"),
    ("dt", datetime.datetime,
     datetime.datetime(2026, 7, 30, 12, 0, tzinfo=datetime.timezone.utc),
     datetime.datetime(1999, 1, 1, tzinfo=datetime.timezone.utc)),
    ("u", uuid.UUID, uuid.uuid5(uuid.NAMESPACE_DNS, "a"),
     uuid.uuid5(uuid.NAMESPACE_DNS, "b")),
    ("g", Geoshape, Geoshape.point(1, 2),
     Geoshape.multipolygon([[(0, 0), (0, 2), (2, 2), (2, 1)]])),
]


@pytest.mark.parametrize("card", [
    Cardinality.SINGLE, Cardinality.LIST, Cardinality.SET
], ids=lambda c: c.name)
def test_cardinality_datatype_matrix(card):
    sm = InMemoryStoreManager()
    g = open_graph({"schema.default": "none"}, store_manager=sm)
    m = g.management()
    for name, typ, _v1, _v2 in VALUES:
        m.make_property_key(name, typ, card)
    tx = g.new_transaction()
    v = tx.add_vertex()
    for name, _typ, v1, v2 in VALUES:
        v.property(name, v1)
        v.property(name, v2)
        if card == Cardinality.SET:
            v.property(name, v2)  # duplicate: SET dedupes
    tx.commit()
    vid = v.id
    g.close()

    # reload through a fresh graph over the same backend
    g2 = open_graph({"schema.default": "none"}, store_manager=sm)
    tx = g2.new_transaction()
    v = tx.get_vertex(vid)
    for name, _typ, v1, v2 in VALUES:
        got = [p.value for p in v.properties(name)]
        if card == Cardinality.SINGLE:
            assert got == [v2], name       # last write wins
        elif card == Cardinality.LIST:
            assert sorted(map(repr, got)) == sorted(
                map(repr, [v1, v2])
            ), name                         # both kept
        else:
            assert sorted(map(repr, got)) == sorted(
                map(repr, [v1, v2])
            ), name                         # deduped to two
    tx.rollback()
    g2.close()


def test_single_cardinality_composite_index_follows_updates():
    """Index rows move with SINGLE updates across every indexable type."""
    g = open_graph({"schema.default": "none"})
    m = g.management()
    m.make_property_key("k_str", str)
    m.make_property_key("k_int", int)
    m.build_composite_index("by_str", ["k_str"])
    m.build_composite_index("by_int", ["k_int"])
    tx = g.new_transaction()
    v = tx.add_vertex()
    v.property("k_str", "first")
    v.property("k_int", 1)
    tx.commit()
    tx = g.new_transaction()
    v2 = tx.get_vertex(v.id)
    v2.property("k_str", "second")
    v2.property("k_int", 2)
    tx.commit()
    t = g.traversal()
    assert [x.id for x in t.V().has("k_str", "second").to_list()] == [v.id]
    assert t.V().has("k_str", "first").to_list() == []
    assert [x.id for x in t.V().has("k_int", 2).to_list()] == [v.id]
    assert t.V().has("k_int", 1).to_list() == []
    g.close()


def test_value_map_list_cardinality_preserved():
    """value_map keeps every value of LIST-cardinality keys (regression:
    an overlay-shadowing guard must not halt multi-value accumulation)."""
    from janusgraph_tpu.core.codecs import Cardinality
    from janusgraph_tpu.core.graph import open_graph

    g = open_graph({"ids.authority-wait-ms": 0.0})
    mgmt = g.management()
    mgmt.make_property_key("tag", str, cardinality=Cardinality.LIST)
    mgmt.make_vertex_label("doc")
    t = g.traversal()
    v = t.add_v("doc")
    tx = t.tx
    tx.add_property(v, "tag", "a")
    tx.add_property(v, "tag", "b")
    t.commit()
    got = g.traversal().V().has_label("doc").value_map("tag").to_list()
    assert got == [{"tag": ["a", "b"]}]
    vals = g.traversal().V().has_label("doc").values("tag").to_list()
    assert sorted(vals) == ["a", "b"]
    g.close()
