"""Randomized OLTP mutation fuzz vs a plain-Python oracle model.

A deterministic random op stream (add/remove vertices, edges, SINGLE
properties; commit boundaries; reopen) runs against the graph AND a dict
model; after every commit the committed state must match the model exactly.
This is the breadth-style complement to the targeted suites (reference:
graphdb/JanusGraphTest.java's wide mutation/read matrix)."""

import pytest
import random

from janusgraph_tpu.core.codecs import Direction
from janusgraph_tpu.core.traversal import GraphTraversalSource
from janusgraph_tpu.core.graph import open_graph
from janusgraph_tpu.storage.inmemory import InMemoryStoreManager


def _check(graph, model):
    tx = graph.new_transaction()
    for vid, props in model["vertices"].items():
        v = tx.get_vertex(vid)
        assert v is not None, f"vertex {vid} missing"
        for k, val in props.items():
            assert v.value(k) == val, (vid, k)
    for vid in model["removed"]:
        assert tx.get_vertex(vid) is None, f"vertex {vid} resurrected"
    # EXACT vertex-set equality: a mutation that silently creates a
    # phantom vertex (e.g. a merge that matches AND creates) must diverge
    assert {v.id for v in tx.vertices()} == set(model["vertices"])
    # edge sets per vertex (as (label, other) multisets)
    for vid in model["vertices"]:
        want = sorted(
            (lbl, other)
            for (src, lbl, other) in model["edges"]
            if src == vid
        )
        got = sorted(
            (e.label, e.in_vertex.id)
            for e in tx.get_edges(tx.get_vertex(vid), Direction.OUT, ())
        )
        assert got == want, (vid, got, want)
    tx.rollback()


@pytest.mark.parametrize("seed", [20260730, 7, 424242])
def test_fuzz_mutations_match_oracle(seed):
    rng = random.Random(seed)
    mgr = InMemoryStoreManager()
    graph = open_graph(store_manager=mgr)
    m = graph.management()
    for k in ("p0", "p1"):
        m.make_property_key(k, int)
    for l in ("e0", "e1"):
        m.make_edge_label(l)

    model = {"vertices": {}, "edges": [], "removed": set()}
    tx = graph.new_transaction()
    pending = {"vertices": {}, "edges": [], "removed_v": set(),
               "removed_e": []}
    live_handles = {}

    def commit():
        nonlocal tx
        tx.commit()
        for vid, props in pending["vertices"].items():
            model["vertices"].setdefault(vid, {}).update(props)
        model["edges"].extend(pending["edges"])
        for vid in pending["removed_v"]:
            model["vertices"].pop(vid, None)
            model["removed"].add(vid)
            model["edges"] = [
                e for e in model["edges"] if e[0] != vid and e[2] != vid
            ]
        for e in pending["removed_e"]:
            # an endpoint removed in the same tx already dropped the edge
            if e in model["edges"]:
                model["edges"].remove(e)
        pending["vertices"].clear()
        pending["edges"].clear()
        pending["removed_v"].clear()
        pending["removed_e"].clear()
        live_handles.clear()
        _check(graph, model)
        tx = graph.new_transaction()

    def vertex_pool():
        return [
            vid for vid in dict.fromkeys(
                list(model["vertices"]) + list(pending["vertices"])
            )
            if vid not in pending["removed_v"]
        ]

    for step in range(300):
        op = rng.random()
        pool = vertex_pool()
        if op < 0.30 or not pool:
            v = tx.add_vertex()
            props = {f"p{rng.randint(0,1)}": rng.randint(0, 99)}
            for k, val in props.items():
                v.property(k, val)
            pending["vertices"][v.id] = props
            live_handles[v.id] = v
        elif op < 0.55 and len(pool) >= 2:
            a, b = rng.sample(pool, 2)
            lbl = f"e{rng.randint(0,1)}"
            committed_pair = (
                a in model["vertices"] and a not in pending["vertices"]
                and b in model["vertices"] and b not in pending["vertices"]
            )
            if committed_pair and rng.random() < 0.4:
                # round-5 AddEdgeStep path through the DSL
                vb = live_handles.get(b) or tx.get_vertex(b)
                GraphTraversalSource(graph, tx).V(a).add_e_(lbl).to_(
                    vb
                ).iterate()
            else:
                va = live_handles.get(a) or tx.get_vertex(a)
                vb = live_handles.get(b) or tx.get_vertex(b)
                tx.add_edge(va, lbl, vb)
            pending["edges"].append((a, lbl, b))
        elif op < 0.75 and pool:
            vid = rng.choice(pool)
            k, val = f"p{rng.randint(0,1)}", rng.randint(0, 99)
            if rng.random() < 0.5 or vid in pending["vertices"]:
                v = live_handles.get(vid) or tx.get_vertex(vid)
                v.property(k, val)
            else:
                # round-5 PropertyStep path: mutate COMMITTED vertices
                # through the traversal DSL inside the SAME fuzz tx
                GraphTraversalSource(graph, tx).V(vid).property(
                    k, val
                ).iterate()
            pending["vertices"].setdefault(vid, {})[k] = val
        elif op < 0.82 and pool:
            vid = rng.choice(pool)
            v = live_handles.get(vid) or tx.get_vertex(vid)
            tx.remove_vertex(v)
            pending["removed_v"].add(vid)
        elif op < 0.88:
            # remove one committed edge through a loaded handle
            committed = [
                e for e in model["edges"]
                if e[0] not in pending["removed_v"]
                and e[2] not in pending["removed_v"]
                and e not in pending["removed_e"]
            ]
            if committed:
                src, lbl, dst = rng.choice(committed)
                v = tx.get_vertex(src)
                for e in tx.get_edges(v, Direction.OUT, (lbl,)):
                    if e.in_vertex.id == dst and not e.is_new:
                        tx.remove_edge(e)
                        pending["removed_e"].append((src, lbl, dst))
                        break
        elif op < 0.94:
            # round-5 merge_v upsert through the DSL: the model does the
            # SAME find-or-create over its tx-visible view
            uk = rng.randint(0, 19)
            visible = {}
            for vid in vertex_pool():
                props = dict(model["vertices"].get(vid, {}))
                props.update(pending["vertices"].get(vid, {}))
                visible[vid] = props
            expect_match = [
                vid for vid, props in visible.items()
                if props.get("uk") == uk
            ]
            got = GraphTraversalSource(graph, tx).merge_v(
                {"uk": uk}
            ).to_list()
            if expect_match:
                assert sorted(v.id for v in got) == sorted(expect_match)
            else:
                assert len(got) == 1
                pending["vertices"][got[0].id] = {"uk": uk}
                live_handles[got[0].id] = got[0]
        else:
            commit()
    commit()
    # survive a reopen: everything above rides the shared store manager
    graph.close()
    graph2 = open_graph(store_manager=mgr)
    _check(graph2, model)
    graph2.close()


def test_fuzz_mixed_index_consistency():
    """Index-maintenance fuzz: random score updates/removals with commits,
    then mixed-index range queries must agree EXACTLY with a dict oracle —
    the drift-detection complement to the mutation fuzz (reference:
    JanusGraphIndexTest's add/update/delete index maintenance matrix).
    Also covers LIST-cardinality properties through the same stream."""
    from janusgraph_tpu.core.traversal import P

    rng = random.Random(77)
    graph = open_graph({"schema.default": "none"})
    m = graph.management()
    m.make_property_key("score", float)
    from janusgraph_tpu.core.codecs import Cardinality

    m.make_property_key("tag", str, Cardinality.LIST)
    m.build_mixed_index("scores", ["score"], backing="search")

    model = {}      # vid -> score
    tags = {}       # vid -> multiset of tags
    tx = graph.new_transaction()
    staged = {}
    staged_tags = {}
    removed = set()

    def commit():
        nonlocal tx, staged, staged_tags, removed
        tx.commit()
        for vid, s in staged.items():
            model[vid] = s
        for vid, ts in staged_tags.items():
            tags.setdefault(vid, []).extend(ts)
        for vid in removed:
            model.pop(vid, None)
            tags.pop(vid, None)
        staged, staged_tags, removed = {}, {}, set()
        # exact agreement with the oracle at 3 random thresholds
        t = graph.traversal()
        for _ in range(3):
            thr = rng.uniform(0, 100)
            got = {v.id for v in t.V().has("score", P.gt(thr)).to_list()}
            want = {vid for vid, s in model.items() if s > thr}
            assert got == want, (thr, got ^ want)
        tx = graph.new_transaction()

    for step in range(200):
        op = rng.random()
        # committed AND same-tx-staged vertices: the add->update->remove
        # before-first-commit matrix must be exercised too
        pool = [
            v for v in dict.fromkeys(list(model) + list(staged))
            if v not in removed
        ]
        if op < 0.35 or not pool:
            v = tx.add_vertex()
            s = rng.uniform(0, 100)
            v.property("score", s)
            staged[v.id] = s
        elif op < 0.60:
            vid = rng.choice(pool)
            v = tx.get_vertex(vid)
            s = rng.uniform(0, 100)
            v.property("score", s)  # SINGLE: replaces -> index move
            staged[vid] = s
        elif op < 0.72:
            vid = rng.choice(pool)
            v = tx.get_vertex(vid)
            tg = f"t{rng.randint(0, 5)}"
            v.property("tag", tg)
            staged_tags.setdefault(vid, []).append(tg)
        elif op < 0.82:
            vid = rng.choice(pool)
            tx.get_vertex(vid).remove()
            removed.add(vid)
        else:
            commit()
    commit()
    # LIST values all survived in order-insensitive multiset terms
    tx = graph.new_transaction()
    for vid, ts in tags.items():
        got = sorted(p.value for p in tx.get_vertex(vid).properties("tag"))
        assert got == sorted(ts), vid
    tx.rollback()
    graph.close()
