"""Durable CDC log + follower replicas (ISSUE 18).

Covers the acceptance list:

- codec roundtrip: batches encode via the fixed-width bulk edge codec
  lanes and decode bitwise-identical (edges, vertex add/del, poison);
- torn-tail recovery: a torn/garbage tail suffix costs exactly the torn
  frames, never a sealed segment, never the log;
- seal/manifest discipline: sealed segments + digest-verified manifest
  survive restart; replay serves across the seal boundary;
- replay idempotence: replay_from(cursor) twice == once, and a follower
  applying the same records twice folds to the same CSR;
- cursor-gap honesty: retention pruning answers None (re-bootstrap),
  counted; poison in range answers None;
- follower-read bitwise-equivalence: bootstrap from a shard checkpoint
  + pulled CDC records == a fresh-scan materialize at the same epoch;
- seeded cdc-torn-segment / cdc-lagging-follower fault kinds: pure in
  the seed, journal byte-equal across runs;
- staleness-hinted routing: unhinted traffic never sees a follower,
  hinted traffic prefers fresh followers, stale ones fall back to the
  leader; /timeseries trend slope sharpens the tie-break;
- /healthz cdc block: leader + follower roles, degraded past the bound.
"""

from __future__ import annotations

import json
import os
import urllib.request

import numpy as np
import pytest

from janusgraph_tpu.core.graph import JanusGraphTPU
from janusgraph_tpu.olap import delta as D
from janusgraph_tpu.olap.csr import load_csr, load_csr_snapshot
from janusgraph_tpu.olap.sharded_checkpoint import save_csr_checkpoint
from janusgraph_tpu.server import (
    FleetRouter,
    JanusGraphManager,
    JanusGraphServer,
)
from janusgraph_tpu.server.fleet import CDCFollower, goodput_slope
from janusgraph_tpu.storage.cdc import (
    CDCLog,
    CDCReader,
    CDCTornWrite,
    LeaderCDCState,
    TAIL_NAME,
    decode_batch,
    encode_batch,
)
from janusgraph_tpu.storage.faults import FaultPlan
from janusgraph_tpu.storage.inmemory import InMemoryStoreManager
from janusgraph_tpu.observability import flight_recorder, registry

BASE_CFG = {
    "ids.authority-wait-ms": 0.0,
    "locks.wait-ms": 0.0,
    "computer.delta": True,
}


def _counter(name):
    return registry.snapshot().get(name, {}).get("count", 0)


def _batch(adds=(), dels=(), v_add=None, v_del=None):
    def _lanes(rows):
        if not rows:
            z = np.empty(0, np.int64)
            return z, z.copy(), z.copy()
        a = np.asarray(rows, np.int64).reshape(-1, 3)
        return a[:, 0].copy(), a[:, 1].copy(), a[:, 2].copy()

    a = _lanes(list(adds))
    d = _lanes(list(dels))
    v_add = dict(v_add or {})
    v_del = list(v_del or [])
    return {
        "n": len(a[0]) + len(d[0]) + len(v_add) + len(v_del),
        "add": a,
        "del": d,
        "v_add": v_add,
        "v_del": v_del,
    }


def _assert_batch_equal(x, y):
    for lane in ("add", "del"):
        for i in range(3):
            np.testing.assert_array_equal(x[lane][i], y[lane][i])
    assert x["v_add"] == y["v_add"]
    assert list(x["v_del"]) == list(y["v_del"])
    assert x["n"] == y["n"]


def _graph_chain(tmp_path=None, n=24, extra=None):
    cfg = dict(BASE_CFG)
    if tmp_path is not None:
        cfg["storage.cdc.dir"] = str(tmp_path)
        cfg["storage.cdc.segment-records"] = 4
    cfg.update(extra or {})
    g = JanusGraphTPU(cfg, store_manager=InMemoryStoreManager())
    g.management().make_edge_label("link")
    tx = g.new_transaction()
    ids = [tx.add_vertex().id for _ in range(n)]
    for i in range(n - 1):
        tx.add_edge(tx.get_vertex(ids[i]), "link", tx.get_vertex(ids[i + 1]))
    tx.commit()
    return g, ids


def _burst(g, ids, seed=7, adds=10, dels=2):
    from janusgraph_tpu.core.codecs import Direction

    rng = np.random.default_rng(seed)
    tx = g.new_transaction()
    for _ in range(adds):
        a, b = rng.integers(0, len(ids), 2)
        tx.add_edge(
            tx.get_vertex(ids[int(a)]), "link",
            tx.get_vertex(ids[int(b)]),
        )
    removed = 0
    for i in rng.permutation(len(ids)):
        if removed >= dels:
            break
        es = tx.get_edges(
            tx.get_vertex(ids[int(i)]), Direction.OUT, ("link",)
        )
        if es:
            tx.remove_edge(es[0])
            removed += 1
    tx.commit()


def _assert_csr_equal(a, b):
    np.testing.assert_array_equal(a.vertex_ids, b.vertex_ids)
    np.testing.assert_array_equal(a.out_indptr, b.out_indptr)
    np.testing.assert_array_equal(a.in_indptr, b.in_indptr)
    np.testing.assert_array_equal(a.out_dst, b.out_dst)
    np.testing.assert_array_equal(a.in_src, b.in_src)


# ---------------------------------------------------------------- codec
class TestCodec:
    def test_roundtrip_mixed(self):
        b = _batch(
            adds=[(1, 2, 9), (3, 4, 9), (1, 2, 9)],
            dels=[(5, 6, 11)],
            v_add={7: 0, 8: 3},
            v_del=[9, 10],
        )
        epoch, back = decode_batch(encode_batch(42, b))
        assert epoch == 42
        _assert_batch_equal(b, back)

    def test_roundtrip_empty_lanes(self):
        b = _batch(v_del=[3])
        epoch, back = decode_batch(encode_batch(1, b))
        assert epoch == 1
        _assert_batch_equal(b, back)

    def test_poison_roundtrip(self):
        epoch, back = decode_batch(encode_batch(5, None))
        assert epoch == 5 and back is None

    def test_large_vids_survive(self):
        big = (1 << 60) + 12345
        b = _batch(adds=[(big, big - 1, 1 << 40)])
        _epoch, back = decode_batch(encode_batch(9, b))
        assert int(back["add"][0][0]) == big
        assert int(back["add"][1][0]) == big - 1
        assert int(back["add"][2][0]) == 1 << 40


# ---------------------------------------------------------------- the log
class TestCDCLog:
    def _fill(self, log, n, start_epoch=1):
        for i in range(n):
            log.append(start_epoch + i, _batch(adds=[(i, i + 1, 1)]))

    def test_append_replay_reopen(self, tmp_path):
        log = CDCLog(str(tmp_path), segment_records=4)
        self._fill(log, 6)
        records, nxt = log.replay_from(0)
        assert len(records) == 6 and nxt == 6
        assert log.stats()["sealed_segments"] == 1
        log.close()
        # restart: sealed segment + tail survive
        log2 = CDCLog(str(tmp_path), segment_records=4)
        records2, nxt2 = log2.replay_from(0)
        assert nxt2 == 6
        for (e1, b1), (e2, b2) in zip(records, records2):
            assert e1 == e2
            _assert_batch_equal(b1, b2)
        log2.close()

    def test_replay_is_idempotent(self, tmp_path):
        log = CDCLog(str(tmp_path), segment_records=4)
        self._fill(log, 5)
        r1 = log.replay_from(2)
        r2 = log.replay_from(2)
        assert r1[1] == r2[1]
        assert [e for e, _ in r1[0]] == [e for e, _ in r2[0]]
        log.close()

    def test_torn_tail_costs_only_torn_suffix(self, tmp_path):
        log = CDCLog(str(tmp_path), segment_records=64)
        self._fill(log, 3)
        log.close()
        # tear: garbage bytes land after the intact frames
        with open(os.path.join(str(tmp_path), TAIL_NAME), "ab") as f:
            f.write(b"\x00\x01torn-partial-frame")
        before = _counter("cdc.torn_frames_dropped")
        log2 = CDCLog(str(tmp_path), segment_records=64)
        assert _counter("cdc.torn_frames_dropped") == before + 1
        records, nxt = log2.replay_from(0)
        assert len(records) == 3 and nxt == 3
        # and the log keeps appending cleanly after recovery
        log2.append(10, _batch(adds=[(9, 9, 9)]))
        assert log2.replay_from(0)[1] == 4
        log2.close()

    def test_injected_torn_write_recovers_deterministically(self, tmp_path):
        plan = FaultPlan(seed=7, cdc_torn_at=2)
        log = CDCLog(str(tmp_path), segment_records=64, fault_plan=plan)
        log.append(1, _batch(adds=[(1, 2, 1)]))
        log.append(2, _batch(adds=[(2, 3, 1)]))
        with pytest.raises(CDCTornWrite):
            log.append(3, _batch(adds=[(3, 4, 1)]))
        log.close()
        log2 = CDCLog(str(tmp_path), segment_records=64)
        records, nxt = log2.replay_from(0)
        assert nxt == 2, "exactly the torn frame is gone"
        assert [e for e, _ in records] == [1, 2]
        assert plan.journal == [{"kind": "cdc_torn_segment", "n": 2}]
        log2.close()

    def test_sealed_segments_survive_tail_loss(self, tmp_path):
        log = CDCLog(str(tmp_path), segment_records=4)
        self._fill(log, 9)  # 2 sealed segments + 1 tail record
        log.close()
        os.unlink(os.path.join(str(tmp_path), TAIL_NAME))
        log2 = CDCLog(str(tmp_path), segment_records=4)
        records, nxt = log2.replay_from(0)
        assert nxt == 8 and len(records) == 8
        log2.close()

    def test_retention_prune_makes_honest_gap(self, tmp_path):
        log = CDCLog(
            str(tmp_path), segment_records=4, retention_segments=1
        )
        self._fill(log, 12, start_epoch=1)  # 3 seals; first two pruned
        assert log.base_cursor == 8
        assert log.replay_from(0) is None, "pruned range must not serve"
        records, nxt = log.replay_from(8)
        assert nxt == 12 and len(records) == 4
        # a bootstrap checkpoint older than the pruned range cannot
        # anchor: records past its epoch are gone
        assert log.cursor_for_epoch(2) is None
        assert log.cursor_for_epoch(11) == 11
        log.close()

    def test_poison_in_range_refuses(self, tmp_path):
        log = CDCLog(str(tmp_path), segment_records=64)
        log.append(1, _batch(adds=[(1, 2, 1)]))
        log.append(2, None)  # poison
        log.append(3, _batch(adds=[(3, 4, 1)]))
        assert log.replay_from(0) is None
        assert log.replay_from(1) is None
        records, nxt = log.replay_from(2)
        assert len(records) == 1 and nxt == 3
        log.close()

    def test_cursor_for_epoch_brackets(self, tmp_path):
        log = CDCLog(str(tmp_path), segment_records=4)
        self._fill(log, 6, start_epoch=10)  # epochs 10..15
        assert log.cursor_for_epoch(9) == 0
        assert log.cursor_for_epoch(12) == 3
        assert log.cursor_for_epoch(15) == 6
        assert log.cursor_for_epoch(99) == 6
        log.close()

    def test_reader_matches_writer(self, tmp_path):
        log = CDCLog(str(tmp_path), segment_records=4)
        self._fill(log, 7, start_epoch=1)
        reader = CDCReader(str(tmp_path))
        assert reader.head_cursor() == log.head_cursor() == 7
        rw, nw = log.replay_from(3)
        rr, nr = reader.replay_from(3)
        assert nw == nr
        assert [e for e, _ in rw] == [e for e, _ in rr]
        for (_, b1), (_, b2) in zip(rw, rr):
            _assert_batch_equal(b1, b2)
        assert reader.cursor_for_epoch(4) == log.cursor_for_epoch(4)
        log.close()

    def test_pow2_segment_size_enforced(self, tmp_path):
        with pytest.raises(ValueError):
            CDCLog(str(tmp_path), segment_records=7)


# ------------------------------------------------------- capture -> log
class TestCaptureFeed:
    def test_commits_stream_into_the_log(self, tmp_path):
        g, ids = _graph_chain(tmp_path / "cdc")
        try:
            assert g.cdc_log is not None
            head0 = g.cdc_log.head_cursor()
            assert head0 > 0, "seed commits must have streamed in"
            _burst(g, ids, seed=3)
            assert g.cdc_log.head_cursor() > head0
            records, _nxt = g.cdc_log.replay_from(0)
            assert all(b["n"] > 0 for _e, b in records)
        finally:
            g.close()

    def test_fresh_scan_equivalence_from_cursor_zero(self, tmp_path):
        """The tentpole property: an empty-base materialize over ALL
        durable records == the live graph's fresh scan, bitwise."""
        g, ids = _graph_chain(tmp_path / "cdc")
        try:
            csr0, epoch0 = load_csr_snapshot(g)
            _burst(g, ids, seed=5)
            _burst(g, ids, seed=6)
            cursor = g.cdc_log.cursor_for_epoch(epoch0)
            records, _ = g.cdc_log.replay_from(cursor)
            overlay = D.DeltaOverlay.from_batches([b for _e, b in records])
            folded = D.materialize(csr0, overlay, idm=g.idm)
            _assert_csr_equal(folded, load_csr(g))
        finally:
            g.close()


# ------------------------------------------------------------- follower
class TestFollower:
    def _leader_with_checkpoint(self, tmp_path):
        g, ids = _graph_chain(tmp_path / "cdc")
        csr, epoch = load_csr_snapshot(g)
        ckpt = str(tmp_path / "ckpt")
        save_csr_checkpoint(ckpt, csr, epoch, num_shards=2)
        return g, ids, ckpt

    def test_follower_read_bitwise_equivalence(self, tmp_path):
        g, ids, ckpt = self._leader_with_checkpoint(tmp_path)
        try:
            f = CDCFollower(g.cdc_log, ckpt, idm=g.idm, name="f0")
            assert f.bootstrap()
            _burst(g, ids, seed=11)
            rep = f.pull()
            assert rep["ok"] and rep["applied"] >= 1
            # leader materialize at the SAME epoch == follower state
            _assert_csr_equal(f.csr, load_csr(g))
            assert f.lag_records() == 0
        finally:
            g.close()

    def test_apply_twice_equals_apply_once(self, tmp_path):
        g, ids, ckpt = self._leader_with_checkpoint(tmp_path)
        try:
            _burst(g, ids, seed=13)
            f1 = CDCFollower(g.cdc_log, ckpt, idm=g.idm)
            assert f1.bootstrap()
            f1.pull()
            once = f1.csr
            # second follower rewinds its cursor and pulls the SAME
            # records again: the epoch guard folds them to nothing
            f2 = CDCFollower(g.cdc_log, ckpt, idm=g.idm)
            assert f2.bootstrap()
            f2.pull()
            f2.cursor = 0
            rep = f2.pull()
            assert rep["ok"] and rep["applied"] == 0
            _assert_csr_equal(once, f2.csr)
        finally:
            g.close()

    def test_cursor_gap_rebootstraps_honestly(self, tmp_path):
        g, ids = _graph_chain(
            tmp_path / "cdc", extra={"storage.cdc.retention-segments": 1}
        )
        try:
            csr, epoch = load_csr_snapshot(g)
            ckpt = str(tmp_path / "ckpt")
            save_csr_checkpoint(ckpt, csr, epoch, num_shards=1)
            f = CDCFollower(g.cdc_log, ckpt, idm=g.idm)
            assert f.bootstrap()
            # churn far past retention (each burst commit is one CDC
            # record; 12 records == 3 sealed segments, 2 pruned): the
            # follower's cursor falls inside the pruned range
            for s in range(12):
                _burst(g, ids, seed=20 + s, adds=12, dels=0)
            assert g.cdc_log.base_cursor > 0, "prune must have happened"
            f.cursor = 0
            before = _counter("fleet.follower.cursor_gaps")
            # stale checkpoint cannot re-anchor either -> honest failure
            rep = f.pull()
            assert _counter("fleet.follower.cursor_gaps") == before + 1
            assert rep.get("rebootstrap") and not rep["ok"]
            assert f.rebootstraps == 1
            # a FRESH checkpoint (epoch past the pruned range) heals it
            csr2, epoch2 = load_csr_snapshot(g)
            save_csr_checkpoint(ckpt, csr2, epoch2, num_shards=1)
            assert f.bootstrap()
            _burst(g, ids, seed=30)
            rep2 = f.pull()
            assert rep2["ok"]
            _assert_csr_equal(f.csr, load_csr(g))
        finally:
            g.close()

    def test_lagging_follower_fault_then_promote(self, tmp_path):
        g, ids, ckpt = self._leader_with_checkpoint(tmp_path)
        try:
            fake = {"t": 100.0}
            plan = FaultPlan(seed=3, follower_lag_at=0, follower_lag_pulls=2)
            f = CDCFollower(
                g.cdc_log, ckpt, idm=g.idm, name="f1",
                max_staleness_ms=500.0, fault_plan=plan,
                clock=lambda: fake["t"],
            )
            assert f.bootstrap()
            _burst(g, ids, seed=41)
            assert f.pull().get("lagging")
            fake["t"] += 1.0  # 1s > the 500ms bound
            block = f.healthz_block()
            assert block["role"] == "follower" and block["degraded"]
            assert block["lag_records"] > 0
            # promotion force-pulls THROUGH the lag window
            before = len(flight_recorder.events())
            rep = f.promote()
            assert rep["ok"] and f.role == "leader"
            _assert_csr_equal(f.csr, load_csr(g))
            cats = [
                e["category"] for e in flight_recorder.events()[before:]
            ]
            assert "follower_promote" in cats
            caught = [
                e for e in flight_recorder.events()[before:]
                if e["category"] == "cdc_replay"
                and e.get("action") == "caught_up"
            ]
            assert caught, "promotion must prove itself caught up"
            assert not f.healthz_block()["degraded"], (
                "a promoted leader is never stale against itself"
            )
            assert plan.journal[0]["kind"] == "cdc_lagging_follower"
        finally:
            g.close()

    def test_fault_journal_is_seed_deterministic(self):
        def _run():
            plan = FaultPlan(
                seed=77, cdc_torn_at=1,
                follower_lag_at=1, follower_lag_pulls=2,
            )
            for _ in range(4):
                plan.cdc_torn_write()
            for _ in range(5):
                plan.follower_lag()
            return json.dumps(plan.journal, sort_keys=True)

        assert _run() == _run()


# ------------------------------------------------------ routing + healthz
class TestStalenessRouting:
    def _router_with_roles(self):
        r = FleetRouter(fetch=lambda url, t: {})
        for i in range(3):
            r.add_replica(f"r{i}", "127.0.0.1", 9000 + i)
        reps = r.replicas()
        reps["r1"].role = "follower"
        reps["r1"].staleness_ms = 50.0
        reps["r2"].role = "follower"
        reps["r2"].staleness_ms = 5000.0
        return r, reps

    def test_unhinted_requests_never_see_followers(self):
        r, _ = self._router_with_roles()
        names = [h.name for h in r.candidates_for("k")]
        assert names == ["r0"]

    def test_hinted_requests_prefer_fresh_followers(self):
        r, _ = self._router_with_roles()
        names = [
            h.name for h in r.candidates_for("k", max_staleness_ms=100.0)
        ]
        assert names[0] == "r1", "the fresh follower absorbs the read"
        assert "r2" not in names, "a too-stale follower must not serve"
        assert names[-1] == "r0", "the leader stays as freshness fallback"
        loose = [
            h.name for h in r.candidates_for("k", max_staleness_ms=10_000)
        ]
        assert set(loose) == {"r0", "r1", "r2"}

    def test_unknown_staleness_is_never_fresh(self):
        r, reps = self._router_with_roles()
        reps["r1"].staleness_ms = None
        names = [
            h.name for h in r.candidates_for("k", max_staleness_ms=100.0)
        ]
        assert "r1" not in names

    def test_trend_slope_signal(self):
        def payload(deltas):
            return {"series": {"server.admission.admitted": [
                {"delta": d} for d in deltas
            ]}}

        assert goodput_slope(payload([1, 2, 3, 4])) > 0
        assert goodput_slope(payload([4, 3, 2, 1])) < 0
        assert goodput_slope(payload([5, 5, 5, 5])) == 0.0
        assert goodput_slope(payload([])) == 0.0
        assert goodput_slope({}) == 0.0
        assert -1.0 <= goodput_slope(payload([0, 1000])) <= 1.0

    def test_probe_trend_sharpens_tie_break(self):
        def fetch(url, timeout):
            if "/timeseries" not in url:
                return {"status": "ok"}
            rising = "9001" in url
            d = [1, 2, 3, 4] if rising else [4, 3, 2, 1]
            return {"series": {"server.admission.admitted": [
                {"delta": x} for x in d
            ]}}

        r = FleetRouter(fetch=fetch, trend_windows=4, candidates=2)
        r.add_replica("up", "127.0.0.1", 9001)
        r.add_replica("down", "127.0.0.1", 9002)
        r.probe()
        reps = r.replicas()
        assert reps["up"].goodput_trend > 0 > reps["down"].goodput_trend
        # identical health -> the trend decides the tie
        assert reps["up"].load_score() < reps["down"].load_score()
        assert r.candidates_for("k")[0].name == "up"

    def test_healthz_cdc_blocks(self, tmp_path):
        g, _ids = _graph_chain(tmp_path / "cdc")
        m = JanusGraphManager()
        m.put_graph("graph", g)
        server = JanusGraphServer(
            manager=m, history_enabled=False, slo_enabled=False,
            replica_name="leader0",
        ).start()
        try:
            server.cdc_state = LeaderCDCState(g.cdc_log)
            payload = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/healthz", timeout=5
            ).read())
            assert payload["cdc"]["role"] == "leader"
            assert payload["cdc"]["cursor"] == g.cdc_log.head_cursor()
            assert payload["cdc"]["staleness_s"] == 0.0
            # an unbootstrapped/stale follower reports degraded -> 503
            server.cdc_state = CDCFollower(
                g.cdc_log, str(tmp_path / "none"), idm=g.idm,
                max_staleness_ms=100.0,
            )
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/healthz", timeout=5
                )
            body = json.loads(ei.value.read())
            assert ei.value.code == 503
            assert body["status"] == "degraded"
            assert body["cdc"]["role"] == "follower"
            assert body["cdc"]["degraded"] is True
        finally:
            server.stop()
            g.close()
