"""Sort-key RANGE queries — the vertex-centric index (VERDICT r2 #10).

Sort keys are written as order-preserving encodings inside edge columns;
get_edges(..., sort_range=(lo, hi)) must compile to a column-range slice
(reference: BasicVertexCentricQueryBuilder.java:780 interval constraints,
EdgeSerializer.java:235-319 byte-order sort-key encoding), not a post-filter
— verified here both for results and for slice-read behavior, plus the
tx-overlay path (uncommitted edges honor the same bounds).
"""

import pytest

from janusgraph_tpu.core import gods
from janusgraph_tpu.core.codecs import Direction
from janusgraph_tpu.core.graph import open_graph
from janusgraph_tpu.exceptions import QueryError


@pytest.fixture()
def g():
    graph = open_graph()
    gods.load(graph)
    yield graph
    graph.close()


def hercules(tx, g):
    return tx.get_vertex(g.traversal().V().has("name", "hercules").next().id)


def test_battled_time_range(g):
    # battled is sorted by time: 1 (nemean), 2 (hydra), 12 (cerberus)
    tx = g.new_transaction()
    h = hercules(tx, g)
    edges = tx.get_edges(h, Direction.OUT, ("battled",), sort_range=(1, 3))
    assert sorted(e.property_values()["time"] for e in edges) == [1, 2]
    edges = tx.get_edges(h, Direction.OUT, ("battled",), sort_range=(3, None))
    assert [e.property_values()["time"] for e in edges] == [12]
    edges = tx.get_edges(h, Direction.OUT, ("battled",), sort_range=(None, None))
    assert len(edges) == 3


def test_range_results_arrive_time_ordered(g):
    """Byte order == value order: a range slice returns edges already sorted
    by the sort key, no client-side sorting."""
    tx = g.new_transaction()
    h = hercules(tx, g)
    edges = tx.get_edges(h, Direction.OUT, ("battled",), sort_range=(None, None))
    times = [e.property_values()["time"] for e in edges]
    assert times == sorted(times) == [1, 2, 12]


def test_tx_overlay_respects_range(g):
    tx = g.new_transaction()
    h = hercules(tx, g)
    mon = tx.add_vertex("monster", name="sphinx")
    tx.add_edge(h, "battled", mon, time=5)
    times = sorted(
        e.property_values()["time"]
        for e in tx.get_edges(h, Direction.OUT, ("battled",), sort_range=(2, 6))
    )
    assert times == [2, 5]  # uncommitted edge at t=5 included, t=1/12 excluded


def test_sort_range_traversal_step(g):
    t = g.traversal()
    from janusgraph_tpu.core.traversal import P

    names = (
        t.V().has("name", "hercules")
        .out_e("battled", sort_range=(2, None)).in_v().values("name").to_list()
    )
    assert sorted(names) == ["cerberus", "hydra"]


def test_sort_range_is_a_slice_not_a_postfilter(g):
    """The store must only be asked for the bounded column range."""
    tx = g.new_transaction()
    h = hercules(tx, g)
    seen = []
    orig = tx.backend_tx.edge_store_query

    def spy(q):
        seen.append(q)
        return orig(q)

    tx.backend_tx.edge_store_query = spy
    tx.get_edges(h, Direction.OUT, ("battled",), sort_range=(2, 3))
    (q,) = seen
    sl = q.slice
    # the slice's column bounds embed the encoded sort-key range: the width
    # byte is the label's sort-key width and the bounds differ only in the
    # encoded time value
    assert sl.start != sl.end
    assert sl.start[:10] == sl.end[:10]  # same cat+type+dir prefix


def test_sort_range_validation(g):
    tx = g.new_transaction()
    h = hercules(tx, g)
    with pytest.raises(QueryError, match="exactly one"):
        tx.get_edges(h, Direction.OUT, (), sort_range=(1, 2))
    with pytest.raises(QueryError, match="concrete direction"):
        tx.get_edges(h, Direction.BOTH, ("battled",), sort_range=(1, 2))
    with pytest.raises(QueryError, match="no sort key"):
        tx.get_edges(h, Direction.OUT, ("father",), sort_range=(1, 2))


def test_multi_property_sort_key():
    graph = open_graph()
    mgmt = graph.management()
    mgmt.make_property_key("t", int)
    mgmt.make_property_key("seq", int)
    mgmt.make_edge_label("event", sort_key=("t", "seq"))
    tx = graph.new_transaction()
    a = tx.add_vertex()
    b = tx.add_vertex()
    for t_, s_ in [(1, 1), (1, 2), (2, 1), (3, 9)]:
        tx.add_edge(a, "event", b, t=t_, seq=s_)
    tx.commit()

    tx2 = graph.new_transaction()
    va = tx2.get_vertex(a.id)
    got = [
        (e.property_values()["t"], e.property_values()["seq"])
        for e in tx2.get_edges(
            va, Direction.OUT, ("event",), sort_range=((1, 2), (3,))
        )
    ]
    assert got == [(1, 2), (2, 1)]
    graph.close()


def test_sort_range_rejects_lossy_bound(g):
    tx = g.new_transaction()
    h = hercules(tx, g)
    with pytest.raises(QueryError, match="representable"):
        tx.get_edges(h, Direction.OUT, ("battled",), sort_range=(1.5, None))
