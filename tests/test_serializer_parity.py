"""Round-4 serializer registry additions (reference:
StandardSerializer.java:78-132): containers, object fallback, class values,
extra array dtypes, lifecycle enums, and the extended Geoshape vocabulary
(reference: attribute/Geoshape.java:623). Round-trip per type, plus
order-preservation where the codec claims it."""

import numpy as np
import pytest

from janusgraph_tpu.core.attributes import Serializer, SerializerError
from janusgraph_tpu.core.codecs import Consistency
from janusgraph_tpu.core.management import SchemaStatus
from janusgraph_tpu.core.predicates import Geo, Geoshape


@pytest.fixture(scope="module")
def ser():
    return Serializer()


def rt(ser, value):
    out, _ = ser.read_object(ser.write_object(value))
    return out


# ------------------------------------------------------------- containers
def test_dict_roundtrip(ser):
    d = {"a": 1, 2: "b", "nested": {"x": 0.5}, "list": [1.0, 2.0]}
    assert rt(ser, d) == d


def test_tuple_roundtrip_heterogeneous(ser):
    t = ("s", 42, 0.5, True, (1, "inner"))
    assert rt(ser, t) == t


def test_object_pickle_fallback_roundtrip(ser):
    class Thing:
        def __init__(self, x):
            self.x = x

        def __eq__(self, o):
            return o.x == self.x

    # an unregistered, non-container stdlib type falls through to pickle
    v = complex(1.5, -2.5)
    assert rt(ser, v) == v
    # dict SUBCLASSES ride the dict codec (value-preserving, type-erasing)
    import collections

    assert rt(ser, collections.Counter("aabbb")) == {"a": 2, "b": 3}


def test_pickle_refused_on_network_registry():
    safe = Serializer(allow_pickle=False)
    with pytest.raises(SerializerError, match="fallback disabled"):
        safe.write_object(complex(1, 2))
    trusted = Serializer()
    frame = trusted.write_object(complex(1, 2))
    with pytest.raises(SerializerError, match="refused"):
        safe.read_object(frame)


def test_class_values_roundtrip(ser):
    import decimal

    for cls in (str, int, float, decimal.Decimal, Geoshape, np.int32):
        assert rt(ser, cls) is cls


def test_class_import_allowlist(ser):
    frame = bytearray(ser.write_object(str))
    evil = b"os:system"
    bad = frame[:2] + evil
    with pytest.raises(SerializerError, match="refused"):
        ser.read_object(bytes(bad))


def test_new_array_dtypes(ser):
    for dt in (np.uint16, np.uint32, np.uint64, np.float16):
        a = np.arange(5).astype(dt)
        out = rt(ser, a)
        assert out.dtype == a.dtype and np.array_equal(out, a)


def test_lifecycle_enums_roundtrip(ser):
    assert rt(ser, SchemaStatus.ENABLED) is SchemaStatus.ENABLED
    assert rt(ser, Consistency.LOCK) is Consistency.LOCK


def test_registry_id_count():
    s = Serializer()
    assert len(s._by_id) >= 48


# ------------------------------------------------------------- geoshapes
SHAPES = [
    Geoshape.line([(0, 0), (1, 1), (1, 2)]),
    Geoshape.multipoint([(0, 0), (2, 2)]),
    Geoshape.multilinestring([[(0, 0), (1, 1)], [(2, 2), (3, 3)]]),
    Geoshape.multipolygon(
        [[(0, 0), (0, 2), (2, 2), (2, 1)], [(5, 5), (5, 7), (7, 7), (7, 5)]]
    ),
    Geoshape.geometry_collection(
        [Geoshape.point(1, 1), Geoshape.circle(2, 2, 5.0),
         Geoshape.line([(0, 0), (4, 4)])]
    ),
]


@pytest.mark.parametrize("shape", SHAPES, ids=lambda s: s.kind)
def test_geoshape_binary_roundtrip(ser, shape):
    assert rt(ser, shape) == shape


@pytest.mark.parametrize("shape", SHAPES, ids=lambda s: s.kind)
def test_geoshape_wkt_roundtrip(shape):
    back = Geoshape.from_wkt(shape.to_wkt())
    # multipolygon boxes normalize: compare via WKT fixpoint
    assert Geoshape.from_wkt(back.to_wkt()) == back


@pytest.mark.parametrize("shape", SHAPES, ids=lambda s: s.kind)
def test_geoshape_geojson_roundtrip(shape):
    back = Geoshape.from_geojson(shape.to_geojson())
    assert Geoshape.from_geojson(back.to_geojson()) == back


def test_multi_shape_predicates():
    mp = Geoshape.multipolygon(
        [[(0, 0), (0, 2), (2, 2), (2, 0)], [(5, 5), (5, 7), (7, 7), (7, 5)]]
    )
    assert Geo.INTERSECT.evaluate(Geoshape.point(1, 1), mp)
    assert Geo.INTERSECT.evaluate(Geoshape.point(6, 6), mp)
    assert Geo.DISJOINT.evaluate(Geoshape.point(3.5, 3.5), mp)
    assert Geo.WITHIN.evaluate(
        Geoshape.multipoint([(1, 1), (6, 6)]), mp
    )
    assert not Geo.WITHIN.evaluate(
        Geoshape.multipoint([(1, 1), (3.5, 3.5)]), mp
    )
    line = Geoshape.line([(1, -1), (1, 3)])
    assert Geo.INTERSECT.evaluate(line, mp)
    coll = Geoshape.geometry_collection([Geoshape.point(6, 6), line])
    assert Geo.INTERSECT.evaluate(coll, mp)


def test_line_contains_point():
    ln = Geoshape.line([(0, 0), (2, 2)])
    assert ln.contains_point(1, 1)
    assert not ln.contains_point(1, 1.5)


def test_mixed_index_multi_geoshape(tmp_path):
    """The new shapes work through the index tier end to end."""
    from janusgraph_tpu.indexing import (
        IndexMutation,
        IndexQuery,
        KeyInformation,
        LocalIndexProvider,
        PredicateCondition,
    )

    p = LocalIndexProvider(directory=str(tmp_path / "gidx"))
    p.register("s", "area", KeyInformation(Geoshape))
    m = IndexMutation(is_new=True)
    m.add("area", Geoshape.multipolygon(
        [[(0, 0), (0, 2), (2, 2), (2, 0)], [(5, 5), (5, 7), (7, 7), (7, 5)]]
    ))
    p.mutate({"s": {"d1": m}}, {})
    hits = p.query("s", IndexQuery(
        PredicateCondition("area", Geo.INTERSECT, Geoshape.point(6, 6))
    ))
    assert hits == ["d1"]
    p.close()
