"""Locking tests: local mediation, consistent-key claim protocol, expected
value checking, and unique-index safety across two graph instances sharing
one store (reference test model: LockKeyColumnValueStoreTest.java:542 — two
stores + two local mediators simulate two processes)."""

import threading

import pytest

from janusgraph_tpu.core.graph import JanusGraphTPU
from janusgraph_tpu.exceptions import SchemaViolationError
from janusgraph_tpu.storage.inmemory import InMemoryStoreManager
from janusgraph_tpu.storage.locking import (
    ConsistentKeyLocker,
    KeyColumn,
    LocalLockMediator,
    PermanentLockingError,
    TemporaryLockingError,
)


def make_locker(mgr, rid, mediator=None, **kw):
    store = mgr.open_database("test_lock_")
    return ConsistentKeyLocker(
        store,
        mgr.begin_transaction,
        rid,
        mediator or LocalLockMediator(),
        wait_ms=0.0,
        **kw,
    )


class TestLocalLockMediator:
    def test_claim_release(self):
        med = LocalLockMediator()
        t = KeyColumn(b"k", b"c")
        assert med.claim(t, "tx1", expiry=1e12)
        assert not med.claim(t, "tx2", expiry=1e12)
        assert med.claim(t, "tx1", expiry=1e12)  # re-entrant
        med.release(t, "tx2")  # not the holder: no-op
        assert not med.claim(t, "tx2", expiry=1e12)
        med.release(t, "tx1")
        assert med.claim(t, "tx2", expiry=1e12)

    def test_expired_claim_is_stealable(self):
        med = LocalLockMediator()
        t = KeyColumn(b"k", b"c")
        assert med.claim(t, "tx1", expiry=0.0)  # already expired
        assert med.claim(t, "tx2", expiry=1e12)


class TestConsistentKeyLocker:
    def test_single_holder_wins(self):
        mgr = InMemoryStoreManager()
        lk = make_locker(mgr, b"rid1")
        t = KeyColumn(b"key", b"col")
        lk.write_lock(t, "tx1")
        lk.check_locks("tx1")  # no contest: we hold it
        lk.delete_locks("tx1")
        # afterwards another tx can take it
        lk.write_lock(t, "tx2")
        lk.check_locks("tx2")
        lk.delete_locks("tx2")

    def test_local_contention_fails_fast(self):
        mgr = InMemoryStoreManager()
        med = LocalLockMediator()
        lk = make_locker(mgr, b"rid1", med)
        t = KeyColumn(b"key", b"col")
        lk.write_lock(t, "tx1")
        with pytest.raises(TemporaryLockingError, match="local lock"):
            lk.write_lock(t, "tx2")
        lk.delete_locks("tx1")

    def test_cross_process_race_first_claim_wins(self):
        """Two lockers with DIFFERENT mediators (= two processes) share the
        lock store; the earlier claim timestamp wins the re-read."""
        mgr = InMemoryStoreManager()
        a = make_locker(mgr, b"rid_a")
        b = make_locker(mgr, b"rid_b")
        t = KeyColumn(b"key", b"col")
        a.write_lock(t, "txA")
        b.write_lock(t, "txB")  # different mediator: local claim succeeds
        a.check_locks("txA")  # a claimed first → wins
        with pytest.raises(TemporaryLockingError, match="lost lock race"):
            b.check_locks("txB")
        a.delete_locks("txA")
        b.delete_locks("txB")
        # loser's claim got cleaned up: store row holds nothing live
        c = make_locker(mgr, b"rid_c")
        c.write_lock(t, "txC")
        c.check_locks("txC")
        c.delete_locks("txC")

    def test_expired_remote_claim_ignored(self):
        import time

        mgr = InMemoryStoreManager()
        # cluster-wide expiry of 50ms; a's claim ages past it, b's does not
        a = make_locker(mgr, b"rid_a", expiry_ms=50.0)
        b = make_locker(mgr, b"rid_b", expiry_ms=50.0)
        t = KeyColumn(b"key", b"col")
        a.write_lock(t, "txA")
        time.sleep(0.1)
        b.write_lock(t, "txB")
        b.check_locks("txB")  # a's claim is expired → b wins
        b.delete_locks("txB")
        a.delete_locks("txA")

    def test_expected_value_drift_fails_commit(self):
        mgr = InMemoryStoreManager()
        lk = make_locker(mgr, b"rid1")
        t = KeyColumn(b"key", b"col")
        lk.write_lock(t, "tx1", expected=[(b"col", b"v1")])
        lk.check_locks("tx1")
        with pytest.raises(PermanentLockingError, match="expected value"):
            lk.check_expected_values("tx1", lambda _t: [(b"col", b"CHANGED")])
        lk.delete_locks("tx1")

    def test_expected_value_stable_passes(self):
        mgr = InMemoryStoreManager()
        lk = make_locker(mgr, b"rid1")
        t = KeyColumn(b"key", b"col")
        lk.write_lock(t, "tx1", expected=[])
        lk.check_locks("tx1")
        lk.check_expected_values("tx1", lambda _t: [])
        lk.delete_locks("tx1")


class TestUniqueIndexAcrossInstances:
    """The end-to-end reason locking exists: two graph instances over one
    storage manager cannot both claim a unique value."""

    def _open_pair(self):
        mgr = InMemoryStoreManager()
        g1 = JanusGraphTPU({"ids.authority-wait-ms": 0.0, "locks.wait-ms": 0.0}, store_manager=mgr)
        g2 = JanusGraphTPU({"ids.authority-wait-ms": 0.0, "locks.wait-ms": 0.0}, store_manager=mgr)
        mgmt = g1.management()
        mgmt.make_property_key("name", str)
        mgmt.build_composite_index("byName", ["name"], unique=True)
        # second instance must see the schema: drop its caches and re-read
        # (the mgmt-log broadcast automates this in the log milestone)
        g2.backend.clear_caches()
        g2.schema_cache.invalidate("name")
        g2._load_index_registry()
        return g1, g2

    def test_sequential_claims_conflict(self):
        g1, g2 = self._open_pair()
        tx1 = g1.new_transaction()
        v1 = tx1.add_vertex()
        tx1.add_property(v1, "name", "zeus")
        tx1.commit()
        tx2 = g2.new_transaction()
        v2 = tx2.add_vertex()
        tx2.add_property(v2, "name", "zeus")
        with pytest.raises(SchemaViolationError, match="unique"):
            tx2.commit()
        g1.close()
        g2.close()

    def test_concurrent_claims_one_wins(self):
        g1, g2 = self._open_pair()
        results = []
        barrier = threading.Barrier(2)

        def writer(g):
            tx = g.new_transaction()
            v = tx.add_vertex()
            tx.add_property(v, "name", "hera")
            barrier.wait()
            try:
                tx.commit()
                results.append("ok")
            except Exception:
                results.append("fail")

        t1 = threading.Thread(target=writer, args=(g1,))
        t2 = threading.Thread(target=writer, args=(g2,))
        t1.start(); t2.start(); t1.join(); t2.join()
        assert sorted(results) == ["fail", "ok"]
        # exactly one owner persisted
        tx = g1.new_transaction()
        hits = g1.index_lookup(tx, "byName", ("hera",))
        assert len(hits) == 1
        tx.rollback()
        g1.close()
        g2.close()


class TestLeaseExpiry:
    """ISSUE 3 satellite: an expired lock lease must raise
    TemporaryLockingError and the target must be immediately re-acquirable,
    including under injected clock skew (the chaos engine's lock fault)."""

    def test_expired_lease_raises_and_is_reacquirable(self):
        import time as _time

        mgr = InMemoryStoreManager()
        skew = [0]
        lk = make_locker(
            mgr, b"rid1", clock_ns=lambda: _time.time_ns() + skew[0]
        )
        t = KeyColumn(b"k", b"c")
        lk.write_lock(t, "tx1")
        skew[0] = 3_600 * 10**9  # the check sees the claim as an hour old
        with pytest.raises(TemporaryLockingError, match="lease expired"):
            lk.check_locks("tx1")
        # re-acquirable: a fresh claim under a normal clock wins cleanly
        skew[0] = 0
        lk.write_lock(t, "tx1")
        lk.check_locks("tx1")
        lk.delete_locks("tx1")

    def test_expired_lease_target_claimable_by_other_tx(self):
        import time as _time

        mgr = InMemoryStoreManager()
        skew = [0]
        lk = make_locker(
            mgr, b"rid1", clock_ns=lambda: _time.time_ns() + skew[0]
        )
        t = KeyColumn(b"k", b"c")
        lk.write_lock(t, "tx1")
        skew[0] = 3_600 * 10**9
        with pytest.raises(TemporaryLockingError, match="lease expired"):
            lk.check_locks("tx1")
        # the expired holder's claim column and mediator slot were released:
        # another tx acquires the same target immediately
        skew[0] = 0
        lk.write_lock(t, "tx2")
        lk.check_locks("tx2")
        lk.delete_locks("tx2")

    def test_fault_plan_lock_clock_drives_expiry(self):
        from janusgraph_tpu.storage.faults import FaultPlan

        mgr = InMemoryStoreManager()
        plan = FaultPlan(seed=11, lock_expiry_at=1)
        lk = make_locker(mgr, b"rid1", clock_ns=plan.lock_clock_ns)
        t = KeyColumn(b"k", b"c")
        lk.write_lock(t, "tx1")
        lk.check_locks("tx1")  # check #0: normal clock
        lk.delete_locks("tx1")
        lk.write_lock(t, "tx1")
        with pytest.raises(TemporaryLockingError, match="lease expired"):
            lk.check_locks("tx1")  # check #1: the scheduled skew fires
        assert [e["kind"] for e in plan.journal] == ["lock"]
        # and the fault is one-shot at that index: the retry succeeds
        lk.write_lock(t, "tx1")
        lk.check_locks("tx1")
        lk.delete_locks("tx1")
