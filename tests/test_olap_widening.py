"""GraphFilter, MapReduce stage, and superstep checkpointing tests
(reference: FulgoraGraphComputer map-reduce phase :288-357, GraphFilter via
vertices()/edges(); checkpointing exceeds parity per SURVEY.md §5.4)."""

import numpy as np
import pytest

from janusgraph_tpu.core import gods
from janusgraph_tpu.core.graph import open_graph
from janusgraph_tpu.olap import (
    ClusterCountMapReduce,
    StatsMapReduce,
    TopKMapReduce,
    csr_from_edges,
    load_csr,
    load_checkpoint,
    run_map_reduce,
)
from janusgraph_tpu.olap.programs import (
    ConnectedComponentsProgram,
    PageRankProgram,
)
from janusgraph_tpu.olap.tpu_executor import TPUExecutor
from janusgraph_tpu.parallel import ShardedExecutor


@pytest.fixture(scope="module")
def gods_graph():
    g = open_graph({"ids.authority-wait-ms": 0.0})
    gods.load(g)
    yield g
    g.close()


def random_graph(n=150, m=600, seed=7):
    rng = np.random.default_rng(seed)
    return csr_from_edges(
        n, rng.integers(0, n, m).astype(np.int32),
        rng.integers(0, n, m).astype(np.int32), None,
    )


# -------------------------------------------------------------- GraphFilter
def test_vertex_label_filter(gods_graph):
    full = load_csr(gods_graph)
    only_gods = load_csr(gods_graph, vertex_labels=("god",))
    assert only_gods.num_vertices < full.num_vertices
    names = load_csr(
        gods_graph, vertex_labels=("god",), property_keys=("name",)
    ).properties["name"]
    assert set(names.tolist()) == {"jupiter", "neptune", "pluto"}
    # edges incident to non-god vertices are gone; brother edges remain
    assert only_gods.num_edges == 6  # 3 gods x 2 brother edges each


def test_vertex_filter_via_computer(gods_graph):
    res = (
        gods_graph.compute()
        .vertices("monster")
        .program(ConnectedComponentsProgram(max_iterations=5))
        .submit()
    )
    assert res.csr.num_vertices == 3  # nemean, hydra, cerberus


# ---------------------------------------------------------------- MapReduce
def test_cluster_count_map_reduce():
    csr = csr_from_edges(
        6,
        np.array([0, 1, 3, 4], dtype=np.int32),
        np.array([1, 2, 4, 5], dtype=np.int32),
        None,
    )
    ex = TPUExecutor(csr, strategy="ell")
    states = ex.run(ConnectedComponentsProgram(max_iterations=20))
    out = run_map_reduce(ClusterCountMapReduce("component"), states, csr)
    assert out["count"] == 2
    assert sorted(out["sizes"].values()) == [3.0, 3.0]


def test_stats_and_topk_map_reduce(gods_graph):
    res = (
        gods_graph.compute()
        .program(PageRankProgram(max_iterations=20))
        .map_reduce(StatsMapReduce("rank"))
        .map_reduce(TopKMapReduce("rank", k=3))
        .submit()
    )
    stats = res.memory["stats"]
    assert stats["count"] == 12
    assert abs(stats["sum"] - 1.0) < 1e-3
    top = res.memory["topK"]
    assert len(top) == 3
    assert top[0][1] >= top[1][1] >= top[2][1]


# ------------------------------------------------------------ checkpointing
def test_checkpoint_resume_matches_uninterrupted(tmp_path):
    csr = random_graph()
    path = str(tmp_path / "ck.npz")
    prog = lambda: PageRankProgram(max_iterations=24, tol=0.0)

    direct = TPUExecutor(csr, strategy="ell").run(prog())

    # run with checkpoints every 5 steps, "crash" after the first chunk by
    # reloading from the checkpoint and resuming with a fresh executor
    ex1 = TPUExecutor(csr, strategy="ell")
    ex1.run(prog(), checkpoint_path=path, checkpoint_every=5)
    st, mem, steps = load_checkpoint(path)
    assert steps == 24 and "rank" in st

    # simulate interruption: rewind by saving a mid-run checkpoint
    from janusgraph_tpu.olap.checkpoint import save_checkpoint

    ex2 = TPUExecutor(csr, strategy="ell")
    # produce a genuine mid-run state: run 2 chunks of 5 then stop
    p = PageRankProgram(max_iterations=10, tol=0.0)
    mid = ex2.run(p, checkpoint_path=path, checkpoint_every=5)
    st, mem, steps = load_checkpoint(path)
    assert steps == 10

    resumed = TPUExecutor(csr, strategy="ell").run(
        prog(), checkpoint_path=path, checkpoint_every=5, resume=True
    )
    np.testing.assert_allclose(
        resumed["rank"], direct["rank"], rtol=1e-5, atol=1e-7
    )


def test_checkpoint_resume_sharded(tmp_path):
    csr = random_graph(seed=13)
    path = str(tmp_path / "ck_sharded.npz")
    direct = ShardedExecutor(csr).run(PageRankProgram(max_iterations=16, tol=0.0))

    ex = ShardedExecutor(csr)
    ex.run(
        PageRankProgram(max_iterations=8, tol=0.0),
        checkpoint_path=path, checkpoint_every=4,
    )
    _st, _mem, steps = load_checkpoint(path)
    assert steps == 8

    resumed = ShardedExecutor(csr).run(
        PageRankProgram(max_iterations=16, tol=0.0),
        checkpoint_path=path, checkpoint_every=4, resume=True,
    )
    np.testing.assert_allclose(
        resumed["rank"], direct["rank"], rtol=1e-5, atol=1e-7
    )


def test_checkpoint_early_termination_preserved(tmp_path):
    """A program that converges inside a chunk stops and the checkpoint
    records the true step count."""
    src = np.array([0, 1, 2], dtype=np.int32)
    dst = np.array([1, 2, 3], dtype=np.int32)
    csr = csr_from_edges(5, src, dst, None)
    path = str(tmp_path / "cc.npz")
    ex = TPUExecutor(csr, strategy="ell")
    res = ex.run(
        ConnectedComponentsProgram(max_iterations=50),
        checkpoint_path=path, checkpoint_every=10,
    )
    _st, _mem, steps = load_checkpoint(path)
    assert steps < 50
    comp = np.asarray(res["component"])
    assert (comp[:4] == comp[0]).all()


def test_checkpoint_resume_host_loop_path(tmp_path):
    """Phase-alternating programs (host loop) also checkpoint + resume."""
    from janusgraph_tpu.olap.programs import PeerPressureProgram

    csr = random_graph(seed=41)
    path = str(tmp_path / "pp.npz")
    direct = TPUExecutor(csr, strategy="ell").run(
        PeerPressureProgram(num_buckets=128, rounds=6)
    )
    ex = TPUExecutor(csr, strategy="ell")
    ex.run(
        PeerPressureProgram(num_buckets=128, rounds=3),
        checkpoint_path=path, checkpoint_every=2,
    )
    _st, _mem, steps = load_checkpoint(path)
    assert steps > 0
    resumed = TPUExecutor(csr, strategy="ell").run(
        PeerPressureProgram(num_buckets=128, rounds=6),
        checkpoint_path=path, checkpoint_every=2, resume=True,
    )
    np.testing.assert_allclose(resumed["cluster"], direct["cluster"])
