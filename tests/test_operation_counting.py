"""Operation-counting conformance: backend call counts prove cache behavior
(reference: JanusGraphOperationCountingTest.java:649 — asserts getSlice
counts through metrics instrumentation, demonstrating the tx-level and
store-level caches actually absorb repeat reads)."""

import pytest

from janusgraph_tpu.core.graph import open_graph
from janusgraph_tpu.util.metrics import metrics


@pytest.fixture(autouse=True)
def _reset_metrics():
    metrics.reset()
    yield
    metrics.reset()


def _slice_count():
    return metrics.get_count("storage.edgestore.getSlice")


def _load(g):
    tx = g.new_transaction()
    a = tx.add_vertex(name="a", score=1.0)
    b = tx.add_vertex(name="b", score=2.0)
    tx.add_edge(a, "knows", b)
    tx.commit()
    return a.id, b.id


def test_repeat_reads_in_one_tx_hit_the_tx_cache():
    g = open_graph({
        "schema.default": "auto", "metrics.enabled": True,
        "cache.db-cache": False,  # isolate the TX-level slice cache
    })
    aid, _ = _load(g)
    tx = g.new_transaction()
    v = tx.get_vertex(aid)
    v.value("name")
    first = _slice_count()
    assert first > 0
    # identical reads inside the SAME tx: served by the tx slice cache
    for _ in range(5):
        tx.get_vertex(aid).value("name")
    assert _slice_count() == first
    tx.rollback()
    g.close()


def test_fresh_tx_reads_hit_the_store_cache():
    g = open_graph({
        "schema.default": "auto", "metrics.enabled": True,
        "cache.db-cache": True,
    })
    aid, _ = _load(g)
    tx = g.new_transaction()
    tx.get_vertex(aid).value("name")
    tx.rollback()
    warm = _slice_count()
    # fresh transactions re-read the same rows: the db-cache sits ABOVE the
    # instrumented store (Backend wraps instrumentation first), so repeat
    # slice reads never reach the backend
    for _ in range(4):
        tx = g.new_transaction()
        tx.get_vertex(aid).value("name")
        tx.rollback()
    assert _slice_count() == warm
    g.close()


def test_cache_disabled_reads_reach_the_backend():
    g = open_graph({
        "schema.default": "auto", "metrics.enabled": True,
        "cache.db-cache": False,
    })
    aid, _ = _load(g)
    tx = g.new_transaction()
    tx.get_vertex(aid).value("name")
    tx.rollback()
    before = _slice_count()
    for _ in range(3):
        tx = g.new_transaction()
        tx.get_vertex(aid).value("name")
        tx.rollback()
    # every fresh tx pays real backend reads with the cache off
    assert _slice_count() > before
    g.close()


def test_mutation_invalidates_the_store_cache():
    g = open_graph({
        "schema.default": "auto", "metrics.enabled": True,
        "cache.db-cache": True,
    })
    aid, _ = _load(g)
    tx = g.new_transaction()
    assert tx.get_vertex(aid).value("score") == 1.0
    tx.rollback()
    warm = _slice_count()
    # a write through THIS instance invalidates the touched rows
    tx = g.new_transaction()
    tx.get_vertex(aid).property("score", 9.0)
    tx.commit()
    tx = g.new_transaction()
    assert tx.get_vertex(aid).value("score") == 9.0  # fresh value visible
    tx.rollback()
    assert _slice_count() > warm  # the invalidated row was re-read
    g.close()
