"""Graph-level OLTP conformance tests.

Reference model: janusgraph-backend-testutils .../graphdb/JanusGraphTest.java
(the 6k-line conformance suite): schema constraints, CRUD, tx isolation and
overlay semantics, cardinality/multiplicity enforcement, composite index
reads/uniqueness, traversal semantics on the Graph of the Gods.
"""

import pytest

from janusgraph_tpu.core import gods
from janusgraph_tpu.core.attributes import GeoshapePoint
from janusgraph_tpu.core.codecs import Cardinality, Direction, Multiplicity
from janusgraph_tpu.core.graph import open_graph
from janusgraph_tpu.core.traversal import P
from janusgraph_tpu.exceptions import SchemaViolationError


@pytest.fixture
def graph():
    g = open_graph({"ids.block-size": 500, "ids.authority-wait-ms": 0.0})
    yield g
    g.close()


@pytest.fixture
def gods_graph(graph):
    gods.load(graph)
    return graph


# ------------------------------------------------------------------ basic CRUD
def test_add_and_read_vertex(graph):
    tx = graph.new_transaction()
    v = tx.add_vertex("person", name="alice", age=30)
    vid = v.id
    tx.commit()

    tx2 = graph.new_transaction()
    v2 = tx2.get_vertex(vid)
    assert v2 is not None
    assert v2.value("name") == "alice"
    assert v2.value("age") == 30
    assert v2.label == "person"


def test_edge_roundtrip_both_directions(graph):
    tx = graph.new_transaction()
    a = tx.add_vertex(name="a")
    b = tx.add_vertex(name="b")
    e = tx.add_edge(a, "knows", b, weight=0.5)
    tx.commit()

    tx2 = graph.new_transaction()
    a2, b2 = tx2.get_vertex(a.id), tx2.get_vertex(b.id)
    out = tx2.get_edges(a2, Direction.OUT, ("knows",))
    assert len(out) == 1
    assert out[0].in_vertex.id == b.id
    assert out[0].value("weight") == 0.5
    inn = tx2.get_edges(b2, Direction.IN, ("knows",))
    assert len(inn) == 1
    assert inn[0].out_vertex.id == a.id
    assert inn[0].id == out[0].id


def test_tx_overlay_visible_before_commit(graph):
    tx = graph.new_transaction()
    a = tx.add_vertex(name="a")
    b = tx.add_vertex(name="b")
    tx.add_edge(a, "knows", b)
    # same-tx visibility
    assert [e.in_vertex.id for e in tx.get_edges(a, Direction.OUT, ())] == [b.id]
    assert a.value("name") == "a"
    # isolation: other tx sees nothing
    tx2 = graph.new_transaction()
    assert tx2.get_vertex(a.id) is None


def test_rollback_discards_everything(graph):
    tx = graph.new_transaction()
    v = tx.add_vertex(name="ghost")
    vid = v.id
    tx.rollback()
    tx2 = graph.new_transaction()
    assert tx2.get_vertex(vid) is None


def test_remove_vertex_removes_incident_edges(graph):
    tx = graph.new_transaction()
    a = tx.add_vertex(name="a")
    b = tx.add_vertex(name="b")
    tx.add_edge(a, "knows", b)
    tx.commit()

    tx2 = graph.new_transaction()
    tx2.get_vertex(b.id).remove()
    tx2.commit()

    tx3 = graph.new_transaction()
    assert tx3.get_vertex(b.id) is None
    a3 = tx3.get_vertex(a.id)
    assert tx3.get_edges(a3, Direction.OUT, ()) == []


def test_remove_edge(graph):
    tx = graph.new_transaction()
    a = tx.add_vertex(name="a")
    b = tx.add_vertex(name="b")
    tx.add_edge(a, "knows", b)
    tx.commit()

    tx2 = graph.new_transaction()
    e = tx2.get_edges(tx2.get_vertex(a.id), Direction.OUT, ("knows",))[0]
    e.remove()
    tx2.commit()

    tx3 = graph.new_transaction()
    assert tx3.get_edges(tx3.get_vertex(a.id), Direction.OUT, ()) == []
    assert tx3.get_edges(tx3.get_vertex(b.id), Direction.IN, ()) == []


# ----------------------------------------------------------- schema constraints
def test_single_cardinality_replaces(graph):
    tx = graph.new_transaction()
    v = tx.add_vertex(name="x")
    tx.commit()

    tx2 = graph.new_transaction()
    v2 = tx2.get_vertex(v.id)
    v2.property("name", "y")
    assert v2.value("name") == "y"
    tx2.commit()

    tx3 = graph.new_transaction()
    assert tx3.get_vertex(v.id).values("name") == ["y"]


def test_set_cardinality(graph):
    mgmt = graph.management()
    mgmt.make_property_key("nick", str, Cardinality.SET)
    tx = graph.new_transaction()
    v = tx.add_vertex()
    v.property("nick", "ace")
    v.property("nick", "ace")  # duplicate collapses
    v.property("nick", "blade")
    tx.commit()

    tx2 = graph.new_transaction()
    assert sorted(tx2.get_vertex(v.id).values("nick")) == ["ace", "blade"]


def test_list_cardinality(graph):
    mgmt = graph.management()
    mgmt.make_property_key("score", int, Cardinality.LIST)
    tx = graph.new_transaction()
    v = tx.add_vertex()
    v.property("score", 1)
    v.property("score", 1)
    v.property("score", 2)
    tx.commit()

    tx2 = graph.new_transaction()
    assert sorted(tx2.get_vertex(v.id).values("score")) == [1, 1, 2]


def test_property_type_enforced(graph):
    mgmt = graph.management()
    mgmt.make_property_key("cnt", int)
    tx = graph.new_transaction()
    v = tx.add_vertex()
    with pytest.raises(SchemaViolationError):
        v.property("cnt", "not-a-number")


def test_strict_schema_rejects_undefined(graph):
    graph.auto_schema = False
    tx = graph.new_transaction()
    with pytest.raises(SchemaViolationError):
        tx.add_vertex(name="nope")


def test_multiplicity_many2one(graph):
    mgmt = graph.management()
    mgmt.make_edge_label("father", Multiplicity.MANY2ONE)
    tx = graph.new_transaction()
    a, b, c = tx.add_vertex(), tx.add_vertex(), tx.add_vertex()
    tx.add_edge(a, "father", b)
    with pytest.raises(SchemaViolationError):
        tx.add_edge(a, "father", c)
    tx.add_edge(c, "father", b)  # other out-vertex fine
    tx.commit()
    # enforced against committed state too
    tx2 = graph.new_transaction()
    with pytest.raises(SchemaViolationError):
        tx2.add_edge(tx2.get_vertex(a.id), "father", tx2.get_vertex(c.id))


def test_multiplicity_simple(graph):
    mgmt = graph.management()
    mgmt.make_edge_label("married", Multiplicity.SIMPLE)
    tx = graph.new_transaction()
    a, b = tx.add_vertex(), tx.add_vertex()
    tx.add_edge(a, "married", b)
    with pytest.raises(SchemaViolationError):
        tx.add_edge(a, "married", b)


def test_duplicate_schema_name_rejected(graph):
    mgmt = graph.management()
    mgmt.make_property_key("p1", str)
    with pytest.raises(SchemaViolationError):
        mgmt.make_property_key("p1", int)
    with pytest.raises(SchemaViolationError):
        mgmt.make_edge_label("p1")


# -------------------------------------------------------------- composite index
def test_index_lookup_and_maintenance(graph):
    mgmt = graph.management()
    mgmt.make_property_key("user", str)
    mgmt.build_composite_index("byUser", ["user"])
    tx = graph.new_transaction()
    v1 = tx.add_vertex(user="sam")
    v2 = tx.add_vertex(user="sam")
    v3 = tx.add_vertex(user="max")
    tx.commit()

    tx2 = graph.new_transaction()
    assert sorted(graph.index_lookup(tx2, "byUser", ["sam"])) == sorted([v1.id, v2.id])
    assert graph.index_lookup(tx2, "byUser", ["max"]) == [v3.id]
    # update moves index entry
    tx2.get_vertex(v3.id).property("user", "sam")
    tx2.commit()
    tx3 = graph.new_transaction()
    assert graph.index_lookup(tx3, "byUser", ["max"]) == []
    assert len(graph.index_lookup(tx3, "byUser", ["sam"])) == 3
    # vertex removal clears index entry
    tx3.get_vertex(v1.id).remove()
    tx3.commit()
    tx4 = graph.new_transaction()
    assert sorted(graph.index_lookup(tx4, "byUser", ["sam"])) == sorted([v2.id, v3.id])


def test_unique_index_enforced(graph):
    mgmt = graph.management()
    mgmt.make_property_key("ssn", str)
    mgmt.build_composite_index("bySsn", ["ssn"], unique=True)
    tx = graph.new_transaction()
    tx.add_vertex(ssn="123")
    tx.commit()
    tx2 = graph.new_transaction()
    tx2.add_vertex(ssn="123")
    with pytest.raises(SchemaViolationError):
        tx2.commit()


def test_multikey_index(graph):
    mgmt = graph.management()
    mgmt.make_property_key("first", str)
    mgmt.make_property_key("last", str)
    mgmt.build_composite_index("byName", ["first", "last"])
    tx = graph.new_transaction()
    v = tx.add_vertex(first="ada", last="lovelace")
    tx.add_vertex(first="ada")  # incomplete: not indexed
    tx.commit()
    tx2 = graph.new_transaction()
    assert graph.index_lookup(tx2, "byName", ["ada", "lovelace"]) == [v.id]


# ------------------------------------------------------------- gods + traversal
def test_gods_counts(gods_graph):
    g = gods_graph.traversal()
    assert g.V().count() == 12
    assert g.E().count() == 17


def test_gods_index_traversal(gods_graph):
    g = gods_graph.traversal()
    saturn = g.V().has("name", "saturn").next()
    assert saturn.value("age") == 10000
    assert saturn.label == "titan"
    # grandchild: who calls saturn grandfather? hercules
    names = g.V().has("name", "saturn").in_("father").in_("father").values("name").to_list()
    assert names == ["hercules"]


def test_gods_battles(gods_graph):
    g = gods_graph.traversal()
    monsters = (
        g.V().has("name", "hercules").out("battled").values("name").to_set()
    )
    assert monsters == {"nemean", "hydra", "cerberus"}
    # edge property filter: battles after time 1
    late = (
        gods_graph.traversal()
        .V()
        .has("name", "hercules")
        .out_e("battled")
        .has("time", P.gt(1))
        .in_v()
        .values("name")
        .to_set()
    )
    assert late == {"hydra", "cerberus"}


def test_gods_label_and_predicates(gods_graph):
    g = gods_graph.traversal()
    god_names = g.V().has_label("god").values("name").to_set()
    assert god_names == {"jupiter", "neptune", "pluto"}
    olds = gods_graph.traversal().V().has("age", P.gte(4500)).values("name").to_set()
    assert olds == {"saturn", "jupiter", "neptune"}


def test_gods_both_and_dedup(gods_graph):
    g = gods_graph.traversal()
    brothers = g.V().has("name", "jupiter").both("brother").dedup().values("name").to_set()
    assert brothers == {"neptune", "pluto"}


def test_gods_group_count(gods_graph):
    g = gods_graph.traversal()
    by_label = g.V().group_count(None)
    # group by label via label_()
    labels = gods_graph.traversal().V().label_().group_count()
    assert labels["god"] == 3
    assert labels["location"] == 3
    assert sum(by_label.values()) == 12


def test_gods_repeat(gods_graph):
    g = gods_graph.traversal()
    # pluto -> brother -> brother (2 hops) includes pluto again
    two_hop = (
        g.V().has("name", "pluto").repeat(lambda t: t.both("brother"), times=2)
        .values("name").to_set()
    )
    assert "pluto" in two_hop


def test_gods_age_index(gods_graph):
    tx = gods_graph.new_transaction()
    assert len(gods_graph.index_lookup(tx, "age", [5000])) == 1


def test_gods_unique_name(gods_graph):
    tx = gods_graph.new_transaction()
    tx.add_vertex("god", name="jupiter")
    with pytest.raises(SchemaViolationError):
        tx.commit()


def test_traversal_with_uncommitted_data(gods_graph):
    g = gods_graph.traversal()
    v = g.add_v("god", name="minerva", age=900)
    assert g.V().has("name", "minerva").count() == 1
    assert g.V().count() == 13
    g.rollback()
    assert gods_graph.traversal().V().count() == 12


def test_sort_key_edges_ordered(gods_graph):
    """battled edges carry a `time` sort key: stored column order == time
    order (vertex-centric index parity)."""
    tx = gods_graph.new_transaction()
    g = gods_graph.traversal()
    herc = g.V().has("name", "hercules").next()
    edges = gods_graph.traversal().V().has("name", "hercules").out_e("battled").to_list()
    times = [e.value("time") for e in edges]
    assert times == sorted(times)


def test_bigint_schema_key_accepts_plain_int():
    from janusgraph_tpu.core.attributes import BigInt
    from janusgraph_tpu.core.graph import open_graph

    graph = open_graph()
    graph.management().make_property_key("bignum", data_type=BigInt)
    tx = graph.new_transaction()
    v = tx.add_vertex()
    v.property("bignum", 2**100)  # plain int promotes
    tx.commit()
    tx2 = graph.new_transaction()
    got = tx2.get_vertex(v.id).value("bignum")
    assert got == 2**100
    # read-back value (plain int) is legal to write again
    w = tx2.add_vertex()
    w.property("bignum", got)
    tx2.commit()
    graph.close()


def test_drop_graph_destroys_everything():
    """JanusGraphFactory.drop analogue: storage, indexes, and instance
    registry all gone; a re-open over the same manager starts empty."""
    from janusgraph_tpu.core.graph import drop_graph, open_graph
    from janusgraph_tpu.core.traversal import P
    from janusgraph_tpu.storage.inmemory import InMemoryStoreManager

    sm = InMemoryStoreManager()
    g = open_graph({"schema.default": "auto"}, store_manager=sm)
    mgmt = g.management()
    mgmt.make_property_key("bio", str)
    mgmt.build_mixed_index("bios", ["bio"], backing="search")
    tx = g.new_transaction()
    tx.add_vertex(name="doomed", bio="soon gone")
    tx.commit()
    assert g.traversal().V().has("bio", P.text_contains("gone")).to_list()
    drop_graph(g)
    g2 = open_graph({"schema.default": "auto"}, store_manager=sm)
    assert g2.traversal().V().to_list() == []
    # schema gone too: the old mixed index no longer exists
    assert "bios" not in g2.indexes
    g2.close()


def test_drop_graph_local_backend_releases_and_destroys(tmp_path):
    """drop over the persistent backend: exists() false afterward, WAL
    handle released, re-open empty (the close/clear ordering regression)."""
    from janusgraph_tpu.core.graph import drop_graph, open_graph

    d = str(tmp_path / "dropme")
    g = open_graph({
        "schema.default": "auto", "storage.backend": "local",
        "storage.directory": d, "storage.fsync": False,
    })
    tx = g.new_transaction()
    tx.add_vertex(name="gone")
    tx.commit()
    drop_graph(g)
    g2 = open_graph({
        "schema.default": "auto", "storage.backend": "local",
        "storage.directory": d, "storage.fsync": False,
    })
    assert g2.traversal().V().to_list() == []
    g2.close()
