"""Concurrent OLTP smoke (reference: JanusGraphConcurrentTest.java:482 —
many threads mutating and reading one graph instance must neither corrupt
state nor raise; RandomRemovalList-style interleaving)."""

import random
import threading

from janusgraph_tpu.core.codecs import Direction
from janusgraph_tpu.core.graph import open_graph


def test_threaded_writers_and_readers():
    g = open_graph({"schema.default": "auto", "ids.authority-wait-ms": 0.0})
    # seed a hub so readers always have something to traverse
    tx = g.new_transaction()
    hub = tx.add_vertex(name="hub")
    tx.commit()
    hub_id = hub.id

    errors = []
    written = [0]
    lock = threading.Lock()
    N_WRITERS, N_READERS, OPS = 4, 3, 40

    def writer(seed):
        rng = random.Random(seed)
        try:
            for i in range(OPS):
                tx = g.new_transaction()
                v = tx.add_vertex(name=f"w{seed}-{i}", score=rng.random())
                h = tx.get_vertex(hub_id)
                tx.add_edge(h, "spoke", v)
                tx.commit()
                with lock:
                    written[0] += 1
        except Exception as e:  # noqa: BLE001
            errors.append(("writer", seed, repr(e)))

    def reader(seed):
        rng = random.Random(1000 + seed)
        try:
            for _ in range(OPS):
                tx = g.new_transaction()
                h = tx.get_vertex(hub_id)
                edges = list(tx.get_edges(h, Direction.OUT, ("spoke",)))
                # every visible edge must resolve to a live, named vertex
                for e in rng.sample(edges, min(3, len(edges))):
                    assert e.in_vertex.value("name") is not None
                tx.rollback()
        except Exception as e:  # noqa: BLE001
            errors.append(("reader", seed, repr(e)))

    threads = [
        threading.Thread(target=writer, args=(s,)) for s in range(N_WRITERS)
    ] + [
        threading.Thread(target=reader, args=(s,)) for s in range(N_READERS)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    assert written[0] == N_WRITERS * OPS
    # final state: exactly one spoke per committed writer op, all distinct
    tx = g.new_transaction()
    edges = list(tx.get_edges(tx.get_vertex(hub_id), Direction.OUT, ("spoke",)))
    assert len(edges) == N_WRITERS * OPS
    names = {e.in_vertex.value("name") for e in edges}
    assert len(names) == N_WRITERS * OPS  # no duplicated/lost vertices
    g.close()


def test_threaded_id_allocation_unique():
    """Concurrent vertex creation must never hand out one id twice
    (reference: IDAuthorityTest.java:510 concurrent allocators)."""
    g = open_graph({"ids.block-size": 50, "ids.authority-wait-ms": 0.0,
                    "schema.default": "auto"})
    ids, errors = [], []
    lock = threading.Lock()

    def alloc(seed):
        try:
            got = []
            for i in range(120):
                tx = g.new_transaction()
                v = tx.add_vertex(name=f"a{seed}-{i}")
                tx.commit()
                got.append(v.id)
            with lock:
                ids.extend(got)
        except Exception as e:  # noqa: BLE001
            errors.append(repr(e))

    threads = [threading.Thread(target=alloc, args=(s,)) for s in range(5)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    assert len(ids) == 5 * 120
    assert len(set(ids)) == len(ids)  # globally unique across threads
    g.close()
