"""Observability plane tests (ISSUE 13): time-series history windows,
SLO burn-rate ladder on a fake clock, superstep timelines as valid
Chrome-trace JSON, benchdiff verdicts, and the seeded injected-latency
storm whose SLO burn-alert sequence is byte-stable across runs."""

import json
import urllib.error
import urllib.request

import pytest

from janusgraph_tpu.observability import (
    flight_recorder,
    registry,
    tracer,
)
from janusgraph_tpu.observability.metrics_core import TelemetryRegistry
from janusgraph_tpu.observability.slo import (
    DIGEST_TIMER_PREFIX,
    SLOEngine,
    SLOSpec,
    default_specs,
)
from janusgraph_tpu.observability.timeline import (
    chrome_trace,
    render_run,
    validate_chrome_trace,
)
from janusgraph_tpu.observability.timeseries import MetricsHistory

SEED = 1234


@pytest.fixture(autouse=True)
def _reset_telemetry():
    registry.reset()
    tracer.reset()
    flight_recorder.reset()
    yield
    registry.reset()
    tracer.reset()
    flight_recorder.reset()


def _fake_clock(start=1000.0):
    state = {"t": start}

    def clock():
        state["t"] += 1.0
        return state["t"]

    return clock


def _history(reg, capacity=64):
    return MetricsHistory(
        reg, capacity=capacity, interval_s=1.0,
        clock=_fake_clock(), wall_clock=_fake_clock(5000.0),
    )


# ---------------------------------------------------------------- history
def test_counter_windows_store_deltas():
    m = TelemetryRegistry()
    h = _history(m)
    m.counter("x.ops").inc(10)
    w1 = h.sample()
    m.counter("x.ops").inc(3)
    w2 = h.sample()
    w3 = h.sample()  # idle window: no delta entry at all
    assert w1["counters"]["x.ops"] == 10
    assert w2["counters"]["x.ops"] == 3
    assert "x.ops" not in w3["counters"]
    pts = h.series("x.ops")
    assert [p["delta"] for p in pts] == [10, 3]


def test_counter_delta_survives_registry_restart():
    """A reset (restart) registry re-counts from zero; the window delta
    is the full new value, never negative — the Prometheus rate() reset
    convention."""
    m = TelemetryRegistry()
    h = _history(m)
    m.counter("x.ops").inc(100)
    h.sample()
    m.reset()  # the "restart"
    m.counter("x.ops").inc(7)
    w = h.sample()
    assert w["counters"]["x.ops"] == 7
    assert all(
        delta >= 0 for win in h.windows() for delta in win["counters"].values()
    )


def test_timer_windows_percentiles_are_windowed_not_lifetime():
    m = TelemetryRegistry()
    h = _history(m)
    t = m.timer("req.wall")
    for _ in range(100):
        t.update(1_000_000)  # 1 ms era
    h.sample()
    for _ in range(100):
        t.update(100_000_000)  # 100 ms era
    w = h.sample()
    s = w["series"]["req.wall"]
    assert s["count"] == 100
    # the second WINDOW is all-slow even though lifetime p50 is fast
    assert s["p50"] >= 100_000_000 / 2
    assert sum(s["buckets"]) == s["count"]


def test_gauge_windows_store_sampled_values():
    m = TelemetryRegistry()
    h = _history(m)
    m.set_gauge("aimd.limit", 8.0)
    h.sample()
    m.set_gauge("aimd.limit", 4.0)
    h.sample()
    assert [p["value"] for p in h.series("aimd.limit")] == [8.0, 4.0]


def test_retention_evicts_oldest_windows():
    m = TelemetryRegistry()
    h = _history(m, capacity=4)
    for i in range(10):
        m.counter("x").inc()
        h.sample()
    ws = h.windows()
    assert len(ws) == 4
    assert [w["seq"] for w in ws] == [7, 8, 9, 10]
    # and reconfiguring retention down trims in place
    h.configure(capacity=2)
    assert len(h.windows()) == 2


def test_query_payload_and_prefix_filter():
    m = TelemetryRegistry()
    h = _history(m)
    m.counter("a.ops").inc()
    m.counter("b.ops").inc()
    m.set_gauge("a.depth", 2.0)
    h.sample()
    payload = h.query(name="a.")
    assert set(payload["series"]) == {"a.ops", "a.depth"}
    assert payload["windows"] == 1
    json.dumps(payload)  # JSON-clean
    # window bound: only the last N windows surface
    m.counter("a.ops").inc()
    h.sample()
    bounded = h.query(name="a.ops", window=1)
    assert len(bounded["series"]["a.ops"]) == 1


def test_export_jsonl_roundtrip(tmp_path):
    m = TelemetryRegistry()
    h = _history(m)
    m.counter("x").inc(5)
    h.sample()
    m.counter("x").inc(2)
    h.sample()
    path = str(tmp_path / "history.jsonl")
    assert h.export_jsonl(path) == 2
    lines = [json.loads(ln) for ln in open(path)]
    assert [ln["counters"].get("x") for ln in lines] == [5, 2]
    # full bucket vectors ride along for offline percentile math
    assert all("series" in ln for ln in lines)


def test_sample_sets_overhead_gauge():
    m = TelemetryRegistry()
    h = _history(m)
    h.sample()
    snap = m.snapshot()
    assert "observability.history.overhead_ms" in snap
    assert snap["observability.history.overhead_ms"]["value"] >= 0
    assert snap["observability.history.sample"]["count"] == 1


# ------------------------------------------------------------- SLO engine
def _avail_spec(**kw):
    base = dict(
        name="availability", kind="availability", objective=0.99,
        good_counter="good", bad_counter="bad",
        fast_windows=2, slow_windows=4,
        page_burn=10.0, ticket_burn=3.0, clear_windows=2,
    )
    base.update(kw)
    return SLOSpec(**base)


def _engine(m, spec):
    h = _history(m)
    eng = SLOEngine(h, [spec])
    return h, eng


def _traffic(m, good, bad):
    if good:
        m.counter("good").inc(good)
    if bad:
        m.counter("bad").inc(bad)


def test_burn_rate_math_availability():
    m = TelemetryRegistry()
    h, eng = _engine(m, _avail_spec())
    # error rate 0.2 over a 0.01 budget = burn 20 in both windows
    _traffic(m, 80, 20)
    h.sample()
    _traffic(m, 80, 20)
    h.sample()
    alerts = eng.evaluate()
    assert alerts[0]["fast_burn"] == pytest.approx(20.0)
    assert alerts[0]["slow_burn"] == pytest.approx(20.0)
    assert alerts[0]["severity"] == "page"


def test_no_traffic_means_no_burn():
    m = TelemetryRegistry()
    h, eng = _engine(m, _avail_spec())
    h.sample()
    alerts = eng.evaluate()
    assert alerts[0]["fast_burn"] == 0.0
    assert alerts[0]["severity"] == "ok"


def test_both_windows_must_burn_to_alert():
    """One hot fast window with a cold slow window is a blip, not an
    alert — the multi-window veto."""
    m = TelemetryRegistry()
    h, eng = _engine(m, _avail_spec(fast_windows=1, slow_windows=8))
    for _ in range(7):
        _traffic(m, 300, 0)
        h.sample()
        eng.evaluate()
    _traffic(m, 0, 100)  # one catastrophic window
    h.sample()
    alerts = eng.evaluate()
    assert alerts[0]["fast_burn"] > 10.0
    assert alerts[0]["slow_burn"] < 10.0 * 0.9
    assert alerts[0]["severity"] in ("ok", "ticket")


def test_enter_exit_hysteresis_matrix():
    """The full ladder walk: ok -> ticket -> page, then exit one rung at
    a time only after clear_windows consecutive clean evaluations."""
    m = TelemetryRegistry()
    spec = _avail_spec(fast_windows=1, slow_windows=1)
    h, eng = _engine(m, spec)

    def step(good, bad):
        _traffic(m, good, bad)
        h.sample()
        return eng.evaluate()[0]["severity"]

    assert step(100, 0) == "ok"
    # burn 5 (rate 0.05 / budget 0.01): past ticket_burn=3, below page=10
    assert step(95, 5) == "ticket"
    # burn 50: page
    assert step(50, 50) == "page"
    # still burning: stays page
    assert step(50, 50) == "page"
    # clean window 1 of 2: still page (hysteresis)
    assert step(100, 0) == "page"
    # clean window 2: exits ONE rung, to ticket
    assert step(100, 0) == "ticket"
    # two more clean windows: back to ok
    step(100, 0)
    assert step(100, 0) == "ok"
    # flight recorded every transition with direction
    dirs = [
        (e["severity"], e["direction"])
        for e in flight_recorder.events("slo_burn")
    ]
    assert dirs == [
        ("ticket", "enter"), ("page", "enter"),
        ("ticket", "exit"), ("ok", "exit"),
    ]


def test_partial_recovery_resets_clear_streak():
    m = TelemetryRegistry()
    spec = _avail_spec(fast_windows=1, slow_windows=1, clear_windows=2)
    h, eng = _engine(m, spec)

    def step(good, bad):
        _traffic(m, good, bad)
        h.sample()
        return eng.evaluate()[0]["severity"]

    step(50, 50)
    assert step(50, 50) == "page"
    assert step(100, 0) == "page"   # clean 1/2
    assert step(50, 50) == "page"   # relapse resets the streak
    assert step(100, 0) == "page"   # clean 1/2 again
    assert step(100, 0) == "ticket"


def test_slo_gauges_published():
    m = TelemetryRegistry()
    h, eng = _engine(m, _avail_spec(fast_windows=1, slow_windows=1))
    _traffic(m, 50, 50)
    h.sample()
    eng.evaluate()
    # gauges land in the PROCESS registry (the /metrics surface)
    snap = registry.snapshot()
    assert snap["observability.slo.availability.burn_fast"]["value"] > 0
    assert snap["observability.slo.availability.severity"]["value"] == 2.0


def test_latency_slo_counts_over_threshold_fraction():
    m = TelemetryRegistry()
    spec = SLOSpec(
        name="latency", kind="latency", objective=0.9,
        metric="req.wall", threshold_ms=10.0,
        fast_windows=1, slow_windows=1,
        page_burn=5.0, ticket_burn=2.0,
    )
    h, eng = _engine(m, spec)
    t = m.timer("req.wall")
    for _ in range(20):
        t.update(1_000_000)      # 1 ms: good
    for _ in range(80):
        t.update(1_000_000_000)  # 1 s: bad
    h.sample()
    a = eng.evaluate()[0]
    # error rate 0.8 / budget 0.1 = burn 8
    assert a["fast_burn"] == pytest.approx(8.0)
    assert a["severity"] == "page"


def test_latency_slo_digest_classes_priced_from_book():
    """With metric='' the engine evaluates per-digest-class timers, each
    held to price_factor x its book mean (floored at threshold_ms): an
    expensive analytical shape is allowed its measured cost."""
    from janusgraph_tpu.observability.profiler import DigestTable

    m = TelemetryRegistry()
    book = DigestTable(capacity=8)
    book.observe("deadbeef", "server>g.V().count()", 100.0)  # mean 100ms
    spec = SLOSpec(
        name="latency", kind="latency", objective=0.9,
        metric="", threshold_ms=10.0, price_factor=4.0,
        fast_windows=1, slow_windows=1,
        page_burn=5.0, ticket_burn=2.0,
    )
    h = _history(m)
    eng = SLOEngine(h, [spec], price_book_fn=lambda: book)
    t = m.timer(DIGEST_TIMER_PREFIX + "deadbeef")
    for _ in range(100):
        t.update(int(200e6))  # 200 ms: under 4 x 100 ms -> GOOD
    h.sample()
    assert eng.evaluate()[0]["severity"] == "ok"
    for _ in range(100):
        t.update(int(900e6))  # 900 ms: over the priced 400 ms -> BAD
    h.sample()
    a = eng.evaluate()[0]
    assert a["fast_burn"] > 5.0
    assert a["severity"] == "page"


def test_freshness_slo_from_staleness_gauge():
    m = TelemetryRegistry()
    spec = SLOSpec(
        name="olap_freshness", kind="freshness", objective=0.99,
        gauge="olap.spillover.staleness", max_staleness=100.0,
        fast_windows=1, slow_windows=1,
        page_burn=10.0, ticket_burn=3.0, clear_windows=1,
    )
    h, eng = _engine(m, spec)
    m.set_gauge("olap.spillover.staleness", 50.0)
    h.sample()
    assert eng.evaluate()[0]["severity"] == "ok"  # half the bound
    m.set_gauge("olap.spillover.staleness", 2000.0)  # 20x the bound
    h.sample()
    a = eng.evaluate()[0]
    assert a["severity"] == "page"
    m.set_gauge("olap.spillover.staleness", 0.0)
    h.sample()
    eng.evaluate()
    h.sample()
    assert eng.evaluate()[0]["severity"] in ("ticket", "ok")


def test_engine_installs_on_history_listener():
    m = TelemetryRegistry()
    h = _history(m)
    eng = SLOEngine(h, [_avail_spec(fast_windows=1, slow_windows=1)])
    eng.install()
    _traffic(m, 0, 100)
    h.sample()  # listener fires evaluate()
    assert eng.snapshot()["worst"] == "page"
    eng.uninstall()


# ------------------------------------------------------- timeline renderer
def _fused_record():
    return {
        "path": "fused", "executor": "tpu", "supersteps": 3,
        "wall_s": 0.3, "pad_ratio": 1.1,
        "superstep_records": [
            {"step": 0, "wall_ms": 100.0, "approx": True, "frontier": 64},
            {"step": 1, "wall_ms": 100.0, "approx": True, "frontier": 64},
            {"step": 2, "wall_ms": 100.0, "approx": True, "frontier": 64,
             "checkpoint_ms": 4.0},
        ],
    }


def _sharded_record():
    return {
        "path": "host-loop", "executor": "sharded", "supersteps": 2,
        "wall_s": 0.2, "resumes": 1, "resume_ms": 12.0,
        "checkpoint": {"format": "sharded", "saves": 1},
        "superstep_records": [
            {"step": 0, "wall_ms": 80.0},
            {"step": 1, "wall_ms": 90.0, "checkpoint_ms": 6.0},
        ],
        "exchange": {
            "mode": "blocked", "agg": "ell",
            "elems_per_superstep": 2048,
            "bytes_per_superstep": 16384,
            "batches_per_superstep": 1,
        },
        "shards": {"per_shard": [
            {"shard": 0, "modeled_ms": 40.0, "cost_source": "plan"},
            {"shard": 1, "modeled_ms": 20.0, "cost_source": "plan"},
        ]},
    }


def test_timeline_fused_is_valid_chrome_trace():
    doc = chrome_trace(_fused_record())
    assert validate_chrome_trace(doc) is None
    slices = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    steps = [e for e in slices if e["name"].startswith("superstep")]
    assert len(steps) == 3
    # cumulative, gap-free timestamps
    assert steps[1]["ts"] == pytest.approx(
        steps[0]["ts"] + steps[0]["dur"]
    )
    # the checkpoint save renders on the control lane at step 2's tail
    saves = [e for e in slices if e["name"] == "checkpoint_save"]
    assert len(saves) == 1
    assert saves[0]["args"]["step"] == 2
    assert saves[0]["dur"] == pytest.approx(4000.0)


def test_timeline_sharded_resumed_run():
    """The acceptance shape: sharded + resumed loads as valid catapult
    JSON with per-shard compute/exchange lanes and the resume slice."""
    doc = chrome_trace(_sharded_record())
    assert validate_chrome_trace(doc) is None
    evs = doc["traceEvents"]
    lanes = {e["args"]["name"] for e in evs if e["ph"] == "M"}
    assert {"shard 0", "shard 1", "checkpoint"} <= lanes
    computes = [e for e in evs if e["name"] == "compute"]
    exchanges = [e for e in evs if e["name"] == "exchange"]
    assert len(computes) == 4 and len(exchanges) == 4  # 2 shards x 2 steps
    # shard 0 is the pace-setter: full share; shard 1 half
    s0 = [e for e in computes if e["tid"] == 2][0]
    s1 = [e for e in computes if e["tid"] == 3][0]
    assert s1["dur"] == pytest.approx(s0["dur"] / 2)
    # exchange covers the rest of the superstep and carries the volume
    assert exchanges[0]["args"]["mode"] == "blocked"
    assert exchanges[0]["args"]["bytes_per_superstep"] == 16384
    # the resume slice shifts every superstep right
    resume = [e for e in evs if e["name"].startswith("resume")][0]
    assert resume["dur"] == pytest.approx(12_000.0)
    first_step = [e for e in evs if e["name"] == "superstep 0"][0]
    assert first_step["ts"] == pytest.approx(12_000.0)
    json.dumps(doc)


def test_timeline_real_resumed_run_via_registry(tmp_path):
    """A REAL preempted-and-resumed PageRank run (PR 3 chaos plane)
    renders from the registry's run record: valid trace, resume slice,
    checkpoint saves from the executor's checkpoint_ms markers."""
    from janusgraph_tpu.olap.computer import run_on
    from janusgraph_tpu.olap.generators import rmat_csr
    from janusgraph_tpu.olap.programs.pagerank import PageRankProgram
    from janusgraph_tpu.storage.faults import FaultPlan

    csr = rmat_csr(6, 4)
    plan = FaultPlan(seed=SEED, preempt_superstep=4)
    run_on(
        csr, PageRankProgram(max_iterations=8), "tpu",
        checkpoint_path=str(tmp_path / "pr.npz"), checkpoint_every=2,
        fault_hook=plan.olap_hook,
    )
    rec = registry.last_run("olap")
    assert rec["resumes"] >= 1
    assert rec.get("resume_steps")
    assert any(
        "checkpoint_ms" in r for r in rec["superstep_records"]
    )
    doc = render_run(registry)
    assert validate_chrome_trace(doc) is None
    names = [e["name"] for e in doc["traceEvents"]]
    assert any(n.startswith("resume") for n in names)
    assert "checkpoint_save" in names


def test_timeline_run_index_and_missing():
    assert render_run(registry) is None  # nothing retained
    registry.record_run("olap", _fused_record())
    registry.record_run("olap", _sharded_record())
    last = render_run(registry)
    assert last["otherData"]["executor"] == "sharded"
    first = render_run(registry, run=0)
    assert first["otherData"]["executor"] == "tpu"
    assert render_run(registry, run=7) is None


# --------------------------------------------------------------- benchdiff
def _stage(ms, **kw):
    s = {"stage": "pagerank", "platform": "cpu", "scale": 16,
         "pagerank_superstep_ms": ms}
    s.update(kw)
    return s


def test_benchdiff_verdict_matrix():
    from janusgraph_tpu.observability.benchdiff import compare

    old = _stage(100.0)
    assert compare(old, _stage(120.0))["verdict"] == "regress"
    assert compare(old, _stage(80.0))["verdict"] == "improve"
    assert compare(old, _stage(105.0))["verdict"] == "noise"
    # higher-is-better metrics flip the direction
    o = {"stage": "saturate", "platform": "cpu",
         "peak_goodput_per_s": 100.0}
    n = dict(o, peak_goodput_per_s=70.0)
    assert compare(o, n)["verdict"] == "regress"


def test_benchdiff_cell_matching_is_strict():
    from janusgraph_tpu.observability.benchdiff import (
        best_prior,
        cell_key,
    )

    stages = [
        _stage(50.0, scale=20),
        _stage(70.0, platform="tpu"),
        _stage(90.0),
        _stage(60.0),
    ]
    best = best_prior(stages, cell_key(_stage(0.0)))
    # only the two (pagerank, 16, cpu) rows compete; the BEST (60) wins
    assert best["pagerank_superstep_ms"] == 60.0
    assert best_prior(stages, cell_key(_stage(0.0, scale=99))) is None


def test_benchdiff_artifact_shapes(tmp_path):
    from janusgraph_tpu.observability.benchdiff import load_stages

    # single stage dict
    p1 = tmp_path / "one.json"
    p1.write_text(json.dumps(_stage(50.0)))
    assert len(load_stages(str(p1))) == 1
    # jsonl of stage lines (+ garbage tolerated)
    p2 = tmp_path / "many.jsonl"
    p2.write_text(
        json.dumps(_stage(50.0)) + "\nnot json\n" +
        json.dumps(_stage(60.0, stage="bfs")) + "\n"
    )
    assert len(load_stages(str(p2))) == 2
    # supervisor wrapper with stage objects embedded in a tail blob
    p3 = tmp_path / "wrap.json"
    p3.write_text(json.dumps({
        "rc": 0,
        "tail": "noise " + json.dumps(_stage(55.0)) + " trailing",
        "parsed": None,
    }))
    st = load_stages(str(p3))
    assert len(st) == 1 and st[0]["pagerank_superstep_ms"] == 55.0


def test_benchdiff_cli_flags_synthetic_regression(tmp_path, capsys):
    """Acceptance: a synthetic 20% superstep_ms regression exits
    non-zero under --fail-on-regress."""
    from janusgraph_tpu.cli import main as cli_main

    old = tmp_path / "old.json"
    new = tmp_path / "new.json"
    old.write_text(json.dumps(_stage(75.0)))
    new.write_text(json.dumps(_stage(90.0)))  # +20%
    assert cli_main(
        ["benchdiff", str(old), str(new), "--fail-on-regress"]
    ) == 1
    report = json.loads(capsys.readouterr().out)
    assert report["regressed"] is True
    m = report["comparisons"][0]["metrics"][0]
    assert m["verdict"] == "regress" and m["delta_pct"] == 20.0
    # without the gate flag the report prints but exits 0
    assert cli_main(["benchdiff", str(old), str(new)]) == 0
    # improvement never fails the gate
    better = tmp_path / "better.json"
    better.write_text(json.dumps(_stage(50.0)))
    assert cli_main(
        ["benchdiff", str(old), str(better), "--fail-on-regress"]
    ) == 0
    capsys.readouterr()


def test_bench_baseline_index_attaches_regression(tmp_path):
    from janusgraph_tpu.observability.benchdiff import BaselineIndex

    art_dir = tmp_path / "arts"
    art_dir.mkdir()
    (art_dir / "r1.json").write_text(json.dumps(_stage(75.0)))
    idx = BaselineIndex([str(art_dir)])
    fresh = _stage(90.0)
    idx.attach_regression(fresh)
    assert fresh["regression"]["verdict"] == "regress"
    # a cell with no baseline gets the no-op note, not a verdict
    novel = _stage(10.0, stage="bfs", bfs_4hop_wall_s=1.0)
    del novel["pagerank_superstep_ms"]
    idx.attach_regression(novel)
    assert novel["regression"]["verdict"] == "no_baseline"


# ------------------------------------------- e2e: seeded latency storm
def _run_latency_storm(seed):
    """One seeded storm: latency decisions from the PR 3 chaos plane's
    pure (seed, kind, index) hash feed the request timer; the SLO engine
    evaluates per window. Returns (masked flight events, alerts)."""
    from janusgraph_tpu.storage.faults import FaultPlan

    m = TelemetryRegistry()
    h = _history(m)
    spec = SLOSpec(
        name="latency", kind="latency", objective=0.9,
        metric="server.request.wall", threshold_ms=50.0,
        fast_windows=2, slow_windows=4,
        page_burn=3.0, ticket_burn=1.5, clear_windows=2,
    )
    eng = SLOEngine(h, [spec])
    eng.install()
    plan = FaultPlan(seed=seed, latency_ms=200.0, latency_rate=0.7)
    t = m.timer("server.request.wall")
    op = 0
    severities = []
    for _window in range(8):
        for _req in range(25):
            # the storm: the plan's pure per-op decision says which
            # requests eat the injected 200 ms spike (vs 2 ms baseline)
            spiked = plan._chance("latency", op, plan.latency_rate)
            wall_ms = 200.0 if spiked else 2.0
            t.update(int(wall_ms * 1e6))
            op += 1
        h.sample()
        severities.append(eng.snapshot()["worst"])
    eng.uninstall()
    masked = [
        {k: v for k, v in e.items() if k not in ("ts", "mono", "seq")}
        for e in flight_recorder.events("slo_burn")
    ]
    return masked, severities


def test_latency_storm_burns_slo_and_reaches_flight():
    events, severities = _run_latency_storm(SEED)
    # the storm (70% spike rate over a 10% budget) must page
    assert "page" in severities
    assert any(
        e["severity"] == "page" and e["direction"] == "enter"
        for e in events
    )


def test_latency_storm_alert_sequence_deterministic_by_seed():
    """Acceptance: same seed -> byte-equal flight slo_burn sequence
    (modulo ts/seq); different seed -> the plan's decisions differ."""
    ev1, sev1 = _run_latency_storm(SEED)
    flight_recorder.reset()
    ev2, sev2 = _run_latency_storm(SEED)
    assert json.dumps(ev1, sort_keys=True) == json.dumps(
        ev2, sort_keys=True
    )
    assert sev1 == sev2


def test_slo_page_degrades_healthz_and_dumps_flight(tmp_path):
    """page burn -> /healthz degraded -> the existing ok->degraded edge
    trigger dumps the flight ring (with the slo_burn events in it)."""
    from janusgraph_tpu.observability import slo_engine
    from janusgraph_tpu.server import server as server_mod

    flight_recorder.configure(dump_dir=str(tmp_path))
    old_specs = slo_engine.specs
    old_states = dict(slo_engine._states)
    m = TelemetryRegistry()
    h = _history(m)
    slo_engine.history = h
    slo_engine.specs = [_avail_spec(fast_windows=1, slow_windows=1)]
    slo_engine.reset()
    try:
        with server_mod._HEALTH_LOCK:
            server_mod._HEALTH_STATE["status"] = None
        hz = server_mod.healthz_snapshot()
        assert hz["status"] == "ok"
        assert hz["slo"]["worst"] == "ok"
        m.counter("good").inc(10)
        m.counter("bad").inc(90)
        h.sample()
        slo_engine.evaluate()
        hz = server_mod.healthz_snapshot()
        assert hz["status"] == "degraded"
        assert hz["slo"]["paging"] == ["availability"]
        # the degradation flip dumped the ring, and the dump holds the
        # slo_burn event that caused it
        dump_path = flight_recorder.last_dump_path
        assert dump_path is not None
        dumped = json.load(open(dump_path))
        assert any(
            e["category"] == "slo_burn" and e["severity"] == "page"
            for e in dumped["events"]
        )
        # staying degraded must not dump again (edge trigger)
        n_dumps = registry.get_count("flight.dumps")
        server_mod.healthz_snapshot()
        assert registry.get_count("flight.dumps") == n_dumps
    finally:
        from janusgraph_tpu.observability.timeseries import (
            history as global_history,
        )

        slo_engine.history = global_history
        slo_engine.specs = old_specs
        slo_engine._states = old_states
        with server_mod._HEALTH_LOCK:
            server_mod._HEALTH_STATE["status"] = None


# --------------------------------------------------------- server surface
@pytest.fixture
def plane_server():
    from janusgraph_tpu.core.graph import open_graph
    from janusgraph_tpu.server import JanusGraphManager, JanusGraphServer

    g = open_graph({"ids.authority-wait-ms": 0.0})
    tx = g.new_transaction()
    tx.add_vertex(name="x")
    tx.commit()
    m = JanusGraphManager()
    m.put_graph("graph", g)
    s = JanusGraphServer(manager=m).start()
    yield s, g
    s.stop()
    g.close()
    from janusgraph_tpu.observability import history, slo_engine

    history.reset()
    slo_engine.reset()


def test_timeseries_endpoint_serves_windows(plane_server):
    s, _g = plane_server
    from janusgraph_tpu.observability import history

    registry.counter("e2e.ops").inc(3)
    history.sample()
    base = f"http://127.0.0.1:{s.port}"
    payload = json.loads(urllib.request.urlopen(
        base + "/timeseries?name=e2e.", timeout=5
    ).read())
    assert payload["series"]["e2e.ops"][0]["delta"] == 3
    assert payload["interval_s"] > 0
    # bad window param is a 400, not a traceback
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(base + "/timeseries?window=x", timeout=5)
    assert ei.value.code == 400


def test_timeline_endpoint_serves_chrome_trace(plane_server):
    s, _g = plane_server
    registry.record_run("olap", _sharded_record())
    base = f"http://127.0.0.1:{s.port}"
    doc = json.loads(urllib.request.urlopen(
        base + "/profile/timeline", timeout=5
    ).read())
    assert validate_chrome_trace(doc) is None
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(
            base + "/profile/timeline?run=99", timeout=5
        )
    assert ei.value.code == 404


def test_server_records_request_timers_for_slo(plane_server):
    s, _g = plane_server
    from janusgraph_tpu.driver import JanusGraphClient

    client = JanusGraphClient(port=s.port)
    for _ in range(3):
        client.submit("g.V().count()")
    snap = registry.snapshot()
    assert snap["server.request.wall"]["count"] >= 3
    digest_timers = [
        n for n in snap if n.startswith(DIGEST_TIMER_PREFIX)
    ]
    # the digest-class timer appears once the shape is in the price book
    assert digest_timers, "no per-digest-class request timer recorded"


def test_cli_timeseries_and_timeline(tmp_path, capsys):
    from janusgraph_tpu.cli import main as cli_main
    from janusgraph_tpu.observability import history

    history.reset()
    history.bind(registry)
    registry.counter("cli.plane").inc(2)
    history.sample()
    assert cli_main(["timeseries", "--name", "cli."]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["series"]["cli.plane"][0]["delta"] == 2
    registry.record_run("olap", _fused_record())
    out = str(tmp_path / "trace.json")
    assert cli_main(["timeline", "--out", out]) == 0
    capsys.readouterr()
    doc = json.load(open(out))
    assert validate_chrome_trace(doc) is None
    history.reset()


def test_history_export_cli(tmp_path, capsys):
    from janusgraph_tpu.cli import main as cli_main
    from janusgraph_tpu.observability import history

    history.reset()
    history.bind(registry)
    registry.counter("cli.exp").inc()
    history.sample()
    path = str(tmp_path / "w.jsonl")
    assert cli_main(["timeseries", "--export", path]) == 0
    capsys.readouterr()
    assert len(open(path).readlines()) == 1
    history.reset()
