"""Unified telemetry tests (janusgraph_tpu/observability/): histogram
percentiles, span nesting + slow-op log, concurrent registry hammering,
Prometheus/JSON exposition, the server scrape endpoints, and the OLAP
submit() span tree with per-superstep children — the ISSUE 2 acceptance
surface."""

import json
import re
import threading
import urllib.request

import numpy as np
import pytest

from janusgraph_tpu.core import gods
from janusgraph_tpu.core.graph import open_graph
from janusgraph_tpu.observability import (
    Histogram,
    json_snapshot,
    prometheus_text,
    registry,
    span,
    tracer,
)
from janusgraph_tpu.observability.exposition import validate_prometheus_text
from janusgraph_tpu.util.metrics import metrics


@pytest.fixture(autouse=True)
def _reset_telemetry():
    metrics.reset()
    tracer.reset()
    tracer.configure(slow_threshold_ms=100.0, max_roots=256, slow_buffer=128)
    yield
    metrics.reset()
    tracer.reset()
    tracer.configure(slow_threshold_ms=100.0, max_roots=256, slow_buffer=128)


# ------------------------------------------------------------------ registry
def test_registry_is_the_util_metrics_singleton():
    """util.metrics absorbed its registry from observability: one object."""
    assert metrics is registry


def test_histogram_percentiles_log_buckets():
    h = Histogram()
    for v in range(1, 1001):
        h.observe(float(v))
    assert h.count == 1000
    assert h.total == pytest.approx(500500.0)
    assert h.max == 1000.0
    # log2 buckets: exact to within 2x
    assert 256 <= h.percentile(0.50) <= 1024
    assert h.percentile(0.95) <= 1024
    assert h.percentile(0.50) <= h.percentile(0.99)


def test_timer_reports_percentiles_uniformly():
    """Satellite: the old flat mean/max timer asymmetry is gone — dict and
    console reporters expose count + p50/p95/p99 for every timer."""
    m = type(metrics)()
    t = m.timer("storage.edgestore.getSlice")
    for ns in (1_000, 10_000, 100_000, 1_000_000, 10_000_000):
        t.update(ns)
    snap = m.snapshot()
    entry = snap["storage.edgestore.getSlice"]
    for key in ("count", "total_ms", "mean_ms", "max_ms",
                "p50_ms", "p95_ms", "p99_ms"):
        assert key in entry, key
    assert entry["count"] == 5
    assert 0 < entry["p50_ms"] <= entry["p95_ms"] <= entry["p99_ms"]
    report = m.report()
    assert "p95_ms" in report and "storage.edgestore.getSlice" in report


def test_snapshot_is_stably_name_sorted_across_kinds():
    m = type(metrics)()
    m.counter("z.counter").inc()
    m.timer("a.timer").update(5)
    m.set_gauge("m.gauge", 3.0)
    m.histogram("b.hist").observe(1.0)
    names = list(m.snapshot())
    assert names == sorted(names)
    # deterministic across repeated snapshots (diff-stable)
    assert list(m.snapshot()) == names


def test_run_records_surface_through_registry():
    m = type(metrics)()
    m.record_run("olap", {"path": "fused", "supersteps": 3})
    m.record_run("olap", {"path": "host-loop", "supersteps": 5})
    assert m.last_run("olap")["supersteps"] == 5
    assert [r["path"] for r in m.runs("olap")] == ["fused", "host-loop"]
    m.reset()
    assert m.last_run("olap") is None


# -------------------------------------------------------------------- spans
def test_span_nesting_and_attrs():
    with tracer.span("outer", kind="test") as o:
        with tracer.span("inner") as i:
            i.annotate(x=1)
    roots = tracer.recent("outer")
    assert len(roots) == 1
    root = roots[0]
    assert root.attrs["kind"] == "test"
    assert [c.name for c in root.children] == ["inner"]
    assert root.children[0].attrs["x"] == 1
    assert root.duration_ms >= root.children[0].duration_ms
    d = root.to_dict()
    assert d["children"][0]["name"] == "inner"
    json.dumps(d)  # JSON-clean


def test_record_span_pretimed_child():
    with tracer.span("run") as r:
        s = tracer.record_span("superstep", 5.0, step=0, frontier=10)
    assert s in r.children
    assert s.duration_ms == pytest.approx(5.0, rel=0.01)
    assert s.attrs == {"step": 0, "frontier": 10}


def test_slow_op_log_threshold():
    tracer.configure(slow_threshold_ms=1e-6)
    with tracer.span("slow.thing", tag="x"):
        pass
    events = tracer.slow_ops()
    assert any(e["name"] == "slow.thing" for e in events)
    tracer.configure(slow_threshold_ms=0.0)  # 0 = off
    tracer.reset()
    with tracer.span("slow.thing2"):
        pass
    assert tracer.slow_ops() == []


def test_concurrent_counters_histograms_spans():
    """Satellite: hammer the registry + tracer from N threads — exact
    totals, and every thread's span tree stays well-formed (contextvars
    keep nesting thread-local)."""
    n_threads, iters = 8, 400
    errors = []

    def work(tid):
        try:
            with tracer.span(f"root-{tid}") as root:
                for i in range(iters):
                    metrics.counter("hammer.count").inc()
                    metrics.timer("hammer.timer").update(1000 + i)
                    metrics.histogram("hammer.hist").observe(float(i))
                    if i < 3:
                        with tracer.span(f"child-{i}"):
                            pass
                assert len(root.children) == 3
                assert [c.name for c in root.children] == [
                    "child-0", "child-1", "child-2"
                ]
        except Exception as e:  # surfaced after join
            errors.append(e)

    threads = [
        threading.Thread(target=work, args=(t,)) for t in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert errors == []
    assert metrics.get_count("hammer.count") == n_threads * iters
    assert metrics.get_count("hammer.timer") == n_threads * iters
    assert metrics.get_count("hammer.hist") == n_threads * iters
    roots = [r for r in tracer.recent() if r.name.startswith("root-")]
    assert len(roots) == n_threads
    for r in roots:
        assert len(r.children) == 3
        assert r.end_ns >= r.start_ns


def test_history_sampler_never_sees_torn_windows_under_hammer():
    """ISSUE 13 satellite: the history sampler racing N observer threads
    must take a CONSISTENT point-in-time view per metric — every sampled
    window satisfies sum(bucket deltas) == count delta and the deltas
    reconcile exactly against the final totals. Before the one-lock
    `Histogram.state()` read, a sampler could catch a histogram between
    its bucket increment and its count increment (a torn window)."""
    from janusgraph_tpu.observability.timeseries import MetricsHistory

    m = type(metrics)()
    h = MetricsHistory(m, capacity=4096, interval_s=0.0005)
    n_threads, iters = 8, 2000
    errors = []
    stop = threading.Event()

    def observe(tid):
        try:
            for i in range(iters):
                m.counter("hammer.count").inc()
                m.timer("hammer.timer").update(1000 + (i % 7) * 1_000_000)
                m.histogram("hammer.hist").observe(float(i % 100))
        except Exception as e:  # surfaced after join
            errors.append(e)

    def sample():
        try:
            while not stop.is_set():
                h.sample()
        except Exception as e:
            errors.append(e)

    threads = [
        threading.Thread(target=observe, args=(t,))
        for t in range(n_threads)
    ] + [threading.Thread(target=sample)]
    for t in threads:
        t.start()
    for t in threads[:-1]:
        t.join()
    stop.set()
    threads[-1].join()
    h.sample()  # the closing window catches the tail
    assert errors == []
    total = n_threads * iters
    for name in ("hammer.timer", "hammer.hist"):
        win_count = 0
        for w in h.windows():
            s = w["series"].get(name)
            if s is None:
                continue
            # THE torn-window assertion: every window is internally
            # consistent, however the sampler raced the observers
            assert sum(s["buckets"]) == s["count"], (name, w["seq"])
            assert all(b >= 0 for b in s["buckets"]), (name, w["seq"])
            assert s["sum"] >= 0
            win_count += s["count"]
        # and the windows partition the run exactly: no loss, no double
        assert win_count == total, name
    assert sum(
        w["counters"].get("hammer.count", 0) for w in h.windows()
    ) == total


# --------------------------------------------------------------- exposition
def _populate(m):
    m.counter("tx.commit").inc(4)
    for ns in (50_000, 400_000, 2_500_000):
        m.timer("storage.edgestore.getSlice").update(ns)
    m.set_gauge("olap.superstep.count", 7.0)
    m.histogram("olap.frontier.size").observe(128.0)


def test_prometheus_text_valid_and_complete():
    m = type(metrics)()
    _populate(m)
    text = prometheus_text(m)
    assert validate_prometheus_text(text) is None, text
    assert "# TYPE janusgraph_tx_commit_total counter" in text
    assert "janusgraph_tx_commit_total 4" in text
    assert ("# TYPE janusgraph_storage_edgestore_getSlice_seconds histogram"
            in text)
    assert 'janusgraph_storage_edgestore_getSlice_seconds_bucket{le="+Inf"} 3' in text
    assert "janusgraph_storage_edgestore_getSlice_seconds_count 3" in text
    assert "# TYPE janusgraph_olap_superstep_count gauge" in text
    assert "janusgraph_olap_superstep_count 7" in text
    # bucket cumulative counts are monotone and end at _count
    bucket_re = re.compile(
        r'janusgraph_olap_frontier_size_bucket\{le="([^"]+)"\} (\d+)'
    )
    cums = [int(c) for _le, c in bucket_re.findall(text)]
    assert cums == sorted(cums) and cums[-1] == 1


def test_json_snapshot_shape():
    m = type(metrics)()
    _populate(m)
    m.record_run("olap", {"path": "fused", "supersteps": 2})
    with tracer.span("olap.run"):
        pass
    snap = json_snapshot(m, tracer)
    assert snap["metrics"]["tx.commit"]["count"] == 4
    assert snap["runs"]["olap"][0]["supersteps"] == 2
    assert any(s["name"] == "olap.run" for s in snap["spans"])
    json.dumps(snap, default=str)


# ------------------------------------------------------- OLTP wiring (spans)
def test_tx_lifecycle_spans_and_counters():
    g = open_graph({"schema.default": "auto"})
    tx = g.new_transaction()
    tx.add_vertex(name="s")
    tx.commit()
    tx = g.new_transaction()
    tx.rollback()
    g.close()
    commits = tracer.recent("tx.commit")
    assert commits, "no tx.commit span recorded"
    assert commits[-1].attrs["added"] >= 1
    assert commits[-1].attrs["lifetime_ms"] >= 0
    assert tracer.recent("tx.rollback")
    assert metrics.get_count("tx.begin") >= 2
    assert metrics.get_count("tx.rollback") >= 1
    # the tx layer records commit latency through a histogram-backed timer
    entry = metrics.snapshot()["tx.commit"]
    assert entry["type"] == "timer"
    assert entry["count"] >= 1 and "p95_ms" in entry


def test_profile_feeds_from_spans_and_store_nesting():
    """Spans feed .profile(): steps run inside oltp.step spans, storage
    ops (instrumented store) nest under them and surface as store_ops
    annotations."""
    g = open_graph({"schema.default": "auto", "metrics.enabled": True})
    tx = g.new_transaction()
    a = tx.add_vertex(name="a")
    b = tx.add_vertex(name="b")
    tx.add_edge(a, "knows", b)
    tx.commit()
    src = g.traversal()
    prof = src.V().has("name", "a").out("knows").profile()
    assert len(prof.result) == 1
    roots = tracer.recent("oltp.traversal")
    assert roots, "no traversal root span"
    steps = [c for c in roots[-1].children if c.name.startswith("oltp.step.")]
    assert steps
    store_spans = roots[-1].find("store.getSlice")
    assert store_spans, "instrumented store ops did not nest under steps"
    annotated = [
        c for c in prof.as_dict()["children"] if "store_ops" in c["annotations"]
    ]
    assert annotated, "no profiler step carries span-fed store_ops"
    g.close()


def test_store_histograms_under_metrics_enabled():
    g = open_graph({"schema.default": "auto", "metrics.enabled": True})
    tx = g.new_transaction()
    v = tx.add_vertex(name="h")
    tx.commit()
    tx = g.new_transaction()
    tx.get_vertex(v.id)
    tx.rollback()
    snap = metrics.snapshot()
    # batched writes time at the manager level, reads per-store
    wr = snap.get("storage.mutateMany")
    assert wr is not None and wr["type"] == "timer" and wr["count"] >= 1
    rd = snap.get("storage.edgestore.getSlice")
    assert rd is not None and rd["type"] == "timer"
    assert rd["count"] >= 1 and "p99_ms" in rd
    g.close()


# ------------------------------------------------------------- OLAP wiring
@pytest.fixture
def olap_graph():
    g = open_graph({"ids.authority-wait-ms": 0.0})
    gods.load(g)
    yield g
    g.close()


def test_olap_submit_span_tree_with_superstep_children(olap_graph):
    """Acceptance: a PageRank run via GraphComputer.submit() produces a
    span tree with per-superstep children carrying frontier/pad/transfer
    attributes."""
    from janusgraph_tpu.olap.programs import PageRankProgram

    res = olap_graph.compute().program(
        PageRankProgram(max_iterations=3, tol=0.0)
    ).submit()
    assert res.states["rank"].shape[0] == res.csr.num_vertices
    roots = tracer.recent("olap.submit")
    assert roots, "no olap.submit root span"
    root = roots[-1]
    child_names = [c.name for c in root.children]
    assert "olap.load_csr" in child_names
    runs = root.find("olap.run")
    assert runs, "olap.run did not nest under submit"
    steps = runs[-1].find("superstep")
    assert len(steps) == 3
    for s in steps:
        assert "frontier" in s.attrs
        assert "pad_ratio" in s.attrs
        assert "h2d_bytes" in s.attrs
    # transfer bytes ride the first superstep only
    assert steps[0].attrs["h2d_bytes"] > 0
    assert runs[-1].attrs["supersteps"] == 3


def test_olap_run_record_in_registry(olap_graph):
    """Satellite: the per-run execution record is surfaced through the
    registry, not just the executor attribute."""
    from janusgraph_tpu.olap.programs import PageRankProgram

    olap_graph.compute().program(
        PageRankProgram(max_iterations=2, tol=0.0)
    ).submit()
    rec = metrics.last_run("olap")
    assert rec is not None
    assert rec["path"] in ("fused", "host-loop", "frontier")
    assert rec["supersteps"] == 2
    assert rec["wall_s"] > 0
    assert len(rec["superstep_records"]) == 2
    assert rec["h2d_arg_bytes"] > 0
    snap = metrics.snapshot()
    assert snap["olap.superstep.count"]["value"] == 2.0
    assert metrics.get_count("olap.runs") == 1


# ------------------------------------------------------------ server scrape
@pytest.fixture
def server(olap_graph):
    from janusgraph_tpu.server import JanusGraphManager, JanusGraphServer

    m = JanusGraphManager()
    m.put_graph("graph", olap_graph)
    s = JanusGraphServer(manager=m).start()
    yield s
    s.stop()


def test_metrics_endpoint_prometheus(server, olap_graph):
    """Acceptance: GET /metrics returns valid Prometheus text including at
    least one storage histogram and one OLAP superstep gauge."""
    from janusgraph_tpu.olap.programs import PageRankProgram

    # storage latency histograms need an instrumented store on SOME graph;
    # the registry is process-global, so populate it directly too
    for ns in (40_000, 900_000):
        metrics.timer("storage.edgestore.getSlice").update(ns)
    olap_graph.compute().program(
        PageRankProgram(max_iterations=2, tol=0.0)
    ).submit()
    url = f"http://127.0.0.1:{server.port}/metrics"
    with urllib.request.urlopen(url) as resp:
        assert resp.status == 200
        assert resp.headers["Content-Type"].startswith("text/plain")
        text = resp.read().decode()
    assert validate_prometheus_text(text) is None, text
    assert ("# TYPE janusgraph_storage_edgestore_getSlice_seconds histogram"
            in text)
    assert 'le="+Inf"' in text
    assert "# TYPE janusgraph_olap_superstep_count gauge" in text
    assert "janusgraph_olap_superstep_count 2" in text


def test_telemetry_endpoint_json(server, olap_graph):
    from janusgraph_tpu.olap.programs import PageRankProgram

    olap_graph.compute().program(
        PageRankProgram(max_iterations=2, tol=0.0)
    ).submit()
    url = f"http://127.0.0.1:{server.port}/telemetry"
    with urllib.request.urlopen(url) as resp:
        assert resp.status == 200
        payload = json.loads(resp.read().decode())
    assert "metrics" in payload and "spans" in payload
    assert payload["runs"]["olap"][-1]["supersteps"] == 2
    submit_spans = [
        s for s in payload["spans"] if s["name"] == "olap.submit"
    ]
    assert submit_spans
    assert "slow_ops" in payload


def test_timeseries_endpoint_scrape(server, olap_graph):
    """ISSUE 13 satellite: /timeseries serves the history ring alongside
    the point-in-time endpoints, and /metrics stays schema-valid with
    the sampler's own gauges in the registry."""
    from janusgraph_tpu.observability import history

    metrics.counter("scrape.ts").inc(5)
    history.sample()
    base = f"http://127.0.0.1:{server.port}"
    with urllib.request.urlopen(base + "/timeseries?name=scrape.") as resp:
        assert resp.status == 200
        payload = json.loads(resp.read().decode())
    assert payload["series"]["scrape.ts"][-1]["delta"] == 5
    assert payload["interval_s"] > 0 and payload["windows"] >= 1
    # the sampler's self-overhead gauge rides the normal exposition and
    # the whole /metrics payload still validates
    with urllib.request.urlopen(base + "/metrics") as resp:
        text = resp.read().decode()
    assert validate_prometheus_text(text) is None, text
    assert "janusgraph_observability_history_overhead_ms" in text


# ------------------------------------------------------------------- CLI
def test_cli_telemetry_dump(capsys):
    from janusgraph_tpu.cli import main as cli_main

    metrics.counter("cli.smoke").inc()
    assert cli_main(["telemetry"]) == 0
    out = capsys.readouterr().out
    assert "janusgraph_cli_smoke_total 1" in out
    assert validate_prometheus_text(out) is None
    assert cli_main(["telemetry", "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["metrics"]["cli.smoke"]["count"] == 1


def test_cli_telemetry_scrape_url(server, capsys):
    from janusgraph_tpu.cli import main as cli_main

    metrics.counter("cli.scrape").inc()
    assert cli_main(
        ["telemetry", "--url", f"127.0.0.1:{server.port}"]
    ) == 0
    out = capsys.readouterr().out
    assert "janusgraph_cli_scrape_total 1" in out
    assert validate_prometheus_text(out) is None, out


# ----------------------------------------------------- pool handoff (JG402)
def test_capture_scope_carries_span_and_ledger_across_pool():
    """graphlint v2 satellite: span/ledger attribution must survive a
    thread-pool handoff. A bare pool worker starts from an empty
    contextvars context (no current span, no ambient ledger); a worker
    entered through capture_scope() re-enters the submitter's scope, so
    its reads see the parent span and its accruals land in the parent
    ledger."""
    from concurrent.futures import ThreadPoolExecutor

    from janusgraph_tpu.observability import capture_scope, ledger_scope
    from janusgraph_tpu.observability.profiler import accrue

    def work(_i):
        accrue(rows=10)
        cur = tracer.current()
        return cur.name if cur is not None else None

    with ledger_scope() as led:
        with span("parent"):
            with ThreadPoolExecutor(max_workers=2) as pool:
                bare = list(pool.map(work, range(2)))
                kept = list(pool.map(capture_scope(work), range(2)))
    assert bare == [None, None]
    assert kept == ["parent", "parent"]
    # only the wrapped workers accrued into the submitting request's ledger
    assert led.counters.get("rows") == 20


def test_capture_scope_restores_vars_after_each_call():
    """The wrapper sets/resets contextvars per invocation: the worker
    thread's own ambience is untouched outside the call."""
    from janusgraph_tpu.observability import capture_scope

    with span("outer"):
        wrapped = capture_scope(lambda: tracer.current().name)
    assert tracer.current() is None
    assert wrapped() == "outer"
    assert tracer.current() is None
