"""Schema constraints (reference: SchemaManager.addProperties/addConnection
+ schema.constraints, enforced at StandardJanusGraphTx.java:669-698: with
constraints enabled, labeled elements carry only declared keys and edges
only declared (outLabel, inLabel) connections; auto schema creates missing
constraints, 'none' rejects; the default label is exempt)."""

import pytest

from janusgraph_tpu.core.codecs import Multiplicity
from janusgraph_tpu.core.graph import open_graph
from janusgraph_tpu.exceptions import SchemaViolationError


def _strict_graph():
    g = open_graph({"schema.default": "none", "schema.constraints": True})
    m = g.management()
    m.make_vertex_label("person")
    m.make_vertex_label("city")
    m.make_property_key("name", str)
    m.make_property_key("age", int)
    m.make_property_key("weight", float)
    m.make_edge_label("lives_in")
    m.add_properties("person", "name", "age")
    m.add_properties("lives_in", "weight")
    m.add_connection("lives_in", "person", "city")
    return g


def test_declared_writes_pass_and_undeclared_reject():
    g = _strict_graph()
    tx = g.new_transaction()
    p = tx.add_vertex("person", name="ada", age=36)
    c = tx.add_vertex("city")
    e = tx.add_edge(p, "lives_in", c, weight=1.0)
    tx.commit()
    tx = g.new_transaction()
    with pytest.raises(SchemaViolationError, match="not declared"):
        tx.get_vertex(p.id).property("weight", 2.0)  # undeclared on person
    tx.rollback()
    g.close()


def test_undeclared_connection_rejects():
    g = _strict_graph()
    tx = g.new_transaction()
    a = tx.add_vertex("person", name="a")
    b = tx.add_vertex("person", name="b")
    with pytest.raises(SchemaViolationError, match="connection"):
        tx.add_edge(a, "lives_in", b)  # person->person not declared
    tx.rollback()
    g.close()


def test_undeclared_edge_property_rejects():
    g = _strict_graph()
    m = g.management()
    tx = g.new_transaction()
    p = tx.add_vertex("person", name="x")
    c = tx.add_vertex("city")
    with pytest.raises(SchemaViolationError, match="not declared"):
        tx.add_edge(p, "lives_in", c, name="home")  # name not on lives_in
    tx.rollback()
    g.close()


def test_default_label_exempt():
    g = open_graph({"schema.default": "none", "schema.constraints": True})
    m = g.management()
    m.make_property_key("note", str)
    m.make_edge_label("rel")
    tx = g.new_transaction()
    a = tx.add_vertex(note="free")   # default 'vertex' label: exempt
    b = tx.add_vertex()
    tx.add_edge(a, "rel", b)         # default-labeled endpoints: exempt
    tx.commit()
    g.close()


def test_auto_schema_auto_creates_constraints():
    g = open_graph({"schema.default": "auto", "schema.constraints": True})
    m = g.management()
    m.make_vertex_label("thing")
    tx = g.new_transaction()
    t = tx.add_vertex("thing", kind="widget")  # auto-declares kind on thing
    tx.commit()
    vl = g.schema_cache.get_by_name("thing")
    pk = g.schema_cache.get_by_name("kind")
    assert pk.id in vl.allowed_property_ids
    g.close()


def test_constraints_survive_reopen():
    from janusgraph_tpu.storage.inmemory import InMemoryStoreManager

    sm = InMemoryStoreManager()
    g = open_graph(
        {"schema.default": "none", "schema.constraints": True},
        store_manager=sm,
    )
    m = g.management()
    m.make_vertex_label("person")
    m.make_vertex_label("city")
    m.make_property_key("name", str)
    m.make_edge_label("lives_in")
    m.add_properties("person", "name")
    m.add_connection("lives_in", "person", "city")
    g.close()
    g2 = open_graph(
        {"schema.default": "none", "schema.constraints": True},
        store_manager=sm,
    )
    vl = g2.schema_cache.get_by_name("person")
    el = g2.schema_cache.get_by_name("lives_in")
    assert len(vl.allowed_property_ids) == 1
    assert len(el.connections) == 1
    tx = g2.new_transaction()
    p = tx.add_vertex("person", name="ok")
    with pytest.raises(SchemaViolationError):
        tx.add_edge(p, "lives_in", tx.add_vertex("person", name="x"))
    tx.rollback()
    g2.close()


def test_print_schema_shows_declarations():
    g = _strict_graph()
    out = g.management().print_schema()
    assert "props=[name,age]" in out
    assert "connections=[person->city]" in out
    g.close()


def test_disabled_by_default_no_enforcement():
    g = open_graph({"schema.default": "none"})
    m = g.management()
    m.make_vertex_label("person")
    m.make_property_key("name", str)
    m.make_property_key("other", str)
    m.add_properties("person", "name")
    tx = g.new_transaction()
    # schema.constraints defaults False: declarations exist but don't bind
    tx.add_vertex("person", other="fine")
    tx.commit()
    g.close()


def test_set_edge_property_after_creation_enforced():
    """Constraints bind post-creation edge property writes too (the
    set_edge_property path, not just add_edge kwargs)."""
    g = _strict_graph()
    tx = g.new_transaction()
    p = tx.add_vertex("person", name="y")
    c = tx.add_vertex("city")
    e = tx.add_edge(p, "lives_in", c)
    with pytest.raises(SchemaViolationError, match="not declared"):
        e.set_property("name", "home")
    e.set_property("weight", 3.0)  # declared: fine
    tx.commit()
    g.close()


def test_concurrent_auto_declarations_not_lost():
    """Two threads auto-declaring different keys on one label must both
    survive (the serialized RMW; lost-update regression)."""
    import threading

    g = open_graph({"schema.default": "auto", "schema.constraints": True})
    g.management().make_vertex_label("thing")
    errors = []

    def write(key):
        try:
            tx = g.new_transaction()
            tx.add_vertex("thing", **{key: "v"})
            tx.commit()
        except Exception as e:  # noqa: BLE001
            errors.append(repr(e))

    ts = [threading.Thread(target=write, args=(k,)) for k in
          ("alpha", "beta", "gamma", "delta")]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    assert not errors, errors
    vl = g.schema_cache.get_by_name("thing")
    declared = {
        g.schema_cache.get_by_id(i).name for i in vl.allowed_property_ids
    }
    assert {"alpha", "beta", "gamma", "delta"} <= declared
    g.close()


def test_rejected_type_write_leaves_no_schema_mutation():
    """A type-invalid write must not auto-declare the key first (durable
    schema side effects from failed writes)."""
    g = open_graph({"schema.default": "auto", "schema.constraints": True})
    m = g.management()
    m.make_vertex_label("person")
    m.make_property_key("age", int)
    tx = g.new_transaction()
    v = tx.add_vertex("person")
    with pytest.raises(SchemaViolationError, match="expects"):
        v.property("age", "not-a-number")
    vl = g.schema_cache.get_by_name("person")
    assert vl.allowed_property_ids == ()  # nothing declared by the failure
    tx.rollback()
    g.close()


def test_set_ttl_and_declarations_compose():
    """set_ttl/set_consistency share the RMW lock with declarations —
    neither update may erase the other."""
    import threading

    g = open_graph({"schema.default": "auto", "schema.constraints": True})
    m = g.management()
    m.make_vertex_label("thing")
    done = []

    def declare():
        tx = g.new_transaction()
        tx.add_vertex("thing", zeta="v")
        tx.commit()
        done.append("declare")

    def modify():
        # static-label-free TTL rejection would end the thread early on
        # inmemory (supports cell ttl); use consistency instead for a
        # schema-field RMW racing the declaration
        from janusgraph_tpu.core.codecs import Consistency

        m2 = g.management()
        m2.make_property_key("guarded", str)
        m2.set_consistency("guarded", Consistency.LOCK)
        done.append("modify")

    ts = [threading.Thread(target=declare), threading.Thread(target=modify)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    assert sorted(done) == ["declare", "modify"]
    vl = g.schema_cache.get_by_name("thing")
    assert len(vl.allowed_property_ids) == 1  # declaration survived
    g.close()
