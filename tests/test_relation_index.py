"""RelationTypeIndex: vertex-centric indexes built AFTER the edge label
exists (reference: ManagementSystem.buildEdgeIndex ->
core/schema/RelationTypeIndex.java; cells are a duplicate relation type,
invisible to normal traversal, queried via sort-key column ranges)."""

import pytest

from janusgraph_tpu.core.codecs import Direction
from janusgraph_tpu.core.graph import open_graph
from janusgraph_tpu.exceptions import QueryError, SchemaViolationError


def _graph_with_data():
    g = open_graph()
    m = g.management()
    m.make_property_key("time", int)
    m.make_edge_label("battled")  # NO sort key at creation
    tx = g.new_transaction()
    h = tx.add_vertex()
    monsters = []
    for t in (1, 5, 9, 12, 20):
        mv = tx.add_vertex()
        tx.add_edge(h, "battled", mv, time=t)
        monsters.append((t, mv.id))
    tx.commit()
    return g, h.id, monsters


def test_build_reindex_and_query():
    g, hid, monsters = _graph_with_data()
    m = g.management()
    ri = m.build_edge_index("battled", "battlesByTime", ["time"])
    assert ri.status == "REGISTERED"
    # pre-existing edges need the reindex pass
    n = m.reindex_relation_index("battlesByTime")
    assert n == 5
    tx = g.new_transaction()
    hits = tx.get_edges(
        tx.get_vertex(hid), Direction.OUT, ("battled",), sort_range=(5, 12)
    )
    assert sorted(e.value("time") for e in hits) == [5, 9]
    g.close()


def test_new_edges_indexed_without_reindex():
    g, hid, _ = _graph_with_data()
    m = g.management()
    m.build_edge_index("battled", "battlesByTime", ["time"])
    m.reindex_relation_index("battlesByTime")
    tx = g.new_transaction()
    h = tx.get_vertex(hid)
    mv = tx.add_vertex()
    tx.add_edge(h, "battled", mv, time=7)
    tx.commit()
    tx2 = g.new_transaction()
    hits = tx2.get_edges(
        tx2.get_vertex(hid), Direction.OUT, ("battled",), sort_range=(6, 10)
    )
    assert sorted(e.value("time") for e in hits) == [7, 9]
    g.close()


def test_overlay_edges_respect_index_range():
    g, hid, _ = _graph_with_data()
    m = g.management()
    m.build_edge_index("battled", "battlesByTime", ["time"])
    m.reindex_relation_index("battlesByTime")
    tx = g.new_transaction()
    h = tx.get_vertex(hid)
    mv = tx.add_vertex()
    tx.add_edge(h, "battled", mv, time=8)  # uncommitted
    hits = tx.get_edges(h, Direction.OUT, ("battled",), sort_range=(6, 10))
    assert sorted(e.value("time") for e in hits) == [8, 9]
    g.close()


def test_index_cells_invisible_to_plain_traversal():
    g, hid, monsters = _graph_with_data()
    m = g.management()
    m.build_edge_index("battled", "battlesByTime", ["time"])
    m.reindex_relation_index("battlesByTime")
    tx = g.new_transaction()
    edges = tx.get_edges(tx.get_vertex(hid), Direction.OUT, ())
    assert len(edges) == 5  # no duplicates from index cells
    assert {e.label for e in edges} == {"battled"}
    # OLAP load is equally blind to index cells
    from janusgraph_tpu.olap.csr import load_csr

    csr = load_csr(g)
    assert csr.num_edges == 5
    g.close()


def test_unindexed_label_range_still_rejected():
    g, hid, _ = _graph_with_data()
    tx = g.new_transaction()
    with pytest.raises(QueryError):
        tx.get_edges(
            tx.get_vertex(hid), Direction.OUT, ("battled",), sort_range=(1, 2)
        )
    g.close()


def test_disabled_index_not_used():
    g, hid, _ = _graph_with_data()
    m = g.management()
    m.build_edge_index("battled", "battlesByTime", ["time"])
    m.reindex_relation_index("battlesByTime")
    m.set_relation_index_status("battlesByTime", "DISABLED")
    tx = g.new_transaction()
    with pytest.raises(QueryError):
        tx.get_edges(
            tx.get_vertex(hid), Direction.OUT, ("battled",), sort_range=(1, 2)
        )
    g.close()


def test_build_validation():
    g = open_graph()
    m = g.management()
    m.make_property_key("note", str)  # variable-width
    m.make_property_key("t", int)
    m.make_edge_label("l")
    with pytest.raises(SchemaViolationError):
        m.build_edge_index("nope", "x", ["t"])
    with pytest.raises(SchemaViolationError):
        m.build_edge_index("l", "x", ["note"])  # not fixed width
    with pytest.raises(SchemaViolationError):
        m.build_edge_index("l", "x", [])
    g.close()


def test_delete_via_index_routed_edge_removes_primary(


):
    g, hid, _ = _graph_with_data()
    m = g.management()
    m.build_edge_index("battled", "battlesByTime", ["time"])
    m.reindex_relation_index("battlesByTime")
    tx = g.new_transaction()
    h = tx.get_vertex(hid)
    [e] = tx.get_edges(h, Direction.OUT, ("battled",), sort_range=(5, 6))
    e.remove()
    tx.commit()
    tx2 = g.new_transaction()
    plain = tx2.get_edges(tx2.get_vertex(hid), Direction.OUT, ("battled",))
    assert sorted(x.value("time") for x in plain) == [1, 9, 12, 20]
    ranged = tx2.get_edges(
        tx2.get_vertex(hid), Direction.OUT, ("battled",), sort_range=(0, 50)
    )
    assert sorted(x.value("time") for x in ranged) == [1, 9, 12, 20]
    g.close()


def test_delete_while_disabled_leaves_no_phantom():
    g, hid, _ = _graph_with_data()
    m = g.management()
    m.build_edge_index("battled", "battlesByTime", ["time"])
    m.reindex_relation_index("battlesByTime")
    m.set_relation_index_status("battlesByTime", "DISABLED")
    tx = g.new_transaction()
    h = tx.get_vertex(hid)
    [e] = [x for x in tx.get_edges(h, Direction.OUT, ("battled",))
           if x.value("time") == 9]
    e.remove()
    tx.commit()
    m.set_relation_index_status("battlesByTime", "ENABLED")
    tx2 = g.new_transaction()
    ranged = tx2.get_edges(
        tx2.get_vertex(hid), Direction.OUT, ("battled",), sort_range=(0, 50)
    )
    assert sorted(x.value("time") for x in ranged) == [1, 5, 12, 20]
    g.close()


def test_input_format_blind_to_index_cells():
    from janusgraph_tpu.olap.input_format import GraphInputFormat

    g, hid, _ = _graph_with_data()
    m = g.management()
    m.build_edge_index("battled", "battlesByTime", ["time"])
    m.reindex_relation_index("battlesByTime")
    svs = list(GraphInputFormat(g).read_all())
    edges = [e for sv in svs for e in sv.edges]
    assert len(edges) == 5
    assert {lbl for lbl, _other, _p in edges} == {"battled"}
    g.close()


def test_vertex_removal_strikes_index_cells():
    g, hid, _ = _graph_with_data()
    m = g.management()
    m.build_edge_index("battled", "battlesByTime", ["time"])
    m.reindex_relation_index("battlesByTime")
    tx = g.new_transaction()
    tx.remove_vertex(tx.get_vertex(hid))
    tx.commit()
    # raw row must hold NO index cells on the removed vertex's key
    from janusgraph_tpu.storage.kcvs import KeySliceQuery, SliceQuery

    key = g.idm.get_key(hid)
    stx = g.backend.manager.begin_transaction()
    store = g.backend.edgestore
    while hasattr(store, "wrapped"):
        store = store.wrapped
    assert store.get_slice(KeySliceQuery(key, SliceQuery()), stx) == []
    g.close()
