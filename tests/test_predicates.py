"""Predicate vocabulary + Geoshape tests (reference behavior:
attribute/Cmp.java, Text.java, Geo.java, Geoshape.java)."""

import pytest

from janusgraph_tpu.core.predicates import (
    Cmp,
    Geo,
    Geoshape,
    Text,
    fuzzy_distance,
    levenshtein,
    predicate_by_name,
    tokenize,
)


def test_tokenize():
    assert tokenize("Hello, World! foo_bar 42") == ["hello", "world", "foo_bar", "42"]


def test_cmp():
    assert Cmp.EQUAL.evaluate(3, 3)
    assert not Cmp.EQUAL.evaluate(3, 4)
    assert Cmp.NOT_EQUAL.evaluate(3, 4)
    assert Cmp.LESS_THAN.evaluate(2, 3)
    assert Cmp.GREATER_THAN_EQUAL.evaluate(3, 3)
    assert not Cmp.GREATER_THAN.evaluate(None, 3)


def test_text_contains_family():
    s = "The quick brown fox jumps"
    assert Text.CONTAINS.evaluate(s, "quick fox")
    assert not Text.CONTAINS.evaluate(s, "quick wolf")
    assert Text.CONTAINS_PREFIX.evaluate(s, "qui")
    assert not Text.CONTAINS_PREFIX.evaluate(s, "uick")
    assert Text.CONTAINS_REGEX.evaluate(s, "qu.ck")
    assert Text.CONTAINS_FUZZY.evaluate(s, "quicc")
    assert Text.CONTAINS_PHRASE.evaluate(s, "quick brown fox")
    assert not Text.CONTAINS_PHRASE.evaluate(s, "quick fox brown")


def test_text_fullstring_family():
    assert Text.PREFIX.evaluate("hercules", "herc")
    assert Text.REGEX.evaluate("hercules", "her.*")
    assert not Text.REGEX.evaluate("hercules", "her")
    assert Text.FUZZY.evaluate("hercules", "herculez")


def test_fuzzy_distance_auto():
    assert fuzzy_distance("ab") == 0
    assert fuzzy_distance("abcd") == 1
    assert fuzzy_distance("abcdef") == 2
    assert levenshtein("kitten", "sitting", 3) == 3
    assert levenshtein("abc", "abc", 2) == 0


def test_geoshape_point_circle():
    athens = Geoshape.point(37.97, 23.72)
    near = Geoshape.circle(38.0, 23.7, 50)
    far = Geoshape.circle(52.5, 13.4, 50)
    assert Geo.WITHIN.evaluate(athens, near)
    assert not Geo.WITHIN.evaluate(athens, far)
    assert Geo.INTERSECT.evaluate(athens, near)
    assert Geo.DISJOINT.evaluate(athens, far)
    assert Geo.CONTAINS.evaluate(near, athens)


def test_geoshape_box_polygon():
    box = Geoshape.box(37.0, 23.0, 39.0, 25.0)
    p = Geoshape.point(38.0, 24.0)
    assert box.contains_point(38.0, 24.0)
    assert Geo.WITHIN.evaluate(p, box)
    poly = Geoshape.polygon([(0, 0), (0, 10), (10, 10), (10, 0)])
    assert poly.contains_point(5, 5)
    assert not poly.contains_point(11, 5)


def test_geoshape_wkt_roundtrip():
    for shape in (
        Geoshape.point(37.97, 23.72),
        Geoshape.circle(38.0, 23.7, 50),
        Geoshape.polygon([(0, 0), (0, 10), (10, 10)]),
    ):
        assert Geoshape.from_wkt(shape.to_wkt()) == shape


def test_geoshape_geojson_roundtrip():
    for shape in (
        Geoshape.point(37.97, 23.72),
        Geoshape.circle(38.0, 23.7, 50),
        Geoshape.box(37.0, 23.0, 39.0, 25.0),
        Geoshape.polygon([(0, 0), (0, 10), (10, 10)]),
    ):
        assert Geoshape.from_geojson(shape.to_geojson()) == shape


def test_predicate_registry():
    assert predicate_by_name("textContains") is Text.CONTAINS
    assert predicate_by_name("geoWithin") is Geo.WITHIN
    assert predicate_by_name("eq") is Cmp.EQUAL
    assert predicate_by_name("nope") is None
