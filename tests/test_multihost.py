"""Multi-host glue: single-process no-op init, global mesh construction,
partition-range assignment, and a ShardedExecutor run over the multihost
mesh (virtual 8-device CPU mesh stands in for the real DCN topology —
SURVEY.md §2.4.3)."""

import numpy as np

from janusgraph_tpu.olap.cpu_executor import CPUExecutor
from janusgraph_tpu.olap.generators import rmat_csr
from janusgraph_tpu.olap.programs import PageRankProgram
from janusgraph_tpu.parallel.multihost import (
    global_mesh,
    host_partition_range,
    init_multihost,
)
from janusgraph_tpu.parallel.sharded import ShardedExecutor


def test_single_process_init_is_noop():
    assert init_multihost() == 0
    assert init_multihost(num_processes=1, process_id=0) == 0


def test_multiprocess_without_coordinator_raises():
    import pytest

    with pytest.raises(ValueError):
        init_multihost(num_processes=4, process_id=1)


def test_partition_ranges_cover_exactly():
    for nproc in (1, 3, 8):
        covered = []
        for pid in range(nproc):
            lo, hi = host_partition_range(32, pid, nproc)
            covered.extend(range(lo, hi))
        assert covered == list(range(32))


def test_sharded_executor_on_global_mesh():
    mesh = global_mesh()
    assert mesh.devices.size == 8  # conftest forces 8 virtual devices
    csr = rmat_csr(10, 8)
    ex = ShardedExecutor(csr, mesh=mesh)
    got = ex.run(PageRankProgram(max_iterations=6, tol=0.0))
    want = CPUExecutor(csr).run(PageRankProgram(max_iterations=6, tol=0.0))
    np.testing.assert_allclose(got["rank"], want["rank"], rtol=1e-5, atol=1e-8)
