"""Tier-1 gate + self-tests for graphlint (janusgraph_tpu/analysis/).

Two jobs:

1. **Gate the real tree**: the whole package must analyze clean (zero
   non-suppressed errors) and pass the import sweep, so every future PR
   rides this invariant without extra CI plumbing.
2. **Prove the rules**: each rule ID fires exactly where the bad-snippet
   fixtures say it should (``# expect: JGnnn`` markers), suppression
   comments work, and the JSON reporter round-trips.
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys

import pytest

from janusgraph_tpu.analysis import Analyzer, RULES, analyze_paths
from janusgraph_tpu.analysis.cli import filter_changed, main as cli_main
from janusgraph_tpu.analysis.imports_check import check_imports
from janusgraph_tpu.analysis.reporting import from_json, to_json

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(REPO, "janusgraph_tpu")
FIXTURES = os.path.join(REPO, "tests", "fixtures", "graphlint")
XMOD = os.path.join(FIXTURES, "xmod_pkg")

_EXPECT_RE = re.compile(r"#\s*expect:\s*([A-Z0-9, ]+)")
_EXPECT_FILE_RE = re.compile(r"#\s*expect-file:\s*([A-Z0-9, ]+)")


def _expectations(path):
    """((line, rule) set, file-level rule set) parsed from fixture markers."""
    per_line, per_file = set(), set()
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f, start=1):
            m = _EXPECT_FILE_RE.search(line)
            if m:
                per_file.update(
                    r.strip() for r in m.group(1).split(",") if r.strip()
                )
                continue
            m = _EXPECT_RE.search(line)
            if m:
                for rule in m.group(1).split(","):
                    if rule.strip():
                        per_line.add((i, rule.strip()))
    return per_line, per_file


# --------------------------------------------------------------------- gate
def test_package_analyzes_clean():
    """THE gate: zero non-suppressed findings on the real tree — and the
    whole-program pass stays inside the 30 s runtime budget (the
    pre-commit-hook ceiling from the v2 acceptance criteria)."""
    import time

    t0 = time.perf_counter()
    findings = analyze_paths([PACKAGE])
    elapsed = time.perf_counter() - t0
    assert findings == [], "graphlint found issues:\n" + "\n".join(
        f"{f.path}:{f.line}: {f.rule_id} {f.message}" for f in findings
    )
    assert elapsed < 30.0, f"full-package lint took {elapsed:.1f}s (budget 30s)"


def test_package_import_sweep_clean():
    """--check-imports: every module byte-compiles and imports (catches
    syntax errors / circular imports in rarely-run server/ and driver/)."""
    findings = check_imports([PACKAGE])
    assert findings == [], "\n".join(
        f"{f.path}: {f.rule_id} {f.message}" for f in findings
    )


def test_suppressions_in_package_carry_justification():
    """Every in-tree suppression must say WHY (`-- reason` suffix) — a bare
    disable defeats the point of machine-checked invariants."""
    from janusgraph_tpu.analysis.core import _DISABLE_FILE_RE, _DISABLE_RE

    bad = []
    for root, dirs, files in os.walk(PACKAGE):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for fn in files:
            if not fn.endswith(".py"):
                continue
            p = os.path.join(root, fn)
            with open(p, encoding="utf-8") as f:
                for i, line in enumerate(f, start=1):
                    if not (_DISABLE_RE.search(line)
                            or _DISABLE_FILE_RE.search(line)):
                        continue
                    if " -- " not in line:
                        bad.append(f"{p}:{i}")
    assert bad == [], f"suppressions without justification: {bad}"


# ----------------------------------------------------------- fixture firing
FIXTURE_FILES = sorted(
    fn for fn in os.listdir(FIXTURES) if fn.startswith("bad_")
)


def test_fixture_inventory_covers_all_rule_ids():
    """Every JG1xx/JG2xx/JG3xx/JG4xx rule has at least one firing fixture
    (cross-module-only rules like JG403/JG202-cycles live in xmod_pkg/)."""
    covered = set()
    for fn in FIXTURE_FILES:
        per_line, per_file = _expectations(os.path.join(FIXTURES, fn))
        covered |= {r for _l, r in per_line} | per_file
    for fn in sorted(os.listdir(XMOD)):
        if fn.endswith(".py"):
            per_line, per_file = _expectations(os.path.join(XMOD, fn))
            covered |= {r for _l, r in per_line} | per_file
    analyzer_rules = {r for r in RULES if not r.startswith("JG0")}
    assert analyzer_rules <= covered, (
        f"rules without fixtures: {sorted(analyzer_rules - covered)}"
    )
    assert len(analyzer_rules) >= 12


@pytest.mark.parametrize("fixture", FIXTURE_FILES)
def test_fixture_fires_exactly_where_expected(fixture):
    path = os.path.join(FIXTURES, fixture)
    per_line, per_file = _expectations(path)
    findings = analyze_paths([path])
    got_lines = {(f.line, f.rule_id) for f in findings}
    got_rules = {f.rule_id for f in findings}
    missing = per_line - got_lines
    assert not missing, f"expected findings did not fire: {sorted(missing)}"
    for rule in per_file:
        assert rule in got_rules, f"{rule} did not fire anywhere in {fixture}"
    # no rule fires anywhere it wasn't declared (file-level rules exempt)
    unexpected = {
        (line, r) for line, r in got_lines
        if (line, r) not in per_line and r not in per_file
    }
    assert not unexpected, f"unexpected findings: {sorted(unexpected)}"


def test_jg106_flags_telemetry_recording_in_traced_code():
    """ISSUE 2 satellite: metric/span calls inside jit context are a host
    sync hazard (and record once per compile) — JG106 fires on the
    fixture and ONLY JG106."""
    assert "JG106" in RULES
    path = os.path.join(FIXTURES, "bad_trace_telemetry.py")
    findings = analyze_paths([path])
    assert findings, "JG106 fixture produced no findings"
    assert {f.rule_id for f in findings} == {"JG106"}
    # the observability package itself records host-side only
    assert analyze_paths(
        [os.path.join(PACKAGE, "observability")]
    ) == []


def test_suppression_comments_silence_findings():
    path = os.path.join(FIXTURES, "suppressed_ok.py")
    assert analyze_paths([path]) == []
    kept, _n = Analyzer().analyze_paths([path], keep_suppressed=True)
    assert {f.rule_id for f in kept} == {"JG301", "JG203"}
    assert all(f.suppressed for f in kept)


def test_disable_file_suppresses_whole_file(tmp_path):
    p = tmp_path / "mod.py"
    p.write_text(
        "# graphlint: disable-file=JG301 -- test\n"
        "E_CAP = 3000\nF_MIN = 999\n"
    )
    assert analyze_paths([str(p)]) == []


# ------------------------------------------------------------ reporter/CLI
def test_json_reporter_round_trip(tmp_path, capsys):
    path = os.path.join(FIXTURES, "bad_shape_tier.py")
    rc = cli_main(["--json", path])
    out = capsys.readouterr().out
    assert rc == 1
    data = json.loads(out)
    assert data["tool"] == "graphlint"
    assert data["counts"]["errors"] >= 2
    loaded = from_json(out)
    direct = analyze_paths([path])
    assert [f.to_dict() for f in loaded] == [f.to_dict() for f in direct]
    # and to_json(from_json(x)) is stable
    assert to_json(loaded, data["files_scanned"]) == out.rstrip("\n")


def test_cli_select_and_ignore(capsys):
    path = os.path.join(FIXTURES, "bad_lock_blocking.py")
    assert cli_main(["--select", "JG3", path]) == 0  # JG203 filtered out
    capsys.readouterr()
    assert cli_main(["--ignore", "JG203", path]) == 0
    capsys.readouterr()
    assert cli_main([path]) == 1


def test_cli_module_entrypoint_subprocess():
    """`python -m janusgraph_tpu.analysis` works end to end and exits 0 on
    the real package (the acceptance-criteria invocation, jax-free)."""
    proc = subprocess.run(
        [sys.executable, "-m", "janusgraph_tpu.analysis", PACKAGE],
        capture_output=True, text=True, cwd=REPO, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "graphlint: 0 error(s)" in proc.stdout


def test_check_imports_catches_syntax_error(tmp_path):
    pkg = tmp_path / "brokenpkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "ok.py").write_text("X = 1\n")
    (pkg / "bad.py").write_text("def broken(:\n")
    findings = check_imports([str(pkg)])
    assert any(f.rule_id == "JG001" and f.path.endswith("bad.py")
               for f in findings)


def test_check_imports_catches_import_error(tmp_path):
    pkg = tmp_path / "imppkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "boom.py").write_text("import not_a_real_module_xyz\n")
    findings = check_imports([str(pkg)])
    assert any(f.rule_id == "JG002" and "boom" in f.message
               for f in findings)


def test_changed_only_filter():
    changed = [
        "janusgraph_tpu/olap/kernels.py",
        "tests/test_static_analysis.py",
        "janusgraph_tpu/missing_file.py",
    ]
    cwd = os.getcwd()
    os.chdir(REPO)
    try:
        out = filter_changed(["janusgraph_tpu"], changed)
    finally:
        os.chdir(cwd)
    assert out == ["janusgraph_tpu/olap/kernels.py"]


# ------------------------------------------------- whole-program layer (v2)
def test_cross_module_fixture_package():
    """Findings that only exist whole-program: the two-module taint chain
    (JG102 in helpers.py via kernels.py's jit), cross-module
    blocking-under-lock in both directions (JG403), the cross-module
    lock-order cycle (JG202), and a thread-entry mutation whose spawn and
    mutation sites live in different modules (JG401)."""
    findings = analyze_paths([XMOD])
    got = {(os.path.basename(f.path), f.line, f.rule_id) for f in findings}
    want = set()
    for fn in sorted(os.listdir(XMOD)):
        if fn.endswith(".py"):
            per_line, _pf = _expectations(os.path.join(XMOD, fn))
            want |= {(fn, line, rule) for line, rule in per_line}
    assert want, "xmod_pkg fixtures lost their expect markers"
    assert got == want, (
        f"missing: {sorted(want - got)}; unexpected: {sorted(got - want)}"
    )


@pytest.mark.parametrize("fn", [
    "kernels.py", "helpers.py", "registry.py", "wire.py", "racy.py",
    "pump.py",
])
def test_cross_module_findings_vanish_module_locally(fn):
    """The same modules analyzed ALONE are clean — proof the findings
    above come from the whole-program layer, not module-local rules."""
    assert analyze_paths([os.path.join(XMOD, fn)]) == []


def test_json_report_byte_identical_across_processes():
    """Determinism: two CLI runs under different hash seeds produce
    byte-identical JSON (sorted iteration everywhere in the call-graph
    and rule passes)."""
    outs = []
    for seed in ("0", "424242"):
        env = dict(os.environ, PYTHONHASHSEED=seed)
        proc = subprocess.run(
            [sys.executable, "-m", "janusgraph_tpu.analysis",
             "--format", "json", XMOD],
            capture_output=True, text=True, cwd=REPO, env=env, timeout=300,
        )
        assert proc.returncode == 1, proc.stdout + proc.stderr
        outs.append(proc.stdout)
    assert outs[0] == outs[1]
    assert json.loads(outs[0])["counts"]["errors"] >= 5


def test_json_schema_v2_stable_keys(capsys):
    """--format json: every finding carries the stable
    file/line/rule/severity keys (plus col/message/suppressed); `path`
    stays as the v1 alias."""
    rc = cli_main([
        "--format", "json",
        os.path.join(FIXTURES, "bad_thread_lifecycle.py"),
    ])
    out = capsys.readouterr().out
    assert rc == 1
    data = json.loads(out)
    assert data["schema_version"] == 2
    assert data["findings"], "lifecycle fixture produced no findings"
    for f in data["findings"]:
        assert {"file", "line", "rule", "severity", "col", "message",
                "suppressed"} <= set(f)
        assert f["file"] == f["path"]


def test_handoff_marker_silences_jg402(tmp_path):
    """`# graphlint: handoff` on the spawn line is the explicit-handoff
    declaration: the entry is trusted and the walk never starts."""
    with open(os.path.join(FIXTURES, "bad_thread_ambient.py"),
              encoding="utf-8") as f:
        src = f.read()
    marked = src.replace(
        "return list(pool.map(work, items))",
        "return list(pool.map(work, items))  # graphlint: handoff",
    )
    assert marked != src
    p = tmp_path / "mod.py"
    p.write_text(marked)
    assert analyze_paths([str(p)]) == []


def test_stats_reports_callgraph_and_rule_counts(capsys):
    """--stats: per-rule finding/suppression counts plus call-graph size
    (the graphlint_v2_report.json artifact shape)."""
    rc = cli_main([XMOD, "--stats"])
    out = capsys.readouterr().out
    assert rc == 1
    data = json.loads(out)
    assert data["files_scanned"] == 7
    assert data["callgraph"]["modules"] == 7
    assert data["callgraph"]["functions"] >= 12
    assert data["callgraph"]["call_edges"] >= 6
    assert data["findings_by_rule"]["JG403"] == 2
    assert data["findings_by_rule"]["JG401"] == 1
    assert data["findings_by_rule"]["JG202"] == 1
    assert data["traced_defs"] >= 2  # gather_rows + cross-module coerce_rows


# --------------------------------------------------- suppression ratchet
def test_suppression_baseline_ratchet(tmp_path, capsys):
    from janusgraph_tpu.analysis.baseline import (
        compare, load_baseline, write_baseline,
    )

    path = os.path.join(FIXTURES, "suppressed_ok.py")
    base = str(tmp_path / "base.json")
    assert cli_main([path, "--write-baseline", base]) == 0
    capsys.readouterr()
    budget = load_baseline(base)
    assert set(budget) == {"JG203", "JG301"}
    assert all(n >= 1 for n in budget.values())

    # same tree passes the ratchet; byte-stable re-write
    assert cli_main([path, "--baseline", base]) == 0
    capsys.readouterr()
    with open(base, encoding="utf-8") as f:
        first = f.read()
    write_baseline(base, budget)
    with open(base, encoding="utf-8") as f:
        assert f.read() == first

    # shrinking the budget makes the same suppressions a regression
    zero = str(tmp_path / "zero.json")
    write_baseline(zero, {})
    assert cli_main([path, "--baseline", zero]) == 1
    err = capsys.readouterr().err
    assert "suppression ratchet" in err

    regs, imps = compare({"JG203": 2}, {"JG203": 1, "JG110": 3})
    assert regs == [("JG203", 2, 1)]
    assert imps == [("JG110", 0, 3)]


def test_report_suppressions_budget_table(capsys):
    path = os.path.join(FIXTURES, "suppressed_ok.py")
    assert cli_main([path, "--report-suppressions"]) == 0
    out = capsys.readouterr().out
    assert "suppression budget:" in out
    assert "JG203" in out and "JG301" in out


def test_package_baseline_artifact_matches_tree():
    """The checked-in .graphlint-baseline.json stays honest: analyzing
    the real package must not exceed any rule's banked budget."""
    from janusgraph_tpu.analysis.baseline import compare, load_baseline

    base = os.path.join(REPO, ".graphlint-baseline.json")
    assert os.path.exists(base), "run bin/graphlint.sh --write-baseline"
    budget = load_baseline(base)
    analyzer = Analyzer()
    analyzer.analyze_paths([PACKAGE])
    used = analyzer.last_stats["suppressions_by_rule"]
    regressions, _improvements = compare(used, budget)
    assert regressions == [], (
        f"suppression count grew past the banked budget: {regressions}"
    )


# --------------------------------------------------- merge-base changed-only
def test_changed_only_uses_merge_base(tmp_path):
    """--changed-only sees the branch's own commits (merge-base diff),
    not just the dirty working tree."""
    from janusgraph_tpu.analysis.cli import changed_python_files

    def git(*args):
        return subprocess.run(
            ["git", *args], cwd=tmp_path, check=True,
            capture_output=True, text=True,
        ).stdout

    git("init", "-q")
    git("config", "user.email", "t@example.com")
    git("config", "user.name", "t")
    (tmp_path / "a.py").write_text("A = 1\n")
    git("add", "a.py")
    git("commit", "-qm", "base")
    trunk = git("rev-parse", "--abbrev-ref", "HEAD").strip()
    git("checkout", "-qb", "feature")
    (tmp_path / "b.py").write_text("B = 2\n")
    git("add", "b.py")
    git("commit", "-qm", "feature work")
    (tmp_path / "c.py").write_text("C = 3\n")  # untracked, working tree

    files = changed_python_files(str(tmp_path), base_ref=trunk)
    assert files == ["b.py", "c.py"]
    # a.py is untouched on the branch: never reported
    assert "a.py" not in files
