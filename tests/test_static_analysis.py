"""Tier-1 gate + self-tests for graphlint (janusgraph_tpu/analysis/).

Two jobs:

1. **Gate the real tree**: the whole package must analyze clean (zero
   non-suppressed errors) and pass the import sweep, so every future PR
   rides this invariant without extra CI plumbing.
2. **Prove the rules**: each rule ID fires exactly where the bad-snippet
   fixtures say it should (``# expect: JGnnn`` markers), suppression
   comments work, and the JSON reporter round-trips.
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys

import pytest

from janusgraph_tpu.analysis import Analyzer, RULES, analyze_paths
from janusgraph_tpu.analysis.cli import filter_changed, main as cli_main
from janusgraph_tpu.analysis.imports_check import check_imports
from janusgraph_tpu.analysis.reporting import from_json, to_json

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(REPO, "janusgraph_tpu")
FIXTURES = os.path.join(REPO, "tests", "fixtures", "graphlint")

_EXPECT_RE = re.compile(r"#\s*expect:\s*([A-Z0-9, ]+)")
_EXPECT_FILE_RE = re.compile(r"#\s*expect-file:\s*([A-Z0-9, ]+)")


def _expectations(path):
    """((line, rule) set, file-level rule set) parsed from fixture markers."""
    per_line, per_file = set(), set()
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f, start=1):
            m = _EXPECT_FILE_RE.search(line)
            if m:
                per_file.update(
                    r.strip() for r in m.group(1).split(",") if r.strip()
                )
                continue
            m = _EXPECT_RE.search(line)
            if m:
                for rule in m.group(1).split(","):
                    if rule.strip():
                        per_line.add((i, rule.strip()))
    return per_line, per_file


# --------------------------------------------------------------------- gate
def test_package_analyzes_clean():
    """THE gate: zero non-suppressed findings on the real tree."""
    findings = analyze_paths([PACKAGE])
    assert findings == [], "graphlint found issues:\n" + "\n".join(
        f"{f.path}:{f.line}: {f.rule_id} {f.message}" for f in findings
    )


def test_package_import_sweep_clean():
    """--check-imports: every module byte-compiles and imports (catches
    syntax errors / circular imports in rarely-run server/ and driver/)."""
    findings = check_imports([PACKAGE])
    assert findings == [], "\n".join(
        f"{f.path}: {f.rule_id} {f.message}" for f in findings
    )


def test_suppressions_in_package_carry_justification():
    """Every in-tree suppression must say WHY (`-- reason` suffix) — a bare
    disable defeats the point of machine-checked invariants."""
    from janusgraph_tpu.analysis.core import _DISABLE_FILE_RE, _DISABLE_RE

    bad = []
    for root, dirs, files in os.walk(PACKAGE):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for fn in files:
            if not fn.endswith(".py"):
                continue
            p = os.path.join(root, fn)
            with open(p, encoding="utf-8") as f:
                for i, line in enumerate(f, start=1):
                    if not (_DISABLE_RE.search(line)
                            or _DISABLE_FILE_RE.search(line)):
                        continue
                    if " -- " not in line:
                        bad.append(f"{p}:{i}")
    assert bad == [], f"suppressions without justification: {bad}"


# ----------------------------------------------------------- fixture firing
FIXTURE_FILES = sorted(
    fn for fn in os.listdir(FIXTURES) if fn.startswith("bad_")
)


def test_fixture_inventory_covers_all_rule_ids():
    """Every JG1xx/JG2xx/JG3xx rule has at least one firing fixture."""
    covered = set()
    for fn in FIXTURE_FILES:
        per_line, per_file = _expectations(os.path.join(FIXTURES, fn))
        covered |= {r for _l, r in per_line} | per_file
    analyzer_rules = {r for r in RULES if not r.startswith("JG0")}
    assert analyzer_rules <= covered, (
        f"rules without fixtures: {sorted(analyzer_rules - covered)}"
    )
    assert len(analyzer_rules) >= 8


@pytest.mark.parametrize("fixture", FIXTURE_FILES)
def test_fixture_fires_exactly_where_expected(fixture):
    path = os.path.join(FIXTURES, fixture)
    per_line, per_file = _expectations(path)
    findings = analyze_paths([path])
    got_lines = {(f.line, f.rule_id) for f in findings}
    got_rules = {f.rule_id for f in findings}
    missing = per_line - got_lines
    assert not missing, f"expected findings did not fire: {sorted(missing)}"
    for rule in per_file:
        assert rule in got_rules, f"{rule} did not fire anywhere in {fixture}"
    # no rule fires anywhere it wasn't declared (file-level rules exempt)
    unexpected = {
        (line, r) for line, r in got_lines
        if (line, r) not in per_line and r not in per_file
    }
    assert not unexpected, f"unexpected findings: {sorted(unexpected)}"


def test_jg106_flags_telemetry_recording_in_traced_code():
    """ISSUE 2 satellite: metric/span calls inside jit context are a host
    sync hazard (and record once per compile) — JG106 fires on the
    fixture and ONLY JG106."""
    assert "JG106" in RULES
    path = os.path.join(FIXTURES, "bad_trace_telemetry.py")
    findings = analyze_paths([path])
    assert findings, "JG106 fixture produced no findings"
    assert {f.rule_id for f in findings} == {"JG106"}
    # the observability package itself records host-side only
    assert analyze_paths(
        [os.path.join(PACKAGE, "observability")]
    ) == []


def test_suppression_comments_silence_findings():
    path = os.path.join(FIXTURES, "suppressed_ok.py")
    assert analyze_paths([path]) == []
    kept, _n = Analyzer().analyze_paths([path], keep_suppressed=True)
    assert {f.rule_id for f in kept} == {"JG301", "JG203"}
    assert all(f.suppressed for f in kept)


def test_disable_file_suppresses_whole_file(tmp_path):
    p = tmp_path / "mod.py"
    p.write_text(
        "# graphlint: disable-file=JG301 -- test\n"
        "E_CAP = 3000\nF_MIN = 999\n"
    )
    assert analyze_paths([str(p)]) == []


# ------------------------------------------------------------ reporter/CLI
def test_json_reporter_round_trip(tmp_path, capsys):
    path = os.path.join(FIXTURES, "bad_shape_tier.py")
    rc = cli_main(["--json", path])
    out = capsys.readouterr().out
    assert rc == 1
    data = json.loads(out)
    assert data["tool"] == "graphlint"
    assert data["counts"]["errors"] >= 2
    loaded = from_json(out)
    direct = analyze_paths([path])
    assert [f.to_dict() for f in loaded] == [f.to_dict() for f in direct]
    # and to_json(from_json(x)) is stable
    assert to_json(loaded, data["files_scanned"]) == out.rstrip("\n")


def test_cli_select_and_ignore(capsys):
    path = os.path.join(FIXTURES, "bad_lock_blocking.py")
    assert cli_main(["--select", "JG3", path]) == 0  # JG203 filtered out
    capsys.readouterr()
    assert cli_main(["--ignore", "JG203", path]) == 0
    capsys.readouterr()
    assert cli_main([path]) == 1


def test_cli_module_entrypoint_subprocess():
    """`python -m janusgraph_tpu.analysis` works end to end and exits 0 on
    the real package (the acceptance-criteria invocation, jax-free)."""
    proc = subprocess.run(
        [sys.executable, "-m", "janusgraph_tpu.analysis", PACKAGE],
        capture_output=True, text=True, cwd=REPO, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "graphlint: 0 error(s)" in proc.stdout


def test_check_imports_catches_syntax_error(tmp_path):
    pkg = tmp_path / "brokenpkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "ok.py").write_text("X = 1\n")
    (pkg / "bad.py").write_text("def broken(:\n")
    findings = check_imports([str(pkg)])
    assert any(f.rule_id == "JG001" and f.path.endswith("bad.py")
               for f in findings)


def test_check_imports_catches_import_error(tmp_path):
    pkg = tmp_path / "imppkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "boom.py").write_text("import not_a_real_module_xyz\n")
    findings = check_imports([str(pkg)])
    assert any(f.rule_id == "JG002" and "boom" in f.message
               for f in findings)


def test_changed_only_filter():
    changed = [
        "janusgraph_tpu/olap/kernels.py",
        "tests/test_static_analysis.py",
        "janusgraph_tpu/missing_file.py",
    ]
    cwd = os.getcwd()
    os.chdir(REPO)
    try:
        out = filter_changed(["janusgraph_tpu"], changed)
    finally:
        os.chdir(cwd)
    assert out == ["janusgraph_tpu/olap/kernels.py"]
