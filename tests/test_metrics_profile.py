"""Metrics + query-profiler tests (reference test model:
JanusGraphOperationCountingTest.java:649 asserts backend-call counts through
metric instrumentation — i.e. cache behavior is observable via metrics)."""

import pytest

from janusgraph_tpu.core.graph import open_graph
from janusgraph_tpu.core.traversal import P
from janusgraph_tpu.util.metrics import (
    MetricInstrumentedStore,
    MetricManager,
    metrics,
)


@pytest.fixture(autouse=True)
def _reset_metrics():
    metrics.reset()
    yield
    metrics.reset()


def test_metric_manager_counters_timers():
    m = MetricManager()
    m.counter("a.b").inc()
    m.counter("a.b").inc(2)
    assert m.get_count("a.b") == 3
    with m.time("op"):
        pass
    with m.time("op"):
        pass
    snap = m.snapshot()
    assert snap["op"]["count"] == 2
    assert snap["op"]["total_ms"] >= 0
    assert "a.b" in m.report()
    m.reset()
    assert m.get_count("a.b") == 0


def test_timer_percentiles_uniform_in_reporters():
    """Satellite (ISSUE 2): timers expose p50/p95/p99 + counts uniformly
    in the dict and console reporters — no more flat mean/max-only
    asymmetry — and snapshots stay dotted-name sorted across metric
    kinds so diffs are deterministic."""
    m = MetricManager()
    m.counter("z.last").inc()
    for ns in (10_000, 20_000, 40_000, 5_000_000):
        m.timer("a.first").update(ns)
    m.set_gauge("m.middle", 2.5)
    snap = m.snapshot()
    assert list(snap) == ["a.first", "m.middle", "z.last"]
    t = snap["a.first"]
    assert t["count"] == 4
    assert 0 < t["p50_ms"] <= t["p95_ms"] <= t["p99_ms"]
    assert t["p99_ms"] <= 2 * t["max_ms"]  # log-bucket upper bound
    console = m.report()
    assert "p50_ms" in console and "p99_ms" in console
    assert console.index("a.first") < console.index("z.last")


def test_olap_run_record_surfaced_through_registry():
    """Satellite (ISSUE 2): the executor's per-run record ("path",
    "supersteps", "wall_s", per-superstep records) is published through
    the registry, not just the `last_run_info` attribute."""
    import numpy as np

    from janusgraph_tpu.olap import csr_from_edges, run_on
    from janusgraph_tpu.olap.programs import PageRankProgram

    rng = np.random.default_rng(3)
    n, m_edges = 50, 200
    csr = csr_from_edges(
        n,
        rng.integers(0, n, m_edges).astype(np.int32),
        rng.integers(0, n, m_edges).astype(np.int32),
    )
    run_on(csr, PageRankProgram(max_iterations=3, tol=0.0), executor="tpu")
    rec = metrics.last_run("olap")
    assert rec is not None
    assert rec["path"] in ("fused", "host-loop")
    assert rec["supersteps"] == 3
    assert rec["wall_s"] > 0
    assert len(rec["superstep_records"]) == 3
    first = rec["superstep_records"][0]
    assert first["frontier"] == n and first["h2d_bytes"] > 0
    assert metrics.snapshot()["olap.superstep.count"]["value"] == 3.0


def test_instrumented_store_counts_ops():
    g = open_graph({"schema.default": "auto", "metrics.enabled": True})
    tx = g.new_transaction()
    v = tx.add_vertex(name="x")
    tx.commit()
    assert metrics.get_count("storage.edgestore.mutate.rows") > 0
    before = metrics.get_count("storage.edgestore.getSlice")
    tx = g.new_transaction()
    tx.get_vertex(v.id)
    tx.rollback()
    assert metrics.get_count("storage.edgestore.getSlice") >= before
    g.close()


def test_cache_visible_through_metrics():
    """Repeated identical reads hit the cache: store-level getSlice count
    stays flat (the JanusGraphOperationCountingTest property)."""
    g = open_graph({"schema.default": "auto", "metrics.enabled": True})
    tx = g.new_transaction()
    v = tx.add_vertex(name="y")
    tx.commit()
    tx = g.new_transaction()
    tx.get_vertex(v.id)
    tx.get_properties(tx.get_vertex(v.id), "name")
    tx.rollback()
    count1 = metrics.get_count("storage.edgestore.getSlice")
    # a fresh tx re-reading the same slices should be served by the cache
    tx = g.new_transaction()
    tx.get_properties(tx.get_vertex(v.id), "name")
    tx.rollback()
    count2 = metrics.get_count("storage.edgestore.getSlice")
    assert count2 == count1
    g.close()


def test_metrics_off_by_default():
    g = open_graph({"schema.default": "auto"})
    tx = g.new_transaction()
    tx.add_vertex(name="z")
    tx.commit()
    assert metrics.get_count("storage.edgestore.mutate.rows") == 0
    g.close()


# ------------------------------------------------------------------- profiler
@pytest.fixture
def graph():
    g = open_graph({"schema.default": "auto"})
    yield g
    g.close()


def _seed(g):
    tx = g.new_transaction()
    a = tx.add_vertex(name="a")
    b = tx.add_vertex(name="b")
    c = tx.add_vertex(name="c")
    tx.add_edge(a, "knows", b)
    tx.add_edge(a, "knows", c)
    tx.commit()
    return a, b, c


def test_profile_full_scan(graph):
    _seed(graph)
    g = graph.traversal()
    prof = g.V().has("name", "a").out("knows").profile()
    assert len(prof.result) == 2
    d = prof.as_dict()
    assert d["group"] == "traversal"
    groups = [c["group"] for c in d["children"]]
    assert groups[0] == "start"
    assert any(g.startswith("out") for g in groups)
    start = d["children"][0]
    assert start["annotations"]["access"] == "full-scan"
    assert prof.elapsed_ms > 0
    assert "traversal" in str(prof)


def test_profile_composite_index(graph):
    _seed(graph)
    graph.management().build_composite_index("byname", ["name"])
    g = graph.traversal()
    prof = g.V().has("name", "a").profile()
    start = prof.as_dict()["children"][0]
    assert start["annotations"]["access"] == "composite-index"
    assert start["annotations"]["index"] == "byname"
    assert len(prof.result) == 1


def test_profile_mixed_index(graph):
    _seed(graph)
    mgmt = graph.management()
    mgmt.make_property_key("bio", str)
    mgmt.build_mixed_index("bios", ["bio"], backing="search")
    tx = graph.new_transaction()
    tx.add_vertex(bio="some words")
    tx.commit()
    g = graph.traversal()
    prof = g.V().has("bio", P.text_contains("words")).profile()
    start = prof.as_dict()["children"][0]
    assert start["annotations"]["access"] == "mixed-index"
    assert start["annotations"]["conditions_pushed"] == 1
    assert len(prof.result) == 1


def test_profile_step_labels_and_counts(graph):
    _seed(graph)
    g = graph.traversal()
    prof = g.V().out("knows").dedup().limit(1).profile()
    groups = [c["group"] for c in prof.as_dict()["children"]]
    assert groups[0] == "start"
    assert "out(knows)" in groups
    assert "dedup" in groups
    assert "limit" in groups
    last = prof.as_dict()["children"][-1]
    assert last["annotations"]["traversers"] == 1
