"""Metrics + query-profiler tests (reference test model:
JanusGraphOperationCountingTest.java:649 asserts backend-call counts through
metric instrumentation — i.e. cache behavior is observable via metrics)."""

import pytest

from janusgraph_tpu.core.graph import open_graph
from janusgraph_tpu.core.traversal import P
from janusgraph_tpu.util.metrics import (
    MetricInstrumentedStore,
    MetricManager,
    metrics,
)


@pytest.fixture(autouse=True)
def _reset_metrics():
    metrics.reset()
    yield
    metrics.reset()


def test_metric_manager_counters_timers():
    m = MetricManager()
    m.counter("a.b").inc()
    m.counter("a.b").inc(2)
    assert m.get_count("a.b") == 3
    with m.time("op"):
        pass
    with m.time("op"):
        pass
    snap = m.snapshot()
    assert snap["op"]["count"] == 2
    assert snap["op"]["total_ms"] >= 0
    assert "a.b" in m.report()
    m.reset()
    assert m.get_count("a.b") == 0


def test_instrumented_store_counts_ops():
    g = open_graph({"schema.default": "auto", "metrics.enabled": True})
    tx = g.new_transaction()
    v = tx.add_vertex(name="x")
    tx.commit()
    assert metrics.get_count("storage.edgestore.mutate.rows") > 0
    before = metrics.get_count("storage.edgestore.getSlice")
    tx = g.new_transaction()
    tx.get_vertex(v.id)
    tx.rollback()
    assert metrics.get_count("storage.edgestore.getSlice") >= before
    g.close()


def test_cache_visible_through_metrics():
    """Repeated identical reads hit the cache: store-level getSlice count
    stays flat (the JanusGraphOperationCountingTest property)."""
    g = open_graph({"schema.default": "auto", "metrics.enabled": True})
    tx = g.new_transaction()
    v = tx.add_vertex(name="y")
    tx.commit()
    tx = g.new_transaction()
    tx.get_vertex(v.id)
    tx.get_properties(tx.get_vertex(v.id), "name")
    tx.rollback()
    count1 = metrics.get_count("storage.edgestore.getSlice")
    # a fresh tx re-reading the same slices should be served by the cache
    tx = g.new_transaction()
    tx.get_properties(tx.get_vertex(v.id), "name")
    tx.rollback()
    count2 = metrics.get_count("storage.edgestore.getSlice")
    assert count2 == count1
    g.close()


def test_metrics_off_by_default():
    g = open_graph({"schema.default": "auto"})
    tx = g.new_transaction()
    tx.add_vertex(name="z")
    tx.commit()
    assert metrics.get_count("storage.edgestore.mutate.rows") == 0
    g.close()


# ------------------------------------------------------------------- profiler
@pytest.fixture
def graph():
    g = open_graph({"schema.default": "auto"})
    yield g
    g.close()


def _seed(g):
    tx = g.new_transaction()
    a = tx.add_vertex(name="a")
    b = tx.add_vertex(name="b")
    c = tx.add_vertex(name="c")
    tx.add_edge(a, "knows", b)
    tx.add_edge(a, "knows", c)
    tx.commit()
    return a, b, c


def test_profile_full_scan(graph):
    _seed(graph)
    g = graph.traversal()
    prof = g.V().has("name", "a").out("knows").profile()
    assert len(prof.result) == 2
    d = prof.as_dict()
    assert d["group"] == "traversal"
    groups = [c["group"] for c in d["children"]]
    assert groups[0] == "start"
    assert any(g.startswith("out") for g in groups)
    start = d["children"][0]
    assert start["annotations"]["access"] == "full-scan"
    assert prof.elapsed_ms > 0
    assert "traversal" in str(prof)


def test_profile_composite_index(graph):
    _seed(graph)
    graph.management().build_composite_index("byname", ["name"])
    g = graph.traversal()
    prof = g.V().has("name", "a").profile()
    start = prof.as_dict()["children"][0]
    assert start["annotations"]["access"] == "composite-index"
    assert start["annotations"]["index"] == "byname"
    assert len(prof.result) == 1


def test_profile_mixed_index(graph):
    _seed(graph)
    mgmt = graph.management()
    mgmt.make_property_key("bio", str)
    mgmt.build_mixed_index("bios", ["bio"], backing="search")
    tx = graph.new_transaction()
    tx.add_vertex(bio="some words")
    tx.commit()
    g = graph.traversal()
    prof = g.V().has("bio", P.text_contains("words")).profile()
    start = prof.as_dict()["children"][0]
    assert start["annotations"]["access"] == "mixed-index"
    assert start["annotations"]["conditions_pushed"] == 1
    assert len(prof.result) == 1


def test_profile_step_labels_and_counts(graph):
    _seed(graph)
    g = graph.traversal()
    prof = g.V().out("knows").dedup().limit(1).profile()
    groups = [c["group"] for c in prof.as_dict()["children"]]
    assert groups[0] == "start"
    assert "out(knows)" in groups
    assert "dedup" in groups
    assert "limit" in groups
    last = prof.as_dict()["children"][-1]
    assert last["annotations"]["traversers"] == 1
